"""dtpu-lint (ISSUE 10): rule fixtures, suppression/baseline semantics,
the tier-1 gate on the live tree, seeded-mutation detection, and
regression tests for the real violations the analyzer surfaced (and PR
10 fixed rather than baselined).

The gate contract: ``run_lint()`` on the shipped tree reports ZERO
non-baselined violations, and each seeded mutation — an un-offloaded
fsync in an async route, a guarded field written without its lock, an
``np.asarray`` in the denoise spine, an undeclared ``DTPU_*`` read —
is caught as a NEW violation against the SHIPPED baseline.
"""

import asyncio
import json
import os
import sys
import threading

import pytest

from comfyui_distributed_tpu.analysis import engine

ROOT = engine.repo_root()
PKG = "comfyui_distributed_tpu"


def lint_sources(files, rules=None):
    """Lint an in-memory mini-project (no disk, no baseline)."""
    project = engine.Project(
        ROOT,
        {rel: engine._parse_file(rel, src)
         for rel, src in files.items() if rel != "README.md"},
        readme=(engine._parse_file("README.md", files["README.md"])
                if "README.md" in files else None))
    return engine.lint_project(project, rules=rules)


def rules_of(violations):
    return sorted({v.rule for v in violations})


# --- rule fixtures: async-blocking -------------------------------------------

ASYNC_POS = f"""
import os, time, asyncio

async def handler(request):
    os.fsync(3)
    time.sleep(1)
    state.manager.launch_worker(w)
    return 1
"""

ASYNC_NEG = """
import os, time, asyncio

async def handler(request):
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, lambda: os.fsync(3))
    await asyncio.sleep(1)

    def thunk():
        time.sleep(1)          # runs on the executor, not the loop
    await loop.run_in_executor(None, thunk)

def sync_helper():
    os.fsync(3)                # sync code may block freely
"""


class TestAsyncBlockingRule:
    def test_positive(self):
        vs = lint_sources({f"{PKG}/server/app.py": ASYNC_POS},
                          rules=["async-blocking"])
        msgs = [v.message for v in vs]
        assert len(vs) == 3
        assert any("os.fsync" in m for m in msgs)
        assert any("time.sleep" in m for m in msgs)
        assert any("launch_worker" in m for m in msgs)

    def test_negative_offloaded_and_sync(self):
        vs = lint_sources({f"{PKG}/server/app.py": ASYNC_NEG},
                          rules=["async-blocking"])
        assert vs == []

    def test_suppression_with_reason(self):
        src = ASYNC_POS.replace(
            "os.fsync(3)",
            "os.fsync(3)  # dtpu-lint: ignore[async-blocking] test-only")
        vs = lint_sources({f"{PKG}/server/app.py": src},
                          rules=["async-blocking"])
        assert len(vs) == 2  # fsync suppressed, the other two stay

    def test_reasonless_suppression_does_not_suppress(self):
        src = ASYNC_POS.replace(
            "os.fsync(3)",
            "os.fsync(3)  # dtpu-lint: ignore[async-blocking]")
        vs = lint_sources({f"{PKG}/server/app.py": src},
                          rules=["async-blocking"])
        assert len(vs) == 3
        # and the inert marker is diagnosed, not silently ignored
        noted = [v for v in vs if "suppresses nothing" in v.message]
        assert len(noted) == 1 and "os.fsync" in noted[0].message

    def test_unknown_rule_id_raises(self):
        with pytest.raises(ValueError, match="unknown rule id"):
            lint_sources({f"{PKG}/server/app.py": ASYNC_POS},
                         rules=["async_blocking"])  # typo: underscore


# --- rule fixtures: lockset --------------------------------------------------

LOCKSET_SRC = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0          # guarded-by: self._lock
        self.unguarded = 0  # no annotation: never checked

    def good(self):
        with self._lock:
            self.n += 1

    def bad(self):
        self.n += 1

    def closure_bad(self):
        def run():
            self.n += 1     # thread target: lock NOT held
        return run

    def lambda_inline_ok(self):
        with self._lock:
            return max([1], key=lambda _: self.n)

    def _bump_locked(self):
        self.n += 1         # *_locked contract: caller holds it

    # dtpu-lint: holds[self._lock]
    def bump_held(self):
        self.n += 1

    def free(self):
        self.unguarded += 1
"""


class TestLocksetRule:
    def test_fixture(self):
        vs = lint_sources({f"{PKG}/runtime/fixture.py": LOCKSET_SRC},
                          rules=["lockset"])
        assert [(v.scope, "self.n" in v.message) for v in vs] == [
            ("Counter.bad", True), ("Counter.closure_bad.run", True)]

    def test_init_exempt_and_with_scope_ends(self):
        src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.x = 1  # guarded-by: self._lock
        self.x = 2  # __init__ is pre-publication

    def after_with(self):
        with self._lock:
            self.x = 3
        self.x = 4  # lock released: flagged
"""
        vs = lint_sources({f"{PKG}/runtime/fixture.py": src},
                          rules=["lockset"])
        assert len(vs) == 1
        assert "self.x = 4" in src.splitlines()[vs[0].line - 1]


# --- rule fixtures: device spine ---------------------------------------------

SPINE_SRC = """
import numpy as np
import jax
import jax.numpy as jnp

def denoise_step(x, sigma):
    y = jnp.asarray(x)            # device-side: fine
    host = np.asarray(x)          # d2h: flagged
    v = x.item()                  # sync: flagged
    f = float(sigma)              # sync: flagged
    g = float(0.5)                # literal: fine
    d = jax.device_get(x)         # flagged
    return y
"""

RETRACE_SRC = """
import jax

def step(x, n):
    if x > 0:                     # traced branch: flagged
        return x
    if n is None:                 # trace-time check: fine
        return x
    while x.shape[0] > 1:         # shape probe: fine
        break
    return x

jitted = jax.jit(step)
"""


class TestSpineRules:
    def test_host_fetch_fixture(self):
        vs = lint_sources({f"{PKG}/ops/fixture.py": SPINE_SRC},
                          rules=["spine-host-fetch"])
        assert len(vs) == 4

    def test_outside_spine_not_flagged(self):
        vs = lint_sources({f"{PKG}/server/fixture.py": SPINE_SRC},
                          rules=["spine-host-fetch"])
        assert vs == []

    def test_retrace_fixture(self):
        vs = lint_sources({f"{PKG}/models/fixture.py": RETRACE_SRC},
                          rules=["retrace-hazard"])
        assert len(vs) == 1 and "x" in vs[0].message


# --- rule fixtures: tp-spec-discipline (ISSUE 16) ----------------------------

HAND_SPEC_DIRECT = '''
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_rows(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P("data")))
'''

HAND_SPEC_MODULE = '''
import jax.sharding as js


def spec_for():
    return js.PartitionSpec(None, None)
'''

SPEC_VIA_HELPERS = '''
from comfyui_distributed_tpu.parallel import sharding as shd


def shard_rows(x, mesh):
    return shd.put_rows(x, mesh)
'''


class TestTpSpecDisciplineRule:
    def test_direct_alias_construction_flagged(self):
        vs = lint_sources(
            {f"{PKG}/workflow/hand.py": HAND_SPEC_DIRECT},
            rules=["tp-spec-discipline"])
        assert len(vs) == 2          # NamedSharding AND the P() inside
        assert all(v.rule == "tp-spec-discipline" for v in vs)
        assert "rule table" in vs[0].message

    def test_module_attribute_construction_flagged(self):
        vs = lint_sources(
            {f"{PKG}/models/hand.py": HAND_SPEC_MODULE},
            rules=["tp-spec-discipline"])
        assert [v.line for v in vs] and len(vs) == 1

    def test_sharding_home_and_helper_callers_exempt(self):
        # the rule table itself constructs specs freely; callers that
        # go through its helpers are clean
        vs = lint_sources(
            {f"{PKG}/parallel/sharding.py": HAND_SPEC_DIRECT,
             f"{PKG}/workflow/clean.py": SPEC_VIA_HELPERS},
            rules=["tp-spec-discipline"])
        assert vs == []

    def test_suppression_needs_reason(self):
        line = "return js.PartitionSpec(None, None)"
        bad = HAND_SPEC_MODULE.replace(
            line, line + "  # dtpu-lint: ignore[tp-spec-discipline]")
        # reasonless ignore does not suppress
        assert lint_sources({f"{PKG}/models/hand.py": bad},
                            rules=["tp-spec-discipline"])
        ok = HAND_SPEC_MODULE.replace(
            line, line + "  # dtpu-lint: ignore[tp-spec-discipline] "
                         "host-only layout probe")
        assert lint_sources({f"{PKG}/models/hand.py": ok},
                            rules=["tp-spec-discipline"]) == []


# --- rule fixtures: cb-slot-state-discipline (ISSUE 17) ----------------------

SLOT_WRITE_OUTSIDE = f'''
def nudge(slot):
    slot.step = 0
    slot.t_admit += 1.0
'''

SLOT_TUPLE_WRITE = '''
def swap(a, b):
    a.item, b.item = b.item, a.item
'''

SLOT_READS_ONLY = '''
def view(slot):
    s = slot.step
    return (s, slot.item["id"], slot.t_admit)
'''

CB_HOME_WITH_SLOTS = '''
class _Slot:
    __slots__ = ("item", "step", "t_admit")


class _ParkedRow:
    __slots__ = ("pid", "item", "sig", "rank", "step", "t_admit",
                 "t_park", "x_rows")


def park(rec):
    rec.x_rows = None
'''


class TestCbSlotStateDisciplineRule:
    def test_direct_writes_outside_home_flagged(self):
        vs = lint_sources(
            {f"{PKG}/workflow/scheduler.py": SLOT_WRITE_OUTSIDE},
            rules=["cb-slot-state-discipline"])
        assert len(vs) == 2          # plain assign AND the augassign
        assert all(v.rule == "cb-slot-state-discipline" for v in vs)
        assert "park" in vs[0].message

    def test_tuple_unpack_write_flagged(self):
        vs = lint_sources(
            {f"{PKG}/runtime/jobs.py": SLOT_TUPLE_WRITE},
            rules=["cb-slot-state-discipline"])
        assert len(vs) == 2          # both .item targets

    def test_reads_and_home_file_exempt(self):
        vs = lint_sources(
            {f"{PKG}/workflow/batch_executor.py": CB_HOME_WITH_SLOTS,
             f"{PKG}/server/app.py": SLOT_READS_ONLY},
            rules=["cb-slot-state-discipline"])
        assert vs == []

    def test_field_set_tracks_home_slots_declaration(self):
        # the protected set comes from batch_executor.py's __slots__:
        # a _ParkedRow-only field (x_rows) is protected too
        vs = lint_sources(
            {f"{PKG}/workflow/batch_executor.py": CB_HOME_WITH_SLOTS,
             f"{PKG}/runtime/jobs.py":
                 "def f(rec):\n    rec.x_rows = []\n"},
            rules=["cb-slot-state-discipline"])
        assert len(vs) == 1 and ".x_rows" in vs[0].message

    def test_suppression_needs_reason(self):
        bad = SLOT_WRITE_OUTSIDE.replace(
            "slot.step = 0",
            "slot.step = 0  # dtpu-lint: ignore[cb-slot-state-discipline]")
        assert len(lint_sources(
            {f"{PKG}/workflow/scheduler.py": bad},
            rules=["cb-slot-state-discipline"])) == 2
        ok = SLOT_WRITE_OUTSIDE.replace(
            "slot.step = 0",
            "slot.step = 0  # dtpu-lint: "
            "ignore[cb-slot-state-discipline] test-only fixture")
        assert len(lint_sources(
            {f"{PKG}/workflow/scheduler.py": ok},
            rules=["cb-slot-state-discipline"])) == 1


# --- rule fixtures: registry drift -------------------------------------------

CONSTANTS_FIXTURE = '''
FOO_ENV = "DTPU_FOO"
TRACE_ATTR_WHITELIST = frozenset({"job", "worker"})
'''

README_FIXTURE = """
### env table
| Variable | Default | Meaning |
| `DTPU_FOO` | unset | test |
"""


class TestRegistryDriftRules:
    def test_env_undeclared(self):
        src = ('import os\n'
               'a = os.environ.get("DTPU_FOO")\n'
               'b = os.environ.get("DTPU_MYSTERY")\n')
        vs = lint_sources({f"{PKG}/utils/constants.py": CONSTANTS_FIXTURE,
                           f"{PKG}/runtime/x.py": src},
                          rules=["env-undeclared"])
        assert len(vs) == 1 and "DTPU_MYSTERY" in vs[0].message

    def test_env_indirect_constant_resolved(self):
        src = ('import os\n'
               'K = "DTPU_INDIRECT"\n'
               'v = os.environ.get(K)\n')
        vs = lint_sources({f"{PKG}/utils/constants.py": CONSTANTS_FIXTURE,
                           f"{PKG}/runtime/x.py": src},
                          rules=["env-undeclared"])
        assert len(vs) == 1 and "DTPU_INDIRECT" in vs[0].message

    def test_readme_drift_both_directions(self):
        consts = CONSTANTS_FIXTURE + 'BAR_ENV = "DTPU_BAR"\n'
        readme = README_FIXTURE + "| `DTPU_GHOST` | unset | gone |\n"
        vs = lint_sources({f"{PKG}/utils/constants.py": consts,
                           "README.md": readme},
                          rules=["env-readme-drift"])
        msgs = " ".join(v.message for v in vs)
        assert len(vs) == 2
        assert "DTPU_BAR" in msgs and "DTPU_GHOST" in msgs

    def test_metric_name_conventions(self):
        src = ('fams = [\n'
               '  ("dtpu_good_total", "counter", "ok.", []),\n'
               '  ("dtpu_bad_count", "counter", "no suffix.", []),\n'
               '  ("plain_gauge", "gauge", "no prefix.", []),\n'
               ']\n')
        vs = lint_sources({f"{PKG}/server/x.py": src},
                          rules=["metric-name"])
        assert len(vs) == 2

    def test_span_attr_whitelist(self):
        src = ('from x import trace_mod\n'
               'def f(sp):\n'
               '    sp.attrs["job"] = 1\n'
               '    sp.attrs["rogue_attr"] = 2\n'
               '    with trace_mod.span("collect", worker="w"):\n'
               '        pass\n'
               '    with trace_mod.span("collect", rogue_kw=1):\n'
               '        pass\n')
        vs = lint_sources({f"{PKG}/utils/constants.py": CONSTANTS_FIXTURE,
                           f"{PKG}/ops/x.py": src},
                          rules=["span-attr"])
        assert sorted(v.message.split("'")[1] for v in vs) == [
            "rogue_attr", "rogue_kw"]


# --- baseline-delta semantics ------------------------------------------------

class TestBaselineSemantics:
    def _report(self, n_fsync, baseline):
        body = "\n".join(["    os.fsync(3)"] * n_fsync) or "    pass"
        src = f"import os\n\nasync def h(request):\n{body}\n"
        project = engine.Project(
            ROOT, {f"{PKG}/server/app.py":
                   engine._parse_file(f"{PKG}/server/app.py", src)})
        vs = engine.lint_project(project, rules=["async-blocking"])
        return engine._split_new(vs, baseline), vs

    def test_baselined_violation_not_new(self):
        new, vs = self._report(1, {})
        assert len(new) == 1
        key = vs[0].key
        new2, _ = self._report(1, {key: 1})
        assert new2 == []

    def test_count_increase_is_new(self):
        _, vs = self._report(1, {})
        key = vs[0].key
        new, _ = self._report(3, {key: 1})
        # two instances beyond the single grandfathered one
        assert len(new) == 2

    def test_keys_survive_line_moves(self):
        _, vs = self._report(1, {})
        src = ("import os\n\n# a new comment shifting lines\n\n"
               "async def h(request):\n    os.fsync(3)\n")
        project = engine.Project(
            ROOT, {f"{PKG}/server/app.py":
                   engine._parse_file(f"{PKG}/server/app.py", src)})
        vs2 = engine.lint_project(project, rules=["async-blocking"])
        assert vs2[0].key == vs[0].key


# --- THE tier-1 gate ---------------------------------------------------------

class TestLiveTreeGate:
    def test_shipped_tree_is_clean(self):
        report = engine.run_lint(root=ROOT)
        assert report.new == [], "NEW dtpu-lint violations:\n" + "\n".join(
            v.format() for v in report.new)

    def test_baseline_exists_and_matches_schema(self):
        with open(engine.baseline_path(ROOT)) as f:
            data = json.load(f)
        assert data["version"] == 1
        assert all(isinstance(v, int) and v > 0
                   for v in data["entries"].values())

    def _mutated(self, relpath, anchor, inject):
        full = os.path.join(ROOT, *relpath.split("/"))
        with open(full, "r", encoding="utf-8") as f:
            src = f.read()
        assert anchor in src, f"mutation anchor missing in {relpath}"
        return src.replace(anchor, anchor + inject, 1)

    def test_seeded_async_fsync_caught(self):
        src = self._mutated(
            f"{PKG}/server/app.py",
            '    async def interrupt(request):\n',
            '        os.fsync(0)\n')
        rep = engine.run_lint(root=ROOT,
                              overrides={f"{PKG}/server/app.py": src})
        assert any(v.rule == "async-blocking" and "os.fsync"
                   in v.message and v.path.endswith("app.py")
                   for v in rep.new)

    def test_seeded_unlocked_guarded_write_caught(self):
        src = self._mutated(
            f"{PKG}/runtime/autoscale.py",
            '    def stop(self) -> None:\n',
            '        self.flaps = 0\n')
        rep = engine.run_lint(
            root=ROOT,
            overrides={f"{PKG}/runtime/autoscale.py": src})
        assert any(v.rule == "lockset" and "self.flaps" in v.message
                   for v in rep.new)

    def test_seeded_spine_asarray_caught(self):
        src = self._mutated(
            f"{PKG}/models/denoiser.py",
            '        xin = x * c_in\n',
            '        xin = np.asarray(xin)\n')
        rep = engine.run_lint(
            root=ROOT,
            overrides={f"{PKG}/models/denoiser.py": src})
        assert any(v.rule == "spine-host-fetch"
                   and "np.asarray" in v.message
                   and v.path.endswith("denoiser.py") for v in rep.new)

    def test_seeded_undeclared_env_caught(self):
        src = self._mutated(
            f"{PKG}/runtime/interrupt.py",
            'import numpy as np\n',
            'UNDECLARED = __import__("os").environ.get('
            '"DTPU_TOTALLY_NEW")\n')
        rep = engine.run_lint(
            root=ROOT,
            overrides={f"{PKG}/runtime/interrupt.py": src})
        assert any(v.rule == "env-undeclared"
                   and "DTPU_TOTALLY_NEW" in v.message for v in rep.new)


# --- cli lint ----------------------------------------------------------------

class TestCliLint:
    def test_clean_tree_exits_zero(self, capsys):
        from comfyui_distributed_tpu import cli
        rc = cli.main(["lint"])
        out = capsys.readouterr().out
        assert rc == 0 and "clean" in out

    def test_new_violation_exits_nonzero_with_file_line(self, tmp_path,
                                                        capsys):
        pkg = tmp_path / PKG
        (pkg / "server").mkdir(parents=True)
        (pkg / "analysis").mkdir()
        (pkg / "server" / "app.py").write_text(
            "import os\n\nasync def h(request):\n    os.fsync(1)\n")
        from comfyui_distributed_tpu import cli
        rc = cli.main(["lint", "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert f"{PKG}/server/app.py:4" in out
        assert "[async-blocking]" in out

    def test_write_baseline_then_clean(self, tmp_path, capsys):
        pkg = tmp_path / PKG
        (pkg / "server").mkdir(parents=True)
        (pkg / "analysis").mkdir()
        (pkg / "server" / "app.py").write_text(
            "import os\n\nasync def h(request):\n    os.fsync(1)\n")
        from comfyui_distributed_tpu import cli
        assert cli.main(["lint", "--root", str(tmp_path),
                         "--write-baseline"]) == 0
        capsys.readouterr()
        assert cli.main(["lint", "--root", str(tmp_path)]) == 0

    def test_unknown_rule_exits_2(self, capsys):
        from comfyui_distributed_tpu import cli
        assert cli.main(["lint", "--rule", "locksets"]) == 2
        assert "unknown rule id" in capsys.readouterr().err

    def test_partial_write_baseline_refused(self, capsys):
        # --rule + --write-baseline would overwrite the audited
        # baseline with one rule's findings, destroying the rest
        from comfyui_distributed_tpu import cli
        assert cli.main(["lint", "--rule", "lockset",
                         "--write-baseline"]) == 2
        assert "full run" in capsys.readouterr().err

    def test_lint_never_imports_jax(self):
        # the "runs on CPU, no device" satellite: lint must stay
        # importable and runnable without initializing any backend
        import subprocess
        code = ("import sys\n"
                "from comfyui_distributed_tpu.analysis import run_lint\n"
                "rep = run_lint()\n"
                "assert 'jax' not in sys.modules, 'lint imported jax'\n"
                "sys.exit(0 if rep.ok else 1)\n")
        proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr


# --- regression tests for the REAL violations PR 10 fixed --------------------

def _run_route(route_handler, request):
    """Drive one aiohttp-style handler on a fresh loop, recording which
    thread executes the (monkeypatched) blocking call."""
    return asyncio.new_event_loop().run_until_complete(
        route_handler(request))


class TestAsyncOffloadRegressions:
    """Each previously-blocking route now runs its blocking core on an
    executor thread, not the event-loop thread (the dtpu-lint
    async-blocking findings fixed in PR 10)."""

    @pytest.fixture()
    def app_state(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DTPU_RESOURCE", "0")
        from comfyui_distributed_tpu.server.app import ServerState
        state = ServerState(start_exec_thread=False,
                            input_dir=str(tmp_path / "in"),
                            output_dir=str(tmp_path / "out"))
        return state

    def _handler(self, state, name):
        from comfyui_distributed_tpu.server.app import build_app
        app = build_app(state)
        for route in app.router.routes():
            if route.handler.__name__ == name:
                return route.handler
        raise AssertionError(f"route handler {name} not found")

    @staticmethod
    def _record_thread(record):
        def recorder(*a, **kw):
            record.append(threading.current_thread())
            return recorder.result
        recorder.result = None
        return recorder

    def _assert_off_loop(self, record):
        assert record, "blocking call never ran"
        assert all(t is not threading.current_thread() for t in record), \
            "blocking call executed on the event-loop thread"

    class _Req:
        def __init__(self, payload=None, query=None):
            self._payload = payload or {}
            self.query = query or {}
            self.remote = "127.0.0.1"

        async def json(self):
            return self._payload

    def test_stop_worker_offloaded(self, app_state):
        record = []
        rec = self._record_thread(record)
        rec.result = True
        app_state.manager.stop_worker = rec
        handler = self._handler(app_state, "stop_worker")

        async def drive():
            return await handler(self._Req({"id": "w0"}))

        resp = asyncio.new_event_loop().run_until_complete(drive())
        assert resp.status == 200
        self._assert_off_loop(record)

    def test_worker_log_offloaded(self, app_state):
        record = []
        rec = self._record_thread(record)
        rec.result = "log text"
        app_state.manager.tail_log = rec
        handler = self._handler(app_state, "worker_log")

        async def drive():
            return await handler(self._Req(query={"id": "w0"}))

        resp = asyncio.new_event_loop().run_until_complete(drive())
        assert resp.status == 200
        self._assert_off_loop(record)

    def test_launch_worker_offloaded(self, app_state, monkeypatch):
        from comfyui_distributed_tpu.utils import config as cfg_mod
        record = []

        def fake_load(path=None):
            record.append(threading.current_thread())
            return {"workers": [{"id": "w0", "port": 1}],
                    "settings": {}}
        monkeypatch.setattr(cfg_mod, "load_config", fake_load)
        rec = self._record_thread(record)
        rec.result = {"id": "w0"}
        app_state.manager.launch_worker = rec
        handler = self._handler(app_state, "launch_worker")

        async def drive():
            return await handler(self._Req({"id": "w0"}))

        resp = asyncio.new_event_loop().run_until_complete(drive())
        assert resp.status == 200
        assert len(record) == 2  # config load AND spawn, both off-loop
        self._assert_off_loop(record)

    def test_clear_memory_offloaded(self, app_state, monkeypatch):
        from comfyui_distributed_tpu.utils import resource as res_mod
        record = []

        def fake_snap():
            record.append(threading.current_thread())
            return {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                    "bytes_limit": None, "n_devices": 0,
                    "source": "host_rss"}
        monkeypatch.setattr(res_mod, "device_memory_snapshot", fake_snap)
        handler = self._handler(app_state, "clear_memory")

        async def drive():
            return await handler(self._Req())

        resp = asyncio.new_event_loop().run_until_complete(drive())
        assert resp.status == 200
        self._assert_off_loop(record)

    def test_upload_image_offloaded(self, app_state, tmp_path):
        record = []
        handler = self._handler(app_state, "upload_image")

        class _File:
            def read(self):
                record.append(threading.current_thread())
                return b"png-bytes"

        class _Img:
            filename = "x.png"
            file = _File()

        class _Req:
            remote = "127.0.0.1"

            async def post(self):
                return {"image": _Img()}

        resp = asyncio.new_event_loop().run_until_complete(
            handler(_Req()))
        assert resp.status == 200
        self._assert_off_loop(record)
        with open(os.path.join(app_state.input_dir, "x.png"),
                  "rb") as f:
            assert f.read() == b"png-bytes"


class TestLocksetFixRegressions:
    def test_autoscaler_decision_state_consistent_under_races(self):
        """sample_once (reconciliation thread) vs snapshot (HTTP
        handlers): hammering both concurrently must leave consistent
        counters — the PR 10 lockset fix."""
        from comfyui_distributed_tpu.runtime.autoscale import (
            FleetAutoscaler)
        scaler = FleetAutoscaler(
            registry=None, queue_depth_fn=lambda: 100,
            spawner=lambda: "w", retirer=lambda wid: True,
            min_workers=0, max_workers=10**9, up_queue=1.0,
            down_queue=0.5, window=1, cooldown_s=0.0, interval_s=0.02,
            drain_s=0.0, flap_window_s=10.0)
        errors = []

        def sampler():
            t = 0.0
            try:
                for _ in range(200):
                    t += 1.0
                    scaler.sample_once(now=t)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def snapshotter():
            try:
                for _ in range(400):
                    snap = scaler.snapshot()
                    assert snap["scale_ups"] >= 0
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=sampler),
                   threading.Thread(target=snapshotter),
                   threading.Thread(target=snapshotter)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        snap = scaler.snapshot()
        # every spawned id is tracked exactly once per scale-up
        assert snap["scale_ups"] == len(snap["spawned"]) \
            + snap["scale_downs"]

    def test_ledger_recovered_job_consumed_exactly_once(self):
        """create_job pops the recovered record under the ledger lock
        (it used to race attach_wal / concurrent creates)."""
        from comfyui_distributed_tpu.runtime.cluster import WorkLedger
        for _ in range(20):
            ledger = WorkLedger()
            ledger.attach_wal(None, None, {
                "j": {"kind": "tile",
                      "units": {"0": {"owner": "w1", "done": False}}}})
            seen = []

            def create():
                ledger.create_job("j", {0: "master"}, kind="tile")
                with ledger._lock:
                    seen.append("j" in ledger._recovered_jobs)
            t1 = threading.Thread(target=create)
            t2 = threading.Thread(target=create)
            t1.start(); t2.start(); t1.join(); t2.join()
            # recovered state fully consumed, never resurrected
            with ledger._lock:
                assert "j" not in ledger._recovered_jobs

    def test_monitor_concurrent_sample_once_utilization_sane(self):
        """_util_mark swaps under the lock now: concurrent sample_once
        callers (monitor thread + heartbeat latest()) keep utilization
        in [0, 1] and never crash."""
        from comfyui_distributed_tpu.utils.resource import (
            ResourceMonitor)
        mon = ResourceMonitor(interval=60, ring=32,
                              queue_depth_fn=lambda: 0)
        errors = []

        def hammer():
            try:
                for _ in range(30):
                    snap = mon.sample_once()
                    u = snap["utilization"]
                    assert u is None or 0.0 <= u <= 1.0
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        assert mon.n_samples == 120


class TestOpsDrainOffloadRegression:
    """The WAL-appending ledger transitions the async drains used to
    call inline (reassign/mark_hedged) are now executor-offloaded —
    verified by source shape since driving a full drain needs a
    cluster.  The lint gate enforces it structurally; this pins the
    exact sites."""

    def _src(self, rel):
        with open(os.path.join(ROOT, *rel.split("/"))) as f:
            return f.read()

    def test_no_inline_wal_calls_left_in_async_bodies(self):
        report = engine.run_lint(root=ROOT, rules=["async-blocking"])
        assert report.new == []
        # and the shipped baseline grandfathers NO async-blocking
        # finding — the satellite was "fix, don't baseline"
        baseline = engine.load_baseline(ROOT)
        assert not any(k.startswith("async-blocking|")
                       for k in baseline)

    def test_hedge_mark_offloaded_in_tile_drain(self):
        src = self._src(f"{PKG}/ops/tiled_upscale.py")
        assert "lambda: ledger.mark_hedged(" in src
        src2 = self._src(f"{PKG}/ops/distributed.py")
        assert "ledger.mark_hedged(" in src2
        assert "run_in_executor(\n                                None, lambda u=unit: ledger.mark_hedged(" in src2 \
            or "lambda u=unit: ledger.mark_hedged(" in src2


class TestBaselineHygiene:
    def test_no_bug_class_rule_grandfathered(self):
        """Only the audited spine host-edge class is baselined; the
        bug-class rules ship clean.  The ISSUE 15 satellite extends
        the zero set: a *deadlock-cycle* or *wal-fencing* finding (or
        a transitive async-blocking / route drift one) must never be
        grandfathered — like async-blocking, these classes are fixed,
        not baselined."""
        baseline = engine.load_baseline(ROOT)
        assert baseline, "shipped baseline missing"
        bad = [k for k in baseline
               if k.split("|", 1)[0] in ("async-blocking", "lockset",
                                         "env-undeclared",
                                         "env-readme-drift",
                                         "metric-name", "span-attr",
                                         "parse-error",
                                         "async-blocking-transitive",
                                         "deadlock-cycle",
                                         "wal-fencing",
                                         "route-contract",
                                         "tp-spec-discipline",
                                         "cb-slot-state-discipline",
                                         "sim-virtual-time-discipline")]
        assert bad == []


class TestSimVirtualTimeRule:
    """ISSUE 19 satellite: the traffic twin's determinism ban.  Files
    under sim/ may never read the wall clock, draw from the global
    random module, or import jax — the rule is structural (one leak
    silently un-twins every replay) and NEVER baselined."""

    RULE = "sim-virtual-time-discipline"
    SIM_FLEET = f"{PKG}/sim/fleet.py"

    def _mutated(self, anchor, inject):
        full = os.path.join(ROOT, *self.SIM_FLEET.split("/"))
        with open(full, "r", encoding="utf-8") as f:
            src = f.read()
        assert anchor in src, "mutation anchor missing in sim/fleet.py"
        return src.replace(anchor, anchor + inject, 1)

    def test_shipped_sim_is_clean_and_never_baselined(self):
        rep = engine.run_lint(root=ROOT, rules=[self.RULE])
        assert rep.new == [], "\n".join(v.format() for v in rep.new)
        assert not any(k.startswith(f"{self.RULE}|")
                       for k in engine.load_baseline(ROOT))

    def test_seeded_time_import_caught(self):
        src = self._mutated("from __future__ import annotations\n",
                            "import time\n")
        rep = engine.run_lint(root=ROOT, rules=[self.RULE],
                              overrides={self.SIM_FLEET: src})
        assert any(v.rule == self.RULE and "'time'" in v.message
                   and v.path == self.SIM_FLEET for v in rep.new)

    def test_seeded_wall_clock_call_caught(self):
        # no import needed: a smuggled module object (or a stale
        # global) still reads the wall clock — the call site is banned
        src = self._mutated(
            "        now = self.vclock.now\n",
            "        _leak = time.monotonic()  # type: ignore\n")
        rep = engine.run_lint(root=ROOT, rules=[self.RULE],
                              overrides={self.SIM_FLEET: src})
        assert any(v.rule == self.RULE
                   and "time.monotonic" in v.message for v in rep.new)

    def test_seeded_global_random_caught(self):
        src = self._mutated(
            "        end = self.vclock.now + self._service_sample(jid)",
            "\n        _jitter = random.random()  # type: ignore\n")
        rep = engine.run_lint(root=ROOT, rules=[self.RULE],
                              overrides={self.SIM_FLEET: src})
        assert any(v.rule == self.RULE
                   and "random.random" in v.message for v in rep.new)

    def test_seeded_jax_import_caught(self):
        src = self._mutated("from __future__ import annotations\n",
                            "import jax.numpy as jnp\n")
        rep = engine.run_lint(root=ROOT, rules=[self.RULE],
                              overrides={self.SIM_FLEET: src})
        assert any(v.rule == self.RULE and "jax" in v.message
                   for v in rep.new)

    def test_package_imports_stay_legal(self):
        # the sim imports the real policy modules and utils.clock.Rng;
        # the rule must not flag package-internal imports
        src = self._mutated(
            "from comfyui_distributed_tpu.utils.clock import Rng\n",
            "from comfyui_distributed_tpu.utils import clock\n")
        rep = engine.run_lint(root=ROOT, rules=[self.RULE],
                              overrides={self.SIM_FLEET: src})
        assert rep.new == []

    def test_rule_scoped_to_sim_package(self):
        # `import time` is everyday code everywhere else in the repo
        target = f"{PKG}/runtime/autoscale.py"
        full = os.path.join(ROOT, *target.split("/"))
        with open(full, "r", encoding="utf-8") as f:
            src = f.read()
        rep = engine.run_lint(
            root=ROOT, rules=[self.RULE],
            overrides={target: "import time\nimport random\n" + src})
        assert rep.new == []


# =============================================================================
# dtpu-lint v2: the interprocedural tier (ISSUE 15)
# =============================================================================

from comfyui_distributed_tpu.analysis import callgraph as cg  # noqa: E402


def mini_project(files):
    """An in-memory project (like lint_sources, but returning the
    Project so tests can inspect the call graph too)."""
    return engine.Project(
        ROOT,
        {rel: engine._parse_file(rel, src)
         for rel, src in files.items() if rel != "README.md"},
        readme=(engine._parse_file("README.md", files["README.md"])
                if "README.md" in files else None))


TRANSITIVE_POS = f"""
import os

async def route(request):
    helper()
    return 1

def helper():
    deeper()

def deeper():
    os.fsync(3)
"""


class TestCallGraphSummaries:
    def test_transitive_chain_through_module_helpers(self):
        vs = lint_sources({f"{PKG}/server/x.py": TRANSITIVE_POS},
                          rules=["async-blocking-transitive"])
        assert len(vs) == 1
        assert "route -> helper -> deeper -> os.fsync()" \
            in vs[0].message
        assert vs[0].chain[-1] == "os.fsync()"
        assert len(vs[0].chain) == 4  # route, helper, deeper, leaf

    def test_direct_blocking_not_double_reported(self):
        src = ("import os\n\nasync def route(request):\n"
               "    os.fsync(3)\n")
        vs = lint_sources({f"{PKG}/server/x.py": src},
                          rules=["async-blocking-transitive"])
        assert vs == []  # v1's finding, not the transitive tier's

    def test_executor_thunk_cuts_chain(self):
        src = """
import os, asyncio, functools, threading

def helper():
    os.fsync(3)

async def named_thunk(request):
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, helper)

async def lambda_thunk(request):
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, lambda: helper())

async def partial_thunk(request):
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, functools.partial(helper))

async def thread_target(request):
    threading.Thread(target=helper, daemon=True).start()
"""
        vs = lint_sources({f"{PKG}/server/x.py": src},
                          rules=["async-blocking-transitive"])
        assert vs == []

    def test_off_loop_helper_cuts_chain(self):
        src = """
import os

def _spill_off_loop():
    os.fsync(3)

async def route(request):
    _spill_off_loop()
"""
        vs = lint_sources({f"{PKG}/server/x.py": src},
                          rules=["async-blocking-transitive"])
        assert vs == []

    def test_recursion_bounded_fixpoint_terminates(self):
        src = """
import os

def ping(n):
    pong(n)

def pong(n):
    ping(n - 1)
    os.fsync(3)

async def route(request):
    ping(9)
"""
        project = mini_project({f"{PKG}/server/x.py": src})
        vs = engine.lint_project(project,
                                 rules=["async-blocking-transitive"])
        assert len(vs) == 1 and "os.fsync" in vs[0].message
        graph = cg.get_callgraph(project)
        assert graph.stats["block_fixpoint_passes"] \
            <= cg.MAX_FIXPOINT_PASSES

    def test_dynamic_dispatch_unknown_callee_is_conservative(self):
        """An unresolvable obj.method() gets no summary — no finding,
        but the gap is COUNTED (surfaced by `cli lint --stats`)."""
        src = """
async def route(request):
    request.app.mystery_dispatch()
"""
        project = mini_project({f"{PKG}/server/x.py": src})
        vs = engine.lint_project(project,
                                 rules=["async-blocking-transitive"])
        assert vs == []
        graph = cg.get_callgraph(project)
        assert graph.stats["unresolved_calls"] >= 1

    def test_unique_attr_resolution_crosses_files(self):
        helper_mod = """
import os

class SpillPlane:
    def spill_everything(self):
        os.fsync(3)
"""
        app_mod = """
async def route(request):
    request.plane.spill_everything()
"""
        vs = lint_sources({f"{PKG}/runtime/plane.py": helper_mod,
                           f"{PKG}/server/x.py": app_mod},
                          rules=["async-blocking-transitive"])
        assert len(vs) == 1
        assert "SpillPlane.spill_everything" in vs[0].message


# --- deadlock-cycle ----------------------------------------------------------

ABBA_SRC = """
import threading

class Alpha:
    def __init__(self, beta):
        self._lock = threading.Lock()
        self.beta = beta

    def forward(self):
        with self._lock:
            self.beta.poke_beta()

    def poke_alpha(self):
        with self._lock:
            pass

class Beta:
    def __init__(self, alpha):
        self._lock = threading.Lock()
        self.alpha = alpha

    def backward(self):
        with self._lock:
            self.alpha.poke_alpha()

    def poke_beta(self):
        with self._lock:
            pass
"""


class TestDeadlockCycleRule:
    def test_abba_cycle_reported_with_both_witness_chains(self):
        vs = lint_sources({f"{PKG}/runtime/abba.py": ABBA_SRC},
                          rules=["deadlock-cycle"])
        assert len(vs) == 1
        v = vs[0]
        assert "Alpha._lock" in v.message and "Beta._lock" in v.message
        # one witness chain per cycle edge, both directions
        assert len(v.chain) == 2
        joined = " ".join(v.chain)
        assert "Alpha.forward" in joined and "Beta.backward" in joined

    def test_consistent_order_is_clean(self):
        src = ABBA_SRC.replace(
            "    def backward(self):\n"
            "        with self._lock:\n"
            "            self.alpha.poke_alpha()\n",
            "    def backward(self):\n"
            "        self.alpha.poke_alpha()\n")
        vs = lint_sources({f"{PKG}/runtime/abba.py": src},
                          rules=["deadlock-cycle"])
        assert vs == []

    def test_thread_handoff_under_lock_is_not_an_edge(self):
        """A Thread(target=...) started while holding a lock runs
        later, without the lexical lock — no ordering edge, no false
        cycle."""
        src = ABBA_SRC.replace(
            "        with self._lock:\n"
            "            self.beta.poke_beta()\n",
            "        with self._lock:\n"
            "            threading.Thread(\n"
            "                target=self.beta.poke_beta).start()\n")
        vs = lint_sources({f"{PKG}/runtime/abba.py": src},
                          rules=["deadlock-cycle"])
        assert vs == []

    def test_holds_marker_seeds_the_held_set(self):
        """A `# dtpu-lint: holds[self._lock]` caller-holds contract
        contributes ordering edges exactly like a lexical `with`: the
        contract-held lock is the outer of every acquisition the body
        reaches."""
        src = """
import threading

class Gamma:
    def __init__(self, delta):
        self._lock = threading.Lock()
        self.delta = delta

    # dtpu-lint: holds[self._lock]
    def under_contract(self):
        self.delta.poke_delta()

    def grab_gamma(self):
        with self._lock:
            pass

class Delta:
    def __init__(self, gamma):
        self._lock = threading.Lock()
        self.gamma = gamma

    def poke_delta(self):
        with self._lock:
            pass

    def reverse(self):
        with self._lock:
            self.gamma.grab_gamma()
"""
        vs = lint_sources({f"{PKG}/runtime/hold.py": src},
                          rules=["deadlock-cycle"])
        assert len(vs) == 1
        assert "Gamma._lock" in vs[0].message \
            and "Delta._lock" in vs[0].message


# --- wal-fencing -------------------------------------------------------------

class TestWalFencingRule:
    def test_raw_append_outside_fenced_surfaces_flagged(self):
        src = """
class SneakyPlane:
    def __init__(self, wal):
        self._wal = wal

    def mutate(self):
        self._wal.append("exec_done", pid="x")
"""
        vs = lint_sources({f"{PKG}/runtime/sneaky.py": src},
                          rules=["wal-fencing"])
        assert len(vs) == 1
        assert "raw WAL append" in vs[0].message

    def test_plane_chokepoints_allowed(self):
        src = """
class WorkLedger:
    def _wal_append(self, rtype, **fields):
        self._wal.append(rtype, **fields)


class JobStore:
    def _log_idem(self, scope, job_id, idem_key):
        self._wal.append("idem", scope=scope, job=job_id,
                         key=idem_key)
"""
        vs = lint_sources({f"{PKG}/runtime/planes.py": src},
                          rules=["wal-fencing"])
        assert vs == []

    def test_uncredentialed_ctor_flagged_credentialed_allowed(self):
        bad = """
from comfyui_distributed_tpu.runtime.durable import WriteAheadLog

def zombie_writer(path):
    wal = WriteAheadLog(path, epoch=1)
    wal.append("enqueue", pid="p")
"""
        vs = lint_sources({f"{PKG}/runtime/z.py": bad},
                          rules=["wal-fencing"])
        # the lease-less construction AND its append are both findings
        assert len(vs) == 2
        assert any("fencing credentials" in v.message for v in vs)
        good = bad.replace("WriteAheadLog(path, epoch=1)",
                           "WriteAheadLog(path, epoch=epoch, "
                           "lease=lease)")
        vs = lint_sources({f"{PKG}/runtime/z.py": good},
                          rules=["wal-fencing"])
        assert vs == []

    def test_recovery_surface_needs_epoch_checked_entry(self):
        bad = """
def casual_merge(state, replayed):
    state.ledger.merge_recovered(dict(replayed.jobs))
"""
        vs = lint_sources({f"{PKG}/runtime/m.py": bad},
                          rules=["wal-fencing"])
        assert len(vs) == 1
        assert "epoch-checked entry point" in vs[0].message
        good = """
def takeover_merge(state, replayed, lease, lease_s):
    epoch = lease.acquire("m0", lease_s)
    state.ledger.merge_recovered(dict(replayed.jobs))
"""
        vs = lint_sources({f"{PKG}/runtime/m.py": good},
                          rules=["wal-fencing"])
        assert vs == []

    def test_replay_state_mutation_outside_durable_flagged(self):
        src = """
def poke(wal, rec):
    wal.tracker.apply(rec)
"""
        vs = lint_sources({f"{PKG}/runtime/r.py": src},
                          rules=["wal-fencing"])
        assert len(vs) == 1
        assert "ReplayState" in vs[0].message


# --- route-contract ----------------------------------------------------------

ROUTE_APP = """
from aiohttp import web
from comfyui_distributed_tpu.utils import trace as trace_mod


def build_app(state):
    app = web.Application()
    r = app.router

    async def traced(request):
        trace_mod.start_span("job")
        return web.json_response({})

    async def plain(request):
        return web.json_response({})

    r.add_get("/a", traced)
    r.add_post("/b", plain)
    return app
"""

ROUTE_README = """
### HTTP route registry
| Surface | Method | Path | Span | Purpose |
|---|---|---|---|---|
| master | GET | `/a` | span | traced read |
| master | POST | `/b` | — | plain write |
"""


class TestRouteContractRule:
    def test_in_sync_table_is_clean(self):
        vs = lint_sources({f"{PKG}/server/app.py": ROUTE_APP,
                           "README.md": ROUTE_README},
                          rules=["route-contract"])
        assert vs == []

    def test_both_direction_drift(self):
        app = ROUTE_APP.replace(
            'r.add_post("/b", plain)',
            'r.add_post("/b", plain)\n    r.add_get("/ghostless", '
            'plain)')
        readme = ROUTE_README + "| master | GET | `/phantom` | — | gone |\n"
        vs = lint_sources({f"{PKG}/server/app.py": app,
                           "README.md": readme},
                          rules=["route-contract"])
        msgs = " ".join(v.message for v in vs)
        assert len(vs) == 2
        assert "/ghostless" in msgs and "/phantom" in msgs

    def test_span_drift_both_ways(self):
        # documented traced but handler never reaches a span factory
        readme = ROUTE_README.replace("| master | POST | `/b` | — |",
                                      "| master | POST | `/b` | span |")
        vs = lint_sources({f"{PKG}/server/app.py": ROUTE_APP,
                           "README.md": readme},
                          rules=["route-contract"])
        assert len(vs) == 1 and "never reaches a span" in vs[0].message
        # handler traces but the row says untraced
        readme = ROUTE_README.replace("| master | GET | `/a` | span |",
                                      "| master | GET | `/a` | — |")
        vs = lint_sources({f"{PKG}/server/app.py": ROUTE_APP,
                           "README.md": readme},
                          rules=["route-contract"])
        assert len(vs) == 1 and "marks it untraced" in vs[0].message

    def test_router_and_master_surfaces_are_distinct(self):
        app = ROUTE_APP + """

def build_router_app(masters):
    from aiohttp import web as w2
    app = w2.Application()

    async def post_prompt(request):
        return None

    app.router.add_post("/b", post_prompt)
    return app
"""
        # the router's POST /b needs its OWN row — the master row
        # cannot cover it
        vs = lint_sources({f"{PKG}/server/app.py": app,
                           "README.md": ROUTE_README},
                          rules=["route-contract"])
        assert len(vs) == 1 and "(router)" in vs[0].message
        readme = ROUTE_README + "| router | POST | `/b` | — | routed |\n"
        vs = lint_sources({f"{PKG}/server/app.py": app,
                           "README.md": readme},
                          rules=["route-contract"])
        assert vs == []


# --- the live tree + seeded mutations (v2 acceptance) ------------------------

@pytest.fixture(scope="module")
def live_report():
    import time as _time
    t0 = _time.perf_counter()
    report = engine.run_lint(root=ROOT)
    return report, _time.perf_counter() - t0


def _live_src(rel):
    with open(os.path.join(ROOT, *rel.split("/")),
              encoding="utf-8") as f:
        return f.read()


class TestInterprocLiveGate:
    def test_live_tree_clean_and_bug_class_rules_at_zero(self,
                                                         live_report):
        report, _ = live_report
        assert report.new == [], "\n".join(v.format()
                                           for v in report.new)
        # the new bug-class families report ZERO findings on the
        # shipped tree — not zero-new, zero-total (nothing baselined,
        # nothing suppressed away silently)
        for rule in ("async-blocking-transitive", "deadlock-cycle",
                     "wal-fencing", "route-contract",
                     "tp-spec-discipline",
                     "cb-slot-state-discipline"):
            assert report.rule_counts.get(rule, {}).get("found", 0) \
                == 0, rule

    def test_runtime_budget_under_30s(self, live_report):
        """The tier-1 gate stays cheap: the FULL rule suite (call
        graph build + fixpoints included) completes well inside 30s
        on CPU."""
        _, elapsed = live_report
        assert elapsed < 30.0, f"full lint took {elapsed:.1f}s"

    def test_graph_stats_exposed(self, live_report):
        report, _ = live_report
        g = report.graph_stats
        assert g is not None
        assert g["functions"] > 500
        assert g["resolved_by_tier"].get("unique", 0) > 0
        assert g["unresolved_calls"] > 0  # conservative no-summaries
        assert g["block_fixpoint_passes"] <= cg.MAX_FIXPOINT_PASSES

    # -- the four seeded mutations, each vs the SHIPPED baseline --------------

    def test_seeded_transitive_blocking_caught_with_chain(self):
        app = _live_src(f"{PKG}/server/app.py")
        anchor = "    async def interrupt(request):\n"
        assert anchor in app
        mutated = app.replace(
            anchor, anchor + "        _seeded_sync_helper()\n", 1) + (
            "\n\ndef _seeded_sync_helper():\n"
            "    _seeded_deeper_helper()\n"
            "\n\ndef _seeded_deeper_helper():\n"
            "    os.fsync(0)\n")
        rep = engine.run_lint(
            root=ROOT, overrides={f"{PKG}/server/app.py": mutated})
        hits = [v for v in rep.new
                if v.rule == "async-blocking-transitive"]
        assert len(hits) == 1
        v = hits[0]
        assert ("interrupt -> _seeded_sync_helper -> "
                "_seeded_deeper_helper -> os.fsync()") in v.message
        assert v.chain[-1] == "os.fsync()"
        assert all(":" in hop for hop in v.chain[:-1])  # file:line hops

    def test_seeded_lock_order_inversion_caught(self):
        """Re-introduce the pre-ISSUE-15 gossip edge (ring lock held
        across queue_remaining) AND seed the reverse edge — the
        detector reports the ABBA cycle with both witness chains."""
        shard = _live_src(f"{PKG}/runtime/shard.py")
        a_ring = ("with self._lock:\n"
                  "            return self._ring_epoch")
        assert a_ring in shard
        shard_mut = shard.replace(
            a_ring,
            "with self._lock:\n"
            "            self._state.queue_remaining()\n"
            "            return self._ring_epoch", 1)
        app = _live_src(f"{PKG}/server/app.py")
        a_q = ("with self._queue_lock:\n"
               "            n = len(self._queue) "
               "+ (1 if self._running else 0)")
        assert a_q in app
        app_mut = app.replace(
            a_q,
            "with self._queue_lock:\n"
            "            self.shard.ring_epoch()\n"
            "            n = len(self._queue) "
            "+ (1 if self._running else 0)", 1)
        rep = engine.run_lint(
            root=ROOT,
            overrides={f"{PKG}/runtime/shard.py": shard_mut,
                       f"{PKG}/server/app.py": app_mut})
        hits = [v for v in rep.new if v.rule == "deadlock-cycle"]
        assert len(hits) == 1
        v = hits[0]
        assert "ServerState._queue_lock" in v.message
        assert "ShardManager._lock" in v.message
        assert len(v.chain) == 2  # both directions witnessed
        joined = " ".join(v.chain)
        assert "ShardManager.ring_epoch" in joined
        assert "ServerState.queue_remaining" in joined

    def test_seeded_unfenced_wal_append_caught(self):
        app = _live_src(f"{PKG}/server/app.py")
        anchor = "    async def interrupt(request):\n"
        mutated = app.replace(
            anchor,
            anchor + '        state.durable.wal.append('
                     '"exec_done", pid="zombie")\n', 1)
        rep = engine.run_lint(
            root=ROOT, overrides={f"{PKG}/server/app.py": mutated})
        hits = [v for v in rep.new if v.rule == "wal-fencing"]
        assert len(hits) == 1
        assert "raw WAL append" in hits[0].message
        assert hits[0].chain  # entry-chain witness attached

    def test_seeded_undocumented_route_caught(self):
        app = _live_src(f"{PKG}/server/app.py")
        anchor = 'r.add_get("/history", history)'
        assert anchor in app
        mutated = app.replace(
            anchor,
            anchor + '\n    r.add_get("/distributed/lint_probe", '
                     'history)', 1)
        rep = engine.run_lint(
            root=ROOT, overrides={f"{PKG}/server/app.py": mutated})
        hits = [v for v in rep.new if v.rule == "route-contract"]
        assert len(hits) == 1
        assert "/distributed/lint_probe" in hits[0].message

    def test_readme_ghost_route_caught(self):
        readme = _live_src("README.md")
        anchor = "| router | GET | `/distributed/fleet` | — |"
        assert anchor in readme
        mutated = readme.replace(
            anchor,
            "| master | GET | `/distributed/ghost_route` | — | "
            "gone |\n" + anchor, 1)
        rep = engine.run_lint(root=ROOT,
                              overrides={"README.md": mutated})
        hits = [v for v in rep.new if v.rule == "route-contract"]
        assert len(hits) == 1
        assert "/distributed/ghost_route" in hits[0].message
        assert hits[0].path == "README.md"


# --- cli lint v2 flags -------------------------------------------------------

class TestCliLintV2:
    def test_stats_flag(self, capsys):
        from comfyui_distributed_tpu import cli
        rc = cli.main(["lint", "--stats"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "per-rule stats" in out
        assert "call graph:" in out
        assert "fixpoint passes:" in out
        assert "async-blocking-transitive" in out

    def test_graph_flag_dumps_json(self, capsys):
        from comfyui_distributed_tpu import cli
        rc = cli.main(["lint", "--graph"])
        out = capsys.readouterr().out
        assert rc == 0
        data = json.loads(out)
        assert data["functions"] > 500
        assert isinstance(data["lock_edges"], list)
        assert all({"outer", "inner", "witnesses"} <= set(e)
                   for e in data["lock_edges"])

    def test_chain_flag_prints_witness(self, tmp_path, capsys):
        pkg = tmp_path / PKG
        (pkg / "server").mkdir(parents=True)
        (pkg / "analysis").mkdir()
        (pkg / "server" / "app.py").write_text(
            "import os\n\n"
            "async def h(request):\n"
            "    helper()\n\n"
            "def helper():\n"
            "    os.fsync(1)\n")
        from comfyui_distributed_tpu import cli
        rc = cli.main(["lint", "--root", str(tmp_path), "--chain"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "witness chain:" in out
        assert "helper" in out and "os.fsync()" in out


# --- regression tests for the REAL v2 findings fixed this PR -----------------

class TestV2OffloadRegressions:
    """profile_start/profile_stop (device-trace start mkdirs + flush)
    and managed_workers (pid liveness probes subprocess) were the two
    live async-blocking-transitive findings — all three now run their
    blocking core on an executor thread (fixed, not baselined)."""

    @pytest.fixture()
    def app_state(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DTPU_RESOURCE", "0")
        from comfyui_distributed_tpu.server.app import ServerState
        return ServerState(start_exec_thread=False,
                           input_dir=str(tmp_path / "in"),
                           output_dir=str(tmp_path / "out"))

    def _handler(self, state, name):
        from comfyui_distributed_tpu.server.app import build_app
        app = build_app(state)
        for route in app.router.routes():
            if route.handler.__name__ == name:
                return route.handler
        raise AssertionError(f"route handler {name} not found")

    class _Req:
        can_read_body = False
        remote = "127.0.0.1"

        async def json(self):
            return {}

    def test_profile_start_offloaded(self, app_state, monkeypatch):
        from comfyui_distributed_tpu.utils import trace as trace_mod
        record = []

        def fake_start(out_dir=None):
            record.append(threading.current_thread())
            return "/tmp/t"
        monkeypatch.setattr(trace_mod, "start_device_trace",
                            fake_start)
        handler = self._handler(app_state, "profile_start")
        resp = asyncio.new_event_loop().run_until_complete(
            handler(self._Req()))
        assert resp.status == 200
        assert record and all(t is not threading.current_thread()
                              for t in record)

    def test_profile_stop_offloaded(self, app_state, monkeypatch):
        from comfyui_distributed_tpu.utils import trace as trace_mod
        record = []

        def fake_stop():
            record.append(threading.current_thread())
            return "/tmp/t"
        monkeypatch.setattr(trace_mod, "stop_device_trace", fake_stop)
        handler = self._handler(app_state, "profile_stop")
        resp = asyncio.new_event_loop().run_until_complete(
            handler(self._Req()))
        assert resp.status == 200
        assert record and all(t is not threading.current_thread()
                              for t in record)

    def test_managed_workers_offloaded(self, app_state):
        record = []

        def fake_managed():
            record.append(threading.current_thread())
            return []
        app_state.manager.get_managed_workers = fake_managed
        handler = self._handler(app_state, "managed_workers")
        resp = asyncio.new_event_loop().run_until_complete(
            handler(self._Req()))
        assert resp.status == 200
        assert record and all(t is not threading.current_thread()
                              for t in record)


class TestLockNarrowingRegressions:
    """The deadlock-cycle edge dump drove two critical-section
    narrowings: ShardManager._gossip_payload no longer calls into
    ServerState while holding the ring lock, and enqueue_prompt's
    rejection paths seal/commit the job span AFTER releasing the
    queue lock."""

    def test_gossip_payload_reads_queue_outside_ring_lock(self):
        from comfyui_distributed_tpu.runtime.shard import ShardManager
        holder = {}
        calls = []

        class _FakeState:
            is_worker = False

            def queue_remaining(self):
                calls.append(holder["mgr"]._lock.locked())
                return 7

        mgr = ShardManager(_FakeState(), "m0", {"m0": ""},
                           start_threads=False)
        holder["mgr"] = mgr
        payload = mgr._gossip_payload()
        assert payload["queue_remaining"] == 7
        assert calls == [False], \
            "queue_remaining called while holding ShardManager._lock"

    def test_enqueue_rejection_seals_span_outside_queue_lock(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("DTPU_RESOURCE", "0")
        monkeypatch.setenv("DTPU_MAX_QUEUE", "1")
        from comfyui_distributed_tpu.server import app as app_mod
        state = app_mod.ServerState(start_exec_thread=False,
                                    input_dir=str(tmp_path / "in"),
                                    output_dir=str(tmp_path / "out"))
        with state._queue_lock:
            state._queue.append({"id": "p0", "prompt": {},
                                 "client_id": "c", "extra_data": {},
                                 "sig": None, "cb": False,
                                 "rkey": None, "tenant": "paid",
                                 "span": None, "t_enq": 0.0})
        lock_states = []
        state._abandon_span = (
            lambda sp, pid, reason:
            lock_states.append(state._queue_lock.locked()))
        with pytest.raises(app_mod.QueueFullError):
            state.enqueue_prompt(
                {"1": {"class_type": "EmptyLatentImage",
                       "inputs": {}}}, "client")
        assert lock_states == [False], \
            "span sealed while still holding the queue lock"
