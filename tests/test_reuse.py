"""Cross-request compute reuse + SSE previews (ISSUE 13).

Covers the three cache tiers (exact-hit result, sub-graph embeddings /
VAE conditioning, changed-tile upscaling), the DTPU_CACHE_* budgets
(LRU order, ResourceMonitor residency ring, the DTPU_CACHE=0 kill
switch's zero-lookup guarantee), bit-identical cache-on vs cache-off
outputs with near-miss keys never hitting, and the preview/cancellation
channel (SSE frames from the CB denoise loop; client-gone abandonment
freeing the batch slot and purging queued copies).
"""

import asyncio
import base64
import json
import os
import queue as queue_mod
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.runtime import reuse as reuse_mod
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import resource as resource_mod
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.image import encode_png
from comfyui_distributed_tpu.workflow.executor import WorkflowExecutor


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture(autouse=True)
def fresh_plane():
    """Every test sees an empty plane built from ITS env pins, and
    leaves a clean one behind (the plane is process-global)."""
    plane = reuse_mod.reset_reuse()
    yield plane
    reuse_mod.reset_reuse()


def make_prompt(seed, steps=1, size=32, text="cat", cfg=2.0,
                sampler="euler"):
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": text, "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "9": {"class_type": "EmptyLatentImage",
              "inputs": {"width": size, "height": size, "batch_size": 1}},
        "8": {"class_type": "KSampler",
              "inputs": {"model": ["7", 0], "positive": ["5", 0],
                         "negative": ["6", 0], "latent_image": ["9", 0],
                         "seed": seed, "steps": steps, "cfg": cfg,
                         "sampler_name": sampler, "scheduler": "normal",
                         "denoise": 1.0}},
        "1": {"class_type": "VAEDecode",
              "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
        "3": {"class_type": "PreviewImage", "inputs": {"images": ["1", 0]}},
    }


def img2img_prompt(seed, name="cond.png", steps=1):
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "remix", "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "10": {"class_type": "LoadImage", "inputs": {"image": name}},
        "11": {"class_type": "VAEEncode",
               "inputs": {"pixels": ["10", 0], "vae": ["7", 2]}},
        "8": {"class_type": "KSampler",
              "inputs": {"model": ["7", 0], "positive": ["5", 0],
                         "negative": ["6", 0], "latent_image": ["11", 0],
                         "seed": seed, "steps": steps, "cfg": 2.0,
                         "sampler_name": "euler", "scheduler": "normal",
                         "denoise": 0.6}},
        "1": {"class_type": "VAEDecode",
              "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
        "3": {"class_type": "PreviewImage", "inputs": {"images": ["1", 0]}},
    }


def upscale_prompt(seed=7, denoise=0.4, name="src.png"):
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "a map", "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "10": {"class_type": "LoadImage", "inputs": {"image": name}},
        "2": {"class_type": "UltimateSDUpscaleDistributed",
              "inputs": {"upscaled_image": ["10", 0], "model": ["7", 0],
                         "positive": ["5", 0], "negative": ["6", 0],
                         "vae": ["7", 2], "seed": seed, "steps": 1,
                         "cfg": 2.0, "sampler_name": "euler",
                         "scheduler": "normal", "denoise": denoise,
                         "tile_width": 32, "tile_height": 32,
                         "padding": 8, "mask_blur": 2,
                         "force_uniform_tiles": True}},
        "3": {"class_type": "PreviewImage", "inputs": {"images": ["2", 0]}},
    }


def make_state(tmp_path, **kw):
    return ServerState(config_path=str(tmp_path / "cfg.json"),
                       input_dir=str(tmp_path / "in"),
                       output_dir=str(tmp_path / "out"), **kw)


def wait_history(state, pids, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(p in state._history for p in pids):
            return {p: state._history[p] for p in pids}
        time.sleep(0.01)
    raise AssertionError(f"prompts never finished: "
                         f"{[p for p in pids if p not in state._history]}")


# --- keys --------------------------------------------------------------------

class TestKeys:
    def test_result_key_deterministic(self):
        a = reuse_mod.result_key(make_prompt(42))
        b = reuse_mod.result_key(make_prompt(42))
        assert a is not None and a == b

    @pytest.mark.parametrize("mutate", [
        lambda p: p["8"]["inputs"].__setitem__("seed", 43),
        lambda p: p["8"]["inputs"].__setitem__("cfg", 2.5),
        lambda p: p["8"]["inputs"].__setitem__("steps", 2),
        lambda p: p["5"]["inputs"].__setitem__("text", "dog"),
        lambda p: p["9"]["inputs"].__setitem__("width", 64),
    ])
    def test_near_miss_changes_key(self, mutate):
        base = reuse_mod.result_key(make_prompt(42))
        changed = make_prompt(42)
        mutate(changed)
        assert reuse_mod.result_key(changed) != base

    def test_result_key_load_image_stat_salt(self, tmp_path):
        p = img2img_prompt(1)
        path = tmp_path / "cond.png"
        path.write_bytes(encode_png(np.zeros((1, 8, 8, 3), np.float32)))
        k1 = reuse_mod.result_key(p, input_dir=str(tmp_path))
        assert k1 is not None
        # same name, different content on disk -> different key (a
        # re-upload must never replay the old image's outputs)
        path.write_bytes(encode_png(np.ones((1, 16, 16, 3), np.float32)))
        assert reuse_mod.result_key(p, input_dir=str(tmp_path)) != k1

    def test_uncacheable_graphs(self):
        p = make_prompt(1)
        p["8"]["hidden"] = {"multi_job_id": "j"}   # orchestrated state
        assert reuse_mod.result_key(p) is None
        assert reuse_mod.result_key(
            {"1": {"class_type": "CheckpointLoaderSimple",
                   "inputs": {"ckpt_name": "x"}}}) is None
        # SaveImage graphs never replay: a replay cannot write the new
        # counter-numbered file the node's contract promises per queue
        p = make_prompt(1)
        p["3"] = {"class_type": "SaveImage",
                  "inputs": {"images": ["1", 0],
                             "filename_prefix": "x"}}
        assert reuse_mod.result_key(p) is None

    def test_subgraph_keys_propagate_upstream_changes(self):
        from comfyui_distributed_tpu.workflow.graph import parse_workflow
        g1 = parse_workflow(make_prompt(1, text="cat"))
        g2 = parse_workflow(make_prompt(1, text="dog"))
        k1 = reuse_mod.subgraph_keys(g1, {})
        k2 = reuse_mod.subgraph_keys(g2, {})
        assert k1["5"] != k2["5"]          # the encode node re-keys
        assert k1["7"] == k2["7"]          # the loader does not
        assert k1["6"] == k2["6"]          # untouched branch stable

    def test_subgraph_keys_hidden_override_disqualifies(self):
        from comfyui_distributed_tpu.workflow.graph import parse_workflow
        g = parse_workflow(make_prompt(1))
        keys = reuse_mod.subgraph_keys(g, {"5": {"anything": 1}})
        assert "5" not in keys

    def test_load_image_stat_salt(self, tmp_path):
        from comfyui_distributed_tpu.workflow.graph import parse_workflow
        img = np.zeros((1, 8, 8, 3), np.float32)
        path = tmp_path / "a.png"
        path.write_bytes(encode_png(img))
        g = parse_workflow(img2img_prompt(1, name="a.png"))
        k1 = reuse_mod.subgraph_keys(g, {}, input_dir=str(tmp_path))
        # rewrite with different content (different size on disk)
        path.write_bytes(encode_png(np.ones((1, 16, 16, 3), np.float32)))
        k2 = reuse_mod.subgraph_keys(g, {}, input_dir=str(tmp_path))
        assert k1["10"] != k2["10"]
        assert k1["11"] != k2["11"]        # propagates into VAEEncode


# --- the bounded LRU ---------------------------------------------------------

class TestByteLRU:
    def test_lru_eviction_order_under_byte_budget(self):
        lru = reuse_mod.ByteLRU("t", max_bytes=1000, max_entries=100)
        for i in range(5):
            lru.put(f"k{i}", i, 300)       # 5 x 300 > 1000
        # budget holds and the OLDEST entries were evicted first
        assert lru.bytes <= 1000
        assert lru.keys() == ["k2", "k3", "k4"]
        # a get refreshes recency: k2 survives the next eviction
        assert lru.get("k2") == 2
        lru.put("k5", 5, 300)
        assert "k2" in lru.keys() and "k3" not in lru.keys()
        assert lru.snapshot()["evictions"] == 3

    def test_oversized_value_rejected(self):
        lru = reuse_mod.ByteLRU("t", max_bytes=100, max_entries=10)
        assert not lru.put("big", 1, 101)
        assert len(lru) == 0

    def test_entry_cap_and_clear(self):
        lru = reuse_mod.ByteLRU("t", max_bytes=1 << 20, max_entries=2)
        for i in range(4):
            lru.put(f"k{i}", i, 10)
        assert lru.keys() == ["k2", "k3"]
        assert lru.clear() == 20
        assert len(lru) == 0 and lru.bytes == 0

    def test_budget_env_resolution(self, monkeypatch):
        monkeypatch.setenv(C.CACHE_BYTES_ENV, "4096")
        monkeypatch.setenv(C.CACHE_ENTRIES_ENV, "7")
        plane = reuse_mod.ReusePlane()
        assert plane.result.max_bytes == 4096
        assert plane.result.max_entries == 7

    def test_monitor_ring_bounded_residency(self, monkeypatch):
        """Fill past DTPU_CACHE_BYTES: the plane stays inside the
        budget and the ResourceMonitor's cache_bytes ring reports the
        bounded residency (satellite: eviction under the telemetry
        budget)."""
        monkeypatch.setenv(C.CACHE_BYTES_ENV, "2048")
        plane = reuse_mod.reset_reuse()
        for i in range(16):
            plane.result.put(f"k{i}", {"images": []}, 512)
        assert plane.result.bytes <= 2048
        assert plane.result.snapshot()["evictions"] == 12
        mon = resource_mod.ResourceMonitor(interval=60)
        mon.sample_once()
        pts = mon.series_tail("cache_bytes")
        assert pts and pts[-1][1] == plane.bytes_total()
        assert pts[-1][1] <= 2048


# --- kill switch -------------------------------------------------------------

class TestKillSwitch:
    def test_cache_off_means_zero_lookups(self, tmp_path, monkeypatch):
        """DTPU_CACHE=0 must keep the hot path from touching the caches
        AT ALL (the DTPU_RESOURCE=0 pattern): poison every cache method
        and the key builders — a run must never call them."""
        monkeypatch.setenv(C.CACHE_ENV, "0")

        def boom(*a, **k):
            raise AssertionError("cache touched with DTPU_CACHE=0")

        monkeypatch.setattr(reuse_mod.ByteLRU, "get", boom)
        monkeypatch.setattr(reuse_mod.ByteLRU, "put", boom)
        monkeypatch.setattr(reuse_mod, "result_key", boom)
        monkeypatch.setattr(reuse_mod, "subgraph_keys", boom)
        st = make_state(tmp_path)
        pid = st.enqueue_prompt(make_prompt(11), "c")
        hist = wait_history(st, [pid])
        assert hist[pid]["status"] == "success"
        assert "cache_hit" not in hist[pid]

    def test_cache_off_tile_path(self, tmp_path, monkeypatch):
        monkeypatch.setenv(C.CACHE_ENV, "0")

        def boom(*a, **k):
            raise AssertionError("tile cache touched with DTPU_CACHE=0")

        monkeypatch.setattr(reuse_mod, "tile_keys", boom)
        monkeypatch.setattr(reuse_mod, "conditioning_fingerprint", boom)
        (tmp_path / "src.png").write_bytes(
            encode_png(np.zeros((1, 64, 64, 3), np.float32)))
        res = WorkflowExecutor(OpContext(
            input_dir=str(tmp_path), output_dir=str(tmp_path))).execute(
            upscale_prompt())
        assert len(res.images) == 1


# --- result tier (server level) ----------------------------------------------

class TestResultTier:
    def test_exact_hit_replay_and_near_miss(self, tmp_path):
        st = make_state(tmp_path)
        pid1 = st.enqueue_prompt(make_prompt(42), "c")
        wait_history(st, [pid1])
        # byte-identical re-submission: settled synchronously, stamped
        t0 = time.perf_counter()
        pid2 = st.enqueue_prompt(make_prompt(42), "c")
        replay_s = time.perf_counter() - t0
        assert st._history[pid2]["cache_hit"] is True
        assert st._history[pid2]["status"] == "success"
        assert replay_s < 1.0
        assert st.metrics["prompts_replayed"] == 1
        # the replayed job committed a trace with the cache attrs
        rec = trace_mod.GLOBAL_TRACES.get(pid2)
        assert rec is not None
        root = next(s for s in rec["spans"]
                    if s["span_id"] == rec["root_span_id"])
        assert root["attrs"]["cache_hit"] is True
        assert root["attrs"]["cache_tier"] == "result"
        # near miss: ONE widget changed -> full execution, no hit
        pid3 = st.enqueue_prompt(make_prompt(42, cfg=2.5), "c")
        hist = wait_history(st, [pid3])
        assert "cache_hit" not in hist[pid3]
        assert st.metrics["prompts_replayed"] == 1

    def test_replay_bit_identical_to_recompute(self, tmp_path,
                                               fresh_plane):
        st = make_state(tmp_path)
        pid1 = st.enqueue_prompt(make_prompt(7), "c")
        wait_history(st, [pid1])
        key = reuse_mod.result_key(make_prompt(7),
                                   input_dir=st.input_dir)
        stored = fresh_plane.result.get(key)["images"]
        # recompute from scratch (cache emptied): same bytes
        fresh_plane.result.clear()
        pid2 = st.enqueue_prompt(make_prompt(7), "c")
        wait_history(st, [pid2])
        again = fresh_plane.result.get(key)["images"]
        assert len(stored) == len(again) == 1
        assert np.array_equal(stored[0], again[0])

    def test_clear_memory_invalidates_and_reports(self, tmp_path):
        async def go():
            state = make_state(tmp_path)
            app = build_app(state)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                loop = asyncio.get_running_loop()
                pid = await loop.run_in_executor(
                    None, lambda: state.enqueue_prompt(
                        make_prompt(5), "c"))
                await loop.run_in_executor(
                    None, lambda: wait_history(state, [pid]))
                plane = reuse_mod.get_reuse()
                assert plane.bytes_total() > 0
                r = await client.post("/distributed/clear_memory")
                body = await r.json()
                assert r.status == 200
                assert body["cache_freed_bytes"] > 0
                assert plane.bytes_total() == 0
                # a re-submission now re-executes (no stale replay)
                pid2 = await loop.run_in_executor(
                    None, lambda: state.enqueue_prompt(
                        make_prompt(5), "c"))
                hist = await loop.run_in_executor(
                    None, lambda: wait_history(state, [pid2]))
                assert "cache_hit" not in hist[pid2]
            finally:
                await client.close()
        asyncio.run(go())


# --- sub-graph tier ----------------------------------------------------------

class TestEmbedTier:
    def test_variant_storm_hits_and_stays_bit_identical(self, tmp_path,
                                                        monkeypatch):
        """Seed variants share the text encodes; the cached-conditioning
        run's image is bit-identical to a cache-off run."""
        ctx = lambda: OpContext(input_dir=str(tmp_path),  # noqa: E731
                                output_dir=str(tmp_path))
        WorkflowExecutor(ctx()).execute(make_prompt(1))    # warm the cache
        before = reuse_mod.get_reuse().subgraph.snapshot()["hits"]
        cached = WorkflowExecutor(ctx()).execute(make_prompt(2))
        assert reuse_mod.get_reuse().subgraph.snapshot()["hits"] \
            >= before + 2                                  # both encodes
        monkeypatch.setenv(C.CACHE_ENV, "0")
        plain = WorkflowExecutor(ctx()).execute(make_prompt(2))
        assert np.array_equal(cached.images[0], plain.images[0])

    def test_vae_encode_tier_bit_identical(self, tmp_path, monkeypatch):
        (tmp_path / "cond.png").write_bytes(encode_png(
            np.linspace(0, 1, 1 * 32 * 32 * 3, dtype=np.float32)
            .reshape(1, 32, 32, 3)))
        ctx = lambda: OpContext(input_dir=str(tmp_path),  # noqa: E731
                                output_dir=str(tmp_path))
        WorkflowExecutor(ctx()).execute(img2img_prompt(1))
        hits0 = trace_mod.GLOBAL_COUNTERS.get("cache_embed_hits")
        cached = WorkflowExecutor(ctx()).execute(img2img_prompt(2))
        assert trace_mod.GLOBAL_COUNTERS.get("cache_embed_hits") \
            >= hits0 + 3                   # 2 text encodes + VAE encode
        monkeypatch.setenv(C.CACHE_ENV, "0")
        plain = WorkflowExecutor(ctx()).execute(img2img_prompt(2))
        assert np.array_equal(cached.images[0], plain.images[0])


# --- tile tier ---------------------------------------------------------------

@pytest.mark.slow
class TestTileTier:
    def _write_src(self, tmp_path, mutate_corner=False):
        rng = np.random.default_rng(3)
        img = rng.random((1, 64, 64, 3)).astype(np.float32)
        if mutate_corner:
            img[0, :16, :16, :] = 0.5      # dirties ONLY tile 0 (of 4)
        (tmp_path / "src.png").write_bytes(encode_png(img))

    def test_changed_tile_only_refine_bit_identical(self, tmp_path):
        ctx = lambda: OpContext(input_dir=str(tmp_path),  # noqa: E731
                                output_dir=str(tmp_path))
        self._write_src(tmp_path)
        r1 = WorkflowExecutor(ctx()).execute(upscale_prompt())
        # clean re-run: every tile skips, blend identical
        sk0 = trace_mod.GLOBAL_COUNTERS.get("tiles_skipped")
        r2 = WorkflowExecutor(ctx()).execute(upscale_prompt())
        assert trace_mod.GLOBAL_COUNTERS.get("tiles_skipped") == sk0 + 4
        assert np.array_equal(r1.images[0], r2.images[0])
        # dirty ONE tile: skip count == clean-tile count...
        self._write_src(tmp_path, mutate_corner=True)
        sk1 = trace_mod.GLOBAL_COUNTERS.get("tiles_skipped")
        r3 = WorkflowExecutor(ctx()).execute(upscale_prompt())
        assert trace_mod.GLOBAL_COUNTERS.get("tiles_skipped") == sk1 + 3
        # ...and the partial blend matches a full re-run bit-identically
        # at the PNG wire (uint8) level — the same oracle the cluster
        # recovery tests use: XLA may differ at the last float ulp
        # between batch-of-1 and batch-of-4 refine programs, which the
        # 8-bit quantize absorbs exactly like the worker->master wire
        reuse_mod.get_reuse().clear()
        r4 = WorkflowExecutor(ctx()).execute(upscale_prompt())
        assert np.allclose(r3.images[0], r4.images[0], atol=1e-5)
        q = lambda a: np.clip(a * 255.0 + 0.5, 0,  # noqa: E731
                              255).astype(np.uint8)
        assert np.array_equal(q(r3.images[0]), q(r4.images[0]))

    def test_param_near_miss_never_hits(self, tmp_path):
        ctx = lambda: OpContext(input_dir=str(tmp_path),  # noqa: E731
                                output_dir=str(tmp_path))
        self._write_src(tmp_path)
        WorkflowExecutor(ctx()).execute(upscale_prompt(denoise=0.4))
        hits0 = reuse_mod.get_reuse().tiles.snapshot()["hits"]
        WorkflowExecutor(ctx()).execute(upscale_prompt(denoise=0.5))
        assert reuse_mod.get_reuse().tiles.snapshot()["hits"] == hits0


# --- previews + client-gone cancellation -------------------------------------

class TestPreviewChannel:
    def test_latent_preview_png(self):
        png = reuse_mod.latent_preview_png(
            np.random.default_rng(0).normal(size=(1, 8, 8, 4)))
        assert png[:4] == b"\x89PNG"

    def test_bus_subscribe_publish_finish(self):
        bus = reuse_mod.PreviewBus(max_clients=2)
        q = bus.subscribe("p1")
        assert bus.wants("p1") and not bus.wants("p2")
        bus.publish_latent("p1", 3, 10, np.zeros((1, 4, 4, 4)))
        ev = q.get_nowait()
        assert ev["type"] == "preview" and ev["step"] == 3
        bus.finish("p1", "success")
        assert q.get_nowait()["type"] == "done"
        assert bus.unsubscribe("p1", q) == 0
        # client cap
        a, b = bus.subscribe("x"), bus.subscribe("y")
        assert a is not None and b is not None
        assert bus.subscribe("z") is None

    def test_abandoned_queued_prompt_is_purged(self, tmp_path):
        st = make_state(tmp_path)
        st._exec_gate.clear()
        try:
            pid = st.enqueue_prompt(make_prompt(21, steps=1), "c")
            reuse_mod.PREVIEWS.abandon(pid)
        finally:
            st._exec_gate.set()
        hist = wait_history(st, [pid])
        assert hist[pid]["status"] == "abandoned"
        assert st.metrics["prompts_abandoned"] == 1
        # the flag was consumed at finalize
        assert not reuse_mod.PREVIEWS.is_abandoned(pid)

    def test_preview_route_disabled(self, tmp_path, monkeypatch):
        monkeypatch.setenv(C.PREVIEW_ENV, "0")

        async def go():
            state = make_state(tmp_path, start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                r = await client.get("/distributed/preview/p_x")
                assert r.status == 403
            finally:
                await client.close()
        asyncio.run(go())

    def test_done_event_for_finished_prompt(self, tmp_path):
        async def go():
            state = make_state(tmp_path)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                loop = asyncio.get_running_loop()
                pid = await loop.run_in_executor(
                    None, lambda: state.enqueue_prompt(
                        make_prompt(31), "c"))
                await loop.run_in_executor(
                    None, lambda: wait_history(state, [pid]))
                r = await client.get(f"/distributed/preview/{pid}")
                assert r.status == 200
                body = (await r.content.read()).decode()
                assert "event: done" in body
                assert '"status": "success"' in body
            finally:
                await client.close()
        asyncio.run(go())


@pytest.mark.slow
class TestPreviewSSEAcceptance:
    def test_sse_stream_and_client_gone_frees_slot(self, tmp_path):
        """THE channel acceptance over real HTTP: preview frames stream
        from the CB denoise loop; dropping the connection mid-stream
        abandons the job — its slot exits at the next step boundary
        (freeing capacity for the sibling, which completes), and the
        history records the abandonment."""
        async def go():
            state = make_state(tmp_path, cb=True)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                loop = asyncio.get_running_loop()
                pid_long = await loop.run_in_executor(
                    None, lambda: state.enqueue_prompt(
                        make_prompt(1, steps=80), "c"))
                resp = await client.get(
                    f"/distributed/preview/{pid_long}")
                assert resp.status == 200
                # read until one COMPLETE preview frame arrives (the
                # base64 PNG spans several reads; a frame ends at \n\n)
                buf = b""
                deadline = time.monotonic() + 120
                marker = b"event: preview\ndata: "
                while time.monotonic() < deadline:
                    buf += await resp.content.read(256)
                    at = buf.find(marker)
                    if at >= 0 and buf.find(b"\n\n", at) >= 0:
                        break
                at = buf.find(marker)
                assert at >= 0, buf[:200]
                frame = buf[at + len(marker):buf.find(b"\n\n", at)]
                ev = json.loads(frame)
                png = base64.b64decode(ev["png_b64"])
                assert png[:4] == b"\x89PNG"
                assert ev["total_steps"] == 80
                # client gone: hard-close the connection mid-stream
                resp.close()
                await asyncio.sleep(0)
                pid_next = await loop.run_in_executor(
                    None, lambda: state.enqueue_prompt(
                        make_prompt(2, steps=2, text="dog"), "c"))
                hist = await loop.run_in_executor(
                    None, lambda: wait_history(
                        state, [pid_long, pid_next], 120))
                assert hist[pid_long]["status"] == "abandoned"
                assert hist[pid_next]["status"] == "success"
                assert state.cb.snapshot()["slots_active"] == 0
                assert state.cb.snapshot()["abandoned"] == 1
                # both metrics surfaces carry the counters
                m = await (await client.get(
                    "/distributed/metrics")).json()
                assert m["reuse"]["previews"]["clients"] == 0
                assert m["prompts_abandoned"] == 1
                prom = await (await client.get(
                    "/distributed/metrics.prom")).text()
                assert "dtpu_jobs_abandoned_total 1" in prom
                assert "dtpu_preview_events_total" in prom
                assert "dtpu_cache_hits_total" in prom
            finally:
                await client.close()
        asyncio.run(go())


# --- metrics surfaces --------------------------------------------------------

class TestMetricsSurfaces:
    def test_reuse_block_and_prom_families(self, tmp_path):
        async def go():
            state = make_state(tmp_path, start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                m = await (await client.get(
                    "/distributed/metrics")).json()
                assert m["reuse"]["enabled"] is True
                assert set(m["reuse"]) >= {"result", "embed", "tile",
                                           "previews", "bytes_total"}
                prom = await (await client.get(
                    "/distributed/metrics.prom")).text()
                for family in ("dtpu_cache_hits_total",
                               "dtpu_cache_misses_total",
                               "dtpu_cache_bytes",
                               "dtpu_cache_replays_total",
                               "dtpu_cache_tiles_skipped_total",
                               "dtpu_preview_clients",
                               "dtpu_jobs_abandoned_total"):
                    assert family in prom, family
            finally:
                await client.close()
        asyncio.run(go())
