"""Continuous capture plane (ISSUE 18): durable trace export with
rotation + retention, the SLO burn-rate engine, exemplar-linked
histograms, the CB flight deck, and the server surfaces that tie them
together (`/distributed/slo`, extended metrics/reset, Perfetto export).

CPU-only, tier-1-eligible: exporter/engine units run against local
instances; the server tests use in-process ServerStates over aiohttp
TestServer sockets like test_observability.py.
"""

import json
import os
import time
from types import SimpleNamespace

import pytest

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import slo as slo_mod
from comfyui_distributed_tpu.utils import trace as tr
from comfyui_distributed_tpu.utils import trace_export as te
from tests.test_observability import (make_prompt, run_with_client,
                                      validate_prometheus,
                                      wait_remote_history)


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture(autouse=True)
def tracing_on():
    was = tr.tracing_enabled()
    tr.set_tracing(True)
    yield
    tr.set_tracing(was)


@pytest.fixture(autouse=True)
def export_off(monkeypatch):
    """Each test opts into export with its own dir; never inherit one."""
    monkeypatch.delenv(C.TRACE_EXPORT_DIR_ENV, raising=False)
    yield
    # drop the module singleton so the next test re-reads the env
    te.current()


def commit_trace(pid, n_children=2, status="ok", worker=None):
    """One committed multi-span trace through the REAL span model."""
    root = tr.start_span("job", attrs={"prompt_id": pid})
    children = []
    for i in range(n_children):
        attrs = {"node": f"n{i}"}
        if worker:
            attrs["worker"] = worker
        c = tr.start_span(f"compute_{i}", parent=root, attrs=attrs)
        c.end(status="error" if (status != "ok" and i == 0) else "ok")
        children.append(c)
    tr.event_span("queue_wait", root.start_s, root.start_s + 0.01,
                  parent=root)
    root.end()
    tr.GLOBAL_TRACES.commit(pid, root.trace_id, status=status,
                            root_span_id=root.span_id, duration_s=1.25)
    return root


class TestExportRoundTrip:
    def test_roundtrip_field_for_field(self, tmp_path, monkeypatch):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(C.TRACE_EXPORT_DIR_ENV, d)
        root = commit_trace("rt1", status="error")
        mem = tr.GLOBAL_TRACES.get("rt1")
        disk = te.load_trace(d, prompt_id="rt1")
        assert disk is not None
        key = lambda s: s["span_id"]  # noqa: E731
        assert sorted(disk["spans"], key=key) \
            == sorted(mem["spans"], key=key)
        for k in ("prompt_id", "trace_id", "status", "root_span_id",
                  "duration_s", "finished_at"):
            assert disk[k] == mem[k], k
        assert disk["schema"] == te.SCHEMA_VERSION
        assert disk["trace_id"] == root.trace_id
        # the reconstructed forest nests exactly like the in-memory one
        forest = te.load_forest(disk)
        assert [n["name"] for n in forest] == ["job"]
        assert sorted(c["name"] for c in forest[0]["children"]) \
            == ["compute_0", "compute_1", "queue_wait"]

    def test_load_by_trace_id_newest_wins(self, tmp_path, monkeypatch):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(C.TRACE_EXPORT_DIR_ENV, d)
        commit_trace("tw1")
        root2 = commit_trace("tw2")
        assert te.load_trace(d, trace_id=root2.trace_id)[
            "prompt_id"] == "tw2"
        assert te.load_trace(d)["prompt_id"] == "tw2"  # newest record
        assert te.load_trace(d, prompt_id="nope") is None

    def test_unset_dir_writes_nothing(self, tmp_path):
        # export_off fixture guarantees the env is unset
        commit_trace("off1")
        assert te.current() is None
        assert te.stats() == {"enabled": False}
        assert not list((tmp_path).glob("capture-*"))

    def test_torn_and_foreign_lines_skipped(self, tmp_path, monkeypatch):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(C.TRACE_EXPORT_DIR_ENV, d)
        commit_trace("ok1")
        seg = te.segment_paths(d)[-1]
        with open(seg, "ab") as fh:
            fh.write(b'{"schema": 999, "prompt_id": "future"}\n')
            fh.write(b'not json at all\n')
            fh.write(b'{"schema": 1, "prompt_id": "torn"')  # no newline
        recs = list(te.iter_records(d))
        assert [r["prompt_id"] for r in recs] == ["ok1"]


class TestRotationRetention:
    def _rec(self, i, pad=80):
        return {"prompt_id": f"p{i:04d}", "trace_id": f"{i:032x}",
                "status": "ok", "root_span_id": None, "duration_s": 0.1,
                "finished_at": 1.0, "spans": [{"pad": "x" * pad}]}

    def test_rotation_respects_byte_budget(self, tmp_path):
        exp = te.TraceExporter(str(tmp_path), segment_bytes=400,
                               retain_bytes=100000)
        for i in range(20):
            assert exp.export(self._rec(i))
        exp.close()
        segs = te.segment_paths(str(tmp_path))
        assert len(segs) > 1 and exp.rotations >= len(segs) - 1
        for p in segs:
            assert os.path.getsize(p) <= 400, p
        assert len(list(te.iter_records(str(tmp_path)))) == 20

    def test_oversized_record_lands_alone(self, tmp_path):
        exp = te.TraceExporter(str(tmp_path), segment_bytes=200,
                               retain_bytes=100000)
        exp.export(self._rec(0, pad=16))
        exp.export(self._rec(1, pad=600))   # single record > budget
        exp.export(self._rec(2, pad=16))
        exp.close()
        assert exp.dropped == 0
        sizes = [os.path.getsize(p)
                 for p in te.segment_paths(str(tmp_path))]
        assert any(s > 200 for s in sizes)  # it landed...
        got = [r["prompt_id"] for r in te.iter_records(str(tmp_path))]
        assert got == ["p0000", "p0001", "p0002"]  # ...and nothing lost

    def test_retention_deletes_oldest_segments(self, tmp_path):
        exp = te.TraceExporter(str(tmp_path), segment_bytes=300,
                               retain_bytes=1200)
        for i in range(40):
            exp.export(self._rec(i))
        exp.close()
        segs = te.segment_paths(str(tmp_path))
        assert exp.retired_segments > 0
        total = sum(os.path.getsize(p) for p in segs)
        assert total <= 1200
        recs = [r["prompt_id"] for r in te.iter_records(str(tmp_path))]
        # survivors are a contiguous NEWEST suffix — retention only
        # ever eats from the oldest end
        assert recs and recs[-1] == "p0039"
        assert recs == [f"p{i:04d}"
                        for i in range(40 - len(recs), 40)]

    def test_capture_dir_under_budget_across_200_traces(self, tmp_path):
        exp = te.TraceExporter(str(tmp_path), segment_bytes=1000,
                               retain_bytes=5000)
        for i in range(200):
            exp.export(self._rec(i))
        exp.close()
        assert exp.exported == 200 and exp.dropped == 0
        total = sum(os.path.getsize(p)
                    for p in te.segment_paths(str(tmp_path)))
        assert total <= 5000

    def test_resume_numbering_after_restart(self, tmp_path):
        exp = te.TraceExporter(str(tmp_path), segment_bytes=60,
                               retain_bytes=100000)
        exp.export(self._rec(0, pad=16))
        exp.export(self._rec(1, pad=16))
        exp.close()
        before = te.segment_paths(str(tmp_path))
        exp2 = te.TraceExporter(str(tmp_path), segment_bytes=60,
                                retain_bytes=100000)
        exp2.export(self._rec(2, pad=16))
        exp2.close()
        after = te.segment_paths(str(tmp_path))
        assert before == after[:len(before)]  # nothing overwritten
        assert len(after) == len(before) + 1


class TestSloSpec:
    def test_parse_grammar(self):
        spec = slo_mod.parse_slo_spec(
            "paid:p95<2s,completion>0.999;free:p99<500ms")
        assert set(spec) == {"paid", "free"}
        lat, comp = spec["paid"]
        assert lat.kind == "latency" and lat.quantile == 0.95
        assert lat.threshold_s == 2.0
        assert abs(lat.budget_frac - 0.05) < 1e-9
        assert comp.kind == "completion" and comp.min_ratio == 0.999
        assert abs(comp.budget_frac - 0.001) < 1e-9
        assert spec["free"][0].threshold_s == 0.5

    def test_malformed_clauses_skipped_not_fatal(self):
        spec = slo_mod.parse_slo_spec(
            "paid:p95<2s;bogus;free:pXX<1s,completion>0.99;:p95<1s")
        assert set(spec) == {"paid", "free"}
        assert [o.raw for o in spec["free"]] == ["completion>0.99"]
        assert slo_mod.parse_slo_spec(None) == {}
        assert slo_mod.parse_slo_spec("") == {}

    def test_out_of_range_objectives_rejected(self):
        assert slo_mod.parse_slo_spec("a:p0<1s") == {}
        assert slo_mod.parse_slo_spec("a:completion>1.0") == {}
        assert slo_mod.parse_slo_spec("a:p95<0s") == {}


class TestSloEngine:
    def _engine(self, spec="paid:p95<1s,completion>0.99"):
        return slo_mod.SLOEngine(slo_mod.parse_slo_spec(spec),
                                 fast_s=10.0, slow_s=100.0)

    def test_burn_rate_math_latency(self):
        eng = self._engine()
        now = 1000.0
        for i in range(20):         # 2/20 slow = 10% bad vs 5% budget
            eng.record("paid", 2.0 if i < 2 else 0.1, True, now=now)
        assert abs(eng.burn_rate("paid", "fast", now=now) - 2.0) < 1e-9

    def test_burn_rate_math_completion(self):
        eng = self._engine("paid:completion>0.9")
        now = 1000.0
        for i in range(10):         # 2/10 failed = 20% bad vs 10% budget
            eng.record("paid", 0.1, i >= 2, now=now)
        assert abs(eng.burn_rate("paid", "fast", now=now) - 2.0) < 1e-9

    def test_window_pruning_decays_burn(self):
        eng = self._engine()
        now = 1000.0
        for _ in range(10):
            eng.record("paid", 5.0, True, now=now)   # all violate
        assert eng.burn_rate("paid", "fast", now=now) > 1.0
        # fast window (10s) ages out; slow window (100s) still burning
        later = now + 11.0
        assert eng.burn_rate("paid", "fast", now=later) == 0.0
        assert eng.burn_rate("paid", "slow", now=later) > 1.0

    def test_evaluate_shape_and_budget(self):
        eng = self._engine()
        now = 1000.0
        for _ in range(4):
            eng.record("paid", 5.0, True, now=now)
        snap = eng.evaluate(now=now)
        assert snap["enabled"] is True
        t = snap["tenants"]["paid"]
        assert [o["raw"] for o in t["objectives"]] \
            == ["p95<1s", "completion>0.99"]
        fast = t["windows"]["fast"]
        assert fast["count"] == 4 and fast["ok_ratio"] == 1.0
        assert fast["burn_rate"] == fast["burn_rates"]["p95<1s"]
        assert fast["burn_rate"] > 1.0
        assert t["budget_remaining"] == 0.0     # slow window burning too
        # unknown-tenant traffic still shows up (objective-less)
        eng.record("mystery", 0.1, True, now=now)
        snap = eng.evaluate(now=now)
        assert snap["tenants"]["mystery"]["objectives"] == []

    def test_latency_threshold_is_tightest(self):
        eng = self._engine("paid:p95<2s,p99<5s,completion>0.9")
        assert eng.latency_threshold("paid") == 2.0
        assert eng.latency_threshold("free") is None

    def test_disarmed_engine_is_noop(self):
        eng = slo_mod.SLOEngine({})
        assert not eng.enabled
        eng.record("paid", 9.0, False)
        assert eng.evaluate()["tenants"] == {}
        assert eng.burn_rate("paid") == 0.0
        assert eng.prom_families() == []

    def test_prom_families_and_reset(self):
        eng = self._engine()
        now = 1000.0
        eng.record("paid", 5.0, True, now=now)
        fams = eng.prom_families()
        names = [f[0] for f in fams]
        assert names == ["dtpu_slo_burn_rate",
                         "dtpu_slo_budget_remaining"]
        burn = fams[0][3]
        assert {tuple(sorted(lbl.items())) for lbl, _ in burn} \
            == {(("tenant", "paid"), ("window", "fast")),
                (("tenant", "paid"), ("window", "slow"))}
        eng.reset()
        snap = eng.evaluate(now=now)
        assert snap["tenants"]["paid"]["windows"]["fast"]["count"] == 0

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv(C.SLO_SPEC_ENV, "paid:p95<2s")
        monkeypatch.setenv(C.SLO_FAST_WINDOW_ENV, "7")
        monkeypatch.setenv(C.SLO_SLOW_WINDOW_ENV, "70")
        eng = slo_mod.SLOEngine.from_env()
        assert eng.enabled and eng.fast_s == 7.0 and eng.slow_s == 70.0

    def test_autoscale_arming(self, monkeypatch):
        monkeypatch.delenv(C.AUTOSCALE_SLO_ENV, raising=False)
        assert not slo_mod.autoscale_slo_armed()
        monkeypatch.setenv(C.AUTOSCALE_SLO_ENV, "1")
        assert slo_mod.autoscale_slo_armed()


class TestExemplars:
    def test_histogram_records_bucket_exemplar(self):
        h = tr.LatencyHistogram(bounds=(0.01, 0.1, 1.0))
        h.record(0.05, trace_id="aa" * 16)
        h.record(5.0, trace_id="bb" * 16)
        h.record(0.06)                      # no trace -> no overwrite
        ex = h.exemplars_snapshot()
        assert ex[1][0] == "aa" * 16 and ex[1][1] == 0.05
        assert ex[3][0] == "bb" * 16        # overflow bucket
        assert set(ex) == {1, 3}

    def test_prometheus_renders_exemplar_and_validator_accepts(self):
        tr.GLOBAL_STAGES.record("exem_stage", 0.015,
                                trace_id="cd" * 16)
        text = tr.prometheus_text()
        lines = [l for l in text.splitlines()
                 if l.startswith("dtpu_stage_seconds_bucket")
                 and 'stage="exem_stage"' in l and " # {" in l]
        assert len(lines) == 1
        assert f'# {{trace_id="{"cd" * 16}"}} 0.015' in lines[0]
        validate_prometheus(text)           # exemplar-aware grammar

    def test_reset_clears_exemplars(self):
        tr.GLOBAL_STAGES.record("exem_gone", 0.01, trace_id="ee" * 16)
        tr.reset_aggregate_metrics()
        assert "exem_gone" not in tr.prometheus_text()


class TestEvictionAccounting:
    def test_ring_eviction_counted(self):
        rec = tr.FlightRecorder(max_traces=2)
        before = tr.GLOBAL_COUNTERS.snapshot().get("trace_evictions", 0)
        for i in range(5):
            sp = tr.Span(f"j{i}")
            rec.add(sp.trace_id, sp.to_dict())
            rec.commit(f"ev{i}", sp.trace_id, status="ok")
        assert rec.eviction_count() == 3
        assert tr.GLOBAL_COUNTERS.snapshot()["trace_evictions"] \
            == before + 3
        rec.reset()
        assert rec.eviction_count() == 0

    def test_evictions_total_in_prom(self):
        text = tr.prometheus_text()
        assert "# TYPE dtpu_trace_evictions_total counter" in text


class TestFlightDeck:
    def _executor(self, monkeypatch, ring=4):
        monkeypatch.setenv(C.CB_DECK_RING_ENV, str(ring))
        from comfyui_distributed_tpu.workflow import batch_executor \
            as cb_mod
        return cb_mod.ContinuousBatchExecutor(SimpleNamespace())

    def test_deck_ring_rows_and_cap(self, monkeypatch):
        ex = self._executor(monkeypatch, ring=4)
        bkt = SimpleNamespace(sig="cafebabe1234", n_active=3, capacity=4)
        with ex._lock:
            ex._stats["admits"] = 5
        for i in range(6):
            ex._deck_record(bkt)
        snap = ex.snapshot()
        assert snap["deck_ring"] == 4 and len(snap["deck"]) == 4
        rows = snap["deck"]
        assert [r["seq"] for r in rows] == [2, 3, 4, 5]
        assert rows[-1]["bucket"] == "cafebabe"
        assert rows[-1]["busy"] == 3 and rows[-1]["free"] == 1
        # counter deltas: all 5 admits land on the FIRST boundary only
        assert rows[0]["admits"] == 0 if rows[0]["seq"] else 5
        assert sum(r["admits"] for r in rows) == 0  # later rows: no new

    def test_deck_counts_deltas_between_boundaries(self, monkeypatch):
        ex = self._executor(monkeypatch, ring=8)
        bkt = SimpleNamespace(sig="deadbeef0000", n_active=1, capacity=2)
        ex._deck_record(bkt)
        with ex._lock:
            ex._stats["admits"] += 2
            ex._stats["retires"] += 1
            ex._stats["preemptions"] += 1
        ex._deck_record(bkt)
        rows = ex.snapshot()["deck"]
        assert rows[-1]["admits"] == 2 and rows[-1]["retires"] == 1
        assert rows[-1]["preemptions"] == 1

    def test_admit_to_first_step_histogram_end_to_end(
            self, tmp_path, monkeypatch):
        """A real bucket stepped by the driver path records the
        admit-to-first-step wait exactly once per row."""
        from tests.test_batching import item, make_state
        from comfyui_distributed_tpu.workflow import batch_executor \
            as cb_mod
        monkeypatch.setenv(C.CB_SLOTS_ENV, "2")
        st = make_state(tmp_path, cb=False)
        ex = cb_mod.ContinuousBatchExecutor(st)
        ex._admit_cb([item(401, steps=2), item(402, steps=2)])
        bkt = next(iter(ex._buckets.values()))
        for _ in range(6):
            if not bkt.n_active:
                break
            ex._step_and_retire(bkt)
        snap = ex.snapshot()
        assert snap["admit_to_first_step"]["count"] == 2
        assert snap["deck"], "step boundaries recorded deck rows"
        assert snap["deck"][0]["bucket"] == bkt.sig[:8]
        stages = tr.GLOBAL_STAGES.snapshot()
        assert stages.get("cb_admit_to_first_step",
                          {}).get("count", 0) >= 2


class TestPerfetto:
    def test_conversion_lanes_and_events(self, tmp_path, monkeypatch):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(C.TRACE_EXPORT_DIR_ENV, d)
        commit_trace("pf1", worker="worker_a")
        rec = te.load_trace(d, prompt_id="pf1")
        doc = te.to_perfetto(rec)
        assert doc["displayTimeUnit"] == "ms"
        evs = doc["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        metas = [e for e in evs if e["ph"] == "M"]
        assert len(xs) == len(rec["spans"])
        lane_names = {m["args"]["name"] for m in metas
                      if m["name"] == "thread_name"}
        assert lane_names == {"master", "worker_a"}
        job = [e for e in xs if e["name"] == "job"][0]
        span = [s for s in rec["spans"] if s["name"] == "job"][0]
        assert job["ts"] == round(span["start_s"] * 1e6, 3)
        assert job["args"]["trace_id"] == rec["trace_id"]
        # events are start-ordered for the viewer
        assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)

    def test_cli_offline_listing_and_perfetto(self, tmp_path,
                                              monkeypatch, capsys):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(C.TRACE_EXPORT_DIR_ENV, d)
        commit_trace("cli1")
        from comfyui_distributed_tpu import cli
        assert cli.main(["trace", "--export-dir", d]) == 0
        assert "cli1" in capsys.readouterr().out
        assert cli.main(["trace", "cli1", "--export-dir", d]) == 0
        assert "job" in capsys.readouterr().out
        out = str(tmp_path / "pf.json")
        assert cli.main(["trace", "cli1", "--export-dir", d,
                         "--perfetto", "--out", out]) == 0
        doc = json.load(open(out))
        assert doc["traceEvents"]
        assert cli.main(["trace", "missing", "--export-dir", d]) == 1


class TestServerSurfaces:
    def test_slo_route_metrics_and_total_reset(self, tmp_path,
                                               monkeypatch):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(C.TRACE_EXPORT_DIR_ENV, d)
        monkeypatch.setenv(C.SLO_SPEC_ENV,
                           "paid:p95<0.001s,completion>0.999")

        async def body(client, state):
            r = await client.post("/prompt", json={
                "prompt": make_prompt(11), "client_id": "cp"})
            pid = (await r.json())["prompt_id"]
            await wait_remote_history(client, pid)

            # /distributed/slo: the tight objective is burning
            slo = await (await client.get("/distributed/slo")).json()
            assert slo["enabled"] is True
            paid = slo["tenants"]["paid"]
            assert paid["windows"]["fast"]["count"] >= 1
            assert paid["windows"]["fast"]["burn_rate"] > 1.0

            # breach event span landed in the committed trace
            rec = tr.GLOBAL_TRACES.get(pid)
            names = {s["name"] for s in rec["spans"]}
            assert "slo_breach" in names
            breach = [s for s in rec["spans"]
                      if s["name"] == "slo_breach"][0]
            assert breach["attrs"]["tenant"] == "paid"

            # JSON metrics: slo block + export stats + evictions
            m = await (await client.get("/distributed/metrics")).json()
            assert m["slo"]["enabled"] is True
            assert m["tracing"]["export"]["enabled"] is True
            assert m["tracing"]["export"]["exported"] >= 1
            assert "evictions" in m["tracing"]

            # prom text: new families + exemplar-aware grammar
            text = await (await client.get(
                "/distributed/metrics.prom")).text()
            types = validate_prometheus(text)
            assert types.get("dtpu_slo_burn_rate") == "gauge"
            assert types.get("dtpu_slo_budget_remaining") == "gauge"
            assert types.get("dtpu_trace_export_traces_total") \
                == "counter"
            assert types.get("dtpu_trace_evictions_total") == "counter"
            assert 'dtpu_slo_burn_rate{tenant="paid",window="fast"}' \
                in text
            # the e2e histogram carries the committed trace's exemplar
            ex_lines = [l for l in text.splitlines()
                        if l.startswith("dtpu_stage_seconds_bucket")
                        and 'stage="job_e2e"' in l and " # {" in l]
            assert ex_lines, "job_e2e bucket exemplar missing"
            assert rec["trace_id"] in ex_lines[0]

            # capture file round-trips the job
            disk = te.load_trace(d, prompt_id=pid)
            assert disk is not None and disk["status"] == "ok"

            # total reset: SLO windows + exporter counters clear too
            r = await client.post("/distributed/metrics/reset", json={})
            cleared = (await r.json())["cleared"]
            assert cleared["slo_windows"] and cleared["export_counters"]
            slo = await (await client.get("/distributed/slo")).json()
            assert slo["tenants"]["paid"]["windows"]["fast"][
                "count"] == 0
            m = await (await client.get("/distributed/metrics")).json()
            assert m["tracing"]["export"]["exported"] == 0

        run_with_client(body, tmp_path)

    def test_tracing_off_writes_no_capture_files(self, tmp_path,
                                                 monkeypatch):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(C.TRACE_EXPORT_DIR_ENV, d)
        tr.set_tracing(False)

        async def body(client, state):
            r = await client.post("/prompt", json={
                "prompt": make_prompt(12), "client_id": "cp"})
            pid = (await r.json())["prompt_id"]
            await wait_remote_history(client, pid)
            assert te.segment_paths(d) == []

        run_with_client(body, tmp_path)

    def test_autoscaler_reads_paid_fast_burn(self, monkeypatch):
        """The DTPU_AUTOSCALE_SLO hook: burn > 1 alone trips scale-up
        pressure with a dedicated reason."""
        from comfyui_distributed_tpu.runtime import autoscale as aus
        eng = slo_mod.SLOEngine(
            slo_mod.parse_slo_spec("paid:p95<0.001s"),
            fast_s=1e9, slow_s=1e9)
        for _ in range(10):
            eng.record("paid", 1.0, True)
        a = aus.FleetAutoscaler(
            registry=None,
            queue_depth_fn=lambda: 0,   # queue looks IDLE — burn alone
            spawner=lambda: "w_new",    # must trip the scale-up
            slo_burn_fn=lambda: eng.burn_rate("paid", "fast"),
            window=1, cooldown_s=0.0, min_workers=0, max_workers=3,
            up_queue=100.0, down_queue=-1.0)
        sig = a.fleet_signal()
        assert sig["slo_burn"] > 1.0
        a.sample_once(now=0.0)
        assert a.scale_ups == 1
        assert "SLO burn rate" in a.decisions[-1]["reason"]
