"""Packaging (VERDICT r3 missing-#2): the install story.

The reference installs by documented convention (drop the repo into
``custom_nodes/``, ``/root/reference/README.md:23-40``); the TPU-native
equivalent is a normal Python package — ``pip install`` + a ``dtpu``
console entry point usable from any cwd.  Proven here WITHOUT touching
the live environment: ``pip install --target`` into a tmp dir
(``--no-deps --no-build-isolation`` keeps it zero-egress — every
dependency is already in the image).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.integration
class TestInstall:
    @pytest.fixture(scope="class")
    def installed(self, tmp_path_factory):
        target = tmp_path_factory.mktemp("site")
        r = subprocess.run(
            [sys.executable, "-m", "pip", "install", "--no-deps",
             "--no-build-isolation", "--target", str(target), REPO, "-q"],
            capture_output=True, text=True, timeout=300)
        # the in-tree build leaves build/ + *.egg-info behind — a full
        # stale copy of the package that double-counts every LoC audit
        # of the checkout; the installed --target tree is all we need
        import glob
        import shutil
        shutil.rmtree(os.path.join(REPO, "build"), ignore_errors=True)
        for p in glob.glob(os.path.join(REPO, "*.egg-info")):
            shutil.rmtree(p, ignore_errors=True)
        assert r.returncode == 0, r.stderr[-2000:]
        return target

    def test_wheel_contains_package_and_script(self, installed):
        assert (installed / "comfyui_distributed_tpu" / "cli.py").exists()
        assert (installed / "bin" / "dtpu").exists()

    def test_console_script_runs_from_foreign_cwd(self, installed,
                                                  tmp_path):
        env = dict(os.environ, PYTHONPATH=str(installed),
                   JAX_PLATFORMS="cpu",
                   DISTRIBUTED_TPU_CONFIG=str(tmp_path / "c.json"))
        r = subprocess.run([str(installed / "bin" / "dtpu"), "devices"],
                           capture_output=True, text=True, timeout=120,
                           cwd=str(tmp_path), env=env)
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.loads(r.stdout)["platform"] == "cpu"

    def test_help_for_every_subcommand(self, installed, tmp_path):
        env = dict(os.environ, PYTHONPATH=str(installed),
                   JAX_PLATFORMS="cpu",
                   DISTRIBUTED_TPU_CONFIG=str(tmp_path / "c.json"))
        for sub in ("serve", "worker", "run", "status"):
            r = subprocess.run(
                [str(installed / "bin" / "dtpu"), sub, "--help"],
                capture_output=True, text=True, timeout=60,
                cwd=str(tmp_path), env=env)
            assert r.returncode == 0, (sub, r.stderr[-500:])
            assert sub in r.stdout or "usage" in r.stdout
