"""Model zoo: UNet/VAE/CLIP shapes, tokenizer weighting, pipeline bundle."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import registry, tokenizer as tok_mod
from comfyui_distributed_tpu.models.clip import TINY_CLIP_CONFIG, CLIPTextModel
from comfyui_distributed_tpu.models.unet import TINY_CONFIG, UNet
from comfyui_distributed_tpu.models.upscalers import TINY_RRDB_CONFIG, RRDBNet
from comfyui_distributed_tpu.models.vae import TINY_VAE_CONFIG, VAE


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


class TestUNet:
    def test_forward_shape_and_dtype(self):
        unet = UNet(TINY_CONFIG)
        x = jnp.zeros((2, 8, 8, 4))
        ts = jnp.zeros((2,))
        ctx = jnp.zeros((2, 77, 64))
        params = unet.init(jax.random.PRNGKey(0), x, ts, ctx)["params"]
        out = unet.apply({"params": params}, x, ts, ctx)
        assert out.shape == (2, 8, 8, 4)
        assert out.dtype == jnp.float32

    def test_odd_spatial_dims_multiple_of_downscale(self):
        unet = UNet(TINY_CONFIG)
        x = jnp.zeros((1, 16, 8, 4))
        params = unet.init(jax.random.PRNGKey(0), x, jnp.zeros((1,)),
                           jnp.zeros((1, 77, 64)))["params"]
        out = unet.apply({"params": params}, x, jnp.zeros((1,)),
                         jnp.zeros((1, 77, 64)))
        assert out.shape == x.shape


class TestVAE:
    def test_encode_decode_round_trip_shapes(self):
        vae = VAE(TINY_VAE_CONFIG)
        img = jnp.zeros((1, 16, 16, 3))
        params = vae.init(jax.random.PRNGKey(0), img)["params"]
        lat = vae.apply({"params": params}, img, method=vae.encode)
        assert lat.shape == (1, 8, 8, 4)  # downscale 2 for tiny config
        dec = vae.apply({"params": params}, lat, method=vae.decode)
        assert dec.shape == img.shape
        assert float(jnp.min(dec)) >= 0.0 and float(jnp.max(dec)) <= 1.0

    def test_encode_stochastic_with_key(self):
        vae = VAE(TINY_VAE_CONFIG)
        img = jnp.ones((1, 16, 16, 3)) * 0.5
        params = vae.init(jax.random.PRNGKey(0), img)["params"]
        a = vae.apply({"params": params}, img, jax.random.PRNGKey(1),
                      method=vae.encode)
        b = vae.apply({"params": params}, img, method=vae.encode)
        assert a.shape == b.shape


class TestCLIP:
    def test_hidden_and_pooled(self):
        m = CLIPTextModel(TINY_CLIP_CONFIG)
        toks = jnp.zeros((2, 77), jnp.int32).at[:, 0].set(10)
        params = m.init(jax.random.PRNGKey(0), toks)["params"]
        hidden, pooled = m.apply({"params": params}, toks)
        assert hidden.shape == (2, 77, 64)
        assert pooled.shape == (2, 64)


class TestTokenizer:
    def test_weight_parsing(self):
        p = tok_mod.parse_weighted_prompt
        assert p("plain text") == [("plain text", 1.0)]
        frags = p("a (cat) dog")
        assert ("cat", pytest.approx(1.1)) in [(t, w) for t, w in frags]
        frags = p("a ((cat))")
        assert any(abs(w - 1.21) < 1e-6 for _, w in frags)
        frags = p("[down] up")
        assert any(abs(w - 1 / 1.1) < 1e-6 for _, w in frags)
        frags = p("(exact:1.5)")
        assert frags == [("exact", 1.5)]

    def test_unbalanced_is_literal(self):
        frags = tok_mod.parse_weighted_prompt("smile :) and (open")
        joined = "".join(t for t, _ in frags)
        assert "smile :)" in joined and "open" in joined

    def test_hash_tokenizer_stable_and_padded(self):
        t = tok_mod.HashTokenizer(vocab_size=4096)
        ids1, w1 = t.encode("hello world")
        ids2, _ = t.encode("hello world")
        assert np.array_equal(ids1, ids2)
        assert ids1.shape == (77,)
        assert ids1[0] == t.start
        assert t.end in ids1
        assert w1.shape == (77,)

    def test_weights_reach_tokens(self):
        t = tok_mod.HashTokenizer(vocab_size=4096)
        _, w = t.encode("a (strong:2.0) word")
        assert 2.0 in w.tolist()


class TestPipeline:
    def test_virtual_pipeline_deterministic(self):
        registry.clear_pipeline_cache()
        p1 = registry.load_pipeline("anything.safetensors")
        leaf1 = jax.tree_util.tree_leaves(p1.unet_params)[0]
        registry.clear_pipeline_cache()
        p2 = registry.load_pipeline("anything.safetensors")
        leaf2 = jax.tree_util.tree_leaves(p2.unet_params)[0]
        assert np.array_equal(np.asarray(leaf1), np.asarray(leaf2))
        registry.clear_pipeline_cache()

    def test_pipeline_cached(self):
        a = registry.load_pipeline("x.safetensors")
        b = registry.load_pipeline("x.safetensors")
        assert a is b

    def test_jit_cache_lru_bounded(self, monkeypatch):
        """A resolution sweep must not leak one executable per shape
        (VERDICT r2 weak #8): the per-pipeline jit cache is LRU-capped."""
        monkeypatch.setenv("DTPU_JIT_CACHE_CAP", "4")
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("lru.safetensors")
        assert p._jit_cache_cap == 4
        made = []
        for size in (8, 16, 24, 32, 40, 48):  # the sample() static-key shape
            key = ("sample", "euler", "karras", 2, 7.5, 1.0, True, False,
                   (1, size, size, 4), (1, 77, 64))
            made.append(p._cache_get_or_make(key, object))
        assert len(p._jit_cache) <= 4
        # oldest entries evicted, newest retained; a hit refreshes recency
        assert p._cache_get_or_make(key, object) is made[-1]
        first_key = ("sample", "euler", "karras", 2, 7.5, 1.0, True, False,
                     (1, 8, 8, 4), (1, 77, 64))
        assert p._cache_get_or_make(first_key, object) is not made[0]
        registry.clear_pipeline_cache()

    def test_vae_decode_tiled(self):
        """Tiled decode covers the canvas seamlessly: exact passthrough when
        one tile suffices; close to the full decode elsewhere (per-tile
        GroupNorm stats differ slightly — the feather hides seams)."""
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("tiled.safetensors")
        ds = p.family.vae.downscale
        lat = jnp.asarray(np.random.default_rng(0).standard_normal(
            (1, 24, 24, 4)).astype(np.float32))
        full = np.asarray(p.vae_decode(lat))
        # tile >= image -> identical path
        same = np.asarray(p.vae_decode_tiled(lat, tile_size=24 * ds))
        np.testing.assert_allclose(same, full, atol=1e-6)
        tiled = np.asarray(p.vae_decode_tiled(lat, tile_size=16 * ds,
                                              overlap=4 * ds))
        assert tiled.shape == full.shape
        assert np.isfinite(tiled).all()
        # same decoder, overlapping tiles: strongly correlated with full
        cc = np.corrcoef(tiled.ravel(), full.ravel())[0, 1]
        assert cc > 0.98, cc
        registry.clear_pipeline_cache()

    def test_encode_prompt_shapes(self):
        p = registry.load_pipeline("x.safetensors")
        ctx, pooled = p.encode_prompt(["a cat", "a dog"])
        assert ctx.shape == (2, 77, 64)
        assert pooled.shape == (2, 64)

    def test_full_txt2img_sample(self):
        """End-to-end tiny pipeline: prompt -> latents -> sample -> decode."""
        p = registry.load_pipeline("x.safetensors")
        ctx, _ = p.encode_prompt(["a cat"])
        unc, _ = p.encode_prompt([""])
        lat = jnp.zeros((1, 8, 8, 4))
        seeds = jnp.asarray([42], jnp.uint32)
        out = p.sample(lat, ctx, unc, seeds, steps=3, cfg=3.0,
                       sampler_name="euler", scheduler="normal")
        assert out.shape == lat.shape
        assert np.all(np.isfinite(np.asarray(out)))
        img = p.vae_decode(out)
        assert img.shape == (1, 16, 16, 3)

    def test_seed_determinism_and_divergence(self):
        p = registry.load_pipeline("x.safetensors")
        ctx, _ = p.encode_prompt(["a cat"])
        unc, _ = p.encode_prompt([""])
        lat = jnp.zeros((2, 8, 8, 4))
        s_a = jnp.asarray([7, 8], jnp.uint32)
        a = p.sample(lat, ctx[:1].repeat(2, 0), unc[:1].repeat(2, 0), s_a,
                     steps=2, cfg=1.0, sampler_name="euler_ancestral",
                     scheduler="normal")
        b = p.sample(lat, ctx[:1].repeat(2, 0), unc[:1].repeat(2, 0), s_a,
                     steps=2, cfg=1.0, sampler_name="euler_ancestral",
                     scheduler="normal")
        assert np.array_equal(np.asarray(a), np.asarray(b))
        # the two samples inside the batch differ (different seeds)
        assert not np.allclose(np.asarray(a)[0], np.asarray(a)[1])


class TestUpscaler:
    def test_rrdb_scale(self):
        net = RRDBNet(TINY_RRDB_CONFIG)
        x = jnp.zeros((1, 8, 8, 3))
        params = net.init(jax.random.PRNGKey(0), x)["params"]
        out = net.apply({"params": params}, x)
        assert out.shape == (1, 16, 16, 3)

    def test_registry_upscaler_virtual(self):
        net, params, scale = registry.load_upscaler("tiny_2x.pth")
        assert scale == 2
        out = net.apply({"params": params}, jnp.zeros((1, 4, 4, 3)))
        assert out.shape == (1, 8, 8, 3)


class TestSD21Family:
    def test_detect_family_stability_names(self):
        cases = {
            "v2-1_768-ema-pruned.safetensors": "sd21",
            "v2-1_512-ema-pruned.ckpt": "sd21_base",
            "512-base-ema.ckpt": "sd21_base",  # official SD2.0-base name
            "sd2_vpred_custom.safetensors": "sd21",
            "v1-5-pruned-emaonly.safetensors": "sd15",
            "sd_xl_base_1.0.safetensors": "sdxl",
            # SD1.5-architecture community finetunes with v2 in the NAME
            # must not be misrouted to the sd21 converter
            "anything-v2.ckpt": "sd15",
            "counterfeit-v2.5.safetensors": "sd15",
        }
        env = os.environ.pop(registry.FAMILY_ENV, None)
        try:
            for name, fam in cases.items():
                assert registry.detect_family(name) == fam, name
        finally:
            if env is not None:
                os.environ[registry.FAMILY_ENV] = env

    def test_sd21_configs(self):
        fam = registry.FAMILIES["sd21"]
        assert fam.unet.prediction_type == "v"
        assert fam.unet.context_dim == 1024
        assert fam.unet.use_linear_in_transformer
        assert fam.clips[0].layout == "openclip"
        assert fam.clips[0].output_layer == -2
        assert registry.FAMILIES["sd21_base"].unet.prediction_type == "eps"

    def test_openclip_family_pads_with_zero(self):
        """SD2.x pad convention: OpenCLIP towers pad with 0 after EOT;
        CLIP towers (SD1.x/SDXL) pad with EOT — ComfyUI tokenizer parity."""
        import dataclasses as dc
        fam_oc = dc.replace(
            registry.FAMILIES["tiny"], name="tiny_oc",
            clips=(dc.replace(TINY_CLIP_CONFIG, layout="openclip"),))
        pipe_oc = registry.DiffusionPipeline("toc", fam_oc, {}, [{}], {})
        ids, _ = pipe_oc.tokenizer.encode("hello")
        assert ids[-1] == 0
        pipe_clip = registry.DiffusionPipeline(
            "tcl", registry.FAMILIES["tiny"], {}, [{}], {})
        ids2, _ = pipe_clip.tokenizer.encode("hello")
        assert ids2[-1] == pipe_clip.tokenizer.end

    def test_v_prediction_pipeline_samples(self):
        """End-to-end sample through a v-prediction pipeline at tiny scale:
        the family's prediction_type must reach the denoiser (finite,
        deterministic output differing from the eps pipeline's)."""
        import dataclasses as dc
        fam_v = dc.replace(
            registry.FAMILIES["tiny"], name="tiny_v",
            unet=dc.replace(TINY_CONFIG, prediction_type="v"))
        seed = 7
        rng = jax.random.PRNGKey(seed)
        x = jnp.zeros((1, 8, 8, 4))
        ts = jnp.zeros((1,))
        ctx = jnp.zeros((1, 77, TINY_CONFIG.context_dim))
        unet_p = jax.jit(UNet(fam_v.unet).init)(rng, x, ts, ctx)["params"]
        clip_p = CLIPTextModel(fam_v.clips[0]).init(
            rng, jnp.zeros((1, 77), jnp.int32))["params"]
        vae_p = VAE(fam_v.vae).init(rng, jnp.zeros((1, 16, 16, 3)))["params"]

        def build(fam):
            return registry.DiffusionPipeline(
                "vtest", fam, unet_p, [clip_p], vae_p,
                prediction_type=fam.unet.prediction_type)

        pipe_v = build(fam_v)
        ctx_b, _ = pipe_v.encode_prompt(["x"])
        seeds = np.asarray([3], np.uint64)
        out_v = pipe_v.sample(x, ctx_b, ctx_b, seeds, steps=2, cfg=1.0,
                              sampler_name="euler", scheduler="normal")
        assert np.isfinite(np.asarray(out_v)).all()

        pipe_e = build(registry.FAMILIES["tiny"])
        out_e = pipe_e.sample(x, ctx_b, ctx_b, seeds, steps=2, cfg=1.0,
                              sampler_name="euler", scheduler="normal")
        assert not np.allclose(np.asarray(out_v), np.asarray(out_e)), \
            "v-pred pipeline produced identical output to eps — the " \
            "prediction_type never reached the denoiser"


class TestAdvancedOps:
    """CLIPSetLastLayer / VAELoader / KSamplerAdvanced (ComfyUI schemas)."""

    def _pipe(self):
        return registry.load_pipeline("adv-ops.ckpt")

    def test_clip_set_last_layer(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        pipe = self._pipe()
        op = get_op("CLIPSetLastLayer")
        (skip2,) = op.execute(OpContext(), pipe, -2)
        assert skip2 is not pipe
        assert all(c.output_layer == -2 for c in skip2.family.clips)
        c0, _ = pipe.encode_prompt(["hello"])
        c2, _ = skip2.encode_prompt(["hello"])
        assert not np.allclose(np.asarray(c0), np.asarray(c2))
        # weights are shared, not copied
        assert skip2.clip_params is pipe.clip_params
        # -1 (the default) is the identity
        (same,) = op.execute(OpContext(), pipe, -1)
        assert same is pipe
        # derived pipelines are cached by (base, tag)
        (again,) = op.execute(OpContext(), pipe, -2)
        assert again is skip2

    def test_vae_loader_virtual_and_file_forms(self, tmp_path):
        from comfyui_distributed_tpu.models import checkpoints as ckpt
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        op = get_op("VAELoader")
        (v1,) = op.execute(OpContext(), "fancy-vae.safetensors")
        (v2,) = op.execute(OpContext(), "fancy-vae.safetensors")
        assert v1 is v2                       # cached
        lat = jnp.zeros((1, 4, 4, v1.family.latent_channels))
        img = v1.vae_decode(lat)
        ds = v1.family.vae.downscale
        assert img.shape == (1, 4 * ds, 4 * ds, 3)

        # file forms: bare VAE keys and full-checkpoint prefix both load
        pipe = self._pipe()
        sd_prefixed = {k: v for k, v in ckpt.export_state_dict(
            pipe.unet_params, pipe.clip_params, pipe.vae_params,
            pipe.family).items() if k.startswith("first_stage_model.")}
        sd_bare = {k[len("first_stage_model."):]: v
                   for k, v in sd_prefixed.items()}
        # save through the framework helper: raw safetensors save_file
        # silently serializes non-contiguous views (export transposes)
        # as their underlying buffer bytes — corrupt weights
        ckpt.save_state_dict(sd_prefixed,
                             str(tmp_path / "prefixed.safetensors"))
        ckpt.save_state_dict(sd_bare, str(tmp_path / "bare.safetensors"))
        ctx = OpContext(models_dir=str(tmp_path))
        (vp,) = op.execute(ctx, "prefixed.safetensors")
        (vb,) = op.execute(ctx, "bare.safetensors")
        z = jnp.asarray(np.random.default_rng(0).standard_normal(
            (1, 4, 4, pipe.family.latent_channels)), jnp.float32)
        np.testing.assert_allclose(np.asarray(vp.vae_decode(z)),
                                   np.asarray(vb.vae_decode(z)),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(vp.vae_decode(z)),
                                   np.asarray(pipe.vae_decode(z)),
                                   rtol=1e-5, atol=1e-6)

    def test_ksampler_advanced_window_composition(self):
        """Two chained windows (0..3 with leftover noise, 3..6 without
        added noise) must reproduce the single 6-step run — ComfyUI's
        staged-sampling contract for deterministic samplers."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        pipe = self._pipe()
        ctx_arr, _ = pipe.encode_prompt(["a fox"])
        neg_arr, _ = pipe.encode_prompt([""])
        from comfyui_distributed_tpu.ops.base import Conditioning
        pos = Conditioning(context=ctx_arr, pooled=None)
        neg = Conditioning(context=neg_arr, pooled=None)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        op = get_op("KSamplerAdvanced")
        octx = OpContext()

        (full,) = op.execute(octx, pipe, "enable", 55, 6, 1.5, "euler",
                             "normal", pos, neg, lat, 0, 10000, "disable")
        (s1,) = op.execute(octx, pipe, "enable", 55, 6, 1.5, "euler",
                           "normal", pos, neg, lat, 0, 3, "enable")
        (s2,) = op.execute(octx, pipe, "disable", 55, 6, 1.5, "euler",
                           "normal", pos, neg,
                           {"samples": np.asarray(s1["samples"])},
                           3, 10000, "disable")
        np.testing.assert_allclose(np.asarray(s2["samples"]),
                                   np.asarray(full["samples"]),
                                   rtol=1e-4, atol=1e-4)
        # the mid-point is a genuine intermediate, not the final result
        assert not np.allclose(np.asarray(s1["samples"]),
                               np.asarray(full["samples"]), atol=1e-3)


class TestUtilityOps:
    """Conditioning combinators, latent batch utilities, CheckpointSave."""

    def _pipe(self):
        return registry.load_pipeline("util-ops.ckpt")

    def test_conditioning_concat_average_combine(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        a = Conditioning(context=jnp.ones((1, 77, 64)),
                         pooled=jnp.ones((1, 64)))
        b = Conditioning(context=jnp.zeros((1, 77, 64)),
                         pooled=jnp.zeros((1, 64)))
        octx = OpContext()
        (cat,) = get_op("ConditioningConcat").execute(octx, a, b)
        assert cat.context.shape == (1, 154, 64)
        (avg,) = get_op("ConditioningAverage").execute(octx, a, b, 0.25)
        np.testing.assert_allclose(np.asarray(avg.context),
                                   np.full((1, 77, 64), 0.25), atol=1e-6)
        np.testing.assert_allclose(np.asarray(avg.pooled),
                                   np.full((1, 64), 0.25), atol=1e-6)
        # Combine bundles BOTH entries for a stacked sample-time eval
        # (true ComfyUI semantics — no longer the average approximation)
        (comb,) = get_op("ConditioningCombine").execute(octx, a, b)
        np.testing.assert_array_equal(np.asarray(comb.context),
                                      np.asarray(a.context))
        assert len(comb.siblings) == 1
        np.testing.assert_array_equal(np.asarray(comb.siblings[0].context),
                                      np.asarray(b.context))
        # combine of combines flattens
        (comb2,) = get_op("ConditioningCombine").execute(octx, comb, a)
        assert len(comb2.siblings) == 2

    def test_repeat_and_from_batch(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        octx = OpContext()
        lat = {"samples": np.arange(2 * 4 * 4 * 4, dtype=np.float32)
               .reshape(2, 4, 4, 4), "local_batch": 2, "fanout": 1}
        (rep,) = get_op("RepeatLatentBatch").execute(octx, lat, 3)
        assert rep["samples"].shape == (6, 4, 4, 4)
        assert rep["local_batch"] == 6
        np.testing.assert_array_equal(rep["samples"][2:4],
                                      lat["samples"])
        (sel,) = get_op("LatentFromBatch").execute(octx, lat, 1, 1)
        assert sel["samples"].shape == (1, 4, 4, 4)
        np.testing.assert_array_equal(sel["samples"][0], lat["samples"][1])
        # out-of-range clamps instead of crashing
        (sel2,) = get_op("LatentFromBatch").execute(octx, lat, 5, 9)
        assert sel2["samples"].shape == (1, 4, 4, 4)

    def test_repeat_latent_batch_keeps_replica_blocks(self):
        """A fanned batch is replica-major: repeating must stay WITHIN
        each replica's contiguous block, or downstream seed fold-ins and
        the collector's ordering attribute latents to the wrong replica."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        lat = np.stack([np.full((4, 4, 4), float(r)) for r in range(2)])
        d = {"samples": lat, "local_batch": 1, "fanout": 2}
        (rep,) = get_op("RepeatLatentBatch").execute(OpContext(), d, 2)
        assert rep["samples"].shape == (4, 4, 4, 4)
        assert rep["local_batch"] == 2 and rep["fanout"] == 2
        # block layout: [r0, r0, r1, r1] — NOT [r0, r1, r0, r1]
        got = rep["samples"][:, 0, 0, 0].tolist()
        assert got == [0.0, 0.0, 1.0, 1.0], got

    def test_conditioning_average_mismatched_lengths(self):
        """ComfyUI pads the shorter cond_from with zeros; pooled falls
        back to cond_from's when cond_to has none."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        a = Conditioning(context=jnp.ones((1, 154, 64)), pooled=None)
        b = Conditioning(context=jnp.ones((1, 77, 64)),
                         pooled=jnp.full((1, 64), 3.0))
        (avg,) = get_op("ConditioningAverage").execute(
            OpContext(), a, b, 0.5)
        assert avg.context.shape == (1, 154, 64)
        out = np.asarray(avg.context)
        np.testing.assert_allclose(out[:, :77], 1.0, atol=1e-6)
        np.testing.assert_allclose(out[:, 77:], 0.5, atol=1e-6)  # zero pad
        np.testing.assert_allclose(np.asarray(avg.pooled), 3.0, atol=1e-6)

    def test_latent_from_batch_slices_noise_mask(self):
        """ADVICE r3: the mask travels with its rows through a batch
        slice — dropping it would silently resample the whole image."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        lat = {"samples": np.zeros((4, 4, 4, 4), np.float32),
               "noise_mask": np.stack([np.full((8, 8), float(i))
                                       for i in range(4)])}
        (sel,) = get_op("LatentFromBatch").execute(OpContext(), lat, 2, 2)
        assert "noise_mask" in sel
        np.testing.assert_array_equal(
            np.asarray(sel["noise_mask"])[:, 0, 0], [2.0, 3.0])
        # a single mask broadcasts: forwarded untouched
        lat1 = {"samples": np.zeros((4, 4, 4, 4), np.float32),
                "noise_mask": np.ones((1, 8, 8), np.float32)}
        (sel1,) = get_op("LatentFromBatch").execute(OpContext(), lat1, 1, 2)
        assert np.asarray(sel1["noise_mask"]).shape[0] == 1
        # short (but >1) mask cycles the batch before slicing, ComfyUI-style
        lat2 = {"samples": np.zeros((4, 4, 4, 4), np.float32),
                "noise_mask": np.stack([np.full((8, 8), float(i))
                                        for i in range(2)])}
        (sel2,) = get_op("LatentFromBatch").execute(OpContext(), lat2, 2, 2)
        np.testing.assert_array_equal(
            np.asarray(sel2["noise_mask"])[:, 0, 0], [0.0, 1.0])

    def test_checkpoint_save_rejects_escaping_prefix(self, tmp_path):
        """ADVICE r3: a '../..'-style filename_prefix must not write
        outside the output root."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        pipe = self._pipe()
        out = tmp_path / "out"
        out.mkdir()
        octx = OpContext(output_dir=str(out))
        with pytest.raises(ValueError, match="escapes"):
            get_op("CheckpointSave").execute(octx, pipe, pipe, pipe,
                                             "../escaped/evil")
        assert not (tmp_path / "escaped").exists()
        # SaveImage shares the guard (same user-supplied prefix join)
        img = np.zeros((1, 8, 8, 3), np.float32)
        with pytest.raises(ValueError, match="escapes"):
            get_op("SaveImage").execute(octx, img, "../escaped/evil")
        assert not (tmp_path / "escaped").exists()
        # a legitimate subdirectory prefix still works
        get_op("SaveImage").execute(octx, img, "subdir/ok")
        assert (out / "subdir" / "ok_00000.png").exists()

    def test_checkpoint_save_round_trips(self, tmp_path):
        from comfyui_distributed_tpu.models import checkpoints as ckpt
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        pipe = self._pipe()
        octx = OpContext(output_dir=str(tmp_path))
        get_op("CheckpointSave").execute(octx, pipe, pipe, pipe,
                                         "checkpoints/exported")
        path = tmp_path / "checkpoints" / "exported.safetensors"
        assert path.exists()
        sd = ckpt.load_state_dict(str(path))
        ref = ckpt.export_state_dict(pipe.unet_params, pipe.clip_params,
                                     pipe.vae_params, pipe.family)
        for k, v in ref.items():
            np.testing.assert_array_equal(sd[k], np.asarray(v), err_msg=k)
        # and the file round-trips back into IDENTICAL param trees
        u2, c2, v2 = ckpt.convert_state_dict(sd, pipe.family)

        def trees_equal(a, b):
            fa = jax.tree_util.tree_leaves_with_path(a)
            fb = dict(jax.tree_util.tree_leaves_with_path(b))
            assert len(fa) == len(fb)
            for path_k, leaf in fa:
                np.testing.assert_array_equal(
                    np.asarray(leaf), np.asarray(fb[path_k]),
                    err_msg=str(path_k))

        trees_equal(u2, pipe.unet_params)
        trees_equal(c2[0], pipe.clip_params[0])
        trees_equal(v2, pipe.vae_params)


class TestInpainting:
    """noise_mask sampling (KSamplerX0Inpaint semantics), mask ops."""

    def _pipe(self):
        return registry.load_pipeline("inpaint.ckpt")

    def test_unmasked_region_anchored_to_source(self):
        """mask=1 resamples; mask=0 returns the source latent EXACTLY
        (the final output is re-anchored to the clean source there)."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        pipe = self._pipe()
        rng = np.random.default_rng(5)
        src = rng.standard_normal((1, 8, 8, 4)).astype(np.float32)
        mask = np.zeros((1, 16, 16), np.float32)   # image res (downscale 2)
        mask[:, :, 8:] = 1.0                       # right half inpainted
        lat = {"samples": src, "noise_mask": mask}
        ctx_arr, _ = pipe.encode_prompt(["replace"])
        from comfyui_distributed_tpu.ops.base import Conditioning
        pos = Conditioning(context=ctx_arr, pooled=None)
        (out,) = get_op("KSampler").execute(
            OpContext(), pipe, 11, 4, 1.5, "euler", "normal", pos, pos,
            lat, 1.0)
        o = np.asarray(out["samples"])
        np.testing.assert_array_equal(o[:, :, :4], src[:, :, :4])  # kept
        assert not np.allclose(o[:, :, 4:], src[:, :, 4:])         # redone
        assert out["noise_mask"] is mask  # mask stays on the latent

    def test_mask_wrapper_propagates_cfg_pp_side_channel(self, monkeypatch):
        """ADVICE r4 (medium): the inpaint mask wrapper must re-expose
        the CFG denoiser's ``last_uncond`` side-channel — otherwise CFG++
        samplers under a noise_mask fall back to the CFG result and
        silently degrade to plain-euler semantics.  A probe sampler
        reads the side-channel exactly like the CFG++ samplers do
        (getattr off the callable it was handed) and returns
        ``last_uncond - denoised``: zero everywhere pre-fix (fallback),
        nonzero INSIDE the mask post-fix (cfg!=1, cond!=uncond), and
        source-anchored outside either way."""
        from comfyui_distributed_tpu.models import samplers as smp_mod
        pipe = self._pipe()

        def probe_sampler(model, x, sigmas, extra_args=None, keys=None):
            den = model(x, sigmas[0], **(extra_args or {}))
            lu = getattr(model, "last_uncond", den)
            return lu - den

        monkeypatch.setitem(smp_mod.SAMPLERS, "_lu_probe", probe_sampler)
        rng = np.random.default_rng(7)
        src = rng.standard_normal((1, 8, 8, 4)).astype(np.float32)
        mask = np.zeros((1, 8, 8, 1), np.float32)
        mask[:, :, 4:] = 1.0                  # latent-res mask
        ctx_c, _ = pipe.encode_prompt(["a cat"])
        ctx_u, _ = pipe.encode_prompt([""])
        out = np.asarray(pipe.sample(
            jnp.asarray(src), ctx_c, ctx_u,
            np.asarray([11], np.uint64), steps=3, cfg=7.5,
            sampler_name="_lu_probe", scheduler="normal",
            noise_mask=jnp.asarray(mask)))
        # outside the mask the final re-anchor returns the source
        np.testing.assert_array_equal(out[:, :, :4], src[:, :, :4])
        # inside: uncond != cfg result -> the probe saw a REAL uncond
        assert np.abs(out[:, :, 4:]).max() > 1e-4, \
            "last_uncond side-channel lost by the mask wrapper"

    def test_no_mask_output_differs_everywhere(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        pipe = self._pipe()
        src = np.random.default_rng(6).standard_normal(
            (1, 8, 8, 4)).astype(np.float32)
        ctx_arr, _ = pipe.encode_prompt(["x"])
        pos = Conditioning(context=ctx_arr, pooled=None)
        (out,) = get_op("KSampler").execute(
            OpContext(), pipe, 11, 2, 1.5, "euler", "normal", pos, pos,
            {"samples": src}, 1.0)
        assert not np.allclose(np.asarray(out["samples"]), src)

    def test_set_latent_noise_mask_op(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32),
               "local_batch": 1, "fanout": 1}
        m = np.ones((16, 16), np.float32)
        (out,) = get_op("SetLatentNoiseMask").execute(OpContext(), lat, m)
        assert out["noise_mask"].shape == (1, 16, 16)
        assert out["local_batch"] == 1

    def test_set_mask_replaces_existing_mask(self):
        """A new mask must WIN over one already on the latent (forwarded
        by sampler outputs) — spread-order regression."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        old = np.zeros((1, 16, 16), np.float32)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32),
               "noise_mask": old}
        new = np.ones((16, 16), np.float32)
        (out,) = get_op("SetLatentNoiseMask").execute(OpContext(), lat, new)
        assert out["noise_mask"].sum() == 16 * 16, "old mask survived"

    def test_masked_add_noise_disable_keeps_source_unnoised(self):
        """Stage-2 inpaint (add_noise=disable): the protected region's
        blend must use ZERO noise — the input latent already is the noised
        state (ComfyUI disable_noise semantics)."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        pipe = self._pipe()
        rng = np.random.default_rng(8)
        src = rng.standard_normal((1, 8, 8, 4)).astype(np.float32)
        mask = np.zeros((1, 16, 16), np.float32)
        mask[:, :, 8:] = 1.0
        ctx_arr, _ = pipe.encode_prompt(["x"])
        pos = Conditioning(context=ctx_arr, pooled=None)
        lat = {"samples": src, "noise_mask": mask}
        (out,) = get_op("KSamplerAdvanced").execute(
            OpContext(), pipe, "disable", 11, 4, 1.5, "euler", "normal",
            pos, pos, lat, 2, 10000, "disable")
        o = np.asarray(out["samples"])
        np.testing.assert_array_equal(o[:, :, :4], src[:, :, :4])
        assert not np.allclose(o[:, :, 4:], src[:, :, 4:])

    def test_vae_encode_for_inpaint(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        pipe = self._pipe()
        img = np.full((1, 16, 16, 3), 0.9, np.float32)
        mask = np.zeros((1, 16, 16), np.float32)
        mask[:, 6:10, 6:10] = 1.0
        (out,) = get_op("VAEEncodeForInpaint").execute(
            OpContext(), img, pipe, mask, 2)
        assert "noise_mask" in out
        # grown mask covers MORE area than the input mask
        assert out["noise_mask"].sum() > mask.sum()
        ds = pipe.family.vae.downscale
        assert out["samples"].shape == (1, 16 // ds, 16 // ds, 4)

    def test_mask_survives_latent_ops(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32),
               "noise_mask": np.ones((1, 16, 16), np.float32)}
        (up,) = get_op("LatentUpscaleBy").execute(OpContext(), lat,
                                                  "bilinear", 2.0)
        assert "noise_mask" in up


class TestTiledSR:
    def test_tiled_sr_matches_whole_image(self, monkeypatch):
        """Above the pixel threshold the SR net runs in overlapping
        feathered tiles; result must closely match the whole-image pass
        (identical away from seams — RRDB convs are local, unlike the
        VAE's global attention)."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        from comfyui_distributed_tpu.ops.basic import ImageUpscaleWithModel
        ul = get_op("UpscaleModelLoader").execute(
            OpContext(), "2x_tiny_sr.pth")[0]
        img = np.random.default_rng(4).uniform(
            0, 1, (1, 48, 64, 3)).astype(np.float32)
        op = get_op("ImageUpscaleWithModel")
        (whole,) = op.execute(OpContext(), ul, img)
        monkeypatch.setattr(ImageUpscaleWithModel, "TILE_THRESHOLD", 512)
        monkeypatch.setattr(ImageUpscaleWithModel, "TILE", 32)
        monkeypatch.setattr(ImageUpscaleWithModel, "OVERLAP", 8)
        (tiled,) = op.execute(OpContext(), ul, img)
        assert tiled.shape == whole.shape
        # interior agreement: small RRDB receptive-field halo at seams
        diff = np.abs(np.asarray(tiled) - np.asarray(whole))
        assert np.median(diff) < 1e-4, float(np.median(diff))
        assert np.mean(diff) < 0.02, float(np.mean(diff))


class TestBf16WeightStorage:
    def test_flag_casts_unet_clip_not_vae(self, monkeypatch):
        """DTPU_BF16_WEIGHTS: UNet/CLIP weight storage drops to bf16 (on
        TPU, fp32 storage doubles HBM weight traffic per step and SDXL
        fp32 wouldn't fit a 16 GB v5e); the VAE stays fp32.  Sampling
        still produces finite output with bf16-stored params."""
        import jax.numpy as jnp
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        monkeypatch.setenv("DTPU_BF16_WEIGHTS", "1")
        registry.clear_pipeline_cache()
        try:
            pipe = registry.load_pipeline("bf16-flag.ckpt",
                                          family_name="tiny")
            u = jax.tree_util.tree_leaves(pipe.unet_params)
            assert all(x.dtype == jnp.bfloat16 for x in u
                       if x.dtype in (jnp.float32, jnp.bfloat16))
            v = jax.tree_util.tree_leaves(pipe.vae_params)
            assert any(x.dtype == jnp.float32 for x in v)
            ctx_arr, _ = pipe.encode_prompt(["x"])
            pos = Conditioning(context=ctx_arr, pooled=None)
            lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
            (out,) = get_op("KSampler").execute(
                OpContext(), pipe, 3, 2, 1.5, "euler", "normal",
                pos, pos, lat, 1.0)
            assert np.isfinite(np.asarray(out["samples"])).all()
        finally:
            registry.clear_pipeline_cache()

    def test_default_off_for_tiny(self, monkeypatch):
        """tiny (fp32 module, deterministic CPU tests) keeps fp32 storage
        by default — only the real bf16-compute families opt in."""
        monkeypatch.delenv("DTPU_BF16_WEIGHTS", raising=False)
        registry.clear_pipeline_cache()
        pipe = registry.load_pipeline("fp32-default.ckpt",
                                      family_name="tiny")
        import jax.numpy as jnp
        u = jax.tree_util.tree_leaves(pipe.unet_params)
        assert all(x.dtype == jnp.float32 for x in u)
        registry.clear_pipeline_cache()


class TestSaveImageCounters:
    def test_second_run_does_not_overwrite(self, tmp_path):
        """ComfyUI save semantics: counters continue across runs — a
        re-queued workflow appends new files instead of clobbering."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        img = np.zeros((2, 8, 8, 3), np.float32)
        octx = OpContext(output_dir=str(tmp_path))
        get_op("SaveImage").execute(octx, img, "run")
        get_op("SaveImage").execute(octx, img + 0.5, "run")
        names = sorted(p.name for p in tmp_path.glob("run_*.png"))
        assert names == ["run_00000.png", "run_00001.png",
                         "run_00002.png", "run_00003.png"]


class TestVAEEncodeTiled:
    def test_tiled_encode_close_to_full(self):
        """Latent-space feathered blend of pixel tiles tracks the
        one-shot encode (per-tile GroupNorm stats differ slightly, like
        the tiled decode); one-tile inputs take the exact path."""
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("enc-tiled.ckpt")
        ds = p.family.vae.downscale
        img = jnp.asarray(np.random.default_rng(7).uniform(
            0, 1, (1, 48, 48, 3)).astype(np.float32))
        full = np.asarray(p.vae_encode(img))
        same = np.asarray(p.vae_encode_tiled(img, tile_size=48,
                                             overlap=8))
        np.testing.assert_allclose(same, full, atol=1e-6)
        tiled = np.asarray(p.vae_encode_tiled(img, tile_size=16 * ds,
                                              overlap=4 * ds))
        assert tiled.shape == full.shape
        assert np.isfinite(tiled).all()
        cc = np.corrcoef(tiled.ravel(), full.ravel())[0, 1]
        assert cc > 0.98, cc
        registry.clear_pipeline_cache()

    def test_op_fans_out_like_vaeencode(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        p = registry.load_pipeline("enc-tiled-op.ckpt")
        img = np.random.default_rng(8).uniform(
            0, 1, (1, 32, 32, 3)).astype(np.float32)
        octx = OpContext()
        octx.fanout = 4
        (lat,) = get_op("VAEEncodeTiled").execute(octx, img, p,
                                                  tile_size=16, overlap=4)
        assert lat["samples"].shape[0] == 4    # batch * fanout
        assert lat["fanout"] == 4 and lat["local_batch"] == 1
        # all replicas hold the SAME source latent (img2img sweep)
        s = np.asarray(lat["samples"])
        np.testing.assert_array_equal(s[0], s[3])


class TestImagePadForOutpaint:
    def test_pad_mask_and_feather(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        img = np.ones((1, 32, 32, 3), np.float32) * 0.25
        (out, mask) = get_op("ImagePadForOutpaint").execute(
            OpContext(), img, left=0, top=0, right=16, bottom=0,
            feathering=8)
        assert out.shape == (1, 32, 48, 3)
        assert mask.shape == (32, 48)
        # original content preserved; new area mid-gray
        np.testing.assert_array_equal(out[:, :, :32], img)
        np.testing.assert_allclose(out[:, :, 32:], 0.5)
        # mask: 1 over the new area, quadratic feather into the original
        np.testing.assert_allclose(mask[:, 32:], 1.0)
        assert mask[16, 31] == pytest.approx((7 / 8) ** 2)  # d=1 to edge
        assert mask[16, 25] == pytest.approx((1 / 8) ** 2)  # d=7, band rim
        assert mask[16, 23] == 0.0     # d=9 >= feathering: outside band
        assert mask[16, 0] == 0.0      # far side untouched (not extended)
        assert mask[0, 0] == 0.0       # unextended top edge: no feather

    def test_feeds_inpaint_encode(self):
        """Outpaint chain: pad -> VAEEncodeForInpaint consumes the pair
        (the mask rides along as noise_mask)."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("outpaint.ckpt")
        img = np.ones((1, 32, 32, 3), np.float32) * 0.25
        (out, mask) = get_op("ImagePadForOutpaint").execute(
            OpContext(), img, right=16, feathering=4)
        (lat,) = get_op("VAEEncodeForInpaint").execute(
            OpContext(), out, p, mask, grow_mask_by=0)
        assert "noise_mask" in lat
        ds = p.family.vae.downscale
        assert lat["samples"].shape[1:3] == (32 // ds, 48 // ds)
        registry.clear_pipeline_cache()


class TestInpaintEncodeFanout:
    def test_fanned_pixels_pass_through(self):
        """ADVICE-style regression: already-fanned pixels into
        VAEEncodeForInpaint must pass through, not re-tile (the
        fan-out-squaring bug the shared helper fixed for VAEEncode)."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        from comfyui_distributed_tpu.ops.basic import ImageBatch
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("inp-fan.ckpt")
        img = ImageBatch(np.full((4, 16, 16, 3), 0.5, np.float32),
                         local_batch=1, fanout=4)
        octx = OpContext()
        octx.fanout = 4
        (lat,) = get_op("VAEEncodeForInpaint").execute(
            octx, img, p, np.ones((16, 16), np.float32), 0)
        assert lat["samples"].shape[0] == 4          # NOT 16
        assert lat["fanout"] == 4 and lat["local_batch"] == 1
        assert "noise_mask" in lat
        registry.clear_pipeline_cache()


class TestRegionalPrompting:
    """ConditioningSetArea/SetMask + Combine -> stacked multi-cond eval.

    One-step oracle: with a single denoise step, the blended output's
    left half must match the left half of a run conditioned only on
    prompt A (same seed, same noise, same uncond — the blend is
    per-pixel linear in the per-entry denoised predictions; tolerance
    covers batch-size-dependent XLA reduction order)."""

    def _run(self, p, pos, neg, seed=11):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        lat = {"samples": np.zeros((1, 16, 16, 4), np.float32)}
        (out,) = get_op("KSampler").execute(
            OpContext(), p, seed, 1, 4.0, "euler", "normal", pos, neg,
            lat, 1.0)
        return np.asarray(out["samples"])

    def test_one_step_halves_match_single_cond_runs(self):
        from comfyui_distributed_tpu.ops.base import Conditioning, get_op
        from comfyui_distributed_tpu.ops.base import OpContext
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("region.ckpt")
        ca, _ = p.encode_prompt(["a red square"])
        cb, _ = p.encode_prompt(["a blue circle"])
        cn, _ = p.encode_prompt([""])
        A = Conditioning(context=ca, pooled=None)
        B = Conditioning(context=cb, pooled=None)
        N = Conditioning(context=cn, pooled=None)
        octx = OpContext()
        (setA,) = get_op("ConditioningSetAreaPercentage").execute(
            octx, A, width=0.5, height=1.0, x=0.0, y=0.0)
        (setB,) = get_op("ConditioningSetAreaPercentage").execute(
            octx, B, width=0.5, height=1.0, x=0.5, y=0.0)
        (comb,) = get_op("ConditioningCombine").execute(octx, setA, setB)

        blended = self._run(p, comb, N)
        only_a = self._run(p, A, N)
        only_b = self._run(p, B, N)
        assert not np.allclose(only_a, only_b)   # prompts actually differ
        # tolerance: the blended run's stacked batch (3 rows) and the
        # single runs (2 rows) take different XLA fusion paths — ULP-level
        # reduction-order noise, far below the prompt-difference signal
        np.testing.assert_allclose(blended[:, :, :8], only_a[:, :, :8],
                                   rtol=5e-4, atol=5e-4)
        np.testing.assert_allclose(blended[:, :, 8:], only_b[:, :, 8:],
                                   rtol=5e-4, atol=5e-4)
        registry.clear_pipeline_cache()

    def test_mask_node_and_multistep_finite(self):
        """SetMask with an image-res array mask through a multi-step
        sample: finite, differs from the single-cond run, and a
        full-coverage single mask equals the plain path exactly."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("region2.ckpt")
        ca, _ = p.encode_prompt(["meadow"])
        cb, _ = p.encode_prompt(["sky"])
        cn, _ = p.encode_prompt([""])
        A = Conditioning(context=ca, pooled=None)
        B = Conditioning(context=cb, pooled=None)
        N = Conditioning(context=cn, pooled=None)
        octx = OpContext()
        m = np.zeros((32, 32), np.float32)
        m[:16] = 1.0                                   # top half
        (setB,) = get_op("ConditioningSetMask").execute(octx, B, m, 0.8)
        (comb,) = get_op("ConditioningCombine").execute(octx, A, setB)
        out = self._run(p, comb, N, seed=3)
        assert np.isfinite(out).all()
        assert not np.allclose(out, self._run(p, A, N, seed=3))
        # full-coverage unit mask on a single entry == plain path
        ones = np.ones((32, 32), np.float32)
        (setA1,) = get_op("ConditioningSetMask").execute(octx, A, ones,
                                                         1.0)
        np.testing.assert_allclose(self._run(p, setA1, N, seed=3),
                                   self._run(p, A, N, seed=3),
                                   rtol=1e-6, atol=1e-6)
        registry.clear_pipeline_cache()


class TestRegionalPromptingFixups:
    """Review fixups: combined negatives, sibling controls, and
    Set-after-Combine must all reach sampling."""

    def _run(self, p, pos, neg, seed=21, steps=2):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (out,) = get_op("KSampler").execute(
            OpContext(), p, seed, steps, 4.0, "euler", "normal", pos,
            neg, lat, 1.0)
        return np.asarray(out["samples"])

    def test_combined_negative_reaches_sampling(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("multineg.ckpt")
        pos = Conditioning(context=p.encode_prompt(["castle"])[0])
        na = Conditioning(context=p.encode_prompt(["blurry"])[0])
        nb = Conditioning(context=p.encode_prompt(["cropped"])[0])
        (comb_n,) = get_op("ConditioningCombine").execute(OpContext(),
                                                          na, nb)
        combined = self._run(p, pos, comb_n)
        only_na = self._run(p, pos, na)
        assert np.isfinite(combined).all()
        # the second negative influences the output (pre-fix it was
        # silently dropped and combined == only_na)
        assert not np.allclose(combined, only_na)
        registry.clear_pipeline_cache()

    def test_sibling_control_reaches_sampling(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("sibctrl.ckpt")
        module, params = registry.load_controlnet("sib_cn.safetensors")
        params = jax.tree_util.tree_map(lambda a: a + 0.05, params)
        A = Conditioning(context=p.encode_prompt(["tree"])[0])
        B = Conditioning(context=p.encode_prompt(["river"])[0])
        N = Conditioning(context=p.encode_prompt([""])[0])
        octx = OpContext()
        hint = np.random.default_rng(2).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        (b_ctrl,) = get_op("ControlNetApply").execute(
            octx, B, (module, params), hint, 1.0)
        (comb,) = get_op("ConditioningCombine").execute(octx, A, b_ctrl)
        with_ctrl = self._run(p, comb, N)
        (comb_plain,) = get_op("ConditioningCombine").execute(octx, A, B)
        without = self._run(p, comb_plain, N)
        # the control on the SECOND combine input steers the sample
        # (pre-fix it was silently dropped and the runs were identical)
        assert not np.allclose(with_ctrl, without)
        registry.clear_pipeline_cache()

    def test_set_after_combine_masks_every_entry(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        octx = OpContext()
        A = Conditioning(context=jnp.ones((1, 7, 8)))
        B = Conditioning(context=jnp.zeros((1, 7, 8)))
        (comb,) = get_op("ConditioningCombine").execute(octx, A, B)
        m = np.ones((8, 8), np.float32)
        (masked,) = get_op("ConditioningSetMask").execute(octx, comb, m,
                                                          0.7)
        assert masked.area_mask is not None
        assert masked.area_strength == pytest.approx(0.7)
        assert all(s.area_mask is not None
                   and s.area_strength == pytest.approx(0.7)
                   for s in masked.siblings)

    def test_sibling_control_scoped_to_its_region(self):
        """A control on the right-region sibling must NOT steer the left
        region: per-entry strength blocks (one step; the left half of
        the blended output matches the control-free run)."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("scopectrl.ckpt")
        module, params = registry.load_controlnet("scope_cn.safetensors")
        params = jax.tree_util.tree_map(lambda a: a + 0.05, params)
        A = Conditioning(context=p.encode_prompt(["tree"])[0])
        B = Conditioning(context=p.encode_prompt(["river"])[0])
        N = Conditioning(context=p.encode_prompt([""])[0])
        octx = OpContext()
        hint = np.random.default_rng(4).uniform(
            0, 1, (1, 16, 16, 3)).astype(np.float32)
        (setA,) = get_op("ConditioningSetAreaPercentage").execute(
            octx, A, width=0.5, height=1.0, x=0.0, y=0.0)
        (b_ctrl,) = get_op("ControlNetApply").execute(
            octx, B, (module, params), hint, 1.0)
        (setB,) = get_op("ConditioningSetAreaPercentage").execute(
            octx, b_ctrl, width=0.5, height=1.0, x=0.5, y=0.0)
        (setB_plain,) = get_op("ConditioningSetAreaPercentage").execute(
            octx, B, width=0.5, height=1.0, x=0.5, y=0.0)
        (comb,) = get_op("ConditioningCombine").execute(octx, setA, setB)
        (comb0,) = get_op("ConditioningCombine").execute(octx, setA,
                                                         setB_plain)
        with_c = self._run(p, comb, N, steps=1)
        without = self._run(p, comb0, N, steps=1)
        # right region steered by the control...
        assert not np.allclose(with_c[:, :, 4:], without[:, :, 4:])
        # ...left region untouched (per-entry scale; ULP-level tolerance
        # for the batched-eval fusion differences)
        np.testing.assert_allclose(with_c[:, :, :4], without[:, :, :4],
                                   rtol=5e-4, atol=5e-4)
        registry.clear_pipeline_cache()

    def test_concat_and_average_apply_to_all_entries(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        octx = OpContext()
        A = Conditioning(context=jnp.ones((1, 7, 8)))
        B = Conditioning(context=jnp.zeros((1, 7, 8)))
        C = Conditioning(context=jnp.full((1, 5, 8), 2.0))
        (comb,) = get_op("ConditioningCombine").execute(octx, A, B)
        (cat,) = get_op("ConditioningConcat").execute(octx, comb, C)
        assert cat.context.shape == (1, 12, 8)
        assert len(cat.siblings) == 1
        assert cat.siblings[0].context.shape == (1, 12, 8)  # B + C too
        (avg,) = get_op("ConditioningAverage").execute(
            octx, comb, Conditioning(context=jnp.full((1, 7, 8), 4.0)),
            0.5)
        np.testing.assert_allclose(np.asarray(avg.context), 2.5)  # (1+4)/2
        np.testing.assert_allclose(np.asarray(avg.siblings[0].context),
                                   2.0)                           # (0+4)/2

    def test_controlnet_after_combine_steers_all_entries(self):
        """ControlNetApply downstream of Combine attaches to every entry
        (ComfyUI loops the cond list) — both regions steered."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        octx = OpContext()
        A = Conditioning(context=jnp.ones((1, 7, 8)))
        B = Conditioning(context=jnp.zeros((1, 7, 8)))
        (comb,) = get_op("ConditioningCombine").execute(octx, A, B)
        registry.clear_pipeline_cache()
        module, params = registry.load_controlnet("comb_cn.safetensors")
        hint = np.zeros((1, 16, 16, 3), np.float32)
        (ctl,) = get_op("ControlNetApply").execute(
            octx, comb, (module, params), hint, 0.9)
        assert ctl.control is not None
        assert all(s.control is not None and s.control[0][3] == 0.9
                   for s in ctl.siblings)      # 1-chain spec per entry
        registry.clear_pipeline_cache()


class TestTimestepRange:
    def test_schedule_percent_to_sigma(self):
        from comfyui_distributed_tpu.models import schedules as sch
        ds = sch.make_discrete_schedule()
        assert ds.percent_to_sigma(1.0) == 0.0
        assert ds.percent_to_sigma(0.0) > ds.sigmas[-1]    # ~inf
        mid = ds.percent_to_sigma(0.5)
        assert ds.sigmas[0] < mid < ds.sigmas[-1]

    def test_scheduled_prompts_change_sampling(self):
        """Two prompts scheduled over halves of the run produce a result
        different from either prompt alone; a [0,1] full-range schedule
        on a single prompt equals the plain path."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("sched.ckpt")
        A = Conditioning(context=p.encode_prompt(["oak tree"])[0])
        B = Conditioning(context=p.encode_prompt(["pine tree"])[0])
        N = Conditioning(context=p.encode_prompt([""])[0])
        octx = OpContext()

        def run(pos, seed=17, steps=4):
            lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
            (out,) = get_op("KSampler").execute(
                octx, p, seed, steps, 4.0, "euler", "normal", pos, N,
                lat, 1.0)
            return np.asarray(out["samples"])

        (a_early,) = get_op("ConditioningSetTimestepRange").execute(
            octx, A, 0.0, 0.5)
        (b_late,) = get_op("ConditioningSetTimestepRange").execute(
            octx, B, 0.5, 1.0)
        (sched,) = get_op("ConditioningCombine").execute(octx, a_early,
                                                         b_late)
        out = run(sched)
        assert np.isfinite(out).all()
        assert not np.allclose(out, run(A))
        assert not np.allclose(out, run(B))
        # full-range schedule == plain (always-active gate is exact)
        (a_full,) = get_op("ConditioningSetTimestepRange").execute(
            octx, A, 0.0, 1.0)
        np.testing.assert_allclose(run(a_full), run(A), rtol=1e-6,
                                   atol=1e-6)
        registry.clear_pipeline_cache()


class TestFreeU:
    def test_fourier_filter_lowpass(self):
        from comfyui_distributed_tpu.models.unet import _fourier_filter
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((1, 8, 8, 4)), jnp.float32)
        # scale=1: identity (within fft round-trip noise)
        same = _fourier_filter(x, 1, 1.0)
        np.testing.assert_allclose(np.asarray(same), np.asarray(x),
                                   atol=1e-5)
        # scale=0: the DC/low box is removed -> per-channel mean ~0
        killed = np.asarray(_fourier_filter(x, 1, 0.0))
        assert abs(killed.mean()) < 1e-5
        assert not np.allclose(killed, np.asarray(x))

    def test_freeu_changes_output_and_params_shared(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("freeu.ckpt")
        octx = OpContext()
        (p1,) = get_op("FreeU").execute(octx, p, 1.5, 1.6, 0.5, 0.5)
        (p2,) = get_op("FreeU_V2").execute(octx, p, 1.5, 1.6, 0.5, 0.5)
        assert p1.unet_params is p.unet_params        # params shared
        assert p1 is not p and p2 is not p1
        # same settings -> cached derived pipeline
        (p1b,) = get_op("FreeU").execute(octx, p, 1.5, 1.6, 0.5, 0.5)
        assert p1b is p1
        x = jnp.asarray(np.random.default_rng(1).standard_normal(
            (1, 8, 8, 4)), jnp.float32)
        ts = jnp.zeros((1,))
        ctx_a = jnp.asarray(np.random.default_rng(2).standard_normal(
            (1, 16, 64)), jnp.float32)
        base = np.asarray(p.unet.apply({"params": p.unet_params}, x, ts,
                                       ctx_a))
        v1 = np.asarray(p1.unet.apply({"params": p1.unet_params}, x, ts,
                                      ctx_a))
        v2 = np.asarray(p2.unet.apply({"params": p2.unet_params}, x, ts,
                                      ctx_a))
        # tiny's max width is model_channels*2 -> the b2/s2 pair engages
        assert not np.allclose(base, v1)
        assert not np.allclose(v1, v2)     # v2's mean-scaled boost differs
        assert np.isfinite(v1).all() and np.isfinite(v2).all()

    def test_freeu_sampling_e2e(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("freeu-e2e.ckpt")
        octx = OpContext()
        (pf,) = get_op("FreeU").execute(octx, p, 1.4, 1.6, 0.8, 0.4)
        pos = Conditioning(context=p.encode_prompt(["hills"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (out,) = get_op("KSampler").execute(
            octx, pf, 5, 2, 4.0, "euler", "normal", pos, neg, lat, 1.0)
        s = np.asarray(out["samples"])
        assert np.isfinite(s).all()
        (plain,) = get_op("KSampler").execute(
            octx, p, 5, 2, 4.0, "euler", "normal", pos, neg, lat, 1.0)
        assert not np.allclose(s, np.asarray(plain["samples"]))
        registry.clear_pipeline_cache()


class TestRescaleCFG:
    def test_node_patches_and_rides_derivations(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("rescale.ckpt")
        octx = OpContext()
        (pr,) = get_op("RescaleCFG").execute(octx, p, 0.7)
        assert pr is not p and pr.cfg_rescale == 0.7
        assert pr.unet_params is p.unet_params
        # rides further derivations (clip-skip AND LoRA chains)
        (pc,) = get_op("CLIPSetLastLayer").execute(octx, pr, -2)
        assert getattr(pc, "cfg_rescale", 0.0) == 0.7
        (pl, _) = get_op("LoraLoader").execute(octx, pr, pr,
                                               "style.safetensors", 0.5,
                                               0.5)
        assert getattr(pl, "cfg_rescale", 0.0) == 0.7
        # multiplier 0 is a no-op passthrough
        (p0,) = get_op("RescaleCFG").execute(octx, p, 0.0)
        assert p0 is p
        # sampling: finite and different from the unpatched run
        pos = Conditioning(context=p.encode_prompt(["dunes"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (a,) = get_op("KSampler").execute(octx, pr, 9, 2, 7.0, "euler",
                                          "normal", pos, neg, lat, 1.0)
        (b,) = get_op("KSampler").execute(octx, p, 9, 2, 7.0, "euler",
                                          "normal", pos, neg, lat, 1.0)
        assert np.isfinite(np.asarray(a["samples"])).all()
        assert not np.allclose(np.asarray(a["samples"]),
                               np.asarray(b["samples"]))
        registry.clear_pipeline_cache()


class TestCustomSampling:
    """SamplerCustom chain: KSamplerSelect + scheduler/sigma nodes."""

    def test_sigma_nodes(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("custom-sig.ckpt")
        octx = OpContext()
        (sig,) = get_op("BasicScheduler").execute(octx, p, "karras", 8,
                                                  1.0)
        assert sig.shape == (9,) and sig[-1] == 0.0
        assert np.all(np.diff(sig) < 1e-7)
        (ksig,) = get_op("KarrasScheduler").execute(octx, 6, 10.0, 0.1,
                                                    7.0)
        assert ksig.shape == (7,)
        assert ksig[0] == pytest.approx(10.0) and ksig[-1] == 0.0
        hi, lo = get_op("SplitSigmas").execute(octx, sig, 3)
        assert hi.shape == (4,) and lo.shape == (6,)
        assert hi[-1] == lo[0]
        (flipped,) = get_op("FlipSigmas").execute(octx, sig)
        assert flipped[0] == pytest.approx(1e-4)     # leading 0 -> eps
        assert flipped[-1] == sig[0]
        # denoise<=0: 1-entry sigmas -> SamplerCustom is a no-op
        # (ComfyUI passes the latent through unchanged)
        (sig0,) = get_op("BasicScheduler").execute(octx, p, "karras", 8,
                                                   0.0)
        assert sig0.shape[0] < 2
        from comfyui_distributed_tpu.ops.base import Conditioning
        c = Conditioning(context=p.encode_prompt(["x"])[0])
        lat0 = {"samples": np.full((1, 8, 8, 4), 0.25, np.float32)}
        (sampler0,) = get_op("KSamplerSelect").execute(octx, "euler")
        noop, _ = get_op("SamplerCustom").execute(
            octx, p, True, 1, 4.0, c, c, lat0, sampler0, sig0)
        np.testing.assert_array_equal(np.asarray(noop["samples"]),
                                      lat0["samples"])
        with pytest.raises(ValueError):
            get_op("KSamplerSelect").execute(octx, "not_a_sampler")

    def test_sampler_custom_matches_ksampler(self):
        """SamplerCustom with BasicScheduler sigmas must reproduce the
        KSampler result for the same (sampler, scheduler, steps, seed) —
        the custom chain is the exploded form of the same computation."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("custom-eq.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (ks_out,) = get_op("KSampler").execute(
            octx, p, 31, 4, 5.0, "dpmpp_2m", "karras", pos, neg, lat, 1.0)
        (sampler,) = get_op("KSamplerSelect").execute(octx, "dpmpp_2m")
        (sig,) = get_op("BasicScheduler").execute(octx, p, "karras", 4,
                                                  1.0)
        out, out2 = get_op("SamplerCustom").execute(
            octx, p, True, 31, 5.0, pos, neg, lat, sampler, sig)
        np.testing.assert_allclose(np.asarray(out["samples"]),
                                   np.asarray(ks_out["samples"]),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(out["samples"]),
                                      np.asarray(out2["samples"]))
        registry.clear_pipeline_cache()

    def test_split_sigmas_two_stage_roundtrip(self):
        """hi/lo split driven through two SamplerCustom stages equals the
        single full run (euler: the deterministic two-window identity)."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("custom-2stage.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a bay"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (sampler,) = get_op("KSamplerSelect").execute(octx, "euler")
        (sig,) = get_op("BasicScheduler").execute(octx, p, "normal", 6,
                                                  1.0)
        full, _ = get_op("SamplerCustom").execute(
            octx, p, True, 5, 4.0, pos, neg, lat, sampler, sig)
        hi, lo = get_op("SplitSigmas").execute(octx, sig, 3)
        stage1, _ = get_op("SamplerCustom").execute(
            octx, p, True, 5, 4.0, pos, neg, lat, sampler, hi)
        stage2, _ = get_op("SamplerCustom").execute(
            octx, p, False, 5, 4.0, pos, neg, stage1, sampler, lo)
        np.testing.assert_allclose(np.asarray(stage2["samples"]),
                                   np.asarray(full["samples"]),
                                   rtol=1e-4, atol=1e-4)
        registry.clear_pipeline_cache()


class TestCustomSamplingAdvanced:
    """NOISE/GUIDER suite: RandomNoise, DisableNoise, BasicGuider,
    CFGGuider, DualCFGGuider -> SamplerCustomAdvanced."""

    def _setup(self, name):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline(name)
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (sampler,) = get_op("KSamplerSelect").execute(octx, "euler")
        (sig,) = get_op("BasicScheduler").execute(octx, p, "normal", 4,
                                                  1.0)
        return octx, get_op, p, pos, neg, lat, sampler, sig

    def test_cfg_guider_matches_sampler_custom(self):
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-cfg.ckpt")
        (noise,) = get_op("RandomNoise").execute(octx, 7)
        (guider,) = get_op("CFGGuider").execute(octx, p, pos, neg, 5.0)
        adv, adv2 = get_op("SamplerCustomAdvanced").execute(
            octx, noise, guider, sampler, sig, lat)
        ref, _ = get_op("SamplerCustom").execute(
            octx, p, True, 7, 5.0, pos, neg, lat, sampler, sig)
        np.testing.assert_allclose(np.asarray(adv["samples"]),
                                   np.asarray(ref["samples"]),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(adv["samples"]),
                                      np.asarray(adv2["samples"]))
        registry.clear_pipeline_cache()

    def test_basic_guider_is_cfg_one(self):
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-basic.ckpt")
        (noise,) = get_op("RandomNoise").execute(octx, 3)
        (guider,) = get_op("BasicGuider").execute(octx, p, pos)
        adv, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, guider, sampler, sig, lat)
        ref, _ = get_op("SamplerCustom").execute(
            octx, p, True, 3, 1.0, pos, neg, lat, sampler, sig)
        np.testing.assert_allclose(np.asarray(adv["samples"]),
                                   np.asarray(ref["samples"]),
                                   rtol=1e-5, atol=1e-5)
        registry.clear_pipeline_cache()

    def test_disable_noise(self):
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-nonoise.ckpt")
        lat = {"samples": np.full((1, 8, 8, 4), 0.4, np.float32)}
        (noise,) = get_op("DisableNoise").execute(octx)
        (guider,) = get_op("CFGGuider").execute(octx, p, pos, neg, 4.0)
        adv, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, guider, sampler, sig, lat)
        ref, _ = get_op("SamplerCustom").execute(
            octx, p, False, 0, 4.0, pos, neg, lat, sampler, sig)
        np.testing.assert_allclose(np.asarray(adv["samples"]),
                                   np.asarray(ref["samples"]),
                                   rtol=1e-5, atol=1e-5)
        registry.clear_pipeline_cache()

    def test_dual_cfg_collapses_to_cfg_when_cond2_is_negative(self):
        """(neg + cfg2*(neg-neg)) + cfg1*(pos-neg) == plain CFG at cfg1 —
        the dual combine's exact algebraic reduction, any cfg2."""
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-dual-eq.ckpt")
        (noise,) = get_op("RandomNoise").execute(octx, 11)
        (dual,) = get_op("DualCFGGuider").execute(octx, p, pos, neg, neg,
                                                  6.0, 3.3)
        adv, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dual, sampler, sig, lat)
        (cfgg,) = get_op("CFGGuider").execute(octx, p, pos, neg, 6.0)
        ref, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, cfgg, sampler, sig, lat)
        np.testing.assert_allclose(np.asarray(adv["samples"]),
                                   np.asarray(ref["samples"]),
                                   rtol=1e-4, atol=1e-4)
        registry.clear_pipeline_cache()

    def test_dual_cfg_distinct_middle_finite_and_differs(self):
        from comfyui_distributed_tpu.ops.base import Conditioning
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-dual.ckpt")
        mid = Conditioning(context=p.encode_prompt(["oil painting"])[0])
        (noise,) = get_op("RandomNoise").execute(octx, 5)
        (dual,) = get_op("DualCFGGuider").execute(octx, p, pos, mid, neg,
                                                  7.0, 1.5)
        adv, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dual, sampler, sig, lat)
        s = np.asarray(adv["samples"])
        assert np.isfinite(s).all()
        (cfgg,) = get_op("CFGGuider").execute(octx, p, pos, neg, 7.0)
        ref, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, cfgg, sampler, sig, lat)
        assert not np.allclose(s, np.asarray(ref["samples"]))
        registry.clear_pipeline_cache()

    def test_dual_cfg_mixed_token_lengths(self):
        """cond1 chained to 154 tokens via ConditioningConcat while
        middle/negative stay 77: the tripled-batch concat must align all
        three to one length (lcm-repeat), not crash at trace time."""
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-dual-tok.ckpt")
        from comfyui_distributed_tpu.ops.base import Conditioning
        mid = Conditioning(context=p.encode_prompt(["sketch"])[0])
        (long_pos,) = get_op("ConditioningConcat").execute(octx, pos, pos)
        assert long_pos.context.shape[1] == 2 * pos.context.shape[1]
        (noise,) = get_op("RandomNoise").execute(octx, 13)
        (dual,) = get_op("DualCFGGuider").execute(
            octx, p, long_pos, mid, neg, 6.0, 2.0)
        adv, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dual, sampler, sig, lat)
        assert np.isfinite(np.asarray(adv["samples"])).all()
        registry.clear_pipeline_cache()

    def test_dual_cfg_with_controlnet(self):
        """Control on the positive rides the dual path with a per-block
        [cond, middle, uncond] strength tuple; a fresh virtual net
        (zero-convs) is bit-identical to no control."""
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-dual-cn.ckpt")
        from comfyui_distributed_tpu.ops.base import Conditioning
        mid = Conditioning(context=p.encode_prompt(["photo"])[0])
        module, params = registry.load_controlnet("dual_cn.safetensors")
        hint = np.random.default_rng(5).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        (noise,) = get_op("RandomNoise").execute(octx, 21)
        (dual,) = get_op("DualCFGGuider").execute(octx, p, pos, mid, neg,
                                                  5.0, 1.5)
        plain, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dual, sampler, sig, lat)
        (posc,) = get_op("ControlNetApply").execute(
            octx, pos, (module, params), hint, 1.0)
        (dualc,) = get_op("DualCFGGuider").execute(octx, p, posc, mid,
                                                   neg, 5.0, 1.5)
        zeroed, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dualc, sampler, sig, lat)
        np.testing.assert_array_equal(np.asarray(plain["samples"]),
                                      np.asarray(zeroed["samples"]))
        import jax as _jax
        params2 = _jax.tree_util.tree_map(lambda a: a + 0.05, params)
        (posc2,) = get_op("ControlNetApply").execute(
            octx, pos, (module, params2), hint, 1.0)
        (dualc2,) = get_op("DualCFGGuider").execute(octx, p, posc2, mid,
                                                    neg, 5.0, 1.5)
        steered, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dualc2, sampler, sig, lat)
        assert not np.allclose(np.asarray(plain["samples"]),
                               np.asarray(steered["samples"]))
        registry.clear_pipeline_cache()

    def test_dual_cfg_rejects_regional_conds(self):
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-dual-rej.ckpt")
        from comfyui_distributed_tpu.ops.base import Conditioning
        mid = Conditioning(context=p.encode_prompt(["left half"])[0])
        mask = np.ones((64, 64), np.float32)
        (masked_mid,) = get_op("ConditioningSetMask").execute(
            octx, mid, mask, 0.8, "default")
        (noise,) = get_op("RandomNoise").execute(octx, 2)
        (dual,) = get_op("DualCFGGuider").execute(
            octx, p, pos, masked_mid, neg, 5.0, 1.5)
        with pytest.raises(ValueError, match="multi-entry"):
            get_op("SamplerCustomAdvanced").execute(
                octx, noise, dual, sampler, sig, lat)
        registry.clear_pipeline_cache()

    def test_dual_prep_middle_own_pooled_and_control(self):
        """The middle entry carries its OWN pooled ADM vector (y list is
        [cond, middle, uncond-rides-positive]) and a control attached to
        the middle alone becomes a flat per-block strength tuple."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext)
        from comfyui_distributed_tpu.ops.basic import \
            _prepare_sample_inputs

        class _U:
            adm_in_channels = 2816

        class _F:
            unet = _U()

        class _P:
            family = _F()

        pos = Conditioning(context=np.zeros((1, 77, 32), np.float32),
                           pooled=np.full((1, 1280), 0.1, np.float32))
        mid = Conditioning(context=np.zeros((1, 77, 32), np.float32),
                           pooled=np.full((1, 1280), 0.9, np.float32),
                           control=(object(), {"w": 1},
                                    np.zeros((1, 64, 64, 3), np.float32),
                                    0.7))
        neg = Conditioning(context=np.zeros((1, 77, 32), np.float32))
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        prep = _prepare_sample_inputs(OpContext(), _P(), 0, lat, pos,
                                      neg, middle=mid)
        assert isinstance(prep.y, list) and len(prep.y) == 3
        assert not np.allclose(np.asarray(prep.y[1]),
                               np.asarray(prep.y[0]))
        np.testing.assert_array_equal(np.asarray(prep.y[2]),
                                      np.asarray(prep.y[0]))
        assert prep.mid_context.shape == prep.context.shape
        assert prep.control is not None
        assert prep.control[0][3] == (0.0, 0.7, 0.0)  # 1-chain wire

    def test_dual_cfg_honors_rescale_patch(self):
        octx, get_op, p, pos, neg, lat, sampler, sig = \
            self._setup("adv-dual-rs.ckpt")
        from comfyui_distributed_tpu.ops.base import Conditioning
        mid = Conditioning(context=p.encode_prompt(["ink wash"])[0])
        (noise,) = get_op("RandomNoise").execute(octx, 8)
        (dual,) = get_op("DualCFGGuider").execute(octx, p, pos, mid, neg,
                                                  7.0, 3.0)
        base, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dual, sampler, sig, lat)
        (pr,) = get_op("RescaleCFG").execute(octx, p, 0.7)
        (dual_r,) = get_op("DualCFGGuider").execute(octx, pr, pos, mid,
                                                    neg, 7.0, 3.0)
        rs, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, dual_r, sampler, sig, lat)
        r = np.asarray(rs["samples"])
        assert np.isfinite(r).all()
        assert not np.allclose(r, np.asarray(base["samples"]))
        registry.clear_pipeline_cache()


class TestSDXLTextEncodeNodes:
    """CLIPTextEncodeSDXL / CLIPTextEncodeSDXLRefiner: per-tower prompts
    + explicit ADM size scalars."""

    def test_texts_alt_feeds_later_towers_only(self):
        """Duplicate the tiny family's single tower into a 2-tower
        pipeline: text_l drives the first half of the context, text_g
        the second."""
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("sdxl-enc.ckpt")
        p.clip_models = [p.clip_models[0], p.clip_models[0]]
        p.clip_params = [p.clip_params[0], p.clip_params[0]]
        same, _ = p.encode_prompt(["a fox"], texts_alt=["a fox"])
        split, _ = p.encode_prompt(["a fox"], texts_alt=["a crow"])
        base, _ = p.encode_prompt(["a fox"])
        np.testing.assert_array_equal(np.asarray(same), np.asarray(base))
        half = same.shape[-1] // 2
        np.testing.assert_array_equal(np.asarray(split[..., :half]),
                                      np.asarray(base[..., :half]))
        assert not np.allclose(np.asarray(split[..., half:]),
                               np.asarray(base[..., half:]))
        registry.clear_pipeline_cache()

    def test_size_cond_rides_adm_vector(self):
        from comfyui_distributed_tpu.ops.base import Conditioning
        from comfyui_distributed_tpu.ops.basic import _sdxl_vector_cond

        class _U:
            adm_in_channels = 2816

        class _F:
            unet = _U()

        class _P:
            family = _F()

        pooled = np.full((1, 1280), 0.2, np.float32)
        derived = _sdxl_vector_cond(
            _P(), Conditioning(context=None, pooled=pooled), 2, 512, 512)
        explicit = _sdxl_vector_cond(
            _P(), Conditioning(context=None, pooled=pooled,
                               size_cond=(512, 512, 0, 0, 512, 512)),
            2, 512, 512)
        np.testing.assert_array_equal(np.asarray(derived),
                                      np.asarray(explicit))
        shifted = _sdxl_vector_cond(
            _P(), Conditioning(context=None, pooled=pooled,
                               size_cond=(1024, 1024, 0, 0, 512, 512)),
            2, 512, 512)
        assert shifted.shape == (2, 2816)
        assert not np.allclose(np.asarray(shifted), np.asarray(derived))
        # refiner 5-scalar layout: pooled 1280 + 5*256 = 2560, padded to
        # the family's adm width
        ref = _sdxl_vector_cond(
            _P(), Conditioning(context=None, pooled=pooled,
                               size_cond=(512, 512, 0, 0, 6.0)),
            1, 512, 512)
        assert ref.shape == (1, 2816)
        assert not np.allclose(np.asarray(ref)[:, :2560],
                               np.asarray(derived)[:1, :2560])

    def test_nodes_build_size_cond(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("sdxl-enc2.ckpt")
        octx = OpContext()
        (c,) = get_op("CLIPTextEncodeSDXL").execute(
            octx, p, 1024, 1024, 0, 0, 1024, 1024, "a fox", "a fox")
        assert c.size_cond == (1024, 1024, 0, 0, 1024, 1024)
        assert c.context.shape[0] == 1
        (r,) = get_op("CLIPTextEncodeSDXLRefiner").execute(
            octx, p, 6.0, 1024, 1024, "a fox")
        assert r.size_cond == (1024, 1024, 0, 0, 6.0)
        registry.clear_pipeline_cache()


class TestTextualInversion:
    """embedding:name prompt refs splice learned vectors into the token
    stream (ComfyUI textual-inversion syntax)."""

    def _write_embedding(self, models_dir, name, arr, key="emb_params"):
        import os

        from safetensors.numpy import save_file
        os.makedirs(os.path.join(models_dir, "embeddings"), exist_ok=True)
        save_file({key: arr}, os.path.join(models_dir, "embeddings",
                                           name + ".safetensors"))

    def test_embedding_changes_encoding(self, tmp_path):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("ti-base.ckpt",
                                   models_dir=str(tmp_path))
        width = int(p.clip_models[0].cfg.width)
        rng = np.random.default_rng(5)
        self._write_embedding(str(tmp_path), "mystyle",
                              rng.standard_normal((2, width))
                              .astype(np.float32))
        octx = OpContext()
        (plain,) = get_op("CLIPTextEncode").execute(octx, p, "a fox")
        (with_emb,) = get_op("CLIPTextEncode").execute(
            octx, p, "a fox embedding:mystyle")
        assert with_emb.context.shape == plain.context.shape
        assert not np.allclose(np.asarray(with_emb.context),
                               np.asarray(plain.context))
        # unknown name: dropped -> identical to the plain prompt
        (dropped,) = get_op("CLIPTextEncode").execute(
            octx, p, "a fox embedding:doesnotexist")
        np.testing.assert_array_equal(np.asarray(dropped.context),
                                      np.asarray(plain.context))
        registry.clear_pipeline_cache()

    def test_spliced_positions_and_weights(self, tmp_path):
        from comfyui_distributed_tpu.models.registry import \
            load_textual_embedding
        from comfyui_distributed_tpu.models.tokenizer import (
            encode_with_embeddings, make_tokenizer)
        width = 16
        rng = np.random.default_rng(3)
        vecs = rng.standard_normal((3, width)).astype(np.float32)
        self._write_embedding(str(tmp_path), "tivec", vecs)
        tok = make_tokenizer()

        def look(nm):
            return load_textual_embedding(nm, str(tmp_path), width)

        ids, w, ov, mask = encode_with_embeddings(
            tok, "a (embedding:tivec:1.5) fox", look, width)
        assert ids.shape == (tok.max_length,)
        assert mask.sum() == 3.0
        pos = np.nonzero(mask)[0]
        np.testing.assert_array_equal(ov[pos], vecs)
        np.testing.assert_array_equal(ids[pos], np.zeros(3, np.int32))
        np.testing.assert_allclose(w[pos], 1.5)
        # width mismatch -> None -> dropped
        assert load_textual_embedding("tivec", str(tmp_path), 32) is None

    def test_per_tower_keys(self, tmp_path):
        import os

        from safetensors.numpy import save_file
        from comfyui_distributed_tpu.models.registry import \
            load_textual_embedding
        os.makedirs(os.path.join(str(tmp_path), "embeddings"),
                    exist_ok=True)
        l = np.ones((1, 8), np.float32)
        g = np.full((1, 12), 2.0, np.float32)
        save_file({"clip_l": l, "clip_g": g},
                  os.path.join(str(tmp_path), "embeddings",
                               "xl.safetensors"))
        np.testing.assert_array_equal(
            load_textual_embedding("xl", str(tmp_path), 8, tower_idx=0), l)
        np.testing.assert_array_equal(
            load_textual_embedding("xl", str(tmp_path), 12, tower_idx=1),
            g)
        # tower 0 must not fall back to the g-tensor
        assert load_textual_embedding("xl", str(tmp_path), 12,
                                      tower_idx=0) is None


class TestModelPatchesRound4:
    """ModelSamplingDiscrete / PerpNeg / HyperTile."""

    def test_model_sampling_discrete(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("msd.ckpt")
        octx = OpContext()
        (pv,) = get_op("ModelSamplingDiscrete").execute(octx, p,
                                                        "v_prediction",
                                                        False)
        assert pv.prediction_type == "v" and pv.unet_params is p.unet_params
        (pz,) = get_op("ModelSamplingDiscrete").execute(octx, p, "eps",
                                                        True)
        assert pz.schedule.sigma_max > p.schedule.sigma_max * 10
        # the reference ecosystem's pinned terminal abar (ADVICE r4):
        # sigma_max = sqrt((1-abar)/abar) at abar=4.8973451890853435e-08
        ref_abar = 4.8973451890853435e-08
        np.testing.assert_allclose(
            float(pz.schedule.sigma_max),
            float(np.sqrt((1.0 - ref_abar) / ref_abar)), rtol=1e-4)
        assert np.isclose(pz.schedule.sigmas[0], p.schedule.sigmas[0],
                          rtol=0.15)       # clean end barely moves
        # patch rides a LoRA derivation
        (pl, _) = get_op("LoraLoader").execute(octx, pv, pv,
                                               "style.safetensors", 0.5,
                                               0.5)
        assert pl.prediction_type == "v"
        with pytest.raises(ValueError):
            get_op("ModelSamplingDiscrete").execute(octx, p, "nope",
                                                    False)
        # sampling: v-interpretation of the same weights differs from eps
        pos = Conditioning(context=p.encode_prompt(["dunes"])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (a,) = get_op("KSampler").execute(octx, p, 5, 2, 4.0, "euler",
                                          "normal", pos, pos, lat, 1.0)
        (b,) = get_op("KSampler").execute(octx, pv, 5, 2, 4.0, "euler",
                                          "normal", pos, pos, lat, 1.0)
        assert np.isfinite(np.asarray(b["samples"])).all()
        assert not np.allclose(np.asarray(a["samples"]),
                               np.asarray(b["samples"]))
        registry.clear_pipeline_cache()

    def test_perp_neg_reduces_to_cfg_when_empty_is_negative(self):
        """neg == empty -> the perpendicular component vanishes and the
        combine is EXACTLY plain CFG against the empty prompt."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("pn-eq.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (pp,) = get_op("PerpNeg").execute(octx, p, neg, 1.0)
        (a,) = get_op("KSampler").execute(octx, pp, 5, 2, 6.0, "euler",
                                          "normal", pos, neg, lat, 1.0)
        (b,) = get_op("KSampler").execute(octx, p, 5, 2, 6.0, "euler",
                                          "normal", pos, neg, lat, 1.0)
        # tripled- vs doubled-batch executables fuse differently; the
        # reduction is algebraically exact, numerically ~1e-6 relative
        np.testing.assert_allclose(np.asarray(a["samples"]),
                                   np.asarray(b["samples"]),
                                   rtol=1e-3, atol=1e-4)
        # a DISTINCT empty changes the guidance
        emp = Conditioning(context=p.encode_prompt(["photo"])[0])
        (pd,) = get_op("PerpNeg").execute(octx, p, emp, 1.0)
        (c,) = get_op("KSampler").execute(octx, pd, 5, 2, 6.0, "euler",
                                          "normal", pos, neg, lat, 1.0)
        s = np.asarray(c["samples"])
        assert np.isfinite(s).all()
        assert not np.allclose(s, np.asarray(b["samples"]))
        registry.clear_pipeline_cache()

    def test_perp_neg_guider_matches_patch(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("pn-g.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=p.encode_prompt(["blurry"])[0])
        emp = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (sampler,) = get_op("KSamplerSelect").execute(octx, "euler")
        (sig,) = get_op("BasicScheduler").execute(octx, p, "normal", 3,
                                                  1.0)
        (noise,) = get_op("RandomNoise").execute(octx, 9)
        (guider,) = get_op("PerpNegGuider").execute(octx, p, pos, neg,
                                                    emp, 6.0, 1.0)
        a, _ = get_op("SamplerCustomAdvanced").execute(
            octx, noise, guider, sampler, sig, lat)
        (pp,) = get_op("PerpNeg").execute(octx, p, emp, 1.0)
        b, _ = get_op("SamplerCustom").execute(
            octx, pp, True, 9, 6.0, pos, neg, lat, sampler, sig)
        np.testing.assert_allclose(np.asarray(a["samples"]),
                                   np.asarray(b["samples"]),
                                   rtol=1e-5, atol=1e-5)
        registry.clear_pipeline_cache()

    def test_hypertile_module_level(self):
        import jax as _jax

        from comfyui_distributed_tpu.models.layers import (
            SpatialTransformer, _hypertile_divisor)
        assert _hypertile_divisor(32, 4) == 8
        assert _hypertile_divisor(32, 32) == 1
        assert _hypertile_divisor(30, 7) == 3   # 30/3=10 >= 7
        st = SpatialTransformer(num_heads=2, dtype=jnp.float32)
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((1, 8, 8, 32)), jnp.float32)
        ctx = jnp.asarray(rng.standard_normal((1, 7, 64)), jnp.float32)
        params = st.init(_jax.random.PRNGKey(0), x, ctx)
        base = st.apply(params, x, ctx)
        tiled = SpatialTransformer(num_heads=2, dtype=jnp.float32,
                                   hypertile_tile=4)
        out = tiled.apply(params, x, ctx)
        assert out.shape == base.shape
        assert not np.allclose(np.asarray(out), np.asarray(base))
        # a tile >= the whole map is a no-op (nh = nw = 1)
        whole = SpatialTransformer(num_heads=2, dtype=jnp.float32,
                                   hypertile_tile=8)
        np.testing.assert_array_equal(np.asarray(whole.apply(params, x,
                                                             ctx)),
                                      np.asarray(base))

    def test_hypertile_node_runs(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("ht.ckpt")
        octx = OpContext()
        (ph,) = get_op("HyperTile").execute(octx, p, 32, 2, 1, False)
        assert ph.family.unet.hypertile == (32, 1, False)
        assert ph.unet_params is p.unet_params
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        lat = {"samples": np.zeros((1, 16, 16, 4), np.float32)}
        (a,) = get_op("KSampler").execute(octx, ph, 5, 2, 4.0, "euler",
                                          "normal", pos, pos, lat, 1.0)
        s = np.asarray(a["samples"])
        assert np.isfinite(s).all()
        (b,) = get_op("KSampler").execute(octx, p, 5, 2, 4.0, "euler",
                                          "normal", pos, pos, lat, 1.0)
        assert not np.allclose(s, np.asarray(b["samples"]))
        registry.clear_pipeline_cache()


class TestPerpNegIntegration:
    def test_cache_keyed_by_empty_cond_and_rides_chains(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("pn-cache.ckpt")
        octx = OpContext()
        e1 = Conditioning(context=p.encode_prompt(["a"])[0])
        e2 = Conditioning(context=p.encode_prompt(["b"])[0])
        (p1,) = get_op("PerpNeg").execute(octx, p, e1, 1.0)
        (p2,) = get_op("PerpNeg").execute(octx, p, e2, 1.0)
        assert p1 is not p2            # distinct empties: distinct clones
        assert p2.perp_neg_cond is e2
        (p1b,) = get_op("PerpNeg").execute(octx, p, e1, 1.0)
        assert p1b is p1               # same empty: cache hit
        (pl, _) = get_op("LoraLoader").execute(octx, p1, p1,
                                               "s.safetensors", 0.5, 0.5)
        assert getattr(pl, "perp_neg_cond", None) is e1
        assert getattr(pl, "perp_neg_scale", None) == 1.0
        registry.clear_pipeline_cache()

    def test_refine_batch_passes_perp_neg(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        import jax.numpy as jnp
        captured = {}

        class _U:
            adm_in_channels = None

        class _F:
            unet = _U()

        class _Pipe:
            family = _F()
            perp_neg_cond = Conditioning(
                context=np.ones((1, 77, 8), np.float32))
            perp_neg_scale = 0.7

            def vae_encode(self, t):
                return jnp.zeros((t.shape[0], 4, 4, 4))

            def sample(self, lat, c, u, seeds, **kw):
                captured.update(kw)
                return lat

            def vae_decode(self, lat):
                return np.zeros((lat.shape[0], 8, 8, 3), np.float32)

        op = get_op("UltimateSDUpscaleDistributed")
        pos = Conditioning(context=np.zeros((1, 77, 8), np.float32))
        params = {"seed": 1, "steps": 1, "cfg": 4.0,
                  "sampler_name": "euler", "scheduler": "normal",
                  "denoise": 0.5}
        op._refine_batch(OpContext(), _Pipe(),
                         np.zeros((2, 8, 8, 3), np.float32), [0, 1],
                         pos, pos, params)
        assert captured["guidance"] == "perp_neg"
        assert captured["cfg2"] == 0.7
        assert captured["middle_context"].shape == (2, 77, 8)


class TestSelfAttentionGuidance:
    def test_sag_changes_output_and_zero_scale_matches_plain(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("sag.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (plain,) = get_op("KSampler").execute(octx, p, 3, 2, 6.0, "euler",
                                              "normal", pos, neg, lat,
                                              1.0)
        (p0,) = get_op("SelfAttentionGuidance").execute(octx, p, 0.0,
                                                        2.0)
        (z,) = get_op("KSampler").execute(octx, p0, 3, 2, 6.0, "euler",
                                          "normal", pos, neg, lat, 1.0)
        # scale 0: the SAG term vanishes; only fusion noise remains
        np.testing.assert_allclose(np.asarray(z["samples"]),
                                   np.asarray(plain["samples"]),
                                   rtol=1e-3, atol=1e-4)
        (ps,) = get_op("SelfAttentionGuidance").execute(octx, p, 0.8,
                                                        2.0)
        assert ps.family.unet.sag_capture is True
        assert ps.sag_params == (0.8, 2.0)
        (s,) = get_op("KSampler").execute(octx, ps, 3, 2, 6.0, "euler",
                                          "normal", pos, neg, lat, 1.0)
        arr = np.asarray(s["samples"])
        assert np.isfinite(arr).all()
        assert not np.allclose(arr, np.asarray(plain["samples"]))
        registry.clear_pipeline_cache()

    def test_sag_falls_back_without_uncond_benefit(self):
        """cfg == 1 (no uncond evaluated): SAG logs and samples without
        guidance instead of crashing."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("sag-fb.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (ps,) = get_op("SelfAttentionGuidance").execute(octx, p, 0.5,
                                                        2.0)
        (out,) = get_op("KSampler").execute(octx, ps, 3, 2, 1.0, "euler",
                                            "normal", pos, pos, lat, 1.0)
        assert np.isfinite(np.asarray(out["samples"])).all()
        registry.clear_pipeline_cache()

    def test_gaussian_blur_reflect_constant_invariant(self):
        from comfyui_distributed_tpu.models import samplers as smp
        import jax.numpy as jnp
        flat = jnp.full((1, 12, 12, 4), 0.7, jnp.float32)
        out = smp._gaussian_blur_nhwc(flat, 9, 2.0)
        np.testing.assert_allclose(np.asarray(out), 0.7, atol=1e-6)


class TestInpaintModelFamily:
    """9-channel inpaint checkpoints (sd15_inpaint / tiny_inpaint) +
    InpaintModelConditioning."""

    def test_family_detection_and_virtual_init(self, monkeypatch):
        monkeypatch.delenv(registry.FAMILY_ENV, raising=False)
        assert registry.detect_family("sd-v1-5-inpainting.ckpt") \
            == "sd15_inpaint"
        assert registry.detect_family("tiny-inpaint.ckpt") \
            == "tiny_inpaint"
        assert registry.detect_family("dreamlike.safetensors") == "sd15"
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("tiny-inpaint-a.ckpt")
        assert p.family.unet.in_channels == 9
        # conv_in consumes 9 channels
        kern = p.unet_params["conv_in"]["kernel"]
        assert kern.shape[2] == 9
        registry.clear_pipeline_cache()

    def test_inpaint_model_conditioning_e2e(self, monkeypatch):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        monkeypatch.setenv(registry.FAMILY_ENV, "tiny_inpaint")
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("tiny-inpaint-b.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        rng = np.random.default_rng(3)
        img = rng.uniform(0, 1, (1, 32, 32, 3)).astype(np.float32)
        mask = np.zeros((1, 32, 32), np.float32)
        mask[:, 8:24, 8:24] = 1.0
        pos2, neg2, lat = get_op("InpaintModelConditioning").execute(
            octx, pos, neg, p, img, mask, True)
        # tiny VAE downscales 2x: latent 16x16; concat = mask(1)+lat(4)
        assert pos2.concat_latent.shape == (1, 16, 16, 5)
        assert neg2.concat_latent is pos2.concat_latent
        assert "noise_mask" in lat
        (out,) = get_op("KSampler").execute(octx, p, 5, 2, 4.0, "euler",
                                            "normal", pos2, neg2, lat,
                                            0.6)
        s = np.asarray(out["samples"])
        assert np.isfinite(s).all()
        # the concat channels actually steer: a different mask/masked
        # content changes the result
        mask2 = np.zeros((1, 32, 32), np.float32)
        mask2[:, 0:8, 0:8] = 1.0
        pos3, neg3, lat3 = get_op("InpaintModelConditioning").execute(
            octx, pos, neg, p, img, mask2, True)
        (out2,) = get_op("KSampler").execute(octx, p, 5, 2, 4.0, "euler",
                                             "normal", pos3, neg3, lat3,
                                             0.6)
        assert not np.allclose(s, np.asarray(out2["samples"]))
        # noise_mask widget off: no mask on the latent (pure
        # model-driven inpainting)
        _, _, lat_nm = get_op("InpaintModelConditioning").execute(
            octx, pos, neg, p, img, mask, False)
        assert "noise_mask" not in lat_nm
        registry.clear_pipeline_cache()


class TestDeepShrink:
    def test_unet_shrunk_config_shapes(self):
        import jax as _jax

        from comfyui_distributed_tpu.models import unet as unet_mod
        cfg = unet_mod.TINY_CONFIG
        mod = unet_mod.UNet(cfg)
        x = jnp.zeros((1, 16, 16, 4), jnp.float32)
        ts = jnp.zeros((1,))
        c = jnp.zeros((1, 77, cfg.context_dim), jnp.float32)
        params = registry._virtual_params(mod, 3, x, ts, c)
        plain = mod.apply({"params": params}, x, ts, c)
        import dataclasses as dc
        sh_mod = unet_mod.UNet(dc.replace(cfg, deep_shrink=(1, 2.0)))
        shrunk = sh_mod.apply({"params": params}, x, ts, c)
        assert shrunk.shape == plain.shape
        assert not np.allclose(np.asarray(shrunk), np.asarray(plain))

    def test_node_patch_and_window(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("dshrink.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        lat = {"samples": np.zeros((1, 16, 16, 4), np.float32)}
        (plain,) = get_op("KSampler").execute(octx, p, 3, 3, 4.0,
                                              "euler", "normal", pos,
                                              pos, lat, 1.0)
        (pd,) = get_op("PatchModelAddDownscale").execute(
            octx, p, 3, 2.0, 0.0, 0.35, True, "bicubic", "bicubic")
        lvl, fac, t_lo, t_hi = pd.deep_shrink_spec
        assert lvl == 1.0 and fac == 2.0 and t_hi > t_lo
        (out,) = get_op("KSampler").execute(octx, pd, 3, 3, 4.0,
                                            "euler", "normal", pos, pos,
                                            lat, 1.0)
        s = np.asarray(out["samples"])
        assert np.isfinite(s).all()
        assert not np.allclose(s, np.asarray(plain["samples"]))
        # window [0, 0): never active -> results match the plain run
        (p0,) = get_op("PatchModelAddDownscale").execute(
            octx, p, 3, 2.0, 0.0, 0.0, True, "bicubic", "bicubic")
        (same,) = get_op("KSampler").execute(octx, p0, 3, 3, 4.0,
                                             "euler", "normal", pos,
                                             pos, lat, 1.0)
        np.testing.assert_allclose(np.asarray(same["samples"]),
                                   np.asarray(plain["samples"]),
                                   rtol=1e-4, atol=1e-5)
        # rides a LoRA derivation
        (pl, _) = get_op("LoraLoader").execute(octx, pd, pd,
                                               "s.safetensors", 0.5, 0.5)
        assert getattr(pl, "deep_shrink_spec", None) is not None
        registry.clear_pipeline_cache()

    def test_block_number_level_mapping(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("dshrink-map.ckpt")   # tiny: nrb=1
        octx = OpContext()
        # tiny num_res_blocks=1: block1 -> level0, block2 (its
        # downsample) -> level1
        (a,) = get_op("PatchModelAddDownscale").execute(
            octx, p, 1, 2.0, 0.0, 0.5, True, "bicubic", "bicubic")
        assert a.deep_shrink_spec[0] == 0.0
        (b,) = get_op("PatchModelAddDownscale").execute(
            octx, p, 2, 2.0, 0.0, 0.5, True, "bicubic", "bicubic")
        assert b.deep_shrink_spec[0] == 1.0
        registry.clear_pipeline_cache()


class TestRound4ReviewFixes:
    def test_inpaint_family_routing(self, monkeypatch):
        monkeypatch.delenv(registry.FAMILY_ENV, raising=False)
        assert registry.detect_family("512-inpainting-ema.ckpt") \
            == "sd21_inpaint"
        assert registry.detect_family("sd2-inpainting.safetensors") \
            == "sd21_inpaint"
        assert registry.detect_family("sd_xl_inpainting_0.1.safetensors") \
            == "sdxl_inpaint"
        assert registry.detect_family("sd-v1-5-inpainting.ckpt") \
            == "sd15_inpaint"
        assert registry.FAMILIES["sd21_inpaint"].unet.context_dim == 1024
        assert registry.FAMILIES["sdxl_inpaint"].unet.in_channels == 9

    def test_image_quantize_dither_has_effect(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        octx = OpContext()
        rng = np.random.default_rng(6)
        grad = np.linspace(0, 1, 64, dtype=np.float32)
        img = np.broadcast_to(grad, (32, 64)).astype(np.float32)
        img = np.stack([img, img, img], axis=-1)[None]
        img = img + rng.uniform(0, 0.02, img.shape).astype(np.float32)
        (nd,) = get_op("ImageQuantize").execute(octx, img, 4, "none")
        (fd,) = get_op("ImageQuantize").execute(octx, img, 4,
                                                "floyd-steinberg")
        assert not np.array_equal(nd, fd)    # dithering actually runs

    def test_sag_falls_back_with_hypertiled_mid(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("sag-ht.ckpt")
        octx = OpContext()
        (ph,) = get_op("HyperTile").execute(octx, p, 32, 2, 3, False)
        (ps,) = get_op("SelfAttentionGuidance").execute(octx, ph, 0.5,
                                                        2.0)
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 16, 16, 4), np.float32)}
        (out,) = get_op("KSampler").execute(octx, ps, 3, 2, 5.0, "euler",
                                            "normal", pos, neg, lat, 1.0)
        assert np.isfinite(np.asarray(out["samples"])).all()
        registry.clear_pipeline_cache()


class TestHypernetwork:
    def _make_pt(self, path, dim, seed=0):
        """A real A1111-layout .pt: torch Sequential exports + metadata."""
        import torch
        g = torch.Generator().manual_seed(seed)

        def stream():
            return {
                "linear.0.weight": torch.randn((dim * 2, dim),
                                               generator=g) * 0.2,
                "linear.0.bias": torch.zeros(dim * 2),
                "linear.2.weight": torch.randn((dim, dim * 2),
                                               generator=g) * 0.2,
                "linear.2.bias": torch.zeros(dim),
            }
        torch.save({"layer_structure": [1, 2, 1],
                    "activation_func": "relu",
                    "is_layer_norm": False,
                    "activate_output": False,
                    dim: [stream(), stream()]}, path)

    def test_parse_and_apply_real_pt(self, tmp_path):
        import os

        from comfyui_distributed_tpu.models import hypernetwork as hn_mod
        d = os.path.join(str(tmp_path), "hypernetworks")
        os.makedirs(d)
        self._make_pt(os.path.join(d, "style.pt"), 16, seed=3)
        hn = hn_mod.load_hypernetwork("style", models_dir=str(tmp_path))
        assert 16 in hn
        ctx = jnp.asarray(np.random.default_rng(1).standard_normal(
            (1, 7, 16)), jnp.float32)
        ck, cv = hn_mod.apply_hypernetwork(hn, 1.0, ctx)
        assert ck.shape == ctx.shape and cv.shape == ctx.shape
        assert not np.allclose(np.asarray(ck), np.asarray(ctx))
        assert not np.allclose(np.asarray(ck), np.asarray(cv))
        # strength 0: exact passthrough
        ck0, cv0 = hn_mod.apply_hypernetwork(hn, 0.0, ctx)
        np.testing.assert_array_equal(np.asarray(ck0), np.asarray(ctx))
        # unknown width: passthrough untouched
        other = jnp.zeros((1, 7, 24), jnp.float32)
        ok, ov = hn_mod.apply_hypernetwork(hn, 1.0, other)
        assert ok is other and ov is other
        # torch-reference parity for the k stream: x + relu-MLP(x)
        import torch
        sd = torch.load(os.path.join(d, "style.pt"),
                        weights_only=True)
        k_sd = sd[16][0]
        xt = torch.from_numpy(np.asarray(ctx))
        ref = xt + (torch.relu(xt @ k_sd["linear.0.weight"].T
                               + k_sd["linear.0.bias"])
                    @ k_sd["linear.2.weight"].T + k_sd["linear.2.bias"])
        np.testing.assert_allclose(np.asarray(ck), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)
        hn_mod.clear_hypernetwork_cache()

    def test_loader_node_steers_sampling(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("hn-base.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (plain,) = get_op("KSampler").execute(octx, p, 3, 2, 4.0,
                                              "euler", "normal", pos,
                                              pos, lat, 1.0)
        (ph,) = get_op("HypernetworkLoader").execute(octx, p,
                                                     "vstyle.pt", 0.8)
        assert ph is not p and ph.hypernets[0][1] == 0.8
        (out,) = get_op("KSampler").execute(octx, ph, 3, 2, 4.0,
                                            "euler", "normal", pos, pos,
                                            lat, 1.0)
        s = np.asarray(out["samples"])
        assert np.isfinite(s).all()
        assert not np.allclose(s, np.asarray(plain["samples"]))
        # strength 0 is a passthrough (no derivation)
        (p0,) = get_op("HypernetworkLoader").execute(octx, p,
                                                     "vstyle.pt", 0.0)
        assert p0 is p
        # rides a LoRA chain
        (pl, _) = get_op("LoraLoader").execute(octx, ph, ph,
                                               "s.safetensors", 0.5, 0.5)
        assert getattr(pl, "hypernets", None) is not None
        # chained loaders COMPOSE (reference: attn patches stack)
        (p2,) = get_op("HypernetworkLoader").execute(octx, ph,
                                                     "other.pt", 0.3)
        assert len(p2.hypernets) == 2
        assert p2.hypernets[0][1] == 0.8 and p2.hypernets[1][1] == 0.3
        (out2,) = get_op("KSampler").execute(octx, p2, 3, 2, 4.0,
                                             "euler", "normal", pos,
                                             pos, lat, 1.0)
        assert np.isfinite(np.asarray(out2["samples"])).all()
        assert not np.allclose(np.asarray(out2["samples"]), s)
        registry.clear_pipeline_cache()


class TestModelMergingAndSaves:
    def test_model_merge_simple_exact(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        import jax as _jax
        registry.clear_pipeline_cache()
        a = registry.load_pipeline("merge-a.ckpt")
        b = registry.load_pipeline("merge-b.ckpt")
        octx = OpContext()
        (m1,) = get_op("ModelMergeSimple").execute(octx, a, b, 1.0)
        _jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-6),
            m1.unet_params, a.unet_params)
        (mh,) = get_op("ModelMergeSimple").execute(octx, a, b, 0.25)
        la = _jax.tree_util.tree_leaves(a.unet_params)[0]
        lb = _jax.tree_util.tree_leaves(b.unet_params)[0]
        lm = _jax.tree_util.tree_leaves(mh.unet_params)[0]
        np.testing.assert_allclose(
            np.asarray(lm),
            np.asarray(la) * 0.25 + np.asarray(lb) * 0.75, rtol=1e-5)
        # CLIP/VAE stay model1's (ComfyUI merges the UNet only here)
        assert mh.clip_params is a.clip_params
        registry.clear_pipeline_cache()

    def test_model_merge_blocks_sections(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        a = registry.load_pipeline("mergeb-a.ckpt")
        b = registry.load_pipeline("mergeb-b.ckpt")
        octx = OpContext()
        (m,) = get_op("ModelMergeBlocks").execute(octx, a, b, 1.0, 0.0,
                                                  1.0)
        # middle ratio 0 -> mid blocks are exactly model2's
        np.testing.assert_allclose(
            np.asarray(m.unet_params["mid_res_0"]["in_conv"]["kernel"]),
            np.asarray(b.unet_params["mid_res_0"]["in_conv"]["kernel"]),
            rtol=1e-6)
        # encoder ratio 1 -> down blocks are exactly model1's
        np.testing.assert_allclose(
            np.asarray(m.unet_params["conv_in"]["kernel"]),
            np.asarray(a.unet_params["conv_in"]["kernel"]), rtol=1e-6)
        registry.clear_pipeline_cache()

    def test_clip_merge_and_lora_model_only(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        import jax as _jax
        registry.clear_pipeline_cache()
        a = registry.load_pipeline("cm-a.ckpt")
        b = registry.load_pipeline("cm-b.ckpt")
        octx = OpContext()
        (c,) = get_op("CLIPMergeSimple").execute(octx, a, b, 0.5)
        la = _jax.tree_util.tree_leaves(a.clip_params[0])[0]
        lb = _jax.tree_util.tree_leaves(b.clip_params[0])[0]
        lc = _jax.tree_util.tree_leaves(c.clip_params[0])[0]
        np.testing.assert_allclose(
            np.asarray(lc), (np.asarray(la) + np.asarray(lb)) / 2,
            rtol=1e-5)
        (lm,) = get_op("LoraLoaderModelOnly").execute(
            octx, a, "style.safetensors", 0.7)
        assert lm is not a and lm.clip_params is a.clip_params
        assert lm.unet_params is not a.unet_params
        registry.clear_pipeline_cache()

    def test_vae_and_clip_save_round_trip(self, tmp_path, monkeypatch):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        import jax as _jax
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("saver.ckpt")
        octx = OpContext()
        octx.output_dir = str(tmp_path)
        get_op("VAESave").execute(octx, p, "vae/exported")
        import os
        vp = os.path.join(str(tmp_path), "vae", "exported.safetensors")
        assert os.path.exists(vp)
        # bare-key standalone file loads back through VAELoader
        reloaded = registry.load_vae(
            os.path.relpath(vp, str(tmp_path)), models_dir=str(tmp_path),
            family_name="tiny")
        _jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6),
            reloaded.vae_params, p.vae_params)
        get_op("CLIPSave").execute(octx, p, "clip/exported")
        assert os.path.exists(os.path.join(str(tmp_path), "clip",
                                           "exported.safetensors"))
        registry.clear_pipeline_cache()


class TestMergeBlocksSectionAnchoring:
    def test_encoder_inner_out_norm_uses_input_ratio(self):
        """ResBlocks contain an inner 'out_norm'; a substring match
        would misroute encoder norms into the 'out' section."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        a = registry.load_pipeline("anchor-a.ckpt")
        b = registry.load_pipeline("anchor-b.ckpt")
        octx = OpContext()
        (m,) = get_op("ModelMergeBlocks").execute(octx, a, b, 1.0, 1.0,
                                                  0.0)
        # encoder ResBlock's INNER out_norm follows the input ratio (1.0
        # -> model1), not the out ratio
        np.testing.assert_allclose(
            np.asarray(m.unet_params["down_0_res_0"]["out_norm"]
                       ["GroupNorm_0"]["scale"])
            if "GroupNorm_0" in m.unet_params["down_0_res_0"]["out_norm"]
            else np.asarray(m.unet_params["down_0_res_0"]["out_norm"]
                            [next(iter(m.unet_params["down_0_res_0"]
                                       ["out_norm"]))]["scale"]),
            np.asarray(a.unet_params["down_0_res_0"]["out_norm"]
                       ["GroupNorm_0"]["scale"])
            if "GroupNorm_0" in a.unet_params["down_0_res_0"]["out_norm"]
            else np.asarray(a.unet_params["down_0_res_0"]["out_norm"]
                            [next(iter(a.unet_params["down_0_res_0"]
                                       ["out_norm"]))]["scale"]),
            rtol=1e-6)
        # the top-level out_norm follows the OUT ratio (0.0 -> model2)
        top = m.unet_params["out_norm"]
        key = next(iter(top))
        np.testing.assert_allclose(
            np.asarray(top[key]["scale"]),
            np.asarray(b.unet_params["out_norm"][key]["scale"]),
            rtol=1e-6)
        # cache probe: re-execution returns the same object
        (m2,) = get_op("ModelMergeBlocks").execute(octx, a, b, 1.0, 1.0,
                                                   0.0)
        assert m2 is m
        registry.clear_pipeline_cache()


class TestUnCLIP:
    def test_vision_tower_encode_shapes(self):
        registry.clear_pipeline_cache()
        tower = registry.load_clip_vision("tiny-vision")
        rng = np.random.default_rng(2)
        img = rng.uniform(0, 1, (2, 48, 96, 3)).astype(np.float32)
        out = tower.encode(img, crop="center")
        assert out.image_embeds.shape == (2, 32)
        n_tok = (64 // 16) ** 2 + 1
        assert out.last_hidden.shape == (2, n_tok, 64)
        assert np.isfinite(np.asarray(out.image_embeds)).all()
        # center crop differs from squash on a non-square source
        out2 = tower.encode(img, crop="none")
        assert not np.allclose(np.asarray(out.image_embeds),
                               np.asarray(out2.image_embeds))
        registry.clear_pipeline_cache()

    def test_vision_checkpoint_round_trip(self, tmp_path):
        """A real HF-layout vision safetensors loads through the
        converter and matches the exporting params."""
        import os

        import jax as _jax
        from comfyui_distributed_tpu.models import clip_vision as cv
        from comfyui_distributed_tpu.models.checkpoints import \
            save_state_dict
        registry.clear_pipeline_cache()
        tower = registry.load_clip_vision("tiny-vision-rt")
        p = tower.params
        sd = {}
        sd["vision_model.embeddings.class_embedding"] = \
            np.asarray(p["class_embedding"], np.float32)
        sd["vision_model.embeddings.position_embedding.weight"] = \
            np.asarray(p["position_embedding"], np.float32)
        k = np.asarray(p["patch_embed"]["kernel"], np.float32)
        sd["vision_model.embeddings.patch_embedding.weight"] = \
            k.transpose(3, 2, 0, 1)
        for tk, fk in (("pre_layrnorm", "pre_ln"),
                       ("post_layernorm", "post_ln")):
            sd[f"vision_model.{tk}.weight"] = \
                np.asarray(p[fk]["scale"], np.float32)
            sd[f"vision_model.{tk}.bias"] = \
                np.asarray(p[fk]["bias"], np.float32)
        for i in range(tower.cfg.layers):
            lp = p[f"layers_{i}"]
            t = f"vision_model.encoder.layers.{i}"
            for tn, fn in (("layer_norm1", "ln1"), ("layer_norm2",
                                                    "ln2")):
                sd[f"{t}.{tn}.weight"] = np.asarray(lp[fn]["scale"])
                sd[f"{t}.{tn}.bias"] = np.asarray(lp[fn]["bias"])
            for tn, fn in (("self_attn.q_proj", "q"),
                           ("self_attn.k_proj", "k"),
                           ("self_attn.v_proj", "v"),
                           ("self_attn.out_proj", "proj"),
                           ("mlp.fc1", "fc1"), ("mlp.fc2", "fc2")):
                sd[f"{t}.{tn}.weight"] = \
                    np.asarray(lp[fn]["kernel"]).T
                sd[f"{t}.{tn}.bias"] = np.asarray(lp[fn]["bias"])
        sd["visual_projection.weight"] = \
            np.asarray(p["visual_projection"]["kernel"]).T
        d = os.path.join(str(tmp_path), "clip_vision")
        os.makedirs(d)
        # save_state_dict, NOT raw safetensors save_file: transposed
        # views silently round-trip WRONG through save_file (it ignores
        # strides) — the production saver makes arrays contiguous
        save_state_dict(sd, os.path.join(d, "tiny_vit.safetensors"))
        loaded = registry.load_clip_vision("tiny_vit.safetensors",
                                           models_dir=str(tmp_path),
                                           config_name="tiny")
        _jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6),
            loaded.params, tower.params)
        registry.clear_pipeline_cache()

    def test_unclip_conditioning_and_sampling(self, monkeypatch):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        monkeypatch.delenv(registry.FAMILY_ENV, raising=False)
        assert registry.detect_family("sd21-unclip-h.ckpt") \
            == "sd21_unclip"
        registry.clear_pipeline_cache()
        octx = OpContext()
        model, clip, vae, vision = get_op("unCLIPCheckpointLoader") \
            .execute(octx, "tiny-unclip-a.ckpt")
        assert model.family.adm_kind == "unclip"
        rng = np.random.default_rng(5)
        img = rng.uniform(0, 1, (1, 64, 64, 3)).astype(np.float32)
        (vout,) = get_op("CLIPVisionEncode").execute(octx, vision, img,
                                                     "center")
        pos = Conditioning(context=model.encode_prompt(["a fox"])[0])
        neg = Conditioning(context=model.encode_prompt([""])[0])
        (posu,) = get_op("unCLIPConditioning").execute(octx, pos, vout,
                                                       1.0, 0.1)
        assert len(posu.unclip) == 1
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (out,) = get_op("KSampler").execute(octx, model, 3, 2, 5.0,
                                            "euler", "normal", posu, neg,
                                            lat, 1.0)
        s = np.asarray(out["samples"])
        assert np.isfinite(s).all()
        # the image conditioning steers: dropping it changes the result
        (plain,) = get_op("KSampler").execute(octx, model, 3, 2, 5.0,
                                              "euler", "normal", pos,
                                              neg, lat, 1.0)
        assert not np.allclose(s, np.asarray(plain["samples"]))
        # higher noise augmentation changes the ADM
        (posn,) = get_op("unCLIPConditioning").execute(octx, pos, vout,
                                                       1.0, 0.9)
        (outn,) = get_op("KSampler").execute(octx, model, 3, 2, 5.0,
                                             "euler", "normal", posn,
                                             neg, lat, 1.0)
        assert not np.allclose(s, np.asarray(outn["samples"]))
        registry.clear_pipeline_cache()


class TestUnCLIPReviewFixes:
    def test_uncond_adm_is_zero_and_clamping(self):
        from comfyui_distributed_tpu.ops.base import Conditioning
        from comfyui_distributed_tpu.ops.basic import _unclip_vector_cond
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("tiny-unclip-fix.ckpt",
                                   family_name="tiny_unclip")
        # no entries -> zeros (the reference's zero-fill for uncond)
        z = _unclip_vector_cond(
            p, Conditioning(context=None), 2)
        np.testing.assert_array_equal(np.asarray(z),
                                      np.zeros((2, 64), np.float32))
        emb = np.ones((1, 32), np.float32)
        # negative augmentation clamps to level 0, >1 clamps to max
        lo = _unclip_vector_cond(
            p, Conditioning(context=None, unclip=((emb, 1.0, -0.5),)), 1)
        lo0 = _unclip_vector_cond(
            p, Conditioning(context=None, unclip=((emb, 1.0, 0.0),)), 1)
        np.testing.assert_array_equal(np.asarray(lo), np.asarray(lo0))
        hi = _unclip_vector_cond(
            p, Conditioning(context=None, unclip=((emb, 1.0, 2.0),)), 1)
        assert np.isfinite(np.asarray(hi)).all()
        # batched embeds: row 0 wins, with identical result to passing
        # row 0 directly
        b2 = np.stack([np.ones(32, np.float32),
                       np.full(32, 9.0, np.float32)])
        vb = _unclip_vector_cond(
            p, Conditioning(context=None, unclip=((b2, 1.0, 0.1),)), 1)
        v0 = _unclip_vector_cond(
            p, Conditioning(context=None, unclip=((b2[:1], 1.0, 0.1),)),
            1)
        np.testing.assert_array_equal(np.asarray(vb), np.asarray(v0))
        registry.clear_pipeline_cache()


class TestUnCLIPUncondZeroFill:
    def test_uncond_block_gets_zero_adm(self, monkeypatch):
        """The CFG uncond row must ride the negative's ZERO-filled ADM,
        not a replicated positive image embedding — otherwise
        cfg*(cond-uncond) cancels the image guidance."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext)
        from comfyui_distributed_tpu.ops.basic import \
            _prepare_sample_inputs
        monkeypatch.setenv(registry.FAMILY_ENV, "tiny_unclip")
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("zero-unc.ckpt")
        emb = np.ones((1, 32), np.float32)
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0],
                           unclip=((emb, 1.0, 0.0),))
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        prep = _prepare_sample_inputs(OpContext(), p, 0, lat, pos, neg)
        assert isinstance(prep.y, list) and len(prep.y) == 2
        assert not np.allclose(np.asarray(prep.y[0]), 0.0)
        np.testing.assert_array_equal(np.asarray(prep.y[1]),
                                      np.zeros_like(
                                          np.asarray(prep.y[1])))
        registry.clear_pipeline_cache()


class TestTokenMerging:
    def test_merge_unmerge_contract(self):
        """Kept tokens round-trip EXACTLY; merged tokens adopt their
        destination's row; r=0 is the identity."""
        from comfyui_distributed_tpu.models import tome
        rng = np.random.default_rng(3)
        h = w = 4
        x = jnp.asarray(rng.standard_normal((2, h * w, 8)), jnp.float32)
        m0, u0, r0 = tome.build_merge(x, h, w, 0.0)
        assert r0 == 0 and m0(x) is x and u0(x) is x
        merge, unmerge, r = tome.build_merge(x, h, w, 0.25)
        assert r == 4
        y = merge(x)
        assert y.shape == (2, h * w - r, 8)
        out = unmerge(y)
        assert out.shape == x.shape
        dst_idx, src_idx = tome.dst_grid_indices(h, w)
        # dst rows in the unmerge output must equal the pooled dst rows
        np.testing.assert_allclose(np.asarray(out[:, dst_idx]),
                                   np.asarray(y[:, -dst_idx.shape[0]:]),
                                   rtol=1e-6)
        # EXACT oracle: replicate the matching in numpy and assert the
        # full unmerge(merge(x)) output positionally
        xs = np.asarray(x)
        for b in range(2):
            mm = xs[b] / np.maximum(
                np.linalg.norm(xs[b], axis=-1, keepdims=True), 1e-6)
            scores = mm[src_idx] @ mm[dst_idx].T
            node_max = scores.max(-1)
            node_tgt = scores.argmax(-1)
            order = np.argsort(-node_max, kind="stable")
            merged_sel, kept_sel = order[:r], order[r:]
            pooled = xs[b][dst_idx].copy()
            cnt = np.ones(len(dst_idx), np.float32)
            for srow in merged_sel:
                pooled[node_tgt[srow]] += xs[b][src_idx[srow]]
                cnt[node_tgt[srow]] += 1.0
            pooled /= cnt[:, None]
            expect = np.empty_like(xs[b])
            expect[dst_idx] = pooled
            expect[src_idx[kept_sel]] = xs[b][src_idx[kept_sel]]
            expect[src_idx[merged_sel]] = pooled[node_tgt[merged_sel]]
            np.testing.assert_allclose(np.asarray(out[b]), expect,
                                       rtol=1e-5, atol=1e-6)

    def test_merge_pools_identical_tokens_losslessly(self):
        """If every token in a cell is identical, merging then
        unmerging an identity transform reconstructs the input
        EXACTLY (mean of identical rows = the row)."""
        from comfyui_distributed_tpu.models import tome
        h = w = 4
        base = np.random.default_rng(5).standard_normal((1, 4, 8))
        cells = np.repeat(np.repeat(
            base.reshape(1, 2, 2, 8), 2, axis=1), 2, axis=2) \
            .reshape(1, h * w, 8).astype(np.float32)
        x = jnp.asarray(cells)
        merge, unmerge, r = tome.build_merge(x, h, w, 0.5)
        assert r == 8
        np.testing.assert_allclose(np.asarray(unmerge(merge(x))),
                                   np.asarray(x), rtol=1e-5, atol=1e-5)

    def test_node_patches_and_steers(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("tome.ckpt")
        octx = OpContext()
        (pt,) = get_op("TomePatchModel").execute(octx, p, 0.3)
        assert pt.family.unet.tome_ratio == 0.3
        assert pt.unet_params is p.unet_params
        (p0,) = get_op("TomePatchModel").execute(octx, p, 0.0)
        assert p0 is p
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        lat = {"samples": np.zeros((1, 16, 16, 4), np.float32)}
        (a,) = get_op("KSampler").execute(octx, pt, 3, 2, 4.0, "euler",
                                          "normal", pos, pos, lat, 1.0)
        s = np.asarray(a["samples"])
        assert np.isfinite(s).all()
        (b,) = get_op("KSampler").execute(octx, p, 3, 2, 4.0, "euler",
                                          "normal", pos, pos, lat, 1.0)
        assert not np.allclose(s, np.asarray(b["samples"]))
        registry.clear_pipeline_cache()


class TestGligen:
    def test_position_net_and_fuser_shapes(self):
        import jax as _jax

        from comfyui_distributed_tpu.models import gligen as gg
        from comfyui_distributed_tpu.models.layers import \
            GatedSelfAttention
        registry.clear_pipeline_cache()
        gm = gg.load_gligen("tiny-gligen.pth", text_dim=64)
        embs = np.ones((1, 3, 64), np.float32)
        boxes = np.asarray([[[0, 0, .5, .5], [.5, 0, 1, .5],
                             [0, .5, 1, 1]]], np.float32)
        toks = gm.grounding_tokens(embs, boxes, np.ones((1, 3)))
        assert toks.shape == (1, 3, 64)
        nulls = gm.grounding_tokens(np.zeros_like(embs),
                                    np.zeros_like(boxes),
                                    np.zeros((1, 3)))
        assert not np.allclose(np.asarray(toks), np.asarray(nulls))
        # zero-init gates: a FRESH fuser is an exact no-op
        fus = GatedSelfAttention(num_heads=2, dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(1).standard_normal(
            (1, 16, 32)), jnp.float32)
        params = fus.init(_jax.random.PRNGKey(0), x, toks)
        np.testing.assert_array_equal(np.asarray(fus.apply(params, x,
                                                           toks)),
                                      np.asarray(x))
        registry.clear_pipeline_cache()

    def test_textbox_apply_reaches_combined_siblings(self):
        """ADVICE r4: the reference applies the grounding spec to EVERY
        entry of the conditioning list — siblings bundled earlier by
        ConditioningCombine must carry it too, or their stacked blocks
        sample with null grounding tokens."""
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("gligen-base.ckpt")
        octx = OpContext()
        (gm,) = get_op("GLIGENLoader").execute(octx, "tiny-gligen.pth")
        a = Conditioning(context=p.encode_prompt(["a meadow"])[0])
        b = Conditioning(context=p.encode_prompt(["a lake"])[0])
        (a1,) = get_op("GLIGENTextBoxApply").execute(
            octx, a, p, gm, "a red fox", 32, 32, 0, 0)
        (b1,) = get_op("GLIGENTextBoxApply").execute(
            octx, b, p, gm, "a blue bird", 32, 32, 32, 32)
        (combined,) = get_op("ConditioningCombine").execute(octx, a1, b1)
        assert combined.siblings
        (grounded,) = get_op("GLIGENTextBoxApply").execute(
            octx, combined, p, gm, "a green tree", 16, 16, 16, 0)
        # head: its own prior box + the new one
        assert len(grounded.gligen[1]) == 2
        # sibling: ITS prior box (the bird) survives + the new one
        sib = grounded.siblings[0]
        assert len(sib.gligen[1]) == 2
        assert sib.gligen is not grounded.gligen
        np.testing.assert_array_equal(sib.gligen[1][0][0],
                                      b1.gligen[1][0][0])
        # distinct per-block specs sample end-to-end (stacked token
        # sets padded to a common object count)
        neg = Conditioning(context=p.encode_prompt([""])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (out,) = get_op("KSampler").execute(octx, p, 3, 2, 5.0, "euler",
                                            "normal", grounded, neg,
                                            lat, 1.0)
        assert np.isfinite(np.asarray(out["samples"])).all()

    def test_textbox_apply_and_sampling(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("gligen-base.ckpt")
        octx = OpContext()
        (gm,) = get_op("GLIGENLoader").execute(octx, "tiny-gligen.pth")
        pos = Conditioning(context=p.encode_prompt(["a meadow"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        (posg,) = get_op("GLIGENTextBoxApply").execute(
            octx, pos, p, gm, "a red fox", 32, 32, 0, 0)
        (posg2,) = get_op("GLIGENTextBoxApply").execute(
            octx, posg, p, gm, "a blue bird", 32, 32, 32, 32)
        assert len(posg2.gligen[1]) == 2
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (out,) = get_op("KSampler").execute(octx, p, 3, 2, 5.0, "euler",
                                            "normal", posg2, neg, lat,
                                            1.0)
        s = np.asarray(out["samples"])
        assert np.isfinite(s).all()
        (plain,) = get_op("KSampler").execute(octx, p, 3, 2, 5.0,
                                              "euler", "normal", pos,
                                              neg, lat, 1.0)
        # virtual fusers zero-init their gates: grounded == plain
        # EXACTLY (the graft preserves the base weights bit-exact)
        np.testing.assert_allclose(s, np.asarray(plain["samples"]),
                                   rtol=2e-3, atol=2e-3)
        # boost the gates -> grounding steers
        from comfyui_distributed_tpu.ops.basic import gligen_attach
        pg = gligen_attach(p, gm)
        import jax as _jax

        def boost(path, a):
            kp = _jax.tree_util.keystr(path)
            if "alpha_attn" in kp or "alpha_dense" in kp:
                return jnp.full_like(a, 0.5)
            return a
        pg.unet_params = _jax.tree_util.tree_map_with_path(
            boost, pg.unet_params)
        pg._jit_cache.clear()
        (steered,) = get_op("KSampler").execute(octx, pg, 3, 2, 5.0,
                                                "euler", "normal",
                                                posg2, neg, lat, 1.0)
        assert np.isfinite(np.asarray(steered["samples"])).all()
        assert not np.allclose(np.asarray(steered["samples"]), s,
                               atol=1e-3)
        registry.clear_pipeline_cache()


class TestGligenCarryFlags:
    def test_flags_follow_the_carrying_entry(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        from comfyui_distributed_tpu.ops.basic import \
            _prepare_sample_inputs
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("gligen-flags.ckpt")
        octx = OpContext()
        (gm,) = get_op("GLIGENLoader").execute(octx, "tiny-gg2.pth")
        pos = Conditioning(context=p.encode_prompt(["a"])[0])
        neg = Conditioning(context=p.encode_prompt([""])[0])
        (negg,) = get_op("GLIGENTextBoxApply").execute(
            octx, neg, p, gm, "x", 16, 16, 0, 0)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        # gligen on the NEGATIVE only: spec indices (pos=-1, neg=0)
        prep = _prepare_sample_inputs(octx, p, 0, lat, pos, negg)
        assert prep.gligen_objs is not None
        assert prep.gligen_objs[2] == (-1, 0)
        # and on the positive: (0, -1)
        (posg,) = get_op("GLIGENTextBoxApply").execute(
            octx, pos, p, gm, "x", 16, 16, 0, 0)
        prep2 = _prepare_sample_inputs(octx, p, 0, lat, posg, neg)
        assert prep2.gligen_objs[2] == (0, -1)
        # distinct specs on BOTH sides: each block keeps its own set
        prep3 = _prepare_sample_inputs(octx, p, 0, lat, posg, negg)
        assert prep3.gligen_objs[2] == (0, 1)
        assert prep3.gligen_objs[0].shape[0] == 2   # stacked [S, ...]
        registry.clear_pipeline_cache()


class TestComponentLoadersRound5:
    """CLIPLoader / DualCLIPLoader / UNETLoader: standalone towers
    assemble into usable wires (reference-ecosystem split-checkpoint
    workflows)."""

    def test_clip_save_load_round_trip(self, tmp_path):
        """CLIPSave's in-checkpoint-prefix export reloads through
        load_clip into a tower that encodes IDENTICALLY."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        pipe = registry.load_pipeline("cliprt.ckpt")
        octx = OpContext(output_dir=str(tmp_path))
        get_op("CLIPSave").execute(octx, pipe, "tower")
        loaded = registry.load_clip(["tower.safetensors"],
                                    models_dir=str(tmp_path),
                                    family_name="tiny")
        a, _ = pipe.encode_prompt(["a red fox"])
        b, _ = loaded.encode_prompt(["a red fox"])
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_clip_loader_op_virtual_and_type_validation(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        (clip,) = get_op("CLIPLoader").execute(OpContext(), "solo.bin",
                                               "stable_diffusion")
        ctx_arr, _ = clip.encode_prompt(["x"])
        assert ctx_arr.shape[0] == 1
        with pytest.raises(ValueError):
            get_op("CLIPLoader").execute(OpContext(), "x.bin", "nope")
        with pytest.raises(ValueError):   # sdxl needs the dual loader
            get_op("CLIPLoader").execute(OpContext(), "x.bin", "sdxl")

    def test_dual_clip_loader_sdxl_towers(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        (clip,) = get_op("DualCLIPLoader").execute(
            OpContext(), "clip_l.safetensors", "clip_g.safetensors",
            "sdxl")
        assert len(clip.clip_params) == 2
        ctx_arr, pooled = clip.encode_prompt(["x"])
        # SDXL concat: CLIP-L width + bigG width
        assert ctx_arr.shape[-1] == sum(c.width
                                        for c in clip.family.clips)

    def test_unet_loader_samples_end_to_end(self):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        (model,) = get_op("UNETLoader").execute(OpContext(),
                                                "tiny-solo-unet.sft")
        assert model.family.name == "tiny"
        pos = Conditioning(context=model.encode_prompt(["x"])[0])
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (out,) = get_op("KSampler").execute(OpContext(), model, 3, 2,
                                            3.0, "euler", "normal", pos,
                                            pos, lat, 1.0)
        assert np.isfinite(np.asarray(out["samples"])).all()


class TestModelMergeArithmetic:
    """ModelMergeAdd / ModelMergeSubtract — the add-difference pair."""

    def test_subtract_then_add_round_trips(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        a = registry.load_pipeline("ma.ckpt")
        b = registry.load_pipeline("mb.ckpt")
        octx = OpContext()
        (delta,) = get_op("ModelMergeSubtract").execute(octx, a, b, 1.0)
        (back,) = get_op("ModelMergeAdd").execute(octx, delta, b)
        import jax
        for la, lb in zip(jax.tree_util.tree_leaves(a.unet_params),
                          jax.tree_util.tree_leaves(back.unet_params)):
            np.testing.assert_allclose(np.asarray(la, np.float32),
                                       np.asarray(lb, np.float32),
                                       rtol=1e-3, atol=1e-3)

    def test_family_mismatch_raises(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        a = registry.load_pipeline("ma.ckpt")
        c = registry.load_pipeline("inp.ckpt",
                                   family_name="tiny_inpaint")
        with pytest.raises(ValueError):
            get_op("ModelMergeAdd").execute(OpContext(), a, c)


class TestImageBlendOp:
    def _imgs(self):
        a = np.full((1, 4, 4, 3), 0.5, np.float32)
        b = np.full((1, 4, 4, 3), 0.25, np.float32)
        return a, b

    def test_modes(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        a, b = self._imgs()
        op = get_op("ImageBlend")
        octx = OpContext()
        (normal,) = op.execute(octx, a, b, 1.0, "normal")
        np.testing.assert_allclose(normal, 0.25)
        (mult,) = op.execute(octx, a, b, 1.0, "multiply")
        np.testing.assert_allclose(mult, 0.125)
        (scr,) = op.execute(octx, a, b, 1.0, "screen")
        np.testing.assert_allclose(scr, 1 - 0.5 * 0.75, rtol=1e-6)
        (diff,) = op.execute(octx, a, b, 1.0, "difference")
        np.testing.assert_allclose(diff, 0.25)
        (ovl,) = op.execute(octx, a, b, 1.0, "overlay")
        np.testing.assert_allclose(ovl, 0.25, rtol=1e-6)  # a<=0.5: 2ab
        (half,) = op.execute(octx, a, b, 0.5, "normal")
        np.testing.assert_allclose(half, 0.375)
        (soft,) = op.execute(octx, a, b, 1.0, "soft_light")
        assert np.all((soft >= 0) & (soft <= 1))
        with pytest.raises(ValueError):
            op.execute(octx, a, b, 1.0, "dodge")

    def test_mismatched_sizes_resize(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        a = np.zeros((1, 8, 8, 3), np.float32)
        b = np.ones((1, 4, 4, 3), np.float32)
        (out,) = get_op("ImageBlend").execute(OpContext(), a, b, 1.0,
                                              "normal")
        assert out.shape == a.shape
        np.testing.assert_allclose(out, 1.0)


class TestInstructPixToPix:
    def test_conditioning_and_sampling(self, monkeypatch):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        monkeypatch.delenv(registry.FAMILY_ENV, raising=False)
        assert registry.detect_family("tiny-ip2p.ckpt") == "tiny_ip2p"
        assert registry.detect_family(
            "instruct-pix2pix-00-22000.safetensors") == "sd15_ip2p"
        pipe = registry.load_pipeline("tiny-ip2p.ckpt")
        assert pipe.family.unet.in_channels == 8
        octx = OpContext()
        img = np.random.default_rng(0).random((1, 16, 16, 3)
                                              ).astype(np.float32)
        pos = Conditioning(context=pipe.encode_prompt(["make it snowy"])[0])
        neg = Conditioning(context=pipe.encode_prompt([""])[0])
        (p2, n2, lat) = get_op("InstructPixToPixConditioning").execute(
            octx, pos, neg, pipe, img)
        assert p2.concat_latent is not None
        assert n2.concat_latent is not None
        np.testing.assert_array_equal(np.asarray(lat["samples"]), 0.0)
        assert lat["samples"].shape[-1] == 4
        (out,) = get_op("KSampler").execute(octx, pipe, 3, 2, 3.0,
                                            "euler", "normal", p2, n2,
                                            lat, 1.0)
        assert np.isfinite(np.asarray(out["samples"])).all()
        registry.clear_pipeline_cache()


class TestRound5SaveMergeTail:
    def test_clip_merge_subtract_then_add_round_trips(self):
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        a = registry.load_pipeline("cma.ckpt")
        b = registry.load_pipeline("cmb.ckpt")
        octx = OpContext()
        (delta,) = get_op("CLIPMergeSubtract").execute(octx, a, b, 1.0)
        (back,) = get_op("CLIPMergeAdd").execute(octx, delta, b)
        import jax
        for ta, tb in zip(a.clip_params, back.clip_params):
            for la, lb in zip(jax.tree_util.tree_leaves(ta),
                              jax.tree_util.tree_leaves(tb)):
                np.testing.assert_allclose(np.asarray(la, np.float32),
                                           np.asarray(lb, np.float32),
                                           rtol=1e-3, atol=1e-3)

    def test_model_save_unet_loader_round_trip(self, tmp_path,
                                               monkeypatch):
        """ModelSave's model.diffusion_model export reloads through
        UNETLoader into a pipeline whose UNet forward matches."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        pipe = registry.load_pipeline("msave.ckpt")
        octx = OpContext(output_dir=str(tmp_path),
                         models_dir=str(tmp_path))
        get_op("ModelSave").execute(octx, pipe, "unet_rt")
        monkeypatch.delenv(registry.FAMILY_ENV, raising=False)
        # geometry validation: the tiny-geometry file against the
        # name-detected sd15 config must FAIL LOUDLY, not mis-load
        with pytest.raises(KeyError):
            get_op("UNETLoader").execute(octx, "unet_rt.safetensors")
        registry.clear_pipeline_cache()
        loaded = registry.load_unet("unet_rt.safetensors",
                                    models_dir=str(tmp_path),
                                    family_name="tiny")
        import jax
        x = jnp.zeros((1, 8, 8, 4))
        ts = jnp.zeros((1,))
        c = jnp.zeros((1, 77, pipe.family.unet.context_dim))
        a = pipe.unet.apply({"params": pipe.unet_params}, x, ts, c)
        b = loaded.unet.apply({"params": loaded.unet_params}, x, ts, c)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)
        registry.clear_pipeline_cache()


class TestSDXLRefinerFamily:
    def test_detection_geometry_and_prefix(self, monkeypatch):
        monkeypatch.delenv(registry.FAMILY_ENV, raising=False)
        assert registry.detect_family("sd_xl_refiner_1.0.safetensors") \
            == "sdxl_refiner"
        assert registry.detect_family("sd_xl_base_1.0.safetensors") \
            == "sdxl"
        fam = registry.FAMILIES["sdxl_refiner"]
        assert fam.unet.model_channels == 384
        assert fam.unet.transformer_depth == (0, 4, 4, 0)
        assert fam.unet.transformer_depth_middle == 4
        assert fam.unet.context_dim == 1280
        assert fam.unet.adm_in_channels == 2560
        assert len(fam.clips) == 1
        assert fam.clips[0].layout == "openclip"
        from comfyui_distributed_tpu.models.checkpoints import \
            _clip_prefixes
        assert _clip_prefixes(fam) == ["conditioner.embedders.0.model."]

    def test_refiner_shaped_unet_forward_and_key_walk(self):
        """A scaled-down refiner geometry (edge levels without attention
        + an explicit middle depth) must forward AND round-trip through
        the converter's key walk (missing/extra keys fail loudly)."""
        import dataclasses as dc

        import jax
        from comfyui_distributed_tpu.models.checkpoints import (
            _ExportMapper, _LoadMapper, _run_unet)
        from comfyui_distributed_tpu.models.unet import (UNet, UNetConfig,
                                                         mid_depth)
        cfg = UNetConfig(model_channels=16, channel_mult=(1, 2, 4, 4),
                         num_res_blocks=1,
                         transformer_depth=(0, 1, 1, 0),
                         transformer_depth_middle=2,
                         context_dim=32, num_head_channels=8,
                         adm_in_channels=48,
                         use_linear_in_transformer=True,
                         dtype=jnp.float32)
        assert mid_depth(cfg) == 2
        model = UNet(cfg)
        x = jnp.zeros((1, 16, 16, 4))
        ts = jnp.zeros((1,))
        c = jnp.zeros((1, 7, 32))
        y = jnp.zeros((1, 48))
        params = model.init(jax.random.PRNGKey(0), x, ts, c, y=y)["params"]
        out = model.apply({"params": params}, x, ts, c, y=y)
        assert out.shape == x.shape
        sd = _run_unet(_ExportMapper(params, ""), cfg)
        # the middle transformer carries BOTH depth blocks in the export
        assert any("middle_block.1.transformer_blocks.1." in k
                   for k in sd)
        back = _run_unet(_LoadMapper(sd, ""), cfg)
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_refiner_ascore_reaches_full_width_adm(self):
        """The 5th scalar (aesthetic_score) lands in the 2560-wide
        refiner ADM vector: different scores give different vectors."""
        from comfyui_distributed_tpu.ops.base import Conditioning
        from comfyui_distributed_tpu.ops.basic import _sdxl_vector_cond

        class _U:
            adm_in_channels = 2560

        class _F:
            unet = _U()

        class _P:
            family = _F()

        pooled = np.full((1, 1280), 0.2, np.float32)
        vecs = {}
        for score in (2.0, 9.0):
            vecs[score] = np.asarray(_sdxl_vector_cond(
                _P(), Conditioning(context=None, pooled=pooled,
                                   size_cond=(64, 64, 0, 0, score)),
                1, 64, 64))
        assert vecs[2.0].shape == (1, 2560)
        assert not np.allclose(vecs[2.0], vecs[9.0])

    def test_refiner_size_cond_steers_sampling(self):
        """CLIPTextEncodeSDXLRefiner's scalar conditioning reaches the
        UNet end-to-end: different size scalars give different samples
        (tiny_sdxl stand-in — its 128-wide ADM carries the pooled + the
        first scalar's embedding)."""
        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("ref-asc.ckpt",
                                   family_name="tiny_sdxl")
        octx = OpContext()
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        outs = {}
        for height in (32, 640):
            (cond,) = get_op("CLIPTextEncodeSDXLRefiner").execute(
                octx, p, 6.0, 64, height, "crisp photo")
            assert cond.size_cond == (height, 64, 0, 0, 6.0)
            (out,) = get_op("KSampler").execute(
                octx, p, 3, 2, 3.0, "euler", "normal", cond, cond, lat,
                1.0)
            outs[height] = np.asarray(out["samples"])
        assert np.isfinite(outs[32]).all()
        assert not np.allclose(outs[32], outs[640])
        registry.clear_pipeline_cache()
