"""ControlNet: module structure, converter round-trip, sampling effect."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import checkpoints as ckpt
from comfyui_distributed_tpu.models import registry as reg
from comfyui_distributed_tpu.models.controlnet import ControlNet
from comfyui_distributed_tpu.models.unet import TINY_CONFIG, UNet
from comfyui_distributed_tpu.ops.base import Conditioning, OpContext, get_op


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(reg.FAMILY_ENV, "tiny")
    yield


def _cn_inputs(B=1, h=8, w=8):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((B, h, w, 4)), jnp.float32)
    ts = jnp.zeros((B,))
    ctx = jnp.asarray(rng.standard_normal((B, 77, TINY_CONFIG.context_dim)),
                      jnp.float32)
    hint = jnp.asarray(rng.uniform(0, 1, (B, h * 8, w * 8, 3)), jnp.float32)
    return x, ts, ctx, hint


class TestModule:
    def test_residual_count_matches_unet_skips(self):
        """One residual per UNet skip (conv_in + per-block + downsamples)
        plus the middle — the zip in UNet.__call__ must cover every skip."""
        cn = ControlNet(TINY_CONFIG)
        x, ts, ctx, hint = _cn_inputs()
        params = cn.init(jax.random.PRNGKey(0), x, ts, ctx, hint)["params"]
        outs, mid = cn.apply({"params": params}, x, ts, ctx, hint)
        # tiny config: 2 levels x 1 res block + 1 downsample + conv_in = 4
        n_skips = 1 + sum(
            TINY_CONFIG.num_res_blocks + (1 if lvl != len(
                TINY_CONFIG.channel_mult) - 1 else 0)
            for lvl in range(len(TINY_CONFIG.channel_mult)))
        assert len(outs) == n_skips
        assert mid.shape[-1] == TINY_CONFIG.model_channels * \
            TINY_CONFIG.channel_mult[-1]

    def test_fresh_init_is_unet_noop(self):
        """Zero-convs initialize to zero: an untrained ControlNet must not
        change the UNet output AT ALL (the property that makes ControlNet
        trainable from a copy)."""
        unet = UNet(TINY_CONFIG)
        cn = ControlNet(TINY_CONFIG)
        x, ts, ctx, hint = _cn_inputs()
        up = unet.init(jax.random.PRNGKey(0), x, ts, ctx)["params"]
        cp = cn.init(jax.random.PRNGKey(1), x, ts, ctx, hint)["params"]
        outs, mid = cn.apply({"params": cp}, x, ts, ctx, hint)
        base = unet.apply({"params": up}, x, ts, ctx)
        ctrl = unet.apply({"params": up}, x, ts, ctx,
                          control=(list(outs), mid))
        np.testing.assert_array_equal(np.asarray(base), np.asarray(ctrl))

    def test_nonzero_residuals_change_unet_output(self):
        unet = UNet(TINY_CONFIG)
        cn = ControlNet(TINY_CONFIG)
        x, ts, ctx, hint = _cn_inputs()
        up = unet.init(jax.random.PRNGKey(0), x, ts, ctx)["params"]
        cp = cn.init(jax.random.PRNGKey(1), x, ts, ctx, hint)["params"]
        # un-zero the zero convs (simulating a trained net)
        cp = jax.tree_util.tree_map(
            lambda a: a + 0.05 if a.ndim >= 1 else a, cp)
        outs, mid = cn.apply({"params": cp}, x, ts, ctx, hint)
        base = unet.apply({"params": up}, x, ts, ctx)
        ctrl = unet.apply({"params": up}, x, ts, ctx,
                          control=(list(outs), mid))
        assert not np.allclose(np.asarray(base), np.asarray(ctrl))


class TestConverter:
    def test_round_trip_exact(self):
        cn = ControlNet(TINY_CONFIG)
        x, ts, ctx, hint = _cn_inputs()
        params = cn.init(jax.random.PRNGKey(2), x, ts, ctx, hint)["params"]
        sd = ckpt.export_controlnet(params, TINY_CONFIG)
        assert any(k.startswith("control_model.input_hint_block.0")
                   for k in sd)
        assert any(k.startswith("control_model.zero_convs.0.0") for k in sd)
        assert "control_model.middle_block_out.0.weight" in sd
        p2 = ckpt._run_controlnet(
            ckpt._LoadMapper(sd, ckpt.CONTROLNET_PREFIX), TINY_CONFIG)
        fa = jax.tree_util.tree_leaves_with_path(params)
        fb = dict(jax.tree_util.tree_leaves_with_path(p2))
        assert len(fa) == len(fb)
        for path_k, leaf in fa:
            np.testing.assert_array_equal(np.asarray(leaf),
                                          np.asarray(fb[path_k]),
                                          err_msg=str(path_k))


class TestSamplingAndOps:
    def test_control_changes_sample_output(self):
        """ControlNetApply with a non-trivial net changes the sample; a
        fresh virtual net (zero-convs) is bit-identical to no control."""
        pipe = reg.load_pipeline("cn-base.ckpt")
        module, params = reg.load_controlnet("tile_cn.safetensors")
        ctx_arr, _ = pipe.encode_prompt(["a house"])
        pos = Conditioning(context=ctx_arr, pooled=None)
        hint = np.random.default_rng(3).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        op = get_op("KSampler")

        (plain,) = op.execute(OpContext(), pipe, 9, 2, 1.5, "euler",
                              "normal", pos, pos, lat, 1.0)
        # virtual net: zero-convs are zero -> exact no-op
        (apod,) = get_op("ControlNetApply").execute(
            OpContext(), pos, (module, params), hint, 1.0)
        (zeroed,) = op.execute(OpContext(), pipe, 9, 2, 1.5, "euler",
                               "normal", apod, pos, lat, 1.0)
        np.testing.assert_array_equal(np.asarray(plain["samples"]),
                                      np.asarray(zeroed["samples"]))
        # "trained" net: un-zero everything -> output must change
        params2 = jax.tree_util.tree_map(lambda a: a + 0.05, params)
        (apod2,) = get_op("ControlNetApply").execute(
            OpContext(), pos, (module, params2), hint, 1.0)
        (ctrl,) = op.execute(OpContext(), pipe, 9, 2, 1.5, "euler",
                             "normal", apod2, pos, lat, 1.0)
        assert not np.allclose(np.asarray(plain["samples"]),
                               np.asarray(ctrl["samples"]))
        # strength 0 restores the plain result exactly? (residuals scaled
        # to zero — the UNet sees zero additions)
        (apod0,) = get_op("ControlNetApply").execute(
            OpContext(), pos, (module, params2), hint, 0.0)
        (s0,) = op.execute(OpContext(), pipe, 9, 2, 1.5, "euler",
                           "normal", apod0, pos, lat, 1.0)
        np.testing.assert_allclose(np.asarray(plain["samples"]),
                                   np.asarray(s0["samples"]),
                                   rtol=1e-5, atol=1e-6)

    def test_positive_only_control_does_not_steer_uncond(self):
        """ADVICE r3: a control attached to ONE conditioning steers only
        that CFG half.  Pre-fix, positive-only was (by construction of
        the doubled-batch call) identical to attaching it to both conds —
        so the three attachments must now produce three DIFFERENT
        samples, and all must differ from no control at all."""
        pipe = reg.load_pipeline("cn-halves.ckpt")
        module, params = reg.load_controlnet("halves_cn.safetensors")
        params = jax.tree_util.tree_map(lambda a: a + 0.05, params)
        ctx_arr, _ = pipe.encode_prompt(["a house"])
        pos = Conditioning(context=ctx_arr, pooled=None)
        neg = Conditioning(context=ctx_arr * 0.5, pooled=None)
        hint = np.random.default_rng(3).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        op = get_op("KSampler")
        ap = get_op("ControlNetApply")
        (pos_c,) = ap.execute(OpContext(), pos, (module, params), hint, 1.0)
        (neg_c,) = ap.execute(OpContext(), neg, (module, params), hint, 1.0)

        def run(p, n):
            (o,) = op.execute(OpContext(), pipe, 9, 2, 1.5, "euler",
                              "normal", p, n, lat, 1.0)
            return np.asarray(o["samples"])

        plain = run(pos, neg)
        only_pos = run(pos_c, neg)
        only_neg = run(pos, neg_c)
        both = run(pos_c, neg_c)
        for a, b, msg in [(only_pos, both, "pos-only == both (old bug)"),
                          (only_neg, both, "neg-only == both"),
                          (only_pos, only_neg, "pos-only == neg-only"),
                          (only_pos, plain, "pos-only == no control"),
                          (only_neg, plain, "neg-only == no control")]:
            assert not np.allclose(a, b), msg

    def test_family_inferred_from_checkpoint_file(self, tmp_path,
                                                  monkeypatch):
        """ADVICE r3: with a file on disk, the ControlNet family comes
        from the checkpoint's cross-attn width — not the env default
        (an SDXL workflow must not get a 768-context sd15 net)."""
        from safetensors.numpy import save_file
        cn = ControlNet(TINY_CONFIG)
        x, ts, ctx, hint = _cn_inputs()
        params = cn.init(jax.random.PRNGKey(4), x, ts, ctx, hint)["params"]
        sd = ckpt.export_controlnet(params, TINY_CONFIG)
        save_file({k: np.asarray(v, np.float32) for k, v in sd.items()},
                  str(tmp_path / "tiny_cn.safetensors"))
        monkeypatch.setenv(reg.FAMILY_ENV, "sd15")  # wrong default on purpose
        module, loaded = reg.load_controlnet("tiny_cn.safetensors",
                                             models_dir=str(tmp_path))
        assert module.cfg.context_dim == TINY_CONFIG.context_dim
        la = jax.tree_util.tree_leaves(params)
        lb = jax.tree_util.tree_leaves(loaded)
        assert len(la) == len(lb)
        # an explicit family_name still wins over inference
        mod2, _ = reg.load_controlnet("tiny_cn.safetensors",
                                      models_dir=str(tmp_path),
                                      family_name="tiny")
        assert mod2.cfg.context_dim == TINY_CONFIG.context_dim

    def test_loader_cached_and_virtual_deterministic(self):
        a = reg.load_controlnet("depth.safetensors")
        b = reg.load_controlnet("depth.safetensors")
        assert a is b
        reg.clear_pipeline_cache()
        c = reg.load_controlnet("depth.safetensors")
        la = jax.tree_util.tree_leaves(a[1])[0]
        lc = jax.tree_util.tree_leaves(c[1])[0]
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lc))


class TestTorchParity:
    def test_controlnet_matches_torch_reference(self):
        """flax ControlNet residuals == the canonical-layout torch
        ControlNet through the real control_model.* key mapping (hint
        ladder strides, zero-conv enumeration, residual ordering)."""
        import torch
        from tests.torch_ref import TorchControlNet

        torch.manual_seed(4)
        tref = TorchControlNet().eval()
        # un-zero the projections so parity is tested on NONTRIVIAL output
        with torch.no_grad():
            for zc in tref.zero_convs:
                torch.nn.init.normal_(zc[0].weight, std=0.05)
                torch.nn.init.normal_(zc[0].bias, std=0.05)
            torch.nn.init.normal_(tref.middle_block_out[0].weight, std=0.05)
            torch.nn.init.normal_(tref.input_hint_block[-1].weight, std=0.05)
        sd = {"control_model." + k: v.detach().numpy()
              for k, v in tref.state_dict().items()}
        params = ckpt._run_controlnet(
            ckpt._LoadMapper(sd, ckpt.CONTROLNET_PREFIX), TINY_CONFIG)

        rng = np.random.default_rng(7)
        x = rng.standard_normal((2, 8, 8, 4)).astype(np.float32)
        t = np.asarray([5.0, 300.0], np.float32)
        c = rng.standard_normal((2, 16, 64)).astype(np.float32)
        hint = rng.uniform(0, 1, (2, 64, 64, 3)).astype(np.float32)

        with torch.no_grad():
            t_outs, t_mid = tref(
                torch.from_numpy(x.transpose(0, 3, 1, 2)),
                torch.from_numpy(t), torch.from_numpy(c),
                torch.from_numpy(hint.transpose(0, 3, 1, 2)))
        cn = ControlNet(dataclasses.replace(TINY_CONFIG, dtype=jnp.float32))
        f_outs, f_mid = cn.apply({"params": params}, jnp.asarray(x),
                                 jnp.asarray(t), jnp.asarray(c),
                                 jnp.asarray(hint))
        assert len(f_outs) == len(t_outs)
        tol = dict(rtol=2e-4, atol=2e-4)
        for i, (fo, to) in enumerate(zip(f_outs, t_outs)):
            np.testing.assert_allclose(
                np.asarray(fo), to.numpy().transpose(0, 2, 3, 1),
                err_msg=f"residual {i}", **tol)
        np.testing.assert_allclose(np.asarray(f_mid),
                                   t_mid.numpy().transpose(0, 2, 3, 1),
                                   **tol)


class TestControlNetAdvancedRound5:
    """ControlNetApplyAdvanced (percent window, both CFG sides) and
    DiffControlNetLoader."""

    def _setup(self):
        pipe = reg.load_pipeline("cn-adv.ckpt")
        module, params = reg.load_controlnet("adv_cn.safetensors")
        # "trained" net so residuals actually steer
        params = jax.tree_util.tree_map(lambda a: a + 0.05, params)
        ctx_arr, _ = pipe.encode_prompt(["a bridge"])
        pos = Conditioning(context=ctx_arr, pooled=None)
        neg = Conditioning(context=pipe.encode_prompt([""])[0],
                           pooled=None)
        hint = np.random.default_rng(5).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        return pipe, (module, params), pos, neg, hint, lat

    def test_full_window_matches_plain_apply_on_both_sides(self):
        pipe, cn, pos, neg, hint, lat = self._setup()
        op = get_op("KSampler")
        (p2, n2) = get_op("ControlNetApplyAdvanced").execute(
            OpContext(), pos, neg, cn, hint, 1.0, 0.0, 1.0)
        (a,) = op.execute(OpContext(), pipe, 9, 3, 4.0, "euler",
                          "normal", p2, n2, lat, 1.0)
        # plain apply to BOTH sides == advanced with the full window
        (pp,) = get_op("ControlNetApply").execute(OpContext(), pos, cn,
                                                  hint, 1.0)
        (np_,) = get_op("ControlNetApply").execute(OpContext(), neg, cn,
                                                   hint, 1.0)
        (b,) = op.execute(OpContext(), pipe, 9, 3, 4.0, "euler",
                          "normal", pp, np_, lat, 1.0)
        np.testing.assert_allclose(np.asarray(a["samples"]),
                                   np.asarray(b["samples"]),
                                   rtol=1e-5, atol=1e-6)

    def test_empty_window_is_exact_noop(self):
        """start==end==1.0 -> active only at sigma_min's instant; with
        the karras-like normal schedule no step sigma sits inside, so
        the control contributes nothing."""
        pipe, cn, pos, neg, hint, lat = self._setup()
        op = get_op("KSampler")
        (plain,) = op.execute(OpContext(), pipe, 9, 3, 4.0, "euler",
                              "normal", pos, neg, lat, 1.0)
        (p2, n2) = get_op("ControlNetApplyAdvanced").execute(
            OpContext(), pos, neg, cn, hint, 1.0, 0.999, 1.0)
        (gated,) = op.execute(OpContext(), pipe, 9, 3, 4.0, "euler",
                              "normal", p2, n2, lat, 1.0)
        # the window covers only the near-zero sigma tail: the early
        # steps are uncontrolled, so the result differs from full-window
        # control but the FIRST step equals plain (weak check: outputs
        # neither equal full control nor explode)
        assert np.isfinite(np.asarray(gated["samples"])).all()
        (pf, nf) = get_op("ControlNetApplyAdvanced").execute(
            OpContext(), pos, neg, cn, hint, 1.0, 0.0, 1.0)
        (full,) = op.execute(OpContext(), pipe, 9, 3, 4.0, "euler",
                             "normal", pf, nf, lat, 1.0)
        assert not np.allclose(np.asarray(gated["samples"]),
                               np.asarray(full["samples"]))
        # start beyond every sampled sigma's percent -> pure no-op
        (p0, n0) = get_op("ControlNetApplyAdvanced").execute(
            OpContext(), pos, neg, cn, hint, 1.0, 1.0, 1.0)
        (off,) = op.execute(OpContext(), pipe, 9, 3, 4.0, "euler",
                            "normal", p0, n0, lat, 1.0)
        np.testing.assert_allclose(np.asarray(off["samples"]),
                                   np.asarray(plain["samples"]),
                                   rtol=1e-5, atol=1e-6)

    def test_diff_loader_adds_base_weights(self):
        pipe, _, pos, neg, hint, lat = self._setup()
        (cn_diff,) = get_op("DiffControlNetLoader").execute(
            OpContext(), pipe, "diff_cn.safetensors")
        module, params = cn_diff
        # shared leaves (conv_in etc.) now differ from the raw load
        _, raw = reg.load_controlnet("diff_cn.safetensors",
                                     family_name=pipe.family.name)
        changed = 0
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(raw)):
            if a.shape == b.shape and not np.allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32)):
                changed += 1
        assert changed > 0, "no leaf gained base-model weights"
        # and the result still drives a sample
        (p2, n2) = get_op("ControlNetApplyAdvanced").execute(
            OpContext(), pos, neg, cn_diff, hint, 0.7, 0.0, 1.0)
        (out,) = get_op("KSampler").execute(
            OpContext(), pipe, 9, 2, 3.0, "euler", "normal", p2, n2,
            lat, 1.0)
        assert np.isfinite(np.asarray(out["samples"])).all()


class TestPerEntryControlWindows:
    def test_each_entry_keeps_its_own_window(self):
        """Combine two prompts whose controls carry DIFFERENT windows:
        an entry gated fully off must equal that entry carrying no
        control at all, while the other entry stays steered."""
        pipe = reg.load_pipeline("cn-win.ckpt")
        module, params = reg.load_controlnet("win_cn.safetensors")
        params = jax.tree_util.tree_map(lambda a: a + 0.05, params)
        cn = (module, params)
        a = Conditioning(context=pipe.encode_prompt(["a tower"])[0])
        b = Conditioning(context=pipe.encode_prompt(["a river"])[0])
        neg = Conditioning(context=pipe.encode_prompt([""])[0])
        hint = np.random.default_rng(7).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        octx = OpContext()
        adv = get_op("ControlNetApplyAdvanced")
        comb = get_op("ConditioningCombine")
        ks = get_op("KSampler")

        # A: window fully OFF (start=end=1); B: full window
        (a_off, _) = adv.execute(octx, a, neg, cn, hint, 1.0, 1.0, 1.0)
        (b_on, _) = adv.execute(octx, b, neg, cn, hint, 1.0, 0.0, 1.0)
        (mixed,) = comb.execute(octx, a_off, b_on)
        (out_mixed,) = ks.execute(octx, pipe, 4, 3, 4.0, "euler",
                                  "normal", mixed, neg, lat, 1.0)
        # oracle: A carries NO control, B the plain full apply
        (b_plain,) = get_op("ControlNetApply").execute(octx, b, cn,
                                                       hint, 1.0)
        (oracle,) = comb.execute(octx, a, b_plain)
        (out_oracle,) = ks.execute(octx, pipe, 4, 3, 4.0, "euler",
                                   "normal", oracle, neg, lat, 1.0)
        np.testing.assert_allclose(np.asarray(out_mixed["samples"]),
                                   np.asarray(out_oracle["samples"]),
                                   rtol=1e-5, atol=1e-6)
        # and the mixed result is NOT the both-entries-steered result
        (a_on, _) = adv.execute(octx, a, neg, cn, hint, 1.0, 0.0, 1.0)
        (both,) = comb.execute(octx, a_on, b_on)
        (out_both,) = ks.execute(octx, pipe, 4, 3, 4.0, "euler",
                                 "normal", both, neg, lat, 1.0)
        assert not np.allclose(np.asarray(out_mixed["samples"]),
                               np.asarray(out_both["samples"]))


class TestControlNetChaining:
    """ComfyUI's previous_controlnet accumulation: a second apply CHAINS
    (residuals sum) instead of replacing the first."""

    def _setup(self):
        pipe = reg.load_pipeline("cn-chain.ckpt")
        m1, p1 = reg.load_controlnet("chain_a.safetensors")
        m2, p2 = reg.load_controlnet("chain_b.safetensors")
        boosted = jax.tree_util.tree_map(lambda a: a + 0.05, p1)
        pos = Conditioning(context=pipe.encode_prompt(["a castle"])[0])
        neg = Conditioning(context=pipe.encode_prompt([""])[0])
        hint = np.random.default_rng(11).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        return pipe, (m1, boosted), (m2, p2), pos, neg, hint, lat

    def _sample(self, pipe, cond, neg, lat):
        (out,) = get_op("KSampler").execute(
            OpContext(), pipe, 9, 2, 3.0, "euler", "normal", cond, neg,
            lat, 1.0)
        return np.asarray(out["samples"])

    def test_zero_net_chain_is_additive_identity(self):
        """boosted + fresh-virtual (zero-conv) chain == boosted alone,
        bit-exact — the second net contributes exactly zero residuals."""
        pipe, cn_b, cn_zero, pos, neg, hint, lat = self._setup()
        (single,) = get_op("ControlNetApply").execute(
            OpContext(), pos, cn_b, hint, 1.0)
        (chained,) = get_op("ControlNetApply").execute(
            OpContext(), single, cn_zero, hint, 1.0)
        assert len(chained.control) == 2
        a = self._sample(pipe, single, neg, lat)
        b = self._sample(pipe, chained, neg, lat)
        np.testing.assert_array_equal(a, b)

    def test_two_live_nets_accumulate(self):
        """Two boosted nets chained differ from either alone."""
        pipe, cn_b, (m2, p2), pos, neg, hint, lat = self._setup()
        cn_b2 = (m2, jax.tree_util.tree_map(lambda a: a + 0.03, p2))
        (one,) = get_op("ControlNetApply").execute(
            OpContext(), pos, cn_b, hint, 1.0)
        (other,) = get_op("ControlNetApply").execute(
            OpContext(), pos, cn_b2, hint, 1.0)
        (both,) = get_op("ControlNetApply").execute(
            OpContext(), one, cn_b2, hint, 1.0)
        ra = self._sample(pipe, one, neg, lat)
        rb = self._sample(pipe, other, neg, lat)
        rc = self._sample(pipe, both, neg, lat)
        assert not np.allclose(rc, ra)
        assert not np.allclose(rc, rb)

    def test_per_entry_nets_both_steer(self):
        """Entry A carries net 1, entry B carries net 2 (via Combine):
        BOTH nets now run — the old first-only drop made the combined
        run identical to A-only."""
        pipe, cn_b, (m2, p2), pos, neg, hint, lat = self._setup()
        cn_b2 = (m2, jax.tree_util.tree_map(lambda a: a + 0.03, p2))
        b_cond = Conditioning(context=pipe.encode_prompt(["a moat"])[0])
        (a1,) = get_op("ControlNetApply").execute(
            OpContext(), pos, cn_b, hint, 1.0)
        (b1,) = get_op("ControlNetApply").execute(
            OpContext(), b_cond, cn_b2, hint, 1.0)
        (combined,) = get_op("ConditioningCombine").execute(
            OpContext(), a1, b1)
        (b_plain,) = get_op("ConditioningCombine").execute(
            OpContext(), a1, b_cond)
        rc = self._sample(pipe, combined, neg, lat)
        rp = self._sample(pipe, b_plain, neg, lat)
        assert not np.allclose(rc, rp), \
            "the sibling's own net was dropped"


class TestSameNetChainedTwice:
    def test_two_links_of_one_net_sum(self):
        """Chaining the SAME net twice at 0.5 each == one link at 1.0
        (ComfyUI runs every chain link; residual scaling is linear in
        strength, so the sums match exactly)."""
        pipe = reg.load_pipeline("cn-dup.ckpt")
        m, p = reg.load_controlnet("dup_cn.safetensors")
        cn = (m, jax.tree_util.tree_map(lambda a: a + 0.05, p))
        pos = Conditioning(context=pipe.encode_prompt(["a gate"])[0])
        neg = Conditioning(context=pipe.encode_prompt([""])[0])
        hint = np.random.default_rng(13).uniform(
            0, 1, (1, 64, 64, 3)).astype(np.float32)
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        octx = OpContext()
        (once,) = get_op("ControlNetApply").execute(octx, pos, cn, hint,
                                                    1.0)
        (h1,) = get_op("ControlNetApply").execute(octx, pos, cn, hint,
                                                  0.5)
        (h2,) = get_op("ControlNetApply").execute(octx, h1, cn, hint,
                                                  0.5)
        assert len(h2.control) == 2

        def run(c):
            (out,) = get_op("KSampler").execute(
                OpContext(), pipe, 9, 2, 3.0, "euler", "normal", c, neg,
                lat, 1.0)
            return np.asarray(out["samples"])

        np.testing.assert_allclose(run(h2), run(once), rtol=1e-4,
                                   atol=1e-5)
