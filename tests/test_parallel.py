"""Mesh runtime + collectives on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from comfyui_distributed_tpu.parallel import collectives as coll
from comfyui_distributed_tpu.parallel import mesh as mesh_mod
from comfyui_distributed_tpu.utils.constants import DATA_AXIS, SEQ_AXIS, TENSOR_AXIS


@pytest.fixture
def mesh8():
    return mesh_mod.build_mesh({DATA_AXIS: -1})


class TestMesh:
    def test_eight_fake_devices(self):
        assert jax.device_count() == 8

    def test_default_all_data(self, mesh8):
        assert mesh8.shape[DATA_AXIS] == 8
        assert mesh8.shape[TENSOR_AXIS] == 1

    def test_axes_resolution(self):
        m = mesh_mod.build_mesh({DATA_AXIS: 2, TENSOR_AXIS: 2, SEQ_AXIS: 2})
        assert dict(m.shape) == {DATA_AXIS: 2, TENSOR_AXIS: 2, SEQ_AXIS: 2}

    def test_fill_axis(self):
        m = mesh_mod.build_mesh({DATA_AXIS: -1, TENSOR_AXIS: 4})
        assert m.shape[DATA_AXIS] == 2

    def test_bad_product_raises(self):
        with pytest.raises(ValueError):
            mesh_mod.build_mesh({DATA_AXIS: 3})
        with pytest.raises(ValueError):
            mesh_mod.build_mesh({DATA_AXIS: -1, TENSOR_AXIS: -1})

    def test_describe_devices(self):
        d = mesh_mod.describe_devices()
        assert d["num_devices"] == 8
        assert d["platform"] == "cpu"
        assert len(d["devices"]) == 8

    def test_runtime_status(self):
        rt = mesh_mod.MeshRuntime(mesh=mesh_mod.build_mesh())
        st = rt.status()
        assert st["num_participants"] == 8
        rt.enabled = False
        assert rt.num_participants == 1

    def test_runtime_singleton(self):
        mesh_mod.set_runtime(None)
        a = mesh_mod.get_runtime()
        assert mesh_mod.get_runtime() is a
        mesh_mod.set_runtime(None)


class TestSeeds:
    def test_replica_seeds_master_first(self):
        s = coll.replica_seeds(100, 4, batch_per_replica=2)
        # replica-major: master(100,100), worker1(101,101)...
        assert s.tolist() == [100, 100, 101, 101, 102, 102, 103, 103]

    def test_parity_with_reference_offsets(self):
        # reference: master = seed, worker i = seed + i + 1
        s = coll.replica_seeds(7, 3, 1)
        master, w0, w1 = s.tolist()
        assert master == 7 and w0 == 7 + 0 + 1 and w1 == 7 + 1 + 1

    def test_sample_keys_distinct(self):
        seeds = jnp.asarray(coll.replica_seeds(5, 2, 3))
        keys = coll.sample_keys(seeds)
        flat = np.asarray(keys).reshape(keys.shape[0], -1)
        assert len({tuple(k) for k in flat}) == 6  # all distinct streams

    def test_sample_keys_deterministic(self):
        seeds = jnp.asarray(coll.replica_seeds(5, 2, 2))
        k1, k2 = coll.sample_keys(seeds), coll.sample_keys(seeds)
        assert np.array_equal(np.asarray(k1), np.asarray(k2))


class TestCollectives:
    def test_shard_gather_round_trip(self, mesh8, rng):
        x = rng.standard_normal((16, 4, 4, 3)).astype(np.float32)
        sharded = coll.shard_batch(x, mesh8)
        assert sharded.sharding.spec == P(DATA_AXIS)
        back = coll.gather_batch(sharded)
        assert np.array_equal(back, x)  # ordering preserved exactly

    def test_all_gather_replicates(self, mesh8, rng):
        x = rng.standard_normal((8, 4)).astype(np.float32)
        sharded = coll.shard_batch(x, mesh8)
        full = coll.all_gather_data(sharded, mesh8)
        assert full.shape == (8, 4)
        assert np.allclose(coll.gather_batch(full), x)

    def test_psum_data(self, mesh8):
        x = np.ones((8, 3), dtype=np.float32)
        out = coll.psum_data(coll.shard_batch(x, mesh8), mesh8)
        assert np.allclose(coll.gather_batch(out), 8.0)

    def test_pad_to_multiple(self):
        assert coll.pad_to_multiple(0, 8) == 0
        assert coll.pad_to_multiple(1, 8) == 8
        assert coll.pad_to_multiple(8, 8) == 8
        assert coll.pad_to_multiple(17, 8) == 24

    def test_sharded_compute_end_to_end(self, mesh8, rng):
        """A jitted elementwise op on a sharded batch keeps its sharding and
        produces the same numbers as host numpy."""
        x = rng.standard_normal((16, 8)).astype(np.float32)
        sharded = coll.shard_batch(x, mesh8)
        f = jax.jit(lambda a: jnp.tanh(a) * 2.0)
        out = f(sharded)
        assert np.allclose(coll.gather_batch(out), np.tanh(x) * 2.0, atol=1e-6)


class TestBackendEscapeLadder:
    """ensure_usable_backend (VERDICT r3 #7): the serve/bench startup must
    survive a wedged accelerator client with bounded patience, escape via
    an alternate JAX_PLATFORMS config when one works, and fall back to CPU
    loudly as the last resort.  Probes are mocked — no real backend is
    touched (and sleeps are compressed via patience)."""

    def _run(self, monkeypatch, probe, **kw):
        monkeypatch.setattr(mesh_mod.time, "sleep", lambda s: None)
        return mesh_mod.ensure_usable_backend(force=True, _probe=probe, **kw)

    def test_env_config_ok_first_try(self, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        calls = []

        def probe(platforms, timeout):
            calls.append(platforms)
            return True, {"platform": "tpu", "kind": "v5e", "count": 1}

        rep = self._run(monkeypatch, probe, patience_s=10)
        assert rep["ok"] and rep["config"] == "env" and not rep["fell_back"]
        assert calls == [None]

    def test_escape_via_alternate_config(self, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        applied = []
        monkeypatch.setattr(mesh_mod, "_apply_platforms",
                            lambda v: applied.append(v))

        def probe(platforms, timeout):
            if platforms is None:      # env config: wedged
                return False, "probe hung >10s"
            if platforms == "tpu":     # direct PJRT path comes up
                return True, {"platform": "tpu", "kind": "v5e", "count": 1}
            return False, "no backend"

        rep = self._run(monkeypatch, probe, patience_s=30)
        assert rep["ok"] and rep["config"] == "tpu"
        assert applied == ["tpu"]
        # every rung's result is in the report (artifact material)
        assert [a["config"] for a in rep["attempts"]] == ["env", "auto",
                                                          "tpu"]

    def test_cpu_only_alternate_is_not_an_escape(self, monkeypatch):
        """An alternate that initializes CPU-only means it dodged the
        accelerator, not that it escaped the wedge — only the explicit
        fallback may select CPU."""
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        forced = []
        monkeypatch.setattr(mesh_mod, "force_cpu_platform",
                            lambda n: forced.append(n))

        def probe(platforms, timeout):
            if platforms is None:
                return False, "probe hung >10s"
            return True, {"platform": "cpu", "kind": "cpu", "count": 1}

        rep = self._run(monkeypatch, probe, patience_s=5)
        assert rep["ok"] and rep["config"] == "cpu" and rep["fell_back"]
        assert forced == [1]

    def test_no_fallback_reports_failure(self, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "axon")

        def probe(platforms, timeout):
            return False, "probe hung >10s"

        rep = self._run(monkeypatch, probe, patience_s=5,
                        allow_cpu_fallback=False)
        assert not rep["ok"] and rep["config"] is None
        assert len(rep["attempts"]) >= 3   # env + both alternates tried

    def test_cpu_env_short_circuits(self, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        called = []

        def probe(platforms, timeout):
            called.append(platforms)
            return True, {}

        rep = self._run(monkeypatch, probe)
        assert rep["skipped"] and rep["config"] == "cpu"
        assert called == []

    def test_env_cpu_only_success_is_fallback_not_escape(self, monkeypatch):
        """A fast-crash flake leaves the env probe initializing CPU-only:
        with fallback allowed take CPU immediately (and say so); it must
        never be reported as an accelerator success."""
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        forced = []
        monkeypatch.setattr(mesh_mod, "force_cpu_platform",
                            lambda n: forced.append(n))

        def probe(platforms, timeout):
            return True, {"platform": "cpu", "kind": "cpu", "count": 1}

        rep = self._run(monkeypatch, probe, patience_s=5)
        assert rep["ok"] and rep["config"] == "cpu" and rep["fell_back"]
        assert forced == [1]

    def test_env_cpu_only_without_fallback_fails(self, monkeypatch):
        """bench (no-fallback): a CPU-only init must NOT produce a number
        on the accelerator metric — it reports failure instead."""
        monkeypatch.setenv("JAX_PLATFORMS", "axon")

        def probe(platforms, timeout):
            return True, {"platform": "cpu", "kind": "cpu", "count": 1}

        rep = self._run(monkeypatch, probe, patience_s=5,
                        allow_cpu_fallback=False)
        assert not rep["ok"] and rep["config"] is None

    def test_hung_config_not_reprobed_without_claim_window(self,
                                                           monkeypatch):
        """Mid-claim-kill policy: after a config HANGS (its probe child
        was killed, likely mid-claim), it is re-probed only when the
        remaining budget lets a retry resolve naturally — short killed
        retries of the same wedged path just re-wedge the lease."""
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("DTPU_CLAIM_WINDOW_S", "1000")

        def probe(platforms, timeout):
            return False, "probe hung >10s"

        rep = self._run(monkeypatch, probe, patience_s=500,
                        allow_cpu_fallback=False)
        # one shot per config, no blind retries inside the window
        assert [a["config"] for a in rep["attempts"]] == ["env", "auto",
                                                          "tpu"]

    def test_fast_failing_config_stays_retryable(self, monkeypatch):
        """A config that fails FAST exited on its own (no kill): retries
        are free and detect chip recovery between rounds."""
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("DTPU_CLAIM_WINDOW_S", "1000")
        n = {"env": 0}

        def probe(platforms, timeout):
            if platforms is None:
                n["env"] += 1
                if n["env"] >= 3:     # chip comes back on the 3rd round
                    return True, {"platform": "tpu", "kind": "v5e",
                                  "count": 1}
                return False, "rc=1: UNAVAILABLE"
            return False, "rc=1: no backend"

        rep = self._run(monkeypatch, probe, patience_s=100000)
        assert rep["ok"] and rep["config"] == "env"
        assert n["env"] == 3


class TestServingTensorParallel:
    """VERDICT r4 §2.3: tp must reach SERVING, not just the train step —
    a tensor-axis mesh lays the UNet params out via params_shardings and
    the sampled result must match the replicated-weights oracle."""

    @pytest.mark.xfail(
        strict=False,
        reason="upstream XLA CPU SPMD concat miscompile (JAX 0.4.37) — "
               "the serving oracle below is green because the repo's "
               "layout pins route around it; when this XPASSes (fixed "
               "jax) the pins become optional, not wrong")
    def test_upstream_sharded_concat_miscompile(self):
        """The MINIMAL repro behind the oracle mismatch (ROADMAP
        tp-concat-cpu-miscompile): on the CPU backend, jit-compiling
        ``concat([x @ w_col_sharded, x], -1)`` with ``w`` column-sharded
        over a tensor axis returns wrong values in BOTH halves of the
        concat (JAX 0.4.37); a replicate with_sharding_constraint before
        the concat restores exactness.  Kept as xfail(strict=False): the
        day a jax upgrade fixes it this XPASSes — re-enable the serving
        oracle test then."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        # this jit takes tensor-sharded inputs WITHOUT set_runtime, so
        # mesh._tp_compile_cache_guard never sees it: keep its sharded
        # executables out of the persistent cache by hand (sticky, like
        # the guard — this test is slow-tier, where the TP oracle's
        # set_runtime would disable the cache moments later anyway)
        jax.config.update("jax_enable_compilation_cache", False)
        mesh = mesh_mod.build_mesh(
            {DATA_AXIS: 2, TENSOR_AXIS: 2, SEQ_AXIS: 1},
            devices=jax.devices()[:4])
        w = jax.random.normal(jax.random.PRNGKey(0), (8, 8), jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8), jnp.float32)

        def f(w, x):
            return jnp.concatenate([x @ w, x], axis=-1)

        ref = np.asarray(jax.jit(f)(w, x))
        ws = jax.device_put(w, NamedSharding(mesh, P(None, TENSOR_AXIS)))
        out = np.asarray(jax.jit(f)(ws, x))
        np.testing.assert_allclose(ref, out, rtol=1e-5, atol=1e-5)

    def test_tp_sharded_sample_matches_replicated_oracle(self, monkeypatch):
        """Green since ISSUE 16: the UNet pins the skip concat and the
        CFG row-stack to seam-safe layouts (parallel/sharding.py
        ``constrain_rows``/``stack_rows``) so the upstream XLA CPU SPMD
        concat miscompile (still repro'd above) never sees a sharded
        concat dim, and ``_ensure_tp_sharded`` drops the pipeline's jit
        cache on layout transitions so the constraint gates re-trace
        against the live mesh."""
        monkeypatch.setenv("DTPU_TP_MIN_SHARD_ELEMENTS", "2")
        from comfyui_distributed_tpu.models import registry
        registry.clear_pipeline_cache()
        mesh_mod.set_runtime(None)
        try:
            pipe = registry.load_pipeline("tp-serve.ckpt",
                                          family_name="tiny")
            ctx_c, _ = pipe.encode_prompt(["a lighthouse"])
            ctx_u, _ = pipe.encode_prompt([""])
            lat = jnp.zeros((2, 8, 8, 4), jnp.float32)
            seeds = np.asarray([3, 4], np.uint64)

            def run():
                return np.asarray(pipe.sample(
                    lat, jnp.concatenate([ctx_c] * 2),
                    jnp.concatenate([ctx_u] * 2), seeds, steps=3,
                    cfg=5.0, sampler_name="euler", scheduler="normal"))

            oracle = run()                       # replicated weights
            lat_img = jnp.ones((1, 8, 8, 4), jnp.float32) * 0.3
            dec_oracle = np.asarray(pipe.vae_decode(lat_img))
            assert pipe._tp_mesh is None
            mesh = mesh_mod.build_mesh(
                {DATA_AXIS: 2, TENSOR_AXIS: 2, SEQ_AXIS: 1},
                devices=jax.devices()[:4])
            mesh_mod.set_runtime(mesh_mod.MeshRuntime(mesh=mesh))
            tp = run()                           # tp-laid-out weights
            assert pipe._tp_mesh is mesh
            # CLIP + VAE towers lay out too and stay on-oracle
            dec_tp = np.asarray(pipe.vae_decode(lat_img))
            np.testing.assert_allclose(dec_tp, dec_oracle,
                                       rtol=2e-4, atol=2e-4)
            # some leaves actually sharded over tensor
            sharded = [
                x for x in jax.tree_util.tree_leaves(pipe.unet_params)
                if hasattr(x, "sharding")
                and x.sharding.spec != P()
                and TENSOR_AXIS in str(x.sharding.spec)]
            assert sharded, "no parameter leaf was tensor-sharded"
            np.testing.assert_allclose(tp, oracle, rtol=2e-4, atol=2e-4)
        finally:
            mesh_mod.set_runtime(None)
            registry.clear_pipeline_cache()


class TestDryrunMultichip:
    """The driver's multi-chip artifact runs the PRODUCT paths: sharded
    train step + executor fan-out inference (VERDICT r4 #3), and the
    16-device factorization exercises tensor=4 x seq=4 — axis extents
    > 2 — plus a ragged padded batch (VERDICT r4 #8).  Subprocess: the
    dryrun re-pins the backend device count, which must not disturb
    this process's 8-device mesh."""

    @pytest.mark.parametrize("n", [8, 16])
    def test_dryrun_green(self, n):
        import os
        import subprocess
        import sys
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS",)}
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = "/root/repo" + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        out = subprocess.run(
            [sys.executable, "-c",
             f"from __graft_entry__ import dryrun_multichip; "
             f"dryrun_multichip({n})"],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=540)
        assert out.returncode == 0, out.stderr[-2000:]
        assert f"n={n}" in out.stdout and "inference" in out.stdout
        if n == 16:
            assert "'tensor': 4" in out.stdout and "'seq': 4" in out.stdout
        assert "tp_engaged=True" in out.stdout
