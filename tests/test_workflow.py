"""Workflow engine: parsing the reference JSONs, execution, SPMD fan-out."""

import copy
import json

import jax
import numpy as np
import pytest

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.parallel import mesh as mesh_mod
from comfyui_distributed_tpu.workflow import WorkflowExecutor, parse_workflow

TXT2IMG = "/root/reference/workflows/distributed-txt2img.json"
UPSCALE = "/root/reference/workflows/distributed-upscale.json"


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture
def ctx():
    return OpContext(runtime=mesh_mod.MeshRuntime(mesh=mesh_mod.build_mesh()))


class TestParse:
    def test_txt2img_parses(self):
        g = parse_workflow(TXT2IMG)
        assert len(g.nodes) == 9
        ks = g.nodes["8"]
        assert ks.class_type == "KSampler"
        # widget mapping: [seed, control, steps, cfg, sampler, scheduler, den]
        assert ks.inputs["steps"] == 20
        assert ks.inputs["cfg"] == 6
        assert ks.inputs["sampler_name"] == "euler"
        assert ks.inputs["scheduler"] == "normal"
        assert ks.inputs["denoise"] == 1
        # seed widget overridden by link from DistributedSeed (node 4)
        assert ks.inputs["seed"] == ["4", 0]
        assert g.nodes["9"].inputs["width"] == 512

    def test_upscale_parses(self):
        g = parse_workflow(UPSCALE)
        assert len(g.nodes) == 9
        up = g.nodes["13"]
        assert up.inputs["tile_width"] == 512
        assert up.inputs["padding"] == 32
        assert up.inputs["mask_blur"] == 16
        assert up.inputs["force_uniform_tiles"] is True
        assert abs(up.inputs["denoise"] - 0.24) < 1e-6
        assert up.inputs["upscaled_image"] == ["17", 0]

    def test_topo_order(self):
        g = parse_workflow(TXT2IMG)
        order = g.topo_order()
        assert order.index("7") < order.index("8")   # ckpt before sampler
        assert order.index("8") < order.index("1")   # sampler before decode
        assert order.index("2") < order.index("3")   # collector before preview

    def test_cycle_detection(self):
        g = parse_workflow(json.dumps({
            "a": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["b", 0], "vae": ["b", 1]}},
            "b": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["a", 0], "vae": ["a", 1]}},
        }))
        with pytest.raises(ValueError, match="cycle"):
            g.topo_order()

    def test_bypassed_node_passes_through(self):
        """Mode-4 (bypass) nodes are removed with links rewired through
        type-matching inputs — ComfyUI bypass semantics."""
        doc = json.load(open(TXT2IMG))
        for n in doc["nodes"]:
            if n["type"] == "DistributedCollector":
                n["mode"] = 4
        g = parse_workflow(doc)
        assert "2" not in g.nodes
        # PreviewImage (3) now feeds directly from VAEDecode (1)
        assert g.nodes["3"].inputs["images"] == ["1", 0]

    def test_muted_node_drops_link(self):
        doc = json.load(open(TXT2IMG))
        for n in doc["nodes"]:
            if n["type"] == "DistributedSeed":
                n["mode"] = 2
        g = parse_workflow(doc)
        assert "4" not in g.nodes
        # KSampler keeps its widget seed; the dead link is dropped
        assert isinstance(g.nodes["8"].inputs["seed"], int)

    def test_api_format_round_trip(self):
        g = parse_workflow(TXT2IMG)
        api = g.to_api_format()
        g2 = parse_workflow(json.dumps(api))
        assert set(g2.nodes) == set(g.nodes)
        assert g2.nodes["8"].inputs["steps"] == 20


def _scaled_txt2img(width=64, height=64, steps=2, batch=1):
    """Reference txt2img graph with sizes/steps scaled for CPU tests."""
    g = parse_workflow(TXT2IMG)
    g.nodes["9"].inputs.update(width=width, height=height, batch_size=batch)
    g.nodes["8"].inputs.update(steps=steps)
    return g


class TestTxt2ImgE2E:
    def test_fanout_produces_replica_batch(self, ctx):
        res = WorkflowExecutor(ctx).execute(_scaled_txt2img())
        # 8 mesh slots x batch 1, collected master-first
        assert len(res.images) == 8
        imgs = np.stack(res.images)
        assert imgs.shape == (8, 16, 16, 3)  # tiny VAE upscales latent x2
        # distributed seed => every replica's image differs
        for i in range(1, 8):
            assert not np.allclose(imgs[0], imgs[i]), f"replica {i} == master"

    def test_determinism(self, ctx):
        r1 = WorkflowExecutor(ctx).execute(_scaled_txt2img())
        ctx2 = OpContext(runtime=ctx.runtime)
        r2 = WorkflowExecutor(ctx2).execute(_scaled_txt2img())
        assert np.allclose(np.stack(r1.images), np.stack(r2.images))

    def test_plain_seed_replicates_identically(self, ctx):
        """Without DistributedSeed all participants produce the same images
        (reference parity: seed fan-out is what makes replicas differ)."""
        g = _scaled_txt2img()
        g.nodes["8"].inputs["seed"] = 1234  # break link, plain int
        res = WorkflowExecutor(ctx).execute(g)
        imgs = np.stack(res.images)
        assert imgs.shape[0] == 8
        for i in range(1, 8):
            assert np.allclose(imgs[0], imgs[i], atol=1e-5)

    def test_worker_mode_no_fanout(self):
        """Worker processes run the graph without batch expansion."""
        ctx = OpContext(runtime=mesh_mod.MeshRuntime(mesh=mesh_mod.build_mesh()),
                        is_worker=True, worker_id="worker_2")
        res = WorkflowExecutor(ctx).execute(_scaled_txt2img())
        assert len(res.images) == 1

    def test_timings_recorded(self, ctx):
        res = WorkflowExecutor(ctx).execute(_scaled_txt2img())
        assert set(res.timings) == set(parse_workflow(TXT2IMG).nodes)
        assert res.total_s > 0


IMG2IMG = "/root/repo/workflows/distributed-img2img.json"


def _scaled_img2img(size=32, steps=2):
    """The img2img variation-sweep fixture scaled for CPU tests."""
    g = parse_workflow(IMG2IMG)
    g.nodes["1"].inputs["image"] = "__missing__.png"    # synthetic test card
    g.nodes["2"].inputs.update(width=size, height=size)
    g.nodes["3"].inputs.update(steps=steps)
    return g


class TestImg2ImgE2E:
    """BASELINE config 4: seed-offset fan-out over one VAE-encoded source
    (every participant denoises the same latent with its own seed)."""

    def test_variation_sweep_fans_out(self, ctx):
        res = WorkflowExecutor(ctx).execute(_scaled_img2img())
        assert len(res.images) == 8
        imgs = np.stack(res.images)
        assert imgs.shape == (8, 32, 32, 3)
        # same source latent + distributed seed => variations, not copies
        for i in range(1, 8):
            assert not np.allclose(imgs[0], imgs[i]), \
                f"variation {i} identical to master"

    def test_plain_seed_gives_identical_variations(self, ctx):
        g = _scaled_img2img()
        g.nodes["3"].inputs["seed"] = 77  # break link, plain int
        res = WorkflowExecutor(ctx).execute(g)
        imgs = np.stack(res.images)
        assert imgs.shape[0] == 8
        for i in range(1, 8):
            assert np.allclose(imgs[0], imgs[i], atol=1e-5)

    def test_worker_mode_single_variation(self):
        ctx = OpContext(runtime=mesh_mod.MeshRuntime(mesh=mesh_mod.build_mesh()),
                        is_worker=True, worker_id="worker_1")
        res = WorkflowExecutor(ctx).execute(_scaled_img2img())
        assert len(res.images) == 1

    def test_side_branch_not_fanned_out(self, ctx):
        """A branch with no distributed node runs once even when the graph
        has a distributed component elsewhere (reference parity: workers
        are pruned to the connected component, gpupanel.js:1045-1071).
        The side branch needs its OWN loader — sharing node 4 would merge
        the components via the bidirectional walk, as in the reference."""
        g = _scaled_img2img()
        g2 = parse_workflow(json.dumps({
            "20": {"class_type": "CheckpointLoaderSimple",
                   "inputs": {"ckpt_name": "side.ckpt"}},
            "21": {"class_type": "EmptyLatentImage",
                   "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "22": {"class_type": "CLIPTextEncode",
                   "inputs": {"text": "side", "clip": ["20", 1]}},
            "23": {"class_type": "KSampler",
                   "inputs": {"seed": 5, "steps": 1, "cfg": 1.0,
                              "sampler_name": "euler", "scheduler": "normal",
                              "denoise": 1.0, "model": ["20", 0],
                              "positive": ["22", 0], "negative": ["22", 0],
                              "latent_image": ["21", 0]}},
            "24": {"class_type": "VAEDecode",
                   "inputs": {"samples": ["23", 0], "vae": ["20", 2]}},
            "25": {"class_type": "PreviewImage",
                   "inputs": {"images": ["24", 0]}}}))
        g.nodes.update(g2.nodes)
        res = WorkflowExecutor(ctx).execute(g)
        # 8 fanned variations + exactly 1 side-branch image
        assert len(res.images) == 9

    def test_hires_fix_chain_not_reexpanded(self, ctx):
        """A mid-graph VAEEncode (hires-fix: sample -> decode -> upscale ->
        re-encode -> refine) must NOT tile an already-fanned batch again:
        8 variations stay 8, not 64."""
        g = parse_workflow(json.dumps({
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": "hires.ckpt"}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 16, "height": 16, "batch_size": 1}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "x", "clip": ["4", 1]}},
            "13": {"class_type": "DistributedSeed", "inputs": {"seed": 9}},
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": ["13", 0], "steps": 1, "cfg": 1.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["4", 0],
                             "positive": ["6", 0], "negative": ["6", 0],
                             "latent_image": ["5", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
            "16": {"class_type": "UpscaleModelLoader",
                   "inputs": {"model_name": "2x_hires.pth"}},
            "17": {"class_type": "ImageUpscaleWithModel",
                   "inputs": {"upscale_model": ["16", 0],
                              "image": ["8", 0]}},
            "10": {"class_type": "ImageScale",
                   "inputs": {"image": ["17", 0],
                              "upscale_method": "lanczos",
                              "width": 32, "height": 32,
                              "crop": "disabled"}},
            "11": {"class_type": "VAEEncode",
                   "inputs": {"pixels": ["10", 0], "vae": ["4", 2]}},
            "12": {"class_type": "KSampler",
                   "inputs": {"seed": ["13", 0], "steps": 1, "cfg": 1.0,
                              "sampler_name": "euler", "scheduler": "normal",
                              "denoise": 0.5, "model": ["4", 0],
                              "positive": ["6", 0], "negative": ["6", 0],
                              "latent_image": ["11", 0]}},
            "15": {"class_type": "VAEDecode",
                   "inputs": {"samples": ["12", 0], "vae": ["4", 2]}},
            "14": {"class_type": "DistributedCollector",
                   "inputs": {"images": ["15", 0]}},
            "9": {"class_type": "PreviewImage",
                  "inputs": {"images": ["14", 0]}}}))
        res = WorkflowExecutor(ctx).execute(g)
        imgs = np.stack(res.images)
        assert imgs.shape == (8, 32, 32, 3), imgs.shape
        # refined variations still differ per replica
        assert not np.allclose(imgs[0], imgs[1])

    def test_denoise_below_one_preserves_source_structure(self, ctx):
        """img2img at low denoise stays closer to the source than a fresh
        txt2img sample from the same seed would — the encoded latent must
        actually be the starting point (add_noise on top of source)."""
        g = _scaled_img2img()
        g.nodes["3"].inputs["denoise"] = 0.1
        res_low = WorkflowExecutor(ctx).execute(g)
        g2 = _scaled_img2img()
        g2.nodes["3"].inputs["denoise"] = 1.0
        res_full = WorkflowExecutor(ctx).execute(g2)
        # the source card is a smooth gradient; at denoise 0.1 the output
        # must correlate with it far more than the fully-resampled one
        from comfyui_distributed_tpu.ops.base import get_op
        card = get_op("LoadImage").execute(OpContext(), "__missing__.png")[0]
        card = get_op("ImageScale").execute(
            OpContext(), card, "lanczos", 32, 32)[0][0]

        def err(r):
            return float(np.mean(np.abs(np.stack(r.images) - card[None])))

        assert err(res_low) < err(res_full)


HIRES = "/root/repo/workflows/distributed-hires-fix.json"


class TestHiresFixE2E:
    """The staged hires-fix fixture: LoraLoader -> CLIPSetLastLayer ->
    KSamplerAdvanced (leftover noise) -> LatentUpscale -> KSamplerAdvanced
    finish, fanned over the mesh."""

    def test_hires_fix_fans_out(self, ctx):
        g = parse_workflow(HIRES)
        # scale for CPU: tiny latents, 1+1 steps (LatentUpscale divides
        # pixel widgets by 8, ComfyUI convention)
        g.nodes["5"].inputs.update(width=32, height=32)
        g.nodes["3"].inputs.update(steps=2, end_at_step=1)
        g.nodes["10"].inputs.update(width=64, height=64)
        g.nodes["11"].inputs.update(steps=2, start_at_step=1)
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 8
        imgs = np.stack(res.images)
        # tiny VAE: 8x8 latent (64//8) -> 16px image at downscale 2
        assert imgs.shape == (8, 16, 16, 3)
        for i in range(1, 8):
            assert not np.allclose(imgs[0], imgs[i]), \
                f"variation {i} identical to master"

    def test_latent_upscale_preserves_fanout_meta(self, ctx):
        from comfyui_distributed_tpu.ops.base import get_op
        lat = {"samples": np.zeros((8, 8, 8, 4), np.float32),
               "local_batch": 1, "fanout": 8}
        (out,) = get_op("LatentUpscale").execute(
            ctx, lat, "nearest-exact", 128, 128)
        assert out["samples"].shape == (8, 16, 16, 4)
        assert out["fanout"] == 8 and out["local_batch"] == 1
        (out2,) = get_op("LatentUpscaleBy").execute(ctx, lat, "bilinear",
                                                    1.5)
        assert out2["samples"].shape == (8, 12, 12, 4)
        assert out2["fanout"] == 8

    def test_latent_upscale_rectangular_and_zero_dims(self, ctx):
        """Non-square targets (argument-order tripwire), width/height=0
        aspect-derivation, 0/0 passthrough, and center crop — ComfyUI's
        LatentUpscale conventions."""
        from comfyui_distributed_tpu.ops.base import get_op
        op = get_op("LatentUpscale")
        lat = {"samples": np.zeros((1, 8, 16, 4), np.float32)}  # H=8, W=16
        (r,) = op.execute(ctx, lat, "bilinear", 256, 64)   # W=32, H=8
        assert r["samples"].shape == (1, 8, 32, 4)
        (r,) = op.execute(ctx, lat, "bilinear", 0, 128)    # H=16, W by AR
        assert r["samples"].shape == (1, 16, 32, 4)
        (r,) = op.execute(ctx, lat, "bilinear", 128, 0)    # W=16, H by AR
        assert r["samples"].shape == (1, 8, 16, 4)
        (r,) = op.execute(ctx, lat, "bilinear", 0, 0)      # passthrough
        assert r["samples"].shape == (1, 8, 16, 4)
        # center crop: 2:1 latent -> square target without distortion
        (r,) = op.execute(ctx, lat, "bilinear", 128, 128, "center")
        assert r["samples"].shape == (1, 16, 16, 4)

    def test_latent_upscale_by_rectangular(self, ctx):
        from comfyui_distributed_tpu.ops.base import get_op
        lat = {"samples": np.zeros((1, 8, 16, 4), np.float32)}
        (r,) = get_op("LatentUpscaleBy").execute(ctx, lat, "bilinear", 2.0)
        assert r["samples"].shape == (1, 16, 32, 4)
        img = np.zeros((1, 8, 16, 3), np.float32)
        (ri,) = get_op("ImageScaleBy").execute(ctx, img, "bilinear", 2.0)
        assert ri.shape == (1, 16, 32, 3)

    def test_image_scale_by_preserves_fanout_meta(self, ctx):
        from comfyui_distributed_tpu.ops.base import get_op
        from comfyui_distributed_tpu.ops.basic import ImageBatch
        img = ImageBatch(np.zeros((8, 16, 16, 3), np.float32),
                         local_batch=1, fanout=8)
        (out,) = get_op("ImageScaleBy").execute(ctx, img, "bilinear", 2.0)
        assert out.shape == (8, 32, 32, 3)
        assert out.fanout == 8


INPAINT = "/root/repo/workflows/distributed-inpaint.json"
OUTPAINT = "/root/repo/workflows/distributed-outpaint.json"


class TestInpaintE2E:
    def test_inpaint_fixture_fans_out_masked_variations(self, ctx,
                                                        tmp_path):
        """The inpaint fixture over the mesh: every participant resamples
        the masked region with its own seed.  (The unmasked LATENT is
        anchored exactly — covered by test_models.TestInpainting; decoded
        pixels are NOT asserted stable because the VAE decoder's global
        mid-block attention mixes every latent into every pixel.)"""
        from PIL import Image
        # source card with an alpha channel: alpha=0 right half -> mask=1
        rgba = np.zeros((32, 32, 4), np.uint8)
        rgba[..., :3] = 128
        rgba[..., 3] = 255
        rgba[:, 16:, 3] = 0                    # LoadImage: mask = 1-alpha
        (tmp_path / "in").mkdir()
        Image.fromarray(rgba).save(tmp_path / "in" / "card.png")
        ctx.input_dir = str(tmp_path / "in")

        g = parse_workflow(INPAINT)
        g.nodes["1"].inputs["image"] = "card.png"
        g.nodes["2"].inputs.update(width=32, height=32)
        g.nodes["5"].inputs.update(grow_mask_by=0)
        g.nodes["3"].inputs.update(steps=2)
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 8
        imgs = np.stack(res.images)
        # masked halves differ across replicas (seed fan-out).  NOTE: the
        # unmasked LATENT region is anchored exactly (unit-tested in
        # test_models.TestInpainting); pixel-exact stability does not
        # survive VAE decode because the decoder's mid-block attention is
        # global — every output pixel attends to every latent (true of
        # the torch stack as well)
        for i in range(1, 8):
            assert not np.allclose(imgs[0][:, 16:], imgs[i][:, 16:]), \
                f"variation {i} masked region identical to master"
        assert np.isfinite(imgs).all()


    def test_outpaint_fixture_extends_and_fans_out(self, ctx, tmp_path):
        """The outpaint fixture: pad-right canvas extension, feathered
        mask into VAEEncodeForInpaint, seed fan-out of the new area."""
        from PIL import Image
        rgb = np.full((32, 32, 3), 64, np.uint8)
        (tmp_path / "in").mkdir()
        Image.fromarray(rgb).save(tmp_path / "in" / "src.png")
        ctx.input_dir = str(tmp_path / "in")

        g = parse_workflow(OUTPAINT)
        g.nodes["1"].inputs["image"] = "src.png"
        g.nodes["2"].inputs.update(width=32, height=32)
        g.nodes["10"].inputs.update(right=16, feathering=4)
        g.nodes["5"].inputs.update(grow_mask_by=0)
        g.nodes["3"].inputs.update(steps=2)
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 8
        imgs = np.stack(res.images)
        assert imgs.shape[1:] == (32, 48, 3)   # canvas extended right
        assert np.isfinite(imgs).all()
        # the outpainted right side varies across replicas (seed fan-out)
        for i in range(1, 8):
            assert not np.allclose(imgs[0][:, 32:], imgs[i][:, 32:]), i

    def test_batch_gt1_mask_fans_out(self, ctx):
        """ADVICE r3 (medium): a batch>1 noise_mask must fan out with the
        latents — pre-fix only B=1 worked (by broadcasting) and B>1
        crashed with a shape error inside the jitted sampler."""
        from comfyui_distributed_tpu.ops.base import Conditioning, get_op
        pipe = registry.load_pipeline("maskfan.ckpt")
        ctx_arr, _ = pipe.encode_prompt(["x"])
        pos = Conditioning(context=ctx_arr, pooled=None)
        fanout = ctx.fanout = len(jax.devices())
        assert fanout > 1
        b = 2
        lat = np.tile(np.zeros((b, 8, 8, 4), np.float32),
                      (fanout, 1, 1, 1))
        mask = np.zeros((b, 64, 64), np.float32)
        mask[:, :, 32:] = 1.0                    # resample the right half
        latent = {"samples": lat, "local_batch": b, "fanout": fanout,
                  "noise_mask": mask}
        (out,) = get_op("KSampler").execute(ctx, pipe, 7, 2, 1.5, "euler",
                                            "normal", pos, pos, latent, 1.0)
        s = np.asarray(out["samples"])
        assert s.shape[0] == b * fanout
        assert np.isfinite(s).all()
        # unmasked (left) half anchored exactly to the zero source...
        np.testing.assert_array_equal(s[:, :, :4, :],
                                      np.zeros_like(s[:, :, :4, :]))
        # ...masked half resampled
        assert not np.allclose(s[:, :, 4:, :], 0.0)


def _scaled_upscale(tile=32, padding=8, blur=2, steps=1):
    g = parse_workflow(UPSCALE)
    g.nodes["12"].inputs["image"] = "__missing__.png"   # synthetic test card
    g.nodes["17"].inputs.update(width=64, height=64)
    g.nodes["13"].inputs.update(steps=steps, tile_width=tile,
                                tile_height=tile, padding=padding,
                                mask_blur=blur)
    return g


class TestUpscaleE2E:
    def test_distributed_tiled_upscale(self, ctx):
        res = WorkflowExecutor(ctx).execute(_scaled_upscale())
        assert len(res.images) == 1
        out = res.images[0]
        assert out.shape == (64, 64, 3)
        assert np.isfinite(out).all()

    def test_spmd_matches_single_device_oracle(self, ctx):
        """Golden test (SURVEY.md §4): the distributed path must match the
        single-device path — same per-tile seeds, same blend order."""
        res_d = WorkflowExecutor(ctx).execute(_scaled_upscale())
        ctx_s = OpContext(runtime=ctx.runtime)
        ctx_s.runtime.enabled = False  # num_participants -> 1
        try:
            res_s = WorkflowExecutor(ctx_s).execute(_scaled_upscale())
        finally:
            ctx.runtime.enabled = True
        np.testing.assert_allclose(res_d.images[0], res_s.images[0],
                                   atol=2e-3)


class TestRegionalTiledUpscale:
    """VERDICT r4 #4: regional conditioning entries refine with their
    masks cropped through the tile windows (instead of the loud
    primary-prompt fallback)."""

    def _regional_conds(self, pipe):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        octx = OpContext()
        a = Conditioning(context=pipe.encode_prompt(["blue sky"])[0])
        b = Conditioning(context=pipe.encode_prompt(["green forest"])[0])
        left = np.zeros((64, 64), np.float32)
        left[:, :32] = 1.0
        (am,) = get_op("ConditioningSetMask").execute(octx, a, left, 1.0)
        (bm,) = get_op("ConditioningSetMask").execute(octx, b,
                                                      1.0 - left, 1.0)
        (combined,) = get_op("ConditioningCombine").execute(octx, am, bm)
        neg = Conditioning(context=pipe.encode_prompt([""])[0])
        return combined, neg

    def _upscale(self, ctx, pipe, positive, negative):
        from comfyui_distributed_tpu.ops.base import get_op
        rng = np.random.default_rng(3)
        img = rng.random((1, 64, 64, 3)).astype(np.float32)
        (out,) = get_op("UltimateSDUpscaleDistributed").execute(
            ctx, img, pipe, positive, negative, pipe, 5, 1, 4.0,
            "euler", "normal", 0.4, 32, 32, 8, 2, True)
        return np.asarray(out)

    def test_regional_spmd_matches_single_device_oracle(self, ctx):
        pipe = registry.load_pipeline("regup.ckpt")
        pos, neg = self._regional_conds(pipe)
        out_d = self._upscale(ctx, pipe, pos, neg)
        ctx_s = OpContext(runtime=ctx.runtime)
        ctx_s.runtime.enabled = False
        try:
            out_s = self._upscale(ctx_s, pipe, pos, neg)
        finally:
            ctx.runtime.enabled = True
        assert np.isfinite(out_d).all()
        np.testing.assert_allclose(out_d, out_s, atol=2e-3)

    def test_regional_masks_engage(self, ctx):
        """The cropped masks must actually reach the sampler: the
        regional result differs from refining with the primary prompt
        alone (the old fallback behavior)."""
        from comfyui_distributed_tpu.ops.base import Conditioning
        pipe = registry.load_pipeline("regup.ckpt")
        pos, neg = self._regional_conds(pipe)
        out_r = self._upscale(ctx, pipe, pos, neg)
        primary = Conditioning(context=pipe.encode_prompt(["blue sky"])[0])
        out_p = self._upscale(ctx, pipe, primary, neg)
        assert not np.allclose(out_r, out_p, atol=1e-4)


class TestRepoFixtures:
    """The repo's own workflow fixtures (same node-type surface as the
    reference's two workflows) parse and execute end-to-end on the virtual
    mesh with tiny virtual checkpoints."""

    def _ctx(self, tmp_path, monkeypatch):
        import os
        monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny")
        from comfyui_distributed_tpu.models import registry
        registry.clear_pipeline_cache()
        from comfyui_distributed_tpu.ops.base import OpContext
        from comfyui_distributed_tpu.parallel.mesh import MeshRuntime, build_mesh
        rt = MeshRuntime(mesh=build_mesh({"data": 2, "tensor": 1, "seq": 1},
                                         devices=jax.devices()[:2]))
        os.makedirs(tmp_path / "input", exist_ok=True)
        return OpContext(runtime=rt, input_dir=str(tmp_path / "input"),
                         output_dir=str(tmp_path / "out"))

    def test_txt2img_fixture(self, tmp_path, monkeypatch):
        from comfyui_distributed_tpu.workflow import WorkflowExecutor, parse_workflow
        g = parse_workflow("/root/repo/workflows/distributed-txt2img.json")
        g.nodes["5"].inputs.update(width=64, height=64, batch_size=1)
        g.nodes["3"].inputs.update(steps=2)
        res = WorkflowExecutor(self._ctx(tmp_path, monkeypatch)).execute(g)
        assert len(res.images) == 2  # fan-out x2 over the data axis
        # EmptyLatentImage uses the ComfyUI /8 contract; the tiny family's
        # VAE only upsamples x2, so 64px request -> 8px latent -> 16px image
        assert res.images[0].shape == (16, 16, 3)

    def test_upscale_fixture(self, tmp_path, monkeypatch):
        import numpy as np
        from PIL import Image
        ctx = self._ctx(tmp_path, monkeypatch)
        Image.fromarray(
            (np.random.default_rng(0).random((64, 64, 3)) * 255
             ).astype("uint8")).save(f"{ctx.input_dir}/input.png")
        from comfyui_distributed_tpu.workflow import WorkflowExecutor, parse_workflow
        g = parse_workflow("/root/repo/workflows/distributed-upscale.json")
        g.nodes["16"].inputs.update(width=128, height=128)
        g.nodes["2"].inputs.update(steps=1, tile_width=64, tile_height=64,
                                   padding=8, mask_blur=2)
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 1
        assert res.images[0].shape == (128, 128, 3)


class TestRegionalE2E:
    def test_regional_fixture_fans_out(self, ctx):
        """The regional fixture: two prompts on canvas halves, combined,
        seed-fanned; replicas differ, output finite."""
        g = parse_workflow("/root/repo/workflows/distributed-regional.json")
        g.nodes["2"].inputs.update(width=32, height=32)
        g.nodes["3"].inputs.update(steps=2)
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 8
        imgs = np.stack(res.images)
        assert np.isfinite(imgs).all()
        for i in range(1, 8):
            assert not np.allclose(imgs[0], imgs[i]), i


class TestCustomSamplerWidgetBinding:
    def test_sampler_custom_ui_widgets_skip_control_slot(self):
        """ComfyUI UI exports serialize seed widgets with a trailing
        control_after_generate; SamplerCustom/RandomNoise must declare
        the CONTROL slot so cfg doesn't receive 'randomize'."""
        from comfyui_distributed_tpu.workflow.graph import \
            _widgets_to_inputs
        got = _widgets_to_inputs("SamplerCustom",
                                 [True, 5, "randomize", 4.5])
        assert got["add_noise"] is True
        assert got["noise_seed"] == 5
        assert got["cfg"] == 4.5
        assert "control_after_generate" not in got
        got = _widgets_to_inputs("RandomNoise", [7, "fixed"])
        assert got["noise_seed"] == 7


class TestMaskCompositeNodes:
    """SolidMask / InvertMask / GrowMask / MaskComposite / Image* /
    Latent* composite family (ComfyUI mask toolchain)."""

    def _op(self, name):
        from comfyui_distributed_tpu.ops.base import get_op
        return get_op(name)

    def _ctx(self):
        from comfyui_distributed_tpu.ops.base import OpContext
        return OpContext()

    def test_solid_invert_grow(self):
        octx = self._ctx()
        (m,) = self._op("SolidMask").execute(octx, 0.25, 8, 6)
        assert m.shape == (1, 6, 8) and np.all(m == 0.25)
        (inv,) = self._op("InvertMask").execute(octx, m)
        assert np.allclose(inv, 0.75)
        point = np.zeros((1, 7, 7), np.float32)
        point[0, 3, 3] = 1.0
        (grown,) = self._op("GrowMask").execute(octx, point, 1, True)
        assert grown[0, 3, 3] == 1 and grown[0, 2, 3] == 1
        assert grown[0, 2, 2] == 0          # tapered: no corners
        (grown2,) = self._op("GrowMask").execute(octx, point, 1, False)
        assert grown2[0, 2, 2] == 1         # full 3x3
        (shrunk,) = self._op("GrowMask").execute(octx, grown, -1, True)
        np.testing.assert_array_equal(shrunk, point)

    def test_mask_composite_ops(self):
        octx = self._ctx()
        d = np.ones((1, 4, 4), np.float32)
        s = np.full((1, 2, 2), 1.0, np.float32)
        (sub,) = self._op("MaskComposite").execute(octx, d, s, 1, 1,
                                                   "subtract")
        assert sub[0, 1, 1] == 0.0 and sub[0, 0, 0] == 1.0
        (xor,) = self._op("MaskComposite").execute(octx, d, s, 0, 0,
                                                   "xor")
        assert xor[0, 0, 0] == 0.0 and xor[0, 3, 3] == 1.0
        with pytest.raises(ValueError):
            self._op("MaskComposite").execute(octx, d, s, 0, 0, "nope")

    def test_empty_image_and_crop_and_batch(self):
        octx = self._ctx()
        (img,) = self._op("EmptyImage").execute(octx, 8, 4, 2, 0xFF0000)
        assert img.shape == (2, 4, 8, 3)
        assert np.allclose(img[..., 0], 1.0) and np.allclose(img[..., 1:],
                                                             0.0)
        (crop,) = self._op("ImageCrop").execute(octx, img, 4, 2, 2, 1)
        assert crop.shape == (2, 2, 4, 3)
        (inv,) = self._op("ImageInvert").execute(octx, img)
        assert np.allclose(inv[..., 0], 0.0)
        small = np.zeros((1, 2, 4, 3), np.float32)
        (batch,) = self._op("ImageBatch").execute(octx, img, small)
        assert batch.shape == (3, 4, 8, 3)

    def test_image_composite_masked(self):
        octx = self._ctx()
        dest = np.zeros((1, 4, 4, 3), np.float32)
        src = np.ones((1, 2, 2, 3), np.float32)
        (out,) = self._op("ImageCompositeMasked").execute(
            octx, dest, src, 1, 1, False, None)
        assert out[0, 1, 1, 0] == 1.0 and out[0, 0, 0, 0] == 0.0
        mask = np.zeros((1, 2, 2), np.float32)
        mask[0, 0, 0] = 1.0
        (mout,) = self._op("ImageCompositeMasked").execute(
            octx, dest, src, 1, 1, False, mask)
        assert mout[0, 1, 1, 0] == 1.0 and mout[0, 2, 2, 0] == 0.0
        # negative offset crops the source, no wraparound
        (neg,) = self._op("ImageCompositeMasked").execute(
            octx, dest, src, -1, -1, False, None)
        assert neg[0, 0, 0, 0] == 1.0 and neg[0, 1, 1, 0] == 0.0
        assert neg[0, 3, 3, 0] == 0.0

    def test_latent_composites_preserve_meta(self):
        octx = self._ctx()
        to = {"samples": np.zeros((2, 8, 8, 4), np.float32),
              "fanout": 2, "local_batch": 1}
        frm = {"samples": np.ones((1, 4, 4, 4), np.float32)}
        (out,) = self._op("LatentComposite").execute(octx, to, frm,
                                                     16, 16, 0)
        assert out["fanout"] == 2 and out["local_batch"] == 1
        s = out["samples"]
        assert s[0, 2, 2, 0] == 1.0 and s[0, 1, 1, 0] == 0.0
        assert s[1, 2, 2, 0] == 1.0          # short batch cycles
        (fe,) = self._op("LatentComposite").execute(octx, to, frm,
                                                    16, 16, 16)
        sf = fe["samples"]
        assert 0.0 < sf[0, 2, 2, 0] < 1.0    # feather edge ramp
        # border-flush paste: no ramp on the flush (top/left) edges,
        # ramp only toward interior dest content (ComfyUI edge rule)
        (flush,) = self._op("LatentComposite").execute(octx, to, frm,
                                                       0, 0, 16)
        sfl = flush["samples"]
        assert sfl[0, 0, 0, 0] == 1.0        # flush corner stays solid
        assert 0.0 < sfl[0, 3, 3, 0] < 1.0   # interior edge ramps
        # corner toward interior: rates multiply, not min
        assert np.isclose(sfl[0, 3, 3, 0], 0.25)
        mask = np.ones((1, 4, 4), np.float32)
        mask[0, :, :2] = 0.0
        (lm,) = self._op("LatentCompositeMasked").execute(
            octx, to, frm, 16, 16, False, mask)
        sm = lm["samples"]
        assert sm[0, 2, 2, 0] == 0.0 and sm[0, 2, 5, 0] == 1.0


class TestLatentImageUtilityNodes:
    """Round-4 utility batch: latent transforms, image filters,
    conditioning utils."""

    def _op(self, name):
        from comfyui_distributed_tpu.ops.base import get_op
        return get_op(name)

    def _ctx(self):
        from comfyui_distributed_tpu.ops.base import OpContext
        return OpContext()

    def test_latent_flip_rotate_crop(self):
        octx = self._ctx()
        lat = {"samples": np.arange(2 * 4 * 6 * 1, dtype=np.float32)
               .reshape(2, 4, 6, 1), "fanout": 2, "local_batch": 1}
        (fx,) = self._op("LatentFlip").execute(octx, lat,
                                               "x-axis: vertically")
        np.testing.assert_array_equal(fx["samples"][:, ::-1],
                                      lat["samples"])
        assert fx["fanout"] == 2
        (fy,) = self._op("LatentFlip").execute(octx, lat,
                                               "y-axis: horizontally")
        np.testing.assert_array_equal(fy["samples"][:, :, ::-1],
                                      lat["samples"])
        (r90,) = self._op("LatentRotate").execute(octx, lat, "90 degrees")
        assert r90["samples"].shape == (2, 6, 4, 1)
        (r360s,) = self._op("LatentRotate").execute(
            octx, r90, "270 degrees")
        np.testing.assert_array_equal(r360s["samples"], lat["samples"])
        (cr,) = self._op("LatentCrop").execute(octx, lat, 16, 16, 8, 8)
        assert cr["samples"].shape == (2, 2, 2, 1)
        np.testing.assert_array_equal(cr["samples"],
                                      lat["samples"][:, 1:3, 1:3])

    def test_latent_blend_and_batch(self):
        octx = self._ctx()
        a = {"samples": np.ones((2, 4, 4, 4), np.float32)}
        b = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        (bl,) = self._op("LatentBlend").execute(octx, a, b, 0.25)
        assert bl["samples"].shape == (2, 4, 4, 4)
        np.testing.assert_allclose(bl["samples"], 0.25)
        (bt,) = self._op("LatentBatch").execute(octx, a, b)
        assert bt["samples"].shape == (3, 4, 4, 4)

    def test_conditioning_zero_out_and_strength(self):
        from comfyui_distributed_tpu.ops.base import Conditioning
        octx = self._ctx()
        c = Conditioning(context=np.ones((1, 77, 16), np.float32),
                         pooled=np.ones((1, 32), np.float32))
        (z,) = self._op("ConditioningZeroOut").execute(octx, c)
        assert np.all(np.asarray(z.context) == 0)
        assert np.all(np.asarray(z.pooled) == 0)
        (s,) = self._op("ConditioningSetAreaStrength").execute(octx, c,
                                                               0.4)
        assert s.area_strength == 0.4

    def test_image_blur_sharpen_quantize_scale(self):
        octx = self._ctx()
        rng = np.random.default_rng(2)
        img = rng.uniform(0, 1, (1, 16, 16, 3)).astype(np.float32)
        (bl,) = self._op("ImageBlur").execute(octx, img, 2, 1.5)
        assert bl.shape == img.shape
        assert bl.std() < img.std()          # blur reduces variance
        flat = np.full((1, 8, 8, 3), 0.5, np.float32)
        (blf,) = self._op("ImageBlur").execute(octx, flat, 3, 2.0)
        np.testing.assert_allclose(blf, 0.5, atol=1e-6)  # edge replicate
        (sh,) = self._op("ImageSharpen").execute(octx, img, 2, 1.5, 1.0)
        assert sh.shape == img.shape
        assert sh.std() > bl.std()
        (q,) = self._op("ImageQuantize").execute(octx, img, 4, "none")
        assert q.shape == img.shape
        assert len(np.unique(q.reshape(-1, 3), axis=0)) <= 4
        (sc,) = self._op("ImageScaleToTotalPixels").execute(
            octx, img, "bilinear", 0.001)
        assert abs(sc.shape[1] * sc.shape[2] - 0.001 * 1024 * 1024) \
            < 0.25 * 0.001 * 1024 * 1024


class TestRound4Fixtures:
    """The round-4 feature fixtures execute end-to-end on the virtual
    mesh with tiny virtual checkpoints (same scaling recipe as
    TestRepoFixtures)."""

    def _ctx(self, tmp_path, monkeypatch, family="tiny"):
        import os
        monkeypatch.setenv("DTPU_DEFAULT_FAMILY", family)
        registry.clear_pipeline_cache()
        from comfyui_distributed_tpu.parallel.mesh import (MeshRuntime,
                                                           build_mesh)
        rt = MeshRuntime(mesh=build_mesh(
            {"data": 2, "tensor": 1, "seq": 1},
            devices=jax.devices()[:2]))
        os.makedirs(tmp_path / "input", exist_ok=True)
        return OpContext(runtime=rt, input_dir=str(tmp_path / "input"),
                         output_dir=str(tmp_path / "out"))

    def test_sdxl_dualprompt_fixture(self, tmp_path, monkeypatch):
        from comfyui_distributed_tpu.workflow import (WorkflowExecutor,
                                                      parse_workflow)
        g = parse_workflow("/root/repo/workflows/distributed-sdxl.json")
        g.nodes["2"].inputs.update(width=64, height=64, batch_size=1)
        g.nodes["6"].inputs.update(steps=2)
        # tiny_sdxl: an ADM-bearing family so the dual-prompt size
        # conds actually reach the UNet (plain 'tiny' would skip the
        # whole y path)
        ctx = self._ctx(tmp_path, monkeypatch, family="tiny_sdxl")
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 2
        imgs = np.stack(res.images)
        assert np.isfinite(imgs).all()
        assert not np.allclose(imgs[0], imgs[1])
        # the explicit size conds steer: a different declared size
        # changes the prepared ADM vector (deterministic regression net
        # for size_cond handling — image-level inequality at 2 steps
        # proved order-flaky across the full suite)
        from comfyui_distributed_tpu.ops.base import get_op
        from comfyui_distributed_tpu.ops.basic import \
            _prepare_sample_inputs
        p = registry.load_pipeline("sd_xl_base_1.0.safetensors")
        octx2 = OpContext()
        (c1,) = get_op("CLIPTextEncodeSDXL").execute(
            octx2, p, 1024, 1024, 0, 0, 1024, 1024, "a", "b")
        (c2,) = get_op("CLIPTextEncodeSDXL").execute(
            octx2, p, 256, 256, 0, 0, 256, 256, "a", "b")
        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        # through the SAMPLER prep path (not the helper directly): the
        # size_cond must reach the prepared ADM the KSampler consumes
        y1 = np.asarray(_prepare_sample_inputs(octx2, p, 0, lat, c1,
                                               c1).y)
        y2 = np.asarray(_prepare_sample_inputs(octx2, p, 0, lat, c2,
                                               c2).y)
        assert y1.shape == (1, 128)
        assert not np.allclose(y1, y2)

    def test_inpaint_model_fixture(self, tmp_path, monkeypatch):
        from comfyui_distributed_tpu.workflow import (WorkflowExecutor,
                                                      parse_workflow)
        g = parse_workflow(
            "/root/repo/workflows/distributed-inpaint-model.json")
        g.nodes["8"].inputs.update(steps=2)
        # the synthetic 512px test card would be a 256x256-token latent
        # for the tiny family: rescale the pixel path to 64px
        from comfyui_distributed_tpu.workflow.graph import Node
        g.nodes["2s"] = Node(id="2s", class_type="ImageScale",
                             inputs={"image": ["2", 0],
                                     "upscale_method": "bilinear",
                                     "width": 64, "height": 64,
                                     "crop": "disabled"})
        g.nodes["6"].inputs["pixels"] = ["2s", 0]
        ctx = self._ctx(tmp_path, monkeypatch, family="tiny_inpaint")
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 2
        assert np.isfinite(np.stack(res.images)).all()

    def test_unclip_fixture(self, tmp_path, monkeypatch):
        from comfyui_distributed_tpu.workflow import (WorkflowExecutor,
                                                      parse_workflow)
        g = parse_workflow(
            "/root/repo/workflows/distributed-unclip.json")
        g.nodes["7"].inputs.update(width=64, height=64, batch_size=1)
        g.nodes["9"].inputs.update(steps=2)
        monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny_unclip")
        ctx = self._ctx(tmp_path, monkeypatch, family="tiny_unclip")
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 2
        imgs = np.stack(res.images)
        assert np.isfinite(imgs).all()
        assert not np.allclose(imgs[0], imgs[1])


class TestCannyBatchMorphoNodes:
    def _op(self, name):
        from comfyui_distributed_tpu.ops.base import get_op
        return get_op(name)

    def _ctx(self):
        return OpContext()

    def test_canny_finds_a_box_edge(self):
        octx = self._ctx()
        img = np.zeros((1, 32, 32, 3), np.float32)
        img[:, 8:24, 8:24] = 1.0
        (edges,) = self._op("Canny").execute(octx, img, 0.1, 0.3)
        assert edges.shape == (1, 32, 32, 3)
        assert set(np.unique(edges)) <= {0.0, 1.0}
        # edges ring the box, interior and background stay empty
        assert edges[0, 8, 16, 0] == 1.0 or edges[0, 7, 16, 0] == 1.0
        assert edges[0, 16, 16, 0] == 0.0
        assert edges[0, 2, 2, 0] == 0.0
        # a flat image has no edges
        (none,) = self._op("Canny").execute(
            octx, np.full((1, 16, 16, 3), 0.5, np.float32), 0.1, 0.3)
        assert none.sum() == 0.0

    def test_image_from_batch_and_rebatch(self):
        octx = self._ctx()
        img = np.arange(3 * 4 * 4 * 3, dtype=np.float32) \
            .reshape(3, 4, 4, 3)
        (one,) = self._op("ImageFromBatch").execute(octx, img, 1, 1)
        np.testing.assert_array_equal(one, img[1:2])
        (two,) = self._op("ImageFromBatch").execute(octx, img, 1, 2)
        assert two.shape[0] == 2
        (rb,) = self._op("RebatchImages").execute(octx, img, 2)
        np.testing.assert_array_equal(rb, img)
        lat = {"samples": np.ones((2, 4, 4, 4), np.float32),
               "fanout": 2}
        (rl,) = self._op("RebatchLatents").execute(octx, lat, 1)
        assert rl["fanout"] == 2

    def test_morphology_ops(self):
        octx = self._ctx()
        img = np.zeros((1, 9, 9, 3), np.float32)
        img[:, 4, 4] = 1.0
        (d,) = self._op("Morphology").execute(octx, img, "dilate", 3)
        assert d[0, 3, 3, 0] == 1.0 and d[0, 1, 1, 0] == 0.0
        (e,) = self._op("Morphology").execute(octx, d, "erode", 3)
        np.testing.assert_array_equal(e, img)
        (g,) = self._op("Morphology").execute(octx, img, "gradient", 3)
        # gradient of a point: dilation minus erosion is 1 across the
        # whole dilated neighborhood (erosion of a point is empty)
        assert g[0, 4, 4, 0] == 1.0 and g[0, 3, 4, 0] == 1.0
        assert g[0, 1, 1, 0] == 0.0
        with pytest.raises(ValueError):
            self._op("Morphology").execute(octx, img, "nope", 3)


class TestMaskToolchainCompletion:
    def _op(self, name):
        from comfyui_distributed_tpu.ops.base import get_op
        return get_op(name)

    def test_mask_image_conversions(self):
        octx = OpContext()
        m = np.zeros((1, 4, 4), np.float32)
        m[0, 1, 2] = 0.8
        (img,) = self._op("MaskToImage").execute(octx, m)
        assert img.shape == (1, 4, 4, 3)
        np.testing.assert_array_equal(img[..., 0], m)
        (back,) = self._op("ImageToMask").execute(octx, img, "red")
        np.testing.assert_array_equal(back, m)
        rgb = np.zeros((1, 2, 2, 3), np.float32)
        rgb[0, 0, 1] = [1.0, 0.0, 0.0]
        (cm,) = self._op("ImageColorToMask").execute(octx, rgb,
                                                     0xFF0000)
        assert cm[0, 0, 1] == 1.0 and cm.sum() == 1.0

    def test_crop_feather_threshold(self):
        octx = OpContext()
        m = np.ones((1, 8, 8), np.float32)
        (cr,) = self._op("CropMask").execute(octx, m, 2, 2, 4, 4)
        assert cr.shape == (1, 4, 4)
        (fe,) = self._op("FeatherMask").execute(octx, m, 2, 2, 0, 0)
        # reference rate (t+1)/margin: edge 1/2, inner row reaches 1.0
        assert fe[0, 0, 4] == 0.5 and fe[0, 1, 4] == 1.0
        assert fe[0, 4, 7] == 1.0                 # right untouched
        assert fe[0, 0, 0] == fe[0, 0, 4] * fe[0, 4, 0]  # corners mult
        # margin 1 is a no-op (the reference's semantics)
        (noop,) = self._op("FeatherMask").execute(octx, m, 1, 1, 1, 1)
        np.testing.assert_array_equal(noop, m)
        soft = np.linspace(0, 1, 16, dtype=np.float32).reshape(1, 4, 4)
        (th,) = self._op("ThresholdMask").execute(octx, soft, 0.5)
        assert set(np.unique(th)) <= {0.0, 1.0}
        assert th.sum() == (soft > 0.5).sum()

    def test_style_model_apply(self):
        octx = OpContext()
        from comfyui_distributed_tpu.ops.base import Conditioning
        registry.clear_pipeline_cache()
        (sm,) = self._op("StyleModelLoader").execute(octx,
                                                     "tiny-style.pth")
        vision = registry.load_clip_vision("tiny-style-vision")
        rng = np.random.default_rng(3)
        img = rng.uniform(0, 1, (1, 64, 64, 3)).astype(np.float32)
        (vout,) = self._op("CLIPVisionEncode").execute(octx, vision,
                                                       img, "center")
        c = Conditioning(context=np.zeros((1, 7, 64), np.float32))
        (out,) = self._op("StyleModelApply").execute(octx, c, sm, vout)
        assert out.context.shape == (1, 7 + sm.cfg.num_tokens, 64)
        assert np.isfinite(np.asarray(out.context)).all()
        # style tokens depend on the image
        img2 = rng.uniform(0, 1, (1, 64, 64, 3)).astype(np.float32)
        (vout2,) = self._op("CLIPVisionEncode").execute(octx, vision,
                                                        img2, "center")
        (out2,) = self._op("StyleModelApply").execute(octx, c, sm,
                                                      vout2)
        assert not np.allclose(np.asarray(out.context[:, 7:]),
                               np.asarray(out2.context[:, 7:]))
        registry.clear_pipeline_cache()


class TestCompositingAndSeedBehavior:
    def _op(self, name):
        from comfyui_distributed_tpu.ops.base import get_op
        return get_op(name)

    def test_porter_duff_modes(self):
        octx = OpContext()
        cs = np.full((1, 2, 2, 3), 0.8, np.float32)
        cd = np.full((1, 2, 2, 3), 0.2, np.float32)
        a1 = np.ones((1, 2, 2), np.float32)
        a0 = np.zeros((1, 2, 2), np.float32)
        op = self._op("PorterDuffImageComposite")
        # SRC_OVER with opaque source = source
        c, a = op.execute(octx, cs, a1, cd, a1, "SRC_OVER")
        np.testing.assert_allclose(c, 0.8, atol=1e-6)
        np.testing.assert_allclose(a, 1.0)
        # SRC_OVER with transparent source: the reference feeds
        # STRAIGHT values into the premultiplied formula (its known
        # quirk) -> cs + cd, clipped
        c, a = op.execute(octx, cs, a0, cd, a1, "SRC_OVER")
        np.testing.assert_allclose(c, 1.0, atol=1e-5)
        np.testing.assert_allclose(a, 1.0)
        # DST_IN with opaque source keeps the destination exactly
        c, a = op.execute(octx, cs, a1, cd, a1, "DST_IN")
        np.testing.assert_allclose(c, 0.2, atol=1e-5)
        # SCREEN formula
        c, _ = op.execute(octx, cs, a1, cd, a1, "SCREEN")
        np.testing.assert_allclose(c, 0.8 + 0.2 - 0.16, atol=1e-5)
        # DST ignores the source entirely
        c, a = op.execute(octx, cs, a1, cd, a1, "DST")
        np.testing.assert_allclose(c, 0.2, atol=1e-6)
        # MULTIPLY / ADD / DARKEN / LIGHTEN formulas
        c, _ = op.execute(octx, cs, a1, cd, a1, "MULTIPLY")
        np.testing.assert_allclose(c, 0.16, atol=1e-5)
        c, _ = op.execute(octx, cs, a1, cd, a1, "ADD")
        np.testing.assert_allclose(c, 1.0)
        c, _ = op.execute(octx, cs, a1, cd, a1, "DARKEN")
        np.testing.assert_allclose(c, 0.2, atol=1e-5)
        c, _ = op.execute(octx, cs, a1, cd, a1, "LIGHTEN")
        np.testing.assert_allclose(c, 0.8, atol=1e-5)
        # CLEAR zeroes everything
        c, a = op.execute(octx, cs, a1, cd, a1, "CLEAR")
        assert c.sum() == 0.0 and a.sum() == 0.0
        with pytest.raises(ValueError):
            op.execute(octx, cs, a1, cd, a1, "NOPE")

    def test_alpha_split_join_round_trip(self):
        octx = OpContext()
        rng = np.random.default_rng(4)
        rgba = rng.uniform(0, 1, (1, 4, 4, 4)).astype(np.float32)
        rgb, mask = self._op("SplitImageWithAlpha").execute(octx, rgba)
        np.testing.assert_array_equal(rgb, rgba[..., :3])
        np.testing.assert_allclose(mask, 1.0 - rgba[..., 3])
        (joined,) = self._op("JoinImageWithAlpha").execute(octx, rgb,
                                                           mask)
        np.testing.assert_allclose(joined, rgba, atol=1e-6)

    def test_seed_behavior_fixed_gives_identical_batch(self, ctx):
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      get_op)
        registry.clear_pipeline_cache()
        p = registry.load_pipeline("seedfix.ckpt")
        octx = OpContext()
        pos = Conditioning(context=p.encode_prompt(["a fox"])[0])
        lat = {"samples": np.zeros((3, 8, 8, 4), np.float32)}
        (fixed,) = get_op("LatentBatchSeedBehavior").execute(
            octx, lat, "fixed")
        (out,) = get_op("KSampler").execute(octx, p, 5, 2, 4.0, "euler",
                                            "normal", pos, pos, fixed,
                                            1.0)
        s = np.asarray(out["samples"])
        np.testing.assert_allclose(s[0], s[1], atol=1e-5)
        np.testing.assert_allclose(s[0], s[2], atol=1e-5)
        (rand,) = get_op("LatentBatchSeedBehavior").execute(
            octx, lat, "random")
        (out2,) = get_op("KSampler").execute(octx, p, 5, 2, 4.0,
                                             "euler", "normal", pos,
                                             pos, rand, 1.0)
        s2 = np.asarray(out2["samples"])
        assert not np.allclose(s2[0], s2[1])
        registry.clear_pipeline_cache()


class TestLatentAndAnimatedIO:
    def test_save_load_latent_round_trip(self, tmp_path):
        from comfyui_distributed_tpu.ops.base import get_op
        octx = OpContext()
        octx.output_dir = str(tmp_path)
        octx.input_dir = str(tmp_path)
        rng = np.random.default_rng(3)
        lat = {"samples": rng.standard_normal((2, 8, 8, 4))
               .astype(np.float32)}
        get_op("SaveLatent").execute(octx, lat, "latents/rt")
        import os
        p = os.path.join(str(tmp_path), "latents", "rt_00000.latent")
        assert os.path.exists(p)
        # never-overwrite: a second save gets the next counter
        get_op("SaveLatent").execute(octx, lat, "latents/rt")
        assert os.path.exists(os.path.join(str(tmp_path), "latents",
                                           "rt_00001.latent"))
        # NCHW on disk (reference format)
        from safetensors import safe_open
        with safe_open(p, framework="numpy") as f:
            assert f.get_tensor("latent_tensor").shape == (2, 4, 8, 8)
        (loaded,) = get_op("LoadLatent").execute(
            octx, "latents/rt_00000.latent")
        np.testing.assert_allclose(loaded["samples"], lat["samples"],
                                   rtol=1e-6)
        # the reference's pre-versioning files (no marker) load with
        # the 1/0.18215 legacy multiplier
        from comfyui_distributed_tpu.models.checkpoints import \
            save_state_dict
        legacy = os.path.join(str(tmp_path), "latents", "old.latent")
        save_state_dict(
            {"latent_tensor":
             np.ascontiguousarray(lat["samples"].transpose(0, 3, 1, 2))},
            legacy)
        (old,) = get_op("LoadLatent").execute(octx, "latents/old.latent")
        np.testing.assert_allclose(old["samples"],
                                   lat["samples"] / 0.18215, rtol=1e-5)

    def test_animated_savers(self, tmp_path):
        from PIL import Image

        from comfyui_distributed_tpu.ops.base import get_op
        octx = OpContext()
        octx.output_dir = str(tmp_path)
        frames = np.stack([np.full((16, 16, 3), v, np.float32)
                           for v in (0.1, 0.5, 0.9)])
        get_op("SaveAnimatedWEBP").execute(octx, frames, "anim/w", 8.0,
                                           True, 80, "slowest")
        get_op("SaveAnimatedPNG").execute(octx, frames, "anim/p", 8.0, 4)
        import os
        wp = os.path.join(str(tmp_path), "anim", "w_00000.webp")
        pp = os.path.join(str(tmp_path), "anim", "p_00000.png")
        assert os.path.exists(wp) and os.path.exists(pp)
        im = Image.open(wp)
        assert getattr(im, "n_frames", 1) == 3
        im2 = Image.open(pp)
        assert getattr(im2, "n_frames", 1) == 3


class TestPngWorkflowMetadata:
    """VERDICT r4 #5: saved PNGs embed the executing prompt and the
    client's extra_pnginfo (reference ships extra_pnginfo.workflow with
    every dispatch, gpupanel.js:1344-1358) and round-trip into the same
    graph."""

    def test_save_image_embeds_and_round_trips(self, ctx, tmp_path):
        import os

        from PIL import Image
        g = parse_workflow("/root/repo/workflows/distributed-txt2img.json")
        g.nodes["5"].inputs.update(width=64, height=64, batch_size=1)
        g.nodes["3"].inputs.update(steps=1)
        g.nodes["9"].class_type = "SaveImage"
        g.nodes["9"].inputs["filename_prefix"] = "meta_rt"
        ui_doc = json.load(
            open("/root/repo/workflows/distributed-txt2img.json"))
        ctx.output_dir = str(tmp_path / "out")
        res = WorkflowExecutor(ctx).execute(
            g, extra_pnginfo={"workflow": ui_doc})
        assert res.images
        outs = sorted(os.listdir(ctx.output_dir))
        assert outs, "SaveImage wrote nothing"
        im = Image.open(os.path.join(ctx.output_dir, outs[0]))
        assert "prompt" in im.info and "workflow" in im.info
        # the prompt chunk reloads into the SAME executable graph
        g2 = parse_workflow(json.loads(im.info["prompt"]))
        assert g2.to_api_format() == g.to_api_format()
        # the workflow chunk reloads into the same node set
        g3 = parse_workflow(json.loads(im.info["workflow"]))
        assert set(g3.nodes) == set(g.nodes)

    def test_no_metadata_when_none_given(self, tmp_path):
        """A bare op-level SaveImage (no executor run) writes clean PNGs."""
        import os

        from PIL import Image

        from comfyui_distributed_tpu.ops.base import OpContext, get_op
        octx = OpContext(output_dir=str(tmp_path / "out"))
        img = np.zeros((1, 8, 8, 3), np.float32)
        get_op("SaveImage").execute(octx, img, "plain")
        outs = sorted(os.listdir(octx.output_dir))
        im = Image.open(os.path.join(octx.output_dir, outs[0]))
        assert "prompt" not in im.info and "workflow" not in im.info


class TestIp2pFixture:
    """distributed-ip2p.json: the InstructPix2Pix edit sweep over the
    split-component loaders (UNETLoader + CLIPLoader + VAELoader), fanned
    out by DistributedSeed on the SPMD mesh."""

    def test_ip2p_fixture_fans_out(self, tmp_path, monkeypatch):
        import os

        from PIL import Image
        monkeypatch.delenv(registry.FAMILY_ENV, raising=False)
        registry.clear_pipeline_cache()
        rt = mesh_mod.MeshRuntime(mesh=mesh_mod.build_mesh(
            {"data": 2, "tensor": 1, "seq": 1},
            devices=jax.devices()[:2]))
        os.makedirs(tmp_path / "input", exist_ok=True)
        Image.fromarray((np.random.default_rng(1).random((32, 32, 3))
                         * 255).astype("uint8")).save(
            tmp_path / "input" / "input.png")
        ctx = OpContext(runtime=rt, input_dir=str(tmp_path / "input"),
                        output_dir=str(tmp_path / "out"))
        g = parse_workflow("/root/repo/workflows/distributed-ip2p.json")
        # tiny geometry for CPU: 8-channel tiny ip2p UNet via name
        # detection, tiny CLIP via the type map, tiny VAE via name
        g.nodes["2"].inputs["unet_name"] = "tiny-ip2p-unet.sft"
        g.nodes["3"].inputs.update(clip_name="tiny-clip.sft",
                                   type="tiny")
        g.nodes["4"].inputs["vae_name"] = "tiny-vae.sft"
        g.nodes["9"].inputs.update(steps=2)
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 2          # fan-out x2
        imgs = np.stack(res.images)
        assert np.isfinite(imgs).all()
        assert not np.allclose(imgs[0], imgs[1])   # distinct seeds
        registry.clear_pipeline_cache()


class TestSdxlRefinerFixture:
    """distributed-sdxl-refiner.json: the canonical two-stage SDXL flow
    — base denoises [0, end) with leftover noise, the refiner finishes —
    fanned out by DistributedSeed through BOTH stages."""

    def test_two_stage_handoff_fans_out(self, tmp_path, monkeypatch):
        from comfyui_distributed_tpu.workflow import (WorkflowExecutor,
                                                      parse_workflow)
        monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny_sdxl")
        registry.clear_pipeline_cache()
        rt = mesh_mod.MeshRuntime(mesh=mesh_mod.build_mesh(
            {"data": 2, "tensor": 1, "seq": 1},
            devices=jax.devices()[:2]))
        ctx = OpContext(runtime=rt, output_dir=str(tmp_path / "out"))
        g = parse_workflow(
            "/root/repo/workflows/distributed-sdxl-refiner.json")
        g.nodes["3"].inputs.update(width=64, height=64, batch_size=1)
        g.nodes["8"].inputs.update(steps=4, end_at_step=3)
        g.nodes["9"].inputs.update(steps=4, start_at_step=3)
        res = WorkflowExecutor(ctx).execute(g)
        assert len(res.images) == 2          # fan-out through BOTH stages
        imgs = np.stack(res.images)
        assert np.isfinite(imgs).all()
        assert not np.allclose(imgs[0], imgs[1])   # distinct seeds
        # the refiner stage actually changes the latent: base-only
        # (full denoise, no second stage) differs from the handoff
        g2 = parse_workflow(
            "/root/repo/workflows/distributed-sdxl-refiner.json")
        g2.nodes["3"].inputs.update(width=64, height=64, batch_size=1)
        g2.nodes["8"].inputs.update(steps=4, end_at_step=10000)
        g2.nodes["8"].inputs["return_with_leftover_noise"] = "disable"
        g2.nodes["10"].inputs["samples"] = ["8", 0]
        del g2.nodes["9"]          # orphaned refiner stage: don't pay for it
        res2 = WorkflowExecutor(
            OpContext(runtime=rt, output_dir=str(tmp_path / "o2"))
        ).execute(g2)
        assert not np.allclose(np.stack(res2.images), imgs)
        registry.clear_pipeline_cache()
