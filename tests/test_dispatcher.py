"""Dispatcher graph-rewrite parity (gpupanel.js semantics)."""

import asyncio
import json

import pytest

from comfyui_distributed_tpu.workflow import parse_workflow
from comfyui_distributed_tpu.workflow import dispatcher as dsp
from comfyui_distributed_tpu.workflow.graph import Graph, Node

TXT2IMG = "/root/reference/workflows/distributed-txt2img.json"
UPSCALE = "/root/reference/workflows/distributed-upscale.json"


class TestPrune:
    def test_connected_graph_kept_whole(self):
        g = parse_workflow(TXT2IMG)
        pruned = dsp.prune_for_worker(g)
        assert set(pruned.nodes) == set(g.nodes)

    def test_disconnected_branch_pruned(self):
        g = parse_workflow(TXT2IMG)
        # an island node with no links to the distributed component
        g.nodes["99"] = Node(id="99", class_type="EmptyLatentImage",
                             inputs={"width": 8, "height": 8,
                                     "batch_size": 1})
        pruned = dsp.prune_for_worker(g)
        assert "99" not in pruned.nodes
        assert "2" in pruned.nodes  # collector stays

    def test_prune_does_not_mutate_original(self):
        g = parse_workflow(TXT2IMG)
        before = json.dumps(g.to_api_format(), sort_keys=True, default=str)
        dsp.prune_for_worker(g)
        assert json.dumps(g.to_api_format(), sort_keys=True,
                          default=str) == before


class TestInjection:
    def test_master_injection(self):
        g = parse_workflow(TXT2IMG)
        jm = dsp.make_job_id_map(g, prefix="exec_t")
        out = dsp.prepare_for_participant(g, "master", jm, ["worker_0",
                                                            "worker_1"])
        seed = out.nodes["4"].hidden
        assert seed["is_worker"] is False
        coll = out.nodes["2"].hidden
        assert coll["multi_job_id"] == "exec_t_2"
        assert json.loads(coll["enabled_worker_ids"]) == ["worker_0",
                                                          "worker_1"]
        assert "master_url" not in coll

    def test_worker_injection(self):
        g = parse_workflow(TXT2IMG)
        jm = dsp.make_job_id_map(g, prefix="exec_t")
        out = dsp.prepare_for_participant(
            g, "worker", jm, ["worker_0", "worker_1"],
            master_url="http://10.0.0.1:8288", worker_index=1, batch_size=4)
        seed = out.nodes["4"].hidden
        assert seed["is_worker"] is True
        assert seed["worker_id"] == "worker_1"
        coll = out.nodes["2"].hidden
        assert coll["master_url"] == "http://10.0.0.1:8288"
        assert coll["worker_batch_size"] == 4
        assert "enabled_worker_ids" not in coll

    def test_upscaler_injection_both_sides(self):
        g = parse_workflow(UPSCALE)
        jm = dsp.make_job_id_map(g)
        m = dsp.prepare_for_participant(g, "master", jm, ["worker_0"])
        w = dsp.prepare_for_participant(g, "worker", jm, ["worker_0"],
                                        master_url="http://m:1", worker_index=0)
        # workers need the enabled list for tile math (gpupanel.js:1157-1174)
        assert json.loads(m.nodes["13"].hidden["enabled_worker_ids"]) == \
            ["worker_0"]
        assert json.loads(w.nodes["13"].hidden["enabled_worker_ids"]) == \
            ["worker_0"]
        assert w.nodes["13"].hidden["master_url"] == "http://m:1"

    def test_collector_downstream_of_upscaler_passthrough(self):
        """A collector fed (transitively) by a distributed upscaler becomes
        pass_through (gpupanel.js:1146-1154)."""
        g = parse_workflow(UPSCALE)
        g.nodes["20"] = Node(id="20", class_type="DistributedCollector",
                             inputs={"images": ["13", 0]})
        g.nodes["10"].inputs["images"] = ["20", 0]
        jm = dsp.make_job_id_map(g)
        out = dsp.prepare_for_participant(g, "master", jm, ["worker_0"])
        assert out.nodes["20"].hidden.get("pass_through") is True
        assert "multi_job_id" not in out.nodes["20"].hidden

    def test_job_id_map(self):
        g = parse_workflow(TXT2IMG)
        jm = dsp.make_job_id_map(g)
        assert set(jm) == {"2"}
        assert jm["2"].endswith("_2")
        assert jm["2"].startswith("exec_")


class TestUpstream:
    def test_has_upstream_type(self):
        g = parse_workflow(UPSCALE)
        # preview (10) is downstream of the upscaler (13)
        assert dsp.has_upstream_type(g, "10",
                                     ("UltimateSDUpscaleDistributed",))
        assert not dsp.has_upstream_type(g, "13",
                                         ("UltimateSDUpscaleDistributed",))

    def test_cycle_safe(self):
        g = Graph(nodes={
            "a": Node(id="a", class_type="X", inputs={"i": ["b", 0]}),
            "b": Node(id="b", class_type="X", inputs={"i": ["a", 0]}),
        })
        assert not dsp.has_upstream_type(g, "a", ("Y",))


def test_prune_without_distributed_nodes_returns_copy():
    """Regression: a graph with no collector/upscaler must still be deep
    copied, or per-participant hidden inputs leak into the caller's graph."""
    from comfyui_distributed_tpu.workflow.dispatcher import (
        make_job_id_map, prepare_for_participant, prune_for_worker)
    from comfyui_distributed_tpu.workflow.graph import parse_api_format

    g = parse_api_format({
        "1": {"class_type": "DistributedSeed", "inputs": {"seed": 5}},
        "2": {"class_type": "PreviewImage", "inputs": {"images": ["1", 0]}},
    })
    pruned = prune_for_worker(g)
    assert pruned is not g
    assert all(pruned.nodes[n] is not g.nodes[n] for n in g.nodes)

    w0 = prepare_for_participant(g, "worker", {}, ["0", "1"],
                                 worker_index=0)
    w1 = prepare_for_participant(g, "worker", {}, ["0", "1"],
                                 worker_index=1)
    assert w0.nodes["1"].hidden["worker_id"] == "worker_0"
    assert w1.nodes["1"].hidden["worker_id"] == "worker_1"
    assert "worker_id" not in g.nodes["1"].hidden


class TestStagedImageCache:
    """VERDICT r4 #6: images pulled from the master are cached (30 s,
    reference gpupanel.js:1364-1416) so a multi-worker dispatch does ONE
    master read per image and N worker pushes."""

    def test_one_master_read_for_two_workers(self):
        import base64

        from aiohttp import web
        from comfyui_distributed_tpu.workflow import orchestrate as orch

        counts = {"load": 0, "upload": 0}

        async def load_image(request):
            counts["load"] += 1
            return web.json_response(
                {"image_data": base64.b64encode(b"pngbytes").decode()})

        async def upload(request):
            counts["upload"] += 1
            await request.post()
            return web.json_response({"name": "in.png"})

        async def go():
            app = web.Application()
            app.router.add_post("/distributed/load_image", load_image)
            app.router.add_post("/upload/image", upload)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            url = f"http://127.0.0.1:{port}"
            orch._stage_cache.clear()
            try:
                workers = [{"id": f"worker_{i}", "host": "127.0.0.1",
                            "port": port} for i in range(2)]
                # parallel staging, exactly like run_distributed's gather
                await asyncio.gather(*(
                    orch.stage_images_on_worker(url, w, ["in.png"])
                    for w in workers))
            finally:
                await runner.cleanup()
            return counts

        out = asyncio.run(go())
        assert out["upload"] == 2        # every worker got the image
        assert out["load"] == 1, "master was read once per worker"
