"""Fault-tolerant cluster control plane (ISSUE 4): worker registry with
leases, per-job work ledger with exactly-once check-in, automatic
reassignment of lost tiles/slices, hedged straggler dispatch, the
idempotency-key dedupe in the queue layer, and the registry-aware
preflight.

CPU-only, tier-1-eligible except the two marked-slow loopback
integration tests: THE acceptance (master + 2 workers run a tiled
upscale over real loopback HTTP, one worker is killed mid-job, the
final image contains ALL tiles via reassignment and the trace tree
shows the reassign spans) and the hedge-beats-straggler run.  The
cheap tests drive the same drain/ledger/registry code paths with fed
queues and fake refine callbacks — no model, no compile.
"""

import asyncio
import json
import threading
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.runtime import cluster as cl
from comfyui_distributed_tpu.runtime.jobs import JobStore
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as tr
from comfyui_distributed_tpu.utils.net import run_async_in_loop


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture(autouse=True)
def tracing_on():
    was = tr.tracing_enabled()
    tr.set_tracing(True)
    yield
    tr.set_tracing(was)


@pytest.fixture
def server_loop():
    """A real event loop on a side thread (the server-loop stand-in the
    drain coroutines are scheduled onto)."""
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    yield loop
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)


# --- registry: lease state machine -------------------------------------------

class TestClusterRegistry:
    def test_lease_expiry_healthy_to_dead(self):
        reg = cl.ClusterRegistry(lease_s=0.15, suspect_probes=2)
        reg.observe_probe("w0", True)
        assert reg.state("w0") == cl.HEALTHY
        time.sleep(0.2)
        assert reg.state("w0") == cl.DEAD
        # contact resurrects: a restarted worker re-earns its lease
        reg.heartbeat("w0")
        assert reg.state("w0") == cl.HEALTHY

    def test_failed_probes_mark_suspect_then_recovery(self):
        reg = cl.ClusterRegistry(lease_s=30.0, suspect_probes=2)
        reg.observe_probe("w0", True)
        reg.observe_probe("w0", False)
        assert reg.state("w0") == cl.HEALTHY  # one failure < threshold
        reg.observe_probe("w0", False)
        assert reg.state("w0") == cl.SUSPECT
        reg.observe_probe("w0", True)
        assert reg.state("w0") == cl.HEALTHY

    def test_config_seed_stays_unknown_until_contact(self):
        reg = cl.ClusterRegistry(lease_s=0.05, suspect_probes=1)
        reg.seed_from_config([
            {"id": "w0", "enabled": True, "port": 1},
            {"id": "off", "enabled": False, "port": 2}])
        time.sleep(0.1)
        # never contacted: UNKNOWN, not DEAD — preflight probes it
        assert reg.state("w0") == cl.UNKNOWN
        assert reg.state("off") == cl.UNKNOWN  # disabled never seeded
        assert "off" not in reg.snapshot()["workers"]

    def test_touch_only_renews_known_ids(self):
        reg = cl.ClusterRegistry(lease_s=30.0)
        reg.touch("worker_0")   # positional wire label, unknown
        assert "worker_0" not in reg.snapshot()["workers"]
        reg.register("w1")
        reg.touch("w1")
        assert reg.state("w1") == cl.HEALTHY

    def test_transitions_recorded(self):
        reg = cl.ClusterRegistry(lease_s=0.1, suspect_probes=1)
        reg.observe_probe("w0", True)
        time.sleep(0.15)
        reg.state("w0")
        trans = reg.snapshot()["transitions"]
        assert [(t["from"], t["to"]) for t in trans
                if t["worker_id"] == "w0"] == [
            (cl.UNKNOWN, cl.HEALTHY), (cl.HEALTHY, cl.DEAD)]


# --- ledger: exactly-once, reassignment, hedging -----------------------------

class TestWorkLedger:
    def test_check_in_exactly_once(self):
        led = cl.WorkLedger()
        led.create_job("j", {0: "master", 1: "w0"})
        assert led.check_in("j", 0, "master") is True
        assert led.check_in("j", 0, "master") is False   # retried POST
        assert led.check_in("j", 0, "w0") is False       # hedge loser
        assert led.pending("j") == [1]
        assert led.progress("j") == (1, 2)
        # unknown jobs are a no-op pass-through (worker side, SPMD mode)
        assert led.check_in("nope", 5, "x") is True

    def test_reassign_skips_done_units(self):
        led = cl.WorkLedger()
        led.create_job("j", {0: "w0", 1: "w0", 2: "w1"})
        led.check_in("j", 0, "w0")
        moved = led.reassign("j", [0, 1], "master")
        assert moved == [1]
        assert led.pending("j", owner="master") == [1]
        assert led.attempts("j", 1) == 2

    def test_hedge_first_completion_wins(self):
        led = cl.WorkLedger()
        led.create_job("j", {0: "w0", 1: "w0"})
        assert led.mark_hedged("j", [0, 1], "master") == [0, 1]
        assert led.mark_hedged("j", [0], "master") == []  # already hedged
        w0 = tr.GLOBAL_COUNTERS.get("cluster_hedge_wins")
        l0 = tr.GLOBAL_COUNTERS.get("cluster_hedge_losses")
        # unit 0: the hedge (master) lands first -> win; the owner's
        # late completion is deduped
        assert led.check_in("j", 0, "master") is True
        assert led.check_in("j", 0, "w0") is False
        # unit 1: the owner beats the hedge -> loss
        assert led.check_in("j", 1, "w0") is True
        assert tr.GLOBAL_COUNTERS.get("cluster_hedge_wins") == w0 + 1
        assert tr.GLOBAL_COUNTERS.get("cluster_hedge_losses") == l0 + 1

    def test_overdue_units_gated_on_progress_and_latency(self):
        led = cl.WorkLedger()
        led.create_job("j", {i: ("master" if i < 2 else "w0")
                             for i in range(4)})
        # no completions yet: no latency estimate, nothing overdue
        assert led.overdue_units("j", factor=0.0, min_progress_pct=0.0,
                                 min_wait_s=0.0) == {}
        led.check_in("j", 0, "master")
        led.check_in("j", 1, "master")
        # progress gate: 50% done < 75% required
        assert led.overdue_units("j", factor=0.0, min_progress_pct=75.0,
                                 min_wait_s=0.0) == {}
        # the wait floor keeps sub-threshold units un-hedged even with
        # a tiny latency estimate
        assert led.overdue_units("j", factor=0.0, min_progress_pct=50.0,
                                 min_wait_s=30.0) == {}
        time.sleep(0.02)
        over = led.overdue_units("j", factor=0.0, min_progress_pct=50.0,
                                 min_wait_s=0.0)
        assert set(over) == {2, 3} and over[2] == "w0"

    def test_unmark_hedged_restores_eligibility(self):
        """A hedge that never launched (no target / dispatch failed)
        must not pin the unit: rolled back, it stays visible to the
        dead-owner scan and future hedges."""
        led = cl.WorkLedger()
        led.create_job("j", {0: "w0"})
        assert led.mark_hedged("j", [0]) == [0]
        assert led.owners_of_pending("j", skip_hedged=True) == {}
        led.unmark_hedged("j", [0])
        assert led.owners_of_pending("j", skip_hedged=True) == {0: "w0"}
        assert led.attempts("j", 0) == 1
        assert led.mark_hedged("j", [0]) == [0]  # hedgeable again
        led.finish_job("j")

    def test_finish_job_summary(self):
        led = cl.WorkLedger()
        led.create_job("j", {0: "w0", 1: "w1"})
        led.check_in("j", 0, "w0")
        led.reassign("j", [1], "master")
        summary = led.finish_job("j")
        assert summary["done_units"] == 1
        assert summary["pending_units"] == ["1"]
        assert summary["reassigned_units"] == 1
        assert not led.has_job("j")
        assert led.snapshot()["completed_jobs"][-1]["job_id"] == "j"

    def test_redispatch_callback(self):
        led = cl.WorkLedger()
        led.create_job("j", {0: "w0"})
        calls = []

        async def fn(units, lost):
            calls.append((list(units), lost))
            return True

        led.set_redispatcher("j", fn)
        assert led.has_redispatcher("j")
        assert asyncio.run(led.redispatch("j", [0], "w0")) is True
        assert calls == [([0], "w0")]
        # a raising redispatcher degrades to False, never crashes
        async def boom(units, lost):
            raise RuntimeError("no route")

        led.set_redispatcher("j", boom)
        assert asyncio.run(led.redispatch("j", [0], "w0")) is False
        led.finish_job("j")
        assert not led.has_redispatcher("j")


# --- queue-layer idempotency (satellite) -------------------------------------

class TestJobStoreIdempotency:
    def _drain_all(self, q):
        out = []
        while not q.empty():
            out.append(q.get_nowait())
        return out

    def test_tile_replay_acked_but_not_requeued(self):
        async def run():
            js = JobStore()
            await js.prepare_tile_job("j")
            item = {"tile_idx": 3, "worker_id": "w0"}
            assert await js.put_tile("j", item, idem_key="w0:3:0")
            # the retried POST of the SAME send: acknowledged, dropped
            assert await js.put_tile("j", item, idem_key="w0:3:0")
            # a new dispatch attempt is a distinct key: enqueued
            assert await js.put_tile("j", item, idem_key="w0:3:1")
            q = await js.get_tile_queue("j")
            items = self._drain_all(q)
            # key state dies with the queue
            await js.remove_tile_queue("j")
            await js.prepare_tile_job("j")
            assert await js.put_tile("j", item, idem_key="w0:3:0")
            q2 = await js.get_tile_queue("j")
            return items, self._drain_all(q2)

        items, after = asyncio.run(run())
        assert len(items) == 2
        assert len(after) == 1

    def test_image_replay_and_keyless_passthrough(self):
        async def run():
            js = JobStore()
            await js.prepare_job("j")
            assert await js.put_result("j", {"worker_id": "w"},
                                       idem_key="w:0:0")
            assert await js.put_result("j", {"worker_id": "w"},
                                       idem_key="w:0:0")
            # keyless senders (older peers) keep the old semantics
            assert await js.put_result("j", {"worker_id": "w"})
            assert await js.put_result("j", {"worker_id": "w"})
            q = await js.get_queue("j")
            return self._drain_all(q)

        assert len(asyncio.run(run())) == 3


# --- registry-aware preflight (satellite) ------------------------------------

class TestPreflightRegistry:
    def test_dead_worker_skipped_without_probe(self, tmp_path):
        """A registry-DEAD worker is dropped even though its socket
        still answers — the died-between-jobs case the probe alone
        cannot catch."""
        from comfyui_distributed_tpu.workflow import dispatcher as dsp

        async def go():
            state = ServerState(config_path=str(tmp_path / "c.json"),
                                start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                port = client.server.port
                workers = [{"id": "wdead", "host": "127.0.0.1",
                            "port": port, "enabled": True},
                           {"id": "wok", "host": "127.0.0.1",
                            "port": port, "enabled": True}]
                reg = cl.ClusterRegistry(lease_s=0.05, suspect_probes=1)
                reg.observe_probe("wdead", True)
                await asyncio.sleep(0.1)     # lease expires -> DEAD
                alive = await dsp.preflight_check(workers, registry=reg)
                assert [w["id"] for w in alive] == ["wok"]
                # the probe result fed the registry: wok is now healthy
                assert reg.state("wok") == cl.HEALTHY
            finally:
                await client.close()

        asyncio.run(go())


class TestRedispatcherIdentity:
    def test_image_redispatch_follows_unit_not_current_owner(
            self, monkeypatch):
        """Cascade regression: wA's slice was moved to wB, then wB dies.
        The re-redispatch must render unit wA's slice (worker_index of
        wA) on a healthy target — deriving identity from the CURRENT
        owner (wB) would re-render wB's already-delivered slice and
        strand wA's forever."""
        from comfyui_distributed_tpu.workflow import dispatcher as dsp
        from comfyui_distributed_tpu.workflow import orchestrate as orch
        from comfyui_distributed_tpu.workflow.graph import parse_workflow

        graph = parse_workflow(
            {"1": {"class_type": "DistributedCollector", "inputs": {}}})
        enabled = ["wA", "wB", "wC"]
        alive = [{"id": w, "host": "127.0.0.1", "port": 1}
                 for w in enabled]
        reg = cl.ClusterRegistry(lease_s=60.0, suspect_probes=1)
        reg.observe_probe("wA", False)   # the first casualty: not healthy
        reg.observe_probe("wC", True)
        led = cl.WorkLedger()
        led.create_job("jimg", {w: w for w in enabled}, kind="image")
        sent = []

        async def fake_dispatch(worker, wgraph, client_id=None,
                                extra_data=None):
            sent.append((str(worker["id"]), wgraph))

        monkeypatch.setattr(dsp, "dispatch_to_worker", fake_dispatch)
        orch._register_redispatchers(graph, {"1": "jimg"}, enabled,
                                     alive, "http://m", "c", None,
                                     reg, led)
        led.check_in("jimg", "wB", "wB")
        led.check_in("jimg", "wC", "wC")
        led.reassign("jimg", ["wA"], "wB")    # first recovery attempt
        # wB dies: the drain asks to redispatch pending unit wA
        assert asyncio.run(led.redispatch("jimg", ["wA"], "wB")) is True
        target, wgraph = sent[-1]
        assert target == "wC"                 # the only healthy peer
        col = next(n for n in wgraph.nodes.values()
                   if n.class_type == "DistributedCollector")
        # identity = unit wA's slot (index 0), NOT wB's (index 1)
        assert col.hidden["worker_id"] == "worker_0"
        assert col.hidden["dispatch_attempt"] == 3
        assert led.owners_of_pending("jimg") == {"wA": "wC"}
        led.finish_job("jimg")


# --- heartbeat + routes ------------------------------------------------------

class TestClusterRoutes:
    def test_register_heartbeat_and_snapshot(self, tmp_path):
        async def go():
            state = ServerState(config_path=str(tmp_path / "c.json"),
                                start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                r = await client.post("/distributed/register",
                                      json={"worker_id": "ext0",
                                            "port": 9999})
                assert r.status == 200
                body = await r.json()
                assert body["state"] == cl.HEALTHY
                assert body["lease_s"] == state.cluster.lease_s
                r = await client.post("/distributed/heartbeat",
                                      json={"worker_id": "ext0"})
                assert r.status == 200
                r = await client.get("/distributed/cluster")
                snap = await r.json()
                assert snap["workers"]["ext0"]["state"] == cl.HEALTHY
                assert snap["policy"] in C.FAULT_POLICIES
                assert "ledger" in snap and "hedge" in snap
                # metrics carry the cluster block + prom gauge
                m = await (await client.get("/distributed/metrics")).json()
                assert "ext0" in m["cluster"]["workers"]
                prom = await (await client.get(
                    "/distributed/metrics.prom")).text()
                assert 'dtpu_cluster_workers{state="healthy"}' in prom
                # missing id -> 400
                r = await client.post("/distributed/heartbeat", json={})
                assert r.status == 400
            finally:
                await client.close()

        asyncio.run(go())

    def test_heartbeat_sender_renews_lease(self, tmp_path):
        async def go():
            state = ServerState(config_path=str(tmp_path / "c.json"),
                                start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                url = f"http://127.0.0.1:{client.server.port}"
                hb = cl.HeartbeatSender(url, "hb0", interval=999,
                                        port=8290)
                loop = asyncio.get_running_loop()
                ok = await loop.run_in_executor(None, hb.beat_once)
                assert ok and hb.beats_sent == 1
                assert state.cluster.state("hb0") == cl.HEALTHY
                assert state.cluster.snapshot()["workers"]["hb0"][
                    "port"] == 8290
            finally:
                await client.close()

        asyncio.run(go())


# --- drain-level recovery (fed queues, fake refine — no model) ---------------

def _mk_ctx(server_loop, ledger=None, registry=None):
    return OpContext(job_store=JobStore(), server_loop=server_loop,
                     ledger=ledger, cluster=registry)


def _tile_item(idx, wid, is_last=False):
    return {"tile_idx": idx, "worker_id": wid, "is_last": is_last,
            "x": 0, "y": 0, "extracted_width": 1, "extracted_height": 1,
            "padding": 0, "tensor": np.zeros((1, 1, 3), np.float32)}


class TestCollectDrainRecovery:
    def _op(self):
        from comfyui_distributed_tpu.ops.tiled_upscale import (
            UltimateSDUpscaleDistributed)
        return UltimateSDUpscaleDistributed()

    def test_dead_owner_units_reassigned_to_master(self, server_loop,
                                                   monkeypatch):
        """Lease expiry mid-drain: the dead worker's pending units are
        refined master-side (fake refine) and check in exactly once —
        the collect returns with ZERO pending units."""
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "reassign")
        monkeypatch.setenv(C.HEDGE_ENV, "0")
        ledger = cl.WorkLedger()
        registry = cl.ClusterRegistry(lease_s=0.2, suspect_probes=1)
        registry.observe_probe("w0", True)
        registry.observe_probe("w1", True)
        ctx = _mk_ctx(server_loop, ledger, registry)
        mj = "job_reassign"
        ledger.create_job(mj, {0: "master", 1: "w0", 2: "w1", 3: "w1"})
        ledger.check_in(mj, 0, "master")
        refined_units = []

        def refine(units):
            refined_units.extend(units)
            return {u: np.zeros((2, 2, 3), np.float32) for u in units}

        run_async_in_loop(ctx.job_store.prepare_tile_job(mj),
                          server_loop, timeout=5)
        # w0 delivers; w1 never does and its lease expires
        run_async_in_loop(ctx.job_store.put_tile(
            mj, _tile_item(1, "w0", is_last=True)), server_loop,
            timeout=5)
        t0 = time.monotonic()
        collected = self._op()._collect_tiles(ctx, mj, 2,
                                              refine_window=refine)
        assert sorted(refined_units) == [2, 3]
        assert set(collected) == {1, 2, 3}
        assert "window_tensor" in collected[2]
        assert ledger.pending(mj) == []
        # recovery came from the lease, not the 60s drain deadline
        assert time.monotonic() - t0 < C.TILE_COLLECTION_TIMEOUT / 2
        summary = ledger.finish_job(mj)
        assert summary["reassigned_units"] == 2

    def test_policy_fail_raises_on_dead_owner(self, server_loop,
                                              monkeypatch):
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "fail")
        monkeypatch.setenv(C.HEDGE_ENV, "0")
        ledger = cl.WorkLedger()
        registry = cl.ClusterRegistry(lease_s=0.1, suspect_probes=1)
        registry.observe_probe("w0", True)
        ctx = _mk_ctx(server_loop, ledger, registry)
        mj = "job_fail"
        ledger.create_job(mj, {0: "w0"})
        run_async_in_loop(ctx.job_store.prepare_tile_job(mj),
                          server_loop, timeout=5)
        with pytest.raises(cl.ClusterFaultError, match="w0"):
            self._op()._collect_tiles(ctx, mj, 1,
                                      refine_window=lambda u: {})
        ledger.finish_job(mj)

    def test_policy_partial_keeps_seed_semantics(self, server_loop,
                                                 monkeypatch):
        """partial: the drain NEVER recovers — it waits out the
        no-progress timeout and returns what arrived (the seed
        behavior), leaving the lost units pending."""
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "partial")
        monkeypatch.setenv(C.HEDGE_ENV, "0")
        monkeypatch.setattr(C, "TILE_WAIT_TIMEOUT", 0.3)
        ledger = cl.WorkLedger()
        registry = cl.ClusterRegistry(lease_s=0.05, suspect_probes=1)
        registry.observe_probe("w0", True)
        ctx = _mk_ctx(server_loop, ledger, registry)
        mj = "job_partial"
        ledger.create_job(mj, {0: "w0", 1: "w1"})
        run_async_in_loop(ctx.job_store.prepare_tile_job(mj),
                          server_loop, timeout=5)
        run_async_in_loop(ctx.job_store.put_tile(
            mj, _tile_item(0, "w0", is_last=True)), server_loop,
            timeout=5)
        refine_calls = []
        collected = self._op()._collect_tiles(
            ctx, mj, 2, refine_window=lambda u: refine_calls.append(u))
        assert set(collected) == {0}
        assert refine_calls == []
        assert ledger.pending(mj) == [1]
        ledger.finish_job(mj)

    def test_hedge_refines_overdue_straggler_first_wins(self, server_loop,
                                                        monkeypatch):
        """The straggler's units get speculatively refined master-side
        once the job passes the progress gate; its late tiles then
        dedupe as hedge losses."""
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "reassign")
        monkeypatch.setenv(C.HEDGE_ENV, "1")
        monkeypatch.setenv(C.HEDGE_PCT_ENV, "25")
        monkeypatch.setenv(C.HEDGE_FACTOR_ENV, "0.1")
        monkeypatch.setenv(C.HEDGE_MIN_WAIT_ENV, "0.05")
        ledger = cl.WorkLedger()
        registry = cl.ClusterRegistry(lease_s=60.0, suspect_probes=9)
        registry.observe_probe("w0", True)
        ctx = _mk_ctx(server_loop, ledger, registry)
        mj = "job_hedge"
        ledger.create_job(mj, {0: "master", 1: "master",
                               2: "w0", 3: "w0"})
        ledger.check_in(mj, 0, "master")
        time.sleep(0.05)
        ledger.check_in(mj, 1, "master")   # latency estimate exists now
        run_async_in_loop(ctx.job_store.prepare_tile_job(mj),
                          server_loop, timeout=5)
        wins0 = tr.GLOBAL_COUNTERS.get("cluster_hedge_wins")

        def refine(units):
            return {u: np.zeros((2, 2, 3), np.float32) for u in units}

        collected = self._op()._collect_tiles(ctx, mj, 1,
                                              refine_window=refine)
        assert set(collected) == {2, 3}
        assert all("window_tensor" in collected[u] for u in (2, 3))
        assert ledger.pending(mj) == []
        assert tr.GLOBAL_COUNTERS.get("cluster_hedge_wins") == wins0 + 2
        summary = ledger.finish_job(mj)
        assert summary["hedged_units"] == 2

    def test_no_ledger_keeps_precluster_drain(self, server_loop):
        """Without a ledger the drain is the seed's done-count loop."""
        ctx = _mk_ctx(server_loop)
        mj = "job_legacy"
        run_async_in_loop(ctx.job_store.prepare_tile_job(mj),
                          server_loop, timeout=5)
        for idx, last in ((0, False), (1, True)):
            run_async_in_loop(ctx.job_store.put_tile(
                mj, _tile_item(idx, "w0", is_last=last)), server_loop,
                timeout=5)
        collected = self._op()._collect_tiles(ctx, mj, 1)
        assert set(collected) == {0, 1}


# --- loopback integration ----------------------------------------------------

def upscale_prompt(seed=7, size=64, tile=32, steps=1):
    """LoadImage synthesizes a deterministic 512px card (missing file),
    scaled to 64px -> 4 tiles of 32px: master [0,1], w0 [2], w1 [3]."""
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "a map", "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "10": {"class_type": "LoadImage",
               "inputs": {"image": "__cluster_card__.png"}},
        "11": {"class_type": "ImageScale",
               "inputs": {"image": ["10", 0],
                          "upscale_method": "bilinear",
                          "width": size, "height": size,
                          "crop": "disabled"}},
        "2": {"class_type": "UltimateSDUpscaleDistributed",
              "inputs": {"upscaled_image": ["11", 0], "model": ["7", 0],
                         "positive": ["5", 0], "negative": ["6", 0],
                         "vae": ["7", 2], "seed": seed, "steps": steps,
                         "cfg": 2.0, "sampler_name": "euler",
                         "scheduler": "normal", "denoise": 0.4,
                         "tile_width": tile, "tile_height": tile,
                         "padding": 8, "mask_blur": 2,
                         "force_uniform_tiles": True}},
        "3": {"class_type": "PreviewImage", "inputs": {"images": ["2", 0]}},
    }


async def _wait_history(client, pid, timeout_s=240.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        hist = await (await client.get("/history")).json()
        if pid in hist:
            return hist[pid]
        await asyncio.sleep(0.1)
    raise AssertionError(f"prompt {pid} never finished")


class _Cluster:
    """Master + N workers as in-process ServerStates over real loopback
    HTTP sockets (the test_observability topology, plus health polling
    feeding the lease registry)."""

    def __init__(self, tmp_path, n_workers=2):
        self.tmp_path = tmp_path
        self.n_workers = n_workers
        self.workers = []        # (state, client)
        self.master_state = None
        self.master_client = None

    async def start(self):
        import os
        cfg_workers = []
        for i in range(self.n_workers):
            wdir = self.tmp_path / f"worker{i}"
            os.makedirs(wdir / "in"), os.makedirs(wdir / "out")
            st = ServerState(config_path=str(wdir / "cfg.json"),
                             input_dir=str(wdir / "in"),
                             output_dir=str(wdir / "out"),
                             is_worker=True, start_exec_thread=True)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            self.workers.append((st, client))
            cfg_workers.append({"id": f"w{i}", "host": "127.0.0.1",
                                "port": client.server.port,
                                "enabled": True})
        mdir = self.tmp_path / "master"
        os.makedirs(mdir / "in"), os.makedirs(mdir / "out")
        with open(mdir / "cfg.json", "w") as f:
            json.dump({"workers": cfg_workers,
                       "master": {"host": "127.0.0.1"},
                       "settings": {}}, f)
        self.master_state = ServerState(
            config_path=str(mdir / "cfg.json"),
            input_dir=str(mdir / "in"), output_dir=str(mdir / "out"),
            is_worker=False, start_exec_thread=True)
        self.master_client = TestClient(
            TestServer(build_app(self.master_state)))
        await self.master_client.start_server()
        self.master_state.port = self.master_client.server.port
        return self

    async def stop(self):
        self.master_state.health.stop()
        if self.master_client is not None:
            await self.master_client.close()
        for st, client in self.workers:
            try:
                await client.close()
            except Exception:  # noqa: BLE001 - may already be closed
                pass
        self.master_state.drain(5)
        for st, _ in self.workers:
            st.drain(5)


class TestFaultAcceptance:
    @pytest.mark.slow
    def test_kill_one_worker_mid_job_all_tiles_recovered(self, tmp_path,
                                                         monkeypatch):
        """THE acceptance criterion: with DTPU_FAULT_POLICY=reassign,
        killing 1 of 2 workers mid tiled-upscale still yields a complete
        image — every ledger unit checked in exactly once — and the
        reassignment is visible as spans in the job's trace tree."""
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "reassign")
        monkeypatch.setenv(C.HEDGE_ENV, "0")      # isolate the lease path
        monkeypatch.setenv(C.LEASE_ENV, "1.0")
        monkeypatch.setenv(C.SUSPECT_PROBES_ENV, "1")

        async def go():
            clu = await _Cluster(tmp_path, n_workers=2).start()
            try:
                # w1 will die mid-job: it refines its tile but the send
                # loop drops everything (0 tiles sent, no is_last)
                clu.workers[1][0].fault_inject = {"drop_tiles_after": 0}
                # establish w1's lease so its death is a real
                # healthy->dead transition, then poll fast
                clu.master_state.health.interval = 0.2
                await asyncio.get_running_loop().run_in_executor(
                    None, clu.master_state.health.poll_once)
                assert clu.master_state.cluster.state("w1") == cl.HEALTHY
                clu.master_state.health.start()

                r = await clu.master_client.post("/prompt", json={
                    "prompt": upscale_prompt(), "client_id": "acc"})
                assert r.status == 200, await r.text()
                body = await r.json()
                assert sorted(body["workers"]) == ["w0", "w1"], body
                pid = body["prompt_id"]
                # the dispatch landed (the POST above returned after
                # fan-out) — now the worker's server dies
                await clu.workers[1][1].close()

                hist = await _wait_history(clu.master_client, pid)
                assert hist["status"] == "success", hist
                assert hist["images"] == 1

                # ledger: every unit checked in exactly once, the lost
                # one via reassignment
                snap = await (await clu.master_client.get(
                    "/distributed/cluster")).json()
                jobs = [j for j in snap["ledger"]["completed_jobs"]
                        if j["kind"] == "tile"]
                assert jobs, snap["ledger"]
                job = jobs[-1]
                assert job["done_units"] == job["total_units"] == 4
                assert job["pending_units"] == []
                assert job["reassigned_units"] >= 1
                assert snap["workers"]["w1"]["state"] == cl.DEAD

                # the reassignment is visible in the trace tree
                r = await clu.master_client.get(
                    f"/distributed/trace/{pid}")
                assert r.status == 200
                rec = await r.json()
                names = {s["name"] for s in rec["spans"]}
                assert "reassign" in names, sorted(names)
                assert "collect" in names
                re_spans = [s for s in rec["spans"]
                            if s["name"] == "reassign"]
                assert any((s.get("attrs") or {}).get("lost") == "w1"
                           for s in re_spans), re_spans
                # exactly-once at the blend: no duplicate check-ins won
                assert {s["trace_id"] for s in rec["spans"]} == \
                    {rec["trace_id"]}
            finally:
                await clu.stop()

        asyncio.run(go())

    @pytest.mark.slow
    def test_policy_partial_preserves_seed_behavior(self, tmp_path,
                                                    monkeypatch):
        """Opt-out: DTPU_FAULT_POLICY=partial blends what arrived (the
        seed's semantics) — the job still succeeds, the ledger records
        the loss, and no reassign span exists."""
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "partial")
        monkeypatch.setenv(C.HEDGE_ENV, "0")
        monkeypatch.setenv(C.LEASE_ENV, "1.0")
        monkeypatch.setenv(C.SUSPECT_PROBES_ENV, "1")
        monkeypatch.setattr(C, "TILE_WAIT_TIMEOUT", 3.0)
        monkeypatch.setattr(C, "TILE_COLLECTION_TIMEOUT", 20.0)

        async def go():
            clu = await _Cluster(tmp_path, n_workers=2).start()
            try:
                clu.workers[1][0].fault_inject = {"drop_tiles_after": 0}
                r = await clu.master_client.post("/prompt", json={
                    "prompt": upscale_prompt(seed=21),
                    "client_id": "par"})
                assert r.status == 200, await r.text()
                pid = (await r.json())["prompt_id"]
                await clu.workers[1][1].close()
                hist = await _wait_history(clu.master_client, pid)
                assert hist["status"] == "success", hist
                snap = await (await clu.master_client.get(
                    "/distributed/cluster")).json()
                job = [j for j in snap["ledger"]["completed_jobs"]
                       if j["kind"] == "tile"][-1]
                assert job["done_units"] == 3
                assert job["pending_units"] == ["3"]
                assert job["reassigned_units"] == 0
                rec = await (await clu.master_client.get(
                    f"/distributed/trace/{pid}")).json()
                assert "reassign" not in {s["name"]
                                          for s in rec["spans"]}
            finally:
                await clu.stop()

        asyncio.run(go())
