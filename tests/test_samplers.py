"""Samplers + schedules: convergence with an ideal denoiser, determinism,
schedule invariants, CFG wrapper."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import samplers as smp
from comfyui_distributed_tpu.models import schedules as sch


@pytest.fixture(scope="module")
def ds():
    return sch.make_discrete_schedule()


class TestSchedules:
    def test_discrete_table_shape(self, ds):
        assert ds.sigmas.shape == (1000,)
        assert ds.sigma_min > 0
        assert 10 < ds.sigma_max < 200  # SD scaled-linear is ~14.6

    def test_sigma_t_round_trip(self, ds):
        t = ds.t_from_sigma(np.asarray([1.0, 5.0]))
        back = ds.sigma_from_t(t)
        assert np.allclose(back, [1.0, 5.0], rtol=1e-3)

    @pytest.mark.parametrize("name", sch.SCHEDULER_NAMES)
    def test_all_schedulers_valid(self, ds, name):
        for steps in (1, 4, 20):
            sig = sch.compute_sigmas(ds, name, steps)
            assert sig[-1] == 0.0
            assert np.all(np.diff(sig) < 1e-7), f"{name} not descending: {sig}"
            assert sig[0] > 0

    def test_karras_endpoints(self, ds):
        sig = sch.karras_scheduler(ds, 10)
        assert np.isclose(sig[0], ds.sigma_max, rtol=1e-5)
        assert np.isclose(sig[-2], ds.sigma_min, rtol=1e-5)

    def test_denoise_truncation(self, ds):
        full = sch.compute_sigmas(ds, "normal", 20)
        part = sch.compute_sigmas(ds, "normal", 10, denoise=0.5)
        assert len(part) == 11
        assert part[0] < full[0]  # starts mid-schedule (img2img semantics)

    def test_unknown_scheduler_raises(self, ds):
        with pytest.raises(ValueError):
            sch.compute_sigmas(ds, "nope", 10)


def ideal_model(x0):
    """Perfect denoiser for a point-mass distribution at x0: always returns
    x0.  Every correct sampler must converge to x0 as sigma -> 0."""
    def model(x, sigma, **kw):
        return jnp.broadcast_to(x0, x.shape)
    return model


class TestSamplers:
    @pytest.mark.parametrize("name", smp.SAMPLER_NAMES)
    def test_converges_to_target(self, ds, name):
        x0 = jnp.asarray(np.random.default_rng(3).standard_normal(
            (2, 4, 4, 3)).astype(np.float32))
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 12))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(2, dtype=jnp.uint32))
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, x0.shape) * sigmas[0]
        sampler = smp.get_sampler(name)
        out = sampler(ideal_model(x0), x, sigmas, keys=keys)
        # dpm_fast/dpm_adaptive end at sigma_min, not 0 (k-diffusion /
        # ComfyUI parity): residual is O(sigma_min * |noise|)
        atol = 0.12 if name in ("dpm_fast", "dpm_adaptive") else 1e-3
        assert np.allclose(np.asarray(out), np.asarray(x0), atol=atol), name

    @pytest.mark.parametrize("name", ["euler_ancestral", "dpmpp_2m_sde",
                                      "lcm", "dpmpp_sde", "dpmpp_3m_sde",
                                      "ddpm", "er_sde", "seeds_2",
                                      "seeds_3"])
    def test_stochastic_requires_keys(self, ds, name):
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "normal", 4))
        x = jnp.zeros((1, 2, 2, 1))
        with pytest.raises(ValueError):
            smp.get_sampler(name)(ideal_model(x), x, sigmas)

    def test_deterministic_given_keys(self, ds):
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "normal", 6))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(3, dtype=jnp.uint32))
        x = jax.random.normal(jax.random.PRNGKey(1), (3, 4, 4, 2)) * sigmas[0]
        x0 = jnp.ones((3, 4, 4, 2)) * 0.3
        a = smp.sample_euler_ancestral(ideal_model(x0), x, sigmas, keys=keys)
        b = smp.sample_euler_ancestral(ideal_model(x0), x, sigmas, keys=keys)
        assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_different_keys_differ_midrun(self, ds):
        """Distinct per-sample keys give distinct trajectories (replica
        independence) — checked at nonzero final sigma so ancestral noise
        isn't annihilated."""
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "normal", 8))[:5]  # stop early
        keys_a = jax.vmap(jax.random.PRNGKey)(jnp.asarray([1, 2], jnp.uint32))
        keys_b = jax.vmap(jax.random.PRNGKey)(jnp.asarray([3, 4], jnp.uint32))
        x = jnp.zeros((2, 4, 4, 1)) + sigmas[0]
        x0 = jnp.zeros((2, 4, 4, 1))
        a = smp.sample_euler_ancestral(ideal_model(x0), x, sigmas, keys=keys_a)
        b = smp.sample_euler_ancestral(ideal_model(x0), x, sigmas, keys=keys_b)
        assert not np.allclose(np.asarray(a), np.asarray(b))

    def test_samplers_jit_compile(self, ds):
        """The whole sampler must be jittable (scan-based, no python loop)."""
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 5))
        x0 = jnp.ones((1, 4, 4, 2)) * 0.5

        @jax.jit
        def run(x):
            return smp.sample_dpmpp_2m(ideal_model(x0), x, sigmas)

        x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 4, 2)) * sigmas[0]
        out = run(x)
        assert np.allclose(np.asarray(out), 0.5, atol=1e-3)

    def test_unknown_sampler_raises(self):
        with pytest.raises(ValueError):
            smp.get_sampler("plms9000")


class TestPerStepInterrupt:
    """VERDICT r2 #8: /interrupt must stop a sample already inside the
    compiled scan, not just between nodes."""

    @pytest.fixture(autouse=True)
    def _clean_flag(self):
        from comfyui_distributed_tpu.runtime import interrupt as itr
        itr.clear_interrupt()
        yield
        itr.clear_interrupt()

    def _run(self, ds, steps=20, sampler="euler"):
        x0 = jnp.zeros((1, 4, 4, 3), jnp.float32)
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", steps))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(1, dtype=jnp.uint32))
        x = jnp.ones(x0.shape, jnp.float32) * sigmas[0]
        fn = smp.get_sampler(sampler)
        return x, fn(ideal_model(x0), x, sigmas, keys=keys)

    @pytest.mark.parametrize("name", smp.SAMPLER_NAMES)
    def test_interrupt_skips_all_steps(self, ds, name):
        """Flag set -> every scan iteration skips the model call; the
        latent comes back untouched (the partial-result semantics).
        Parametrized over ALL samplers: dpmpp_2m/_sde once had their own
        scans bypassing the polling _scan_sampler."""
        from comfyui_distributed_tpu.runtime import interrupt as itr
        itr.request_interrupt()
        x_in, out = self._run(ds, sampler=name)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x_in))

    def test_clear_resumes_normal_sampling(self, ds):
        x_in, out = self._run(ds)
        # ideal denoiser: converges to 0, far from the initial latent
        assert not np.allclose(np.asarray(out), np.asarray(x_in))
        np.testing.assert_allclose(np.asarray(out),
                                   np.zeros_like(np.asarray(out)), atol=1e-3)

    def test_mid_run_interrupt_returns_partial(self, ds):
        """The model sets the flag on its 3rd call (host callback): every
        later scan iteration must skip, so the result is exactly the
        3-step partial — deterministic proof the poll stops a sample
        mid-scan within one step."""
        from comfyui_distributed_tpu.runtime import interrupt as itr

        steps = 20
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", steps))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(1, dtype=jnp.uint32))
        x = jnp.ones((1, 4, 4, 3), jnp.float32) * sigmas[0]
        calls = []

        def model(xin, sigma, **kw):
            def cb(_x_seq):
                calls.append(1)
                if len(calls) == 3:
                    itr.request_interrupt()
                return np.float32(0.0)
            z = jax.pure_callback(cb, jax.ShapeDtypeStruct((), np.float32),
                                  xin.reshape(-1)[0])
            return jnp.zeros_like(xin) + z   # ideal denoiser to x0 = 0

        out = np.asarray(smp.sample_euler(model, x, sigmas, keys=keys))
        # euler to x0=0: x_{k+1} = x_k * s_{k+1}/s_k, stopped after 3 steps
        expect = np.asarray(x) * float(sigmas[3] / sigmas[0])
        np.testing.assert_allclose(out, expect, rtol=1e-4)
        assert len(calls) == 3   # steps 4..20 never called the model

    def test_preset_interrupt_never_calls_model_uni_pc(self, ds):
        """uni_pc's priming call runs OUTSIDE the scan: it must honor the
        poll too — an already-interrupted run pays ZERO model calls (the
        latent-untouched check alone can't see a wasted forward)."""
        from comfyui_distributed_tpu.runtime import interrupt as itr

        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 6))
        x = jnp.ones((1, 4, 4, 3), jnp.float32) * sigmas[0]
        calls = []

        def model(xin, sigma, **kw):
            z = jax.pure_callback(
                lambda _: (calls.append(1), np.float32(0.0))[1],
                jax.ShapeDtypeStruct((), np.float32), xin.reshape(-1)[0])
            return jnp.zeros_like(xin) + z

        itr.request_interrupt()
        out = np.asarray(smp.sample_uni_pc(model, x, sigmas))
        np.testing.assert_allclose(out, np.asarray(x))
        assert calls == []


class TestCFG:
    def test_cfg_interpolates(self):
        calls = []

        def model(x, sigma, context=None):
            calls.append(x.shape[0])
            # each batch row's "denoised" depends on its own context row
            per_row = jnp.mean(context, axis=(1, 2)).reshape(-1, 1, 1, 1)
            return jnp.ones_like(x) * per_row

        cond = jnp.ones((1, 2, 4)) * 2.0
        uncond = jnp.zeros((1, 2, 4))
        x = jnp.zeros((1, 4, 4, 2))
        wrapped = smp.cfg_denoiser(model, cond, uncond, cfg_scale=6.0)
        out = wrapped(x, jnp.asarray(1.0))
        # d_uncond=0, d_cond=2 -> 0 + (2-0)*6 = 12
        assert np.allclose(np.asarray(out), 12.0)
        assert calls == [2]  # one doubled-batch call

    def test_cfg_scale_one_single_call(self):
        def model(x, sigma, context=None):
            return jnp.ones_like(x) * context.shape[0]
        wrapped = smp.cfg_denoiser(model, jnp.ones((2, 2, 4)),
                                   jnp.zeros((2, 2, 4)), cfg_scale=1.0)
        out = wrapped(jnp.zeros((2, 4, 4, 1)), jnp.asarray(1.0))
        assert np.allclose(np.asarray(out), 2.0)  # context not doubled


class TestDenoiserPredictionTypes:
    """make_denoiser conventions: a model predicting the TRUE quantity
    (eps or v, VP parameterization) must denoise exactly back to x0."""

    def _setup(self, ds, sigma_val):
        rng = np.random.default_rng(11)
        x0 = jnp.asarray(rng.standard_normal((2, 4, 4, 3)), jnp.float32)
        noise = jnp.asarray(rng.standard_normal((2, 4, 4, 3)), jnp.float32)
        sigma = jnp.float32(sigma_val)
        x = x0 + sigma * noise
        return x0, noise, sigma, x

    @pytest.mark.parametrize("sigma_val", [0.5, 2.0, 7.0])
    def test_eps_prediction_recovers_x0(self, ds, sigma_val):
        from comfyui_distributed_tpu.models.denoiser import make_denoiser
        x0, noise, sigma, x = self._setup(ds, sigma_val)

        def apply_fn(params, xin, ts, ctx, y=None, control=None):
            return noise                     # the true eps

        den = make_denoiser(apply_fn, {}, ds, prediction_type="eps")
        np.testing.assert_allclose(np.asarray(den(x, sigma)),
                                   np.asarray(x0), rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("sigma_val", [0.5, 2.0, 7.0])
    def test_v_prediction_recovers_x0(self, ds, sigma_val):
        """VP v-target: v = alpha*eps - sigma_vp*x0 with
        alpha = 1/sqrt(sigma^2+1), sigma_vp = sigma*alpha (the SD2.x
        768-v parameterization)."""
        from comfyui_distributed_tpu.models.denoiser import make_denoiser
        x0, noise, sigma, x = self._setup(ds, sigma_val)
        alpha = 1.0 / jnp.sqrt(sigma ** 2 + 1.0)
        v_true = alpha * noise - (sigma * alpha) * x0

        def apply_fn(params, xin, ts, ctx, y=None, control=None):
            return v_true                    # the true v

        den = make_denoiser(apply_fn, {}, ds, prediction_type="v")
        np.testing.assert_allclose(np.asarray(den(x, sigma)),
                                   np.asarray(x0), rtol=1e-4, atol=1e-4)


class TestLoopOracles:
    """The scan/carry mechanics of the multistep and 2-call samplers vs
    straightforward per-step Python loops (where multistep bugs live):
    same model, same keys, same noise streams — allclose required.  The
    LMS loop integrates its coefficients with scipy.integrate.quad
    (k-diffusion's method), independently validating the in-graph
    Gauss-Legendre quadrature."""

    def _setup(self, ds, steps=7, b=2):
        import numpy as _np
        sigmas = np.asarray(sch.compute_sigmas(ds, "karras", steps),
                            _np.float64)
        rng = _np.random.default_rng(5)
        x = rng.standard_normal((b, 4, 4, 3)).astype(_np.float32) \
            * sigmas[0]
        keys = smp.sample_keys(_np.arange(b, dtype=_np.uint64) + 9)

        def model(xx, s, **kw):
            # nonlinear, sigma-dependent denoiser: exposes wrong-step
            # bugs an ideal (constant) model hides
            return jnp.tanh(xx) * (1.0 / (1.0 + s))

        return sigmas, jnp.asarray(x), keys, model

    @staticmethod
    def _anc(s, s_next, eta=1.0):
        import math
        su = min(s_next, eta * math.sqrt(
            max(s_next ** 2 * (s ** 2 - s_next ** 2) / s ** 2, 0.0)))
        sd = math.sqrt(max(s_next ** 2 - su ** 2, 0.0))
        return sd, su

    def test_dpmpp_sde_matches_loop(self, ds):
        import math
        sigmas, x0, keys, model = self._setup(ds)
        out = smp.sample_dpmpp_sde(model, x0, jnp.asarray(
            np.asarray(sigmas, np.float32)), keys=keys)
        noise_fn = smp.make_noise_fn(keys)
        x = np.asarray(x0, np.float64)
        r, fac = 0.5, 1.0
        for i in range(len(sigmas) - 1):
            s, s_next = sigmas[i], sigmas[i + 1]
            den = np.asarray(model(jnp.asarray(x, jnp.float32), s),
                             np.float64)
            if s_next == 0:
                x = x + (x - den) / s * (s_next - s)
                continue
            t = -math.log(s)
            h = -math.log(s_next) - t
            s_mid = math.exp(-(t + h * r))
            sd1, su1 = self._anc(s, s_mid)
            x2 = (sd1 / s) * (x - den) + den \
                + np.asarray(noise_fn(2 * i, x.shape[1:]), np.float64) * su1
            den2 = np.asarray(model(jnp.asarray(x2, jnp.float32), s_mid),
                              np.float64)
            sd2, su2 = self._anc(s, s_next)
            dd = (1 - fac) * den + fac * den2
            x = (sd2 / s) * (x - dd) + dd \
                + np.asarray(noise_fn(2 * i + 1, x.shape[1:]),
                             np.float64) * su2
        np.testing.assert_allclose(np.asarray(out), x, rtol=2e-4,
                                   atol=2e-4)

    def test_dpmpp_3m_sde_matches_loop(self, ds):
        import math
        sigmas, x0, keys, model = self._setup(ds, steps=9)
        out = smp.sample_dpmpp_3m_sde(model, x0, jnp.asarray(
            np.asarray(sigmas, np.float32)), keys=keys)
        noise_fn = smp.make_noise_fn(keys)
        x = np.asarray(x0, np.float64)
        eta = 1.0
        den_1 = den_2 = None
        h_1 = h_2 = None
        for i in range(len(sigmas) - 1):
            s, s_next = sigmas[i], sigmas[i + 1]
            den = np.asarray(model(jnp.asarray(x, jnp.float32), s),
                             np.float64)
            if s_next == 0:
                x = den
                continue
            h = math.log(s) - math.log(s_next)
            h_eta = h * (eta + 1.0)
            x = math.exp(-h_eta) * x - math.expm1(-h_eta) * den
            phi_2 = math.expm1(-h_eta) / h_eta + 1.0
            if h_2 is not None:
                r0, r1 = h_1 / h, h_2 / h
                d1_0 = (den - den_1) / r0
                d1_1 = (den_1 - den_2) / r1
                d1 = d1_0 + (d1_0 - d1_1) * r0 / (r0 + r1)
                d2 = (d1_0 - d1_1) / (r0 + r1)
                phi_3 = phi_2 / h_eta - 0.5
                x = x + phi_2 * d1 - phi_3 * d2
            elif h_1 is not None:
                x = x + phi_2 * ((den - den_1) / (h_1 / h))
            amt = s_next * math.sqrt(max(-math.expm1(-2 * h * eta), 0.0))
            x = x + np.asarray(noise_fn(i, x.shape[1:]), np.float64) * amt
            den_1, den_2 = den, den_1
            h_1, h_2 = h, h_1
        np.testing.assert_allclose(np.asarray(out), x, rtol=2e-4,
                                   atol=2e-4)

    def test_lms_matches_scipy_quad_loop(self, ds):
        from scipy import integrate
        sigmas, x0, keys, model = self._setup(ds, steps=8)
        out = smp.sample_lms(model, x0, jnp.asarray(
            np.asarray(sigmas, np.float32)))

        def coeff(order, t, i, j):
            def fn(tau):
                prod = 1.0
                for k in range(order):
                    if j == k:
                        continue
                    prod *= (tau - t[i - k]) / (t[i - j] - t[i - k])
                return prod
            return integrate.quad(fn, t[i], t[i + 1], epsrel=1e-6)[0]

        x = np.asarray(x0, np.float64)
        dhist = []
        for i in range(len(sigmas) - 1):
            den = np.asarray(model(jnp.asarray(x, jnp.float32), sigmas[i]),
                             np.float64)
            d = (x - den) / sigmas[i]
            dhist.append(d)
            if len(dhist) > 4:
                dhist.pop(0)
            cur = min(i + 1, 4)
            cs = [coeff(cur, sigmas, i, j) for j in range(cur)]
            x = x + sum(c * dd for c, dd in zip(cs, reversed(dhist)))
        np.testing.assert_allclose(np.asarray(out), x, rtol=2e-4,
                                   atol=2e-4)

    @staticmethod
    def _unipc_loop(sigmas, x0, model, variant):
        """Per-step Python UniPC loop with numpy solves (order ramp at
        both ends, corrector-eval reuse, predictor-only final step on a
        window ending above sigma 0)."""
        import math

        def m_of(xx, s):
            return np.asarray(model(jnp.asarray(xx, jnp.float32), s),
                              np.float64)

        n = len(sigmas) - 1
        x = np.asarray(x0, np.float64)
        m_list = [m_of(x, sigmas[0])]          # priming call
        for i in range(n):
            s, s_next = sigmas[i], sigmas[i + 1]
            m0 = m_list[-1]
            if s_next == 0:
                x = m0
                continue
            last_nonzero = i == n - 1          # window ending above 0
            order = min(i + 1, 3, n - i)
            lam0, lam_t = -math.log(s), -math.log(s_next)
            h = lam_t - lam0
            hh = -h
            h_phi_1 = math.expm1(hh)
            B_h = hh if variant == "bh1" else math.expm1(hh)
            rks, d1s = [], []
            for k in range(1, order):
                lam_k = -math.log(sigmas[i - k])
                rk = (lam_k - lam0) / h
                rks.append(rk)
                d1s.append((m_list[-1 - k] - m0) / rk)
            rks.append(1.0)
            b, h_phi_k, fact = [], h_phi_1 / hh - 1.0, 1.0
            for j in range(1, order + 1):
                b.append(h_phi_k * fact / B_h)
                fact *= j + 1
                h_phi_k = h_phi_k / hh - 1.0 / fact
            R = np.vander(np.asarray(rks), order, increasing=True).T
            x_t_ = (s_next / s) * x - h_phi_1 * m0
            if order == 1:
                x_pred = x_t_
            elif order == 2:
                x_pred = x_t_ - B_h * (0.5 * d1s[0])
            else:
                rhos_p = np.linalg.solve(R[:-1, :-1], np.asarray(b[:-1]))
                x_pred = x_t_ - B_h * sum(
                    rhos_p[k] * d1s[k] for k in range(order - 1))
            if last_nonzero:
                # reference: use_corrector=False on the last step of a
                # window ending above sigma 0 (predictor-only)
                x = x_pred
                continue
            m_t = m_of(x_pred, s_next)
            d1_t = m_t - m0
            if order == 1:
                corr = 0.5 * d1_t
            else:
                rhos_c = np.linalg.solve(R, np.asarray(b))
                corr = rhos_c[-1] * d1_t + sum(
                    rhos_c[k] * d1s[k] for k in range(order - 1))
            x = x_t_ - B_h * corr
            m_list.append(m_t)
        return x

    @pytest.mark.parametrize("variant", ["bh1", "bh2"])
    def test_uni_pc_matches_loop(self, ds, variant):
        """UniPC vs the Python loop oracle on a full schedule (ends at
        sigma 0)."""
        sigmas, x0, keys, model = self._setup(ds, steps=8)
        name = "uni_pc" if variant == "bh1" else "uni_pc_bh2"
        out = smp.get_sampler(name)(model, x0, jnp.asarray(
            np.asarray(sigmas, np.float32)))
        ref = self._unipc_loop(sigmas, x0, model, variant)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4,
                                   atol=3e-4)

    @pytest.mark.parametrize("variant", ["bh1", "bh2"])
    def test_uni_pc_truncated_window_matches_loop(self, ds, variant):
        """A schedule ending ABOVE sigma 0 (img2img-style window): the
        last update must be predictor-only (reference use_corrector=False
        on the final step)."""
        sigmas_full, x0, keys, model = self._setup(ds, steps=7)
        sigmas = sigmas_full[:-1]              # drop the trailing 0
        name = "uni_pc" if variant == "bh1" else "uni_pc_bh2"
        out = smp.get_sampler(name)(model, x0, jnp.asarray(
            np.asarray(sigmas, np.float32)))
        ref = self._unipc_loop(sigmas, x0, model, variant)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4,
                                   atol=3e-4)


class TestMultiCondCFG:
    """cfg_denoiser_multi (regional prompting): mask-weighted blend of
    per-entry denoised predictions before the CFG combine."""

    @staticmethod
    def _model():
        def model(x, sigma, context=None):
            per_row = jnp.mean(context, axis=(1, 2)).reshape(-1, 1, 1, 1)
            return jnp.ones_like(x) * per_row
        return model

    def test_mask_blend_and_cfg(self):
        B, h, w = 1, 4, 4
        cond_a = jnp.full((B, 7, 8), 1.0)
        cond_b = jnp.full((B, 7, 8), 3.0)
        unc = jnp.zeros((B, 7, 8))
        mask_a = jnp.zeros((1, h, w, 1)).at[:, :, :2].set(1.0)
        mask_b = 1.0 - mask_a
        f = smp.cfg_denoiser_multi(
            self._model(), [(cond_a, mask_a, 1.0), (cond_b, mask_b, 1.0)],
            unc, 2.0)
        out = np.asarray(f(jnp.zeros((B, h, w, 3)), jnp.asarray(1.0)))
        # left half: den_cond=1 -> 0 + (1-0)*2 = 2; right: 3 -> 6
        np.testing.assert_allclose(out[:, :, :2], 2.0, atol=1e-5)
        np.testing.assert_allclose(out[:, :, 2:], 6.0, atol=1e-5)

    def test_strengths_weight_overlap(self):
        """Overlapping masks: weighted mean by strength*mask."""
        B, h, w = 1, 2, 2
        cond_a = jnp.full((B, 7, 8), 2.0)
        cond_b = jnp.full((B, 7, 8), 6.0)
        unc = jnp.zeros((B, 7, 8))
        f = smp.cfg_denoiser_multi(
            self._model(), [(cond_a, None, 3.0), (cond_b, None, 1.0)],
            unc, 1.0)   # cfg=1: pure cond blend, no uncond row
        out = np.asarray(f(jnp.zeros((B, h, w, 3)), jnp.asarray(1.0)))
        np.testing.assert_allclose(out, (3 * 2 + 1 * 6) / 4.0, atol=1e-5)

    def test_single_entry_equals_plain_cfg(self):
        B, h, w = 2, 4, 4
        cond = jnp.full((B, 7, 8), 1.5)
        unc = jnp.zeros((B, 7, 8))
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (B, h, w, 3)).astype(np.float32))
        a = smp.cfg_denoiser(self._model(), cond, unc, 3.0)(
            x, jnp.asarray(1.0))
        b = smp.cfg_denoiser_multi(self._model(), [(cond, None, 1.0)],
                                   unc, 3.0)(x, jnp.asarray(1.0))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_timestep_range_gates_entries(self, ds):
        """ComfyUI prompt scheduling: an entry contributes only while
        sigma is inside its range; outside it the other entry takes
        over completely."""
        def model(x, sigma, context=None):
            per_row = jnp.mean(context, axis=(1, 2)).reshape(-1, 1, 1, 1)
            return jnp.ones_like(x) * per_row

        cond_a = jnp.full((1, 7, 8), 1.0)
        cond_b = jnp.full((1, 7, 8), 3.0)
        unc = jnp.zeros((1, 7, 8))
        # a active for sigma in [5, inf); b active for sigma in [0, 5]
        f = smp.cfg_denoiser_multi(
            model, [(cond_a, None, 1.0, (1e9, 5.0)),
                    (cond_b, None, 1.0, (5.0, 0.0))], unc, 1.0)
        hi = np.asarray(f(jnp.zeros((1, 2, 2, 3)), jnp.asarray(9.0)))
        lo = np.asarray(f(jnp.zeros((1, 2, 2, 3)), jnp.asarray(1.0)))
        np.testing.assert_allclose(hi, 1.0, atol=1e-5)   # only a
        np.testing.assert_allclose(lo, 3.0, atol=1e-5)   # only b
        # at the boundary both are active: equal-weight mean
        mid = np.asarray(f(jnp.zeros((1, 2, 2, 3)), jnp.asarray(5.0)))
        np.testing.assert_allclose(mid, 2.0, atol=1e-5)


class TestDdpmIpndmOracles:
    _setup = TestLoopOracles._setup   # shared fixture-free helper
    def test_ddpm_matches_loop(self, ds):
        import math
        sigmas, x0, keys, model = self._setup(ds, steps=7)
        out = smp.sample_ddpm(model, x0, jnp.asarray(
            np.asarray(sigmas, np.float32)), keys=keys)
        noise_fn = smp.make_noise_fn(keys)
        x = np.asarray(x0, np.float64)
        for i in range(len(sigmas) - 1):
            s, s_next = sigmas[i], sigmas[i + 1]
            den = np.asarray(model(jnp.asarray(x, jnp.float32), s),
                             np.float64)
            eps = (x - den) / s
            xs = x / math.sqrt(1.0 + s * s)
            ac = 1.0 / (s * s + 1.0)
            ac_prev = 1.0 / (s_next * s_next + 1.0)
            alpha = ac / ac_prev
            mu = math.sqrt(1.0 / alpha) * (
                xs - (1.0 - alpha) * eps / math.sqrt(1.0 - ac))
            if s_next > 0:
                std = math.sqrt((1.0 - alpha) * (1.0 - ac_prev)
                                / (1.0 - ac))
                mu = mu + np.asarray(noise_fn(i, x.shape[1:]),
                                     np.float64) * std
                x = mu * math.sqrt(1.0 + s_next * s_next)
            else:
                x = mu
        np.testing.assert_allclose(np.asarray(out), x, rtol=2e-4,
                                   atol=2e-4)

    def test_ipndm_matches_loop(self, ds):
        sigmas, x0, keys, model = self._setup(ds, steps=8)
        out = smp.sample_ipndm(model, x0, jnp.asarray(
            np.asarray(sigmas, np.float32)))
        coeffs = ((1.0,), (3 / 2, -1 / 2), (23 / 12, -16 / 12, 5 / 12),
                  (55 / 24, -59 / 24, 37 / 24, -9 / 24))
        x = np.asarray(x0, np.float64)
        hist = []
        for i in range(len(sigmas) - 1):
            s, s_next = sigmas[i], sigmas[i + 1]
            den = np.asarray(model(jnp.asarray(x, jnp.float32), s),
                             np.float64)
            d = (x - den) / s
            order = min(i + 1, 4)
            cs = coeffs[order - 1]
            upd = cs[0] * d
            for k in range(1, order):
                upd = upd + cs[k] * hist[-k]
            x = x + (s_next - s) * upd
            hist.append(d)
        np.testing.assert_allclose(np.asarray(out), x, rtol=2e-4,
                                   atol=2e-4)

    def test_rescale_cfg_math(self):
        """RescaleCFG vs a direct numpy port of the reference patch;
        multiplier=0 must equal plain CFG exactly."""
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((2, 4, 4, 3)), jnp.float32)
        dc = jnp.asarray(rng.standard_normal((2, 4, 4, 3)), jnp.float32)
        du = jnp.asarray(rng.standard_normal((2, 4, 4, 3)), jnp.float32)
        sigma, scale, mult = 3.0, 7.0, 0.6
        out = np.asarray(smp._rescale_cfg(x, jnp.asarray(sigma), dc, du,
                                          scale, mult))
        xn, dcn, dun = (np.asarray(a, np.float64) for a in (x, dc, du))
        s2 = sigma * sigma
        xs = xn / (s2 + 1.0)
        root = np.sqrt(s2 + 1.0)
        v_c = (xs - (xn - dcn)) * root / sigma
        v_u = (xs - (xn - dun)) * root / sigma
        v_cfg = v_u + (v_c - v_u) * scale
        ro_pos = v_c.std(axis=(1, 2, 3), keepdims=True)
        ro_cfg = v_cfg.std(axis=(1, 2, 3), keepdims=True)
        v_fin = mult * (v_cfg * ro_pos / ro_cfg) + (1 - mult) * v_cfg
        ref = xn - (xs - v_fin * sigma / root)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
        # multiplier path off == plain CFG
        cond = jnp.full((2, 7, 8), 1.5)
        unc = jnp.zeros((2, 7, 8))

        def model(xx, s, context=None):
            per = jnp.mean(context, axis=(1, 2)).reshape(-1, 1, 1, 1)
            return xx * 0.1 + per

        a = smp.cfg_denoiser_multi(model, [(cond, None, 1.0)], unc, scale,
                                   cfg_rescale=0.0)(x, jnp.asarray(sigma))
        b = smp.cfg_denoiser(model, cond, unc, scale)(x, jnp.asarray(sigma))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestNewSamplersRound4:
    """heunpp2 / ipndm_v / deis / dpm_fast / dpm_adaptive specifics
    beyond the all-sampler parametrized suites."""

    def test_ab_vs_coeffs_order2_closed_form(self):
        """Variable-step AB order-2 weights must equal the classic
        step-ratio formula c0=(2+r)/2, c1=-r/2 with r=h_n/h_{n-1}."""
        t_prev, t_cur, t_next = 10.0, 6.0, 3.0    # descending sigmas
        h_n = t_next - t_cur
        h_p = t_cur - t_prev
        c = smp._ab_vs_coeffs([jnp.float32(t_cur), jnp.float32(t_prev)],
                              jnp.float32(t_cur), jnp.float32(t_next))
        r = h_n / h_p
        np.testing.assert_allclose(float(c[0]), (2 + r) / 2, rtol=1e-6)
        np.testing.assert_allclose(float(c[1]), -r / 2, rtol=1e-6)

    def test_ab_vs_uniform_reduces_to_ipndm_table(self):
        """On a uniform grid the variable-step weights collapse to the
        classic Adams-Bashforth table (_IPNDM_COEFFS)."""
        ts = [jnp.float32(v) for v in (4.0, 5.0, 6.0, 7.0)]  # newest first
        c = smp._ab_vs_coeffs(ts, jnp.float32(4.0), jnp.float32(3.0))
        np.testing.assert_allclose([float(v) for v in c],
                                   smp._IPNDM_COEFFS[3], rtol=1e-5)
        # and ipndm_v == ipndm exactly on a uniform schedule
        x0 = jnp.full((1, 4, 4, 2), 0.4, jnp.float32)
        sigmas = jnp.linspace(8.0, 0.0, 9)
        x = jnp.ones_like(x0) * sigmas[0]
        a = smp.sample_ipndm(ideal_model(x0), x, sigmas)
        b = smp.sample_ipndm_v(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    def test_heunpp2_final_step_is_euler(self, ds):
        """A 1-step schedule must reduce heunpp2 to plain Euler."""
        x0 = jnp.full((1, 4, 4, 2), 0.3, jnp.float32)
        sigmas = jnp.asarray([5.0, 0.0], jnp.float32)
        x = jnp.ones_like(x0) * 5.0
        a = smp.sample_heunpp2(ideal_model(x0), x, sigmas)
        b = smp.sample_euler(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_dpm_fast_exact_on_linear_ode(self, ds):
        """Ideal denoiser: the trajectory is exactly x0 + sigma*c;
        DPM-Solver's expm1 updates integrate that ODE EXACTLY at every
        order, so dpm_fast must land on x0 + sigma_min*c to fp32."""
        x0 = jnp.zeros((1, 4, 4, 2), jnp.float32)
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 7))
        c = 1.0 / float(sigmas[0])
        x = jnp.ones_like(x0) * sigmas[0] * c
        out = smp.sample_dpm_fast(ideal_model(x0), x, sigmas)
        sig_min = float(sigmas[-2])
        np.testing.assert_allclose(np.asarray(out),
                                   np.full_like(np.asarray(out),
                                                sig_min * c),
                                   rtol=1e-4, atol=1e-5)

    def test_dpm_adaptive_converges_and_bounds_iters(self, ds):
        calls = []

        def counting_model(x, sigma, **kw):
            def cb(_):
                calls.append(1)
                return np.float32(0.0)
            z = jax.pure_callback(cb, jax.ShapeDtypeStruct((), np.float32),
                                  x.reshape(-1)[0])
            return jnp.zeros_like(x) + z

        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 10))
        x = jnp.ones((1, 4, 4, 2), jnp.float32) * sigmas[0]
        out = smp.sample_dpm_adaptive(counting_model, x, sigmas)
        assert np.all(np.abs(np.asarray(out)) < 0.12)
        assert 0 < len(calls) < 3 * 512   # PID accepted its way through

    def test_deis_three_history_converges_tight(self, ds):
        x0 = jnp.full((2, 4, 4, 3), -0.2, jnp.float32)
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "normal", 10))
        x = jnp.zeros_like(x0) + sigmas[0]
        out = smp.sample_deis(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0),
                                   atol=1e-3)


class TestSchedulerNodesRound4:
    """Scheduler node suite: Exponential/Polyexponential/VP/Laplace/
    Beta/AYS/SDTurbo + SplitSigmasDenoise."""

    def _op(self, name):
        from comfyui_distributed_tpu.ops.base import get_op
        return get_op(name)

    def _ctx(self):
        from comfyui_distributed_tpu.ops.base import OpContext
        return OpContext()

    def test_exponential_and_poly(self):
        octx = self._ctx()
        (e,) = self._op("ExponentialScheduler").execute(octx, 8, 10.0,
                                                        0.1)
        assert e.shape == (9,) and e[-1] == 0.0
        np.testing.assert_allclose(e[0], 10.0, rtol=1e-5)
        np.testing.assert_allclose(e[-2], 0.1, rtol=1e-5)
        # exponential == polyexponential at rho=1; rho=2 bends the ramp
        (p1,) = self._op("PolyexponentialScheduler").execute(
            octx, 8, 10.0, 0.1, 1.0)
        np.testing.assert_array_equal(e, p1)
        (p2,) = self._op("PolyexponentialScheduler").execute(
            octx, 8, 10.0, 0.1, 2.0)
        assert p2[4] < p1[4]        # rho>1 front-loads low sigmas
        # exact log-linear ramp: e[i] = exp(lerp(log 10, log 0.1, i/7))
        expect = np.exp(np.linspace(np.log(10.0), np.log(0.1), 8))
        np.testing.assert_allclose(e[:-1], expect, rtol=1e-5)

    def test_vp_and_laplace(self):
        octx = self._ctx()
        (v,) = self._op("VPScheduler").execute(octx, 10, 19.9, 0.1,
                                               0.001)
        assert v.shape == (11,) and v[-1] == 0.0
        assert np.all(np.diff(v[:-1]) < 0)
        (la,) = self._op("LaplaceScheduler").execute(octx, 10, 14.6,
                                                     0.03, 0.0, 0.5)
        assert la.shape == (11,) and la[-1] == 0.0
        assert la[0] <= 14.6 and la[-2] >= 0.03

    def test_beta_node_matches_scheduler(self, ds):
        octx = self._ctx()

        class _M:
            schedule = ds
        (b,) = self._op("BetaSamplingScheduler").execute(octx, _M(), 9,
                                                         0.6, 0.6)
        np.testing.assert_array_equal(
            b, np.asarray(sch.beta_scheduler(ds, 9, 0.6, 0.6),
                          np.float32))

    def test_ays_tables_and_denoise(self):
        octx = self._ctx()
        (s10,) = self._op("AlignYourStepsScheduler").execute(octx, "SD1",
                                                             10, 1.0)
        np.testing.assert_allclose(
            s10[:-1], sch.AYS_TABLES["SD1"][:-1], rtol=1e-5)
        assert s10[-1] == 0.0
        (s20,) = self._op("AlignYourStepsScheduler").execute(octx,
                                                             "SDXL", 20,
                                                             1.0)
        assert s20.shape == (21,)
        assert np.all(np.diff(s20[:-1]) < 0)
        (half,) = self._op("AlignYourStepsScheduler").execute(octx,
                                                              "SD1", 10,
                                                              0.5)
        assert half.shape == (6,)
        np.testing.assert_allclose(half[:-1], s10[5:-1], rtol=1e-6)
        with pytest.raises(ValueError):
            self._op("AlignYourStepsScheduler").execute(octx, "nope", 10,
                                                        1.0)

    def test_sd_turbo(self, ds):
        octx = self._ctx()

        class _M:
            schedule = ds
        (s1,) = self._op("SDTurboScheduler").execute(octx, _M(), 1, 1.0)
        assert s1.shape == (2,) and s1[-1] == 0.0
        np.testing.assert_allclose(s1[0], ds.sigmas[999], rtol=1e-6)
        (s4,) = self._op("SDTurboScheduler").execute(octx, _M(), 4, 1.0)
        assert s4.shape == (5,)
        np.testing.assert_allclose(
            s4[:-1], ds.sigmas[[999, 899, 799, 699]], rtol=1e-6)
        # denoise 0.5: starts mid-schedule (img2img for turbo)
        (sd,) = self._op("SDTurboScheduler").execute(octx, _M(), 2, 0.5)
        np.testing.assert_allclose(sd[0], ds.sigmas[499], rtol=1e-6)

    def test_split_sigmas_denoise(self):
        octx = self._ctx()
        sig = np.asarray([10, 8, 6, 4, 2, 0], np.float32)
        hi, lo = self._op("SplitSigmasDenoise").execute(octx, sig, 0.4)
        assert lo.shape == (3,)          # 2 of 5 steps kept
        np.testing.assert_array_equal(lo, sig[3:])
        np.testing.assert_array_equal(hi, sig[:4])
        hi1, lo1 = self._op("SplitSigmasDenoise").execute(octx, sig, 1.0)
        np.testing.assert_array_equal(lo1, sig)


class TestLatentArithmeticNodes:
    def _op(self, name):
        from comfyui_distributed_tpu.ops.base import get_op
        return get_op(name)

    def test_add_subtract_multiply_interpolate(self):
        from comfyui_distributed_tpu.ops.base import OpContext
        octx = OpContext()
        a = {"samples": np.full((1, 4, 4, 4), 2.0, np.float32),
             "fanout": 1, "local_batch": 1}
        b = {"samples": np.full((1, 4, 4, 4), 0.5, np.float32)}
        (add,) = self._op("LatentAdd").execute(octx, a, b)
        np.testing.assert_allclose(add["samples"], 2.5)
        (sub,) = self._op("LatentSubtract").execute(octx, a, b)
        np.testing.assert_allclose(sub["samples"], 1.5)
        (mul,) = self._op("LatentMultiply").execute(octx, a, 0.25)
        np.testing.assert_allclose(mul["samples"], 0.5)
        # interpolate: ratio 1 -> exactly a; ratio 0 -> exactly b
        (i1,) = self._op("LatentInterpolate").execute(octx, a, b, 1.0)
        np.testing.assert_allclose(i1["samples"], 2.0, rtol=1e-5)
        (i0,) = self._op("LatentInterpolate").execute(octx, a, b, 0.0)
        np.testing.assert_allclose(i0["samples"], 0.5, rtol=1e-5)
        # parallel directions: magnitudes lerp
        (ih,) = self._op("LatentInterpolate").execute(octx, a, b, 0.5)
        np.testing.assert_allclose(ih["samples"], 1.25, rtol=1e-5)


class TestCFGPlusPlus:
    def test_reduces_to_euler_without_cfg_wrapper(self, ds):
        """A bare model has no uncond side-channel: CFG++ falls back to
        the denoised anchor and the update equals plain euler exactly
        (x' = den + s_next*(x-den)/s == x + d*(s_next - s))."""
        x0 = jnp.full((1, 4, 4, 2), 0.3, jnp.float32)
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 6))
        x = jnp.ones_like(x0) * sigmas[0]
        a = smp.sample_euler_cfg_pp(ideal_model(x0), x, sigmas)
        b = smp.sample_euler(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_uses_the_uncond_direction_under_cfg(self, ds):
        """With a CFG wrapper whose cond and uncond denoise to different
        targets, the step direction must come from the UNCOND (the
        reference's post-cfg uncond_denoised), not the CFG result."""
        cond_t = jnp.full((1, 4, 4, 2), 0.5, jnp.float32)
        unc_t = jnp.full((1, 4, 4, 2), -0.5, jnp.float32)

        def raw(x, sigma, context=None, **kw):
            # rows: [cond; uncond] — pretend contexts select targets
            B = x.shape[0] // 2
            return jnp.concatenate(
                [jnp.broadcast_to(cond_t, (B,) + cond_t.shape[1:]),
                 jnp.broadcast_to(unc_t, (B,) + unc_t.shape[1:])])

        cfg = smp.cfg_denoiser(raw, jnp.zeros((1, 7, 8)),
                               jnp.zeros((1, 7, 8)), 3.0)
        sigmas = jnp.asarray([4.0, 2.0], jnp.float32)
        x = jnp.zeros((1, 4, 4, 2), jnp.float32) + 4.0
        out = smp.sample_euler_cfg_pp(cfg, x, sigmas)
        den = np.asarray(unc_t + (cond_t - unc_t) * 3.0)  # CFG result
        expect = den + (np.asarray(x) - np.asarray(unc_t)) / 4.0 * 2.0
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)

    def test_ancestral_variant_stochastic_contract(self, ds):
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "normal", 4))
        x = jnp.zeros((1, 2, 2, 1))
        with pytest.raises(ValueError):
            smp.sample_euler_ancestral_cfg_pp(ideal_model(x), x, sigmas)


class TestCFGPlusPlusGuiderCoverage:
    def test_ancestral_eta0_equals_euler_cfg_pp(self, ds):
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 6))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(1,
                                                       dtype=jnp.uint32))
        x0 = jnp.full((1, 4, 4, 2), 0.4, jnp.float32)
        x = jnp.ones_like(x0) * sigmas[0]
        a = smp.sample_euler_ancestral_cfg_pp(ideal_model(x0), x,
                                              sigmas, keys=keys,
                                              eta=0.0)
        b = smp.sample_euler_cfg_pp(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_dual_and_perp_wrappers_expose_uncond(self):
        cond_t = jnp.full((1, 4, 4, 2), 0.5, jnp.float32)
        unc_t = jnp.full((1, 4, 4, 2), -0.5, jnp.float32)

        def raw3(x, sigma, context=None, **kw):
            B = x.shape[0] // 3
            t = lambda v: jnp.broadcast_to(v, (B,) + v.shape[1:])  # noqa
            return jnp.concatenate([t(cond_t), t(jnp.zeros_like(cond_t)),
                                    t(unc_t)])

        c = jnp.zeros((1, 7, 8))
        dual = smp.cfg_denoiser_dual(raw3, c, c, c, 2.0, 1.5)
        dual(jnp.zeros((1, 4, 4, 2)), jnp.asarray(3.0))
        np.testing.assert_allclose(np.asarray(dual.last_uncond),
                                   np.asarray(unc_t))
        perp = smp.cfg_denoiser_perp_neg(raw3, c, c, c, 2.0, 1.0)
        perp(jnp.zeros((1, 4, 4, 2)), jnp.asarray(3.0))
        np.testing.assert_allclose(np.asarray(perp.last_uncond),
                                   np.asarray(unc_t))


class TestRound5SamplerLongTail:
    """res_multistep / gradient_estimation / er_sde / sa_solver /
    seeds_2 / seeds_3 (VERDICT r4 #7) — behavioral contracts beyond the
    all-sampler parametrized suites."""

    def _setup(self, ds, steps=8, b=2):
        x0 = jnp.asarray(np.random.default_rng(9).standard_normal(
            (b, 4, 4, 2)).astype(np.float32)) * 0.4
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", steps))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(b, dtype=jnp.uint32))
        x = jax.random.normal(jax.random.PRNGKey(2), x0.shape) * sigmas[0]
        return x0, sigmas, keys, x

    def test_gradient_estimation_equals_euler_for_ideal_model(self, ds):
        """For a constant-x0 denoiser the step directions coincide, so
        the gamma-extrapolation is exact and the trajectory IS euler."""
        x0, sigmas, keys, x = self._setup(ds)
        a = smp.sample_gradient_estimation(ideal_model(x0), x, sigmas)
        b = smp.sample_euler(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_res_multistep_second_order_beats_euler(self, ds):
        """On a sigma-curved denoiser (denoised bends with sigma) the
        2nd-order multistep lands closer to the true limit than euler at
        the same step count."""
        x0 = jnp.full((1, 4, 4, 2), 0.5, jnp.float32)

        def curved(x, sigma, **kw):
            s = jnp.reshape(sigma, (-1,) + (1,) * (x.ndim - 1))
            return x0 * (1.0 + 0.3 * jnp.tanh(s))

        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 6))
        x = jnp.ones_like(x0) * sigmas[0]
        # the true sigma->0 limit of the curved target is x0
        err_res = np.abs(np.asarray(
            smp.sample_res_multistep(curved, x, sigmas)) - 0.5).max()
        err_euler = np.abs(np.asarray(
            smp.sample_euler(curved, x, sigmas)) - 0.5).max()
        assert err_res <= err_euler + 1e-6, (err_res, err_euler)

    def test_sa_solver_corrector_beats_predictor_only(self, ds):
        """The PECE corrector evaluation must tighten the same curved
        trajectory vs the predictor-only res_multistep path."""
        x0 = jnp.full((1, 4, 4, 2), 0.5, jnp.float32)

        def curved(x, sigma, **kw):
            s = jnp.reshape(sigma, (-1,) + (1,) * (x.ndim - 1))
            return x0 * (1.0 + 0.3 * jnp.tanh(s))

        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 6))
        x = jnp.ones_like(x0) * sigmas[0]
        err_sa = np.abs(np.asarray(
            smp.sample_sa_solver(curved, x, sigmas)) - 0.5).max()
        err_res = np.abs(np.asarray(
            smp.sample_res_multistep(curved, x, sigmas)) - 0.5).max()
        assert err_sa <= err_res + 1e-6, (err_sa, err_res)

    @pytest.mark.parametrize("name", ["seeds_2", "seeds_3", "er_sde"])
    def test_stochastic_deterministic_given_keys(self, ds, name):
        x0, sigmas, keys, x = self._setup(ds)
        fn = smp.get_sampler(name)
        a = fn(ideal_model(x0), x, sigmas, keys=keys)
        b = fn(ideal_model(x0), x, sigmas, keys=keys)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("name", ["seeds_2", "seeds_3"])
    def test_seeds_eta_zero_is_deterministic_no_keys(self, ds, name):
        """eta=0 degenerates to the deterministic exponential RK — no
        keys needed, and repeated runs are bit-identical."""
        x0, sigmas, _, x = self._setup(ds)
        fn = smp.get_sampler(name)
        a = fn(ideal_model(x0), x, sigmas, eta=0.0)
        b = fn(ideal_model(x0), x, sigmas, eta=0.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(a), np.asarray(x0),
                                   atol=1e-3)

    @pytest.mark.parametrize("name", ["seeds_2", "seeds_3", "er_sde"])
    def test_distinct_keys_distinct_trajectories(self, ds, name):
        """Per-sample noise streams: different keys diverge mid-run
        (stopped before sigma 0 so the noise isn't annihilated)."""
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "normal", 8))[:5]
        keys_a = jax.vmap(jax.random.PRNGKey)(jnp.asarray([1, 2],
                                                          jnp.uint32))
        keys_b = jax.vmap(jax.random.PRNGKey)(jnp.asarray([3, 4],
                                                          jnp.uint32))
        x = jnp.zeros((2, 4, 4, 1)) + sigmas[0]
        x0 = jnp.zeros((2, 4, 4, 1))
        fn = smp.get_sampler(name)
        a = fn(ideal_model(x0), x, sigmas, keys=keys_a)
        b = fn(ideal_model(x0), x, sigmas, keys=keys_b)
        assert not np.allclose(np.asarray(a), np.asarray(b))

    def test_ksampler_runs_the_long_tail_end_to_end(self, ds):
        """The registry path (static-key jit cache, CFG wrapper, noise
        plumbing) accepts every new sampler name."""
        from comfyui_distributed_tpu.models import registry
        from comfyui_distributed_tpu.ops.base import (Conditioning,
                                                      OpContext, get_op)
        registry.clear_pipeline_cache()
        import os
        os.environ["DTPU_DEFAULT_FAMILY"] = "tiny"
        try:
            pipe = registry.load_pipeline("longtail.ckpt")
            pos = Conditioning(context=pipe.encode_prompt(["x"])[0])
            lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
            for name in ("res_multistep", "gradient_estimation", "er_sde",
                         "sa_solver", "seeds_2", "seeds_3"):
                (out,) = get_op("KSampler").execute(
                    OpContext(), pipe, 3, 2, 3.0, name, "normal", pos,
                    pos, lat, 1.0)
                assert np.isfinite(np.asarray(out["samples"])).all(), name
        finally:
            os.environ.pop("DTPU_DEFAULT_FAMILY", None)
            registry.clear_pipeline_cache()


class TestCfgPpLongTailVariants:
    """res_multistep_cfg_pp / _ancestral(_cfg_pp) / dpmpp_2m_cfg_pp:
    exact reductions + the uncond side-channel engaging."""

    def _x(self, ds, steps=8):
        x0 = jnp.full((1, 4, 4, 2), 0.4, jnp.float32)
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", steps))
        x = jnp.ones_like(x0) * sigmas[0]
        return x0, sigmas, x

    def test_cfg_pp_variants_reduce_to_plain_for_bare_model(self, ds):
        x0, sigmas, x = self._x(ds)
        a = smp.sample_res_multistep_cfg_pp(ideal_model(x0), x, sigmas)
        b = smp.sample_res_multistep(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
        c = smp.sample_dpmpp_2m_cfg_pp(ideal_model(x0), x, sigmas)
        d = smp.sample_dpmpp_2m(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(c), np.asarray(d),
                                   rtol=1e-5, atol=1e-6)

    def test_ancestral_eta_zero_equals_deterministic(self, ds):
        x0, sigmas, x = self._x(ds)
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(1, dtype=jnp.uint32))
        a = smp.sample_res_multistep_ancestral(ideal_model(x0), x,
                                               sigmas, keys=keys, eta=0.0)
        b = smp.sample_res_multistep(ideal_model(x0), x, sigmas)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_cfg_pp_reads_the_uncond_side_channel(self, ds):
        """Under a CFG wrapper with distinct cond/uncond targets the
        CFG++ variant departs from the plain sampler."""
        cond_t = jnp.full((1, 4, 4, 2), 0.5, jnp.float32)
        unc_t = jnp.full((1, 4, 4, 2), -0.5, jnp.float32)

        def raw(x, sigma, context=None, **kw):
            B = x.shape[0] // 2
            return jnp.concatenate(
                [jnp.broadcast_to(cond_t, (B,) + cond_t.shape[1:]),
                 jnp.broadcast_to(unc_t, (B,) + unc_t.shape[1:])])

        cfg = smp.cfg_denoiser(raw, jnp.zeros((1, 7, 8)),
                               jnp.zeros((1, 7, 8)), 3.0)
        # STOP at a nonzero sigma: the stub denoises to a constant, so
        # the final x=denoised step would erase the trajectory split
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "karras", 6))[:4]
        x = jnp.zeros((1, 4, 4, 2), jnp.float32) + sigmas[0]
        for pp, plain in ((smp.sample_res_multistep_cfg_pp,
                           smp.sample_res_multistep),
                          (smp.sample_dpmpp_2m_cfg_pp,
                           smp.sample_dpmpp_2m)):
            a = pp(cfg, x, sigmas)
            b = plain(cfg, x, sigmas)
            assert not np.allclose(np.asarray(a), np.asarray(b)), pp

    def test_ancestral_keyed_noise_contract(self, ds):
        sigmas = jnp.asarray(sch.compute_sigmas(ds, "normal", 8))[:5]
        ka = jax.vmap(jax.random.PRNGKey)(jnp.asarray([1, 2], jnp.uint32))
        kb = jax.vmap(jax.random.PRNGKey)(jnp.asarray([3, 4], jnp.uint32))
        x = jnp.zeros((2, 4, 4, 1)) + sigmas[0]
        x0 = jnp.zeros((2, 4, 4, 1))
        fn = smp.sample_res_multistep_ancestral_cfg_pp
        a = fn(ideal_model(x0), x, sigmas, keys=ka)
        b = fn(ideal_model(x0), x, sigmas, keys=kb)
        assert not np.allclose(np.asarray(a), np.asarray(b))
        with pytest.raises(ValueError):
            fn(ideal_model(x0), x, sigmas)
