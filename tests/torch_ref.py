"""Hand-written torch reference models in the CANONICAL SD checkpoint
layout (CompVis/LDM module structure, attribute names = checkpoint keys).

These are the parity oracles for ``tests/test_torch_parity.py``: they encode
the torch ecosystem's conventions — NCHW, skip-concat order, the VAE's
asymmetric downsample padding, GroupNorm eps (1e-5 UNet / 1e-6 VAE and
spatial-transformer norms), exact-erf GELU — independently of the flax
implementation, so a convention bug in either the flax modules or the
checkpoint converter shows up as a numeric mismatch instead of silently
producing a "working" model that can't load real weights.

Tiny hyperparameters only (tests run them on CPU in seconds); the layout
logic is size-independent.
"""

from __future__ import annotations

import math

import torch
import torch.nn.functional as F
from torch import nn


def _groups(c: int) -> int:
    g = min(32, c)
    while c % g:
        g -= 1
    return g


def norm_unet(c: int) -> nn.GroupNorm:      # openaimodel GroupNorm32
    return nn.GroupNorm(_groups(c), c, eps=1e-5)


def norm_vae(c: int) -> nn.GroupNorm:       # CompVis Normalize
    return nn.GroupNorm(_groups(c), c, eps=1e-6)


def timestep_embedding(t: torch.Tensor, dim: int) -> torch.Tensor:
    half = dim // 2
    freqs = torch.exp(-math.log(10000.0)
                      * torch.arange(half, dtype=torch.float32) / half)
    args = t.float()[:, None] * freqs[None]
    return torch.cat([torch.cos(args), torch.sin(args)], dim=-1)


# --- UNet building blocks (ldm.modules.diffusionmodules.openaimodel) --------

class ResBlock(nn.Module):
    def __init__(self, cin: int, cout: int, time_dim: int):
        super().__init__()
        self.in_layers = nn.Sequential(
            norm_unet(cin), nn.SiLU(), nn.Conv2d(cin, cout, 3, padding=1))
        self.emb_layers = nn.Sequential(nn.SiLU(), nn.Linear(time_dim, cout))
        self.out_layers = nn.Sequential(
            norm_unet(cout), nn.SiLU(), nn.Dropout(0.0),
            nn.Conv2d(cout, cout, 3, padding=1))
        self.skip_connection = nn.Conv2d(cin, cout, 1) if cin != cout \
            else nn.Identity()

    def forward(self, x, emb):
        h = self.in_layers(x)
        h = h + self.emb_layers(emb)[:, :, None, None]
        h = self.out_layers(h)
        return self.skip_connection(x) + h


class CrossAttention(nn.Module):
    def __init__(self, query_dim: int, context_dim: int, heads: int):
        super().__init__()
        inner = query_dim
        self.heads = heads
        self.scale = (inner // heads) ** -0.5
        self.to_q = nn.Linear(query_dim, inner, bias=False)
        self.to_k = nn.Linear(context_dim, inner, bias=False)
        self.to_v = nn.Linear(context_dim, inner, bias=False)
        self.to_out = nn.Sequential(nn.Linear(inner, query_dim),
                                    nn.Dropout(0.0))

    def forward(self, x, context=None):
        ctx = x if context is None else context
        B, N, C = x.shape
        H = self.heads
        q = self.to_q(x).reshape(B, N, H, C // H).permute(0, 2, 1, 3)
        k = self.to_k(ctx).reshape(B, ctx.shape[1], H, C // H).permute(0, 2, 1, 3)
        v = self.to_v(ctx).reshape(B, ctx.shape[1], H, C // H).permute(0, 2, 1, 3)
        sim = torch.einsum("bhnd,bhmd->bhnm", q, k) * self.scale
        attn = sim.softmax(dim=-1)
        out = torch.einsum("bhnm,bhmd->bhnd", attn, v)
        out = out.permute(0, 2, 1, 3).reshape(B, N, C)
        return self.to_out(out)


class GEGLU(nn.Module):
    def __init__(self, dim_in: int, dim_out: int):
        super().__init__()
        self.proj = nn.Linear(dim_in, dim_out * 2)

    def forward(self, x):
        a, gate = self.proj(x).chunk(2, dim=-1)
        return a * F.gelu(gate)     # exact erf gelu (torch default)


class FeedForward(nn.Module):
    def __init__(self, dim: int):
        super().__init__()
        self.net = nn.Sequential(GEGLU(dim, dim * 4), nn.Dropout(0.0),
                                 nn.Linear(dim * 4, dim))

    def forward(self, x):
        return self.net(x)


class BasicTransformerBlock(nn.Module):
    def __init__(self, dim: int, context_dim: int, heads: int):
        super().__init__()
        self.attn1 = CrossAttention(dim, dim, heads)
        self.ff = FeedForward(dim)
        self.attn2 = CrossAttention(dim, context_dim, heads)
        self.norm1 = nn.LayerNorm(dim)
        self.norm2 = nn.LayerNorm(dim)
        self.norm3 = nn.LayerNorm(dim)

    def forward(self, x, context):
        x = self.attn1(self.norm1(x)) + x
        x = self.attn2(self.norm2(x), context=context) + x
        x = self.ff(self.norm3(x)) + x
        return x


class SpatialTransformer(nn.Module):
    """SD1.x conv form (proj_in/out 1x1 convs) or SD2.x/SDXL linear form
    (``use_linear_in_transformer``)."""

    def __init__(self, c: int, context_dim: int, heads: int, depth: int,
                 use_linear: bool = False):
        super().__init__()
        self.use_linear = use_linear
        self.norm = norm_vae(c)          # attention.py Normalize: eps 1e-6
        self.proj_in = nn.Linear(c, c) if use_linear else nn.Conv2d(c, c, 1)
        self.transformer_blocks = nn.ModuleList(
            [BasicTransformerBlock(c, context_dim, heads)
             for _ in range(depth)])
        self.proj_out = nn.Linear(c, c) if use_linear else nn.Conv2d(c, c, 1)

    def forward(self, x, context):
        B, C, H, W = x.shape
        x_in = x
        h = self.norm(x)
        if self.use_linear:
            h = h.reshape(B, C, H * W).permute(0, 2, 1)
            h = self.proj_in(h)
        else:
            h = self.proj_in(h)
            h = h.reshape(B, C, H * W).permute(0, 2, 1)   # b, hw, c
        for blk in self.transformer_blocks:
            h = blk(h, context)
        if self.use_linear:
            h = self.proj_out(h)
            h = h.permute(0, 2, 1).reshape(B, C, H, W)
        else:
            h = h.permute(0, 2, 1).reshape(B, C, H, W)
            h = self.proj_out(h)
        return x_in + h


class Downsample(nn.Module):
    def __init__(self, c: int):
        super().__init__()
        self.op = nn.Conv2d(c, c, 3, stride=2, padding=1)

    def forward(self, x):
        return self.op(x)


class Upsample(nn.Module):
    def __init__(self, c: int):
        super().__init__()
        self.conv = nn.Conv2d(c, c, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2, mode="nearest"))


class TorchUNet(nn.Module):
    """LDM UNet at arbitrary (tiny) hyperparameters, canonical keys."""

    def __init__(self, model_channels=32, channel_mult=(1, 2),
                 num_res_blocks=1, transformer_depth=(1, 1),
                 context_dim=64, num_head_channels=16,
                 in_channels=4, out_channels=4,
                 adm_in_channels=None, use_linear=False):
        super().__init__()
        mc = model_channels
        time_dim = mc * 4
        self.time_embed = nn.Sequential(
            nn.Linear(mc, time_dim), nn.SiLU(),
            nn.Linear(time_dim, time_dim))
        if adm_in_channels is not None:
            # SDXL vector conditioning — keys label_emb.0.{0,2}
            self.label_emb = nn.Sequential(nn.Sequential(
                nn.Linear(adm_in_channels, time_dim), nn.SiLU(),
                nn.Linear(time_dim, time_dim)))
        self.model_channels = mc

        def heads(c):
            return max(c // num_head_channels, 1)

        def st(c, depth):
            return SpatialTransformer(c, context_dim, heads(c), depth,
                                      use_linear=use_linear)

        self.input_blocks = nn.ModuleList(
            [nn.Sequential(nn.Conv2d(in_channels, mc, 3, padding=1))])
        ch = mc
        for level, mult in enumerate(channel_mult):
            out_ch = mc * mult
            for _ in range(num_res_blocks):
                mods = [ResBlock(ch, out_ch, time_dim)]
                ch = out_ch
                if transformer_depth[level] > 0:
                    mods.append(st(ch, transformer_depth[level]))
                self.input_blocks.append(nn.Sequential(*mods))
            if level != len(channel_mult) - 1:
                self.input_blocks.append(nn.Sequential(Downsample(ch)))

        self.middle_block = nn.Sequential(
            ResBlock(ch, ch, time_dim),
            st(ch, max(transformer_depth[-1], 1)),
            ResBlock(ch, ch, time_dim))

        # skip channels per input block, for up-path concat widths
        skip_chs = [mc]
        c = mc
        for level, mult in enumerate(channel_mult):
            for _ in range(num_res_blocks):
                c = mc * mult
                skip_chs.append(c)
            if level != len(channel_mult) - 1:
                skip_chs.append(c)

        self.output_blocks = nn.ModuleList()
        for level in reversed(range(len(channel_mult))):
            out_ch = mc * channel_mult[level]
            for i in range(num_res_blocks + 1):
                mods = [ResBlock(ch + skip_chs.pop(), out_ch, time_dim)]
                ch = out_ch
                if transformer_depth[level] > 0:
                    mods.append(st(ch, transformer_depth[level]))
                if level != 0 and i == num_res_blocks:
                    mods.append(Upsample(ch))
                self.output_blocks.append(nn.Sequential(*mods))

        self.out = nn.Sequential(norm_unet(ch), nn.SiLU(),
                                 nn.Conv2d(ch, out_channels, 3, padding=1))

    def forward(self, x, timesteps, context, y=None):
        emb = self.time_embed(timestep_embedding(timesteps,
                                                 self.model_channels))
        if y is not None:
            emb = emb + self.label_emb(y)
        hs = []
        h = x
        for block in self.input_blocks:
            for mod in block:
                if isinstance(mod, ResBlock):
                    h = mod(h, emb)
                elif isinstance(mod, SpatialTransformer):
                    h = mod(h, context)
                else:
                    h = mod(h)
            hs.append(h)
        for mod in self.middle_block:
            h = mod(h, emb) if isinstance(mod, ResBlock) else mod(h, context)
        for block in self.output_blocks:
            h = torch.cat([h, hs.pop()], dim=1)
            for mod in block:
                if isinstance(mod, ResBlock):
                    h = mod(h, emb)
                elif isinstance(mod, SpatialTransformer):
                    h = mod(h, context)
                else:
                    h = mod(h)
        return self.out(h)


# --- VAE (ldm.modules.diffusionmodules.model / AutoencoderKL) ---------------

class VAEResnetBlock(nn.Module):
    def __init__(self, cin: int, cout: int):
        super().__init__()
        self.norm1 = norm_vae(cin)
        self.conv1 = nn.Conv2d(cin, cout, 3, padding=1)
        self.norm2 = norm_vae(cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            self.nin_shortcut = nn.Conv2d(cin, cout, 1)

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "nin_shortcut"):
            x = self.nin_shortcut(x)
        return x + h


class VAEAttnBlock(nn.Module):
    def __init__(self, c: int):
        super().__init__()
        self.norm = norm_vae(c)
        self.q = nn.Conv2d(c, c, 1)
        self.k = nn.Conv2d(c, c, 1)
        self.v = nn.Conv2d(c, c, 1)
        self.proj_out = nn.Conv2d(c, c, 1)

    def forward(self, x):
        B, C, H, W = x.shape
        h = self.norm(x)
        q = self.q(h).reshape(B, C, H * W).permute(0, 2, 1)
        k = self.k(h).reshape(B, C, H * W)
        w = torch.bmm(q, k) * C ** -0.5
        w = w.softmax(dim=2)
        v = self.v(h).reshape(B, C, H * W)
        out = torch.bmm(v, w.permute(0, 2, 1)).reshape(B, C, H, W)
        return x + self.proj_out(out)


class VAEDownsample(nn.Module):
    def __init__(self, c: int):
        super().__init__()
        self.conv = nn.Conv2d(c, c, 3, stride=2, padding=0)

    def forward(self, x):
        return self.conv(F.pad(x, (0, 1, 0, 1)))   # right/bottom only


class VAEUpsample(nn.Module):
    def __init__(self, c: int):
        super().__init__()
        self.conv = nn.Conv2d(c, c, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2, mode="nearest"))


class _Level(nn.Module):
    def __init__(self):
        super().__init__()
        self.block = nn.ModuleList()


class VAEEncoder(nn.Module):
    def __init__(self, ch=16, ch_mult=(1, 2), num_res=1, z=4):
        super().__init__()
        self.conv_in = nn.Conv2d(3, ch, 3, padding=1)
        self.down = nn.ModuleList()
        cin = ch
        for level, mult in enumerate(ch_mult):
            lv = _Level()
            cout = ch * mult
            for _ in range(num_res):
                lv.block.append(VAEResnetBlock(cin, cout))
                cin = cout
            if level != len(ch_mult) - 1:
                lv.downsample = VAEDownsample(cin)
            self.down.append(lv)
        self.mid = nn.Module()
        self.mid.block_1 = VAEResnetBlock(cin, cin)
        self.mid.attn_1 = VAEAttnBlock(cin)
        self.mid.block_2 = VAEResnetBlock(cin, cin)
        self.norm_out = norm_vae(cin)
        self.conv_out = nn.Conv2d(cin, 2 * z, 3, padding=1)

    def forward(self, x):
        h = self.conv_in(x)
        for lv in self.down:
            for blk in lv.block:
                h = blk(h)
            if hasattr(lv, "downsample"):
                h = lv.downsample(h)
        h = self.mid.block_2(self.mid.attn_1(self.mid.block_1(h)))
        return self.conv_out(F.silu(self.norm_out(h)))


class VAEDecoder(nn.Module):
    def __init__(self, ch=16, ch_mult=(1, 2), num_res=1, z=4):
        super().__init__()
        cin = ch * ch_mult[-1]
        self.conv_in = nn.Conv2d(z, cin, 3, padding=1)
        self.mid = nn.Module()
        self.mid.block_1 = VAEResnetBlock(cin, cin)
        self.mid.attn_1 = VAEAttnBlock(cin)
        self.mid.block_2 = VAEResnetBlock(cin, cin)
        self.up = nn.ModuleList([_Level() for _ in ch_mult])
        for level in reversed(range(len(ch_mult))):
            lv = self.up[level]
            cout = ch * ch_mult[level]
            for _ in range(num_res + 1):
                lv.block.append(VAEResnetBlock(cin, cout))
                cin = cout
            if level != 0:
                lv.upsample = VAEUpsample(cin)
        self.norm_out = norm_vae(cin)
        self.conv_out = nn.Conv2d(cin, 3, 3, padding=1)

    def forward(self, z):
        h = self.conv_in(z)
        h = self.mid.block_2(self.mid.attn_1(self.mid.block_1(h)))
        for level in reversed(range(len(self.up))):
            lv = self.up[level]
            for blk in lv.block:
                h = blk(h)
            if hasattr(lv, "upsample"):
                h = lv.upsample(h)
        return self.conv_out(F.silu(self.norm_out(h)))


class TorchVAE(nn.Module):
    def __init__(self, ch=16, ch_mult=(1, 2), num_res=1, z=4,
                 scaling_factor=0.18215):
        super().__init__()
        self.encoder = VAEEncoder(ch, ch_mult, num_res, z)
        self.decoder = VAEDecoder(ch, ch_mult, num_res, z)
        self.quant_conv = nn.Conv2d(2 * z, 2 * z, 1)
        self.post_quant_conv = nn.Conv2d(z, z, 1)
        self.sf = scaling_factor

    def encode(self, images01):
        moments = self.quant_conv(self.encoder(images01 * 2.0 - 1.0))
        mean, _ = moments.chunk(2, dim=1)
        return mean * self.sf

    def decode(self, latents):
        dec = self.decoder(self.post_quant_conv(latents / self.sf))
        return ((dec + 1.0) / 2.0).clamp(0.0, 1.0)


# --- ESRGAN / Real-ESRGAN RRDBNet (xinntao layout, realesrgan naming) -------

class ResidualDenseBlock(nn.Module):
    def __init__(self, feat: int, growth: int):
        super().__init__()
        for i in range(5):
            cout = feat if i == 4 else growth
            setattr(self, f"conv{i + 1}",
                    nn.Conv2d(feat + i * growth, cout, 3, padding=1))

    def forward(self, x):
        feats = [x]
        for i in range(4):
            h = getattr(self, f"conv{i + 1}")(torch.cat(feats, dim=1))
            feats.append(F.leaky_relu(h, 0.2))
        out = self.conv5(torch.cat(feats, dim=1))
        return x + out * 0.2


class RRDB(nn.Module):
    def __init__(self, feat: int, growth: int):
        super().__init__()
        self.rdb1 = ResidualDenseBlock(feat, growth)
        self.rdb2 = ResidualDenseBlock(feat, growth)
        self.rdb3 = ResidualDenseBlock(feat, growth)

    def forward(self, x):
        return x + self.rdb3(self.rdb2(self.rdb1(x))) * 0.2


class TorchRRDBNet(nn.Module):
    """Real-ESRGAN naming (conv_first/body/conv_body/conv_up*/conv_hr/
    conv_last) — one of the three schemes the loader normalizes."""

    def __init__(self, feat=16, num_blocks=2, growth=8, scale=2):
        super().__init__()
        self.scale = scale
        self.conv_first = nn.Conv2d(3, feat, 3, padding=1)
        self.body = nn.ModuleList(
            [RRDB(feat, growth) for _ in range(num_blocks)])
        self.conv_body = nn.Conv2d(feat, feat, 3, padding=1)
        n_up = {1: 0, 2: 1, 4: 2, 8: 3}[scale]
        for i in range(n_up):
            setattr(self, f"conv_up{i + 1}",
                    nn.Conv2d(feat, feat, 3, padding=1))
        self.n_up = n_up
        self.conv_hr = nn.Conv2d(feat, feat, 3, padding=1)
        self.conv_last = nn.Conv2d(feat, 3, 3, padding=1)

    def forward(self, x):
        fea = self.conv_first(x)
        h = fea
        for blk in self.body:
            h = blk(h)
        h = fea + self.conv_body(h)
        for i in range(self.n_up):
            h = F.interpolate(h, scale_factor=2, mode="nearest")
            h = F.leaky_relu(getattr(self, f"conv_up{i + 1}")(h), 0.2)
        h = F.leaky_relu(self.conv_hr(h), 0.2)
        return self.conv_last(h)


class _OpenClipBlock(nn.Module):
    def __init__(self, width, heads):
        super().__init__()
        self.ln_1 = nn.LayerNorm(width)
        # nn.MultiheadAttention serializes exactly the open_clip layout:
        # packed in_proj_weight/in_proj_bias + out_proj
        self.attn = nn.MultiheadAttention(width, heads, batch_first=True)
        self.ln_2 = nn.LayerNorm(width)
        from collections import OrderedDict
        self.mlp = nn.Sequential(OrderedDict([
            ("c_fc", nn.Linear(width, width * 4)),
            ("gelu", nn.GELU()),                    # exact erf form
            ("c_proj", nn.Linear(width * 4, width)),
        ]))

    def forward(self, x, attn_mask):
        h = self.ln_1(x)
        a, _ = self.attn(h, h, h, need_weights=False, attn_mask=attn_mask)
        x = x + a
        return x + self.mlp(self.ln_2(x))


class _OpenClipTransformer(nn.Module):
    def __init__(self, width, layers, heads):
        super().__init__()
        self.resblocks = nn.ModuleList(
            [_OpenClipBlock(width, heads) for _ in range(layers)])


class TorchOpenClipText(nn.Module):
    """open_clip text tower in FrozenOpenCLIPEmbedder serialization
    (SD2.x ``cond_stage_model.model.*``, SDXL's bigG embedder): resblocks
    with packed q/k/v, raw ``positional_embedding``/``text_projection``
    parameters, causal ``attn_mask`` buffer."""

    def __init__(self, vocab, width, layers, heads, ctx_len=77, proj=None):
        super().__init__()
        self.token_embedding = nn.Embedding(vocab, width)
        self.positional_embedding = nn.Parameter(
            torch.empty(ctx_len, width).normal_(std=0.01))
        self.transformer = _OpenClipTransformer(width, layers, heads)
        self.ln_final = nn.LayerNorm(width)
        self.text_projection = nn.Parameter(
            torch.empty(width, proj or width).normal_(std=0.02))
        self.register_buffer(
            "attn_mask",
            torch.full((ctx_len, ctx_len), float("-inf")).triu_(1))

    def forward(self, ids):
        """Returns the per-layer hidden states (pre-ln_final)."""
        x = self.token_embedding(ids) \
            + self.positional_embedding[:ids.shape[1]]
        m = self.attn_mask[:ids.shape[1], :ids.shape[1]]
        hidden = []
        for blk in self.transformer.resblocks:
            x = blk(x, m)
            hidden.append(x)
        return hidden


class TorchControlNet(nn.Module):
    """Canonical torch ControlNet layout (control_model.*): the TorchUNet
    encoder + input_hint_block ladder + per-skip zero_convs +
    middle_block_out."""

    def __init__(self, model_channels=32, channel_mult=(1, 2),
                 num_res_blocks=1, transformer_depth=(1, 1),
                 context_dim=64, num_head_channels=16, in_channels=4,
                 hint_channels=(16, 16, 32, 32, 96, 96, 256),
                 hint_strides=(1, 1, 2, 1, 2, 1, 2)):
        super().__init__()
        mc = model_channels
        time_dim = mc * 4
        self.model_channels = mc
        self.time_embed = nn.Sequential(
            nn.Linear(mc, time_dim), nn.SiLU(),
            nn.Linear(time_dim, time_dim))

        mods = []
        cin = 3
        for hc, st_ in zip(hint_channels, hint_strides):
            mods += [nn.Conv2d(cin, hc, 3, padding=1, stride=st_),
                     nn.SiLU()]
            cin = hc
        final = nn.Conv2d(cin, mc, 3, padding=1)
        nn.init.zeros_(final.weight), nn.init.zeros_(final.bias)
        mods.append(final)
        self.input_hint_block = nn.Sequential(*mods)

        def heads(c):
            return max(c // num_head_channels, 1)

        def st(c, depth):
            return SpatialTransformer(c, context_dim, heads(c), depth)

        def zc(c):
            conv = nn.Conv2d(c, c, 1)
            nn.init.zeros_(conv.weight), nn.init.zeros_(conv.bias)
            return nn.Sequential(conv)

        self.input_blocks = nn.ModuleList(
            [nn.Sequential(nn.Conv2d(in_channels, mc, 3, padding=1))])
        self.zero_convs = nn.ModuleList([zc(mc)])
        ch = mc
        for level, mult in enumerate(channel_mult):
            out_ch = mc * mult
            for _ in range(num_res_blocks):
                blk = [ResBlock(ch, out_ch, time_dim)]
                ch = out_ch
                if transformer_depth[level] > 0:
                    blk.append(st(ch, transformer_depth[level]))
                self.input_blocks.append(nn.Sequential(*blk))
                self.zero_convs.append(zc(ch))
            if level != len(channel_mult) - 1:
                self.input_blocks.append(nn.Sequential(Downsample(ch)))
                self.zero_convs.append(zc(ch))

        self.middle_block = nn.Sequential(
            ResBlock(ch, ch, time_dim),
            st(ch, max(transformer_depth[-1], 1)),
            ResBlock(ch, ch, time_dim))
        mo = nn.Conv2d(ch, ch, 1)
        nn.init.zeros_(mo.weight), nn.init.zeros_(mo.bias)
        self.middle_block_out = nn.Sequential(mo)

    def forward(self, x, timesteps, context, hint):
        emb = self.time_embed(timestep_embedding(timesteps,
                                                 self.model_channels))
        guided = self.input_hint_block(hint)
        outs = []
        h = x
        for i, block in enumerate(self.input_blocks):
            for mod in block:
                if isinstance(mod, ResBlock):
                    h = mod(h, emb)
                elif isinstance(mod, SpatialTransformer):
                    h = mod(h, context)
                else:
                    h = mod(h)
            if i == 0:
                h = h + guided
            outs.append(self.zero_convs[i](h))
        for mod in self.middle_block:
            h = mod(h, emb) if isinstance(mod, ResBlock) else mod(h, context)
        return outs, self.middle_block_out(h)
