"""Worker process manager + master-death monitor."""

import os
import subprocess
import sys
import time

import pytest

from comfyui_distributed_tpu.runtime import manager as mgr_mod
from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils.process import is_process_alive

SLEEPER = [sys.executable, "-c", "import time; time.sleep(60)"]


@pytest.fixture
def manager(tmp_path, monkeypatch):
    m = mgr_mod.WorkerProcessManager(
        config_path=str(tmp_path / "cfg.json"),
        log_dir=str(tmp_path / "logs"))
    # don't spawn real worker servers in unit tests
    monkeypatch.setattr(m, "build_launch_command", lambda w: list(SLEEPER))
    yield m
    m.cleanup_all()


class TestManager:
    def test_launch_tracks_and_stops(self, manager):
        entry = manager.launch_worker({"id": "w1", "name": "t", "port": 1},
                                      stop_on_master_exit=False)
        assert is_process_alive(entry["pid"])
        managed = manager.get_managed_workers()
        assert managed["w1"]["alive"] is True
        assert managed["w1"]["launching"] is True
        manager.clear_launching("w1")
        assert manager.get_managed_workers()["w1"]["launching"] is False
        assert manager.stop_worker("w1") is True
        assert not is_process_alive(entry["pid"])
        assert manager.stop_worker("w1") is False

    def test_double_launch_conflict(self, manager):
        manager.launch_worker({"id": "w1", "port": 1},
                              stop_on_master_exit=False)
        with pytest.raises(RuntimeError, match="already running"):
            manager.launch_worker({"id": "w1", "port": 1},
                                  stop_on_master_exit=False)

    def test_pid_persistence_revive_and_purge(self, manager, tmp_path):
        entry = manager.launch_worker({"id": "w1", "port": 1},
                                      stop_on_master_exit=False)
        cfg = cfg_mod.load_config(str(tmp_path / "cfg.json"))
        assert cfg["managed_processes"]["w1"]["pid"] == entry["pid"]
        # stale entry purged on load
        cfg["managed_processes"]["dead"] = {"pid": 999999}
        cfg_mod.save_config(cfg, str(tmp_path / "cfg.json"))
        m2 = mgr_mod.WorkerProcessManager(
            config_path=str(tmp_path / "cfg.json"),
            log_dir=str(tmp_path / "logs"))
        assert "w1" in m2.processes          # revived (alive)
        assert "dead" not in m2.processes    # purged
        m2.processes.pop("w1", None)         # owner is `manager` fixture

    def test_log_written_and_tailed(self, manager):
        manager.launch_worker({"id": "w1", "name": "logtest", "port": 1},
                              stop_on_master_exit=False)
        text = manager.tail_log("w1")
        assert "=== session" in text
        with pytest.raises(FileNotFoundError):
            manager.tail_log("nope")

    def test_auto_launch_respects_settings(self, manager, tmp_path):
        cfg = cfg_mod.load_config(str(tmp_path / "cfg.json"))
        cfg_mod.upsert_worker(cfg, {"id": "w1", "port": 1, "enabled": True})
        cfg_mod.upsert_worker(cfg, {"id": "remote", "port": 2,
                                    "enabled": True, "host": "10.0.0.9"})
        cfg_mod.update_setting(cfg, "auto_launch_workers", True)
        cfg_mod.save_config(cfg, str(tmp_path / "cfg.json"))
        t = mgr_mod.auto_launch_workers(manager, delay=0.01)
        t.join(timeout=5)
        time.sleep(0.2)
        managed = manager.get_managed_workers()
        assert "w1" in managed        # local enabled -> launched
        assert "remote" not in managed  # remote never auto-launched


class TestMonitor:
    def test_monitor_kills_worker_when_master_dies(self, tmp_path):
        """Full wrapper flow (reference worker_monitor.py:92-103): fake
        master dies -> monitor terminates the worker and exits."""
        fake_master = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(2)"])
        mon = subprocess.Popen(
            [sys.executable, "-m",
             "comfyui_distributed_tpu.runtime.monitor",
             "--master-pid", str(fake_master.pid), "--"] + SLEEPER,
            env={**os.environ, "PYTHONPATH": "/root/repo"})
        try:
            fake_master.wait(timeout=10)
            mon.wait(timeout=15)
            assert mon.returncode == 0
        finally:
            if mon.poll() is None:
                mon.kill()

    def test_monitor_propagates_worker_exit(self, tmp_path):
        mon = subprocess.Popen(
            [sys.executable, "-m",
             "comfyui_distributed_tpu.runtime.monitor",
             "--master-pid", str(os.getpid()), "--",
             sys.executable, "-c", "import sys; sys.exit(7)"],
            env={**os.environ, "PYTHONPATH": "/root/repo"})
        mon.wait(timeout=15)
        assert mon.returncode == 7


def test_put_tile_requires_existing_queue():
    """Regression: late tile posts after queue removal must be rejected, not
    resurrect an orphan queue (unbounded memory on a long-running master)."""
    import asyncio
    from comfyui_distributed_tpu.runtime.jobs import JobStore

    async def run():
        store = JobStore()
        assert not await store.put_tile("gone", {"tile_idx": 0})
        await store.get_tile_queue("live")
        assert await store.put_tile("live", {"tile_idx": 0})
        await store.remove_tile_queue("live")
        assert not await store.put_tile("live", {"tile_idx": 1})
        assert store.snapshot()["tile_jobs"] == []

    asyncio.run(run())


class TestHealthPoller:
    def test_poll_derives_status(self, tmp_path, monkeypatch):
        """online / processing / offline / disabled derivation (reference
        checkWorkerStatus, gpupanel.js:1249-1311)."""
        from comfyui_distributed_tpu.runtime import health as health_mod
        from comfyui_distributed_tpu.utils import config as cfg_mod

        cfg = cfg_mod.load_config()
        cfg["workers"] = [
            {"id": "up", "port": 1, "enabled": True},
            {"id": "busy", "port": 2, "enabled": True},
            {"id": "down", "port": 3, "enabled": True},
            {"id": "off", "port": 4, "enabled": False},
        ]
        cfg_mod.save_config(cfg)

        def fake_probe(worker, timeout=2.0):
            wid = worker["id"]
            if wid == "up":
                return {"status": "online", "queue_remaining": 0,
                        "last_seen": 1.0}
            if wid == "busy":
                return {"status": "processing", "queue_remaining": 2,
                        "last_seen": 1.0}
            return {"status": "offline", "queue_remaining": None,
                    "last_seen": None}

        monkeypatch.setattr(health_mod, "probe_worker", fake_probe)

        class FakeManager:
            cleared = []

            def clear_launching(self, wid):
                self.cleared.append(wid)

        mgr = FakeManager()
        poller = health_mod.HealthPoller(manager=mgr)
        snap = poller.poll_once()
        assert snap["up"]["status"] == "online"
        assert snap["busy"]["status"] == "processing"
        assert snap["down"]["status"] == "offline"
        assert snap["off"]["status"] == "disabled"
        # first contact clears 'launching' for reachable workers only
        assert sorted(mgr.cleared) == ["busy", "up"]
        assert poller.snapshot() == snap

    def test_probe_worker_offline(self):
        from comfyui_distributed_tpu.runtime.health import probe_worker
        st = probe_worker({"id": "x", "port": 1}, timeout=0.2)
        assert st["status"] == "offline"


class TestInterruptPolling:
    def test_polling_compiles_out_on_no_callback_backends(self, monkeypatch):
        """The axon PJRT plugin raises UNIMPLEMENTED for host callbacks;
        polling_enabled() must gate on the backend (BENCH r4 failure) with
        DTPU_INTERRUPT_POLL as a hard override in both directions."""
        import jax

        from comfyui_distributed_tpu.runtime import interrupt as itr
        monkeypatch.delenv("DTPU_INTERRUPT_POLL", raising=False)
        monkeypatch.setattr(jax, "default_backend", lambda: "axon")
        assert itr.polling_enabled() is False
        monkeypatch.setenv("DTPU_INTERRUPT_POLL", "1")
        assert itr.polling_enabled() is True
        monkeypatch.setenv("DTPU_INTERRUPT_POLL", "0")
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert itr.polling_enabled() is False
        monkeypatch.delenv("DTPU_INTERRUPT_POLL")
        assert itr.polling_enabled() is True
