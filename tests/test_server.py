"""Control/data-plane HTTP API (reference route surface, SURVEY.md §2)."""

import asyncio
import base64
import json
import os

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils.image import decode_png, encode_png


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


def run_with_client(fn, tmp_path, **state_kw):
    """Spin the app in a private event loop and run the async test body."""
    async def go():
        state = ServerState(
            config_path=str(tmp_path / "cfg.json"),
            input_dir=str(tmp_path / "input"),
            output_dir=str(tmp_path / "output"),
            **state_kw)
        app = build_app(state)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await fn(client, state)
        finally:
            await client.close()
    return asyncio.run(go())


class TestConfigRoutes:
    def test_config_crud(self, tmp_path):
        async def body(client, state):
            r = await client.get("/distributed/config")
            assert r.status == 200
            assert (await r.json())["workers"] == []

            r = await client.post("/distributed/config/update_worker",
                                  json={"id": "w1", "name": "n", "port": 9000,
                                        "enabled": True})
            assert r.status == 200
            r = await client.post("/distributed/config/update_worker",
                                  json={"id": "w1", "name": None})
            cfg = await (await client.get("/distributed/config")).json()
            assert "name" not in cfg["workers"][0]

            r = await client.post("/distributed/config/update_setting",
                                  json={"key": "debug", "value": True})
            assert r.status == 200
            r = await client.post("/distributed/config/update_master",
                                  json={"host": "1.2.3.4"})
            cfg = await (await client.get("/distributed/config")).json()
            assert cfg["master"]["host"] == "1.2.3.4"
            assert cfg["settings"]["debug"] is True

            r = await client.post("/distributed/config/delete_worker",
                                  json={"id": "w1"})
            assert r.status == 200
            r = await client.post("/distributed/config/delete_worker",
                                  json={"id": "w1"})
            assert r.status == 404

            r = await client.post("/distributed/config/update_worker",
                                  json={"name": "no id"})
            assert r.status == 400
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestInfoRoutes:
    def test_network_info_status_metrics(self, tmp_path):
        async def body(client, state):
            info = await (await client.get("/distributed/network_info")).json()
            assert "recommended_ip" in info
            st = await (await client.get("/distributed/status")).json()
            assert st["num_devices"] == 8
            assert st["queue_remaining"] == 0
            m = await (await client.get("/distributed/metrics")).json()
            assert m["prompts_executed"] == 0
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_clear_memory(self, tmp_path):
        async def body(client, state):
            r = await client.post("/distributed/clear_memory")
            assert r.status == 200
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestDataPlane:
    def test_prepare_then_job_complete(self, tmp_path, rng):
        async def body(client, state):
            r = await client.post("/distributed/prepare_job",
                                  json={"multi_job_id": "j1"})
            assert r.status == 200

            img = rng.random((1, 8, 8, 3)).astype(np.float32)
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("multi_job_id", "j1")
            form.add_field("worker_id", "worker_0")
            form.add_field("image_index", "0")
            form.add_field("is_last", "true")
            form.add_field("image", encode_png(img), filename="i.png",
                           content_type="image/png")
            r = await client.post("/distributed/job_complete", data=form)
            assert r.status == 200

            q = await state.jobs.get_queue("j1")
            item = q.get_nowait()
            assert item["worker_id"] == "worker_0"
            assert item["is_last"] is True
            assert item["tensor"].shape == (1, 8, 8, 3)
            np.testing.assert_allclose(item["tensor"], img, atol=1 / 255 + 1e-6)
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_job_complete_unknown_job_404(self, tmp_path, rng):
        async def body(client, state):
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("multi_job_id", "nope")
            form.add_field("image", encode_png(
                rng.random((1, 4, 4, 3)).astype(np.float32)),
                filename="i.png", content_type="image/png")
            r = await client.post("/distributed/job_complete", data=form)
            assert r.status == 404
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_tile_complete_and_queue_status(self, tmp_path, rng):
        async def body(client, state):
            r = await client.get("/distributed/queue_status",
                                 params={"multi_job_id": "t1"})
            assert (await r.json())["exists"] is False

            import aiohttp
            png = encode_png(rng.random((1, 8, 8, 3)).astype(np.float32))

            def mkform():  # FormData payloads are single-use
                form = aiohttp.FormData()
                form.add_field("multi_job_id", "t1")
                form.add_field("worker_id", "worker_0")
                form.add_field("tile_idx", "3")
                form.add_field("x", "64")
                form.add_field("y", "0")
                form.add_field("extracted_width", "96")
                form.add_field("extracted_height", "96")
                form.add_field("is_last", "true")
                form.add_field("tile", png, filename="t.png",
                               content_type="image/png")
                return form

            # unknown tile job -> 404 (worker retry loop backs off; the
            # master pre-creates the queue before dispatch)
            r = await client.post("/distributed/tile_complete", data=mkform())
            assert r.status == 404

            await state.jobs.get_tile_queue("t1")  # master-side pre-create
            r = await client.post("/distributed/tile_complete", data=mkform())
            assert r.status == 200

            r = await client.get("/distributed/queue_status",
                                 params={"multi_job_id": "t1"})
            assert (await r.json())["exists"] is True
            q = await state.jobs.get_tile_queue("t1")
            item = q.get_nowait()
            assert item["tile_idx"] == 3 and item["x"] == 64
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_load_image_staging(self, tmp_path, rng):
        async def body(client, state):
            os.makedirs(state.input_dir, exist_ok=True)
            img = rng.random((1, 8, 8, 3)).astype(np.float32)
            with open(os.path.join(state.input_dir, "x.png"), "wb") as f:
                f.write(encode_png(img))
            r = await client.post("/distributed/load_image",
                                  json={"image_name": "x.png"})
            assert r.status == 200
            data = await r.json()
            back = decode_png(base64.b64decode(data["image_data"]))
            assert back.shape == (1, 8, 8, 3)

            r = await client.post("/distributed/load_image",
                                  json={"image_name": "missing.png"})
            assert r.status == 404
            r = await client.post("/distributed/load_image",
                                  json={"image_name": "../../etc/passwd"})
            assert r.status in (400, 404)
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_upload_image(self, tmp_path, rng):
        async def body(client, state):
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("image", encode_png(
                rng.random((1, 4, 4, 3)).astype(np.float32)),
                filename="up.png", content_type="image/png")
            r = await client.post("/upload/image", data=form)
            assert r.status == 200
            assert os.path.exists(os.path.join(state.input_dir, "up.png"))
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestCollectorDedup:
    def test_retransmitted_upload_not_duplicated(self, tmp_path, rng):
        """ADVICE r1: the worker send path retries with backoff, so a
        timed-out-but-delivered job_complete POST arrives twice; the master
        must key results by (worker, image_index), not append."""
        import threading
        from comfyui_distributed_tpu.ops.base import OpContext
        from comfyui_distributed_tpu.ops.distributed import DistributedCollector
        from comfyui_distributed_tpu.runtime.jobs import JobStore

        loop = asyncio.new_event_loop()
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        try:
            store = JobStore()
            img0 = rng.random((1, 4, 4, 3)).astype(np.float32)
            img1 = rng.random((1, 4, 4, 3)).astype(np.float32)

            async def seed():
                await store.prepare_job("j1")
                for idx, tensor, last in ((0, img0, False), (0, img0, False),
                                          (1, img1, True)):
                    await store.put_result("j1", {
                        "worker_id": "worker_1", "image_index": idx,
                        "tensor": tensor, "is_last": last})

            asyncio.run_coroutine_threadsafe(seed(), loop).result(10)
            ctx = OpContext(job_store=store, server_loop=loop)
            master = rng.random((1, 4, 4, 3)).astype(np.float32)
            (out,) = DistributedCollector().execute(
                ctx, master, multi_job_id="j1",
                enabled_worker_ids='["worker_1"]')
            # 1 master + 2 distinct worker images — not 4
            assert out.shape[0] == 3
            np.testing.assert_allclose(out[1], img0[0], atol=1e-6)
            np.testing.assert_allclose(out[2], img1[0], atol=1e-6)
        finally:
            loop.call_soon_threadsafe(loop.stop)
            t.join(5)


class TestPromptSurface:
    def test_get_prompt_health(self, tmp_path):
        async def body(client, state):
            r = await client.get("/prompt")
            assert (await r.json())["exec_info"]["queue_remaining"] == 0
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_post_prompt_executes(self, tmp_path):
        """Full /prompt -> exec queue -> history flow with a tiny graph."""
        prompt = {
            "7": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": "tiny.safetensors"}},
            "5": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "cat", "clip": ["7", 1]}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "", "clip": ["7", 1]}},
            "9": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "8": {"class_type": "KSampler",
                  "inputs": {"model": ["7", 0], "positive": ["5", 0],
                             "negative": ["6", 0], "latent_image": ["9", 0],
                             "seed": 1, "steps": 1, "cfg": 1.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0}},
            "1": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
            "3": {"class_type": "PreviewImage",
                  "inputs": {"images": ["1", 0]}},
        }

        async def body(client, state):
            r = await client.post("/prompt", json={"prompt": prompt,
                                                   "client_id": "t"})
            assert r.status == 200
            pid = (await r.json())["prompt_id"]
            for _ in range(1800):  # generous: exec thread may be compiling
                hist = await (await client.get("/history")).json()
                if pid in hist:
                    assert hist[pid]["status"] == "success", hist[pid]
                    assert hist[pid]["images"] == 1
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("prompt never finished")
            m = await (await client.get("/distributed/metrics")).json()
            assert m["prompts_executed"] == 1
        run_with_client(body, tmp_path, start_exec_thread=True)

    def test_post_prompt_missing(self, tmp_path):
        async def body(client, state):
            r = await client.post("/prompt", json={})
            assert r.status == 400
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_interrupt(self, tmp_path):
        async def body(client, state):
            from comfyui_distributed_tpu.runtime import interrupt as itr
            r = await client.post("/interrupt")
            assert r.status == 200
            assert state.interrupt_event.is_set()
            # the server's event IS the process-global flag that compiled
            # samplers poll per step (runtime/interrupt.py) — so /interrupt
            # reaches a sample already inside its lax.scan
            assert itr.is_interrupted()
            itr.clear_interrupt()
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestPanel:
    def test_panel_serves_html(self, tmp_path):
        async def body(client, state):
            r = await client.get("/panel")
            assert r.status == 200
            assert "text/html" in r.headers.get("Content-Type", "")
            text = await r.text()
            # drives the existing JSON routes, no external deps
            for needle in ("/distributed/workers_status", "_worker",
                           "/distributed/metrics", "<script>"):
                assert needle in text, needle
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_panel_settings_form_contract(self, tmp_path):
        """The panel's settings form (reference settings dialog analog)
        drives exactly these routes with exactly these payload shapes —
        exercise them the way the form does (no browser in CI)."""
        async def body(client, state):
            text = await (await client.get("/panel")).text()
            for needle in ("config/update_worker", "config/delete_worker",
                           "config/update_setting", "config/update_master",
                           "saveWorker", "wf-port"):
                assert needle in text, needle
            # saveWorker(): upsert with explicit nulls for cleared fields
            r = await client.post("/distributed/config/update_worker",
                                  json={"id": "p1", "name": "p1",
                                        "port": 18999, "host": None,
                                        "extra_args": None})
            assert r.status == 200
            w = (await r.json())["worker"]
            assert w["port"] == 18999 and "host" not in w
            # settings checkbox + master host field
            r = await client.post("/distributed/config/update_setting",
                                  json={"key": "debug", "value": True})
            assert r.status == 200
            r = await client.post("/distributed/config/update_master",
                                  json={"host": "10.0.0.9"})
            assert r.status == 200
            cfg = await (await client.get("/distributed/config")).json()
            assert cfg["settings"]["debug"] is True
            assert cfg["master"]["host"] == "10.0.0.9"
            # delete button path
            r = await client.post("/distributed/config/delete_worker",
                                  json={"id": "p1"})
            assert r.status == 200
            cfg = await (await client.get("/distributed/config")).json()
            assert all(x["id"] != "p1" for x in cfg["workers"])
        run_with_client(body, tmp_path, start_exec_thread=False)


    def test_panel_js_endpoints_exist_in_route_table(self, tmp_path):
        """VERDICT r3 #8: every endpoint string the panel's JS fetches
        must resolve against the app's actual route table — a renamed
        route must fail THIS test, not a user's browser session."""
        import re

        async def body(client, state):
            text = await (await client.get("/panel")).text()
            # endpoint literals in quotes or template strings, query/
            # template suffix stripped
            paths = set()
            for m in re.findall(
                    r"[\"'`](/(?:distributed|prompt|interrupt|panel)"
                    r"[A-Za-z0-9_/]*)", text):
                paths.add(m)
            assert len(paths) >= 10, sorted(paths)  # the panel is rich
            table = set()
            for route in client.server.app.router.routes():
                info = route.resource.get_info() if route.resource else {}
                table.add(info.get("path") or info.get("formatter") or "")
            missing = []
            for p in sorted(paths):
                if p.endswith("/"):
                    # a concatenation base ('/distributed/' + kind + ...):
                    # some routed path must extend it
                    if not any(t.startswith(p) for t in table):
                        missing.append(p)
                elif p not in table:
                    missing.append(p)
            assert not missing, f"panel JS fetches unrouted: {missing}"
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_panel_checkbox_and_null_host_semantics(self, tmp_path):
        """VERDICT r3 #8: the enable-checkbox's exact contract, by direct
        endpoint calls.  The checkbox posts ONLY {id, enabled}: a partial
        upsert must flip the flag without clobbering other fields; a
        rejected post must leave config unchanged (that atomicity is what
        makes the JS revert-on-reject correct); update_master with an
        explicit null host clears it (the autodetect mode)."""
        async def body(client, state):
            r = await client.post("/distributed/config/update_worker",
                                  json={"id": "cb1", "name": "worker one",
                                        "port": 18901, "host": "10.0.0.2",
                                        "enabled": True})
            assert r.status == 200
            # the checkbox's exact payload: partial update
            r = await client.post("/distributed/config/update_worker",
                                  json={"id": "cb1", "enabled": False})
            assert r.status == 200
            cfg = await (await client.get("/distributed/config")).json()
            (w,) = [x for x in cfg["workers"] if x["id"] == "cb1"]
            assert w["enabled"] is False
            assert w["name"] == "worker one" and w["port"] == 18901 \
                and w["host"] == "10.0.0.2"   # untouched fields preserved
            # reject path: no id -> 400 and NOTHING changed (the panel's
            # .catch() reverts the checkbox; server must not half-apply)
            r = await client.post("/distributed/config/update_worker",
                                  json={"enabled": True})
            assert r.status == 400
            cfg2 = await (await client.get("/distributed/config")).json()
            assert cfg2["workers"] == cfg["workers"]
            # master host: explicit null clears (autodetect mode)
            r = await client.post("/distributed/config/update_master",
                                  json={"host": "10.9.9.9"})
            assert r.status == 200
            r = await client.post("/distributed/config/update_master",
                                  json={"host": None})
            assert r.status == 200
            cfg3 = await (await client.get("/distributed/config")).json()
            assert not cfg3["master"].get("host")
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestLifecycleRoutes:
    def test_launch_unknown_worker_404(self, tmp_path):
        async def body(client, state):
            r = await client.post("/distributed/launch_worker",
                                  json={"id": "zzz"})
            assert r.status == 404
            r = await client.post("/distributed/stop_worker",
                                  json={"id": "zzz"})
            assert r.status == 404
            r = await client.get("/distributed/worker_log",
                                 params={"id": "zzz"})
            assert r.status == 404
            r = await client.get("/distributed/managed_workers")
            assert await r.json() == {}
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestProfiling:
    def test_profile_endpoints(self, tmp_path):
        async def body(client, state):
            r = await client.get("/distributed/profile/status")
            assert (await r.json())["running"] is False

            r = await client.post("/distributed/profile/start",
                                  json={"dir": str(tmp_path / "tr")})
            assert r.status == 200

            r = await client.post("/distributed/profile/start",
                                  json={"dir": str(tmp_path / "tr2")})
            assert r.status == 409  # already running

            r = await client.get("/distributed/profile/status")
            assert (await r.json())["running"] is True

            r = await client.post("/distributed/profile/stop")
            assert r.status == 200
            assert (await r.json())["dir"] == str(tmp_path / "tr")

            r = await client.post("/distributed/profile/stop")
            assert r.status == 409
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_metrics_include_phases(self, tmp_path):
        from comfyui_distributed_tpu.utils.logging import Timer
        with Timer("unit_test_phase"):
            pass

        async def body(client, state):
            r = await client.get("/distributed/metrics")
            data = await r.json()
            assert "phases" in data
            assert data["phases"]["unit_test_phase"]["count"] >= 1
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestClusterActions:
    def test_workers_status_and_cluster_endpoints(self, tmp_path):
        async def body(client, state):
            r = await client.get("/distributed/workers_status")
            assert r.status == 200 and await r.json() == {}

            # no enabled workers -> fan-out is a no-op but self still acts
            from comfyui_distributed_tpu.runtime import interrupt as itr
            r = await client.post("/distributed/cluster/interrupt")
            assert r.status == 200
            assert (await r.json())["workers"] == {}
            assert state.interrupt_event.is_set()
            itr.clear_interrupt()  # the process-global sampler flag
            # (conftest's _no_leaked_interrupt also guards every test)

            r = await client.post("/distributed/cluster/clear_memory")
            assert r.status == 200
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestPromptExtraPnginfo:
    def test_extra_data_reaches_saved_pngs(self, tmp_path):
        """/prompt's extra_data.extra_pnginfo rides the exec thread into
        SaveImage: the saved PNG embeds the prompt AND the workflow
        chunk (the reference ships extra_pnginfo with every dispatch,
        gpupanel.js:1344-1358)."""
        from PIL import Image
        prompt = {
            "7": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": "tiny.safetensors"}},
            "5": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "cat", "clip": ["7", 1]}},
            "9": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "8": {"class_type": "KSampler",
                  "inputs": {"model": ["7", 0], "positive": ["5", 0],
                             "negative": ["5", 0], "latent_image": ["9", 0],
                             "seed": 1, "steps": 1, "cfg": 1.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0}},
            "1": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
            "3": {"class_type": "SaveImage",
                  "inputs": {"images": ["1", 0],
                             "filename_prefix": "meta_http"}},
        }
        ui_doc = {"nodes": [], "links": [], "note": "source workflow"}

        async def body(client, state):
            r = await client.post("/prompt", json={
                "prompt": prompt, "client_id": "t",
                "extra_data": {"extra_pnginfo": {"workflow": ui_doc}}})
            assert r.status == 200
            pid = (await r.json())["prompt_id"]
            for _ in range(1800):
                hist = await (await client.get("/history")).json()
                if pid in hist:
                    assert hist[pid]["status"] == "success", hist[pid]
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("prompt never finished")
            outs = sorted(os.listdir(state.output_dir))
            assert outs, "SaveImage wrote nothing"
            im = Image.open(os.path.join(state.output_dir, outs[0]))
            assert json.loads(im.info["workflow"]) == ui_doc
            embedded = json.loads(im.info["prompt"])
            assert set(embedded) == set(prompt)
        run_with_client(body, tmp_path, start_exec_thread=True)
