"""Tile math parity invariants + blend properties (SURVEY.md §4 unit list)."""

import numpy as np
import pytest

from comfyui_distributed_tpu.ops import tiling


class TestGrid:
    def test_round_to_multiple(self):
        assert tiling.round_to_multiple(512) == 512
        assert tiling.round_to_multiple(500) == 496  # python round(): 62.5->62
        assert tiling.round_to_multiple(515) == 512
        assert tiling.round_to_multiple(517) == 520

    def test_calculate_tiles_row_major(self):
        assert tiling.calculate_tiles(1024, 512, 512, 512) == \
            [(0, 0), (512, 0)]
        assert tiling.calculate_tiles(1024, 1024, 512, 512) == \
            [(0, 0), (512, 0), (0, 512), (512, 512)]

    def test_calculate_tiles_ragged_edge(self):
        # 1000px with 512 tiles -> positions 0 and 512 (edge tile hangs over)
        tiles = tiling.calculate_tiles(1000, 512, 512, 512)
        assert tiles == [(0, 0), (512, 0)]


class TestPartition:
    @pytest.mark.parametrize("total,workers", [
        (10, 2), (11, 2), (12, 3), (7, 3), (4, 7), (1, 3), (64, 7), (256, 63),
    ])
    def test_partition_invariants(self, total, workers):
        """Partition of [0, total): disjoint, contiguous, master-first,
        concatenation in order reconstructs range(total)."""
        parts = tiling.partition_tiles(total, workers)
        assert len(parts) == workers + 1
        flat = [i for part in parts for i in part]
        assert flat == list(range(total))
        for part in parts:
            if part:
                assert part == list(range(part[0], part[-1] + 1))

    def test_reference_examples(self):
        # worked examples matching the reference's arithmetic
        # (_get_master_tiles/_get_worker_tiles, distributed_upscale.py:329-365)
        parts = tiling.partition_tiles(10, 2)
        assert parts[0] == [0, 1, 2, 3]          # master: per+1 (rem>0)
        assert parts[1] == [4, 5, 6]
        assert parts[2] == [7, 8, 9]
        parts = tiling.partition_tiles(12, 3)     # rem = 0
        assert [len(p) for p in parts] == [3, 3, 3, 3]

    def test_more_workers_than_tiles(self):
        parts = tiling.partition_tiles(2, 7)
        flat = [i for p in parts for i in p]
        assert flat == [0, 1]


class TestExtraction:
    def test_extraction_region_clamped(self):
        assert tiling.extraction_region(0, 0, 64, 64, 16, 256, 256) == \
            (0, 0, 80, 80)
        assert tiling.extraction_region(192, 192, 64, 64, 16, 256, 256) == \
            (176, 176, 256, 256)

    def test_extract_tiles_static_shape(self, rng):
        img = rng.random((1, 100, 130, 3), dtype=np.float32)
        positions = tiling.calculate_tiles(130, 100, 64, 64)
        tiles = tiling.extract_tiles(img, positions, 64, 64, 16)
        assert tiles.shape == (len(positions), 64, 64, 3)

    def test_extract_no_padding_exact_content(self, rng):
        img = rng.random((1, 128, 128, 3), dtype=np.float32)
        tiles = tiling.extract_tiles(img, [(0, 0), (64, 64)], 64, 64, 0)
        assert np.allclose(tiles[0], img[0, :64, :64])
        assert np.allclose(tiles[1], img[0, 64:, 64:])


class TestMaskBlend:
    def test_mask_shape_and_range(self):
        m = tiling.create_tile_mask(128, 96, 32, 32, 64, 32, 8)
        assert m.shape == (96, 128)
        assert 0.0 <= m.min() and m.max() <= 1.0
        assert m[48, 64] > 0.9           # tile interior ~white (blur=8)
        assert m[5, 5] < 0.01            # far corner black

    def test_mask_no_blur_is_binary(self):
        m = tiling.create_tile_mask(64, 64, 16, 16, 32, 32, 0)
        assert set(np.unique(m)).issubset({0.0, 1.0})

    def test_blend_identity_outside_mask(self, rng):
        canvas = rng.random((96, 96, 3), dtype=np.float32)
        tile = rng.random((32, 32, 3), dtype=np.float32)
        out = tiling.blend_tile(canvas, tile, 32, 32, (32, 32), 32, 32,
                                (32, 32), mask_blur=0)
        assert np.allclose(out[:32, :, :], canvas[:32, :, :])  # untouched rows
        assert np.allclose(out[32:64, 32:64, :], tile)         # replaced

    def test_blend_feather_interpolates(self, rng):
        canvas = np.zeros((96, 96, 3), np.float32)
        tile = np.ones((32, 32, 3), np.float32)
        out = tiling.blend_tile(canvas, tile, 32, 32, (32, 32), 32, 32,
                                (32, 32), mask_blur=4)
        center = out[48, 48, 0]
        edge = out[33, 48, 0]
        assert center > 0.95
        assert 0.0 < edge <= 1.0


class TestUniformTileStarts:
    def test_small_input_single_tile(self):
        from comfyui_distributed_tpu.ops.tiling import uniform_tile_starts
        assert uniform_tile_starts(500, 512, 32) == [0]

    def test_clamped_last_start_deduplicated(self):
        """When the clamped last start coincides with a step position the
        window must appear ONCE — a duplicate would run the whole tile
        through the model twice for nothing."""
        from comfyui_distributed_tpu.ops.tiling import uniform_tile_starts
        # step = 480; clamp 992-512 = 480 == the second step position
        assert uniform_tile_starts(992, 512, 32) == [0, 480]

    def test_full_coverage(self):
        from comfyui_distributed_tpu.ops.tiling import uniform_tile_starts
        for total, tile, ov in [(992, 512, 32), (1000, 512, 32),
                                (64, 48, 8), (100, 32, 8)]:
            starts = uniform_tile_starts(total, tile, ov)
            assert starts == sorted(set(starts))
            covered = np.zeros(total, bool)
            for s in starts:
                assert 0 <= s <= total - tile
                covered[s:s + tile] = True
            assert covered.all(), (total, tile, ov, starts)

    def test_feather_mask_normalizes(self):
        """Accumulated overlapping masks sum to ~1 in the overlap band
        after weight normalization (the property tiled_apply relies on)."""
        from comfyui_distributed_tpu.ops.tiling import make_feather_mask
        m = make_feather_mask(32, 32, 8)
        acc = np.zeros(56, np.float32)
        acc[:32] += m[16]                 # two tiles overlapping by 8
        acc[24:] += m[16]
        assert acc[24:32].max() <= 1.2    # feather, not doubling
        assert (acc[4:52] > 0.3).all()
