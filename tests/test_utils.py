"""Utils layer: config round-trip/CRUD, image codecs, logging, net helpers."""

import asyncio
import json
import os
import threading

import numpy as np
import pytest

from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils import image as img_mod
from comfyui_distributed_tpu.utils import net as net_mod
from comfyui_distributed_tpu.utils.logging import Timer, debug_enabled


class TestConfig:
    def test_defaults_created(self):
        path = cfg_mod.ensure_config_exists()
        assert os.path.exists(path)
        cfg = cfg_mod.load_config()
        assert cfg["workers"] == []
        assert cfg["settings"]["stop_workers_on_master_exit"] is True
        assert "mesh" in cfg

    def test_round_trip(self):
        cfg = cfg_mod.get_default_config()
        cfg["master"]["host"] = "10.0.0.5"
        cfg_mod.save_config(cfg)
        assert cfg_mod.load_config()["master"]["host"] == "10.0.0.5"

    def test_corrupt_file_yields_defaults(self):
        path = cfg_mod.default_config_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write("{not json")
        cfg = cfg_mod.load_config()
        assert cfg["workers"] == []

    def test_upsert_worker_insert_update_delete(self):
        cfg = cfg_mod.get_default_config()
        cfg_mod.upsert_worker(cfg, {"id": "1", "name": "w1", "port": 8288})
        assert len(cfg["workers"]) == 1
        assert cfg["workers"][0]["enabled"] is False
        # update + None removes field (reference upsert semantics)
        cfg_mod.upsert_worker(cfg, {"id": "1", "enabled": True, "name": None})
        assert cfg["workers"][0]["enabled"] is True
        assert "name" not in cfg["workers"][0]
        assert cfg_mod.delete_worker(cfg, "1") is True
        assert cfg["workers"] == []
        assert cfg_mod.delete_worker(cfg, "1") is False

    def test_debug_setting_toggles_logging(self):
        cfg = cfg_mod.get_default_config()
        cfg_mod.update_setting(cfg, "debug", True)
        assert debug_enabled() is True
        cfg_mod.update_setting(cfg, "debug", False)
        assert debug_enabled() is False

    def test_enabled_workers(self):
        cfg = cfg_mod.get_default_config()
        cfg_mod.upsert_worker(cfg, {"id": "a", "port": 1, "enabled": True})
        cfg_mod.upsert_worker(cfg, {"id": "b", "port": 2, "enabled": False})
        assert [w["id"] for w in cfg_mod.enabled_workers(cfg)] == ["a"]


class TestImage:
    def test_png_round_trip(self, rng):
        x = rng.random((2, 16, 24, 3), dtype=np.float32)
        png = img_mod.encode_png(x[0:1])
        back = img_mod.decode_png(png)
        assert back.shape == (1, 16, 24, 3)
        # uint8 quantization bound
        assert np.abs(back - x[0:1]).max() <= 1.0 / 255.0 + 1e-6

    def test_tensor_wire_round_trip_exact(self, rng):
        # the raw-tensor HTTP wire (replaces the old npz helpers): every
        # negotiable codec must round-trip float32 bit-exactly
        x = rng.standard_normal((1, 8, 8, 4), dtype=np.float32)
        for codec in img_mod.tensor_codecs():
            assert np.array_equal(
                img_mod.decode_tensor(img_mod.encode_tensor(x, codec)), x)

    def test_pil_tensor_round_trip(self, rng):
        x = rng.random((1, 10, 12, 3), dtype=np.float32)
        pil = img_mod.tensor_to_pil(x)
        back = img_mod.pil_to_tensor(pil)
        assert back.shape == x.shape
        assert np.abs(back - x).max() <= 1.0 / 255.0 + 1e-6

    def test_resize(self, rng):
        x = rng.random((2, 8, 8, 3), dtype=np.float32)
        out = img_mod.resize_image(x, 16, 12, "lanczos")
        assert out.shape == (2, 12, 16, 3)

    def test_grayscale(self):
        x = np.zeros((1, 4, 4, 1), dtype=np.float32)
        pil = img_mod.tensor_to_pil(x)
        assert img_mod.pil_to_tensor(pil).shape[-1] == 1


class TestNet:
    def test_recommended_ip_prefers_private(self, monkeypatch):
        monkeypatch.setattr(net_mod, "get_network_ips",
                            lambda: ["127.0.0.1", "8.8.8.8", "10.1.2.3",
                                     "192.168.1.9", "172.20.0.2"])
        assert net_mod.get_recommended_ip() == "192.168.1.9"

    def test_network_info_has_loopback(self):
        info = net_mod.network_info()
        assert "127.0.0.1" in info["ips"]
        assert info["recommended_ip"] in info["ips"]

    def test_run_async_in_loop(self):
        loop = asyncio.new_event_loop()
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        try:
            async def coro():
                return 41 + 1
            assert net_mod.run_async_in_loop(coro(), loop, timeout=5) == 42
        finally:
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=5)
            loop.close()

    def test_run_async_same_loop_raises(self):
        async def outer():
            loop = asyncio.get_running_loop()
            async def coro():
                return 1
            c = coro()
            with pytest.raises(RuntimeError):
                net_mod.run_async_in_loop(c, loop)
            c.close()
        asyncio.run(outer())


def test_timer_measures():
    with Timer("x", emit=False) as t:
        sum(range(1000))
    assert t.elapsed_s >= 0
