"""Durable job state + master failover (ISSUE 7, runtime/durable.py).

Covers the write-ahead log (checksummed segments, snapshot+truncation,
fsync policies, the crash-point injection matrix), the master lease with
epoch fencing, unit-payload spill/reload, WorkLedger and JobStore
recovery merges, the takeover/rehome HTTP surface, and — slow-marked —
the loopback election/recovery acceptance: kill the master mid
tiled-upscale, the standby (or a restarted master) finishes the job
re-refining only unfinished units.
"""

import asyncio
import json
import os
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.runtime import cluster as cl
from comfyui_distributed_tpu.runtime import durable as dur
from comfyui_distributed_tpu.runtime.jobs import JobStore
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny")
    yield


@pytest.fixture
def wal_dir(tmp_path):
    return str(tmp_path / "wal")


def mk_wal(wal_dir, owner="master", lease_s=60.0, **kw):
    lease = dur.MasterLease(wal_dir)
    epoch = lease.acquire(owner, lease_s)
    return dur.WriteAheadLog(wal_dir, epoch=epoch, lease=lease, **kw), \
        lease


# --- record / segment layer --------------------------------------------------

class TestWalCore:
    def test_roundtrip_all_record_types(self, wal_dir):
        wal, _ = mk_wal(wal_dir)
        wal.append("enqueue", pid="p1", prompt={"1": {"class_type": "X"}},
                   client_id="c", extra={"k": 1})
        wal.append("job_create", job="j1", kind="tile",
                   owners={"0": "master", "1": "w0", "2": "w1"})
        wal.append("unit_checkin", job="j1", unit="0", by="master",
                   spilled=True)
        wal.append("unit_reassign", job="j1", units=["2"], to="master")
        wal.append("unit_hedge", job="j1", units=["1"], by="master")
        wal.append("idem", scope="tile", job="j1", key="w0:1:0")
        wal.append("enqueue", pid="p2", prompt={}, client_id="c")
        wal.append("exec_done", pid="p2", status="ok")
        wal.close()
        st, info = dur.replay(wal_dir)
        assert list(st.prompts) == ["p1"]
        assert st.prompts["p1"]["prompt"] == {"1": {"class_type": "X"}}
        units = st.jobs["j1"]["units"]
        assert units["0"]["done"] and units["0"]["spilled"]
        assert not units["1"]["done"]
        assert units["2"]["owner"] == "master"   # reassign applied
        assert st.idem["tile"]["j1"] == ["w0:1:0"]
        assert info["records_replayed"] == 8 and not info["torn"]

    def test_job_finish_drops_job_and_idem(self, wal_dir):
        wal, _ = mk_wal(wal_dir)
        wal.append("job_create", job="j1", kind="image",
                   owners={"w0": "w0"})
        wal.append("idem", scope="image", job="j1", key="k")
        wal.append("job_finish", job="j1")
        wal.close()
        st, _ = dur.replay(wal_dir)
        assert st.jobs == {} and st.idem["image"] == {}

    def test_torn_tail_tolerated_and_prior_records_survive(self, wal_dir):
        wal, lease = mk_wal(wal_dir)
        wal.append("enqueue", pid="p1", prompt={}, client_id="c")
        wal.close()
        wal2 = dur.WriteAheadLog(wal_dir, epoch=1, lease=lease)
        wal2.inject_crash("torn")
        with pytest.raises(dur.WalCrashedError):
            wal2.append("exec_done", pid="p1", status="ok")
        st, info = dur.replay(wal_dir)
        assert "p1" in st.prompts            # torn record never applied
        assert info["torn"]
        report = dur.verify(wal_dir)
        assert report["ok"]                  # torn tail != corruption
        assert any(s["checksum"] == "torn-tail"
                   for s in report["segments"])

    def test_midfile_corruption_flagged(self, wal_dir):
        wal, _ = mk_wal(wal_dir)
        for i in range(6):
            wal.append("idem", scope="tile", job="j", key=f"k{i}")
        wal.close()
        seg = dur.list_segments(wal_dir)[0][2]
        data = open(seg, "rb").read()
        open(seg, "wb").write(data[:20] + b"XX" + data[22:])
        report = dur.verify(wal_dir)
        assert not report["ok"]
        assert any("CORRUPT" in s["checksum"]
                   for s in report["segments"])

    def test_rotation_snapshot_truncation_equivalence(self, wal_dir):
        wal, _ = mk_wal(wal_dir, segment_bytes=300)
        wal.append("job_create", job="j1", kind="tile",
                   owners={str(i): "master" for i in range(4)})
        for i in range(4):
            wal.append("unit_checkin", job="j1", unit=str(i),
                       by="master", spilled=False)
        for i in range(20):
            wal.append("idem", scope="tile", job="j1", key=f"k{i}")
        wal.close()
        segs = dur.list_segments(wal_dir)
        snaps = dur.list_snapshots(wal_dir)
        assert snaps, "rotation never snapshotted"
        # truncation happened: far fewer segments than rotations
        assert all((e, s) >= (snaps[-1][0], snaps[-1][1])
                   for e, s, _ in segs)
        st, _ = dur.replay(wal_dir)
        assert all(u["done"] for u in st.jobs["j1"]["units"].values())
        assert len(st.idem["tile"]["j1"]) == 20

    def test_sync_policies(self, wal_dir):
        wal, _ = mk_wal(wal_dir, sync="off")
        wal.append("enqueue", pid="p", prompt={}, client_id="c")
        assert wal.stats()["unsynced_records"] == 1
        wal.sync()
        assert wal.stats()["unsynced_records"] == 0
        wal.close()
        wal2 = dur.WriteAheadLog(wal_dir, epoch=2, sync="always")
        wal2.append("enqueue", pid="p2", prompt={}, client_id="c")
        assert wal2.stats()["unsynced_records"] == 0
        wal2.close()


class TestCrashPointMatrix:
    """The satellite: kill the master between append/fsync/ack at every
    transition type; recovery must converge with no duplicate and no
    lost units.  ``post_sync`` = the record IS durable but the caller
    never saw the ack (lost-ack); ``pre_append``/``torn`` = the record
    is NOT durable (the caller was never acked, so the work is redone)."""

    TRANSITIONS = [
        ("enqueue", dict(pid="px", prompt={"1": {}}, client_id="c")),
        ("job_create", dict(job="jx", kind="tile",
                            owners={"0": "master"})),
        ("unit_checkin", dict(job="j1", unit="1", by="w0",
                              spilled=False)),
        ("unit_reassign", dict(job="j1", units=["1"], to="master")),
        ("idem", dict(scope="tile", job="j1", key="kx")),
        ("exec_done", dict(pid="p0", status="ok")),
        ("job_finish", dict(job="j1")),
    ]

    def _base(self, wal):
        wal.append("enqueue", pid="p0", prompt={"1": {}}, client_id="c")
        wal.append("job_create", job="j1", kind="tile",
                   owners={"0": "master", "1": "w0"})
        wal.append("unit_checkin", job="j1", unit="0", by="master",
                   spilled=False)

    @pytest.mark.parametrize("point", ["pre_append", "torn", "post_sync"])
    def test_crash_at_every_transition(self, tmp_path, point):
        for k, (rtype, fields) in enumerate(self.TRANSITIONS):
            wal_dir = str(tmp_path / f"{point}_{k}")
            wal, lease = mk_wal(wal_dir)
            self._base(wal)
            wal.inject_crash(point, rtype)
            with pytest.raises(dur.WalCrashedError):
                wal.append(rtype, **fields)
            # every append after the crash is refused, like a dead process
            with pytest.raises(dur.WalCrashedError):
                wal.append("idem", scope="tile", job="j1", key="late")

            st, _ = dur.replay(wal_dir)
            # the base prefix is never lost
            if not (rtype == "exec_done" and point == "post_sync"):
                assert "p0" in st.prompts, (rtype, point)
            if rtype not in ("job_finish",) or point != "post_sync":
                assert "j1" in st.jobs, (rtype, point)
                assert st.jobs["j1"]["units"]["0"]["done"]
            durable = point == "post_sync"
            if rtype == "unit_checkin":
                assert st.jobs["j1"]["units"]["1"]["done"] == durable
            if rtype == "enqueue":
                assert ("px" in st.prompts) == durable
            if rtype == "idem":
                assert ("kx" in st.idem["tile"].get("j1", [])) == durable
            if rtype == "job_finish":
                assert ("j1" not in st.jobs) == durable
            # replay is idempotent: materializing twice converges
            st2, _ = dur.replay(wal_dir)
            assert st2.to_json() == st.to_json(), (rtype, point)

    def test_lost_ack_checkin_is_exactly_once_after_recovery(
            self, wal_dir):
        """post_sync at a check-in = the unit IS done on disk; the
        caller (who never saw the ack) retries after recovery, and the
        recovered ledger dedupes the redo at the blend."""
        wal, lease = mk_wal(wal_dir)
        wal.append("job_create", job="j1", kind="tile",
                   owners={"0": "master", "1": "w0"})
        wal.inject_crash("post_sync", "unit_checkin")
        with pytest.raises(dur.WalCrashedError):
            wal.append("unit_checkin", job="j1", unit="1", by="w0",
                       spilled=False)
        st, _ = dur.replay(wal_dir)
        led = cl.WorkLedger()
        wal2 = dur.WriteAheadLog(wal_dir, epoch=2, lease=lease,
                                 tracker=st)
        led.attach_wal(wal2, dur.UnitStore(wal_dir), dict(st.jobs))
        led.create_job("j1", {"0": "master", "1": "w0"}, kind="tile")
        # payload never spilled -> downgraded to pending, recomputed
        # ONCE (unit "0" was simply never done)
        assert sorted(led.pending("j1")) == ["0", "1"]
        assert led.check_in("j1", "1", "w0") is True
        assert led.check_in("j1", "1", "w0") is False  # the retried ack
        wal2.close()


# --- lease / fencing ---------------------------------------------------------

class TestMasterLease:
    def test_acquire_renew_expire_epochs(self, wal_dir):
        lease = dur.MasterLease(wal_dir)
        e1 = lease.acquire("m", 0.3)
        assert e1 == 1 and lease.snapshot()["held"]
        assert lease.renew("m", e1, 0.3)
        with pytest.raises(dur.LeaseHeldError):
            lease.acquire("standby", 0.3)
        time.sleep(0.4)
        assert not lease.snapshot()["held"]
        e2 = lease.acquire("standby", 60.0)   # expired -> allowed
        assert e2 == 2
        assert not lease.renew("m", e1, 0.3)  # the old holder lost it

    def test_same_owner_reclaims_live_lease(self, wal_dir):
        lease = dur.MasterLease(wal_dir)
        e1 = lease.acquire("m", 60.0)
        e2 = lease.acquire("m", 60.0)  # crash-restart of the same owner
        assert e2 == e1 + 1

    def test_stale_epoch_append_fenced(self, wal_dir, monkeypatch):
        monkeypatch.setattr(C, "WAL_FENCE_CHECK_S", 0.0)
        wal, lease = mk_wal(wal_dir, owner="m")
        wal.append("enqueue", pid="p", prompt={}, client_id="c")
        lease.acquire("standby", 60.0, force=True)  # the fencing event
        with pytest.raises(dur.FencedError):
            wal.append("enqueue", pid="p2", prompt={}, client_id="c")
        assert wal.fenced
        st, _ = dur.replay(wal_dir)
        assert "p2" not in st.prompts


# --- unit store + ledger recovery -------------------------------------------

class TestUnitStoreAndLedgerRecovery:
    def test_unit_store_roundtrip(self, wal_dir):
        us = dur.UnitStore(wal_dir)
        t = np.random.default_rng(0).random((5, 4, 3)).astype(np.float32)
        us.put("job/1", 3, [t], {"form": "window"})
        assert us.has("job/1", 3) and not us.has("job/1", 4)
        tensors, meta = us.get("job/1", 3)
        np.testing.assert_array_equal(tensors[0], t)
        assert meta == {"form": "window"}
        us.drop_job("job/1")
        assert not us.has("job/1", 3)

    def _recovered_ledger(self, wal_dir, spill_units=(0,)):
        """A ledger that lived, checked units in, 'crashed', and a
        second ledger recovered from its WAL."""
        wal, lease = mk_wal(wal_dir)
        us = dur.UnitStore(wal_dir)
        led = cl.WorkLedger()
        led.attach_wal(wal, us, {})
        led.create_job("j", {0: "master", 1: "w0", 2: "w1"}, kind="tile")
        for u in spill_units:
            assert led.check_in(
                "j", u, "master",
                payload=([np.full((2, 2, 3), float(u), np.float32)],
                         {"form": "window"}))
        wal.simulate_crash()
        st, _ = dur.replay(wal_dir)
        led2 = cl.WorkLedger()
        wal2 = dur.WriteAheadLog(wal_dir, epoch=2, lease=lease,
                                 tracker=st)
        led2.attach_wal(wal2, us, dict(st.jobs))
        led2.create_job("j", {0: "master", 1: "w0", 2: "w1"},
                        kind="tile")
        return led2

    def test_preloaded_done_units_not_pending(self, wal_dir):
        led2 = self._recovered_ledger(wal_dir, spill_units=(0, 1))
        assert led2.pending("j") == [2]
        payloads = led2.load_payloads("j")
        assert set(payloads) == {0, 1}
        tensors, meta = payloads[1]
        assert meta["form"] == "window" and tensors[0][0, 0, 0] == 1.0
        summary = led2.finish_job("j")
        assert summary["recovered"] and summary["preloaded_units"] == 2

    def test_missing_payload_downgrades_to_pending(self, wal_dir):
        led2 = self._recovered_ledger(wal_dir, spill_units=(0, 1))
        us = dur.UnitStore(wal_dir)
        os.remove(us.path("j", 1))
        payloads = led2.load_payloads("j")
        assert set(payloads) == {0}
        assert sorted(led2.pending("j")) == [1, 2]

    def test_take_recovered_lost_groups_nonmaster_owners_once(
            self, wal_dir):
        led2 = self._recovered_ledger(wal_dir, spill_units=(0,))
        lost = led2.take_recovered_lost("j")
        assert lost == {"w0": [1], "w1": [2]}
        assert led2.take_recovered_lost("j") == {}   # consumed
        # a non-recovered job never reports lost owners
        led2.create_job("j2", {0: "w0"}, kind="tile")
        assert led2.take_recovered_lost("j2") == {}


# --- JobStore idempotency persistence ---------------------------------------

class TestIdemPersistence:
    def test_keys_survive_restart_and_replays_dropped(self, wal_dir):
        async def run():
            wal, lease = mk_wal(wal_dir)
            js = JobStore()
            js.attach_wal(wal)
            await js.prepare_tile_job("j")
            item = {"worker_id": "w0", "tile_idx": 1, "tensor": 0}
            assert await js.put_tile("j", item, idem_key="w0:1:0")
            wal.simulate_crash()        # the master dies post-ack

            st, _ = dur.replay(wal_dir)
            js2 = JobStore()
            wal2 = dur.WriteAheadLog(wal_dir, epoch=2, lease=lease,
                                     tracker=st)
            js2.attach_wal(wal2, st.idem)
            await js2.prepare_tile_job("j")
            # the acked-but-dropped upload is replayed against the NEW
            # master: acknowledged, never enqueued
            assert await js2.put_tile("j", item, idem_key="w0:1:0")
            q = await js2.get_tile_queue("j")
            assert q.qsize() == 0
            # a fresh key still enqueues
            assert await js2.put_tile("j", item, idem_key="w0:1:1")
            assert q.qsize() == 1
            wal2.close()
        asyncio.run(run())


# --- ServerState wiring ------------------------------------------------------

class TestServerStateRecovery:
    def test_queue_recovered_with_original_pids(self, tmp_path,
                                                monkeypatch):
        wal = str(tmp_path / "wal")
        monkeypatch.setenv(C.WAL_DIR_ENV, wal)
        st = ServerState(config_path=str(tmp_path / "cfg.json"),
                         start_exec_thread=False)
        assert st.durable is not None
        p1 = st.enqueue_prompt({"1": {"class_type": "X"}}, "c1")
        p2 = st.enqueue_prompt({"2": {"class_type": "Y"}}, "c2")
        st.durable.simulate_crash()

        st2 = ServerState(config_path=str(tmp_path / "cfg.json"),
                          start_exec_thread=False)
        assert st2.durable is not None and st2.durable.epoch == 2
        assert st2.resume_recovered() == 2
        with st2._queue_lock:
            pids = [it["id"] for it in st2._queue]
        assert pids == [p1, p2]
        # resume is idempotent, and the re-enqueue did not re-log
        assert st2.resume_recovered() == 0
        st3_state, _ = dur.replay(wal)
        assert sorted(st3_state.prompts) == sorted([p1, p2])
        st2.durable.close()

    def test_completed_prompts_not_resumed(self, tmp_path, monkeypatch):
        monkeypatch.setenv(C.WAL_DIR_ENV, str(tmp_path / "wal"))
        st = ServerState(config_path=str(tmp_path / "cfg.json"),
                         start_exec_thread=False)
        pid = st.enqueue_prompt({"1": {"class_type": "X"}}, "c")
        st.durable.log_exec_done(pid, "ok")
        st.durable.simulate_crash()
        st2 = ServerState(config_path=str(tmp_path / "cfg.json"),
                          start_exec_thread=False)
        assert st2.resume_recovered() == 0
        st2.durable.close()

    def test_no_wal_dir_means_no_durable(self, tmp_path):
        st = ServerState(config_path=str(tmp_path / "cfg.json"),
                         start_exec_thread=False)
        assert st.durable is None
        st.enqueue_prompt({"1": {}}, "c")   # and nothing breaks


# --- HTTP surface ------------------------------------------------------------

class TestDurabilityRoutes:
    def test_durability_info_and_takeover_conflict(self, tmp_path,
                                                   monkeypatch):
        async def go():
            st = ServerState(config_path=str(tmp_path / "cfg.json"),
                             start_exec_thread=False)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            try:
                r = await client.get("/distributed/durability")
                assert (await r.json()) == {"enabled": False}
                r = await client.post("/distributed/takeover", json={})
                assert r.status == 409
            finally:
                await client.close()
        asyncio.run(go())

    def test_active_master_reports_and_takeover_is_noop(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.setenv(C.WAL_DIR_ENV, str(tmp_path / "wal"))

        async def go():
            st = ServerState(config_path=str(tmp_path / "cfg.json"),
                             start_exec_thread=False)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            try:
                r = await client.get("/distributed/durability")
                body = await r.json()
                assert body["enabled"] and body["epoch"] == 1
                assert body["lease"]["held"]
                assert body["wal"]["records_appended"] == 0
                r = await client.post("/distributed/takeover", json={})
                assert (await r.json())["note"] == "already active"
                # prom gauges ride the standard exposition
                r = await client.get("/distributed/metrics.prom")
                text = await r.text()
                assert "dtpu_master_epoch 1" in text
                assert "dtpu_wal_records_total" in text
                r = await client.get("/distributed/metrics")
                assert (await r.json())["durability"]["epoch"] == 1
            finally:
                await client.close()
                st.durable.close()
        asyncio.run(go())

    def test_rehome_retargets_heartbeat(self, tmp_path, monkeypatch):
        async def go():
            st = ServerState(config_path=str(tmp_path / "cfg.json"),
                             is_worker=True, start_exec_thread=False)
            st.heartbeat = cl.HeartbeatSender("http://127.0.0.1:1",
                                              "w0", interval=3600)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            try:
                r = await client.post("/distributed/rehome", json={
                    "master_url": "http://127.0.0.1:2/",
                    "worker_id": "w0"})
                body = await r.json()
                assert body["master_url"] == "http://127.0.0.1:2"
                assert st.heartbeat.master_url == "http://127.0.0.1:2"
                assert os.environ[C.MASTER_URL_ENV] \
                    == "http://127.0.0.1:2"
                r = await client.post("/distributed/rehome", json={})
                assert r.status == 400
            finally:
                await client.close()
                os.environ.pop(C.MASTER_URL_ENV, None)
        asyncio.run(go())


# --- loopback election/recovery acceptance (slow) ----------------------------

def upscale_prompt(seed=7, size=64, tile=32, steps=1):
    """4 tiles over master [0,1] + w0 [2] + w1 [3], saved to disk so
    the recovered blend has comparable pixels."""
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "a map", "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "10": {"class_type": "LoadImage",
               "inputs": {"image": "__durable_card__.png"}},
        "11": {"class_type": "ImageScale",
               "inputs": {"image": ["10", 0],
                          "upscale_method": "bilinear",
                          "width": size, "height": size,
                          "crop": "disabled"}},
        "2": {"class_type": "UltimateSDUpscaleDistributed",
              "inputs": {"upscaled_image": ["11", 0], "model": ["7", 0],
                         "positive": ["5", 0], "negative": ["6", 0],
                         "vae": ["7", 2], "seed": seed, "steps": steps,
                         "cfg": 2.0, "sampler_name": "euler",
                         "scheduler": "normal", "denoise": 0.4,
                         "tile_width": tile, "tile_height": tile,
                         "padding": 8, "mask_blur": 2,
                         "force_uniform_tiles": True}},
        "8": {"class_type": "SaveImage",
              "inputs": {"images": ["2", 0],
                         "filename_prefix": "durable"}},
    }


async def _wait_history(client, pid, timeout_s=240.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        hist = await (await client.get("/history")).json()
        if pid in hist:
            return hist[pid]
        await asyncio.sleep(0.1)
    raise AssertionError(f"prompt {pid} never finished")


class _DurableCluster:
    """Master + 2 workers over loopback HTTP with a shared WAL dir —
    the test_cluster._Cluster topology plus the durability plane."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.workers = []
        self.states = []
        self.clients = []
        self.cfg_path = str(tmp_path / "cfg.json")

    async def make_master(self, name, standby=False):
        d = self.tmp_path / name
        os.makedirs(d / "in", exist_ok=True)
        if standby:
            os.environ[C.STANDBY_ENV] = "1"
        try:
            st = ServerState(config_path=self.cfg_path,
                             input_dir=str(d / "in"),
                             output_dir=str(d),
                             is_worker=False)
        finally:
            os.environ.pop(C.STANDBY_ENV, None)
        client = TestClient(TestServer(build_app(st)))
        await client.start_server()
        st.port = client.server.port
        self.states.append(st)
        self.clients.append(client)
        return st, client, str(d)

    async def start(self):
        cfg_workers = []
        for i in range(2):
            wdir = self.tmp_path / f"worker{i}"
            os.makedirs(wdir / "in")
            st = ServerState(config_path=str(wdir / "cfg.json"),
                             input_dir=str(wdir / "in"),
                             output_dir=str(wdir), is_worker=True)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            self.workers.append((st, client))
            self.states.append(st)
            self.clients.append(client)
            cfg_workers.append({"id": f"w{i}", "host": "127.0.0.1",
                                "port": client.server.port,
                                "enabled": True})
        with open(self.cfg_path, "w") as f:
            json.dump({"workers": cfg_workers,
                       "master": {"host": "127.0.0.1"},
                       "settings": {}}, f)
        return self

    async def stop(self):
        for st in self.states:
            if getattr(st, "durable", None) is not None:
                st.durable.simulate_crash()
            st.health.stop()
        for client in self.clients:
            try:
                await client.close()
            except Exception:  # noqa: BLE001 - may already be closed
                pass
        for st in self.states:
            st.drain(1)


def _newest_png(d):
    pngs = [os.path.join(d, f) for f in os.listdir(d)
            if f.endswith(".png")]
    assert pngs, f"no PNG in {d}"
    return max(pngs, key=os.path.getmtime)


def _png_pixels(path):
    from comfyui_distributed_tpu.utils.image import decode_png
    return np.asarray(decode_png(open(path, "rb").read()))


async def _run_to_mid_job(clu, mclient, mstate, seed):
    """Post the upscale with w1 stalled; return pid once >=3/4 units
    are durable (the kill point)."""
    clu.workers[1][0].fault_inject = {"stall_s": 300}
    r = await mclient.post("/prompt", json={
        "prompt": upscale_prompt(seed=seed), "client_id": "acc"})
    assert r.status == 200, await r.text()
    body = await r.json()
    assert sorted(body["workers"]) == ["w0", "w1"], body
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        snap = await (await mclient.get("/distributed/cluster")).json()
        if any(j["done_units"] >= 3
               for j in snap["ledger"]["active_jobs"].values()):
            return body["prompt_id"]
        await asyncio.sleep(0.05)
    raise AssertionError("job never reached 3/4 done units")


class TestFailoverAcceptance:
    @pytest.mark.slow
    def test_standby_election_finishes_job_bit_identical(
            self, tmp_path, monkeypatch):
        """THE acceptance: kill the master mid tiled-upscale; the
        standby's lease watcher takes over, replays the shared WAL,
        blends the spilled units and redispatches only the unfinished
        one — completion 1.0, image bit-identical to the no-failure
        run, workers re-homed."""
        monkeypatch.setenv(C.WAL_DIR_ENV, str(tmp_path / "wal"))
        monkeypatch.setenv(C.MASTER_LEASE_ENV, "2.0")
        monkeypatch.setenv(C.LEASE_ENV, "4.0")
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "reassign")
        monkeypatch.setenv(C.HEDGE_ENV, "0")
        monkeypatch.setenv(C.DRAIN_TIMEOUT_ENV, "2")

        async def go():
            clu = await _DurableCluster(tmp_path).start()
            try:
                mstate, mclient, mdir = await clu.make_master("master")
                assert mstate.durable is not None
                mstate.resume_recovered()
                mstate.health.interval = 0.5
                await asyncio.get_running_loop().run_in_executor(
                    None, mstate.health.poll_once)
                mstate.health.start()

                # no-failure reference (same seed as the failover run)
                r = await mclient.post("/prompt", json={
                    "prompt": upscale_prompt(seed=11),
                    "client_id": "base"})
                pid0 = (await r.json())["prompt_id"]
                assert (await _wait_history(mclient, pid0))["status"] \
                    == "success"
                base = _png_pixels(_newest_png(mdir))

                sstate, sclient, sdir = await clu.make_master(
                    "standby", standby=True)
                assert sstate.durable.standby

                pid = await _run_to_mid_job(clu, mclient, mstate,
                                            seed=11)
                mstate.durable.simulate_crash()   # SIGKILL proxy
                mstate.health.stop()
                clu.workers[1][0].fault_inject = {}

                hist = await _wait_history(sclient, pid)
                assert hist["status"] == "success", hist

                snap = await (await sclient.get(
                    "/distributed/cluster")).json()
                job = [j for j in snap["ledger"]["completed_jobs"]
                       if j["kind"] == "tile"][-1]
                assert job["done_units"] == job["total_units"] == 4
                assert job["pending_units"] == []
                assert job["recovered"] is True
                # only the stranded unit was re-refined
                assert job["preloaded_units"] >= 2
                assert job["reassigned_units"] >= 1

                dur_info = await (await sclient.get(
                    "/distributed/durability")).json()
                assert dur_info["epoch"] == 2
                assert dur_info["takeovers"] == 1

                np.testing.assert_array_equal(
                    _png_pixels(_newest_png(sdir)), base)

                # workers re-homed their heartbeats to the new master
                for wst, _ in clu.workers:
                    assert wst.heartbeat is not None
                    assert str(sstate.port) in wst.heartbeat.master_url
            finally:
                await clu.stop()
        asyncio.run(go())

    @pytest.mark.slow
    def test_restart_only_master_resumes_unfinished_units(
            self, tmp_path, monkeypatch):
        """No standby: a restarted master (same owner id reclaims the
        lease) recovers at startup and redispatches only the units the
        crash left unfinished."""
        monkeypatch.setenv(C.WAL_DIR_ENV, str(tmp_path / "wal"))
        monkeypatch.setenv(C.MASTER_LEASE_ENV, "2.0")
        monkeypatch.setenv(C.LEASE_ENV, "4.0")
        monkeypatch.setenv(C.FAULT_POLICY_ENV, "reassign")
        monkeypatch.setenv(C.HEDGE_ENV, "0")
        monkeypatch.setenv(C.DRAIN_TIMEOUT_ENV, "2")

        async def go():
            clu = await _DurableCluster(tmp_path).start()
            try:
                mstate, mclient, _ = await clu.make_master("master")
                mstate.resume_recovered()
                mstate.health.interval = 0.5
                await asyncio.get_running_loop().run_in_executor(
                    None, mstate.health.poll_once)
                mstate.health.start()

                pid = await _run_to_mid_job(clu, mclient, mstate,
                                            seed=21)
                mstate.durable.simulate_crash()
                mstate.health.stop()
                clu.workers[1][0].fault_inject = {}

                m2, m2client, _ = await clu.make_master("master2")
                assert m2.durable.epoch == 2
                assert await asyncio.get_running_loop().run_in_executor(
                    None, m2.resume_recovered) == 1
                hist = await _wait_history(m2client, pid)
                assert hist["status"] == "success", hist
                snap = await (await m2client.get(
                    "/distributed/cluster")).json()
                job = [j for j in snap["ledger"]["completed_jobs"]
                       if j["kind"] == "tile"][-1]
                assert job["done_units"] == job["total_units"] == 4
                assert job["recovered"] and job["preloaded_units"] >= 2
                # the redo went back out to a live worker, with the
                # reassign span in the resumed job's trace
                r = await m2client.get(f"/distributed/trace/{pid}")
                if r.status == 200:
                    names = {s["name"] for s in
                             (await r.json())["spans"]}
                    assert "reassign" in names or "redispatch" in names
            finally:
                await clu.stop()
        asyncio.run(go())
