"""Native (orbax) checkpoint save/restore + registry integration — the
checkpoint/resume subsystem the reference lacks (SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import registry as reg
from comfyui_distributed_tpu.runtime import checkpointing as ckp


def _trees_equal(a, b):
    for (ka, va), (kb, vb) in zip(
            jax.tree_util.tree_flatten_with_path(a)[0],
            jax.tree_util.tree_flatten_with_path(b)[0]):
        assert jax.tree_util.keystr(ka) == jax.tree_util.keystr(kb)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb))


@pytest.fixture
def tiny_pipe(monkeypatch):
    monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny")
    reg.clear_pipeline_cache()
    pipe = reg.load_pipeline("native_src.ckpt", family_name="tiny")
    yield pipe
    reg.clear_pipeline_cache()


def test_pipeline_roundtrip(tmp_path, tiny_pipe):
    path = str(tmp_path / "ckpt_dir")
    ckp.save_pipeline_checkpoint(path, "tiny", tiny_pipe.unet_params,
                                 tiny_pipe.clip_params, tiny_pipe.vae_params)
    assert ckp.is_native_checkpoint(path)
    fam, unet, clips, vae = ckp.load_pipeline_checkpoint(path)
    assert fam == "tiny" and len(clips) == 1
    _trees_equal(tiny_pipe.unet_params, unet)
    _trees_equal(tiny_pipe.vae_params, vae)


def test_registry_loads_native_dir(tmp_path, tiny_pipe, monkeypatch):
    path = str(tmp_path / "my_model")
    ckp.save_pipeline_checkpoint(path, "tiny", tiny_pipe.unet_params,
                                 tiny_pipe.clip_params, tiny_pipe.vae_params)
    reg.clear_pipeline_cache()
    pipe = reg.load_pipeline("my_model", models_dir=str(tmp_path))
    _trees_equal(tiny_pipe.unet_params, pipe.unet_params)
    assert pipe.family.name == "tiny"


def test_train_state_roundtrip(tmp_path):
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt_state = {"mu": {"w": jnp.full((4, 4), 0.5)}}
    path = str(tmp_path / "train")
    ckp.save_train_state(path, params, opt_state, step=7)
    assert ckp.latest_train_step(path) == 7
    p2, o2, step = ckp.load_train_state(path)
    assert step == 7
    _trees_equal(params, p2)
    _trees_equal(opt_state, o2)


def test_load_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckp.load_train_state(str(tmp_path / "nope"))
