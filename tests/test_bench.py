"""bench.py surfaces that must not rot: the real-checkpoint smoke hook
(VERDICT r3 #6) with a real single-file torch-layout checkpoint standing
in at tiny scale — written by the framework's own exporter, loaded back
through the converter by the bench, one image sampled, finite stats
asserted, PNG artifact saved."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.integration
def test_real_ckpt_smoke_hook(tmp_path):
    from comfyui_distributed_tpu.models import registry
    from comfyui_distributed_tpu.ops.base import OpContext, get_op

    # a REAL checkpoint file on disk (tiny family, full torch layout)
    pipe = registry.load_pipeline("bench-export.ckpt", family_name="tiny")
    octx = OpContext(output_dir=str(tmp_path))
    get_op("CheckpointSave").execute(octx, pipe, pipe, pipe, "tiny_real")
    ckpt = tmp_path / "tiny_real.safetensors"
    assert ckpt.exists()

    out = tmp_path / "real_ckpt.json"
    png = tmp_path / "real_ckpt.png"
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "DTPU_DEFAULT_FAMILY": "tiny",
           "DISTRIBUTED_TPU_CONFIG": str(tmp_path / "c.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--real-ckpt", str(ckpt), "--platform", "cpu",
         "--height", "64", "--width", "64", "--steps", "2",
         "--out", str(out), "--png-out", str(png)],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path),
        env=env)
    assert r.returncode == 0, r.stderr[-3000:]
    payload = json.loads(out.read_text())
    assert payload["value"] > 0.0
    assert payload["ckpt"] == "tiny_real.safetensors"
    assert "latent_std" in payload and payload["latent_std"] > 0.0
    assert png.exists() and png.stat().st_size > 0
    # the loader must have consumed the FILE, not virtual-initialized
    assert "virtual checkpoint" not in r.stderr


@pytest.mark.integration
def test_real_ckpt_missing_file_fails_structured(tmp_path):
    out = tmp_path / "fail.json"
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "DTPU_DEFAULT_FAMILY": "tiny",
           "DISTRIBUTED_TPU_CONFIG": str(tmp_path / "c.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--real-ckpt", str(tmp_path / "nope.safetensors"),
         "--platform", "cpu", "--out", str(out)],
        capture_output=True, text=True, timeout=120, cwd=str(tmp_path),
        env=env)
    assert r.returncode != 0
    payload = json.loads(out.read_text())
    assert payload["error"]["stage"] == "config"
    assert payload["value"] == 0.0
