"""bench.py surfaces that must not rot: the real-checkpoint smoke hook
(VERDICT r3 #6) with a real single-file torch-layout checkpoint standing
in at tiny scale — written by the framework's own exporter, loaded back
through the converter by the bench, one image sampled, finite stats
asserted, PNG artifact saved."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.integration
def test_real_ckpt_smoke_hook(tmp_path):
    from comfyui_distributed_tpu.models import registry
    from comfyui_distributed_tpu.ops.base import OpContext, get_op

    # a REAL checkpoint file on disk (tiny family, full torch layout)
    pipe = registry.load_pipeline("bench-export.ckpt", family_name="tiny")
    octx = OpContext(output_dir=str(tmp_path))
    get_op("CheckpointSave").execute(octx, pipe, pipe, pipe, "tiny_real")
    ckpt = tmp_path / "tiny_real.safetensors"
    assert ckpt.exists()

    out = tmp_path / "real_ckpt.json"
    png = tmp_path / "real_ckpt.png"
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "DTPU_DEFAULT_FAMILY": "tiny",
           "DISTRIBUTED_TPU_CONFIG": str(tmp_path / "c.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--real-ckpt", str(ckpt), "--platform", "cpu",
         "--height", "64", "--width", "64", "--steps", "2",
         "--out", str(out), "--png-out", str(png)],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path),
        env=env)
    assert r.returncode == 0, r.stderr[-3000:]
    payload = json.loads(out.read_text())
    assert payload["value"] > 0.0
    assert payload["ckpt"] == "tiny_real.safetensors"
    assert "latent_std" in payload and payload["latent_std"] > 0.0
    assert png.exists() and png.stat().st_size > 0
    # the loader must have consumed the FILE, not virtual-initialized
    assert "virtual checkpoint" not in r.stderr


@pytest.mark.integration
def test_real_ckpt_missing_file_fails_structured(tmp_path):
    out = tmp_path / "fail.json"
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "DTPU_DEFAULT_FAMILY": "tiny",
           "DISTRIBUTED_TPU_CONFIG": str(tmp_path / "c.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--real-ckpt", str(tmp_path / "nope.safetensors"),
         "--platform", "cpu", "--out", str(out)],
        capture_output=True, text=True, timeout=120, cwd=str(tmp_path),
        env=env)
    assert r.returncode != 0
    payload = json.loads(out.read_text())
    assert payload["error"]["stage"] == "config"
    assert payload["value"] == 0.0


class TestSuiteMode:
    """Round-5 driver-window suite: detection, capped ladder budget,
    artifact replay, best-completed-phase delivery."""

    def _bench(self):
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        import bench
        return bench

    def test_bare_invocation_is_suite_and_flags_opt_out(self):
        bench = self._bench()
        assert bench.parse_args([]).suite
        for argv in (["--family", "sdxl"], ["--platform", "cpu"],
                     ["--batch", "8"], ["--upscale"],
                     ["--attn", "pallas"], ["--scaling-sweep"],
                     ["--steps", "50"], ["--sampler", "dpmpp_2m"],
                     ["--repeats", "1"]):
            assert not bench.parse_args(argv).suite, argv

    def test_ladder_budget_caps_suite_and_keeps_single_patient(self,
                                                               monkeypatch):
        bench = self._bench()
        monkeypatch.delenv("DTPU_CLAIM_WINDOW_S", raising=False)
        monkeypatch.delenv("DTPU_SUITE_LADDER_FRACTION", raising=False)
        pat, probe = bench.ladder_budget(bench.parse_args([]))
        assert pat == 312 and probe == 252        # ~20% of 1560, 1 probe
        pat, probe = bench.ladder_budget(bench.parse_args(["--family",
                                                           "sdxl"]))
        assert pat == 1800 and probe >= 1560      # patient single mode

    def test_artifact_replay_prefers_headline_and_skips_zeros(self,
                                                              tmp_path):
        bench = self._bench()
        bdir = tmp_path / "benchmarks"
        bdir.mkdir()
        (tmp_path / "bench.py").symlink_to(os.path.join(REPO, "bench.py"))
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_sandbox", str(tmp_path / "bench.py"))
        bsb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bsb)
        args = bsb.parse_args([])
        assert bsb._artifact_replay(args) is None          # nothing yet
        (bdir / f"sd15_tpu_{bsb.ROUND}.json").write_text(json.dumps(
            {"metric": "sd15_512x512_20step_images_per_sec_per_chip",
             "value": 1.5, "unit": "images/sec/chip",
             "vs_baseline": 1.0}) + "\n")
        rec = bsb._artifact_replay(args)
        assert rec["metric"].startswith("sd15") and "source" in rec
        (bdir / f"sdxl_tpu_{bsb.ROUND}.json").write_text(json.dumps(
            {"metric": "sdxl_1024x1024_20step_images_per_sec_per_chip",
             "value": 0.8, "unit": "images/sec/chip",
             "vs_baseline": 1.0}) + "\n")
        rec = bsb._artifact_replay(args)
        assert rec["metric"].startswith("sdxl")            # headline wins
        (bdir / f"sdxl_tpu_{bsb.ROUND}.json").write_text(json.dumps(
            {"metric": "x", "value": 0.0, "unit": "images/sec/chip",
             "vs_baseline": 0.0, "error": {}}) + "\n")
        rec = bsb._artifact_replay(args)
        assert rec["metric"].startswith("sd15")            # zeros skipped

    def test_fail_delivers_best_completed_phase(self, tmp_path):
        """A later-phase failure must deliver the measured number, not a
        zero (the r4 zeroed-round failure mode)."""
        r = subprocess.run(
            [sys.executable, "-c", (
                "import sys; sys.argv=['bench.py']\n"
                "import bench\n"
                "a = bench.parse_args([])\n"
                "bench.emit(a, {'metric': 'sd15_x', 'value': 2.0,"
                " 'unit': 'images/sec/chip', 'vs_baseline': 1.0},"
                " partial=True)\n"
                "bench.fail(a, 'runtime', 'phase B OOM')\n")],
            capture_output=True, text=True, timeout=120, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO})
        assert r.returncode == 0, r.stderr[-500:]
        last = json.loads(r.stdout.strip().splitlines()[-1])
        assert last["value"] == 2.0
        assert last["error_after"]["stage"] == "runtime"

    def test_sigterm_delivers_best_completed_phase(self):
        r = subprocess.run(
            [sys.executable, "-c", (
                "import os, signal, sys, time; sys.argv=['bench.py']\n"
                "import bench\n"
                "a = bench.parse_args([])\n"
                "bench._install_sigterm_payload(a)\n"
                "bench.emit(a, {'metric': 'sd15_x', 'value': 2.1,"
                " 'unit': 'images/sec/chip', 'vs_baseline': 1.0},"
                " partial=True)\n"
                "os.kill(os.getpid(), signal.SIGTERM)\n"
                "time.sleep(10)\n"
                "sys.exit(3)\n")],
            capture_output=True, text=True, timeout=120, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO})
        assert r.returncode == 0, (r.returncode, r.stderr[-500:])
        last = json.loads(r.stdout.strip().splitlines()[-1])
        assert last["value"] == 2.1 and "terminated" in last


class TestTpServePhaseSurface:
    """ISSUE 16: the tp_serve phase's CLI/metric/watchdog surface.
    The harness itself (mesh build + sharded compiles) runs in
    tests/test_batching.py and the bench subprocess; here we pin the
    cheap contract: the phase parses, names its metric, and its
    exactness bar tolerates zero regression."""

    def _bench(self):
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        import bench
        return bench

    def test_phase_parses_and_names_metric(self):
        bench = self._bench()
        args = bench.parse_args(["--phase", "tp_serve"])
        assert args.phase == "tp_serve"
        assert bench.metric_name(args) == "tp_serve_bit_exact_fraction"
        assert bench.metric_unit(args) == "fraction"

    def test_exactness_bar_tolerates_nothing(self):
        bench = self._bench()
        assert bench.CHECK_TOLERANCE_PCT[
            "tp_serve_bit_exact_fraction"] == 0.0
        fresh = {"metric": "tp_serve_bit_exact_fraction",
                 "value": 0.5, "unit": "fraction"}
        base = {"metric": "tp_serve_bit_exact_fraction",
                "value": 1.0, "unit": "fraction"}
        assert bench.check_regression(fresh, base)["regressed"]
        assert not bench.check_regression(base, dict(base))["regressed"]


class TestPreemptPhaseSurface:
    """ISSUE 17: the preempt phase's CLI/metric/watchdog surface.  The
    harness itself (park/resume round trips under contention) runs in
    the bench subprocess and tests/test_batching.py; here we pin the
    cheap contract: the phase parses, names its metric, and its
    completion bar tolerates zero regression (preemption pauses work,
    never sheds it)."""

    def _bench(self):
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        import bench
        return bench

    def test_phase_parses_and_names_metric(self):
        bench = self._bench()
        args = bench.parse_args(["--phase", "preempt"])
        assert args.phase == "preempt"
        assert bench.metric_name(args) == \
            "preempt_batch_completion_under_preemption"
        assert bench.metric_unit(args) == "fraction"

    def test_completion_bar_tolerates_nothing(self):
        bench = self._bench()
        assert bench.CHECK_TOLERANCE_PCT[
            "preempt_batch_completion_under_preemption"] == 0.0
        fresh = {"metric": "preempt_batch_completion_under_preemption",
                 "value": 0.9, "unit": "fraction"}
        base = {"metric": "preempt_batch_completion_under_preemption",
                "value": 1.0, "unit": "fraction"}
        assert bench.check_regression(fresh, base)["regressed"]
        assert not bench.check_regression(base, dict(base))["regressed"]


class TestSloPhaseSurface:
    """ISSUE 18: the slo phase's CLI/metric/watchdog surface.  The
    harness itself (armed capture plane vs all-off, burn/exemplar/
    round-trip invariants) runs in the bench subprocess and
    tests/test_capture_plane.py; here we pin the cheap contract: the
    phase parses, names its metric, and carries a throughput
    tolerance."""

    def _bench(self):
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        import bench
        return bench

    def test_phase_parses_and_names_metric(self):
        bench = self._bench()
        args = bench.parse_args(["--phase", "slo"])
        assert args.phase == "slo"
        assert bench.metric_name(args) == \
            "slo_capture_plane_imgs_per_s_4prompt"
        assert bench.metric_unit(args) == "imgs/s"

    def test_throughput_tolerance_registered(self):
        bench = self._bench()
        assert bench.CHECK_TOLERANCE_PCT[
            "slo_capture_plane_imgs_per_s_4prompt"] == 15.0
        fresh = {"metric": "slo_capture_plane_imgs_per_s_4prompt",
                 "value": 50.0, "unit": "imgs/s"}
        base = {"metric": "slo_capture_plane_imgs_per_s_4prompt",
                "value": 75.0, "unit": "imgs/s"}
        assert bench.check_regression(fresh, base)["regressed"]
        assert not bench.check_regression(base, dict(base))["regressed"]
