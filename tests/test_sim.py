"""Traffic twin (ISSUE 19): determinism, replay robustness,
calibration and the policy-sweep surface.

The simulator's value proposition is falsifiable three ways and each
gets a test class here: a (seed, scenario) pair must fully determine
the event log (byte-identical digests across runs), the capture-replay
adapter must survive torn segment tails without drifting the virtual
clock, and the committed scenario fixtures must keep reproducing the
measured bench artifacts (the same gate ``bench.py --phase sim``
enforces, run in-tree so a policy change that un-calibrates the twin
fails fast).
"""

import copy
import json
import os

import pytest

from comfyui_distributed_tpu.sim import calibrate
from comfyui_distributed_tpu.sim import fleet
from comfyui_distributed_tpu.sim import replay as replay_mod
from comfyui_distributed_tpu.sim import scenario as sc_mod
from comfyui_distributed_tpu.sim import sweep as sweep_mod
from comfyui_distributed_tpu.sim.engine import Engine, VirtualClock
from comfyui_distributed_tpu.utils import constants as C

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCEN = os.path.join(ROOT, "benchmarks", "scenarios")


def _spec(**over):
    """A small but policy-dense scenario: 3 classes, chaos on the
    completion edge, an autoscaler, a mid-window worker kill and one
    fan-out job — every subsystem on, still <1s to run."""
    spec = {
        "name": "unit",
        "seed": 1234,
        "duration_s": 6.0,
        "traffic": [
            {"cls": "paid", "rate": 3.0, "clients": 2, "slo_s": 30.0},
            {"cls": "free", "rate": 2.0, "clients": 2},
            {"cls": "batch", "rate": 2.0, "clients": 1},
        ],
        "jobs": [{"t": 1.5, "cls": "paid", "units": 4, "slo_s": 30.0,
                  "service_s": 2.0}],
        "service": {"model": "lognormal", "mean_s": 0.3,
                    "sigma": 0.4, "min_s": 0.05},
        "workers": 2,
        "admission": {"max_queue": 32,
                      "shed": {"paid": 1.0, "free": 0.65,
                               "batch": 0.3},
                      "rate": 1000.0, "burst": 1000.0},
        "cluster": {"lease_s": 2.0, "suspect_probes": 2},
        "hedge": {"enabled": True, "min_wait_s": 1.0, "sweep_s": 0.5},
        "autoscale": {"min_workers": 2, "max_workers": 4,
                      "up_queue": 2.0, "down_queue": 0.5,
                      "window": 2, "cooldown_s": 1.0,
                      "interval_s": 0.25, "drain_s": 5.0},
        "chaos": {"drop_pct": 10, "delay_pct": 10, "delay_s": 0.05,
                  "seed": 5,
                  "routes": ["/distributed/job_complete"]},
        "faults": [{"t": 2.0, "kind": "kill_worker", "id": "w1"}],
        "drain_limit_s": 60.0,
    }
    spec.update(over)
    return spec


class TestVirtualClock:
    def test_sleep_is_banned(self):
        clk = VirtualClock()
        with pytest.raises(RuntimeError):
            clk.sleep(0.1)

    def test_engine_orders_ties_by_schedule_sequence(self):
        eng = Engine()
        seen = []
        eng.at(1.0, lambda: seen.append("a"))
        eng.at(1.0, lambda: seen.append("b"))
        eng.at(0.5, lambda: seen.append("c"))
        eng.run(until=2.0)
        assert seen == ["c", "a", "b"]
        assert eng.clock.now == pytest.approx(1.0)


class TestDeterminism:
    def test_same_seed_same_log_and_summary(self):
        s1 = fleet.run_scenario(sc_mod.from_dict(_spec()))
        s2 = fleet.run_scenario(sc_mod.from_dict(_spec()))
        assert s1["log_digest"] == s2["log_digest"]
        assert s1 == s2

    def test_different_seed_different_world(self):
        s1 = fleet.run_scenario(sc_mod.from_dict(_spec()))
        s2 = fleet.run_scenario(sc_mod.from_dict(_spec(seed=99)))
        assert s1["log_digest"] != s2["log_digest"]

    def test_env_seed_override(self, monkeypatch):
        monkeypatch.setenv(C.SIM_SEED_ENV, "99")
        s_env = fleet.run_scenario(sc_mod.from_dict(_spec()))
        monkeypatch.delenv(C.SIM_SEED_ENV)
        s99 = fleet.run_scenario(sc_mod.from_dict(_spec(seed=99)))
        assert s_env["log_digest"] == s99["log_digest"]

    def test_committed_fixtures_are_deterministic(self):
        for name in ("overload_r09.json", "multimaster_r14.json"):
            path = os.path.join(SCEN, name)
            s1 = fleet.run_scenario(sc_mod.load_scenario(path))
            s2 = fleet.run_scenario(sc_mod.load_scenario(path))
            assert s1["log_digest"] == s2["log_digest"], name

    def test_fleet_drains_and_books_balance(self):
        s = fleet.run_scenario(sc_mod.from_dict(_spec()))
        assert s["drained"]
        assert s["completed_total"] == s["admitted_total"]
        assert s["completion_rate"] == 1.0
        # the fan-out job rides outside the per-class books
        assert s["fanout"]["jobs"] == 1
        assert s["fanout"]["completed"] == 1
        per_cls_done = sum(v["completed"]
                          for v in s["per_class"].values())
        assert per_cls_done == s["completed_total"]


def _write_segment(dir_path, name, lines):
    os.makedirs(dir_path, exist_ok=True)
    with open(os.path.join(dir_path, name), "w",
              encoding="utf-8") as f:
        for line in lines:
            f.write(line + "\n")


def _rec(pid, fin, dur, tenant="paid", client="c1", worker_s=None):
    spans = [{"span_id": "root", "name": "job_e2e",
              "duration_s": dur,
              "attrs": {"tenant": tenant, "client_id": client}}]
    if worker_s is not None:
        spans.append({"span_id": "s2", "name": "denoise",
                      "duration_s": worker_s,
                      "attrs": {"worker": "w0"}})
    return json.dumps({"schema": 1, "prompt_id": pid,
                       "trace_id": f"t{pid}", "status": "done",
                       "root_span_id": "root", "duration_s": dur,
                       "finished_at": fin, "spans": spans})


class TestReplayAdapter:
    def test_arrivals_normalized_and_classed(self, tmp_path):
        d = str(tmp_path / "cap")
        _write_segment(d, "capture-000001.jsonl", [
            _rec("p1", fin=100.0, dur=2.0, tenant="free",
                 worker_s=0.5),
            _rec("p2", fin=99.0, dur=1.0, tenant="batch"),
        ])
        arrivals, stats = replay_mod.load_arrivals(d)
        assert stats == {"records": 2, "skipped_lines": 0,
                         "skipped_records": 0, "window_s": 0.0}
        # both arrive at t=98 -> normalized to 0; sorted & stable
        assert [a["t"] for a in arrivals] == [0.0, 0.0]
        assert {a["cls"] for a in arrivals} == {"free", "batch"}
        free = next(a for a in arrivals if a["cls"] == "free")
        assert free["service_s"] == pytest.approx(0.5)
        batch = next(a for a in arrivals if a["cls"] == "batch")
        assert "service_s" not in batch   # no worker span -> model

    def test_torn_lines_skipped_without_clock_drift(self, tmp_path):
        clean = str(tmp_path / "clean")
        torn = str(tmp_path / "torn")
        recs = [_rec("p1", 10.0, 1.0), _rec("p2", 12.0, 1.5),
                _rec("p3", 15.0, 2.0)]
        _write_segment(clean, "capture-000001.jsonl", recs)
        _write_segment(torn, "capture-000001.jsonl", [
            recs[0],
            recs[1][:37],                       # torn mid-record
            json.dumps({"schema": 999, "finished_at": 1.0,
                        "duration_s": 1.0}),    # future schema
            recs[1],
            json.dumps({"schema": 1, "spans": []}),  # no timestamps
            recs[2],
        ])
        a_clean, s_clean = replay_mod.load_arrivals(clean)
        a_torn, s_torn = replay_mod.load_arrivals(torn)
        assert a_torn == a_clean          # same origin, same spacing
        assert s_torn["records"] == 3
        assert s_torn["skipped_lines"] == 2
        assert s_torn["skipped_records"] == 1
        assert s_clean["skipped_lines"] == 0

    def test_replay_spec_runs_deterministically(self, tmp_path):
        d = str(tmp_path / "cap")
        _write_segment(d, "capture-000001.jsonl", [
            _rec(f"p{i}", fin=10.0 + 0.4 * i, dur=0.3,
                 tenant=("paid", "free")[i % 2], worker_s=0.1)
            for i in range(20)
        ])
        spec, stats = replay_mod.build_replay_spec(
            d, base=_spec(duration_s=0.0, jobs=[], faults=[]))
        assert stats["records"] == 20
        assert "traffic" not in spec
        s1 = fleet.run_scenario(sc_mod.from_dict(spec))
        s2 = fleet.run_scenario(sc_mod.from_dict(copy.deepcopy(spec)))
        assert s1["log_digest"] == s2["log_digest"]
        assert s1["drained"]
        assert s1["completed_total"] == 20

    def test_empty_capture_dir(self, tmp_path):
        arrivals, stats = replay_mod.load_arrivals(
            str(tmp_path / "nope"))
        assert arrivals == []
        assert stats["records"] == 0


class TestCalibration:
    """The in-tree copy of the ``bench.py --phase sim`` gate: the
    committed fixtures must keep reproducing the measured artifacts.
    A change to scheduler/cluster/autoscale policy code that breaks
    this is a real behavior change — recalibrate deliberately (see
    benchmarks/README) or fix the regression."""

    def _score(self, kind, scn, art):
        with open(os.path.join(ROOT, art)) as f:
            artifact = json.load(f)
        summary = fleet.run_scenario(
            sc_mod.load_scenario(os.path.join(SCEN, scn)))
        return calibrate.SCORERS[kind](summary, artifact)

    def test_overload_fixture_within_gate(self):
        score = self._score("overload", "overload_r09.json",
                            "BENCH_overload_r09.json")
        assert score["bars_failed"] == []
        assert score["mean_rel_err"] <= C.SIM_CALIBRATION_MAX_ERR

    def test_multimaster_fixture_within_gate(self):
        score = self._score("multimaster", "multimaster_r14.json",
                            "BENCH_multimaster_r14.json")
        assert score["bars_failed"] == []
        assert score["mean_rel_err"] <= C.SIM_CALIBRATION_MAX_ERR

    def test_combine_matches_committed_artifact(self):
        scores = {
            "overload": self._score("overload", "overload_r09.json",
                                    "BENCH_overload_r09.json"),
            "multimaster": self._score("multimaster",
                                       "multimaster_r14.json",
                                       "BENCH_multimaster_r14.json"),
        }
        comb = calibrate.combine(scores)
        assert comb["ok"]
        with open(os.path.join(ROOT, "BENCH_sim_r19.json")) as f:
            committed = json.load(f)
        assert comb["calibration_error"] == committed["value"]

    def test_failed_bar_inflates_error(self):
        score = calibrate._score(
            [("x", 1.0, 1.0)], [("bar_a", False), ("bar_b", True)])
        assert score["bars_failed"] == ["bar_a"]
        assert score["calibration_error"] >= 1.0


class TestSweep:
    def test_shed_sweep_moves_batch_first(self):
        with open(os.path.join(SCEN, "overload_r09.json")) as f:
            base = json.load(f)
        results = sweep_mod.run_sweep(base, "admission.shed.batch",
                                      [0.1, 0.8])
        sheds = [r["summary"]["per_class"]["batch"]["shed_overload"]
                 for r in results]
        # a LOWER shed bar sheds batch earlier/harder — causal, same
        # seed everywhere
        assert sheds[0] > sheds[1]
        # the base spec must not bleed across runs
        assert base["admission"]["shed"]["batch"] == 0.3
        table = sweep_mod.format_table(results)
        assert "admission.shed.batch" in table
        assert "batch_shed" in table

    def test_parse_values(self):
        assert sweep_mod.parse_values("0.1,2,true,exp") == \
            [0.1, 2, True, "exp"]


class TestScaleSmoke:
    def test_midsize_fleet_drains_quickly(self):
        """A 100-worker diurnal slice: the same shape as the bench's
        1000-worker scale proof (that one lives in ``bench.py --phase
        sim`` where its ~30s wall budget belongs), small enough for
        the tier-1 gate."""
        spec = {
            "name": "scale_smoke", "seed": 7, "duration_s": 120.0,
            "traffic": [
                {"cls": "paid", "rate": 8.0, "pattern": "diurnal",
                 "period_s": 120.0, "amplitude": 0.5, "clients": 16},
                {"cls": "batch", "rate": 4.0, "pattern": "burst",
                 "burst_at": 60.0, "burst_x": 2.0,
                 "burst_dur_s": 20.0, "clients": 8},
            ],
            "service": {"model": "lognormal", "mean_s": 6.0,
                        "sigma": 0.5, "min_s": 0.2},
            "workers": 100,
            "admission": {"max_queue": 512, "rate": 1000.0,
                          "burst": 1000.0},
            "cluster": {"lease_s": 10.0, "heartbeat_s": 3.0,
                        "sweep_s": 2.0},
            "hedge": {"enabled": True, "min_wait_s": 20.0,
                      "sweep_s": 10.0},
            "chaos": {},
            "faults": [{"t": 30.0, "kind": "kill_worker",
                        "id": "w5"}],
            "drain_limit_s": 120.0,
        }
        s = fleet.run_scenario(sc_mod.from_dict(spec))
        assert s["drained"]
        assert s["completion_rate"] == 1.0
        assert s["admitted_total"] > 1000
        assert s["counters"].get("worker_kills") == 1


class TestCliSim:
    def test_run_and_sweep_and_replay(self, tmp_path, capsys):
        from comfyui_distributed_tpu import cli
        rc = cli.main(["sim", "run",
                       os.path.join(SCEN, "multimaster_r14.json"),
                       "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["drained"]
        assert out["takeover"]["successor"] == "m0"

        rc = cli.main(["sim", "sweep",
                       os.path.join(SCEN, "multimaster_r14.json"),
                       "--param", "traffic.0.rate",
                       "--values", "1.0,2.0"])
        assert rc == 0
        assert "completion" in capsys.readouterr().out

        d = str(tmp_path / "cap")
        _write_segment(d, "capture-000001.jsonl", [
            _rec("p1", 5.0, 0.5, worker_s=0.2),
            "not json at all",
        ])
        rc = cli.main(["sim", "replay", d, "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["replay"]["records"] == 1
        assert out["replay"]["skipped_lines"] == 1

    def test_replay_empty_dir_fails_loudly(self, tmp_path, capsys):
        from comfyui_distributed_tpu import cli
        rc = cli.main(["sim", "replay", str(tmp_path / "none")])
        assert rc == 1
        assert "no replayable records" in capsys.readouterr().err
