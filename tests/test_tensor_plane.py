"""Device-resident tensor plane: transfer counters, retrace guards,
buffer donation, warmup and the persistent compile cache.

The acceptance contract for the data plane (ISSUE 1): on a repeated SPMD
txt2img workflow the KSampler -> VAEDecode -> DistributedCollector spine
moves ZERO bytes through host (the XLA program IS the data plane; the only
fetch is the PNG edge), and the second run re-traces NOTHING (compilation
is a one-time cost).  All measurable on the virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.ops.base import (
    DeviceImage,
    DeviceLatent,
    OpContext,
    as_device_array,
    as_image_array,
)
from comfyui_distributed_tpu.parallel import mesh as mesh_mod
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.workflow import WorkflowExecutor, parse_workflow

TXT2IMG = "/root/repo/workflows/distributed-txt2img.json"


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture
def ctx():
    return OpContext(runtime=mesh_mod.MeshRuntime(mesh=mesh_mod.build_mesh()))


def _scaled_txt2img(width=64, height=64, steps=2, batch=1):
    g = parse_workflow(TXT2IMG)
    g.nodes["5"].inputs.update(width=width, height=height,
                               batch_size=batch)
    g.nodes["3"].inputs.update(steps=steps)
    return g


def _nodes_by_type(g):
    return {g.nodes[n].class_type: n for n in g.nodes}


class TestDeviceWrappers:
    def test_jnp_consumption_stays_on_device(self):
        """jnp.asarray takes the __jax_array__ fast path: no d2h."""
        img = DeviceImage(jnp.ones((2, 8, 8, 3)), fanout=2)
        before = trace_mod.GLOBAL_TRANSFERS.total("d2h")
        arr = jnp.asarray(img)
        assert isinstance(arr, jax.Array)
        assert trace_mod.GLOBAL_TRANSFERS.total("d2h") == before
        assert as_device_array(img) is img.data

    def test_numpy_consumption_is_counted(self):
        img = DeviceImage(jnp.ones((2, 8, 8, 3)))
        before = trace_mod.GLOBAL_TRANSFERS.total("d2h")
        arr = np.asarray(img)
        assert arr.shape == (2, 8, 8, 3) and arr.dtype == np.float32
        assert trace_mod.GLOBAL_TRANSFERS.total("d2h") - before \
            == arr.nbytes

    def test_as_image_array_is_a_counted_host_edge(self):
        lat = DeviceLatent(jnp.zeros((1, 4, 4, 4)), local_batch=1)
        before = trace_mod.GLOBAL_TRANSFERS.total("d2h")
        out = as_image_array(lat)
        assert out.shape == (1, 4, 4, 4)
        assert trace_mod.GLOBAL_TRANSFERS.total("d2h") > before

    def test_host_input_pays_one_h2d_put(self):
        before = trace_mod.GLOBAL_TRANSFERS.total("h2d")
        arr = as_device_array(np.zeros((2, 4, 4, 4), np.float32))
        assert isinstance(arr, jax.Array)
        assert trace_mod.GLOBAL_TRANSFERS.total("h2d") - before \
            == 2 * 4 * 4 * 4 * 4

    def test_metadata_rides_the_wrapper(self):
        img = DeviceImage(jnp.ones((4, 8, 8, 3)), local_batch=2, fanout=2)
        assert img.fanout == 2 and img.local_batch == 2
        assert len(img) == 4 and img.ndim == 4


class TestWorkflowTensorPlane:
    def test_spine_moves_zero_host_bytes(self, ctx):
        """KSampler -> VAEDecode -> Collector in SPMD mode: 0 d2h bytes;
        the ONLY fetch is the Preview/Save PNG edge."""
        g = _scaled_txt2img()
        res = WorkflowExecutor(ctx).execute(g)
        by_type = _nodes_by_type(g)
        spine = [by_type["KSampler"], by_type["VAEDecode"],
                 by_type["DistributedCollector"]]
        assert res.host_transfer_bytes("d2h", nodes=spine) == 0, \
            res.transfers
        # the true host edge did fetch (8 replicas x 16x16x3 float32)
        preview = by_type["PreviewImage"]
        assert res.transfers[preview]["d2h_bytes"] \
            == 8 * 16 * 16 * 3 * 4
        assert len(res.images) == 8

    def test_collector_output_stays_on_device(self, ctx):
        g = _scaled_txt2img()
        res = WorkflowExecutor(ctx).execute(g)
        coll_out = res.outputs[_nodes_by_type(g)["DistributedCollector"]][0]
        assert isinstance(coll_out, DeviceImage)
        assert coll_out.shape[0] == 8

    def test_second_run_retraces_nothing(self, ctx):
        """The CI retrace guard: a repeated workflow must hit every jit
        cache — zero jaxpr traces, zero XLA compiles."""
        g = _scaled_txt2img()
        WorkflowExecutor(ctx).execute(g)
        res2 = WorkflowExecutor(OpContext(runtime=ctx.runtime)).execute(g)
        assert res2.retraces == {"traces": 0, "compiles": 0}

    def test_results_unchanged_by_tensor_plane(self, ctx):
        """Determinism across runs survives the device-resident rewrite
        (same guarantee test_workflow::test_determinism makes, asserted
        here against the transfer-free path)."""
        r1 = WorkflowExecutor(ctx).execute(_scaled_txt2img())
        r2 = WorkflowExecutor(
            OpContext(runtime=ctx.runtime)).execute(_scaled_txt2img())
        assert np.allclose(np.stack(r1.images), np.stack(r2.images))


class TestDonation:
    def _pipe(self):
        return registry.load_pipeline("donation_test.safetensors",
                                      family_name="tiny")

    def _inputs(self, pipe, batch=1):
        ctx_arr, _ = pipe.encode_prompt(["x"])
        context = jnp.repeat(ctx_arr, batch, axis=0)
        lat = jnp.zeros((batch, 8, 8, pipe.family.latent_channels),
                        jnp.float32)
        return lat, context

    def test_donated_latent_buffer_is_invalidated(self):
        pipe = self._pipe()
        lat, context = self._inputs(pipe)
        out = pipe.sample(lat, context, context,
                          np.zeros((1,), np.uint64), steps=1, cfg=7.5,
                          sampler_name="euler", scheduler="normal",
                          donate_latents=True)
        jax.block_until_ready(out)
        assert lat.is_deleted(), \
            "donate_latents=True must hand the input buffer to XLA"

    def test_undonated_latent_buffer_survives(self):
        pipe = self._pipe()
        lat, context = self._inputs(pipe)
        out = pipe.sample(lat, context, context,
                          np.zeros((1,), np.uint64), steps=1, cfg=7.5,
                          sampler_name="euler", scheduler="normal",
                          donate_latents=False)
        jax.block_until_ready(out)
        assert not lat.is_deleted()
        np.asarray(lat)  # still readable

    def test_donation_does_not_change_numerics(self):
        pipe = self._pipe()
        lat, context = self._inputs(pipe)
        kw = dict(steps=2, cfg=7.5, sampler_name="euler",
                  scheduler="normal")
        a = np.asarray(pipe.sample(lat, context, context,
                                   np.zeros((1,), np.uint64),
                                   donate_latents=False, **kw))
        lat2, _ = self._inputs(pipe)
        b = np.asarray(pipe.sample(lat2, context, context,
                                   np.zeros((1,), np.uint64),
                                   donate_latents=True, **kw))
        assert np.allclose(a, b)

    def test_ksampler_never_donates_a_shared_graph_buffer(self, ctx):
        """The SAME latent output feeding TWO KSampler nodes (fan
        topology): the second consumer must still see a live buffer —
        prep donates only buffers it freshly created."""
        from comfyui_distributed_tpu.ops.base import get_op
        pipe = self._pipe()
        ks = get_op("KSampler")
        octx = OpContext()
        lat_d = {"samples": np.zeros((1, 8, 8, 4), np.float32),
                 "local_batch": 1, "fanout": 1}
        ctx_arr, _ = pipe.encode_prompt(["x"])
        from comfyui_distributed_tpu.ops.base import Conditioning
        cond = Conditioning(context=ctx_arr)
        (first,) = ks.execute(octx, pipe, 7, 1, 7.5, "euler", "normal",
                              positive=cond, negative=cond,
                              latent_image=lat_d)
        # both consumers read the same upstream dict
        (a,) = ks.execute(octx, pipe, 8, 1, 7.5, "euler", "normal",
                          positive=cond, negative=cond, latent_image=first)
        (b,) = ks.execute(octx, pipe, 9, 1, 7.5, "euler", "normal",
                          positive=cond, negative=cond, latent_image=first)
        np.asarray(a["samples"]), np.asarray(b["samples"])  # both live


class TestWarmupAndCompileCache:
    def test_warmup_precompiles_the_serving_shape(self):
        pipe = registry.load_pipeline("warmup_test.safetensors",
                                      family_name="tiny")
        t = pipe.warmup(height=64, width=64, batch=1, steps=2)
        assert t["total_s"] > 0 and "sample_s" in t
        # an identically-shaped request afterwards re-traces nothing
        trace_mod.install_jax_monitoring()
        mark = trace_mod.GLOBAL_RETRACES.mark()
        t2 = pipe.warmup(height=64, width=64, batch=1, steps=2)
        assert trace_mod.GLOBAL_RETRACES.since(mark)["traces"] == 0
        assert t2["sample_s"] <= t["sample_s"]

    def test_persistent_cache_configures_and_exports_env(self, tmp_path,
                                                         monkeypatch):
        import os

        from comfyui_distributed_tpu.runtime import manager as mgr
        prev_dir = mgr._compile_cache_dir
        prev_cfg = jax.config.jax_compilation_cache_dir
        monkeypatch.setattr(mgr, "_compile_cache_dir", None)
        d = str(tmp_path / "xla_cache")
        try:
            out = mgr.enable_persistent_compile_cache(d)
            assert out == d
            assert jax.config.jax_compilation_cache_dir == d
            # spawned workers inherit the resolved dir -> shared cache
            assert os.environ["DTPU_COMPILE_CACHE_DIR"] == d
            # idempotent
            assert mgr.enable_persistent_compile_cache(d) == d
        finally:
            # put the session-wide cache (conftest) back: this test must
            # not redirect every later compile into a deleted tmp dir
            jax.config.update("jax_compilation_cache_dir", prev_cfg)
            mgr._compile_cache_dir = prev_dir
            if prev_cfg:
                os.environ["DTPU_COMPILE_CACHE_DIR"] = prev_cfg

    def test_persistent_cache_env_disable(self, monkeypatch):
        from comfyui_distributed_tpu.runtime import manager as mgr
        monkeypatch.setattr(mgr, "_compile_cache_dir", None)
        monkeypatch.setenv("DTPU_COMPILE_CACHE_DIR", "off")
        assert mgr.enable_persistent_compile_cache() is None


class TestShardMapShim:
    def test_shim_accepts_check_vma_on_installed_jax(self):
        """The seed's `from jax import shard_map` broke 6 test modules on
        JAX without the top-level export; the shim must serve both the
        old check_rep and new check_vma spellings."""
        from comfyui_distributed_tpu.parallel import collectives as coll
        mesh = mesh_mod.build_mesh()
        x = np.arange(8 * 2, dtype=np.float32).reshape(8, 2)
        xs = coll.shard_batch(x, mesh)
        full = np.asarray(coll.all_gather_data(xs, mesh))
        assert np.allclose(full, x)
