"""Ring attention (seq-parallel) and the Pallas flash kernel vs the plain
softmax oracle — exact-match requirements on the 8-device virtual mesh
(SURVEY.md §4: collectives testable single-process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.parallel.mesh import build_mesh
from comfyui_distributed_tpu.parallel.ring import (
    attention_reference,
    ring_attention,
)


def _qkv(rng, B=2, N=32, H=4, D=16, M=None):
    M = M or N
    q = rng.standard_normal((B, N, H, D)).astype(np.float32)
    k = rng.standard_normal((B, M, H, D)).astype(np.float32)
    v = rng.standard_normal((B, M, H, D)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


class TestRingAttention:
    @pytest.mark.parametrize("seq_size", [1, 2, 4])
    def test_matches_reference(self, rng, seq_size):
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": seq_size,
                           }, devices=jax.devices()[:seq_size])
        q, k, v = _qkv(rng)
        out = ring_attention(q, k, v, mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("seq_size", [2, 4])
    def test_causal_matches_reference(self, rng, seq_size):
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": seq_size,
                           }, devices=jax.devices()[:seq_size])
        q, k, v = _qkv(rng, N=64)
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_sequence(self, rng):
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=30)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh)

    def test_cross_attention_matches_reference(self, rng):
        """Nk != Nq (cross-attention): both axes shard over the ring."""
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64, M=32)
        out = ring_attention(q, k, v, mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_kv(self, rng):
        """ADVICE r1: k/v divisibility was unvalidated."""
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64, M=30)
        with pytest.raises(ValueError, match="k/v length"):
            ring_attention(q, k, v, mesh)

    def test_rejects_causal_cross_attention(self, rng):
        """ADVICE r1: causal cross-attention was silently mis-masked."""
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64, M=32)
        with pytest.raises(ValueError, match="causal ring"):
            ring_attention(q, k, v, mesh, causal=True)

    def test_sharded_inputs_roundtrip(self, rng):
        """Works with inputs actually placed with the seq sharding (the way
        the sp train/inference path feeds it)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64)
        sh = NamedSharding(mesh, P(None, "seq", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out = ring_attention(qs, ks, vs, mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestRingIntegration:
    """attn_impl='ring' wired through the model stack (VERDICT r2 #4): the
    sequence-parallel path must be reachable from model configs and match
    the single-device math through real modules, not just standalone."""

    @pytest.fixture
    def seq_mesh(self, monkeypatch):
        from comfyui_distributed_tpu.parallel import mesh as mesh_mod
        monkeypatch.setenv("DTPU_RING_MIN_TOKENS", "1")
        mesh = build_mesh({"data": 2, "tensor": 1, "seq": 2},
                          devices=jax.devices()[:4])
        prev = mesh_mod._runtime
        mesh_mod.set_runtime(mesh_mod.MeshRuntime(mesh=mesh))
        yield mesh
        mesh_mod.set_runtime(prev)

    def test_spatial_transformer_ring_matches_xla(self, rng, seq_mesh):
        from comfyui_distributed_tpu.models.layers import SpatialTransformer
        x = jnp.asarray(rng.standard_normal((2, 8, 8, 32)), jnp.float32)
        ctx = jnp.asarray(rng.standard_normal((2, 16, 32)), jnp.float32)
        st_x = SpatialTransformer(num_heads=2, dtype=jnp.float32,
                                  attn_impl="xla")
        st_r = SpatialTransformer(num_heads=2, dtype=jnp.float32,
                                  attn_impl="ring")
        params = st_x.init(jax.random.PRNGKey(0), x, ctx)
        out_x = st_x.apply(params, x, ctx)
        out_r = st_r.apply(params, x, ctx)   # same params: impl-agnostic
        np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_x),
                                   rtol=2e-5, atol=2e-5)

    def test_unet_forward_ring_matches_oracle(self, rng, seq_mesh):
        import dataclasses
        from comfyui_distributed_tpu.models.unet import TINY_CONFIG, UNet
        x = jnp.asarray(rng.standard_normal((2, 8, 8, 4)), jnp.float32)
        ts = jnp.asarray([3.0, 7.0], jnp.float32)
        ctx = jnp.asarray(rng.standard_normal((2, 16, 64)), jnp.float32)
        m_x = UNet(TINY_CONFIG)
        m_r = UNet(dataclasses.replace(TINY_CONFIG, attn_impl="ring"))
        params = m_x.init(jax.random.PRNGKey(0), x, ts, ctx)
        out_x = m_x.apply(params, x, ts, ctx)
        out_r = m_r.apply(params, x, ts, ctx)
        np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_x),
                                   rtol=1e-4, atol=1e-4)

    @pytest.fixture
    def seq_mesh_default(self, monkeypatch):
        """seq>=2 mesh with the DEFAULT ring threshold — no
        DTPU_RING_MIN_TOKENS override anywhere in the test."""
        from comfyui_distributed_tpu.parallel import mesh as mesh_mod
        monkeypatch.delenv("DTPU_RING_MIN_TOKENS", raising=False)
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 2},
                          devices=jax.devices()[:2])
        prev = mesh_mod._runtime
        mesh_mod.set_runtime(mesh_mod.MeshRuntime(mesh=mesh))
        yield mesh
        mesh_mod.set_runtime(prev)

    @pytest.fixture
    def ring_counter(self, monkeypatch):
        """Counts actual ring_attention invocations — 'ring engaged' must
        be an observation, not an assumption (the impl silently falls
        back to xla below the token floor)."""
        from comfyui_distributed_tpu.parallel import ring as ring_mod
        calls = {"n": 0}
        real = ring_mod.ring_attention

        def counting(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(ring_mod, "ring_attention", counting)
        return calls

    def test_sd_scale_spatial_transformer_default_threshold(
            self, rng, seq_mesh_default, ring_counter):
        """VERDICT r3 #3: a real SpatialTransformer at SD-scale tokens
        (64x64 latent = 4096 tokens, SD1.5's 512px working size) with the
        DEFAULT token floor: ring must actually engage on the
        self-attention (counted) and match the xla path; the 77-token
        cross-attention context silently stays on xla (77 % seq != 0)."""
        from comfyui_distributed_tpu.models.layers import SpatialTransformer
        x = jnp.asarray(rng.standard_normal((1, 64, 64, 32)), jnp.float32)
        ctx = jnp.asarray(rng.standard_normal((1, 77, 32)), jnp.float32)
        st_x = SpatialTransformer(num_heads=2, dtype=jnp.float32,
                                  attn_impl="xla")
        st_r = SpatialTransformer(num_heads=2, dtype=jnp.float32,
                                  attn_impl="ring")
        params = st_x.init(jax.random.PRNGKey(0), x, ctx)
        out_x = st_x.apply(params, x, ctx)
        assert ring_counter["n"] == 0       # xla path never rings
        out_r = st_r.apply(params, x, ctx)
        assert ring_counter["n"] >= 1       # 4096-token self-attn rang
        np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_x),
                                   rtol=2e-5, atol=2e-5)

    def test_sd_scale_unet_forward_default_threshold(
            self, rng, seq_mesh_default, ring_counter):
        """One full UNet forward at a 64x64 latent with the default
        floor: level-0 attention (4096 tokens) and level-1 (1024) both
        ring; output matches the xla UNet bit-for-tolerance."""
        import dataclasses

        from comfyui_distributed_tpu.models.unet import TINY_CONFIG, UNet
        x = jnp.asarray(rng.standard_normal((1, 64, 64, 4)), jnp.float32)
        ts = jnp.asarray([5.0], jnp.float32)
        ctx = jnp.asarray(rng.standard_normal((1, 16, 64)), jnp.float32)
        m_x = UNet(TINY_CONFIG)
        m_r = UNet(dataclasses.replace(TINY_CONFIG, attn_impl="ring"))
        params = m_x.init(jax.random.PRNGKey(0), x, ts, ctx)
        out_x = m_x.apply(params, x, ts, ctx)
        assert ring_counter["n"] == 0
        out_r = m_r.apply(params, x, ts, ctx)
        assert ring_counter["n"] >= 2       # both resolution levels rang
        np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_x),
                                   rtol=1e-4, atol=1e-4)

    def test_short_cross_attention_falls_back(self, rng, seq_mesh,
                                              monkeypatch):
        """77-token text context doesn't divide seq=2: impl='ring' must
        silently use the xla math instead of erroring."""
        from comfyui_distributed_tpu.models.layers import Attention
        x = jnp.asarray(rng.standard_normal((2, 16, 32)), jnp.float32)
        ctx = jnp.asarray(rng.standard_normal((2, 77, 32)), jnp.float32)
        attn = Attention(num_heads=2, dtype=jnp.float32, attn_impl="ring")
        params = attn.init(jax.random.PRNGKey(0), x, ctx)
        ref = Attention(num_heads=2, dtype=jnp.float32, attn_impl="xla")
        np.testing.assert_allclose(
            np.asarray(attn.apply(params, x, ctx)),
            np.asarray(ref.apply(params, x, ctx)), rtol=1e-6, atol=1e-6)


class TestFlashAttention:
    def test_matches_reference(self, rng):
        from comfyui_distributed_tpu.ops.pallas.flash_attention import (
            flash_attention)
        q, k, v = _qkv(rng, B=1, N=200, H=2, D=16)
        out = flash_attention(q, k, v, interpret=True)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_cross_attention_shapes(self, rng):
        from comfyui_distributed_tpu.ops.pallas.flash_attention import (
            flash_attention)
        q, k, v = _qkv(rng, B=2, N=64, H=2, D=16, M=77)
        out = flash_attention(q, k, v, interpret=True)
        ref = attention_reference(q, k, v)
        assert out.shape == (2, 64, 2, 16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_layers_dispatch(self, rng):
        """attn_impl='pallas' routes through the kernel and matches xla."""
        from comfyui_distributed_tpu.models.layers import (
            scaled_dot_product_attention)
        q, k, v = _qkv(rng, B=1, N=48, H=2, D=16)
        out_p = scaled_dot_product_attention(q, k, v, impl="pallas")
        out_x = scaled_dot_product_attention(q, k, v, impl="xla")
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                                   rtol=2e-4, atol=2e-4)

    def test_vmem_guard_falls_back_correctly(self, rng, monkeypatch):
        """Shapes whose full K/V exceed the per-program VMEM budget must
        take the XLA fallback (numerically identical) rather than hand
        pallas_call a program that can't compile on hardware."""
        import importlib
        fa = importlib.import_module(
            "comfyui_distributed_tpu.ops.pallas.flash_attention")
        # shrink the budget so a modest shape trips the guard
        monkeypatch.setattr(fa, "VMEM_BUDGET_BYTES", 64 * 1024)
        called = []
        monkeypatch.setattr(fa.pl, "pallas_call",
                            lambda *a, **k: called.append(1) or fa.pl.pallas_call)
        q, k, v = _qkv(rng, B=1, N=256, H=2, D=16)
        out = fa.flash_attention(q, k, v, interpret=True)
        assert not called, "guard did not divert away from pallas_call"
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestChunkedXLAAttention:
    """Query-chunked score materialization (the r4 on-chip HBM-OOM fix):
    softmax is per-query-row, so chunking N is numerically exact."""

    def test_chunked_matches_unchunked_exactly(self, monkeypatch):
        from comfyui_distributed_tpu.models.layers import xla_attention
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.standard_normal((2, 256, 4, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((2, 77, 4, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, 77, 4, 16)), jnp.float32)
        scale = 0.25
        full = xla_attention(q, k, v, scale)
        # force chunking: ceiling below one row-block's scores
        monkeypatch.setenv("DTPU_ATTN_SCORES_BYTES",
                           str(4 * 2 * 4 * 64 * 77))
        chunked = xla_attention(q, k, v, scale)
        np.testing.assert_array_equal(np.asarray(full),
                                      np.asarray(chunked))

    def test_chunk_picks_divisor(self, monkeypatch):
        """N=96 with a ceiling for ~40 rows -> largest divisor <= 40 is
        32; result still exact."""
        from comfyui_distributed_tpu.models.layers import xla_attention
        rng = np.random.default_rng(11)
        q = jnp.asarray(rng.standard_normal((1, 96, 2, 8)), jnp.float32)
        kv = jnp.asarray(rng.standard_normal((1, 96, 2, 8)), jnp.float32)
        full = xla_attention(q, kv, kv, 0.35)
        monkeypatch.setenv("DTPU_ATTN_SCORES_BYTES",
                           str(4 * 1 * 2 * 40 * 96))
        chunked = xla_attention(q, kv, kv, 0.35)
        np.testing.assert_array_equal(np.asarray(full),
                                      np.asarray(chunked))

    def test_small_shapes_not_chunked_under_jit(self, monkeypatch):
        """The decision is trace-time static: tiny N never chunks even
        with a zero ceiling (N<=128 fast path), and the jitted result
        matches eager."""
        from comfyui_distributed_tpu.models.layers import xla_attention
        monkeypatch.setenv("DTPU_ATTN_SCORES_BYTES", "0")
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((1, 64, 2, 8)), jnp.float32)
        out_e = xla_attention(q, q, q, 0.3)
        out_j = jax.jit(lambda a: xla_attention(a, a, a, 0.3))(q)
        np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_j),
                                   rtol=2e-6, atol=2e-6)
