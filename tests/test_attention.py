"""Ring attention (seq-parallel) and the Pallas flash kernel vs the plain
softmax oracle — exact-match requirements on the 8-device virtual mesh
(SURVEY.md §4: collectives testable single-process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.parallel.mesh import build_mesh
from comfyui_distributed_tpu.parallel.ring import (
    attention_reference,
    ring_attention,
)


def _qkv(rng, B=2, N=32, H=4, D=16, M=None):
    M = M or N
    q = rng.standard_normal((B, N, H, D)).astype(np.float32)
    k = rng.standard_normal((B, M, H, D)).astype(np.float32)
    v = rng.standard_normal((B, M, H, D)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


class TestRingAttention:
    @pytest.mark.parametrize("seq_size", [1, 2, 4])
    def test_matches_reference(self, rng, seq_size):
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": seq_size,
                           }, devices=jax.devices()[:seq_size])
        q, k, v = _qkv(rng)
        out = ring_attention(q, k, v, mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("seq_size", [2, 4])
    def test_causal_matches_reference(self, rng, seq_size):
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": seq_size,
                           }, devices=jax.devices()[:seq_size])
        q, k, v = _qkv(rng, N=64)
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_sequence(self, rng):
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=30)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh)

    def test_cross_attention_matches_reference(self, rng):
        """Nk != Nq (cross-attention): both axes shard over the ring."""
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64, M=32)
        out = ring_attention(q, k, v, mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_kv(self, rng):
        """ADVICE r1: k/v divisibility was unvalidated."""
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64, M=30)
        with pytest.raises(ValueError, match="k/v length"):
            ring_attention(q, k, v, mesh)

    def test_rejects_causal_cross_attention(self, rng):
        """ADVICE r1: causal cross-attention was silently mis-masked."""
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64, M=32)
        with pytest.raises(ValueError, match="causal ring"):
            ring_attention(q, k, v, mesh, causal=True)

    def test_sharded_inputs_roundtrip(self, rng):
        """Works with inputs actually placed with the seq sharding (the way
        the sp train/inference path feeds it)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = build_mesh({"data": 1, "tensor": 1, "seq": 4},
                          devices=jax.devices()[:4])
        q, k, v = _qkv(rng, N=64)
        sh = NamedSharding(mesh, P(None, "seq", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out = ring_attention(qs, ks, vs, mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestFlashAttention:
    def test_matches_reference(self, rng):
        from comfyui_distributed_tpu.ops.pallas.flash_attention import (
            flash_attention)
        q, k, v = _qkv(rng, B=1, N=200, H=2, D=16)
        out = flash_attention(q, k, v, interpret=True)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_cross_attention_shapes(self, rng):
        from comfyui_distributed_tpu.ops.pallas.flash_attention import (
            flash_attention)
        q, k, v = _qkv(rng, B=2, N=64, H=2, D=16, M=77)
        out = flash_attention(q, k, v, interpret=True)
        ref = attention_reference(q, k, v)
        assert out.shape == (2, 64, 2, 16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_layers_dispatch(self, rng):
        """attn_impl='pallas' routes through the kernel and matches xla."""
        from comfyui_distributed_tpu.models.layers import (
            scaled_dot_product_attention)
        q, k, v = _qkv(rng, B=1, N=48, H=2, D=16)
        out_p = scaled_dot_product_attention(q, k, v, impl="pallas")
        out_x = scaled_dot_product_attention(q, k, v, impl="xla")
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                                   rtol=2e-4, atol=2e-4)
