"""Resource telemetry plane (ISSUE 5): probes, rings, monitor, per-job
HBM attribution, fleet federation, freed-bytes clear_memory, build-info
gauge, and the bench perf-regression watchdog.

All CPU-only: the device-memory probe exercises the RSS fallback the CPU
backend forces (its ``memory_stats()`` returns None on this JAX), and
the federation acceptance runs a real loopback master+worker pair over
aiohttp test servers.
"""

import asyncio
import json
import os
import sys
import time

import pytest

from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import resource as res
from comfyui_distributed_tpu.utils import trace as tr

from test_observability import (make_prompt, run_with_client,
                                validate_prometheus)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench
    return bench


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture(autouse=True)
def tracing_on():
    was = tr.tracing_enabled()
    tr.set_tracing(True)
    yield
    tr.set_tracing(was)


FAKE_SNAPSHOT = {
    "t": 1.0, "device_bytes_in_use": 111, "device_peak_bytes": 222,
    "device_bytes_limit": None, "host_rss_bytes": 333,
    "utilization": 0.5, "queue_depth": 2, "source": "memory_stats",
}


def test_prom_families_skip_non_numeric_wire_values():
    # a version-skewed worker shipping "n/a" costs its row, not the
    # whole fleet exposition
    fams = res.resource_prom_families({
        "good": dict(FAKE_SNAPSHOT),
        "bad": {**FAKE_SNAPSHOT, "device_bytes_in_use": "n/a"},
    })
    by_name = {f[0]: f[3] for f in fams}
    in_use = by_name["dtpu_res_device_bytes_in_use"]
    assert [lbl["worker_id"] for lbl, _ in in_use] == ["good"]
    # the bad worker's other, numeric series still render
    rss = by_name["dtpu_res_host_rss_bytes"]
    assert {lbl["worker_id"] for lbl, _ in rss} == {"good", "bad"}


# --- probes ------------------------------------------------------------------

class TestProbes:
    def test_host_rss_positive(self):
        assert res.host_rss_bytes() > 1_000_000
        assert res.host_rss_peak_bytes() >= res.host_rss_bytes() * 0 + 1

    def test_device_snapshot_shape_and_source(self):
        snap = res.device_memory_snapshot()
        assert snap["source"] in ("memory_stats", "host_rss")
        assert snap["bytes_in_use"] > 0
        assert snap["peak_bytes_in_use"] >= 0

    def test_cpu_backend_falls_back_to_rss(self):
        """On a backend whose devices report no memory_stats (the CPU
        backend here), the probe must fall back to host RSS — never
        return zeros or raise."""
        import jax
        if jax.local_devices()[0].memory_stats() is not None:
            pytest.skip("backend reports real memory_stats")
        snap = res.device_memory_snapshot()
        assert snap["source"] == "host_rss"
        assert snap["n_devices"] == 0
        assert snap["bytes_in_use"] == pytest.approx(
            res.host_rss_bytes(), rel=0.5)

    def test_snapshot_now_wire_shape(self):
        snap = res.snapshot_now(queue_depth=7)
        for key in ("t", "device_bytes_in_use", "device_peak_bytes",
                    "host_rss_bytes", "utilization", "queue_depth",
                    "source"):
            assert key in snap
        assert snap["queue_depth"] == 7


# --- ring timeseries ---------------------------------------------------------

class TestRingTimeseries:
    def test_bounded_newest_wins(self):
        ring = res.RingTimeseries("x", maxlen=4)
        for i in range(10):
            ring.append(float(i), float(i * 10))
        assert len(ring) == 4
        assert ring.total_samples == 10
        vals = ring.values()
        assert [t for t, _ in vals] == [6.0, 7.0, 8.0, 9.0]
        assert ring.last() == (9.0, 90.0)

    def test_stats(self):
        ring = res.RingTimeseries("x", maxlen=8)
        assert ring.stats()["n"] == 0
        for i in range(4):
            ring.append(i, i)
        st = ring.stats()
        assert st == {"n": 4, "last": 3.0, "min": 0.0, "max": 3.0,
                      "mean": 1.5}


# --- the monitor -------------------------------------------------------------

class TestResourceMonitor:
    def test_sampling_and_ring_bounds(self):
        m = res.ResourceMonitor(interval=0.01, ring=8,
                                queue_depth_fn=lambda: 3)
        for _ in range(12):
            m.sample_once()
        snap = m.snapshot()
        assert snap["n_samples"] == 12
        assert snap["ring_max"] == 8
        for name, st in snap["series"].items():
            assert st["n"] <= 8, name
        assert snap["series"]["host_rss_bytes"]["n"] == 8
        latest = snap["latest"]
        assert latest["queue_depth"] == 3
        assert latest["host_rss_bytes"] > 0
        assert len(m.series_tail("host_rss_bytes")) == 8
        assert len(m.series_tail("host_rss_bytes", n=3)) == 3

    def test_thread_start_stop_restart(self):
        m = res.ResourceMonitor(interval=0.01, ring=64)
        m.start()
        deadline = time.monotonic() + 2.0
        while m.snapshot()["n_samples"] < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        m.stop(join=True)
        n = m.snapshot()["n_samples"]
        assert n >= 2
        time.sleep(0.05)
        assert m.snapshot()["n_samples"] == n  # actually stopped
        m.start()  # restartable
        assert m.running
        m.stop(join=True)

    def test_utilization_from_stage_timeline(self):
        m = res.ResourceMonitor(interval=0.01, ring=8)
        assert m.sample_once()["utilization"] is None  # needs two marks
        tr.GLOBAL_STAGES.record("compute", 1000.0)
        assert m.sample_once()["utilization"] == 1.0  # clamped busy
        time.sleep(0.02)
        util = m.sample_once()["utilization"]  # no new compute -> idle
        assert util == 0.0

    def test_queue_depth_fn_failure_tolerated(self):
        def boom():
            raise RuntimeError("torn down")
        m = res.ResourceMonitor(interval=0.01, ring=4,
                                queue_depth_fn=boom)
        snap = m.sample_once()
        assert snap["queue_depth"] is None

    def test_latest_samples_on_demand(self):
        m = res.ResourceMonitor(interval=9999, ring=4)
        assert m.latest()["host_rss_bytes"] > 0

    def test_stop_without_join_then_start_keeps_sampling(self):
        # stop() doesn't join; an immediate start() must not see the
        # dying thread as alive, skip the spawn, and leave the monitor
        # permanently dead while running looks True
        m = res.ResourceMonitor(interval=0.01, ring=64)
        m.start()
        m.stop()
        m.start()
        assert m.running
        n0 = m.snapshot()["n_samples"]
        deadline = time.monotonic() + 2.0
        while m.snapshot()["n_samples"] <= n0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        m.stop(join=True)
        assert m.snapshot()["n_samples"] > n0

    def test_weak_callable_does_not_pin_owner(self):
        class Owner:
            def depth(self):
                return 7
        owner = Owner()
        fn = res._weak_callable(owner.depth)
        assert fn() == 7
        import gc
        import weakref
        ref = weakref.ref(owner)
        del owner
        gc.collect()
        assert ref() is None  # the wrapper didn't keep it alive
        m = res.ResourceMonitor(interval=9999, ring=4,
                                queue_depth_fn=fn)
        assert m.sample_once()["queue_depth"] is None  # raises -> None
        plain = lambda: 1  # noqa: E731
        assert res._weak_callable(plain) is plain


# --- per-job attribution -----------------------------------------------------

class TestPerJobAttribution:
    def test_execution_result_and_trace_attrs(self, tmp_path):
        """A real tiny run reports per-run resources + per-node memory
        in ExecutionResult AND stamps memory attrs on the execute span,
        so the flight-recorder trace shows HBM next to latency."""
        from comfyui_distributed_tpu.ops.base import OpContext
        from comfyui_distributed_tpu.parallel.mesh import get_runtime
        from comfyui_distributed_tpu.workflow.executor import \
            WorkflowExecutor

        root = tr.start_span("job", attrs={"prompt_id": "p_res_attr"})
        with tr.use_span(root), tr.span("execute"):
            result = WorkflowExecutor(OpContext(
                runtime=get_runtime(),
                output_dir=str(tmp_path))).execute(make_prompt(seed=3))
        root.end()
        tr.GLOBAL_TRACES.commit("p_res_attr", root.trace_id, status="ok",
                                root_span_id=root.span_id)

        r = result.resources
        assert r["source"] in ("memory_stats", "host_rss")
        assert r["host_rss_bytes"] > 0
        assert r["device_bytes_in_use"] > 0
        assert r["device_peak_delta_bytes"] >= 0
        # every executed node got a memory ledger entry
        assert set(result.node_memory) == set(result.timings)
        for entry in result.node_memory.values():
            assert entry["peak_delta_bytes"] >= 0

        rec = tr.GLOBAL_TRACES.get("p_res_attr")
        execute = [s for s in rec["spans"] if s["name"] == "execute"]
        assert execute, "execute span missing from trace"
        attrs = execute[0].get("attrs") or {}
        assert "device_peak_mb" in attrs
        assert "rss_mb" in attrs and attrs["rss_mb"] > 0
        assert attrs["mem_source"] == r["source"]

    def test_kill_switch_disables_attribution_probes(self, tmp_path,
                                                     monkeypatch):
        """DTPU_RESOURCE=0 must cover the executor's per-node/per-run
        probes on the hot path, not just the monitor thread."""
        from comfyui_distributed_tpu.ops.base import OpContext
        from comfyui_distributed_tpu.parallel.mesh import get_runtime
        from comfyui_distributed_tpu.workflow.executor import \
            WorkflowExecutor

        monkeypatch.setenv(C.RESOURCE_ENV, "0")
        result = WorkflowExecutor(OpContext(
            runtime=get_runtime(),
            output_dir=str(tmp_path))).execute(make_prompt(seed=4))
        assert result.resources == {}
        assert result.node_memory == {}


# --- fleet federation --------------------------------------------------------

class TestFederation:
    def test_merge_master_and_heartbeat_worker(self, tmp_path):
        async def body(client, state):
            r = await client.post("/distributed/heartbeat", json={
                "worker_id": "w0", "port": 1234,
                "resources": dict(FAKE_SNAPSHOT)})
            assert r.status == 200
            r = await client.get("/distributed/cluster/metrics")
            assert r.status == 200
            body = await r.json()
            parts = body["participants"]
            assert set(parts) == {"master", "w0"}
            assert parts["master"]["resources"]["host_rss_bytes"] > 0
            assert parts["master"]["age_s"] == 0.0
            w0 = parts["w0"]
            assert w0["resources"]["device_bytes_in_use"] == 111
            assert w0["age_s"] is not None and w0["age_s"] < 5
            assert w0["stale"] is False
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_prom_exposition_labels_both_participants(self, tmp_path):
        async def body(client, state):
            await client.post("/distributed/heartbeat", json={
                "worker_id": "w0", "port": 1234,
                "resources": dict(FAKE_SNAPSHOT)})
            r = await client.get("/distributed/cluster/metrics.prom")
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            text = await r.text()
            types = validate_prometheus(text)
            assert types["dtpu_res_device_bytes_in_use"] == "gauge"
            assert types["dtpu_res_host_rss_bytes"] == "gauge"
            assert 'dtpu_res_device_bytes_in_use{worker_id="master"}' \
                in text
            assert 'dtpu_res_device_bytes_in_use{worker_id="w0"} 111' \
                in text
            assert 'dtpu_res_utilization_ratio{worker_id="w0"} 0.5' \
                in text
            assert 'dtpu_res_snapshot_age_seconds{worker_id="w0"}' in text
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_stale_snapshot_ages_and_flags(self, tmp_path):
        async def body(client, state):
            await client.post("/distributed/heartbeat", json={
                "worker_id": "w0", "resources": dict(FAKE_SNAPSHOT)})
            # age the retained snapshot past the federation TTL; no
            # host:port -> pull-through can't refresh it, the merged
            # view must serve the cached value marked stale
            with state.cluster._lock:
                state.cluster._workers["w0"]["resources_at"] -= 100.0
                state.cluster._workers["w0"]["info"].pop("host", None)
            r = await client.get("/distributed/cluster/metrics")
            w0 = (await r.json())["participants"]["w0"]
            assert w0["age_s"] > 99
            assert w0["stale"] is True
            assert w0["resources"]["device_bytes_in_use"] == 111
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_pull_through_refreshes_from_loopback_worker(self, tmp_path):
        """Federation acceptance: a REAL loopback worker server is
        registered with no heartbeat snapshot at all; the master's
        merged view pulls GET /distributed/resource from it live and
        caches the result back into the registry."""
        async def go():
            wtmp = tmp_path / "worker"
            wtmp.mkdir()
            wstate = ServerState(config_path=str(wtmp / "cfg.json"),
                                 input_dir=str(wtmp),
                                 output_dir=str(wtmp),
                                 is_worker=True,
                                 start_exec_thread=False)
            wclient = TestClient(TestServer(build_app(wstate)))
            await wclient.start_server()
            mstate = ServerState(config_path=str(tmp_path / "cfg.json"),
                                 input_dir=str(tmp_path),
                                 output_dir=str(tmp_path),
                                 start_exec_thread=False)
            mclient = TestClient(TestServer(build_app(mstate)))
            await mclient.start_server()
            try:
                r = await mclient.post("/distributed/heartbeat", json={
                    "worker_id": "w0", "host": "127.0.0.1",
                    "port": wclient.server.port})  # NO resources field
                assert r.status == 200
                r = await mclient.get("/distributed/cluster/metrics")
                parts = (await r.json())["participants"]
                w0 = parts["w0"]
                assert w0["resources"] is not None, \
                    "pull-through never fetched the worker snapshot"
                assert w0["resources"]["host_rss_bytes"] > 0
                # cached back: the registry now holds it
                reg = mstate.cluster.resource_snapshots()["w0"]
                assert reg["resources"] is not None
                assert reg["age_s"] < 5
                # the prom view shows BOTH participants by worker_id
                text = await (await mclient.get(
                    "/distributed/cluster/metrics.prom")).text()
                validate_prometheus(text)
                assert 'worker_id="master"' in text
                assert 'worker_id="w0"' in text
            finally:
                await mclient.close()
                await wclient.close()
        asyncio.run(go())


# --- clear_memory freed bytes ------------------------------------------------

class TestClearMemoryFreed:
    def test_reports_before_after_and_freed(self, tmp_path):
        async def body(client, state):
            r = await client.post("/distributed/clear_memory")
            assert r.status == 200
            body = await r.json()
            assert body["freed_bytes"] >= 0
            assert body["device_bytes_before"] > 0
            assert body["device_bytes_after"] > 0
            assert body["host_rss_before"] > 0
            assert body["source"] in ("memory_stats", "host_rss")
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_cluster_variant_aggregates(self, tmp_path):
        async def body(client, state):
            r = await client.post("/distributed/cluster/clear_memory")
            assert r.status == 200
            body = await r.json()
            assert body["workers"] == {}  # no configured workers
            assert "master" in body["freed_bytes"]
            assert body["freed_bytes_total"] >= 0
        run_with_client(body, tmp_path, start_exec_thread=False)


# --- local metrics surfaces --------------------------------------------------

class TestLocalMetricsSurfaces:
    def test_json_metrics_resources_block(self, tmp_path):
        async def body(client, state):
            m = await (await client.get("/distributed/metrics")).json()
            blk = m["resources"]
            if blk.get("enabled") is False:
                pytest.skip("DTPU_RESOURCE=0 in this environment")
            assert blk["ring_max"] >= 1
            assert set(blk["series"]) == set(res.SERIES)
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_prom_has_build_info_and_resource_gauges(self, tmp_path):
        async def body(client, state):
            text = await (await client.get(
                "/distributed/metrics.prom")).text()
            types = validate_prometheus(text)
            assert types["dtpu_build_info"] == "gauge"
            line = [l for l in text.splitlines()
                    if l.startswith("dtpu_build_info{")][0]
            assert 'jax="' in line and 'platform="' in line \
                and 'version="' in line
            assert line.rstrip().endswith(" 1")
            assert types["dtpu_res_host_rss_bytes"] == "gauge"
            assert types["dtpu_res_device_bytes_in_use"] == "gauge"
            # unlabelled on the per-process surface
            assert any(l.startswith("dtpu_res_host_rss_bytes ")
                       for l in text.splitlines())
        run_with_client(body, tmp_path, start_exec_thread=False)


# --- bench perf-regression watchdog ------------------------------------------

class TestBenchCheck:
    def _payload(self, value, unit="imgs/s", metric="m"):
        return {"metric": metric, "value": value, "unit": unit}

    def test_flags_injected_20pct_regression(self):
        bench = _bench()
        v = bench.check_regression(self._payload(0.8),
                                   self._payload(1.0),
                                   tolerance_pct=3.0)
        assert v["regressed"] is True
        assert v["change_pct"] == -20.0

    def test_passes_within_tolerance(self):
        bench = _bench()
        v = bench.check_regression(self._payload(0.99),
                                   self._payload(1.0),
                                   tolerance_pct=3.0)
        assert v["regressed"] is False

    def test_improvement_never_regresses(self):
        bench = _bench()
        v = bench.check_regression(self._payload(2.0),
                                   self._payload(1.0),
                                   tolerance_pct=0.0)
        assert v["regressed"] is False

    def test_lower_is_better_direction(self):
        bench = _bench()
        worse = bench.check_regression(
            self._payload(1.3, unit="sec/image"),
            self._payload(1.0, unit="sec/image"), tolerance_pct=10.0)
        assert worse["regressed"] is True
        better = bench.check_regression(
            self._payload(0.8, unit="sec/image"),
            self._payload(1.0, unit="sec/image"), tolerance_pct=10.0)
        assert better["regressed"] is False

    def test_no_baseline_value_passes(self):
        bench = _bench()
        v = bench.check_regression(self._payload(1.0),
                                   self._payload(0.0))
        assert v["regressed"] is False
        assert "note" in v

    def test_per_metric_tolerance_lookup(self):
        bench = _bench()
        v = bench.check_regression(
            self._payload(0.99, metric="fault_recovery_completion_rate",
                          unit="fraction"),
            self._payload(1.0, metric="fault_recovery_completion_rate",
                          unit="fraction"))
        assert v["tolerance_pct"] == 0.0
        assert v["regressed"] is True  # completion rate tolerates nothing

    def test_check_against_non_object_fails_cleanly(self, tmp_path):
        # a valid-JSON but non-object baseline (e.g. a sweep table) must
        # produce the clean rc=1 path, not an AttributeError
        import argparse
        bench = _bench()
        bad = tmp_path / "sweep.json"
        bad.write_text(json.dumps([1, 2, 3]))
        prev = bench._LAST_PAYLOAD
        bench._LAST_PAYLOAD = self._payload(1.0)
        try:
            rc = bench.run_check(argparse.Namespace(
                check_against=str(bad), check_tolerance=None, out=None))
        finally:
            bench._LAST_PAYLOAD = prev
        assert rc == 1

    def test_check_against_metric_mismatch_fails(self, tmp_path):
        # an explicit baseline for a DIFFERENT metric must be an error,
        # not a silently meaningless comparison
        import argparse
        bench = _bench()
        other = tmp_path / "other.json"
        other.write_text(json.dumps(
            {"metric": "other_metric", "value": 9.0, "unit": "imgs/s"}))
        prev = bench._LAST_PAYLOAD
        bench._LAST_PAYLOAD = self._payload(1.0)
        try:
            rc = bench.run_check(argparse.Namespace(
                check_against=str(other), check_tolerance=None,
                out=None))
        finally:
            bench._LAST_PAYLOAD = prev
        assert rc == 1

    def test_find_prior_artifact_scans_and_filters(self, tmp_path):
        bench = _bench()
        (tmp_path / "BENCH_a.json").write_text(json.dumps(
            {"metric": "m1", "value": 1.0, "unit": "imgs/s"}))
        time.sleep(0.02)
        (tmp_path / "BENCH_b.json").write_text(json.dumps(
            {"n": 2, "parsed": {"metric": "m1", "value": 2.0,
                                "unit": "imgs/s"}}))
        (tmp_path / "BENCH_zero.json").write_text(json.dumps(
            {"metric": "m1", "value": 0.0, "unit": "imgs/s"}))
        (tmp_path / "not_bench.json").write_text(json.dumps(
            {"metric": "m1", "value": 9.0}))
        found = bench.find_prior_artifact("m1", search_dir=str(tmp_path))
        assert found is not None
        path, payload = found
        assert path.endswith("BENCH_b.json")  # newest, parsed shape
        assert payload["value"] == 2.0
        assert bench.find_prior_artifact("nope",
                                         search_dir=str(tmp_path)) is None
        # excluding the fresh run's own --out file
        found = bench.find_prior_artifact(
            "m1", search_dir=str(tmp_path),
            exclude=(str(tmp_path / "BENCH_b.json"),))
        assert found[0].endswith("BENCH_a.json")
