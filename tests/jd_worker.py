"""Child process for the jax.distributed multi-host integration test.

Joins the cluster through the framework's own entry points
(``force_cpu_platform`` + ``initialize_multihost`` + ``build_mesh``) —
the same path ``cli.py serve/worker/run`` takes on a real multi-host pod,
with CPU devices standing in for chips and gRPC/Gloo for DCN.  Runs a
cross-process psum and all_gather over the mesh's data axis and prints
JD_OK when the values prove both processes contributed.
"""

import numpy as np

from comfyui_distributed_tpu.parallel.mesh import (
    build_mesh,
    force_cpu_platform,
    initialize_multihost,
)

force_cpu_platform(2)          # 2 local devices/process -> 4 global
initialize_multihost()         # DTPU_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID

import jax                     # noqa: E402  (after platform pin)
import jax.numpy as jnp        # noqa: E402
from jax.experimental.shard_map import shard_map  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()
assert jax.local_device_count() == 2

mesh = build_mesh({"data": 4})
sh = NamedSharding(mesh, P("data"))

# per-process payload: process 0 contributes 1s, process 1 contributes 2s
local = np.full((jax.local_device_count(), 4),
                float(jax.process_index() + 1), np.float32)
x = jax.make_array_from_process_local_data(sh, local)


def f(xs):
    total = jax.lax.psum(xs, "data")                   # cross-host reduce
    gathered = jax.lax.all_gather(xs, "data", axis=0)  # cross-host gather
    return total, gathered


total, gathered = jax.jit(
    shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=(P(), P("data"))))(x)

tv = np.asarray(jax.device_get(total.addressable_data(0)))
assert np.allclose(tv, 1 + 1 + 2 + 2), tv  # both processes contributed
gv = np.asarray(jax.device_get(gathered.addressable_data(0))).reshape(4, 4)
assert sorted(gv[:, 0].tolist()) == [1.0, 1.0, 2.0, 2.0], gv[:, 0]

print("JD_OK", flush=True)
