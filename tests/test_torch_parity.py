"""Cross-framework numerical parity against the torch stack (VERDICT r2
"what's missing" #2: the model zoo had only ever been compared to itself).

No downloads: torch reference models are instantiated from configs with
random weights, their state dicts exported into this framework's checkpoint
converter, and the two frameworks' forward passes compared on identical
inputs.  This proves the converter's layout mapping AND the flax modules'
math against the ecosystem implementation the reference runs on (ComfyUI's
text encoder is transformers-compatible CLIP).
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from comfyui_distributed_tpu.models import checkpoints as ckpt
from comfyui_distributed_tpu.models import clip as clip_mod


def _hf_clip_config(cfg: clip_mod.CLIPConfig):
    return transformers.CLIPTextConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.width,
        intermediate_size=cfg.width * 4,
        num_hidden_layers=cfg.layers,
        num_attention_heads=cfg.heads,
        max_position_embeddings=cfg.max_length,
        hidden_act="quick_gelu",
        eos_token_id=cfg.vocab_size - 1,
        bos_token_id=cfg.vocab_size - 2,
    )


def _load_torch_clip_into_flax(torch_model, cfg):
    sd = {"cond_stage_model.transformer.text_model."
          + k.removeprefix("text_model."): v.detach().numpy()
          for k, v in torch_model.state_dict().items()}
    mapper = ckpt._LoadMapper(sd, ckpt.CLIP_PREFIX_SD15)
    return ckpt._run_clip_hf(mapper, cfg)


@pytest.mark.parametrize("scale", ["tiny", "sd15"])
def test_clip_text_encoder_matches_transformers(scale):
    """flax CLIP forward == transformers CLIPTextModel forward, through the
    real checkpoint key mapping, at tiny scale and at the FULL SD1.5 CLIP-L
    geometry (12 layers / width 768 / vocab 49408)."""
    if scale == "tiny":
        cfg = dataclasses.replace(clip_mod.TINY_CLIP_CONFIG,
                                  vocab_size=512, dtype=jnp.float32)
    else:
        cfg = dataclasses.replace(clip_mod.CLIP_L_CONFIG,
                                  dtype=jnp.float32)
    hf_cfg = _hf_clip_config(cfg)
    torch.manual_seed(0)
    tm = transformers.CLIPTextModel(hf_cfg).eval()

    params = _load_torch_clip_into_flax(tm, cfg)

    rng = np.random.default_rng(0)
    B = 2
    ids = rng.integers(1, cfg.vocab_size - 2,
                       (B, cfg.max_length)).astype(np.int64)
    ids[:, 0] = cfg.vocab_size - 2            # BOS
    ids[:, 10] = cfg.vocab_size - 1           # EOS mid-sequence
    ids[:, 11:] = cfg.vocab_size - 1          # padded with EOS (CLIP-style)

    with torch.no_grad():
        out = tm(input_ids=torch.from_numpy(ids))
    ref_hidden = out.last_hidden_state.numpy()
    ref_pooled = out.pooler_output.numpy()

    fm = clip_mod.CLIPTextModel(cfg)
    hidden, pooled = fm.apply({"params": params},
                              jnp.asarray(ids, jnp.int32))
    tol = dict(rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(hidden), ref_hidden, **tol)
    np.testing.assert_allclose(np.asarray(pooled), ref_pooled, **tol)


def test_clip_skip_matches_transformers_penultimate():
    """output_layer=-2 (SDXL's clip-skip) == transformers hidden_states[-2]
    with the shared final LayerNorm applied — ComfyUI's clip-skip math."""
    cfg = dataclasses.replace(clip_mod.TINY_CLIP_CONFIG, vocab_size=512,
                              dtype=jnp.float32, output_layer=-2)
    hf_cfg = _hf_clip_config(cfg)
    torch.manual_seed(1)
    tm = transformers.CLIPTextModel(hf_cfg).eval()
    params = _load_torch_clip_into_flax(tm, cfg)

    ids = np.full((1, cfg.max_length), 5, np.int64)
    ids[0, 0] = cfg.vocab_size - 2
    ids[0, -1] = cfg.vocab_size - 1
    with torch.no_grad():
        out = tm(input_ids=torch.from_numpy(ids), output_hidden_states=True)
    # hidden_states[-2] is pre-LN; apply the model's final LN like ComfyUI
    with torch.no_grad():
        ref = tm.text_model.final_layer_norm(
            out.hidden_states[-2]).detach().numpy()

    fm = clip_mod.CLIPTextModel(cfg)
    hidden, _ = fm.apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(hidden), ref, rtol=2e-4, atol=2e-4)


def test_openclip_text_tower_matches_torch_reference():
    """flax CLIPTextModel with layout='openclip' == the open_clip-style
    torch tower (packed in_proj split, raw positional_embedding /
    text_projection, exact gelu, penultimate + shared ln_final), through
    the real SD2.x key mapping (``cond_stage_model.model.*``).  Covers the
    tower geometry SD2.1 (ViT-H) and SDXL's bigG serialize."""
    from tests.torch_ref import TorchOpenClipText

    cfg = dataclasses.replace(clip_mod.TINY_CLIP_CONFIG,
                              vocab_size=512, dtype=jnp.float32,
                              act="gelu", output_layer=-2,
                              projection_dim=64, layout="openclip")
    torch.manual_seed(2)
    tref = TorchOpenClipText(vocab=cfg.vocab_size, width=cfg.width,
                             layers=cfg.layers, heads=cfg.heads,
                             proj=cfg.projection_dim).eval()
    sd = {"cond_stage_model.model." + k: v.detach().numpy()
          for k, v in tref.state_dict().items()}
    params = ckpt._run_openclip(
        ckpt._LoadMapper(sd, ckpt.CLIP_PREFIX_SD2), cfg)

    rng = np.random.default_rng(3)
    B = 2
    ids = rng.integers(1, cfg.vocab_size - 2,
                       (B, cfg.max_length)).astype(np.int64)
    ids[:, 0] = cfg.vocab_size - 2
    ids[:, 9] = cfg.vocab_size - 1            # EOT = argmax position
    ids[:, 10:] = 0

    with torch.no_grad():
        hid = tref(torch.from_numpy(ids))
        # SD2 "penultimate": ln_final applied to hidden[-2]; pooled from
        # ln_final(hidden[-1]) at the EOT position, through text_projection
        ref_hidden = tref.ln_final(hid[-2]).numpy()
        final = tref.ln_final(hid[-1])
        eot = torch.from_numpy(ids).argmax(dim=-1)
        ref_pooled = (final[torch.arange(B), eot]
                      @ tref.text_projection).numpy()

    fm = clip_mod.CLIPTextModel(cfg)
    hidden, pooled = fm.apply({"params": params},
                              jnp.asarray(ids, jnp.int32))
    tol = dict(rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(hidden), ref_hidden, **tol)
    np.testing.assert_allclose(np.asarray(pooled), ref_pooled, **tol)


# --- UNet / VAE vs hand-written canonical-layout torch references ----------

@pytest.mark.parametrize("variant", ["sd15", "sdxl"])
def test_unet_matches_torch_reference(variant):
    """flax UNet forward == the canonical-layout torch LDM UNet, through
    the real checkpoint key mapping (validates NCHW<->NHWC transforms, the
    skip-concat order, head split, GN/LN epsilons, exact gelu, timestep
    embedding convention).  The 'sdxl' variant additionally covers linear
    proj_in/out, transformer depth > 1, and label_emb vector
    conditioning."""
    from comfyui_distributed_tpu.models import unet as unet_mod
    from tests.torch_ref import TorchUNet

    xl = variant == "sdxl"
    torch.manual_seed(0)
    tref = TorchUNet(adm_in_channels=32 if xl else None,
                     use_linear=xl,
                     transformer_depth=(1, 2) if xl else (1, 1)).eval()
    sd = {"model.diffusion_model." + k: v.detach().numpy()
          for k, v in tref.state_dict().items()}

    cfg = dataclasses.replace(unet_mod.TINY_CONFIG,
                              adm_in_channels=32 if xl else None,
                              use_linear_in_transformer=xl,
                              transformer_depth=(1, 2) if xl else (1, 1))
    params = ckpt._run_unet(ckpt._LoadMapper(sd, ckpt.UNET_PREFIX), cfg)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 8, 8, 4)).astype(np.float32)
    t = np.asarray([3.0, 711.0], np.float32)
    c = rng.standard_normal((2, 16, 64)).astype(np.float32)
    y = rng.standard_normal((2, 32)).astype(np.float32) if xl else None

    with torch.no_grad():
        ref = tref(torch.from_numpy(x.transpose(0, 3, 1, 2)),
                   torch.from_numpy(t), torch.from_numpy(c),
                   y=torch.from_numpy(y) if xl else None,
                   ).numpy().transpose(0, 2, 3, 1)

    out = unet_mod.UNet(cfg).apply(
        {"params": params}, jnp.asarray(x), jnp.asarray(t), jnp.asarray(c),
        y=jnp.asarray(y) if xl else None)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_vae_matches_torch_reference():
    """flax VAE encode+decode == the canonical-layout torch AutoencoderKL
    (validates the 1x1-conv attention mapping, asymmetric downsample
    padding, eps=1e-6 norms, scaling factor plumbing)."""
    from comfyui_distributed_tpu.models import vae as vae_mod
    from tests.torch_ref import TorchVAE

    torch.manual_seed(0)
    tref = TorchVAE().eval()
    sd = {"first_stage_model." + k: v.detach().numpy()
          for k, v in tref.state_dict().items()}

    cfg = vae_mod.TINY_VAE_CONFIG
    params = ckpt._run_vae(ckpt._LoadMapper(sd, ckpt.VAE_PREFIX), cfg)
    fvae = vae_mod.VAE(cfg)

    rng = np.random.default_rng(1)
    img = rng.random((1, 16, 16, 3)).astype(np.float32)
    with torch.no_grad():
        lat_ref = tref.encode(torch.from_numpy(
            img.transpose(0, 3, 1, 2))).numpy().transpose(0, 2, 3, 1)
        dec_ref = tref.decode(torch.from_numpy(
            lat_ref.transpose(0, 3, 1, 2))).numpy().transpose(0, 2, 3, 1)

    lat = fvae.apply({"params": params}, jnp.asarray(img),
                     method=fvae.encode)
    np.testing.assert_allclose(np.asarray(lat), lat_ref,
                               rtol=2e-4, atol=2e-4)
    dec = fvae.apply({"params": params}, jnp.asarray(lat),
                     method=fvae.decode)
    np.testing.assert_allclose(np.asarray(dec), dec_ref,
                               rtol=2e-4, atol=2e-4)


# --- BPE tokenizer vs transformers CLIPTokenizer ---------------------------

def _mini_clip_assets(tmp_path):
    """Tiny CLIP-format vocab.json + merges.txt covering a few words."""
    words = ["cat", "dog", "a", "photo", "of", "the", "red"]
    chars = sorted({c for w in words for c in w})
    vocab = {}
    for c in chars:
        vocab[c] = len(vocab)
        vocab[c + "</w>"] = len(vocab)
    merges = []
    for w in words:                      # merge each word left-to-right
        parts = list(w[:-1]) + [w[-1] + "</w>"]
        while len(parts) > 1:
            merges.append((parts[0], parts[1]))
            parts = [parts[0] + parts[1]] + parts[2:]
            if parts[0] not in vocab:
                vocab[parts[0]] = len(vocab)
    vocab["<|startoftext|>"] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)
    vpath, mpath = tmp_path / "vocab.json", tmp_path / "merges.txt"
    vpath.write_text(json.dumps(vocab))
    mpath.write_text("#version: 0.2\n"
                     + "\n".join(f"{a} {b}" for a, b in merges))
    return str(vpath), str(mpath)


def test_bpe_tokenizer_matches_transformers(tmp_path):
    """The real-BPE path agrees with transformers' CLIPTokenizer built from
    the SAME vocab/merges files (the ground-truth implementation)."""
    from comfyui_distributed_tpu.models.tokenizer import BPETokenizer
    vpath, mpath = _mini_clip_assets(tmp_path)
    ours = BPETokenizer(vpath, mpath, max_length=16)
    theirs = transformers.CLIPTokenizer(vocab_file=vpath, merges_file=mpath,
                                        model_max_length=16)
    for text in ["a photo of the cat", "the red dog", "cat cat dog"]:
        ids, weights = ours.encode(text)
        ref = theirs(text, padding="max_length", max_length=16,
                     truncation=True)["input_ids"]
        # transformers pads with its pad token; ours pads with EOT — compare
        # through the first EOT (the content + terminator)
        end = ref.index(theirs.eos_token_id) + 1
        assert ids.tolist()[:end] == ref[:end], text
        assert np.all(weights == 1.0)


def test_pipeline_uses_bpe_when_assets_present(tmp_path, monkeypatch):
    """load_pipeline(models_dir=...) activates the real BPE path when
    vocab/merges sit in the models dir (previously unreachable: the
    registry never passed assets_dir)."""
    from comfyui_distributed_tpu.models import registry
    from comfyui_distributed_tpu.models.tokenizer import BPETokenizer
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    _mini_clip_assets(tmp_path)
    registry.clear_pipeline_cache()
    pipe = registry.load_pipeline("bpe-test.ckpt", models_dir=str(tmp_path))
    assert isinstance(pipe.tokenizer, BPETokenizer)
    ctx, _ = pipe.encode_prompt(["a photo of the cat"])
    assert np.isfinite(np.asarray(ctx)).all()
    registry.clear_pipeline_cache()


def test_rrdb_upscaler_matches_torch_reference(tmp_path):
    """flax RRDBNet == the xinntao/Real-ESRGAN torch reference through the
    real .pth key normalization (validates dense-concat channel order,
    residual scaling, lrelu placement, nearest-upsample convs)."""
    from comfyui_distributed_tpu.models.upscalers import (
        RRDBNet, TINY_RRDB_CONFIG)
    from tests.torch_ref import TorchRRDBNet

    torch.manual_seed(0)
    cfg = dataclasses.replace(TINY_RRDB_CONFIG, dtype=jnp.float32)
    tref = TorchRRDBNet(feat=cfg.num_features, num_blocks=cfg.num_blocks,
                        growth=cfg.growth, scale=cfg.scale).eval()
    sd = {k: v.detach().numpy() for k, v in tref.state_dict().items()}
    path = str(tmp_path / "rrdb.safetensors")
    ckpt.save_state_dict(sd, path)
    params = ckpt.load_upscaler_checkpoint(path, cfg)

    rng = np.random.default_rng(0)
    img = rng.random((1, 12, 12, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tref(torch.from_numpy(
            img.transpose(0, 3, 1, 2))).numpy().transpose(0, 2, 3, 1)

    out = RRDBNet(cfg).apply({"params": params}, jnp.asarray(img))
    # the flax net clips to [0,1] at the output boundary; clip the torch
    # reference the same way for comparison
    np.testing.assert_allclose(np.asarray(out), np.clip(ref, 0.0, 1.0),
                               rtol=2e-4, atol=2e-4)


def test_clip_vision_matches_transformers():
    """flax CLIPVisionModel forward == transformers CLIPVisionModelWithProjection
    through the real HF key mapping (_run_clip_vision), tiny ViT
    geometry — the image tower behind CLIPVisionEncode/unCLIP."""
    from comfyui_distributed_tpu.models import clip_vision as cv

    vcfg = dataclasses.replace(cv.TINY_VISION_CONFIG, act="quick_gelu")
    hf_cfg = transformers.CLIPVisionConfig(
        hidden_size=vcfg.width, num_hidden_layers=vcfg.layers,
        num_attention_heads=vcfg.heads, patch_size=vcfg.patch,
        image_size=vcfg.image_size, intermediate_size=vcfg.width * 4,
        projection_dim=vcfg.projection_dim, hidden_act="quick_gelu",
        layer_norm_eps=1e-5)
    torch.manual_seed(3)
    tm = transformers.CLIPVisionModelWithProjection(hf_cfg).eval()

    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    params = ckpt._run_clip_vision(ckpt._LoadMapper(sd, ""), vcfg)

    rng = np.random.default_rng(1)
    px = rng.standard_normal(
        (2, vcfg.image_size, vcfg.image_size, 3)).astype(np.float32)
    with torch.no_grad():
        out = tm(pixel_values=torch.from_numpy(
            px.transpose(0, 3, 1, 2)), output_hidden_states=True)
    ref_embeds = out.image_embeds.numpy()
    ref_hidden = out.last_hidden_state.numpy()
    # hidden_states[-2]: the penultimate tap the style-model path
    # consumes (ADVICE r4)
    ref_penult = out.hidden_states[-2].numpy()

    fm = cv.CLIPVisionModel(vcfg)
    hidden, penult, embeds = fm.apply({"params": params}, jnp.asarray(px))
    tol = dict(rtol=3e-4, atol=3e-4)
    np.testing.assert_allclose(np.asarray(embeds), ref_embeds, **tol)
    np.testing.assert_allclose(np.asarray(hidden), ref_hidden, **tol)
    np.testing.assert_allclose(np.asarray(penult), ref_penult, **tol)
