"""Elastic fleet under overload (ISSUE 9): token-bucket / fair-dequeue
math, autoscaler hysteresis, the chaos injectors, jittered transport
backoff with Retry-After, deadline-aware hedging, the rehome-heartbeat
regression, and one slow loopback acceptance run (3 tenants + a killed
worker + injected faults)."""

import asyncio
import os
import sys
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.runtime import autoscale as autoscale_mod
from comfyui_distributed_tpu.runtime import cluster as cluster_mod
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import chaos as chaos_mod
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import net as net_mod
from comfyui_distributed_tpu.workflow import scheduler as sched

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture(autouse=True)
def no_leaked_chaos():
    chaos_mod.set_chaos(None)
    yield
    chaos_mod.set_chaos(None)


def make_state(tmp_path, **kw):
    return ServerState(config_path=str(tmp_path / "cfg.json"),
                       input_dir=str(tmp_path / "in"),
                       output_dir=str(tmp_path / "out"), **kw)


# --- token buckets -----------------------------------------------------------

class TestTokenBucket:
    def test_burst_cap_then_refill(self):
        tb = sched.TokenBucket(rate=2.0, burst=3)
        now = 100.0
        assert [tb.try_take(now) for _ in range(5)] == \
            [True, True, True, False, False]
        # 1 second at 2 tokens/s refills two takes
        assert tb.try_take(now + 1.0) and tb.try_take(now + 1.0)
        assert not tb.try_take(now + 1.0)

    def test_zero_rate_is_unlimited(self):
        tb = sched.TokenBucket(rate=0.0, burst=1)
        assert all(tb.try_take() for _ in range(100))

    def test_seconds_until_token(self):
        tb = sched.TokenBucket(rate=4.0, burst=1)
        now = 5.0
        assert tb.try_take(now)
        wait = tb.seconds_until_token(now)
        assert 0.0 < wait <= 0.25


# --- admission ---------------------------------------------------------------

def controller(**kw):
    kw.setdefault("weights", dict(C.TENANT_WEIGHTS_DEFAULT))
    kw.setdefault("shed", dict(C.TENANT_SHED_DEFAULT))
    kw.setdefault("rate", {cls: 0.0 for cls in C.TENANT_CLASSES})
    kw.setdefault("burst", {cls: 10.0 for cls in C.TENANT_CLASSES})
    return sched.AdmissionController(**kw)


class TestAdmission:
    def test_classify_default_is_highest_class(self):
        a = controller()
        assert a.classify(None) == "paid"
        assert a.classify("") == "paid"
        assert a.classify("nonsense") == "paid"
        assert a.classify("BATCH") == "batch"
        assert a.classify("free") == "free"

    def test_shed_ladder_batch_first_paid_never(self):
        a = controller()     # defaults: batch 0.5, free 0.85, paid 1.0
        # at half occupancy only batch sheds
        assert a.admit("batch", "c", 5, 10)["reason"] == "overload"
        assert a.admit("free", "c", 5, 10) is None
        assert a.admit("paid", "c", 5, 10) is None
        # at 90% free sheds too; paid still admitted
        assert a.admit("free", "c", 9, 10)["reason"] == "overload"
        assert a.admit("paid", "c", 9, 10) is None
        # paid sheds only at a genuinely full queue
        assert a.admit("paid", "c", 10, 10)["reason"] == "overload"

    def test_token_bucket_rate_shed_carries_retry_after(self):
        a = controller(rate={"paid": 0.0, "free": 1.0, "batch": 0.0},
                       burst={"paid": 1.0, "free": 2.0, "batch": 1.0})
        assert a.admit("free", "alice", 0, 100) is None
        assert a.admit("free", "alice", 0, 100) is None
        rej = a.admit("free", "alice", 0, 100)
        assert rej["reason"] == "rate" and rej["retry_after_s"] >= 1.0
        # buckets are per client: bob is unaffected by alice's flood
        assert a.admit("free", "bob", 0, 100) is None
        snap = a.snapshot()
        assert snap["per_class"]["free"]["shed_rate"] == 1
        assert snap["per_class"]["free"]["admitted"] == 3

    def test_counters_track_decisions(self):
        a = controller()
        a.admit("paid", "c", 0, 10)
        a.admit("batch", "c", 9, 10)
        a.on_complete("paid")
        per = a.snapshot()["per_class"]
        assert per["paid"] == {"admitted": 1, "shed_rate": 0,
                               "shed_overload": 0, "completed": 1}
        assert per["batch"]["shed_overload"] == 1


class TestFairDequeue:
    def test_stride_distribution_matches_weights(self):
        a = controller()
        queued = {"paid": 50, "free": 50, "batch": 50}
        picks = [a.next_class(queued) for _ in range(20)]
        assert picks.count("paid") == 12
        assert picks.count("free") == 6
        assert picks.count("batch") == 2

    def test_idle_class_cannot_bank_credit(self):
        a = controller()
        # paid runs alone for a long stretch...
        for _ in range(50):
            assert a.next_class({"paid": 1}) == "paid"
        # ...then free arrives: it gets its weighted share, not a
        # starvation burst paid banked against
        picks = [a.next_class({"paid": 5, "free": 5}) for _ in range(9)]
        assert picks.count("free") == 3
        assert picks.count("paid") == 6

    def _item(self, pid, tenant, sig=None):
        return {"id": pid, "tenant": tenant, "sig": sig}

    def test_single_class_is_legacy_contiguous_pop(self):
        a = controller()
        q = [self._item("a", "paid", "s1"), self._item("b", "paid", "s1"),
             self._item("c", "paid", "s2"), self._item("d", "paid", "s1")]
        group = sched.pop_fair_group(q, a, coalesce_max=8)
        assert [g["id"] for g in group] == ["a", "b"]
        assert [i["id"] for i in q] == ["c", "d"]

    def test_fair_pop_keeps_per_class_fifo_and_coalesces(self):
        a = controller(weights={"paid": 1.0, "free": 1.0, "batch": 1.0})
        q = [self._item("f1", "free", "x"), self._item("p1", "paid", "y"),
             self._item("f2", "free", "x"), self._item("p2", "paid", "y")]
        seen = []
        while q:
            group = sched.pop_fair_group(q, a, coalesce_max=8)
            seen.append([g["id"] for g in group])
        flat = [pid for grp in seen for pid in grp]
        # per-class FIFO: f1 before f2, p1 before p2 — always
        assert flat.index("f1") < flat.index("f2")
        assert flat.index("p1") < flat.index("p2")
        # coalescing groups a class's signature-run even when another
        # class's items sit between them in the global queue
        assert ["f1", "f2"] in seen or ["p1", "p2"] in seen


# --- autoscaler hysteresis ---------------------------------------------------

def make_scaler(**kw):
    reg = cluster_mod.ClusterRegistry(lease_s=60.0)
    spawned = []
    retired = []

    def spawner():
        wid = f"auto{len(spawned)}"
        spawned.append(wid)
        reg.register(wid, info={"host": "h", "port": 1}, alive=True)
        return wid

    def retirer(wid):
        retired.append(wid)
        return True

    depth = {"v": 0}
    kw.setdefault("min_workers", 0)
    kw.setdefault("max_workers", 3)
    kw.setdefault("up_queue", 4.0)
    kw.setdefault("down_queue", 1.0)
    kw.setdefault("window", 3)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("interval_s", 0.05)
    kw.setdefault("drain_s", 5.0)
    sc = autoscale_mod.FleetAutoscaler(
        registry=reg, queue_depth_fn=lambda: depth["v"],
        spawner=spawner, retirer=retirer,
        worker_queue_fn=lambda wid: 0, **kw)
    return sc, reg, depth, spawned, retired


class TestAutoscalerHysteresis:
    def test_scale_up_needs_sustained_window(self):
        sc, reg, depth, spawned, _ = make_scaler()
        depth["v"] = 100
        t = 0.0
        sc.sample_once(t)
        sc.sample_once(t + 1)
        assert not spawned          # 2 samples < window of 3
        sc.sample_once(t + 2)
        assert spawned == ["auto0"]

    def test_dip_resets_the_streak(self):
        sc, reg, depth, spawned, _ = make_scaler()
        depth["v"] = 100
        sc.sample_once(0.0)
        sc.sample_once(1.0)
        depth["v"] = 0              # one calm sample resets the streak
        sc.sample_once(2.0)
        depth["v"] = 100
        sc.sample_once(3.0)
        sc.sample_once(4.0)
        assert not spawned
        sc.sample_once(5.0)
        assert len(spawned) == 1

    def test_oscillating_signal_never_flaps(self):
        """The acceptance case: a signal bouncing between the up and
        down bars every sample must produce ZERO actions (the sustained
        window filters it) and therefore zero flaps."""
        sc, reg, depth, spawned, retired = make_scaler(cooldown_s=0.0)
        for i in range(30):
            depth["v"] = 100 if i % 2 == 0 else 0
            sc.sample_once(float(i))
        assert spawned == [] and retired == []
        assert sc.flaps == 0

    def test_cooldown_blocks_consecutive_actions(self):
        sc, reg, depth, spawned, _ = make_scaler(cooldown_s=10.0)
        depth["v"] = 100
        for i in range(3):
            sc.sample_once(float(i))
        assert len(spawned) == 1
        for i in range(3, 9):        # still over bar, inside cooldown
            sc.sample_once(float(i))
        assert len(spawned) == 1
        for i in range(13, 17):      # cooldown over: second spawn
            sc.sample_once(float(i))
        assert len(spawned) == 2

    def test_scale_down_drains_then_retires_and_forgets(self):
        sc, reg, depth, spawned, retired = make_scaler(cooldown_s=0.0)
        depth["v"] = 100
        for i in range(3):
            sc.sample_once(float(i))
        assert spawned == ["auto0"]
        depth["v"] = 0
        for i in range(10, 14):
            sc.sample_once(float(i))
        assert retired == ["auto0"]
        assert reg.snapshot()["workers"].get("auto0") is None  # forgotten
        assert sc.scale_downs == 1

    def test_retiring_worker_is_not_dispatchable(self):
        reg = cluster_mod.ClusterRegistry(lease_s=60.0)
        reg.register("w0", info={}, alive=True)
        assert reg.state("w0") == cluster_mod.HEALTHY
        assert reg.set_retiring("w0")
        assert reg.state("w0") == cluster_mod.RETIRING
        assert "w0" not in reg.healthy_ids()
        reg.set_retiring("w0", False)
        assert reg.state("w0") == cluster_mod.HEALTHY

    def test_forced_retirement_keeps_registry_record(self):
        """A worker stopped at the drain DEADLINE (still owing units)
        must stay in the registry: the collector drains detect lost
        owners via state()==DEAD after the lease ages out — forgetting
        the id would read UNKNOWN forever and skip the reassignment."""
        reg = cluster_mod.ClusterRegistry(lease_s=0.1)
        retired = []
        sc = autoscale_mod.FleetAutoscaler(
            registry=reg, queue_depth_fn=lambda: 100,
            spawner=lambda: (reg.register("autoX", alive=True)
                             and None) or "autoX",
            retirer=lambda wid: retired.append(wid) or True,
            worker_queue_fn=lambda wid: 7,   # NEVER drains
            min_workers=0, max_workers=1, up_queue=4.0,
            down_queue=200.0,                # immediately "under"
            window=1, cooldown_s=0.0, interval_s=0.05, drain_s=1.0)
        sc.sample_once(0.0)                  # spawns autoX
        sc.sample_once(1.0)                  # marks it retiring
        assert reg.state("autoX") == cluster_mod.RETIRING
        sc.sample_once(3.0)                  # deadline passed: forced
        assert retired == ["autoX"]
        # record kept; the expired lease now reads DEAD, which is what
        # the drain-recovery path keys on
        time.sleep(0.15)
        assert reg.state("autoX") == cluster_mod.DEAD

    def test_reversal_inside_flap_window_counts(self):
        sc, reg, depth, spawned, retired = make_scaler(
            cooldown_s=0.0, window=1, flap_window_s=100.0)
        depth["v"] = 100
        sc.sample_once(0.0)
        assert spawned
        depth["v"] = 0
        sc.sample_once(1.0)          # immediate reversal = flap
        assert sc.flaps == 1


# --- chaos injectors ---------------------------------------------------------

class TestChaosInjectors:
    def test_deterministic_with_seed(self):
        a = chaos_mod.ChaosMonkey({"drop_pct": 30, "seed": 5})
        b = chaos_mod.ChaosMonkey({"drop_pct": 30, "seed": 5})

        def rolls(cm):
            out = []
            for _ in range(20):
                try:
                    cm.client_edge("u")
                    out.append(False)
                except chaos_mod.ChaosDropError:
                    out.append(True)
            return out
        assert rolls(a) == rolls(b)
        assert any(rolls(chaos_mod.ChaosMonkey(
            {"drop_pct": 30, "seed": 5})))

    def test_drop_delay_and_5xx(self):
        cm = chaos_mod.ChaosMonkey({"drop_pct": 100})
        with pytest.raises(chaos_mod.ChaosDropError):
            cm.client_edge("http://x")
        cm = chaos_mod.ChaosMonkey({"delay_pct": 100, "delay_s": 0.7})
        assert cm.client_edge("http://x") == 0.7
        cm = chaos_mod.ChaosMonkey({"http_5xx_pct": 100,
                                    "routes": ["/prompt"]})
        assert cm.server_edge("/prompt")[0] == 503
        assert cm.server_edge("/history")[0] is None   # route-scoped

    def test_corrupt_flips_bytes_not_length(self):
        cm = chaos_mod.ChaosMonkey({"corrupt_pct": 100})
        data = bytes(range(64))
        out = cm.corrupt(data)
        assert len(out) == len(data) and out != data
        cm = chaos_mod.ChaosMonkey({})
        assert cm.corrupt(data) == data

    def test_freeze_heartbeats_blocks_beat_once(self):
        chaos_mod.set_chaos({"freeze_heartbeats": ["w-frozen"]})
        hb = cluster_mod.HeartbeatSender("http://127.0.0.1:1",
                                         "w-frozen", interval=999)
        assert hb.beat_once() is False       # no socket ever touched
        other = cluster_mod.HeartbeatSender("http://127.0.0.1:1",
                                            "w-live", interval=999,
                                            port=1)
        # not frozen -> really tries the (dead) master and fails there
        assert other.beat_once(timeout=0.2) is False

    def test_env_arming_and_programmatic_override(self, monkeypatch):
        monkeypatch.setenv(C.CHAOS_ENV, '{"drop_pct": 100}')
        assert chaos_mod.get_chaos().active
        monkeypatch.delenv(C.CHAOS_ENV)
        assert not chaos_mod.get_chaos().active
        chaos_mod.set_chaos({"delay_pct": 100})
        assert chaos_mod.get_chaos().active
        chaos_mod.set_chaos(None)
        assert not chaos_mod.get_chaos().active

    def test_middleware_injects_5xx_on_scoped_route(self, tmp_path):
        async def body():
            state = make_state(tmp_path, start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                chaos_mod.set_chaos({"http_5xx_pct": 100,
                                     "routes": ["/history"]})
                r = await client.get("/history")
                assert r.status == 503
                body_json = await r.json()
                assert "chaos" in body_json["error"]
                # other routes unaffected
                r = await client.get("/distributed/queue_status")
                assert r.status == 200
                chaos_mod.set_chaos(None)
                assert (await client.get("/history")).status == 200
                m = await (await client.get(
                    "/distributed/metrics")).json()
                assert m["chaos"]["injected"].get("5xx", 0) >= 1
            finally:
                await client.close()
        asyncio.run(body())


# --- transport backoff + Retry-After ----------------------------------------

class TestTransportBackoff:
    def test_jittered_schedule_shape(self):
        import random
        rng = random.Random(3)
        delays = net_mod.backoff_delays(5, rng=rng)
        assert len(delays) == 4
        nominal = [0.5, 1.0, 2.0, 4.0]
        for d, n in zip(delays, nominal):
            assert n * (1 - C.SEND_JITTER_FRACTION) <= d <= n
        # jitter de-synchronizes: two retry storms don't share a cadence
        other = net_mod.backoff_delays(5, rng=random.Random(4))
        assert delays != other

    def test_retry_after_parse_and_cap(self):
        assert net_mod._retry_after_hint({"Retry-After": "3"}) == 3.0
        assert net_mod._retry_after_hint(
            {"Retry-After": "99999"}) == C.RETRY_AFTER_CAP_S
        assert net_mod._retry_after_hint({"Retry-After": "bogus"}) is None
        assert net_mod._retry_after_hint({}) is None

    def test_post_retry_honors_retry_after_and_recovers(self, tmp_path):
        from aiohttp import web
        hits = []
        sleeps = []

        async def handler(request):
            hits.append(1)
            if len(hits) < 3:
                return web.json_response({"error": "busy"}, status=429,
                                         headers={"Retry-After": "2"})
            return web.json_response({"status": "ok"})

        real_sleep = asyncio.sleep

        async def fake_sleep(s):
            sleeps.append(s)
            await real_sleep(0)

        async def body():
            app = web.Application()
            app.router.add_post("/up", handler)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                url = (f"http://127.0.0.1:{client.server.port}/up")
                import aiohttp
                orig = asyncio.sleep
                asyncio.sleep = fake_sleep
                try:
                    await net_mod.post_form_with_retry(
                        url, lambda: aiohttp.FormData(), timeout=5,
                        what="test")
                finally:
                    asyncio.sleep = orig
            finally:
                await client.close()
        asyncio.run(body())
        assert len(hits) == 3
        # the server's Retry-After (2s) overrode the jittered backoff
        # (first nominal delay is <= 0.5s)
        assert sleeps and max(sleeps) >= 2.0

    def test_chaos_drop_is_retried(self, tmp_path):
        from aiohttp import web
        hits = []

        async def handler(request):
            hits.append(1)
            return web.json_response({"status": "ok"})

        real_sleep = asyncio.sleep

        async def fast_sleep(s):
            await real_sleep(0)

        async def body():
            app = web.Application()
            app.router.add_post("/up", handler)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                import aiohttp
                url = f"http://127.0.0.1:{client.server.port}/up"
                # drop EVERY edge: the send must exhaust its retries
                chaos_mod.set_chaos({"drop_pct": 100})
                asyncio.sleep = fast_sleep
                try:
                    with pytest.raises(chaos_mod.ChaosDropError):
                        await net_mod.post_form_with_retry(
                            url, lambda: aiohttp.FormData(), timeout=5,
                            max_retries=3, what="test")
                    assert hits == []      # nothing reached the wire
                    chaos_mod.set_chaos(None)
                    await net_mod.post_form_with_retry(
                        url, lambda: aiohttp.FormData(), timeout=5,
                        what="test")
                    assert hits == [1]
                finally:
                    asyncio.sleep = real_sleep
            finally:
                await client.close()
        asyncio.run(body())


class TestServerRetryAfter:
    def test_429_carries_retry_after_header(self, tmp_path, monkeypatch):
        monkeypatch.setenv(C.MAX_QUEUE_ENV, "2")
        from tests.test_pipeline import make_prompt

        async def body():
            state = make_state(tmp_path, start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                for i in range(2):
                    r = await client.post("/prompt", json={
                        "prompt": make_prompt(i), "client_id": "c"})
                    assert r.status == 200
                r = await client.post("/prompt", json={
                    "prompt": make_prompt(9), "client_id": "c"})
                assert r.status == 429
                assert "Retry-After" in r.headers
                body_json = await r.json()
                assert int(r.headers["Retry-After"]) == \
                    body_json["retry_after_s"] >= 1
            finally:
                await client.close()
        asyncio.run(body())

    def test_batch_shed_before_paid_over_http(self, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv(C.MAX_QUEUE_ENV, "4")
        from tests.test_pipeline import make_prompt

        async def body():
            state = make_state(tmp_path, start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                # fill half the queue -> batch sheds (bar 0.5), paid ok
                for i in range(2):
                    r = await client.post("/prompt", json={
                        "prompt": make_prompt(i), "client_id": "c",
                        "priority": "paid"})
                    assert r.status == 200
                r = await client.post("/prompt", json={
                    "prompt": make_prompt(7), "client_id": "c",
                    "priority": "batch"})
                assert r.status == 429
                body_json = await r.json()
                assert body_json["tenant"] == "batch"
                assert body_json["reason"] == "overload"
                r = await client.post("/prompt", json={
                    "prompt": make_prompt(8), "client_id": "c",
                    "priority": "paid"})
                assert r.status == 200
                m = await (await client.get(
                    "/distributed/metrics")).json()
                assert m["admission"]["per_class"]["batch"][
                    "shed_overload"] == 1
                assert m["admission"]["queued_by_class"]["paid"] == 3
                fleet = await (await client.get(
                    "/distributed/fleet")).json()
                assert fleet["admission"]["per_class"]["paid"][
                    "admitted"] == 3
                assert fleet["autoscale"]["enabled"] is False
            finally:
                await client.close()
        asyncio.run(body())


    def test_dispatched_share_bypasses_worker_admission(self, tmp_path,
                                                        monkeypatch):
        """A share some master already orchestrated (hidden
        multi_job_id) is mandatory work for an ADMITTED job — the
        receiving worker must not re-shed it, even at an occupancy
        where fresh traffic of that class would 429."""
        monkeypatch.setenv(C.MAX_QUEUE_ENV, "4")
        from tests.test_pipeline import make_prompt

        def share(seed):
            p = make_prompt(seed)
            p["20"] = {"class_type": "DistributedCollector",
                       "inputs": {"images": ["1", 0]},
                       "hidden": {"multi_job_id": f"mj{seed}",
                                  "is_worker": True,
                                  "enabled_worker_ids": "[]"}}
            return p

        async def body():
            state = make_state(tmp_path, is_worker=True,
                               start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                # occupy half the queue: fresh batch traffic sheds here
                for i in range(2):
                    r = await client.post("/prompt", json={
                        "prompt": make_prompt(i), "client_id": "c"})
                    assert r.status == 200
                r = await client.post("/prompt", json={
                    "prompt": make_prompt(7), "client_id": "c",
                    "priority": "batch"})
                assert r.status == 429
                # ...but the dispatched batch-class SHARE is admitted
                r = await client.post("/prompt", json={
                    "prompt": share(8), "client_id": "c",
                    "priority": "batch"})
                assert r.status == 200, await r.text()
                # the hard cap still applies to shares (queue now 3/4)
                r = await client.post("/prompt", json={
                    "prompt": share(9), "client_id": "c"})
                assert r.status == 200
                r = await client.post("/prompt", json={
                    "prompt": share(10), "client_id": "c"})
                assert r.status == 429
            finally:
                await client.close()
        asyncio.run(body())


# --- deadline-aware hedging --------------------------------------------------

class TestSloDeadlineHedging:
    def _job(self, ledger):
        ledger.create_job("j1", {0: "master", 1: "w0", 2: "w0"},
                          kind="tile")
        ledger.check_in("j1", 0, "master")   # EMA exists now

    def test_no_deadline_keeps_min_progress_gate(self):
        ledger = cluster_mod.WorkLedger()
        self._job(ledger)
        # 1/3 done < 50% gate -> no hedging regardless of silence
        assert ledger.overdue_units("j1", factor=0.0,
                                    min_wait_s=0.0) == {}

    def test_deadline_pressure_waives_gate_and_rekeys_threshold(self):
        ledger = cluster_mod.WorkLedger()
        self._job(ledger)
        # budget nearly blown: remaining ~0 -> threshold drops to the
        # SLO floor and the progress gate is waived
        ledger.set_deadline("j1", time.monotonic() + 0.01)
        time.sleep(C.SLO_MIN_WAIT_S + 0.05)
        overdue = ledger.overdue_units("j1", factor=1000.0,
                                       min_progress_pct=50.0,
                                       min_wait_s=1000.0)
        assert set(overdue) == {1, 2}
        assert all(o == "w0" for o in overdue.values())

    def test_comfortable_budget_does_not_loosen_policy(self):
        ledger = cluster_mod.WorkLedger()
        self._job(ledger)
        ledger.set_deadline("j1", time.monotonic() + 3600.0)
        # huge budget: the SLO threshold (0.25 x 3600) is LOOSER than
        # the global policy, so nothing changes
        assert ledger.overdue_units("j1", factor=1000.0,
                                    min_progress_pct=50.0,
                                    min_wait_s=1000.0) == {}

    def test_finish_job_clears_deadline(self):
        ledger = cluster_mod.WorkLedger()
        self._job(ledger)
        ledger.set_deadline("j1", time.monotonic() + 1.0)
        assert ledger.deadline("j1") is not None
        ledger.check_in("j1", 1, "w0")
        ledger.check_in("j1", 2, "w0")
        ledger.finish_job("j1")
        assert ledger.deadline("j1") is None

    def test_slo_rides_the_fanout_into_the_ledger(self, monkeypatch):
        """/prompt {"slo_s": N} -> orchestrate stamps every distributed
        job's deadline before dispatch (the plumbing half; the math is
        tested above)."""
        from comfyui_distributed_tpu.workflow import orchestrate

        worker = {"id": "w0", "host": "127.0.0.1", "port": 1,
                  "enabled": True}

        async def fake_preflight(workers, timeout=None, registry=None):
            return list(workers)

        async def fake_dispatch(w, graph, client_id=None,
                                extra_data=None):
            return {"prompt_id": "wp"}

        monkeypatch.setattr(orchestrate.dsp, "preflight_check",
                            fake_preflight)
        monkeypatch.setattr(orchestrate.dsp, "dispatch_to_worker",
                            fake_dispatch)
        monkeypatch.setattr(orchestrate.dsp, "make_job_id_map",
                            lambda graph, prefix=None: {"2": "job_slo"})

        class FakeJobs:
            async def prepare_job(self, mj):
                pass

            async def prepare_tile_job(self, mj):
                pass

        graph = {
            "1": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 8, "height": 8,
                             "batch_size": 1}},
            "2": {"class_type": "DistributedCollector",
                  "inputs": {"images": ["1", 0]}},
        }
        ledger = cluster_mod.WorkLedger()

        async def body():
            async def master_dispatch(g):
                return "pid"
            t0 = time.monotonic()
            out = await orchestrate.run_distributed(
                graph, "http://127.0.0.1:1", workers=[worker],
                master_dispatch=master_dispatch, job_store=FakeJobs(),
                extra_data={"slo_s": 30.0}, ledger=ledger)
            assert out["workers"] == ["w0"]
            dl = ledger.deadline("job_slo")
            assert dl is not None
            assert 25.0 < dl - t0 <= 30.5
        asyncio.run(body())


# --- rehome-heartbeat regression (satellite) ---------------------------------

class TestRehomeHeartbeat:
    def test_rehome_retries_through_a_racing_master(self, tmp_path):
        """The takeover race: the first rehomed beat fails (the dying
        master's socket), and the fix's retry burst re-registers on the
        next attempt — the worker must NOT stay unregistered for a full
        heartbeat interval.  The chaos freeze injector plays the dying
        master."""
        async def body():
            mstate = make_state(tmp_path, start_exec_thread=False)
            mclient = TestClient(TestServer(build_app(mstate)))
            await mclient.start_server()
            url = f"http://127.0.0.1:{mclient.server.port}"
            try:
                hb = cluster_mod.HeartbeatSender(
                    "http://127.0.0.1:1", "w-rehome", interval=999,
                    port=4242)
                # freeze = the beat that races the dying master fails
                chaos_mod.set_chaos(
                    {"freeze_heartbeats": ["w-rehome"]})
                unfreeze = threading.Timer(0.25, chaos_mod.set_chaos,
                                           args=(None,))
                unfreeze.start()
                loop = asyncio.get_running_loop()
                ok = await loop.run_in_executor(
                    None, lambda: hb.rehome(url, attempts=4))
                unfreeze.join()
                assert ok, "rehome retry burst never landed a beat"
                # the first landed beat re-registered IMMEDIATELY:
                # healthy in the new registry, no probe cycle needed
                assert mstate.cluster.state("w-rehome") \
                    == cluster_mod.HEALTHY
            finally:
                await mclient.close()
        asyncio.run(body())

    def test_rehome_route_registers_at_new_master(self, tmp_path):
        async def body():
            mstate = make_state(tmp_path / "m", start_exec_thread=False)
            mclient = TestClient(TestServer(build_app(mstate)))
            await mclient.start_server()
            wstate = make_state(tmp_path / "w", is_worker=True,
                                start_exec_thread=False)
            wclient = TestClient(TestServer(build_app(wstate)))
            await wclient.start_server()
            wstate.port = wclient.server.port
            url = f"http://127.0.0.1:{mclient.server.port}"
            try:
                r = await wclient.post("/distributed/rehome", json={
                    "master_url": url, "worker_id": "w-route"})
                assert r.status == 200
                body_json = await r.json()
                assert body_json["registered"] is True
                assert mstate.cluster.state("w-route") \
                    == cluster_mod.HEALTHY
            finally:
                if wstate.heartbeat is not None:
                    wstate.heartbeat.stop()
                await wclient.close()
                await mclient.close()
        asyncio.run(body())


# --- slow loopback acceptance ------------------------------------------------

@pytest.mark.slow
class TestOverloadAcceptance:
    def test_three_tenants_killed_worker_chaos(self):
        """ISSUE 9 acceptance, scaled down: 3 Poisson tenants + 1
        killed worker + injected 5xx/drops/delays -> every admitted job
        (paid ESPECIALLY) completes, shedding is batch-first with paid
        untouched, the p95 ordering holds, and the autoscaler scales
        up AND down without a flap."""
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        import bench
        m = bench.measure_overload(duration_s=6.0,
                                   rates={"paid": 2.0, "free": 2.5,
                                          "batch": 3.0})
        assert m["worker_killed"]
        assert m["paid_shed"] == 0
        assert m["paid_completion_rate"] == 1.0
        assert m["completion_rate"] == 1.0
        assert m["fanout_completed"] == m["fanout_jobs"]
        assert m["batch_shed"] >= 1
        assert m["batch_shed"] >= m["free_shed"]
        assert m["p95_paid_s"] is not None \
            and m["p95_batch_s"] is not None
        assert m["p95_paid_s"] < m["p95_batch_s"]
        assert m["scale_ups"] >= 1 and m["scale_downs"] >= 1
        assert m["autoscale_flaps"] == 0
        assert sum(m["chaos_injected"].values()) >= 1
