"""Multi-master sharded control plane (ISSUE 14).

Coverage map:

- consistent-hash ring: deterministic placement, BOUNDED key movement
  on membership change (exactly the leaver's keys move; a joiner takes
  ~1/N), deterministic successor;
- shard-owned prompt-id generation, gossip merge semantics, the
  federated autoscaler signal and the per-shard admission rate split;
- JobStore idempotency-key and result-cache scoping by shard owner
  (epoch) — the takeover-can-never-alias regression tests;
- loopback HTTP: mis-route forwarding (one hop, owner's WAL before the
  ack, header-terminated), the stateless router's hash routing and
  merged read views;
- peer takeover: dead shard absorbed by its ring successor ONLY, with
  `cli wal verify` rc=0 per shard afterwards;
- one slow acceptance: 3 masters + 2 workers, the master owning a
  4-tile tiled-upscale fan-out killed mid-job — the survivor absorbs
  the shard and the final blend is BIT-IDENTICAL to the no-kill run.
"""

import asyncio
import json
import os
import tempfile
import time

import numpy as np
import pytest

from comfyui_distributed_tpu.runtime import shard as shard_mod
from comfyui_distributed_tpu.utils import constants as C

pytestmark = []


# --- the ring itself (no server, no jax) -------------------------------------

class TestHashRing:
    def test_deterministic_placement(self):
        a = shard_mod.HashRing({"m0": "", "m1": "", "m2": ""}, vnodes=64)
        b = shard_mod.HashRing({"m2": "", "m0": "", "m1": ""}, vnodes=64)
        keys = [f"p_{i}" for i in range(500)]
        assert [a.owner(k) for k in keys] == [b.owner(k) for k in keys]
        # every member owns a nontrivial share
        owners = {a.owner(k) for k in keys}
        assert owners == {"m0", "m1", "m2"}

    def test_leave_moves_only_the_leavers_keys(self):
        full = shard_mod.HashRing({"m0": "", "m1": "", "m2": ""},
                                  vnodes=128)
        rest = shard_mod.HashRing({"m0": "", "m1": ""}, vnodes=128)
        keys = [f"p_{i}" for i in range(2000)]
        for k in keys:
            if full.owner(k) != "m2":
                # a surviving member's key NEVER moves on a leave
                assert rest.owner(k) == full.owner(k)

    def test_join_moves_about_one_over_n(self):
        n3 = shard_mod.HashRing({"m0": "", "m1": "", "m2": ""},
                                vnodes=128)
        n4 = shard_mod.HashRing({"m0": "", "m1": "", "m2": "", "m3": ""},
                                vnodes=128)
        keys = [f"p_{i}" for i in range(4000)]
        moved = sum(1 for k in keys if n3.owner(k) != n4.owner(k))
        # all moved keys land on the joiner, and the moved fraction is
        # ~1/4 (generous bound: consistent hashing's whole point)
        for k in keys:
            if n3.owner(k) != n4.owner(k):
                assert n4.owner(k) == "m3"
        assert moved <= len(keys) * 0.40, f"{moved}/{len(keys)} moved"
        assert moved >= len(keys) * 0.10  # the joiner actually joined

    def test_successor_deterministic_and_excludes_dead(self):
        r = shard_mod.HashRing({"m0": "", "m1": "", "m2": ""}, vnodes=64)
        s = r.successor("m1")
        assert s in ("m0", "m2")
        assert s == r.successor("m1")  # stable
        two = shard_mod.HashRing({"m0": "", "m1": ""}, vnodes=64)
        assert two.successor("m1") == "m0"
        assert shard_mod.HashRing({"m0": ""}, vnodes=4).successor(
            "m0") is None

    def test_parse_peers(self):
        assert shard_mod.parse_peers(
            "m0=http://a:1, m1=http://b:2/,,bad") == {
                "m0": "http://a:1", "m1": "http://b:2"}
        assert shard_mod.parse_peers("") == {}


class TestShardManagerUnit:
    def _mgr(self, sid="m0", members=None):
        return shard_mod.ShardManager(
            None, sid, members or {"m0": "u0", "m1": "u1", "m2": "u2"},
            start_threads=False)

    def test_local_pid_owned_by_self(self):
        import itertools
        mgr = self._mgr("m1")
        ctr = itertools.count()
        for _ in range(20):
            pid = mgr.local_pid(ctr)
            assert mgr.owner_of(pid) == "m1"

    def test_merge_gossip_higher_epoch_wins(self):
        mgr = self._mgr("m0")
        reply = mgr.merge_gossip({"from": "m1", "ring_epoch": 1,
                                  "members": {"m0": "u0", "m1": "u1",
                                              "m2": "u2"},
                                  "queue_remaining": 7})
        # equal epoch: own membership kept; peer liveness + queue noted
        assert reply["from"] == "m0" and reply["ring_epoch"] == 1
        assert mgr.peer_queue_depth() == 7
        assert mgr.live_peer_masters() == 1
        # a higher epoch (m1 absorbed m2) replaces the membership
        mgr.merge_gossip({"from": "m1", "ring_epoch": 2,
                          "members": {"m0": "u0", "m1": "u1"},
                          "queue_remaining": 3})
        assert mgr.ring_epoch() == 2
        assert set(mgr.ring_snapshot()["members"]) == {"m0", "m1"}
        # a STALE lower-epoch view can't roll the ring back
        mgr.merge_gossip({"from": "m2", "ring_epoch": 1,
                          "members": {"m0": "u0", "m1": "u1",
                                      "m2": "u2"}})
        assert mgr.ring_epoch() == 2

    def test_merge_gossip_ring_without_self_means_deposed(self):
        mgr = self._mgr("m0")
        mgr.merge_gossip({"from": "m1", "ring_epoch": 5,
                          "members": {"m1": "u1", "m2": "u2"}})
        # the stale ring is never adopted (we'd vanish from our own
        # view) — but a higher-epoch ring that excludes us means a
        # peer absorbed our shard: we are a zombie owner now
        assert mgr.ring_epoch() == 1
        assert "m0" in mgr.ring_snapshot()["members"]
        assert mgr.deposed
        assert mgr.watch_once() == []  # a deposed master never absorbs
        assert mgr.snapshot()["deposed"] is True

    def test_equal_epoch_divergence_converges_by_intersection(self):
        # 4-master ring; m0 absorbed m1 while m2 absorbed m3: both at
        # epoch 2 with DIFFERENT member sets.  One gossip exchange must
        # converge both sides to the intersection {m0, m2}.
        members4 = {"m0": "u0", "m1": "u1", "m2": "u2", "m3": "u3"}
        a = shard_mod.ShardManager(None, "m0", members4,
                                   start_threads=False)
        with a._lock:
            a._members.pop("m1")
            a._ring = shard_mod.HashRing(a._members, None)
            a._ring_epoch = 2
        a.merge_gossip({"from": "m2", "ring_epoch": 2,
                        "members": {"m0": "u0", "m1": "u1",
                                    "m2": "u2"}})
        assert set(a.ring_snapshot()["members"]) == {"m0", "m2"}
        assert a.ring_epoch() == 2  # converged WITHOUT an epoch race

    def test_higher_epoch_gossip_cannot_resurrect_absorbed_member(self):
        # m0 absorbed m1 (epoch 2); a peer's higher-epoch view that
        # predates the takeover still lists m1.  Adopting it must NOT
        # re-add m1: dead_peer_shards skips absorbed ids, so a
        # resurrected dead member would never be removed again.
        mgr = self._mgr("m0")
        with mgr._lock:
            mgr._members.pop("m1")
            mgr._ring = shard_mod.HashRing(mgr._members, None)
            mgr._ring_epoch = 2
            mgr._absorbed["m1"] = {"epoch": 2, "ring_epoch": 2,
                                   "resumed_prompts": 0,
                                   "recovered_jobs": 0, "at": 0.0}
        mgr.merge_gossip({"from": "m2", "ring_epoch": 3,
                          "members": {"m0": "u0", "m1": "u1",
                                      "m2": "u2"}})
        assert mgr.ring_epoch() == 3
        assert set(mgr.ring_snapshot()["members"]) == {"m0", "m2"}
        assert mgr.owned_shards() == ["m0", "m1"]

    def test_snapshot_shape(self):
        snap = self._mgr("m2").snapshot()
        assert snap["enabled"] and snap["id"] == "m2"
        assert snap["owned"] == ["m2"]
        assert set(snap["members"]) == {"m0", "m1", "m2"}
        ring = self._mgr("m2").ring_snapshot()
        assert ring["self"] == "m2" and ring["vnodes"] >= 1


class TestFederatedSignals:
    def test_autoscaler_signal_merges_peer_queues(self):
        from comfyui_distributed_tpu.runtime.autoscale import \
            FleetAutoscaler

        class FakeShard:
            def peer_queue_depth(self):
                return 5

            def live_peer_masters(self):
                return 2

        scaler = FleetAutoscaler(registry=None,
                                 queue_depth_fn=lambda: 2,
                                 shard=FakeShard())
        sig = scaler.fleet_signal()
        assert sig["queue_depth"] == 7
        assert sig["participants"] == 3  # self + 2 peer masters
        assert sig["peer_masters"] == 2
        assert sig["queue_per_participant"] == pytest.approx(7 / 3)

    def test_only_ring_designated_actuator_scales(self):
        """N masters fold the SAME gossiped backlog into their signal;
        only the ring-designated actuator may spawn on it — otherwise
        one backlog draws N scale-ups (and N retires on the rebound)."""
        from comfyui_distributed_tpu.runtime.autoscale import \
            FleetAutoscaler
        from comfyui_distributed_tpu.runtime.shard import HashRing

        ring = HashRing({"m0": None, "m1": None}, 64)
        owner = ring.owner(C.AUTOSCALE_ACTUATOR_KEY)
        loser = next(m for m in ("m0", "m1") if m != owner)

        class FakeShard:
            def __init__(self, me):
                self.me = me

            def peer_queue_depth(self):
                return 50

            def live_peer_masters(self):
                return 1

            def is_autoscale_actuator(self):
                return ring.owner(C.AUTOSCALE_ACTUATOR_KEY) == self.me

        spawned = []

        def mk(me):
            return FleetAutoscaler(
                registry=None, queue_depth_fn=lambda: 50,
                spawner=lambda: spawned.append(me) or f"w-{me}",
                min_workers=0, max_workers=8, up_queue=1.0,
                window=1, cooldown_s=0.0, shard=FakeShard(me))

        # the non-designated shard samples but defers actuation
        out = mk(loser).sample_once(now=100.0)
        assert out["actuator"] is False
        assert out["action"] is None
        assert spawned == []
        # the designated shard acts exactly once on the same signal
        out = mk(owner).sample_once(now=100.0)
        assert out["action"] == "up"
        assert spawned == [owner]

    def test_admission_rate_splits_by_shard_count(self):
        from comfyui_distributed_tpu.workflow.scheduler import \
            AdmissionController
        adm = AdmissionController(rate={"paid": 10.0, "free": 0.0,
                                        "batch": 0.0},
                                  burst={"paid": 1.0, "free": 1.0,
                                         "batch": 1.0})
        adm.set_rate_scale(1.0 / 4)
        assert adm.admit("paid", "c1", 0, 100) is None
        # the per-client bucket was built at the SPLIT rate
        bucket = next(iter(adm._buckets.values()))
        assert bucket.rate == pytest.approx(2.5)
        assert adm.snapshot()["rate_scale"] == pytest.approx(0.25)
        # scale 1.0 (single master) keeps the configured rate
        adm2 = AdmissionController(rate={"paid": 10.0, "free": 0.0,
                                         "batch": 0.0},
                                   burst={"paid": 1.0, "free": 1.0,
                                          "batch": 1.0})
        assert adm2.admit("paid", "c1", 0, 100) is None
        assert next(iter(adm2._buckets.values())).rate == 10.0


# --- takeover-can-never-alias scoping (satellite) ----------------------------

class TestIdemScoping:
    def _put(self, store, job, key):
        return asyncio.run(store.put_result(
            job, {"worker_id": "w", "tensor": None}, idem_key=key,
            require_existing=False))

    def test_absorbed_keys_dedupe_without_aliasing_ours(self):
        from comfyui_distributed_tpu.runtime.jobs import JobStore
        store = JobStore()
        store.set_scope("mA")
        # a peer takeover merges the DEAD shard's replayed keys
        store.merge_idem({"image": {"J": ["w:0:1"]}}, scope="mB")
        # the dead master's acked-but-dropped upload replays: DEDUPED
        # (acked, not enqueued) — exactly-once survives the takeover
        assert self._put(store, "J", "w:0:1")
        q = asyncio.run(store.get_queue("J"))
        assert q.qsize() == 0
        # the SAME key for one of OUR OWN jobs is a different namespace:
        # it inserts (the takeover never mistook it for the absorbed ack)
        assert self._put(store, "J2", "w:0:1")
        assert asyncio.run(store.get_queue("J2")).qsize() == 1
        # and a fresh key on the absorbed job keeps that job's scope
        assert self._put(store, "J", "w:0:2")
        assert asyncio.run(store.get_queue("J")).qsize() == 1
        assert not asyncio.run(store.put_result(
            "J", {"worker_id": "w", "tensor": None}, idem_key="w:0:2",
            require_existing=True)) or \
            asyncio.run(store.get_queue("J")).qsize() == 1

    def test_own_recovered_keys_reseed_under_own_scope(self):
        from comfyui_distributed_tpu.runtime.jobs import JobStore
        store = JobStore()
        store.set_scope("mA")
        store.attach_wal(None, {"image": {"J": ["k1"]},
                                "tile": {"T": ["t1"]}})
        assert self._put(store, "J", "k1")  # ack ...
        assert asyncio.run(store.get_queue("J")).qsize() == 0  # ... drop
        ok = asyncio.run(store.put_tile(
            "T", {"worker_id": "w", "tile_idx": 0, "x": 0, "y": 0,
                  "extracted_width": 1, "extracted_height": 1,
                  "padding": 0, "is_last": True, "tensor": None},
            idem_key="t1", require_existing=False))
        assert ok
        assert asyncio.run(store.get_tile_queue("T")).qsize() == 0

    def test_unscoped_store_is_bit_compatible(self):
        from comfyui_distributed_tpu.runtime.jobs import JobStore
        store = JobStore()
        assert store._scoped("J", "k") == "k"  # legacy keyspace


class TestResultCacheScoping:
    def _prompt(self):
        return {
            "7": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": "tiny.safetensors"}},
            "5": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "x", "clip": ["7", 1]}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "", "clip": ["7", 1]}},
            "1": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32,
                             "batch_size": 1}},
            "2": {"class_type": "KSampler",
                  "inputs": {"model": ["7", 0], "positive": ["5", 0],
                             "negative": ["6", 0],
                             "latent_image": ["1", 0], "seed": 1,
                             "steps": 1, "cfg": 2.0,
                             "sampler_name": "euler",
                             "scheduler": "normal", "denoise": 1.0}},
            "3": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["2", 0], "vae": ["7", 2]}},
        }

    def test_scope_salts_the_key(self):
        from comfyui_distributed_tpu.runtime.reuse import result_key
        p = self._prompt()
        base = result_key(p)
        assert base is not None
        assert result_key(p) == base  # stable, and unchanged w/o scope
        a1 = result_key(p, scope="m0:e1")
        b1 = result_key(p, scope="m1:e1")
        a2 = result_key(p, scope="m0:e2")
        # cross-shard entries never alias; a takeover's epoch bump
        # retires the deposed epoch's entries
        assert len({base, a1, b1, a2}) == 4


# --- loopback HTTP: forwarding + router + takeover ---------------------------

def _upscale_prompt(seed=11, size=64, tile=32, steps=1):
    """4-tile tiled-upscale fan-out with a SaveImage sink (the failover
    shape): master [0,1], w0 [2], w1 [3]."""
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "a map", "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "10": {"class_type": "LoadImage",
               "inputs": {"image": "__shard_card__.png"}},
        "11": {"class_type": "ImageScale",
               "inputs": {"image": ["10", 0],
                          "upscale_method": "bilinear", "width": size,
                          "height": size, "crop": "disabled"}},
        "2": {"class_type": "UltimateSDUpscaleDistributed",
              "inputs": {"upscaled_image": ["11", 0], "model": ["7", 0],
                         "positive": ["5", 0], "negative": ["6", 0],
                         "vae": ["7", 2], "seed": seed, "steps": steps,
                         "cfg": 2.0, "sampler_name": "euler",
                         "scheduler": "normal", "denoise": 0.4,
                         "tile_width": tile, "tile_height": tile,
                         "padding": 8, "mask_blur": 2,
                         "force_uniform_tiles": True}},
        "3": {"class_type": "SaveImage",
              "inputs": {"images": ["2", 0],
                         "filename_prefix": "shard"}},
    }


def _tiny_prompt(seed=100):
    return {
        "1": {"class_type": "EmptyLatentImage",
              "inputs": {"width": 32, "height": 32, "batch_size": 1}},
    }


class _Fleet:
    """N sharded exec-less masters over real loopback ports, one shared
    WAL root.  Exec-less (start_exec_thread=False) keeps the non-slow
    tests cheap: admission/forwarding/WAL behavior without model work."""

    def __init__(self, n=2, exec_threads=False, cfg_path=None,
                 lease_s=None):
        self.n = n
        self.exec_threads = exec_threads
        self.cfg_path = cfg_path
        self.lease_s = lease_s
        self.tmp = tempfile.mkdtemp(prefix="shard_fleet_")
        self.states, self.clients, self.urls = [], [], []
        self._saved = {}

    async def __aenter__(self):
        from aiohttp.test_utils import TestClient, TestServer

        from comfyui_distributed_tpu.server.app import (ServerState,
                                                        build_app)
        from comfyui_distributed_tpu.utils.net import find_free_port
        ports = [find_free_port() for _ in range(self.n)]
        self.urls = [f"http://127.0.0.1:{p}" for p in ports]
        peers = ",".join(f"m{i}={u}" for i, u in enumerate(self.urls))
        keys = (C.SHARD_ID_ENV, C.SHARD_PEERS_ENV, C.SHARD_WAL_ROOT_ENV,
                C.MASTER_LEASE_ENV, C.CACHE_ENV)
        self._saved = {k: os.environ.get(k) for k in keys}
        os.environ[C.SHARD_PEERS_ENV] = peers
        os.environ[C.SHARD_WAL_ROOT_ENV] = os.path.join(self.tmp, "wal")
        os.environ[C.CACHE_ENV] = "0"
        if self.lease_s is not None:
            os.environ[C.MASTER_LEASE_ENV] = str(self.lease_s)
        for i in range(self.n):
            os.environ[C.SHARD_ID_ENV] = f"m{i}"
            d = os.path.join(self.tmp, f"m{i}")
            os.makedirs(os.path.join(d, "in"), exist_ok=True)
            st = ServerState(
                config_path=self.cfg_path,
                input_dir=os.path.join(d, "in"), output_dir=d,
                start_exec_thread=self.exec_threads)
            client = TestClient(TestServer(build_app(st),
                                           port=ports[i]))
            await client.start_server()
            st.port = ports[i]
            self.states.append(st)
            self.clients.append(client)
        os.environ.pop(C.SHARD_ID_ENV, None)
        return self

    async def __aexit__(self, *exc):
        import shutil
        for st in self.states:
            if st.durable is not None and st.durable.wal is not None:
                st.durable.simulate_crash()
            if st.shard is not None:
                st.shard.stop()
        for c in self.clients:
            try:
                await c.close()
            except Exception:  # noqa: BLE001 - already closed
                pass
        loop = asyncio.get_running_loop()
        for st in self.states:
            st.health.stop()
            await loop.run_in_executor(None, lambda s=st: s.drain(0.5))
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(self.tmp, ignore_errors=True)

    def pid_owned_by(self, shard_id, tag="k"):
        mgr = self.states[0].shard
        return next(f"{tag}{i}" for i in range(10_000)
                    if mgr.owner_of(f"{tag}{i}") == shard_id)

    def kill(self, i):
        """SIGKILL proxy + immediate lease expiry, so the takeover test
        doesn't sleep a full master-lease out."""
        st = self.states[i]
        st.durable.simulate_crash()
        st.shard.stop()
        st.health.stop()
        lease = os.path.join(self.tmp, "wal", f"m{i}", "master.lease")
        rec = json.load(open(lease))
        rec["expires_at"] = time.time() - 1.0
        with open(lease, "w") as f:
            json.dump(rec, f)


class TestForwarding:
    def test_misroute_forwarded_one_hop_lands_in_owner_wal(self):
        async def go():
            async with _Fleet(2) as fl:
                pid = fl.pid_owned_by("m1")
                r = await fl.clients[0].post("/prompt", json={
                    "prompt": _tiny_prompt(), "client_id": "c",
                    "prompt_id": pid})
                body = await r.json()
                assert r.status == 200, body
                assert body["prompt_id"] == pid
                assert body["forwarded_from"] == "m0"
                assert body["shard"] == "m1"
                # the job lives at the OWNER (queued there, not here)
                assert pid in fl.states[1]._inflight
                assert pid not in fl.states[0]._inflight
                # ... and its admission was durable in the OWNER's WAL
                # BEFORE the client saw the prompt-id
                from comfyui_distributed_tpu.runtime import durable
                st, _ = durable.replay(
                    os.path.join(fl.tmp, "wal", "m1"))
                assert pid in st.prompts
                st0, _ = durable.replay(
                    os.path.join(fl.tmp, "wal", "m0"))
                assert pid not in st0.prompts
                assert fl.states[0].shard.forwards == 1

        asyncio.run(go())

    def test_forward_header_terminates_at_one_hop(self):
        async def go():
            async with _Fleet(2) as fl:
                pid = fl.pid_owned_by("m1", tag="h")
                # a ring disagreement: the forward header is already
                # set, so m0 must accept locally instead of bouncing
                r = await fl.clients[0].post(
                    "/prompt",
                    json={"prompt": _tiny_prompt(), "client_id": "c",
                          "prompt_id": pid},
                    headers={C.SHARD_FORWARD_HEADER: "m1"})
                body = await r.json()
                assert r.status == 200, body
                assert "forwarded_from" not in body
                assert pid in fl.states[0]._inflight
                assert fl.states[0].shard.forwards == 0

        asyncio.run(go())

    def test_forwarded_shed_keeps_retry_after_header(self):
        """A shed (429) relayed through the mis-route forward must keep
        its HTTP-standard Retry-After header — standards-honoring
        clients would otherwise retry an overloaded fleet instantly."""
        async def go():
            async with _Fleet(2) as fl:
                pid = fl.pid_owned_by("m1", tag="s")
                fl.states[1].max_queue = 0  # the OWNER sheds everything
                r = await fl.clients[0].post("/prompt", json={
                    "prompt": _tiny_prompt(), "client_id": "c",
                    "prompt_id": pid})
                body = await r.json()
                assert r.status == 429, body
                assert int(r.headers["Retry-After"]) >= 1

        asyncio.run(go())

    def test_direct_submission_generates_self_owned_pid(self):
        async def go():
            async with _Fleet(2) as fl:
                for i in range(2):
                    r = await fl.clients[i].post("/prompt", json={
                        "prompt": _tiny_prompt(), "client_id": "c"})
                    body = await r.json()
                    assert r.status == 200, body
                    pid = body["prompt_id"]
                    assert fl.states[i].shard.owner_of(pid) == f"m{i}"
                    assert pid in fl.states[i]._inflight

        asyncio.run(go())

    def test_gossip_roundtrip_and_metrics_surfaces(self):
        async def go():
            async with _Fleet(2) as fl:
                loop = asyncio.get_running_loop()
                # gossip runs on a daemon thread in production; drive
                # one round off the loop so the loopback peer can answer
                reached = await loop.run_in_executor(
                    None, fl.states[0].shard.gossip_once)
                assert reached == 1
                assert fl.states[0].shard.live_peer_masters() == 1
                # both surfaces carry the shard block/gauges
                m = await (await fl.clients[0].get(
                    "/distributed/metrics")).json()
                assert m["shard"]["enabled"] and m["shard"]["id"] == "m0"
                assert m["shard"]["ring_epoch"] == 1
                prom = await (await fl.clients[0].get(
                    "/distributed/metrics.prom")).text()
                assert 'dtpu_shard_owner{shard="m0"} 1' in prom
                assert "dtpu_ring_epoch 1" in prom
                ring = await (await fl.clients[1].get(
                    "/distributed/ring")).json()
                assert ring["self"] == "m1"
                assert set(ring["members"]) == {"m0", "m1"}

        asyncio.run(go())


class TestTakeover:
    def test_successor_absorbs_dead_shard(self):
        async def go():
            async with _Fleet(3) as fl:
                victim = 1
                succ = fl.states[0].shard._ring.successor("m1")
                pid = fl.pid_owned_by("m1", tag="t")
                r = await fl.clients[victim].post("/prompt", json={
                    "prompt": _tiny_prompt(), "client_id": "c",
                    "prompt_id": pid})
                assert r.status == 200
                fl.kill(victim)
                loop = asyncio.get_running_loop()
                others = [i for i in range(3) if i != victim]
                non_succ = next(i for i in others
                                if f"m{i}" != succ)
                succ_i = next(i for i in others if f"m{i}" == succ)
                # the NON-successor sees the death but does not absorb
                got = await loop.run_in_executor(
                    None, fl.states[non_succ].shard.watch_once)
                assert got == []
                assert fl.states[non_succ].shard.ring_epoch() == 1
                # the successor absorbs: ring epoch bump, prompt
                # re-enqueued under its ORIGINAL id, ownership gauges
                got = await loop.run_in_executor(
                    None, fl.states[succ_i].shard.watch_once)
                assert got == ["m1"]
                mgr = fl.states[succ_i].shard
                assert mgr.ring_epoch() == 2
                assert mgr.owned_shards() == [succ, "m1"] or \
                    mgr.owned_shards() == sorted([succ, "m1"])
                assert pid in fl.states[succ_i]._inflight
                # the absorbed keyspace now maps to the survivor
                assert mgr.owner_of(pid) == succ
                prom = await (await fl.clients[succ_i].get(
                    "/distributed/metrics.prom")).text()
                assert 'dtpu_shard_owner{shard="m1"} 1' in prom
                assert "dtpu_shard_takeovers_total 1" in prom
                # `cli wal verify` stays rc=0 PER SHARD after takeover
                from comfyui_distributed_tpu.runtime import durable
                for sid in ("m0", "m1", "m2"):
                    rep = durable.verify(
                        os.path.join(fl.tmp, "wal", sid))
                    assert rep["ok"], (sid, rep)
                # absorb is idempotent: a second scan finds nothing
                got = await loop.run_in_executor(
                    None, fl.states[succ_i].shard.watch_once)
                assert got == []

        asyncio.run(go())

    def test_absorbed_prompt_relogged_in_survivor_wal(self):
        async def go():
            async with _Fleet(2) as fl:
                pid = fl.pid_owned_by("m1", tag="w")
                r = await fl.clients[1].post("/prompt", json={
                    "prompt": _tiny_prompt(), "client_id": "c",
                    "prompt_id": pid})
                assert r.status == 200
                fl.kill(1)
                loop = asyncio.get_running_loop()
                assert await loop.run_in_executor(
                    None, fl.states[0].shard.watch_once) == ["m1"]
                from comfyui_distributed_tpu.runtime import durable
                st, _ = durable.replay(os.path.join(fl.tmp, "wal",
                                                    "m0"))
                # ownership transferred: a crash of the SURVIVOR now
                # also recovers the absorbed prompt (from its own log)
                assert pid in st.prompts
                # ... and the DEAD shard's log shows it closed, so a
                # restart of m1 can never replay it a second time
                st1, _ = durable.replay(os.path.join(fl.tmp, "wal",
                                                     "m1"))
                assert pid not in st1.prompts
                # the survivor keeps renewing the absorbed lease: a
                # restarted m1 is refused at startup (fails loudly)
                # instead of reclaiming its expired lease
                fl.states[0].shard.renew_absorbed_leases()
                lease = durable.MasterLease(
                    os.path.join(fl.tmp, "wal", "m1"))
                with pytest.raises(durable.LeaseHeldError):
                    lease.acquire("m1", 2.0)
                # the per-client rate split re-applied to the new N
                assert fl.states[0].admission.rate_scale() == \
                    pytest.approx(1.0)

        asyncio.run(go())


    def test_double_death_absorbed_by_the_survivor(self):
        """Two masters dying together must not deadlock takeover: the
        one-member-removed successor of each dead shard can be the
        OTHER dead shard (~25% of vnode layouts), so the successor is
        computed over LIVE members only — the sole survivor absorbs
        both, whatever the layout."""
        async def go():
            async with _Fleet(3) as fl:
                fl.kill(1)
                fl.kill(2)
                loop = asyncio.get_running_loop()
                got = await loop.run_in_executor(
                    None, fl.states[0].shard.watch_once)
                assert sorted(got) == ["m1", "m2"]
                mgr = fl.states[0].shard
                assert sorted(mgr.owned_shards()) == ["m0", "m1", "m2"]
                assert set(mgr.ring_snapshot()["members"]) == {"m0"}

        asyncio.run(go())

    def test_lost_absorbed_lease_drops_ownership(self):
        """A superseded absorbed lease (the dead master restarted in an
        expiry gap) must make the survivor STOP driving that shard:
        keeping the absorbed/pending records would re-enqueue prompts
        the new owner is also replaying (duplicate execution)."""
        async def go():
            async with _Fleet(2) as fl:
                pid = fl.pid_owned_by("m1", tag="l")
                r = await fl.clients[1].post("/prompt", json={
                    "prompt": _tiny_prompt(), "client_id": "c",
                    "prompt_id": pid})
                assert r.status == 200
                fl.kill(1)
                loop = asyncio.get_running_loop()
                assert await loop.run_in_executor(
                    None, fl.states[0].shard.watch_once) == ["m1"]
                mgr = fl.states[0].shard
                assert "m1" in mgr.owned_shards()
                # another owner force-acquires m1's lease (epoch bump)
                from comfyui_distributed_tpu.runtime import durable
                durable.MasterLease(os.path.join(
                    fl.tmp, "wal", "m1")).acquire("m1", 30.0, force=True)
                mgr.renew_absorbed_leases()
                assert mgr.owned_shards() == ["m0"]
                assert mgr.snapshot()["pending_reenqueue"] == {}
                # ... and nothing is re-driven for the lost shard
                assert await loop.run_in_executor(
                    None, mgr.retry_absorbed_reenqueues) == 0

        asyncio.run(go())

    def test_failed_reenqueue_retried_until_landed(self):
        """A takeover against a FULL survivor queue must not lose the
        absorbed prompt: it stays durably open in the dead shard's WAL
        (whose lease the survivor holds) and in the pending set, and
        the gossip loop's retry lands + closes it once the queue
        frees — without the retry it would be gone forever (the dead
        member leaves every ring, and its restart is fenced out)."""
        async def go():
            async with _Fleet(2) as fl:
                pid = fl.pid_owned_by("m1", tag="q")
                r = await fl.clients[1].post("/prompt", json={
                    "prompt": _tiny_prompt(), "client_id": "c",
                    "prompt_id": pid})
                assert r.status == 200
                fl.kill(1)
                surv = fl.states[0]
                from comfyui_distributed_tpu.server.app import \
                    QueueFullError

                def full(*a, **k):
                    raise QueueFullError("queue full (test)")
                surv.enqueue_prompt = full
                loop = asyncio.get_running_loop()
                try:
                    assert await loop.run_in_executor(
                        None, surv.shard.watch_once) == ["m1"]
                finally:
                    del surv.enqueue_prompt
                assert pid not in surv._inflight
                assert surv.shard.snapshot()["pending_reenqueue"] \
                    == {"m1": [pid]}
                # still durably OPEN in the dead WAL (the survivor's
                # held lease keeps a fenced restart from replaying it)
                from comfyui_distributed_tpu.runtime import durable
                st1, _ = durable.replay(
                    os.path.join(fl.tmp, "wal", "m1"))
                assert pid in st1.prompts
                # the retry (gossip-loop cadence) lands it
                landed = await loop.run_in_executor(
                    None, surv.shard.retry_absorbed_reenqueues)
                assert landed == 1
                assert pid in surv._inflight
                assert surv.shard.snapshot()["pending_reenqueue"] == {}
                # ... and closes it in the dead shard's log, exactly
                # like a first-pass transfer
                st1, _ = durable.replay(
                    os.path.join(fl.tmp, "wal", "m1"))
                assert pid not in st1.prompts
                rep = durable.verify(os.path.join(fl.tmp, "wal", "m1"))
                assert rep["ok"], rep
                # nothing left to drive: the retry is a no-op now
                assert await loop.run_in_executor(
                    None, surv.shard.retry_absorbed_reenqueues) == 0

        asyncio.run(go())


class TestRouter:
    def test_router_routes_by_hash_and_merges_views(self):
        async def go():
            from aiohttp.test_utils import TestClient, TestServer

            from comfyui_distributed_tpu.runtime.shard import \
                build_router_app
            async with _Fleet(2) as fl:
                rc = TestClient(TestServer(build_router_app(fl.urls)))
                await rc.start_server()
                try:
                    ring = await (await rc.get(
                        "/distributed/ring")).json()
                    assert ring["router"] is True
                    assert set(ring["members"]) == {"m0", "m1"}
                    pids = []
                    for i in range(12):
                        r = await rc.post("/prompt", json={
                            "prompt": _tiny_prompt(),
                            "client_id": "c"})
                        body = await r.json()
                        assert r.status == 200, body
                        pids.append((body["prompt_id"], body["shard"]))
                    mgr = fl.states[0].shard
                    for pid, shard in pids:
                        # the router's placement agrees with the ring
                        assert mgr.owner_of(pid) == shard
                        i = int(shard[1:])
                        assert pid in fl.states[i]._inflight
                    # routed to BOTH shards with overwhelming odds
                    assert len({s for _, s in pids}) == 2
                    # merged /history sees every shard's jobs
                    hist = await (await rc.get("/history")).json()
                    assert isinstance(hist, dict)
                    # merged cluster metrics: shard-prefixed participants
                    cm = await (await rc.get(
                        "/distributed/cluster/metrics")).json()
                    parts = cm["participants"]
                    assert any(k.startswith("m0/") for k in parts)
                    assert any(k.startswith("m1/") for k in parts)
                    # merged fleet admission counters sum across shards
                    fleet = await (await rc.get(
                        "/distributed/fleet")).json()
                    admitted = sum(
                        v.get("admitted", 0) for v in
                        fleet["admission"]["per_class"].values())
                    assert admitted == 12
                    cl = await (await rc.get(
                        "/distributed/cluster")).json()
                    assert cl["shards"] == ["m0", "m1"]
                finally:
                    await rc.close()

        asyncio.run(go())

    def test_router_relays_retry_after_on_shed(self):
        async def go():
            from aiohttp.test_utils import TestClient, TestServer

            from comfyui_distributed_tpu.runtime.shard import \
                build_router_app
            async with _Fleet(2) as fl:
                for st in fl.states:
                    st.max_queue = 0  # every shard sheds
                rc = TestClient(TestServer(build_router_app(fl.urls)))
                await rc.start_server()
                try:
                    r = await rc.post("/prompt", json={
                        "prompt": _tiny_prompt(), "client_id": "c"})
                    assert r.status == 429
                    assert int(r.headers["Retry-After"]) >= 1
                finally:
                    await rc.close()

        asyncio.run(go())


# --- the slow acceptance -----------------------------------------------------

@pytest.mark.slow
class TestKillMasterMidUpscale:
    def test_three_masters_kill_owner_bit_identical_blend(self,
                                                          tmp_path):
        """3 active masters + 2 shared workers; the master owning a
        4-tile tiled-upscale fan-out is killed mid-job (3/4 units
        checked in, one worker stalled).  Its ring successor absorbs
        the shard, blends the spilled units from the dead shard's
        store, redispatches only the remainder — and the final PNG is
        bit-identical to the no-kill reference."""
        from comfyui_distributed_tpu.server.app import (ServerState,
                                                        build_app)

        saved = {k: os.environ.get(k) for k in (
            C.LEASE_ENV, C.FAULT_POLICY_ENV, C.HEDGE_ENV,
            C.DRAIN_TIMEOUT_ENV)}
        os.environ[C.LEASE_ENV] = "4.0"
        os.environ[C.FAULT_POLICY_ENV] = "reassign"
        os.environ[C.HEDGE_ENV] = "0"
        os.environ[C.DRAIN_TIMEOUT_ENV] = "2"

        async def go():
            from aiohttp.test_utils import TestClient, TestServer

            from comfyui_distributed_tpu.utils.image import decode_png
            loop = asyncio.get_running_loop()
            # 2 workers first (their ports go into every master's cfg)
            wstates, wclients, cfg_workers = [], [], []
            for i in range(2):
                d = tmp_path / f"worker{i}"
                (d / "in").mkdir(parents=True)
                st = ServerState(config_path=str(d / "cfg.json"),
                                 input_dir=str(d / "in"),
                                 output_dir=str(d), is_worker=True)
                client = TestClient(TestServer(build_app(st)))
                await client.start_server()
                st.port = client.server.port
                wstates.append(st)
                wclients.append(client)
                cfg_workers.append({"id": f"w{i}", "host": "127.0.0.1",
                                    "port": st.port, "enabled": True})
            cfg_path = tmp_path / "cfg.json"
            cfg_path.write_text(json.dumps(
                {"workers": cfg_workers,
                 "master": {"host": "127.0.0.1"}, "settings": {}}))

            async def wait_history(client, pid, t_s=240.0):
                deadline = time.monotonic() + t_s
                while time.monotonic() < deadline:
                    hist = await (await client.get("/history")).json()
                    if pid in hist:
                        return hist[pid]
                    await asyncio.sleep(0.05)
                raise TimeoutError(f"{pid} never finished")

            def newest_png(d):
                pngs = [os.path.join(d, f) for f in os.listdir(d)
                        if f.endswith(".png")]
                assert pngs, f"no PNG in {d}"
                return max(pngs, key=os.path.getmtime)

            async with _Fleet(3, exec_threads=True,
                              cfg_path=str(cfg_path),
                              lease_s=2.0) as fl:
                for st in fl.states:
                    st.health.interval = 0.5
                    await loop.run_in_executor(None,
                                               st.health.poll_once)
                    st.health.start()
                victim = 1
                succ = fl.states[0].shard._ring.successor("m1")
                succ_i = int(succ[1:])
                # no-kill reference on the victim (same topology as the
                # kill run: master + w0 + w1 split the 4 tiles)
                ref_pid = fl.pid_owned_by("m1", tag="ref")
                r = await fl.clients[victim].post("/prompt", json={
                    "prompt": _upscale_prompt(), "client_id": "t",
                    "prompt_id": ref_pid})
                assert r.status == 200, await r.text()
                h = await wait_history(fl.clients[victim], ref_pid)
                assert h["status"] == "success", h
                ref_png = np.asarray(decode_png(open(
                    newest_png(fl.states[victim].output_dir),
                    "rb").read()))

                # kill run: stall w1 so the job parks at 3/4 units
                wstates[1].fault_inject = {"stall_s": 300}
                pid = fl.pid_owned_by("m1", tag="kill")
                r = await fl.clients[victim].post("/prompt", json={
                    "prompt": _upscale_prompt(), "client_id": "t",
                    "prompt_id": pid})
                assert r.status == 200, await r.text()
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    snap = await (await fl.clients[victim].get(
                        "/distributed/cluster")).json()
                    jobs = snap["ledger"]["active_jobs"].values()
                    if any(j["done_units"] >= 3 for j in jobs):
                        break
                    await asyncio.sleep(0.05)
                else:
                    raise TimeoutError("job never reached 3/4 units")
                fl.kill(victim)
                wstates[1].fault_inject = {}
                # the successor's lease watcher absorbs on its own
                # thread; the job completes on the SURVIVOR
                h = await wait_history(fl.clients[succ_i], pid)
                assert h["status"] == "success", h
                mgr = fl.states[succ_i].shard
                assert "m1" in mgr.owned_shards()
                assert mgr.ring_epoch() >= 2
                snap = await (await fl.clients[succ_i].get(
                    "/distributed/cluster")).json()
                job = [j for j in snap["ledger"]["completed_jobs"]
                       if j["kind"] == "tile"][-1]
                assert job["done_units"] == job["total_units"] == 4
                # spilled units blended from the dead shard's store,
                # only the remainder recomputed
                assert job.get("recovered")
                assert job.get("preloaded_units", 0) >= 1
                kill_png = np.asarray(decode_png(open(
                    newest_png(fl.states[succ_i].output_dir),
                    "rb").read()))
                assert np.array_equal(kill_png, ref_png), \
                    "takeover blend differs from the no-kill run"
                # per-shard WAL verify stays clean after the takeover
                from comfyui_distributed_tpu.runtime import durable
                for sid in ("m0", "m1", "m2"):
                    rep = durable.verify(
                        os.path.join(fl.tmp, "wal", sid))
                    assert rep["ok"], (sid, rep)

            for c in wclients:
                await c.close()
            for st in wstates:
                await loop.run_in_executor(
                    None, lambda s=st: s.drain(0.5))

        try:
            asyncio.run(go())
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
