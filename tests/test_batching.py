"""Iteration-level continuous batching (ISSUE 12): step-granular denoise
executor with persistent shape-bucketed batches — non-contiguous
same-signature merging, per-slot (seed, fold-idx) bit-exactness vs the
serial run, tenant stride fairness through the CB pop, slot-exit-order
PNG/history provenance, and the metrics surfaces."""

import asyncio
import json
import os
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.models import samplers as smp
from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.workflow import batch_executor as cb_mod
from comfyui_distributed_tpu.workflow import scheduler as sched
from comfyui_distributed_tpu.workflow.executor import WorkflowExecutor


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


def make_prompt(seed, steps=2, size=32, text="cat", batch=1,
                sampler="euler", save=False):
    p = {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": text, "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "9": {"class_type": "EmptyLatentImage",
              "inputs": {"width": size, "height": size,
                         "batch_size": batch}},
        "8": {"class_type": "KSampler",
              "inputs": {"model": ["7", 0], "positive": ["5", 0],
                         "negative": ["6", 0], "latent_image": ["9", 0],
                         "seed": seed, "steps": steps, "cfg": 2.0,
                         "sampler_name": sampler, "scheduler": "normal",
                         "denoise": 1.0}},
        "1": {"class_type": "VAEDecode",
              "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
        "3": {"class_type": "PreviewImage", "inputs": {"images": ["1", 0]}},
    }
    if save:
        p["3"] = {"class_type": "SaveImage",
                  "inputs": {"images": ["1", 0],
                             "filename_prefix": f"cb_{seed}"}}
    return p


def make_state(tmp_path, **kw):
    kw.setdefault("cb", True)
    return ServerState(config_path=str(tmp_path / "cfg.json"),
                       input_dir=str(tmp_path / "in"),
                       output_dir=str(tmp_path / "out"), **kw)


def wait_history(state, pids, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(p in state._history for p in pids):
            return {p: state._history[p] for p in pids}
        time.sleep(0.01)
    raise AssertionError(f"prompts never finished: "
                         f"{[p for p in pids if p not in state._history]}")


def item(seed, cls="paid", steps=2, sampler="euler", cb=True):
    p = make_prompt(seed, steps=steps, sampler=sampler)
    return {"id": f"i{seed}", "prompt": p,
            "sig": sched.coalesce_signature(p),
            "cb": cb and cb_mod.quick_eligible(p),
            "tenant": cls, "t_enq": time.perf_counter()}


class TestEligibility:
    def test_safe_sampler_registry_matches_extracted_steps(self):
        """The declared product surface (constants.CB_SAFE_SAMPLERS) and
        the actual extracted step callables must never drift."""
        assert frozenset(C.CB_SAFE_SAMPLERS) \
            == frozenset(smp.SAMPLER_STEPS)

    def test_quick_eligible_plain_txt2img(self):
        assert cb_mod.quick_eligible(make_prompt(1))
        assert cb_mod.quick_eligible(make_prompt(1,
                                                 sampler="euler_ancestral"))

    def test_quick_rejects_non_step_sampler(self):
        assert not cb_mod.quick_eligible(make_prompt(1, sampler="heun"))

    def test_quick_rejects_multi_sampler_graphs(self):
        p = make_prompt(1)
        p["80"] = dict(p["8"])
        assert not cb_mod.quick_eligible(p)

    def test_quick_rejects_dispatched_shares(self):
        p = make_prompt(1)
        p["99"] = {"class_type": "DistributedCollector",
                   "inputs": {"images": ["1", 0],
                              "multi_job_id": "job"}}
        assert not cb_mod.quick_eligible(p)

    def test_quick_rejects_degenerate_steps(self):
        p = make_prompt(1)
        p["8"]["inputs"]["steps"] = 0
        assert not cb_mod.quick_eligible(p)


class TestCbPop:
    def test_non_contiguous_same_signature_merge(self):
        """A/B/A queue: the CB pop takes BOTH A prompts past the B in
        the middle — the head-run-only limitation is gone; B keeps its
        position for the next boundary."""
        adm = sched.AdmissionController()
        a1, b, a2 = item(1, steps=3), item(2, steps=1), item(3, steps=3)
        assert a1["sig"] == a2["sig"] != b["sig"]
        queue = [a1, b, a2]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: 4)
        assert kind == "cb"
        assert [it["id"] for it in items] == ["i1", "i3"]
        assert [it["id"] for it in queue] == ["i2"]

    def test_room_caps_the_sweep(self):
        adm = sched.AdmissionController()
        queue = [item(i) for i in range(5)]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: 2)
        assert kind == "cb" and len(items) == 2 and len(queue) == 3

    def test_ineligible_head_pops_legacy_group(self):
        adm = sched.AdmissionController()
        queue = [item(1, cb=False), item(2, cb=False), item(3, cb=False)]
        kind, group = sched.pop_cb_admit(queue, adm, lambda it: 0,
                                         legacy_max=8)
        assert kind == "fallback"
        # contiguous same-signature run merged, exactly like
        # pop_fair_group would
        assert [it["id"] for it in group] == ["i1", "i2", "i3"]

    def test_batchable_but_full_defers(self):
        """An eligible prompt whose bucket is full must WAIT for a slot
        exit (defer), never burn the mesh through the fallback path."""
        adm = sched.AdmissionController()
        queue = [item(1)]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: -1)
        assert kind == "defer" and not items and len(queue) == 1

    def test_fallback_busy_defers(self):
        adm = sched.AdmissionController()
        queue = [item(1, cb=False)]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: 0,
                                         fallback_ok=False)
        assert kind == "defer" and len(queue) == 1

    def test_tenant_stride_ratios_survive_cb_pop(self):
        """paid/free/batch dequeue ratios through pop_cb_admit match the
        6/3/1 stride weights — fairness survives the new dispatch
        model (the pop shares next_class with pop_fair_group)."""
        adm = sched.AdmissionController(
            weights={"paid": 6.0, "free": 3.0, "batch": 1.0},
            rate={}, burst={}, shed={})
        queue = []
        for i in range(40):
            for cls in ("paid", "free", "batch"):
                queue.append(item(1000 + i * 3, cls=cls))
        order = []
        for _ in range(60):
            kind, items = sched.pop_cb_admit(queue, adm,
                                             lambda it: 1)
            assert kind == "cb" and len(items) == 1
            order.append(items[0]["tenant"])
        counts = {cls: order.count(cls) for cls in
                  ("paid", "free", "batch")}
        assert counts["paid"] == 36 and counts["free"] == 18 \
            and counts["batch"] == 6


class TestBucketExactness:
    def test_late_join_bit_identical_to_serial(self):
        """THE exactness guarantee: a prompt that joins a RUNNING batch
        mid-flight produces a latent bit-identical to its own serial
        run — per-slot (seed, fold-idx) keys + the shared extracted
        step callable, for both a deterministic and an ancestral
        (per-step noise) sampler."""
        for sampler in ("euler", "euler_ancestral"):
            p1 = make_prompt(11, steps=3, sampler=sampler)
            p2 = make_prompt(22, steps=3, sampler=sampler)
            sig = sched.coalesce_signature(p1)
            serial = {}
            for s, p in ((11, p1), (22, p2)):
                res = WorkflowExecutor(OpContext()).execute(p)
                serial[s] = np.asarray(res.outputs["8"][0]["samples"]
                                       .data)
            i1 = {"id": "a", "prompt": p1, "sig": sig, "cb": True}
            i2 = {"id": "b", "prompt": p2, "sig": sig, "cb": True}
            bkt = cb_mod._Bucket(sig, i1, OpContext(), max_slots=4)
            bkt.admit(i1)
            bkt.step_once()          # a is mid-flight...
            bkt.admit(i2)            # ...when b joins at the boundary
            done = {}
            for _ in range(10):
                bkt.step_once()
                for its, rows, _t in bkt.take_finished():
                    arr = np.asarray(rows)
                    for j, it in enumerate(its):
                        done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
                if len(done) == 2:
                    break
            assert (done["a"] == serial[11]).all(), sampler
            assert (done["b"] == serial[22]).all(), sampler

    def test_pad_grows_and_shrinks_along_the_set(self):
        p = make_prompt(1, steps=4)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "x0", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=4)
        assert bkt.pads == [1, 2, 4]
        bkt.admit(it0)
        assert bkt.pad == 1
        for i in range(2):
            pi = make_prompt(10 + i, steps=4)
            bkt.admit({"id": f"x{i + 1}", "prompt": pi, "sig": sig,
                       "cb": True})
        assert bkt.pad == 4 and bkt.n_active == 3
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        # all slots exited together -> pad falls back to the smallest
        assert bkt.pad == 1 and bkt.retires == 3

    def test_zero_steady_state_retraces_across_occupancy_churn(self):
        """After one warm pass over a pad size, steps at that size and
        admit/retire churn within it must not retrace — the per-bucket
        jitted step + slot plumbing all come from caches keyed on the
        declared shape set."""
        from comfyui_distributed_tpu.utils import trace as trace_mod
        p = make_prompt(5, steps=2)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "w", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=2)
        # warm: one full admit->step->retire cycle at EACH pad size —
        # steady state is defined over the declared shape set, so every
        # pad must have compiled once (exactly what a serving warmup or
        # the bench's warm pass does)
        bkt.admit(it0)
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        bkt.admit({"id": "w1", "prompt": make_prompt(4, steps=2),
                   "sig": sig, "cb": True})
        bkt.admit({"id": "w2", "prompt": make_prompt(6, steps=2),
                   "sig": sig, "cb": True})
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        mark = trace_mod.GLOBAL_RETRACES.mark()
        for i in range(3):
            bkt.admit({"id": f"s{i}", "prompt":
                       make_prompt(100 + i, steps=2), "sig": sig,
                       "cb": True})
            bkt.step_once()
            bkt.take_finished()
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        assert trace_mod.GLOBAL_RETRACES.since(mark)["traces"] == 0


class TestBucketTensorParallel:
    """ISSUE 16 composition: with a 2-D data×tensor mesh live, the
    bucket's persistent padded batch is 2-D-sharded (rows over ``data``,
    UNet internals over ``tensor``) and every ISSUE-12 invariant must
    survive: per-image bit-exactness vs the serial run, the canonical
    per-pad buffer layout, and zero steady-state retraces."""

    @pytest.fixture()
    def tp_mesh(self, monkeypatch):
        import jax
        from comfyui_distributed_tpu.parallel import mesh as mesh_mod
        monkeypatch.setenv("DTPU_TP_MIN_SHARD_ELEMENTS", "2")
        registry.clear_pipeline_cache()
        mesh = mesh_mod.build_mesh(
            axes={C.DATA_AXIS: 2, C.TENSOR_AXIS: 2, C.SEQ_AXIS: 1},
            devices=jax.devices()[:4])
        mesh_mod.set_runtime(mesh_mod.MeshRuntime(mesh=mesh))
        yield mesh
        mesh_mod.set_runtime(None)
        registry.clear_pipeline_cache()

    def _drain(self, bkt, done, rounds=12):
        for _ in range(rounds):
            bkt.step_once()
            for its, rows, _t in bkt.take_finished():
                arr = np.asarray(rows)
                for j, it in enumerate(its):
                    done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
            if not bkt.n_active:
                return done
        raise AssertionError("bucket never drained")

    def test_late_join_bit_identical_to_solo_under_tp(self, tp_mesh,
                                                      monkeypatch):
        """CB per-image bit-exactness on the 2-D-sharded bucket: a slot's
        math depends only on its own (seed, fold-idx) and schedule
        position, never on co-tenants — a row that late-joins a running
        batch is BIT-identical to the same prompt run solo through the
        same sharded step kernel.  The pad set is pinned to one size
        because XLA CPU's SPMD matmuls are not row-wise bit-stable
        ACROSS batch sizes (a B=2 and a B=4 lowering round differently
        at ~1e-6) — within one padded shape, rows are bit-independent;
        vs the full-loop serial graph the match is tolerance-tight, not
        bitwise (asserted separately)."""
        monkeypatch.setenv(C.CB_PAD_BUCKETS_ENV, "2")
        p1 = make_prompt(11, steps=3, sampler="euler_ancestral")
        p2 = make_prompt(22, steps=3, sampler="euler_ancestral")
        sig = sched.coalesce_signature(p1)
        serial = {}
        for s, p in ((11, p1), (22, p2)):
            res = WorkflowExecutor(OpContext()).execute(p)
            serial[s] = np.asarray(res.outputs["8"][0]["samples"].data)
        pipe = registry.load_pipeline("tiny.safetensors")
        assert pipe._tp_mesh is tp_mesh     # serving layout engaged
        # solo reference: each prompt alone in its own bucket (padded
        # to the same rows=2 shape the shared run uses)
        solo = {}
        for pid, p in (("a", p1), ("b", p2)):
            it = {"id": pid, "prompt": p, "sig": sig, "cb": True}
            bkt = cb_mod._Bucket(sig, it, OpContext(), max_slots=2)
            assert bkt.pads == [2] and bkt._tp_mesh is tp_mesh
            bkt.admit(it)
            self._drain(bkt, solo)
        # shared run: a is mid-flight when b joins at a step boundary
        i1 = {"id": "a2", "prompt": p1, "sig": sig, "cb": True}
        i2 = {"id": "b2", "prompt": p2, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, i1, OpContext(), max_slots=2)
        bkt.admit(i1)
        bkt.step_once()
        bkt.admit(i2)
        done = {}
        self._drain(bkt, done)
        assert (done["a2"] == solo["a"]).all()
        assert (done["b2"] == solo["b"]).all()
        # and the sharded CB rows track the (differently-lowered)
        # serial full-loop graph tightly
        assert np.allclose(done["a2"], serial[11], atol=5e-4)
        assert np.allclose(done["b2"], serial[22], atol=5e-4)

    def test_bucket_buffers_carry_canonical_rows_layout(self, tp_mesh):
        """Every rows-leading persistent buffer sits on ONE layout per
        pad: rows over ``data`` when divisible, replicated otherwise —
        the invariant that keeps the donated step executable from
        re-lowering (parallel/sharding.put_rows)."""
        from comfyui_distributed_tpu.parallel import sharding as shd
        p = make_prompt(7, steps=2)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "r0", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=4)
        bkt.admit(it0)
        # pad 1: one row cannot split over data=2 -> replicated
        assert bkt.pad == 1
        assert shd.spec_of(bkt.x) == shd.mesh_spec()
        assert shd.spec_of(bkt.keys) == shd.mesh_spec()
        bkt.admit({"id": "r1", "prompt": make_prompt(8, steps=2),
                   "sig": sig, "cb": True})
        # pad 2: rows ride the data axis
        assert bkt.pad == 2
        assert shd.spec_of(bkt.x) == shd.batch_axis_spec(bkt.x.ndim)
        assert shd.spec_of(bkt.keys) == \
            shd.batch_axis_spec(bkt.keys.ndim)
        bkt.step_once()
        # the donated step hands back the SAME canonical layout
        assert bkt.x.sharding.is_equivalent_to(
            shd.named(tp_mesh, shd.batch_axis_spec(bkt.x.ndim)),
            bkt.x.ndim)

    def test_zero_steady_state_retraces_under_tp(self, tp_mesh):
        """Warm pads stay warm on the 2-D mesh: admit/retire churn after
        one pass over each pad size must not retrace — the sharded
        buffers are re-pinned to the canonical layout after every
        write/repad, so each executable only ever sees one input
        sharding."""
        from comfyui_distributed_tpu.utils import trace as trace_mod
        p = make_prompt(5, steps=2)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "w", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=2)
        bkt.admit(it0)
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        bkt.admit({"id": "w1", "prompt": make_prompt(4, steps=2),
                   "sig": sig, "cb": True})
        bkt.admit({"id": "w2", "prompt": make_prompt(6, steps=2),
                   "sig": sig, "cb": True})
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        mark = trace_mod.GLOBAL_RETRACES.mark()
        for i in range(3):
            bkt.admit({"id": f"s{i}", "prompt":
                       make_prompt(100 + i, steps=2), "sig": sig,
                       "cb": True})
            bkt.step_once()
            bkt.take_finished()
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        assert trace_mod.GLOBAL_RETRACES.since(mark)["traces"] == 0


class TestServerContinuousBatching:
    def test_interleaved_signatures_all_complete_and_merge(self,
                                                           tmp_path):
        """A/B/A interleaved queue through a real CB ServerState: all
        succeed, the two A prompts share ONE bucket (non-contiguous
        merge), and the 1-step B exits without waiting for the 3-step
        A batch to drain (slot-exit order != queue order)."""
        st = make_state(tmp_path)
        st._exec_gate.clear()
        pids = [st.enqueue_prompt(make_prompt(1, steps=3), "c"),
                st.enqueue_prompt(make_prompt(2, steps=1), "c"),
                st.enqueue_prompt(make_prompt(3, steps=3), "c")]
        st._exec_gate.set()
        hist = wait_history(st, pids)
        assert all(h["status"] == "success" for h in hist.values())
        snap = st.cb.snapshot()
        assert snap["admits"] == 3 and snap["retires"] == 3
        assert snap["fallbacks"] == 0
        by_admits = sorted(b["admits"] for b in snap["buckets"])
        assert by_admits == [1, 2]
        assert st.drain(20) is True

    def test_slot_exit_order_keeps_png_and_history_provenance(
            self, tmp_path):
        """Satellite: images may now finish out of queue order — each
        saved PNG must still embed ITS OWN prompt's seed and land in
        its own history entry."""
        from PIL import Image
        st = make_state(tmp_path)
        st._exec_gate.clear()
        # enqueue the slow prompt FIRST so the fast one overtakes it
        pids = [st.enqueue_prompt(make_prompt(71, steps=4, save=True),
                                  "c"),
                st.enqueue_prompt(make_prompt(72, steps=1, save=True),
                                  "c")]
        st._exec_gate.set()
        hist = wait_history(st, pids)
        assert all(h["status"] == "success" for h in hist.values())
        assert all(h["images"] == 1 for h in hist.values())
        out = tmp_path / "out"
        embedded = {}
        for n in os.listdir(out):
            meta = json.loads(Image.open(out / n).info["prompt"])
            embedded[meta["8"]["inputs"]["seed"]] = n
        assert set(embedded) == {71, 72}
        assert st.drain(20) is True

    def test_ineligible_prompts_ride_the_fallback(self, tmp_path):
        st = make_state(tmp_path)
        st._exec_gate.clear()
        pids = [st.enqueue_prompt(make_prompt(7, steps=1), "c"),
                st.enqueue_prompt(make_prompt(8, steps=1,
                                              sampler="heun"), "c")]
        st._exec_gate.set()
        hist = wait_history(st, pids)
        assert all(h["status"] == "success" for h in hist.values())
        assert st.cb.snapshot()["fallbacks"] >= 1
        assert st.drain(20) is True

    def test_metrics_surfaces_expose_batching(self, tmp_path):
        async def body():
            st = make_state(tmp_path)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            try:
                m = await (await client.get(
                    "/distributed/metrics")).json()
                b = m["batching"]
                assert b["enabled"] is True
                assert {"max_slots", "pad_buckets", "slots_active",
                        "slots_free", "admits", "retires", "steps",
                        "fallbacks", "buckets"} <= set(b)
                text = await (await client.get(
                    "/distributed/metrics.prom")).text()
                assert 'dtpu_batch_slots{state="active"}' in text
                assert 'dtpu_batch_slots{state="free"}' in text
                assert "dtpu_cb_admits_total" in text
                assert "dtpu_cb_retires_total" in text
                assert "dtpu_cb_steps_total" in text
            finally:
                await client.close()
                st.drain(5)
        asyncio.run(body())

    def test_cb_off_keeps_legacy_dispatch(self, tmp_path):
        """DTPU_CB unset: no executor is constructed and the classic
        exec loop serves the queue — the default path is untouched."""
        st = make_state(tmp_path, cb=False)
        assert st.cb is None
        pid = st.enqueue_prompt(make_prompt(9, steps=1), "c")
        hist = wait_history(st, [pid])
        assert hist[pid]["status"] == "success"
        assert st.drain(20) is True
