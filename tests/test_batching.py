"""Iteration-level continuous batching (ISSUE 12): step-granular denoise
executor with persistent shape-bucketed batches — non-contiguous
same-signature merging, per-slot (seed, fold-idx) bit-exactness vs the
serial run, tenant stride fairness through the CB pop, slot-exit-order
PNG/history provenance, and the metrics surfaces."""

import asyncio
import json
import os
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.models import samplers as smp
from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.workflow import batch_executor as cb_mod
from comfyui_distributed_tpu.workflow import scheduler as sched
from comfyui_distributed_tpu.workflow.executor import WorkflowExecutor


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


def make_prompt(seed, steps=2, size=32, text="cat", batch=1,
                sampler="euler", save=False):
    p = {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": text, "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "9": {"class_type": "EmptyLatentImage",
              "inputs": {"width": size, "height": size,
                         "batch_size": batch}},
        "8": {"class_type": "KSampler",
              "inputs": {"model": ["7", 0], "positive": ["5", 0],
                         "negative": ["6", 0], "latent_image": ["9", 0],
                         "seed": seed, "steps": steps, "cfg": 2.0,
                         "sampler_name": sampler, "scheduler": "normal",
                         "denoise": 1.0}},
        "1": {"class_type": "VAEDecode",
              "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
        "3": {"class_type": "PreviewImage", "inputs": {"images": ["1", 0]}},
    }
    if save:
        p["3"] = {"class_type": "SaveImage",
                  "inputs": {"images": ["1", 0],
                             "filename_prefix": f"cb_{seed}"}}
    return p


def make_state(tmp_path, **kw):
    kw.setdefault("cb", True)
    return ServerState(config_path=str(tmp_path / "cfg.json"),
                       input_dir=str(tmp_path / "in"),
                       output_dir=str(tmp_path / "out"), **kw)


def wait_history(state, pids, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(p in state._history for p in pids):
            return {p: state._history[p] for p in pids}
        time.sleep(0.01)
    raise AssertionError(f"prompts never finished: "
                         f"{[p for p in pids if p not in state._history]}")


def item(seed, cls="paid", steps=2, sampler="euler", cb=True):
    p = make_prompt(seed, steps=steps, sampler=sampler)
    return {"id": f"i{seed}", "prompt": p,
            "sig": sched.coalesce_signature(p),
            "cb": cb and cb_mod.quick_eligible(p),
            "tenant": cls, "t_enq": time.perf_counter()}


class TestEligibility:
    def test_safe_sampler_registry_matches_extracted_steps(self):
        """The declared product surface (constants.CB_SAFE_SAMPLERS) and
        the actual extracted step callables must never drift."""
        assert frozenset(C.CB_SAFE_SAMPLERS) \
            == frozenset(smp.SAMPLER_STEPS)

    def test_quick_eligible_plain_txt2img(self):
        assert cb_mod.quick_eligible(make_prompt(1))
        assert cb_mod.quick_eligible(make_prompt(1,
                                                 sampler="euler_ancestral"))

    def test_quick_rejects_non_step_sampler(self):
        assert not cb_mod.quick_eligible(make_prompt(1, sampler="heun"))

    def test_quick_rejects_multi_sampler_graphs(self):
        p = make_prompt(1)
        p["80"] = dict(p["8"])
        assert not cb_mod.quick_eligible(p)

    def test_quick_rejects_dispatched_shares(self):
        p = make_prompt(1)
        p["99"] = {"class_type": "DistributedCollector",
                   "inputs": {"images": ["1", 0],
                              "multi_job_id": "job"}}
        assert not cb_mod.quick_eligible(p)

    def test_quick_rejects_degenerate_steps(self):
        p = make_prompt(1)
        p["8"]["inputs"]["steps"] = 0
        assert not cb_mod.quick_eligible(p)


class TestCbPop:
    def test_non_contiguous_same_signature_merge(self):
        """A/B/A queue: the CB pop takes BOTH A prompts past the B in
        the middle — the head-run-only limitation is gone; B keeps its
        position for the next boundary."""
        adm = sched.AdmissionController()
        a1, b, a2 = item(1, steps=3), item(2, steps=1), item(3, steps=3)
        assert a1["sig"] == a2["sig"] != b["sig"]
        queue = [a1, b, a2]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: 4)
        assert kind == "cb"
        assert [it["id"] for it in items] == ["i1", "i3"]
        assert [it["id"] for it in queue] == ["i2"]

    def test_room_caps_the_sweep(self):
        adm = sched.AdmissionController()
        queue = [item(i) for i in range(5)]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: 2)
        assert kind == "cb" and len(items) == 2 and len(queue) == 3

    def test_ineligible_head_pops_legacy_group(self):
        adm = sched.AdmissionController()
        queue = [item(1, cb=False), item(2, cb=False), item(3, cb=False)]
        kind, group = sched.pop_cb_admit(queue, adm, lambda it: 0,
                                         legacy_max=8)
        assert kind == "fallback"
        # contiguous same-signature run merged, exactly like
        # pop_fair_group would
        assert [it["id"] for it in group] == ["i1", "i2", "i3"]

    def test_batchable_but_full_defers(self):
        """An eligible prompt whose bucket is full must WAIT for a slot
        exit (defer), never burn the mesh through the fallback path."""
        adm = sched.AdmissionController()
        queue = [item(1)]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: -1)
        assert kind == "defer" and not items and len(queue) == 1

    def test_fallback_busy_defers(self):
        adm = sched.AdmissionController()
        queue = [item(1, cb=False)]
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: 0,
                                         fallback_ok=False)
        assert kind == "defer" and len(queue) == 1

    def test_tenant_stride_ratios_survive_cb_pop(self):
        """paid/free/batch dequeue ratios through pop_cb_admit match the
        6/3/1 stride weights — fairness survives the new dispatch
        model (the pop shares next_class with pop_fair_group)."""
        adm = sched.AdmissionController(
            weights={"paid": 6.0, "free": 3.0, "batch": 1.0},
            rate={}, burst={}, shed={})
        queue = []
        for i in range(40):
            for cls in ("paid", "free", "batch"):
                queue.append(item(1000 + i * 3, cls=cls))
        order = []
        for _ in range(60):
            kind, items = sched.pop_cb_admit(queue, adm,
                                             lambda it: 1)
            assert kind == "cb" and len(items) == 1
            order.append(items[0]["tenant"])
        counts = {cls: order.count(cls) for cls in
                  ("paid", "free", "batch")}
        assert counts["paid"] == 36 and counts["free"] == 18 \
            and counts["batch"] == 6


class TestBucketExactness:
    def test_late_join_bit_identical_to_serial(self):
        """THE exactness guarantee: a prompt that joins a RUNNING batch
        mid-flight produces a latent bit-identical to its own serial
        run — per-slot (seed, fold-idx) keys + the shared extracted
        step callable, for both a deterministic and an ancestral
        (per-step noise) sampler."""
        for sampler in ("euler", "euler_ancestral"):
            p1 = make_prompt(11, steps=3, sampler=sampler)
            p2 = make_prompt(22, steps=3, sampler=sampler)
            sig = sched.coalesce_signature(p1)
            serial = {}
            for s, p in ((11, p1), (22, p2)):
                res = WorkflowExecutor(OpContext()).execute(p)
                serial[s] = np.asarray(res.outputs["8"][0]["samples"]
                                       .data)
            i1 = {"id": "a", "prompt": p1, "sig": sig, "cb": True}
            i2 = {"id": "b", "prompt": p2, "sig": sig, "cb": True}
            bkt = cb_mod._Bucket(sig, i1, OpContext(), max_slots=4)
            bkt.admit(i1)
            bkt.step_once()          # a is mid-flight...
            bkt.admit(i2)            # ...when b joins at the boundary
            done = {}
            for _ in range(10):
                bkt.step_once()
                for its, rows, _t in bkt.take_finished():
                    arr = np.asarray(rows)
                    for j, it in enumerate(its):
                        done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
                if len(done) == 2:
                    break
            assert (done["a"] == serial[11]).all(), sampler
            assert (done["b"] == serial[22]).all(), sampler

    def test_pad_grows_and_shrinks_along_the_set(self):
        p = make_prompt(1, steps=4)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "x0", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=4)
        assert bkt.pads == [1, 2, 4]
        bkt.admit(it0)
        assert bkt.pad == 1
        for i in range(2):
            pi = make_prompt(10 + i, steps=4)
            bkt.admit({"id": f"x{i + 1}", "prompt": pi, "sig": sig,
                       "cb": True})
        assert bkt.pad == 4 and bkt.n_active == 3
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        # all slots exited together -> pad falls back to the smallest
        assert bkt.pad == 1 and bkt.retires == 3

    def test_zero_steady_state_retraces_across_occupancy_churn(self):
        """After one warm pass over a pad size, steps at that size and
        admit/retire churn within it must not retrace — the per-bucket
        jitted step + slot plumbing all come from caches keyed on the
        declared shape set."""
        from comfyui_distributed_tpu.utils import trace as trace_mod
        p = make_prompt(5, steps=2)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "w", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=2)
        # warm: one full admit->step->retire cycle at EACH pad size —
        # steady state is defined over the declared shape set, so every
        # pad must have compiled once (exactly what a serving warmup or
        # the bench's warm pass does)
        bkt.admit(it0)
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        bkt.admit({"id": "w1", "prompt": make_prompt(4, steps=2),
                   "sig": sig, "cb": True})
        bkt.admit({"id": "w2", "prompt": make_prompt(6, steps=2),
                   "sig": sig, "cb": True})
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        mark = trace_mod.GLOBAL_RETRACES.mark()
        for i in range(3):
            bkt.admit({"id": f"s{i}", "prompt":
                       make_prompt(100 + i, steps=2), "sig": sig,
                       "cb": True})
            bkt.step_once()
            bkt.take_finished()
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        assert trace_mod.GLOBAL_RETRACES.since(mark)["traces"] == 0


class TestBucketTensorParallel:
    """ISSUE 16 composition: with a 2-D data×tensor mesh live, the
    bucket's persistent padded batch is 2-D-sharded (rows over ``data``,
    UNet internals over ``tensor``) and every ISSUE-12 invariant must
    survive: per-image bit-exactness vs the serial run, the canonical
    per-pad buffer layout, and zero steady-state retraces."""

    @pytest.fixture()
    def tp_mesh(self, monkeypatch):
        import jax
        from comfyui_distributed_tpu.parallel import mesh as mesh_mod
        monkeypatch.setenv("DTPU_TP_MIN_SHARD_ELEMENTS", "2")
        registry.clear_pipeline_cache()
        mesh = mesh_mod.build_mesh(
            axes={C.DATA_AXIS: 2, C.TENSOR_AXIS: 2, C.SEQ_AXIS: 1},
            devices=jax.devices()[:4])
        mesh_mod.set_runtime(mesh_mod.MeshRuntime(mesh=mesh))
        yield mesh
        mesh_mod.set_runtime(None)
        registry.clear_pipeline_cache()

    def _drain(self, bkt, done, rounds=12):
        for _ in range(rounds):
            bkt.step_once()
            for its, rows, _t in bkt.take_finished():
                arr = np.asarray(rows)
                for j, it in enumerate(its):
                    done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
            if not bkt.n_active:
                return done
        raise AssertionError("bucket never drained")

    def test_late_join_bit_identical_to_solo_under_tp(self, tp_mesh,
                                                      monkeypatch):
        """CB per-image bit-exactness on the 2-D-sharded bucket: a slot's
        math depends only on its own (seed, fold-idx) and schedule
        position, never on co-tenants — a row that late-joins a running
        batch is BIT-identical to the same prompt run solo through the
        same sharded step kernel.  The pad set is pinned to one size
        because XLA CPU's SPMD matmuls are not row-wise bit-stable
        ACROSS batch sizes (a B=2 and a B=4 lowering round differently
        at ~1e-6) — within one padded shape, rows are bit-independent;
        vs the full-loop serial graph the match is tolerance-tight, not
        bitwise (asserted separately)."""
        monkeypatch.setenv(C.CB_PAD_BUCKETS_ENV, "2")
        p1 = make_prompt(11, steps=3, sampler="euler_ancestral")
        p2 = make_prompt(22, steps=3, sampler="euler_ancestral")
        sig = sched.coalesce_signature(p1)
        serial = {}
        for s, p in ((11, p1), (22, p2)):
            res = WorkflowExecutor(OpContext()).execute(p)
            serial[s] = np.asarray(res.outputs["8"][0]["samples"].data)
        pipe = registry.load_pipeline("tiny.safetensors")
        assert pipe._tp_mesh is tp_mesh     # serving layout engaged
        # solo reference: each prompt alone in its own bucket (padded
        # to the same rows=2 shape the shared run uses)
        solo = {}
        for pid, p in (("a", p1), ("b", p2)):
            it = {"id": pid, "prompt": p, "sig": sig, "cb": True}
            bkt = cb_mod._Bucket(sig, it, OpContext(), max_slots=2)
            assert bkt.pads == [2] and bkt._tp_mesh is tp_mesh
            bkt.admit(it)
            self._drain(bkt, solo)
        # shared run: a is mid-flight when b joins at a step boundary
        i1 = {"id": "a2", "prompt": p1, "sig": sig, "cb": True}
        i2 = {"id": "b2", "prompt": p2, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, i1, OpContext(), max_slots=2)
        bkt.admit(i1)
        bkt.step_once()
        bkt.admit(i2)
        done = {}
        self._drain(bkt, done)
        assert (done["a2"] == solo["a"]).all()
        assert (done["b2"] == solo["b"]).all()
        # and the sharded CB rows track the (differently-lowered)
        # serial full-loop graph tightly
        assert np.allclose(done["a2"], serial[11], atol=5e-4)
        assert np.allclose(done["b2"], serial[22], atol=5e-4)

    def test_bucket_buffers_carry_canonical_rows_layout(self, tp_mesh):
        """Every rows-leading persistent buffer sits on ONE layout per
        pad: rows over ``data`` when divisible, replicated otherwise —
        the invariant that keeps the donated step executable from
        re-lowering (parallel/sharding.put_rows)."""
        from comfyui_distributed_tpu.parallel import sharding as shd
        p = make_prompt(7, steps=2)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "r0", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=4)
        bkt.admit(it0)
        # pad 1: one row cannot split over data=2 -> replicated
        assert bkt.pad == 1
        assert shd.spec_of(bkt.x) == shd.mesh_spec()
        assert shd.spec_of(bkt.keys) == shd.mesh_spec()
        bkt.admit({"id": "r1", "prompt": make_prompt(8, steps=2),
                   "sig": sig, "cb": True})
        # pad 2: rows ride the data axis
        assert bkt.pad == 2
        assert shd.spec_of(bkt.x) == shd.batch_axis_spec(bkt.x.ndim)
        assert shd.spec_of(bkt.keys) == \
            shd.batch_axis_spec(bkt.keys.ndim)
        bkt.step_once()
        # the donated step hands back the SAME canonical layout
        assert bkt.x.sharding.is_equivalent_to(
            shd.named(tp_mesh, shd.batch_axis_spec(bkt.x.ndim)),
            bkt.x.ndim)

    def test_zero_steady_state_retraces_under_tp(self, tp_mesh):
        """Warm pads stay warm on the 2-D mesh: admit/retire churn after
        one pass over each pad size must not retrace — the sharded
        buffers are re-pinned to the canonical layout after every
        write/repad, so each executable only ever sees one input
        sharding."""
        from comfyui_distributed_tpu.utils import trace as trace_mod
        p = make_prompt(5, steps=2)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "w", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=2)
        bkt.admit(it0)
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        bkt.admit({"id": "w1", "prompt": make_prompt(4, steps=2),
                   "sig": sig, "cb": True})
        bkt.admit({"id": "w2", "prompt": make_prompt(6, steps=2),
                   "sig": sig, "cb": True})
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        mark = trace_mod.GLOBAL_RETRACES.mark()
        for i in range(3):
            bkt.admit({"id": f"s{i}", "prompt":
                       make_prompt(100 + i, steps=2), "sig": sig,
                       "cb": True})
            bkt.step_once()
            bkt.take_finished()
        while bkt.n_active:
            bkt.step_once()
            bkt.take_finished()
        assert trace_mod.GLOBAL_RETRACES.since(mark)["traces"] == 0


class TestServerContinuousBatching:
    def test_interleaved_signatures_all_complete_and_merge(self,
                                                           tmp_path):
        """A/B/A interleaved queue through a real CB ServerState: all
        succeed, the two A prompts share ONE bucket (non-contiguous
        merge), and the 1-step B exits without waiting for the 3-step
        A batch to drain (slot-exit order != queue order)."""
        st = make_state(tmp_path)
        st._exec_gate.clear()
        pids = [st.enqueue_prompt(make_prompt(1, steps=3), "c"),
                st.enqueue_prompt(make_prompt(2, steps=1), "c"),
                st.enqueue_prompt(make_prompt(3, steps=3), "c")]
        st._exec_gate.set()
        hist = wait_history(st, pids)
        assert all(h["status"] == "success" for h in hist.values())
        snap = st.cb.snapshot()
        assert snap["admits"] == 3 and snap["retires"] == 3
        assert snap["fallbacks"] == 0
        by_admits = sorted(b["admits"] for b in snap["buckets"])
        assert by_admits == [1, 2]
        assert st.drain(20) is True

    def test_slot_exit_order_keeps_png_and_history_provenance(
            self, tmp_path):
        """Satellite: images may now finish out of queue order — each
        saved PNG must still embed ITS OWN prompt's seed and land in
        its own history entry."""
        from PIL import Image
        st = make_state(tmp_path)
        st._exec_gate.clear()
        # enqueue the slow prompt FIRST so the fast one overtakes it
        pids = [st.enqueue_prompt(make_prompt(71, steps=4, save=True),
                                  "c"),
                st.enqueue_prompt(make_prompt(72, steps=1, save=True),
                                  "c")]
        st._exec_gate.set()
        hist = wait_history(st, pids)
        assert all(h["status"] == "success" for h in hist.values())
        assert all(h["images"] == 1 for h in hist.values())
        out = tmp_path / "out"
        embedded = {}
        for n in os.listdir(out):
            meta = json.loads(Image.open(out / n).info["prompt"])
            embedded[meta["8"]["inputs"]["seed"]] = n
        assert set(embedded) == {71, 72}
        assert st.drain(20) is True

    def test_ineligible_prompts_ride_the_fallback(self, tmp_path):
        st = make_state(tmp_path)
        st._exec_gate.clear()
        pids = [st.enqueue_prompt(make_prompt(7, steps=1), "c"),
                st.enqueue_prompt(make_prompt(8, steps=1,
                                              sampler="heun"), "c")]
        st._exec_gate.set()
        hist = wait_history(st, pids)
        assert all(h["status"] == "success" for h in hist.values())
        assert st.cb.snapshot()["fallbacks"] >= 1
        assert st.drain(20) is True

    def test_metrics_surfaces_expose_batching(self, tmp_path):
        async def body():
            st = make_state(tmp_path)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            try:
                m = await (await client.get(
                    "/distributed/metrics")).json()
                b = m["batching"]
                assert b["enabled"] is True
                assert {"max_slots", "pad_buckets", "slots_active",
                        "slots_free", "admits", "retires", "steps",
                        "fallbacks", "buckets"} <= set(b)
                text = await (await client.get(
                    "/distributed/metrics.prom")).text()
                assert 'dtpu_batch_slots{state="active"}' in text
                assert 'dtpu_batch_slots{state="free"}' in text
                assert "dtpu_cb_admits_total" in text
                assert "dtpu_cb_retires_total" in text
                assert "dtpu_cb_steps_total" in text
            finally:
                await client.close()
                st.drain(5)
        asyncio.run(body())

    def test_cb_off_keeps_legacy_dispatch(self, tmp_path):
        """DTPU_CB unset: no executor is constructed and the classic
        exec loop serves the queue — the default path is untouched."""
        st = make_state(tmp_path, cb=False)
        assert st.cb is None
        pid = st.enqueue_prompt(make_prompt(9, steps=1), "c")
        hist = wait_history(st, [pid])
        assert hist[pid]["status"] == "success"
        assert st.drain(20) is True


class TestParkedStore:
    """runtime.jobs.ParkedStore: the host-side beyond-HBM working set
    (ISSUE 17) — capacity backstop, double-park guard, and the
    residency scheduler's resume ordering."""

    @staticmethod
    def rec(pid, sig="A", rank=0, t_park=0.0):
        class R:
            pass
        r = R()
        r.pid, r.sig, r.rank, r.t_park = pid, sig, rank, t_park
        return r

    def test_overflow_raises_and_room_tracks(self):
        from comfyui_distributed_tpu.runtime.jobs import ParkedStore
        st = ParkedStore(2)
        st.park([self.rec("a"), self.rec("b")])
        assert st.room() == 0 and st.count() == 2
        with pytest.raises(ValueError, match="overflow"):
            st.park([self.rec("c")])

    def test_double_park_of_same_prompt_rejected(self):
        from comfyui_distributed_tpu.runtime.jobs import ParkedStore
        st = ParkedStore(4)
        st.park([self.rec("a")])
        with pytest.raises(ValueError, match="double-park"):
            st.park([self.rec("a")])
        # the failed batch must not partially register
        assert st.count() == 1 and st.has("a")

    def test_pop_for_orders_rank_desc_then_fifo(self):
        from comfyui_distributed_tpu.runtime.jobs import ParkedStore
        st = ParkedStore(8)
        st.park([self.rec("b1", rank=0, t_park=1.0),
                 self.rec("f1", rank=1, t_park=3.0),
                 self.rec("b2", rank=0, t_park=2.0),
                 self.rec("f2", rank=1, t_park=4.0)])
        got = st.pop_for("A", 3)
        assert [r.pid for r in got] == ["f1", "f2", "b1"]
        assert st.count() == 1 and st.has("b2")

    def test_pop_for_filters_by_signature(self):
        from comfyui_distributed_tpu.runtime.jobs import ParkedStore
        st = ParkedStore(8)
        st.park([self.rec("a", sig="A"), self.rec("b", sig="B")])
        assert [r.pid for r in st.pop_for("B", 8)] == ["b"]
        assert st.sigs() == ["A"]

    def test_pop_abandoned_frees_only_gone_clients(self):
        from comfyui_distributed_tpu.runtime.jobs import ParkedStore
        st = ParkedStore(8)
        st.park([self.rec("keep"), self.rec("gone")])
        out = st.pop_abandoned(lambda pid: pid == "gone")
        assert [r.pid for r in out] == ["gone"]
        assert st.count() == 1 and not st.has("gone")

    def test_zero_capacity_store_is_inert(self):
        """DTPU_CB_PARK unset -> ParkedStore(0): every park path is
        structurally unreachable (room 0)."""
        from comfyui_distributed_tpu.runtime.jobs import ParkedStore
        st = ParkedStore(0)
        assert st.room() == 0
        with pytest.raises(ValueError, match="overflow"):
            st.park([self.rec("a")])


class TestLatentPagingExactness:
    """Bucket-level park/resume (ISSUE 17 tentpole): a parked row's
    remaining steps are bit-identical to its never-parked serial run —
    the host round trip + recomputed keys change nothing."""

    def _serial(self, p):
        res = WorkflowExecutor(OpContext()).execute(p)
        return np.asarray(res.outputs["8"][0]["samples"].data)

    def _drain(self, bkt, done):
        for _ in range(16):
            if not bkt.n_active:
                return done
            bkt.step_once()
            for its, rows, _t in bkt.take_finished():
                arr = np.asarray(rows)
                for j, it in enumerate(its):
                    done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
        raise AssertionError("bucket never drained")

    def _park_to_rec(self, bkt, idx, rank=0):
        recs = []
        for item, step, t_admit, x_rows in bkt.park_slots(idx):
            recs.append(cb_mod._ParkedRow(item, bkt.sig, rank, step,
                                          t_admit, x_rows,
                                          time.perf_counter()))
        return recs

    def test_park_resume_bit_identical_to_serial(self):
        """THE paging exactness guarantee, for a deterministic and an
        ancestral sampler: park a mid-schedule row to host while its
        co-tenant keeps stepping, resume it later, and the final latent
        is bit-equal to the serial run."""
        for sampler in ("euler", "euler_ancestral"):
            p1 = make_prompt(31, steps=3, sampler=sampler)
            p2 = make_prompt(32, steps=3, sampler=sampler)
            sig = sched.coalesce_signature(p1)
            serial = {s: self._serial(p)
                      for s, p in ((31, p1), (32, p2))}
            i1 = {"id": "a", "prompt": p1, "sig": sig, "cb": True}
            i2 = {"id": "b", "prompt": p2, "sig": sig, "cb": True}
            bkt = cb_mod._Bucket(sig, i1, OpContext(), max_slots=4)
            bkt.admit_many([i1, i2])
            bkt.step_once()                   # both at sigma index 1
            recs = self._park_to_rec(bkt, [0])   # a pages out...
            assert bkt.n_active == 1
            assert recs[0].step == 1
            done = {}
            self._drain(bkt, done)            # ...b runs to completion
            bkt.resume_parked(recs)           # a pages back in
            self._drain(bkt, done)
            assert (done["a"] == serial[31]).all(), sampler
            assert (done["b"] == serial[32]).all(), sampler

    def test_park_on_final_step_is_noop_resume(self):
        """Edge case: a row parked AT its final boundary has no steps
        left — resume must hand it straight to retirement (no extra
        step) and the latent is still the serial run's."""
        p = make_prompt(41, steps=2)
        sig = sched.coalesce_signature(p)
        it = {"id": "z", "prompt": p, "sig": sig, "cb": True}
        serial = self._serial(p)
        bkt = cb_mod._Bucket(sig, it, OpContext(), max_slots=2)
        bkt.admit(it)
        bkt.step_once()
        bkt.step_once()                       # schedule exhausted...
        recs = self._park_to_rec(bkt, [0])    # ...parked anyway
        assert recs[0].step == bkt.n_steps and bkt.n_active == 0
        bkt.resume_parked(recs)
        cohorts = bkt.take_finished()         # no step_once needed
        assert len(cohorts) == 1
        (its, rows, _t0), = cohorts
        assert its[0]["id"] == "z"
        assert (np.asarray(rows) == serial).all()

    def test_double_park_of_same_slot_rejected(self):
        p = make_prompt(42, steps=3)
        sig = sched.coalesce_signature(p)
        it = {"id": "d", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it, OpContext(), max_slots=2)
        bkt.admit(it)
        with pytest.raises(ValueError, match="double-park"):
            bkt.park_slots([0, 0])
        with pytest.raises(ValueError, match="unknown slot"):
            bkt.park_slots([3])
        # the rejected calls left the slot intact and steppable
        assert bkt.n_active == 1
        bkt.step_once()

    def test_park_resume_stays_inside_warmed_shape_set(self):
        """Zero steady-state retraces survive paging (the ISSUE 12
        guarantee): park's gather is a retire-cohort shape pair, resume
        is an admit write pair, keys are recomputed not gathered — after
        one warm pass that exercises park/resume cohort sizes, paging
        churn compiles nothing."""
        from comfyui_distributed_tpu.utils import trace as trace_mod
        p = make_prompt(51, steps=3)
        sig = sched.coalesce_signature(p)
        it0 = {"id": "w0", "prompt": p, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, it0, OpContext(), max_slots=2)
        # warm: one pass of the exact steady-state sequence, so every
        # shape pair (park gather, compaction, pad-1 step, resume
        # write, both retire cohorts) compiles here
        bkt.admit_many([it0, {"id": "w1",
                              "prompt": make_prompt(52, steps=3),
                              "sig": sig, "cb": True}])
        bkt.step_once()
        warm_recs = self._park_to_rec(bkt, [1])
        bkt.step_once()
        bkt.resume_parked(warm_recs)
        self._drain(bkt, {})
        mark = trace_mod.GLOBAL_RETRACES.mark()
        bkt.admit_many([{"id": "s0", "prompt":
                         make_prompt(53, steps=3), "sig": sig,
                         "cb": True},
                        {"id": "s1", "prompt":
                         make_prompt(54, steps=3), "sig": sig,
                         "cb": True}])
        bkt.step_once()
        recs = self._park_to_rec(bkt, [1])
        bkt.step_once()
        bkt.resume_parked(recs)
        self._drain(bkt, {})
        assert trace_mod.GLOBAL_RETRACES.since(mark)["traces"] == 0


class TestLatentPagingTensorParallel:
    """ISSUE 17 × ISSUE 16: parked rows must round-trip the 2-D
    data×tensor mesh layout — park gathers a sharded buffer to host,
    resume's ``_pin`` restores the canonical layout, and the remaining
    steps are bit-identical to the never-parked run."""

    @pytest.fixture()
    def tp_mesh(self, monkeypatch):
        import jax
        from comfyui_distributed_tpu.parallel import mesh as mesh_mod
        monkeypatch.setenv("DTPU_TP_MIN_SHARD_ELEMENTS", "2")
        registry.clear_pipeline_cache()
        mesh = mesh_mod.build_mesh(
            axes={C.DATA_AXIS: 2, C.TENSOR_AXIS: 2, C.SEQ_AXIS: 1},
            devices=jax.devices()[:4])
        mesh_mod.set_runtime(mesh_mod.MeshRuntime(mesh=mesh))
        yield mesh
        mesh_mod.set_runtime(None)
        registry.clear_pipeline_cache()

    def _drain(self, bkt, done):
        for _ in range(16):
            if not bkt.n_active:
                return done
            bkt.step_once()
            for its, rows, _t in bkt.take_finished():
                arr = np.asarray(rows)
                for j, it in enumerate(its):
                    done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
        raise AssertionError("bucket never drained")

    def test_park_resume_bit_identical_under_tp(self, tp_mesh,
                                                monkeypatch):
        """Park one of two rows out of a 2-D-sharded bucket mid-flight,
        resume it after its co-tenant finishes, and both final latents
        are BIT-identical to the same prompts run without any parking
        through the same sharded step kernel.  The pad set is pinned to
        one size (the ISSUE 16 caveat: XLA CPU SPMD matmuls are not
        row-wise bit-stable across batch sizes)."""
        monkeypatch.setenv(C.CB_PAD_BUCKETS_ENV, "2")
        p1 = make_prompt(61, steps=3, sampler="euler_ancestral")
        p2 = make_prompt(62, steps=3, sampler="euler_ancestral")
        sig = sched.coalesce_signature(p1)
        # reference: the same two prompts, same bucket geometry, no
        # parking
        ref = {}
        i1 = {"id": "a", "prompt": p1, "sig": sig, "cb": True}
        i2 = {"id": "b", "prompt": p2, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, i1, OpContext(), max_slots=2)
        assert bkt.pads == [2] and bkt._tp_mesh is tp_mesh
        bkt.admit_many([i1, i2])
        self._drain(bkt, ref)
        # paged run: a parks at sigma index 1, b drains, a resumes
        j1 = {"id": "a2", "prompt": p1, "sig": sig, "cb": True}
        j2 = {"id": "b2", "prompt": p2, "sig": sig, "cb": True}
        bkt = cb_mod._Bucket(sig, j1, OpContext(), max_slots=2)
        bkt.admit_many([j1, j2])
        bkt.step_once()
        recs = [cb_mod._ParkedRow(item, sig, 0, step, t_admit, x_rows,
                                  time.perf_counter())
                for item, step, t_admit, x_rows
                in bkt.park_slots([0])]
        from comfyui_distributed_tpu.parallel import sharding as shd
        # host copy detached from the mesh; the live buffer stays
        # canonically sharded
        assert isinstance(recs[0].x_rows, np.ndarray)
        done = {}
        self._drain(bkt, done)
        bkt.resume_parked(recs)
        # resume restored the canonical rows layout for this pad
        assert bkt.x.sharding.is_equivalent_to(
            shd.named(tp_mesh, shd.spec_of(bkt.x)), bkt.x.ndim)
        self._drain(bkt, done)
        assert (done["a2"] == ref["a"]).all()
        assert (done["b2"] == ref["b"]).all()


def make_harness(tmp_path, monkeypatch, slots=2, park="1",
                 park_max=None):
    """A ContinuousBatchExecutor driven BY THE TEST (never started):
    deterministic single-threaded park/resume scheduling, backed by a
    real ServerState for capture contexts and finalize plumbing."""
    monkeypatch.setenv(C.CB_PARK_ENV, park)
    monkeypatch.setenv(C.CB_SLOTS_ENV, str(slots))
    if park_max is not None:
        monkeypatch.setenv(C.CB_PARK_MAX_ENV, str(park_max))
    st = make_state(tmp_path, cb=False)
    return st, cb_mod.ContinuousBatchExecutor(st)


class TestSloPreemption:
    """Executor-level residency scheduling (ISSUE 17): preempt order
    batch < free < paid-never, victim/resume ordering, the PR 5 HBM
    gate, and the PR 13 client-gone composition."""

    def test_room_for_counts_preemptible_lower_class(
            self, tmp_path, monkeypatch):
        st, ex = make_harness(tmp_path, monkeypatch)
        ex._admit_cb([item(201, cls="batch", steps=4)])
        ex._admit_cb([item(202, cls="free", steps=4)])
        bkt = next(iter(ex._buckets.values()))
        assert bkt.n_active == 2            # full
        assert ex.room_for(item(203, cls="paid", steps=4)) == 2
        assert ex.room_for(item(204, cls="free", steps=4)) == 1
        assert ex.room_for(item(205, cls="batch", steps=4)) == -1

    def test_park_disabled_keeps_hard_full_semantics(
            self, tmp_path, monkeypatch):
        st, ex = make_harness(tmp_path, monkeypatch, park="0")
        assert ex.parked.room() == 0
        ex._admit_cb([item(211, cls="batch", steps=4),
                      item(212, cls="batch", steps=4)])
        assert ex.room_for(item(213, cls="paid", steps=4)) == -1

    def test_paid_admit_parks_youngest_lowest_class(
            self, tmp_path, monkeypatch):
        """A paid arrival into a full bucket parks the YOUNGEST
        batch-tier row (oldest started work keeps its slot), admits the
        paid prompt at the same boundary, and books the park on every
        surface: stats, counters, gauge, store."""
        from comfyui_distributed_tpu.utils import trace as trace_mod
        st, ex = make_harness(tmp_path, monkeypatch)
        ex._admit_cb([item(221, cls="batch", steps=6)])
        ex._admit_cb([item(222, cls="batch", steps=6)])
        ex._admit_cb([item(223, cls="paid", steps=6)])
        bkt = next(iter(ex._buckets.values()))
        assert {s.item["id"] for s in bkt.slots} == {"i221", "i223"}
        assert ex.parked.has("i222") and ex.parked.count() == 1
        snap = ex.snapshot()
        assert snap["parks"] == 1 and snap["preemptions"] == 1
        assert snap["parked"] == 1 and snap["park_enabled"] is True
        assert trace_mod.GLOBAL_GAUGES.get("cb_parked") == 1.0
        assert not ex.idle()                # parked rows pin liveness

    def test_preempted_row_resumes_and_matches_serial(
            self, tmp_path, monkeypatch):
        """End-to-end through the executor's own park/resume methods: a
        batch row preempted mid-schedule by a paid arrival resumes once
        the slot frees and its latent is bit-equal to the serial run."""
        st, ex = make_harness(tmp_path, monkeypatch, slots=1)
        victim = item(231, cls="batch", steps=4)
        serial = np.asarray(WorkflowExecutor(OpContext()).execute(
            victim["prompt"]).outputs["8"][0]["samples"].data)
        ex._admit_cb([victim])
        bkt = next(iter(ex._buckets.values()))
        bkt.step_once()                     # victim is mid-flight...
        ex._admit_cb([item(232, cls="paid", steps=4)])
        assert ex.parked.has("i231")
        done = {}
        for _ in range(8):                  # ...paid runs to completion
            if not bkt.n_active:
                break
            bkt.step_once()
            for its, rows, _t in bkt.take_finished():
                arr = np.asarray(rows)
                for j, it in enumerate(its):
                    done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
        assert "i232" in done
        assert ex._resume_boundary() is True
        assert not ex.parked.has("i231")
        for _ in range(8):
            if not bkt.n_active:
                break
            bkt.step_once()
            for its, rows, _t in bkt.take_finished():
                arr = np.asarray(rows)
                for j, it in enumerate(its):
                    done[it["id"]] = arr[j * bkt.b:(j + 1) * bkt.b]
        assert (done["i231"] == serial).all()
        snap = ex.snapshot()
        assert snap["resumes"] == 1 and snap["parked"] == 0

    def test_resume_order_free_before_batch(self, tmp_path,
                                            monkeypatch):
        st, ex = make_harness(tmp_path, monkeypatch)
        ex._admit_cb([item(241, cls="batch", steps=4)])
        ex._admit_cb([item(242, cls="free", steps=4)])
        bkt = next(iter(ex._buckets.values()))
        ex._park_out(bkt, [0, 1])
        assert bkt.n_active == 0 and ex.parked.count() == 2
        assert ex._resume_boundary() is True
        # both fit, and the higher class landed first
        assert [s.item["id"] for s in bkt.slots] == ["i242", "i241"]

    def test_resume_gated_on_hbm_fraction(self, tmp_path, monkeypatch):
        """PR 5 telemetry drives residency: above the fraction nothing
        resumes (re-admitting under pressure would undo the shed), and
        _pressure_park sheds exactly one lowest-class slot per
        boundary."""
        st, ex = make_harness(tmp_path, monkeypatch)
        ex._admit_cb([item(251, cls="batch", steps=4)])
        ex._admit_cb([item(252, cls="free", steps=4)])
        bkt = next(iter(ex._buckets.values()))
        ex._mem_probe = lambda: {"bytes_in_use": 95, "bytes_limit": 100}
        ex._pressure_park()                 # sheds the batch row only
        assert ex.parked.count() == 1 and ex.parked.has("i251")
        assert ex._resume_boundary() is False   # gate holds it out
        assert ex.parked.count() == 1
        ex._mem_probe = lambda: {"bytes_in_use": 10, "bytes_limit": 100}
        assert ex._resume_boundary() is True
        assert ex.parked.count() == 0 and bkt.n_active == 2

    def test_paid_rows_never_pressure_parked(self, tmp_path,
                                             monkeypatch):
        st, ex = make_harness(tmp_path, monkeypatch)
        ex._admit_cb([item(261, cls="paid", steps=4),
                      item(262, cls="paid", steps=4)])
        ex._mem_probe = lambda: {"bytes_in_use": 99, "bytes_limit": 100}
        ex._pressure_park()
        assert ex.parked.count() == 0       # nothing preemptible

    def test_abandoned_parked_row_freed_without_resume(
            self, tmp_path, monkeypatch):
        """PR 13 composition (satellite): the client of a PARKED row
        disconnects — the row is finalized as abandoned and freed, its
        slot claim evaporates, and no denoise steps are spent on it."""
        from comfyui_distributed_tpu.runtime import reuse as reuse_mod
        st, ex = make_harness(tmp_path, monkeypatch)
        ex._admit_cb([item(271, cls="batch", steps=4)])
        bkt = next(iter(ex._buckets.values()))
        ex._park_out(bkt, [0])
        assert ex.parked.count() == 1
        reuse_mod.PREVIEWS.abandon("i271")
        steps_before = bkt.steps_done
        assert ex._resume_boundary() is False   # freed, not resumed
        assert ex.parked.count() == 0 and bkt.n_active == 0
        assert bkt.steps_done == steps_before
        hist = wait_history(st, ["i271"], timeout=30)
        assert hist["i271"]["status"] == "abandoned"
        assert ex.snapshot()["abandoned"] == 1

    def test_all_parked_bucket_survives_eviction(self, tmp_path,
                                                 monkeypatch):
        """A bucket whose every row is parked is idle-by-count but must
        NOT be evicted: its captured conditioning is the only thing the
        rows can resume into."""
        st, ex = make_harness(tmp_path, monkeypatch)
        ex._admit_cb([item(281, cls="batch", steps=4)])
        bkt = next(iter(ex._buckets.values()))
        ex._park_out(bkt, [0])
        assert bkt.n_active == 0
        ex._evict_idle_bucket()
        assert bkt.sig in ex._buckets
        assert ex._resume_boundary() is True
        assert bkt.n_active == 1

    def test_validate_cb_env_rejects_malformed_knobs(self):
        cb_mod.validate_cb_env({})           # absent -> fine
        cb_mod.validate_cb_env({
            C.CB_SLOTS_ENV: "8", C.CB_PARK_ENV: "1",
            C.CB_PARK_MAX_ENV: "0",
            C.CB_PARK_HBM_FRACTION_ENV: "0.85"})
        for env, frag in (
                ({C.CB_SLOTS_ENV: "0"}, C.CB_SLOTS_ENV),
                ({C.CB_SLOTS_ENV: "two"}, C.CB_SLOTS_ENV),
                ({C.CB_PARK_MAX_ENV: "-1"}, C.CB_PARK_MAX_ENV),
                ({C.CB_PARK_ENV: "maybe"}, C.CB_PARK_ENV),
                ({C.CB_PARK_HBM_FRACTION_ENV: "1.5"},
                 C.CB_PARK_HBM_FRACTION_ENV),
                ({C.CB_PARK_HBM_FRACTION_ENV: "lots"},
                 C.CB_PARK_HBM_FRACTION_ENV)):
            with pytest.raises(ValueError, match=frag):
                cb_mod.validate_cb_env(env)


class TestCbPopPreemption:
    """pop_cb_admit's blocked-class re-peek (ISSUE 17): a class whose
    bucket is full no longer blinds the pop to admissible work behind
    it in stride order."""

    def test_blocked_class_repeeks_next_class(self):
        adm = sched.AdmissionController(
            weights={"batch": 6.0, "paid": 1.0, "free": 1.0},
            rate={}, burst={}, shed={})
        b, p = item(301, cls="batch"), item(302, cls="paid")
        queue = [b, p]
        # batch wins the stride peek but its bucket is full; paid has
        # preemption room
        kind, items = sched.pop_cb_admit(
            queue, adm,
            lambda it: -1 if it["tenant"] == "batch" else 1)
        assert kind == "cb"
        assert [it["id"] for it in items] == ["i302"]
        assert [it["id"] for it in queue] == ["i301"]

    def test_all_classes_blocked_defers_without_stride_charge(self):
        adm = sched.AdmissionController(
            weights={"paid": 6.0, "free": 3.0, "batch": 1.0},
            rate={}, burst={}, shed={})
        queue = [item(311, cls="paid"), item(312, cls="batch")]
        before = dict(adm._pass)
        kind, items = sched.pop_cb_admit(queue, adm, lambda it: -1)
        assert kind == "defer" and not items and len(queue) == 2
        # a deferred boundary must not advance any class's pass
        assert dict(adm._pass) == before


class TestServerPreemptionE2E:
    def test_paid_preempts_running_batch_end_to_end(self, tmp_path,
                                                    monkeypatch):
        """The tentpole scenario through a real CB ServerState: a
        saturated one-slot bucket running a batch-tier prompt gets a
        paid arrival — the batch row PARKS mid-schedule, the paid
        prompt takes the slot, and the parked row resumes and completes
        after it.  Both succeed; every park surface moved."""
        monkeypatch.setenv(C.CB_PARK_ENV, "1")
        monkeypatch.setenv(C.CB_SLOTS_ENV, "1")
        st = make_state(tmp_path)
        # same structural signature (preemption is within-bucket), so
        # the paid arrival lands on the saturated batch bucket
        pid_b = st.enqueue_prompt(make_prompt(91, steps=8), "c",
                                  tenant="batch")
        deadline = time.monotonic() + 60
        while st.cb.snapshot()["admits"] < 1:
            assert time.monotonic() < deadline, "batch never admitted"
            time.sleep(0.002)
        pid_p = st.enqueue_prompt(make_prompt(92, steps=8), "c",
                                  tenant="paid")
        hist = wait_history(st, [pid_b, pid_p])
        assert all(h["status"] == "success" for h in hist.values())
        snap = st.cb.snapshot()
        assert snap["parks"] >= 1 and snap["preemptions"] >= 1
        assert snap["resumes"] >= 1
        assert snap["parked"] == 0 and snap["retires"] == 2
        assert st.drain(20) is True

    def test_metrics_surfaces_expose_paging(self, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv(C.CB_PARK_ENV, "1")

        async def body():
            st = make_state(tmp_path)
            client = TestClient(TestServer(build_app(st)))
            await client.start_server()
            try:
                m = await (await client.get(
                    "/distributed/metrics")).json()
                b = m["batching"]
                assert b["park_enabled"] is True
                assert {"parked", "park_room", "parks", "resumes",
                        "preemptions"} <= set(b)
                text = await (await client.get(
                    "/distributed/metrics.prom")).text()
                assert "dtpu_cb_parked" in text
                assert "dtpu_cb_parks_total" in text
                assert "dtpu_cb_resumes_total" in text
                assert "dtpu_cb_preemptions_total" in text
            finally:
                await client.close()
                st.drain(5)
        asyncio.run(body())
