"""Full canonical SD1.5 checkpoint-layout test (VERDICT r2 #6).

Synthesizes the COMPLETE canonical SD1.5 torch state dict — every key and
exact shape of a real ``v1-5-pruned-emaonly``-style single-file checkpoint,
enumerated here independently from the known LDM/CompVis torch module
structure (NOT by walking this repo's mapper, so an enumeration bug in the
mapper cannot cancel out) — and asserts:

* export covers exactly the canonical key set, shape-for-shape
  (zero missing / zero unexpected, export direction);
* loading the canonical dict (plus the real checkpoints' non-parameter
  buffers: DDPM schedule tensors, CLIP position_ids) consumes every
  parameter key (zero unconsumed, load direction) and fully populates the
  flax trees;
* VAE attention tensors are 4D 1x1 convs both ways (the ADVICE r1 fix).

Full-size arrays are ``np.zeros`` views throughout (lazily mapped pages,
layout transforms are transposes/views), so the whole 860M-param layout is
checked in seconds without gigabytes of RSS.
"""

import jax
import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models import checkpoints as ckpt
from comfyui_distributed_tpu.models import clip as clip_mod
from comfyui_distributed_tpu.models import registry as reg
from comfyui_distributed_tpu.models import unet as unet_mod
from comfyui_distributed_tpu.models import vae as vae_mod


# --- independent canonical SD1.5 inventory (torch LDM layout) ---------------

def sd15_unet_inventory():
    return _ldm_unet_inventory(ctx=768, linear_proj=False)


def sd21_unet_inventory():
    # SD2.1: same topology, OpenCLIP-H context, nn.Linear transformer
    # projections (use_linear_in_transformer)
    return _ldm_unet_inventory(ctx=1024, linear_proj=True)


def _ldm_unet_inventory(ctx, linear_proj):
    keys = {}

    def p(name, *shape):
        keys["model.diffusion_model." + name] = tuple(shape)

    mc = 320
    emb = 4 * mc
    p("time_embed.0.weight", emb, mc); p("time_embed.0.bias", emb)
    p("time_embed.2.weight", emb, emb); p("time_embed.2.bias", emb)
    p("input_blocks.0.0.weight", mc, 4, 3, 3); p("input_blocks.0.0.bias", mc)

    def res(prefix, cin, cout):
        p(f"{prefix}.in_layers.0.weight", cin)
        p(f"{prefix}.in_layers.0.bias", cin)
        p(f"{prefix}.in_layers.2.weight", cout, cin, 3, 3)
        p(f"{prefix}.in_layers.2.bias", cout)
        p(f"{prefix}.emb_layers.1.weight", cout, emb)
        p(f"{prefix}.emb_layers.1.bias", cout)
        p(f"{prefix}.out_layers.0.weight", cout)
        p(f"{prefix}.out_layers.0.bias", cout)
        p(f"{prefix}.out_layers.3.weight", cout, cout, 3, 3)
        p(f"{prefix}.out_layers.3.bias", cout)
        if cin != cout:
            p(f"{prefix}.skip_connection.weight", cout, cin, 1, 1)
            p(f"{prefix}.skip_connection.bias", cout)

    def attn(prefix, c, depth=1):
        p(f"{prefix}.norm.weight", c); p(f"{prefix}.norm.bias", c)
        if linear_proj:                              # SD2.x/SDXL: nn.Linear
            p(f"{prefix}.proj_in.weight", c, c)
        else:
            p(f"{prefix}.proj_in.weight", c, c, 1, 1)  # SD1.x: 1x1 conv
        p(f"{prefix}.proj_in.bias", c)
        for j in range(depth):
            b = f"{prefix}.transformer_blocks.{j}"
            for a, kvdim in (("attn1", c), ("attn2", ctx)):
                p(f"{b}.{a}.to_q.weight", c, c)
                p(f"{b}.{a}.to_k.weight", c, kvdim)
                p(f"{b}.{a}.to_v.weight", c, kvdim)
                p(f"{b}.{a}.to_out.0.weight", c, c)
                p(f"{b}.{a}.to_out.0.bias", c)
            p(f"{b}.ff.net.0.proj.weight", 8 * c, c)   # GEGLU: 2 * 4c
            p(f"{b}.ff.net.0.proj.bias", 8 * c)
            p(f"{b}.ff.net.2.weight", c, 4 * c)
            p(f"{b}.ff.net.2.bias", c)
            for n in ("norm1", "norm2", "norm3"):
                p(f"{b}.{n}.weight", c); p(f"{b}.{n}.bias", c)
        if linear_proj:
            p(f"{prefix}.proj_out.weight", c, c)
        else:
            p(f"{prefix}.proj_out.weight", c, c, 1, 1)
        p(f"{prefix}.proj_out.bias", c)

    mult = (1, 2, 4, 4)
    has_attn = (True, True, True, False)   # attention_resolutions [4,2,1]
    ch = mc
    skip_chans = [mc]
    idx = 1
    for lvl in range(4):
        cout = mult[lvl] * mc
        for _ in range(2):
            res(f"input_blocks.{idx}.0", ch, cout)
            ch = cout
            if has_attn[lvl]:
                attn(f"input_blocks.{idx}.1", ch)
            skip_chans.append(ch)
            idx += 1
        if lvl != 3:
            p(f"input_blocks.{idx}.0.op.weight", ch, ch, 3, 3)
            p(f"input_blocks.{idx}.0.op.bias", ch)
            skip_chans.append(ch)
            idx += 1

    res("middle_block.0", ch, ch)
    attn("middle_block.1", ch)
    res("middle_block.2", ch, ch)

    idx = 0
    for lvl in reversed(range(4)):
        cout = mult[lvl] * mc
        for i in range(3):
            res(f"output_blocks.{idx}.0", ch + skip_chans.pop(), cout)
            ch = cout
            sub = 1
            if has_attn[lvl]:
                attn(f"output_blocks.{idx}.{sub}", ch)
                sub += 1
            if lvl != 0 and i == 2:
                p(f"output_blocks.{idx}.{sub}.conv.weight", ch, ch, 3, 3)
                p(f"output_blocks.{idx}.{sub}.conv.bias", ch)
            idx += 1

    p("out.0.weight", mc); p("out.0.bias", mc)
    p("out.2.weight", 4, mc, 3, 3); p("out.2.bias", 4)
    return keys


def sd15_vae_inventory():
    keys = {}

    def p(name, *shape):
        keys["first_stage_model." + name] = tuple(shape)

    ch, mult, z = 128, (1, 2, 4, 4), 4

    def res(prefix, cin, cout):
        p(f"{prefix}.norm1.weight", cin); p(f"{prefix}.norm1.bias", cin)
        p(f"{prefix}.conv1.weight", cout, cin, 3, 3)
        p(f"{prefix}.conv1.bias", cout)
        p(f"{prefix}.norm2.weight", cout); p(f"{prefix}.norm2.bias", cout)
        p(f"{prefix}.conv2.weight", cout, cout, 3, 3)
        p(f"{prefix}.conv2.bias", cout)
        if cin != cout:
            p(f"{prefix}.nin_shortcut.weight", cout, cin, 1, 1)
            p(f"{prefix}.nin_shortcut.bias", cout)

    def attn(prefix, c):
        p(f"{prefix}.norm.weight", c); p(f"{prefix}.norm.bias", c)
        for n in ("q", "k", "v", "proj_out"):
            p(f"{prefix}.{n}.weight", c, c, 1, 1)    # ALWAYS 1x1 convs
            p(f"{prefix}.{n}.bias", c)

    p("encoder.conv_in.weight", ch, 3, 3, 3); p("encoder.conv_in.bias", ch)
    cin = ch
    for lvl in range(4):
        cout = mult[lvl] * ch
        for i in range(2):
            res(f"encoder.down.{lvl}.block.{i}", cin, cout)
            cin = cout
        if lvl != 3:
            p(f"encoder.down.{lvl}.downsample.conv.weight", cin, cin, 3, 3)
            p(f"encoder.down.{lvl}.downsample.conv.bias", cin)
    res("encoder.mid.block_1", cin, cin)
    attn("encoder.mid.attn_1", cin)
    res("encoder.mid.block_2", cin, cin)
    p("encoder.norm_out.weight", cin); p("encoder.norm_out.bias", cin)
    p("encoder.conv_out.weight", 2 * z, cin, 3, 3)
    p("encoder.conv_out.bias", 2 * z)

    p("decoder.conv_in.weight", cin, z, 3, 3); p("decoder.conv_in.bias", cin)
    res("decoder.mid.block_1", cin, cin)
    attn("decoder.mid.attn_1", cin)
    res("decoder.mid.block_2", cin, cin)
    cur = cin
    for lvl in reversed(range(4)):   # torch builds up.3 (deepest) first
        cout = mult[lvl] * ch
        for i in range(3):
            res(f"decoder.up.{lvl}.block.{i}", cur, cout)
            cur = cout
        if lvl != 0:
            p(f"decoder.up.{lvl}.upsample.conv.weight", cur, cur, 3, 3)
            p(f"decoder.up.{lvl}.upsample.conv.bias", cur)
    p("decoder.norm_out.weight", cur); p("decoder.norm_out.bias", cur)
    p("decoder.conv_out.weight", 3, cur, 3, 3); p("decoder.conv_out.bias", 3)

    p("quant_conv.weight", 2 * z, 2 * z, 1, 1); p("quant_conv.bias", 2 * z)
    p("post_quant_conv.weight", z, z, 1, 1); p("post_quant_conv.bias", z)
    return keys


def sd15_clip_inventory():
    keys = {}
    pre = "cond_stage_model.transformer.text_model."

    def p(name, *shape):
        keys[pre + name] = tuple(shape)

    W, L, V, N = 768, 12, 49408, 77
    p("embeddings.token_embedding.weight", V, W)
    p("embeddings.position_embedding.weight", N, W)
    for i in range(L):
        b = f"encoder.layers.{i}"
        for nm in ("q_proj", "k_proj", "v_proj", "out_proj"):
            p(f"{b}.self_attn.{nm}.weight", W, W)
            p(f"{b}.self_attn.{nm}.bias", W)
        for nm in ("layer_norm1", "layer_norm2"):
            p(f"{b}.{nm}.weight", W); p(f"{b}.{nm}.bias", W)
        p(f"{b}.mlp.fc1.weight", 4 * W, W); p(f"{b}.mlp.fc1.bias", 4 * W)
        p(f"{b}.mlp.fc2.weight", W, 4 * W); p(f"{b}.mlp.fc2.bias", W)
    p("final_layer_norm.weight", W); p("final_layer_norm.bias", W)
    return keys


def sd21_clip_inventory():
    """OpenCLIP ViT-H text tower, FrozenOpenCLIPEmbedder serialization
    (``cond_stage_model.model.*``, packed in_proj, raw text_projection)."""
    keys = {}
    pre = "cond_stage_model.model."

    def p(name, *shape):
        keys[pre + name] = tuple(shape)

    W, L, V, N = 1024, 24, 49408, 77
    p("token_embedding.weight", V, W)
    p("positional_embedding", N, W)
    for i in range(L):
        b = f"transformer.resblocks.{i}"
        p(f"{b}.ln_1.weight", W); p(f"{b}.ln_1.bias", W)
        p(f"{b}.attn.in_proj_weight", 3 * W, W)
        p(f"{b}.attn.in_proj_bias", 3 * W)
        p(f"{b}.attn.out_proj.weight", W, W)
        p(f"{b}.attn.out_proj.bias", W)
        p(f"{b}.ln_2.weight", W); p(f"{b}.ln_2.bias", W)
        p(f"{b}.mlp.c_fc.weight", 4 * W, W); p(f"{b}.mlp.c_fc.bias", 4 * W)
        p(f"{b}.mlp.c_proj.weight", W, 4 * W); p(f"{b}.mlp.c_proj.bias", W)
    p("ln_final.weight", W); p("ln_final.bias", W)
    p("text_projection", W, W)
    return keys


def sd15_nonparam_buffers():
    """Non-parameter tensors real SD1.5 checkpoints carry."""
    sd = {f"{n}": np.zeros((1000,), np.float32) for n in (
        "betas", "alphas_cumprod", "alphas_cumprod_prev",
        "sqrt_alphas_cumprod", "sqrt_one_minus_alphas_cumprod",
        "log_one_minus_alphas_cumprod", "sqrt_recip_alphas_cumprod",
        "sqrt_recipm1_alphas_cumprod", "posterior_variance",
        "posterior_log_variance_clipped", "posterior_mean_coef1",
        "posterior_mean_coef2")}
    sd["logvar"] = np.zeros((1000,), np.float32)
    sd["cond_stage_model.transformer.text_model.embeddings.position_ids"] = \
        np.zeros((1, 77), np.int64)
    return sd


def canonical_sd15():
    inv = {**sd15_unet_inventory(), **sd15_vae_inventory(),
           **sd15_clip_inventory()}
    sd = {k: np.zeros(s, np.float32) for k, s in inv.items()}
    sd.update(sd15_nonparam_buffers())
    return inv, sd


def canonical_sd21():
    inv = {**sd21_unet_inventory(), **sd15_vae_inventory(),
           **sd21_clip_inventory()}
    sd = {k: np.zeros(s, np.float32) for k, s in inv.items()}
    buffers = sd15_nonparam_buffers()
    # SD2.x carries the OpenCLIP tower's buffers instead of HF position_ids
    del buffers["cond_stage_model.transformer.text_model"
                ".embeddings.position_ids"]
    buffers["cond_stage_model.model.attn_mask"] = np.zeros((77, 77),
                                                           np.float32)
    buffers["cond_stage_model.model.logit_scale"] = np.zeros((), np.float32)
    sd.update(buffers)
    return inv, sd


# --- full-size flax trees as zeros (eval_shape: trace only, no compile) -----

def _zeros_params(module, *shaped_args):
    shapes = jax.eval_shape(module.init, jax.random.PRNGKey(0), *shaped_args)
    return jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, np.float32), shapes)["params"]


def _family_trees(name):
    fam = reg.FAMILIES[name]
    unet_p = _zeros_params(unet_mod.UNet(fam.unet),
                           jnp.zeros((1, 8, 8, 4)), jnp.zeros((1,)),
                           jnp.zeros((1, 77, fam.unet.context_dim)))
    clip_p = _zeros_params(clip_mod.CLIPTextModel(fam.clips[0]),
                           jnp.zeros((1, 77), jnp.int32))
    vae_p = _zeros_params(vae_mod.VAE(fam.vae),
                          jnp.zeros((1, 64, 64, 3)))
    return fam, unet_p, clip_p, vae_p


def _sd15_trees():
    return _family_trees("sd15")


def _tree_keys(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(k): tuple(v.shape) for k, v in flat}


def test_export_matches_canonical_inventory_exactly():
    """Zero missing / zero unexpected keys, exact shapes — export side."""
    fam, unet_p, clip_p, vae_p = _sd15_trees()
    inv, _ = canonical_sd15()
    sd = ckpt.export_state_dict(unet_p, [clip_p], vae_p, fam)
    missing = sorted(set(inv) - set(sd))
    unexpected = sorted(set(sd) - set(inv))
    assert not missing, f"{len(missing)} missing, first: {missing[:8]}"
    assert not unexpected, \
        f"{len(unexpected)} unexpected, first: {unexpected[:8]}"
    bad = [(k, sd[k].shape, inv[k]) for k in inv
           if tuple(sd[k].shape) != inv[k]]
    assert not bad, f"{len(bad)} shape mismatches, first: {bad[:5]}"


def test_load_canonical_full_coverage():
    """Every parameter key consumed; flax trees fully populated — load
    side (includes the schedule buffers + position_ids real files carry)."""
    fam, unet_p, clip_p, vae_p = _sd15_trees()
    _, sd = canonical_sd15()
    leftover = ckpt.unconsumed_keys(sd, fam)
    assert leftover == [], \
        f"{len(leftover)} unconsumed param keys, first: {leftover[:8]}"
    u2, (c2,), v2 = ckpt.convert_state_dict(sd, fam)
    assert _tree_keys(u2) == _tree_keys(unet_p)
    assert _tree_keys(c2) == _tree_keys(clip_p)
    assert _tree_keys(v2) == _tree_keys(vae_p)


def test_sd21_export_matches_canonical_inventory_exactly():
    """SD2.1 (v2-1_768 layout): linear transformer projections, OpenCLIP
    ViT-H tower at ``cond_stage_model.model.`` — export side."""
    fam, unet_p, clip_p, vae_p = _family_trees("sd21")
    inv, _ = canonical_sd21()
    sd = ckpt.export_state_dict(unet_p, [clip_p], vae_p, fam)
    missing = sorted(set(inv) - set(sd))
    unexpected = sorted(set(sd) - set(inv))
    assert not missing, f"{len(missing)} missing, first: {missing[:8]}"
    assert not unexpected, \
        f"{len(unexpected)} unexpected, first: {unexpected[:8]}"
    bad = [(k, sd[k].shape, inv[k]) for k in inv
           if tuple(sd[k].shape) != inv[k]]
    assert not bad, f"{len(bad)} shape mismatches, first: {bad[:5]}"


def test_sd21_load_canonical_full_coverage():
    """SD2.1 load side: zero unconsumed keys (incl. the OpenCLIP tower's
    attn_mask/logit_scale buffers), trees fully populated."""
    fam, unet_p, clip_p, vae_p = _family_trees("sd21")
    _, sd = canonical_sd21()
    leftover = ckpt.unconsumed_keys(sd, fam)
    assert leftover == [], \
        f"{len(leftover)} unconsumed param keys, first: {leftover[:8]}"
    u2, (c2,), v2 = ckpt.convert_state_dict(sd, fam)
    assert _tree_keys(u2) == _tree_keys(unet_p)
    assert _tree_keys(c2) == _tree_keys(clip_p)
    assert _tree_keys(v2) == _tree_keys(vae_p)
