"""Multi-host HTTP mode end-to-end: real master + worker server processes.

Exercises the reference's full distributed-generation call stack (SURVEY.md
§3.2) with no browser: dispatcher rewrites, prepare-before-dispatch, worker
execution, PNG-over-HTTP gather, master-first ordering."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from comfyui_distributed_tpu.utils.net import find_free_port
from comfyui_distributed_tpu.workflow import parse_workflow
from comfyui_distributed_tpu.workflow import dispatcher as dsp

TXT2IMG = "/root/reference/workflows/distributed-txt2img.json"


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _wait_up(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            _get(f"http://127.0.0.1:{port}/prompt", timeout=2)
            return
        except Exception:
            time.sleep(0.5)
    raise TimeoutError(f"server on {port} never came up")


@pytest.fixture
def servers(tmp_path):
    env = {
        **os.environ,
        "PYTHONPATH": "/root/repo",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "DTPU_DEFAULT_FAMILY": "tiny",
        "DISTRIBUTED_TPU_CONFIG": str(tmp_path / "cfg.json"),
    }
    mport, wport = find_free_port(), find_free_port()
    logs = [open(tmp_path / "master.log", "w"),
            open(tmp_path / "worker.log", "w")]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "comfyui_distributed_tpu.cli", "serve",
             "--host", "127.0.0.1", "--port", str(mport)],
            env=env, cwd=str(tmp_path), stdout=logs[0], stderr=logs[0]),
        subprocess.Popen(
            [sys.executable, "-m", "comfyui_distributed_tpu.cli", "worker",
             "--host", "127.0.0.1", "--port", str(wport)],
            env=env, cwd=str(tmp_path), stdout=logs[1], stderr=logs[1]),
    ]
    try:
        _wait_up(mport)
        _wait_up(wport)
        yield mport, wport, tmp_path
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in logs:
            f.close()


@pytest.mark.integration
def test_parallel_generation_over_http(servers):
    mport, wport, tmp_path = servers
    master_url = f"http://127.0.0.1:{mport}"

    g = parse_workflow(TXT2IMG)
    g.nodes["9"].inputs.update(width=64, height=64, batch_size=1)
    g.nodes["8"].inputs.update(steps=1)

    # the reference dispatch protocol (gpupanel.js:836-941)
    job_map = dsp.make_job_id_map(g, prefix="exec_test")
    for mj in job_map.values():
        _post(f"{master_url}/distributed/prepare_job", {"multi_job_id": mj})

    worker_ids = ["worker_0"]
    worker_graph = dsp.prepare_for_participant(
        g, "worker", job_map, worker_ids, master_url=master_url,
        worker_index=0)
    master_graph = dsp.prepare_for_participant(
        g, "master", job_map, worker_ids)

    # embed hidden inputs into API inputs, as the reference's JS does
    def to_prompt(graph):
        api = graph.to_api_format()
        for entry in api.values():
            entry["inputs"].update(entry.pop("hidden", {}))
        return api

    wr = _post(f"http://127.0.0.1:{wport}/prompt",
               {"prompt": to_prompt(worker_graph), "client_id": "test"})
    mr = _post(f"{master_url}/prompt",
               {"prompt": to_prompt(master_graph), "client_id": "test"})

    deadline = time.time() + 240
    done = {}
    while time.time() < deadline:
        hist = _get(f"{master_url}/history")
        if mr["prompt_id"] in hist:
            done = hist[mr["prompt_id"]]
            break
        time.sleep(1.0)
    assert done, "master prompt never completed"
    assert done["status"] == "success", done
    # master's 1 image + worker's 1 image, gathered over HTTP
    assert done["images"] == 2

    metrics = _get(f"{master_url}/distributed/metrics")
    assert metrics["images_received"] >= 1

    whist = _get(f"http://127.0.0.1:{wport}/history")
    assert whist[wr["prompt_id"]]["status"] == "success"


@pytest.mark.integration
def test_interceptor_orchestrates_automatically(servers):
    """The headless interceptor (server-side equivalent of the reference's
    queuePrompt monkey-patch, gpupanel.js:819-834): a RAW workflow POSTed to
    the master with an enabled worker fans out with no client-side rewrite."""
    mport, wport, tmp_path = servers
    master_url = f"http://127.0.0.1:{mport}"

    # enable the worker in the master's config (the panel's checkbox)
    _post(f"{master_url}/distributed/config/update_worker",
          {"id": "w0", "name": "w0", "port": wport, "enabled": True})

    g = parse_workflow(TXT2IMG)
    g.nodes["9"].inputs.update(width=64, height=64, batch_size=1)
    g.nodes["8"].inputs.update(steps=1)

    mr = _post(f"{master_url}/prompt",
               {"prompt": g.to_api_format(), "client_id": "test"})
    assert mr.get("workers") == ["w0"], mr
    assert mr.get("failed_workers") == [], mr

    deadline = time.time() + 240
    done = {}
    while time.time() < deadline:
        hist = _get(f"{master_url}/history")
        if mr["prompt_id"] in hist:
            done = hist[mr["prompt_id"]]
            break
        time.sleep(1.0)
    assert done, "master prompt never completed"
    assert done["status"] == "success", done
    assert done["images"] == 2  # master's + worker's, gathered over HTTP
