"""Multi-host HTTP mode end-to-end: real master + worker server processes.

Exercises the reference's full distributed-generation call stack (SURVEY.md
§3.2) with no browser: dispatcher rewrites, prepare-before-dispatch, worker
execution, PNG-over-HTTP gather, master-first ordering."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from comfyui_distributed_tpu.utils.net import find_free_port
from comfyui_distributed_tpu.workflow import parse_workflow
from comfyui_distributed_tpu.workflow import dispatcher as dsp

TXT2IMG = "/root/reference/workflows/distributed-txt2img.json"
UPSCALE = "/root/reference/workflows/distributed-upscale.json"


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _wait_up(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            _get(f"http://127.0.0.1:{port}/prompt", timeout=2)
            return
        except Exception:
            time.sleep(0.5)
    raise TimeoutError(f"server on {port} never came up")


def _spawn_cluster(tmp_path, n_workers=1):
    env = {
        **os.environ,
        "PYTHONPATH": "/root/repo",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "DTPU_DEFAULT_FAMILY": "tiny",
        "DISTRIBUTED_TPU_CONFIG": str(tmp_path / "cfg.json"),
    }
    mport = find_free_port()
    wports = [find_free_port() for _ in range(n_workers)]
    logs = [open(tmp_path / "master.log", "w")]
    procs = [subprocess.Popen(
        [sys.executable, "-m", "comfyui_distributed_tpu.cli", "serve",
         "--host", "127.0.0.1", "--port", str(mport)],
        env=env, cwd=str(tmp_path), stdout=logs[0], stderr=logs[0])]
    for i, wp in enumerate(wports):
        f = open(tmp_path / f"worker{i}.log", "w")
        logs.append(f)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "comfyui_distributed_tpu.cli", "worker",
             "--host", "127.0.0.1", "--port", str(wp)],
            env=env, cwd=str(tmp_path), stdout=f, stderr=f))
    return mport, wports, procs, logs


def _teardown_cluster(procs, logs):
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
    for f in logs:
        f.close()


@pytest.fixture
def servers(tmp_path):
    mport, wports, procs, logs = _spawn_cluster(tmp_path, n_workers=1)
    try:
        _wait_up(mport)
        _wait_up(wports[0])
        yield mport, wports[0], tmp_path
    finally:
        _teardown_cluster(procs, logs)


@pytest.fixture
def servers2(tmp_path):
    mport, wports, procs, logs = _spawn_cluster(tmp_path, n_workers=2)
    try:
        _wait_up(mport)
        for wp in wports:
            _wait_up(wp)
        yield mport, wports, tmp_path
    finally:
        _teardown_cluster(procs, logs)


@pytest.mark.integration
def test_parallel_generation_over_http(servers):
    mport, wport, tmp_path = servers
    master_url = f"http://127.0.0.1:{mport}"

    g = parse_workflow(TXT2IMG)
    g.nodes["9"].inputs.update(width=64, height=64, batch_size=1)
    g.nodes["8"].inputs.update(steps=1)

    # the reference dispatch protocol (gpupanel.js:836-941)
    job_map = dsp.make_job_id_map(g, prefix="exec_test")
    for mj in job_map.values():
        _post(f"{master_url}/distributed/prepare_job", {"multi_job_id": mj})

    worker_ids = ["worker_0"]
    worker_graph = dsp.prepare_for_participant(
        g, "worker", job_map, worker_ids, master_url=master_url,
        worker_index=0)
    master_graph = dsp.prepare_for_participant(
        g, "master", job_map, worker_ids)

    # embed hidden inputs into API inputs, as the reference's JS does
    def to_prompt(graph):
        api = graph.to_api_format()
        for entry in api.values():
            entry["inputs"].update(entry.pop("hidden", {}))
        return api

    wr = _post(f"http://127.0.0.1:{wport}/prompt",
               {"prompt": to_prompt(worker_graph), "client_id": "test"})
    mr = _post(f"{master_url}/prompt",
               {"prompt": to_prompt(master_graph), "client_id": "test"})

    deadline = time.time() + 240
    done = {}
    while time.time() < deadline:
        hist = _get(f"{master_url}/history")
        if mr["prompt_id"] in hist:
            done = hist[mr["prompt_id"]]
            break
        time.sleep(1.0)
    assert done, "master prompt never completed"
    assert done["status"] == "success", done
    # master's 1 image + worker's 1 image, gathered over HTTP
    assert done["images"] == 2

    metrics = _get(f"{master_url}/distributed/metrics")
    assert metrics["images_received"] >= 1

    whist = _get(f"http://127.0.0.1:{wport}/history")
    assert whist[wr["prompt_id"]]["status"] == "success"


@pytest.mark.integration
def test_interceptor_orchestrates_automatically(servers):
    """The headless interceptor (server-side equivalent of the reference's
    queuePrompt monkey-patch, gpupanel.js:819-834): a RAW workflow POSTed to
    the master with an enabled worker fans out with no client-side rewrite."""
    mport, wport, tmp_path = servers
    master_url = f"http://127.0.0.1:{mport}"

    # enable the worker in the master's config (the panel's checkbox)
    _post(f"{master_url}/distributed/config/update_worker",
          {"id": "w0", "name": "w0", "port": wport, "enabled": True})

    g = parse_workflow(TXT2IMG)
    g.nodes["9"].inputs.update(width=64, height=64, batch_size=1)
    g.nodes["8"].inputs.update(steps=1)

    mr = _post(f"{master_url}/prompt",
               {"prompt": g.to_api_format(), "client_id": "test"})
    assert mr.get("workers") == ["w0"], mr
    assert mr.get("failed_workers") == [], mr

    deadline = time.time() + 240
    done = {}
    while time.time() < deadline:
        hist = _get(f"{master_url}/history")
        if mr["prompt_id"] in hist:
            done = hist[mr["prompt_id"]]
            break
        time.sleep(1.0)
    assert done, "master prompt never completed"
    assert done["status"] == "success", done
    assert done["images"] == 2  # master's + worker's, gathered over HTTP


@pytest.mark.integration
def test_jax_distributed_two_process_collectives(tmp_path):
    """The DCN-analog comm backend (SURVEY §2.4 'TPU-native equivalent'):
    two REAL processes join one jax.distributed cluster through the
    framework's initialize_multihost/build_mesh entry points (the path
    cli.py takes on a pod), then run cross-process psum + all_gather over
    the mesh data axis.  CPU devices + gRPC/Gloo stand in for chips + DCN."""
    port = find_free_port()
    env_base = {**os.environ,
                "PYTHONPATH": "/root/repo",
                "DTPU_COORDINATOR": f"127.0.0.1:{port}",
                "DTPU_NUM_PROCESSES": "2"}
    procs = []
    for pid in range(2):
        env = {**env_base, "DTPU_PROCESS_ID": str(pid)}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "jd_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(tmp_path)))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} rc={p.returncode}:\n{out[-2000:]}"
        assert "JD_OK" in out, f"proc {i}:\n{out[-2000:]}"


def _scaled_upscale_graph():
    """The reference's distributed-upscale fixture scaled for CPU CI, with
    the terminal preview swapped for SaveImage so the master persists the
    blended result for pixel comparison."""
    g = parse_workflow(UPSCALE)
    g.nodes["12"].inputs["image"] = "__missing__.png"   # synthetic test card
    g.nodes["17"].inputs.update(width=64, height=64)
    g.nodes["13"].inputs.update(steps=1, tile_width=32, tile_height=32,
                                padding=8, mask_blur=2)
    for n in g.nodes.values():
        if n.class_type == "PreviewImage":
            n.class_type = "SaveImage"
    return g


@pytest.mark.integration
def test_tiled_upscale_over_http_matches_oracle(servers2, tmp_path,
                                                monkeypatch):
    """VERDICT r2 #7: the tile scatter/gather worker->master HTTP path
    (reference distributed_upscale.py:132-199, 606-665) over real sockets
    with 2 workers, blended output compared against the in-process
    single-participant oracle."""
    import numpy as np

    # the oracle runs in THIS process: pin the same family the server
    # processes use, and drop any pipeline cached under another family
    monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny")
    from comfyui_distributed_tpu.models import registry
    registry.clear_pipeline_cache()

    mport, wports, tmp = servers2
    master_url = f"http://127.0.0.1:{mport}"
    for i, wp in enumerate(wports):
        _post(f"{master_url}/distributed/config/update_worker",
              {"id": f"w{i}", "name": f"w{i}", "port": wp, "enabled": True})

    g = _scaled_upscale_graph()
    mr = _post(f"{master_url}/prompt",
               {"prompt": g.to_api_format(), "client_id": "test"})
    assert sorted(mr.get("workers", [])) == ["w0", "w1"], mr
    assert mr.get("failed_workers") == [], mr

    deadline = time.time() + 300
    done = {}
    while time.time() < deadline:
        hist = _get(f"{master_url}/history")
        if mr["prompt_id"] in hist:
            done = hist[mr["prompt_id"]]
            break
        time.sleep(1.0)
    assert done, "master prompt never completed"
    assert done["status"] == "success", done
    assert done["images"] == 1

    metrics = _get(f"{master_url}/distributed/metrics")
    assert metrics["tiles_received"] >= 1, \
        "workers never delivered tiles over HTTP"

    out_files = sorted((tmp / "output").glob("*.png"))
    assert out_files, "master saved no output image"
    from PIL import Image
    got = np.asarray(Image.open(out_files[-1]), np.float32) / 255.0

    # in-process single-participant oracle (the reference's
    # process_single_gpu analog) on the identical graph
    from comfyui_distributed_tpu.ops.base import OpContext
    from comfyui_distributed_tpu.parallel.mesh import MeshRuntime, build_mesh
    from comfyui_distributed_tpu.workflow.executor import WorkflowExecutor
    rt = MeshRuntime(mesh=build_mesh())
    rt.enabled = False   # num_participants -> 1
    ctx = OpContext(runtime=rt, input_dir=str(tmp / "input"),
                    output_dir=str(tmp / "oracle_out"))
    res = WorkflowExecutor(ctx).execute(_scaled_upscale_graph())
    oracle = np.asarray(res.images[0], np.float32)

    assert got.shape == oracle.shape
    # Bound, not bit-equality: the wire quantizes tiles to uint8 PNG before
    # blending, and worker processes (1 XLA device) can diverge from this
    # process (8 virtual devices) by float-fusion noise that the feathered
    # seams amplify.  Misplaced or wrongly-refined tiles fail this by a
    # mile (the two bugs this test caught produced 50-95% mismatch at
    # diff≈1.0); the healthy path leaves scattered seam pixels < 0.15
    # (observed up to ~1.5% of pixels over the 0.02 floor across runs).
    diff = np.abs(got - oracle).max(axis=-1)
    assert (diff > 0.02).mean() < 0.03, \
        f"{(diff > 0.02).mean():.1%} of pixels off (seam noise budget 3%)"
    assert diff.max() < 0.15, f"max pixel diff {diff.max():.3f}"
