"""Sharded training step: dp/tp/sp shardings compile and step on the 8-device
virtual mesh (SURVEY.md §4 — multi-device without a cluster)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from comfyui_distributed_tpu.models.schedules import make_discrete_schedule
from comfyui_distributed_tpu.models.unet import UNet, TINY_CONFIG
from comfyui_distributed_tpu.parallel import sharding as shd
from comfyui_distributed_tpu.parallel.mesh import build_mesh
from comfyui_distributed_tpu.parallel.train import (
    TrainConfig,
    diffusion_loss,
    make_train_step,
    shard_train_step,
)


@pytest.fixture(scope="module")
def setup():
    model = UNet(TINY_CONFIG)
    ds = make_discrete_schedule()
    rng = jax.random.PRNGKey(0)
    B, L = 4, 16
    x = jnp.zeros((B, 8, 8, 4), jnp.float32)
    ts = jnp.zeros((B,), jnp.float32)
    ctx = jnp.zeros((B, L, TINY_CONFIG.context_dim), jnp.float32)
    params = model.init(rng, x, ts, ctx)

    def apply_fn(p, x, t, c, y):
        return model.apply(p, x, t, c, y)

    batch = {"latents": np.random.default_rng(0).normal(
        size=(B, 8, 8, 4)).astype(np.float32),
        "context": np.random.default_rng(1).normal(
        size=(B, L, TINY_CONFIG.context_dim)).astype(np.float32)}
    return model, ds, params, apply_fn, batch


def test_param_spec_rules():
    # trailing dim divisible -> column parallel
    assert shd.param_spec("k", (64, 64), 2, min_elements=2) == P(None, "tensor")
    # only second-to-last divisible -> row parallel
    assert shd.param_spec("k", (64, 63), 2, min_elements=2) == P("tensor", None)
    # biases/scales replicate
    assert shd.param_spec("b", (64,), 2, min_elements=2) == P()
    # too small replicates
    assert shd.param_spec("k", (4, 4), 2, min_elements=2 ** 11) == P()
    # tensor axis of 1 replicates
    assert shd.param_spec("k", (64, 64), 1, min_elements=2) == P()


def test_loss_decreases_and_finite(setup):
    model, ds, params, apply_fn, batch = setup
    loss, metrics = diffusion_loss(apply_fn, params, batch,
                                   jax.random.PRNGKey(0), ds)
    assert np.isfinite(float(loss))
    assert float(loss) > 0


def test_sharded_train_step_runs(setup):
    model, ds, params, apply_fn, batch = setup
    mesh = build_mesh({"data": 2, "tensor": 2, "seq": 2})
    step, tx = make_train_step(apply_fn, ds, TrainConfig(learning_rate=1e-3))
    # the jitted step donates params/opt_state; keep the fixture's copy alive
    params = jax.tree_util.tree_map(jnp.array, params)
    opt_state = tx.init(params)
    jitted, p, o, b = shard_train_step(step, mesh, params, opt_state, batch,
                                       min_shard_elements=2)
    key = jax.random.PRNGKey(1)
    p1, o1, m1 = jitted(p, o, b, key)
    loss1 = float(jax.device_get(m1["loss"]))
    assert np.isfinite(loss1)
    # params actually sharded over the tensor axis somewhere
    specs = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda x: x.sharding.spec, p1,
                               is_leaf=lambda x: hasattr(x, "sharding")))
    assert any("tensor" in str(s) for s in specs)
    # a second step with the same key family keeps making progress (finite)
    b2 = {k: jnp.asarray(v) for k, v in b.items()}
    p2, o2, m2 = jitted(p1, o1, b2, jax.random.fold_in(key, 1))
    assert np.isfinite(float(jax.device_get(m2["loss"])))


def test_training_reduces_loss(setup):
    """A few steps on a fixed batch must reduce the loss (fixed key -> same
    noise draw, so this isolates optimizer correctness)."""
    model, ds, params, apply_fn, batch = setup
    step, tx = make_train_step(apply_fn, ds, TrainConfig(learning_rate=1e-3))
    opt_state = tx.init(params)
    key = jax.random.PRNGKey(7)
    jstep = jax.jit(step)
    losses = []
    p, o = params, opt_state
    for _ in range(5):
        p, o, m = jstep(p, o, batch, key)
        losses.append(float(jax.device_get(m["loss"])))
    assert losses[-1] < losses[0]
