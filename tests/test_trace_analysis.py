"""Critical-path analytics plane (ISSUE 20): blame decomposition on
hand-built span forests, the NTP-style clock-skew estimator, cross-trace
aggregation + the straggler scorecard, regression diffing with the
permutation significance test, the sim capture exporter round-tripped
through the REAL segment loader, and the server surfaces
(`/distributed/analysis`, extended metrics/reset, live anomaly plane).

CPU-only, tier-1-eligible: the analytics are pure stdlib; the one
ServerState e2e test follows test_capture_plane.py's socket idiom and
the sim round-trips run on the virtual clock (<1s each).
"""

import json
import random

import pytest

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.runtime import cluster
from comfyui_distributed_tpu.sim import fleet
from comfyui_distributed_tpu.sim import scenario as sc_mod
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as tr
from comfyui_distributed_tpu.utils import trace_analysis as ta
from comfyui_distributed_tpu.utils import trace_export as te
from tests.test_observability import (make_prompt, run_with_client,
                                      validate_prometheus,
                                      wait_remote_history)


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture(autouse=True)
def analysis_disarmed(monkeypatch):
    """Each test opts into the live plane with its own baseline; the
    process-global LIVE singleton never leaks state across tests."""
    monkeypatch.delenv(C.ANALYSIS_BASELINE_ENV, raising=False)
    ta.reset_live()
    yield
    ta.reset_live()


@pytest.fixture(autouse=True)
def tracing_on():
    was = tr.tracing_enabled()
    tr.set_tracing(True)
    yield
    tr.set_tracing(was)


@pytest.fixture(autouse=True)
def export_off(monkeypatch):
    monkeypatch.delenv(C.TRACE_EXPORT_DIR_ENV, raising=False)
    yield
    te.current()


def span(name, start, end, sid, parent=None, attrs=None):
    """One raw span dict in the committed-record shape."""
    d = {"trace_id": "ab" * 16, "span_id": sid, "parent_id": parent,
         "name": name, "start_s": float(start), "end_s": float(end),
         "duration_s": round(float(end) - float(start), 6),
         "status": "ok"}
    if attrs:
        d["attrs"] = dict(attrs)
    return d


def record(spans, pid="p1", root_id="r"):
    return {"prompt_id": pid, "trace_id": "ab" * 16, "status": "ok",
            "root_span_id": root_id, "duration_s": 1.0,
            "finished_at": 1.0, "spans": spans}


class TestCriticalPath:
    def test_blame_cover_sums_exactly_to_e2e(self):
        # binary-exact boundaries so the reconstruction is EXACT, not
        # approximately-equal
        rec = record([
            span("job", 0.0, 8.0, "r", attrs={"tenant": "paid"}),
            span("queue_wait", 0.0, 2.0, "q", parent="r"),
            span("dispatch", 2.0, 3.0, "d", parent="r"),
            span("execute", 3.0, 6.0, "x", parent="r",
                 attrs={"worker": "w1"}),
            span("d2h", 6.0, 6.5, "h", parent="x"),
            span("finalize", 6.5, 7.5, "f", parent="r"),
        ])
        bd = ta.critical_path(rec)
        assert bd["e2e_s"] == 8.0
        assert bd["categories"] == {"queue_wait": 2.0, "dispatch": 1.0,
                                    "compute": 3.0, "d2h": 0.5,
                                    "blend": 1.0}
        assert bd["unattributed_s"] == 0.5          # 7.5..8.0 uncovered
        assert sum(bd["categories"].values()) + bd["unattributed_s"] \
            == bd["e2e_s"]
        assert bd["unattributed_pct"] == pytest.approx(6.25)
        assert bd["negative_edges"] == 0
        # the compute segment carries its worker
        seg = [s for s in bd["path"] if s["category"] == "compute"][0]
        assert seg["worker"] == "w1"

    def test_deepest_covering_span_wins(self):
        # d2h nested INSIDE execute claims its sub-interval: compute
        # must not double-count the child's time
        rec = record([
            span("job", 0.0, 10.0, "r"),
            span("execute", 1.0, 9.0, "x", parent="r"),
            span("d2h", 4.0, 5.0, "h", parent="x"),
        ])
        bd = ta.critical_path(rec)
        assert bd["categories"] == {"compute": 7.0, "d2h": 1.0}
        assert bd["unattributed_s"] == 2.0

    def test_fanout_overlapping_workers_no_double_count(self):
        # two tiles on two workers overlap; the cover blames each
        # instant ONCE (ties at equal depth: latest start wins)
        rec = record([
            span("job", 0.0, 8.0, "r"),
            span("execute", 2.0, 5.0, "a", parent="r",
                 attrs={"worker": "w1"}),
            span("execute", 3.0, 7.0, "b", parent="r",
                 attrs={"worker": "w2"}),
        ])
        bd = ta.critical_path(rec)
        assert bd["categories"] == {"compute": 5.0}     # 2..7, not 7s
        workers = [s.get("worker") for s in bd["path"]
                   if s["category"] == "compute"]
        assert workers == ["w1", "w2"]

    def test_cb_park_resume_timeline(self):
        # a preempted row: compute, park, compute again — park time is
        # its own category, never blamed on compute
        rec = record([
            span("job", 0.0, 9.0, "r"),
            span("queue_wait", 0.0, 1.0, "q", parent="r"),
            span("execute", 1.0, 3.0, "x1", parent="r"),
            span("cb_park", 3.0, 6.0, "pk", parent="r"),
            span("execute", 6.0, 9.0, "x2", parent="r"),
        ])
        bd = ta.critical_path(rec)
        assert bd["categories"] == {"queue_wait": 1.0, "compute": 5.0,
                                    "park": 3.0}
        assert bd["unattributed_s"] == 0.0

    def test_missing_spans_surface_as_gap_not_inflation(self):
        rec = record([
            span("job", 0.0, 10.0, "r"),
            span("queue_wait", 0.0, 1.0, "q", parent="r"),
        ])
        bd = ta.critical_path(rec)
        assert bd["categories"] == {"queue_wait": 1.0}
        assert bd["unattributed_s"] == 9.0
        assert bd["unattributed_pct"] == 90.0
        gap_segs = [s for s in bd["path"]
                    if s["category"] == "unattributed"]
        assert len(gap_segs) == 1 and gap_segs[0]["dur_s"] == 9.0

    def test_unknown_span_name_degrades_to_other(self):
        rec = record([
            span("job", 0.0, 4.0, "r"),
            span("brand_new_stage", 1.0, 3.0, "n", parent="r"),
        ])
        bd = ta.critical_path(rec)
        assert bd["categories"] == {"other": 2.0}

    def test_negative_parent_child_edge_counted(self):
        # a worker span starting before its master-side parent is the
        # clock-skew signature the corrected ingest must eliminate
        rec = record([
            span("job", 0.0, 5.0, "r"),
            span("dispatch", 2.0, 3.0, "d", parent="r"),
            span("execute", 1.5, 2.8, "x", parent="d"),
        ])
        assert ta.critical_path(rec)["negative_edges"] == 1

    def test_empty_and_rootless_records(self):
        bd = ta.critical_path({"prompt_id": "e", "spans": []})
        assert bd["e2e_s"] == 0.0 and bd["path"] == []
        # no root_span_id: the longest parentless span is the root
        rec = record([
            span("queue_wait", 0.0, 1.0, "q"),
            span("job", 0.0, 6.0, "j"),
        ], root_id=None)
        bd = ta.critical_path(rec)
        assert bd["e2e_s"] == 6.0
        assert bd["categories"] == {"queue_wait": 1.0}


class TestSkewEstimator:
    def test_min_filter_converges_and_error_is_bounded(self):
        reg = cluster.ClusterRegistry(lease_s=30.0)
        reg.register("w1", {})
        rng = random.Random(7)
        true_offset = -3.2          # worker clock 3.2s AHEAD of master
        errors = []
        delays = []
        for _ in range(C.SKEW_SAMPLES_KEPT):
            d = rng.uniform(0.005, 0.25)    # non-negative uplink delay
            delays.append(d)
            reg.update_skew("w1", true_offset + d)
            errors.append(abs(reg.skew("w1") - true_offset))
        # the estimate only improves as samples arrive, never overshoots
        # below the true offset, and lands exactly on the least-delayed
        # sample seen
        assert errors == sorted(errors, reverse=True)
        assert reg.skew("w1") == pytest.approx(true_offset + min(delays))
        assert errors[-1] <= 0.25

    def test_window_slides_past_stale_minimum(self):
        reg = cluster.ClusterRegistry(lease_s=30.0)
        reg.register("w1", {})
        reg.update_skew("w1", 1.001)        # one near-perfect sample
        for _ in range(C.SKEW_SAMPLES_KEPT):
            reg.update_skew("w1", 1.5)      # then only congested ones
        # the deque forgot the old minimum: the estimate tracks the
        # CURRENT network, it does not pin to an ancient best
        assert reg.skew("w1") == pytest.approx(1.5)

    def test_unknown_worker_and_garbage_samples(self):
        reg = cluster.ClusterRegistry(lease_s=30.0)
        assert reg.skew("ghost") == 0.0
        reg.update_skew("ghost", 5.0)       # unknown id: dropped
        assert reg.skew_snapshot() == {}
        reg.register("w1", {})
        reg.update_skew("w1", "not-a-number")
        assert reg.skew("w1") == 0.0

    def test_snapshot_and_reset(self):
        reg = cluster.ClusterRegistry(lease_s=30.0)
        reg.register("w1", {})
        reg.register("w2", {})
        reg.update_skew("w1", 0.25)
        reg.update_skew("w1", 0.125)
        snap = reg.skew_snapshot()
        assert set(snap) == {"w1"}          # w2 has no estimate
        assert snap["w1"]["offset_s"] == 0.125
        assert snap["w1"]["samples"] == 2
        assert snap["w1"]["age_s"] is not None
        assert reg.reset_skew() == 1
        assert reg.skew("w1") == 0.0 and reg.skew_snapshot() == {}


def _tenant_rec(pid, tenant, compute_s, worker, bucket=None):
    spans = [
        span("job", 0.0, compute_s + 1.0, "r",
             attrs={"tenant": tenant}),
        span("queue_wait", 0.0, 1.0, "q", parent="r"),
        span("execute", 1.0, 1.0 + compute_s, "x", parent="r",
             attrs={"worker": worker}),
    ]
    if bucket:
        spans[2]["attrs"]["bucket"] = bucket
    return record(spans, pid=pid)


class TestAggregation:
    def test_group_bys_tenant_worker_signature(self):
        recs = [
            _tenant_rec("a1", "paid", 2.0, "w1", bucket="cafe0001"),
            _tenant_rec("a2", "paid", 4.0, "w1", bucket="cafe0001"),
            _tenant_rec("a3", "free", 1.0, "w2"),
        ]
        bds = ta.collect_breakdowns(recs)
        by_tenant = ta.aggregate(bds, group_by="tenant")
        assert set(by_tenant) == {"paid", "free"}
        paid = by_tenant["paid"]
        assert paid["n"] == 2
        assert paid["e2e_mean_s"] == pytest.approx(4.0)   # (3+5)/2
        assert paid["categories"]["compute"]["mean_s"] \
            == pytest.approx(3.0)
        assert paid["categories"]["compute"]["share_pct"] \
            == pytest.approx(75.0)
        assert paid["unattributed_pct"] == 0.0
        by_worker = ta.aggregate(bds, group_by="worker")
        assert set(by_worker) == {"w1", "w2"}
        by_sig = ta.aggregate(bds, group_by="signature")
        assert set(by_sig) == {"cafe0001", "unknown"}
        assert by_sig["cafe0001"]["n"] == 2

    def test_collect_breakdowns_limit_and_zero_e2e_skip(self):
        recs = [_tenant_rec(f"p{i}", "paid", 1.0, "w1")
                for i in range(10)]
        recs.insert(0, record([span("job", 1.0, 1.0, "r")], pid="z"))
        bds = ta.collect_breakdowns(recs, limit=3)
        assert [bd["prompt_id"] for bd in bds] == ["p0", "p1", "p2"]

    def test_straggler_scorecard_flags_slow_worker(self):
        recs = []
        for i in range(8):
            recs.append(_tenant_rec(f"f{i}", "paid", 0.5,
                                    f"w{i % 2}"))        # healthy pair
        for i in range(8):
            recs.append(_tenant_rec(f"s{i}", "paid", 2.5, "w_slow"))
        sc = ta.straggler_scorecard(ta.collect_breakdowns(recs))
        assert sc["fleet_median_p95_s"] == pytest.approx(0.5)
        cards = sc["workers"]
        assert cards["w_slow"]["straggler"] is True
        assert cards["w_slow"]["vs_fleet_median_x"] \
            == pytest.approx(5.0)
        assert not cards["w0"]["straggler"]
        assert not cards["w1"]["straggler"]


def _fake_bd(v):
    return {"e2e_s": v, "categories": {"compute": v},
            "unattributed_s": 0.0, "unattributed_pct": 0.0,
            "negative_edges": 0}


class TestRegressionDiff:
    A = [_fake_bd(0.2 + 0.002 * (i % 5)) for i in range(40)]

    def test_seeded_regression_flagged_null_clean(self):
        reg = [_fake_bd(0.26 + 0.002 * (i % 5)) for i in range(40)]
        null = [_fake_bd(0.2 + 0.002 * ((i + 3) % 5))
                for i in range(40)]
        d = ta.diff_breakdowns(self.A, reg, seed=0)
        assert "compute" in d["flagged"] and d["regressed"]
        row = d["categories"]["compute"]
        assert row["delta_pct"] == pytest.approx(29.7, abs=0.5)
        assert row["p_value"] < 0.05 and row["flagged"]
        dn = ta.diff_breakdowns(self.A, null, seed=0)
        assert not dn["regressed"] and dn["flagged"] == []

    def test_significant_but_small_delta_not_flagged(self):
        # +5% with tiny spread: p ~ 0 yet below the 10% materiality bar
        b = [_fake_bd(0.21 + 0.002 * (i % 5)) for i in range(40)]
        d = ta.diff_breakdowns(self.A, b, seed=0)
        row = d["categories"]["compute"]
        assert row["significant"] and not row["flagged"]
        assert not d["regressed"]

    def test_diff_is_deterministic_under_seed(self):
        b = [_fake_bd(0.23 + 0.002 * (i % 5)) for i in range(40)]
        d1 = ta.diff_breakdowns(self.A, b, seed=42)
        d2 = ta.diff_breakdowns(self.A, b, seed=42)
        assert d1 == d2


class TestBaselineAndLivePlane:
    def test_profile_save_load_roundtrip(self, tmp_path):
        bds = [_fake_bd(0.25), _fake_bd(0.75)]
        prof = ta.profile_from_breakdowns(bds)
        assert prof["n"] == 2 and prof["e2e_mean_s"] == 0.5
        assert prof["categories"] == {"compute": 0.5}
        path = str(tmp_path / "base.json")
        ta.save_baseline(prof, path)
        loaded = ta.load_baseline(path)
        assert loaded["kind"] == "dtpu_analysis_baseline"
        assert loaded["categories"] == {"compute": 0.5}

    def test_unreadable_baselines_rejected(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("not json")
        assert ta.load_baseline(str(bad)) is None
        empty = tmp_path / "empty.json"
        empty.write_text(json.dumps({"n": 3, "categories": {}}))
        assert ta.load_baseline(str(empty)) is None
        assert ta.load_baseline(str(tmp_path / "missing.json")) is None

    def test_detect_anomalies_thresholds(self):
        baseline = {"e2e_mean_s": 0.2,
                    "categories": {"compute": 0.1}}
        # +100% compute: anomalous at the default 50% bar
        out = ta.detect_anomalies(_fake_bd(0.2), baseline)
        assert [a["category"] for a in out] == ["compute"]
        assert out[0]["change_pct"] == pytest.approx(100.0)
        # +20%: clean
        assert ta.detect_anomalies(_fake_bd(0.12), baseline) == []
        # a category the baseline never saw flags against e2e headroom
        bd = {"e2e_s": 0.3, "categories": {"compute": 0.1,
                                           "upload": 0.15}}
        out = ta.detect_anomalies(bd, baseline)
        assert [a["category"] for a in out] == ["upload"]
        assert out[0]["change_pct"] is None

    def _commit_job(self, pid, compute_s=0.5):
        # explicit past intervals via event_span: the blame cover must
        # see the exact compute duration, not a wall-clock-clipped one
        import hashlib
        import time
        tid = hashlib.md5(pid.encode()).hexdigest()
        t0 = time.time() - 60.0
        root = tr.event_span("job", t0, t0 + 0.1 + compute_s,
                             trace_id=tid,
                             attrs={"prompt_id": pid, "tenant": "paid"})
        tr.event_span("queue_wait", t0, t0 + 0.1, trace_id=tid,
                      parent_id=root["span_id"])
        tr.event_span("execute", t0 + 0.1, t0 + 0.1 + compute_s,
                      trace_id=tid, parent_id=root["span_id"],
                      attrs={"worker": "w1"})
        tr.GLOBAL_TRACES.commit(pid, tid, status="ok",
                                root_span_id=root["span_id"],
                                duration_s=compute_s + 0.1)

    def test_commit_tap_scores_against_armed_baseline(
            self, tmp_path, monkeypatch):
        path = str(tmp_path / "base.json")
        ta.save_baseline({"n": 4, "e2e_mean_s": 0.61,
                          "categories": {"compute": 0.5,
                                         "queue_wait": 0.1}}, path)
        monkeypatch.setenv(C.ANALYSIS_BASELINE_ENV, path)
        assert ta.LIVE.armed()
        self._commit_job("ok1", compute_s=0.5)      # on-profile: clean
        self._commit_job("bad1", compute_s=1.5)     # 3x compute
        snap = ta.LIVE.snapshot()
        assert snap["armed"] and snap["baseline"] == path
        assert snap["traces_analyzed"] == 2
        assert snap["anomalies_total"] == 1
        assert snap["anomalies_by_category"] == {"compute": 1}
        assert snap["last_anomalies"][0]["category"] == "compute"
        assert snap["live_profile"]["categories"]["compute"] \
            == pytest.approx(1.0)
        ta.reset_live()
        assert ta.LIVE.snapshot()["traces_analyzed"] == 0

    def test_disarmed_commit_tap_is_noop(self):
        assert not ta.LIVE.armed()
        self._commit_job("quiet1")
        snap = ta.LIVE.snapshot()
        assert snap["traces_analyzed"] == 0
        assert snap["anomalies_total"] == 0


def _cap_spec(name, seed, mean_s, cap_dir):
    """A tiny fixed-service scenario: ~45 completions in <1s of wall
    time, jitter far inside the differ's 10% materiality bar."""
    return {
        "name": name, "seed": seed, "duration_s": 15.0,
        "traffic": [{"cls": "paid", "rate": 3.0, "clients": 2}],
        "service": {"model": "fixed", "mean_s": mean_s,
                    "jitter_pct": 5.0},
        "workers": 4, "drain_limit_s": 60.0,
        "capture_dir": cap_dir,
    }


class TestSimCaptureRoundTrip:
    def test_exporter_roundtrip_through_real_loader(self, tmp_path):
        cap = str(tmp_path / "cap")
        s = fleet.run_scenario(sc_mod.from_dict(
            _cap_spec("rt", 11, 0.2, cap)))
        assert s["capture"]["exported"] == s["completed_total"] > 20
        assert s["capture"]["dropped"] == 0
        stats: dict = {}
        recs = list(te.iter_records(cap, stats=stats))
        assert stats["records"] == s["capture"]["exported"]
        assert stats["torn_lines"] == 0 and stats["io_errors"] == 0
        assert all(r["schema"] == te.SCHEMA_VERSION for r in recs)
        report = ta.analyze_records(recs)
        assert report["n_traces"] == len(recs)
        assert report["unattributed_pct_mean"] == 0.0
        assert report["negative_edges"] == 0
        assert set(report["profiles"]["tenant"]) == {"paid"}
        prof = report["profiles"]["tenant"]["paid"]
        assert prof["categories"]["compute"]["mean_s"] \
            == pytest.approx(0.2, rel=0.1)

    def test_capture_ids_are_deterministic(self, tmp_path):
        d1, d2 = str(tmp_path / "c1"), str(tmp_path / "c2")
        fleet.run_scenario(sc_mod.from_dict(_cap_spec("det", 5, 0.1,
                                                      d1)))
        fleet.run_scenario(sc_mod.from_dict(_cap_spec("det", 5, 0.1,
                                                      d2)))
        ids1 = sorted((r["prompt_id"], r["trace_id"])
                      for r in te.iter_records(d1))
        ids2 = sorted((r["prompt_id"], r["trace_id"])
                      for r in te.iter_records(d2))
        assert ids1 == ids2 and ids1

    def test_cli_why_and_analyze_offline(self, tmp_path, capsys):
        cap = str(tmp_path / "cap")
        fleet.run_scenario(sc_mod.from_dict(
            _cap_spec("cli", 11, 0.2, cap)))
        pid = next(te.iter_records(cap))["prompt_id"]
        from comfyui_distributed_tpu import cli
        assert cli.main(["why", pid, "--export-dir", cap]) == 0
        out = capsys.readouterr().out
        assert "critical path:" in out and "compute" in out
        assert "(unattributed)" in out
        assert cli.main(["analyze", "--export-dir", cap]) == 0
        out = capsys.readouterr().out
        assert "by tenant:" in out and "paid" in out
        assert "straggler scorecard" in out
        assert cli.main(["why", "ghost", "--export-dir", cap]) == 1

    def test_cli_diff_exit_codes_seeded_vs_null(self, tmp_path,
                                                capsys):
        a, b, c = (str(tmp_path / x) for x in "abc")
        fleet.run_scenario(sc_mod.from_dict(_cap_spec("a", 11, 0.2, a)))
        fleet.run_scenario(sc_mod.from_dict(_cap_spec("b", 12, 0.26,
                                                      b)))
        fleet.run_scenario(sc_mod.from_dict(_cap_spec("c", 13, 0.2, c)))
        from comfyui_distributed_tpu import cli
        assert cli.main(["analyze", "--diff", a, b]) == 3
        out = capsys.readouterr().out
        assert "REGRESSED in" in out and "compute" in out
        assert cli.main(["analyze", "--diff", a, c]) == 0
        assert "verdict: clean" in capsys.readouterr().out

    def test_cli_baseline_out_from_capture(self, tmp_path, capsys):
        cap = str(tmp_path / "cap")
        fleet.run_scenario(sc_mod.from_dict(
            _cap_spec("base", 11, 0.2, cap)))
        out_path = str(tmp_path / "baseline.json")
        from comfyui_distributed_tpu import cli
        assert cli.main(["analyze", "--export-dir", cap,
                         "--baseline-out", out_path]) == 0
        capsys.readouterr()
        prof = ta.load_baseline(out_path)
        assert prof is not None and prof["n"] > 20
        assert prof["categories"]["compute"] > 0


class TestServerSurfaces:
    def test_analysis_route_metrics_and_reset(self, tmp_path,
                                              monkeypatch):
        # a deliberately-stale baseline: any real prompt's compute
        # blows past it, so the live plane must flag anomalies
        path = str(tmp_path / "base.json")
        ta.save_baseline({"n": 1, "e2e_mean_s": 1e-6,
                          "categories": {"compute": 1e-9}}, path)
        monkeypatch.setenv(C.ANALYSIS_BASELINE_ENV, path)

        async def body(client, state):
            r = await client.post("/prompt", json={
                "prompt": make_prompt(21), "client_id": "an"})
            pid = (await r.json())["prompt_id"]
            await wait_remote_history(client, pid)

            # the analysis route: profiles + scorecard + armed plane
            rep = await (await client.get(
                "/distributed/analysis")).json()
            assert rep["n_traces"] >= 1
            assert set(rep["profiles"]) \
                == {"tenant", "signature", "worker"}
            assert rep["unattributed_pct_mean"] < 100.0
            assert rep["negative_edges"] == 0
            assert rep["live"]["armed"] is True
            assert rep["live"]["traces_analyzed"] >= 1
            assert rep["live"]["anomalies_total"] >= 1
            assert isinstance(rep["skew"], dict)
            assert "hedging_latency_ema_s" in rep

            # JSON metrics block mirrors the live snapshot
            m = await (await client.get("/distributed/metrics")).json()
            assert m["analysis"]["armed"] is True
            assert m["analysis"]["anomalies_total"] >= 1
            assert "skew" in m["analysis"]

            # prom: the counter family is always present and valid
            text = await (await client.get(
                "/distributed/metrics.prom")).text()
            types = validate_prometheus(text)
            assert types.get("dtpu_analysis_anomalies_total") \
                == "counter"
            val = [l for l in text.splitlines()
                   if l.startswith("dtpu_analysis_anomalies_total ")]
            assert val and float(val[0].split()[-1]) >= 1

            # total reset clears the analytics plane too
            r = await client.post("/distributed/metrics/reset", json={})
            cleared = (await r.json())["cleared"]
            assert cleared["analysis"] is True
            assert isinstance(cleared["skew_estimates"], int)
            m = await (await client.get("/distributed/metrics")).json()
            assert m["analysis"]["traces_analyzed"] == 0
            assert m["analysis"]["anomalies_total"] == 0

        run_with_client(body, tmp_path)
