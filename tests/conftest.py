"""Test harness: an 8-device virtual CPU mesh (SURVEY.md §4).

The reference's multi-process browser+HTTP topology is untestable in CI; the
TPU framework's collectives are testable single-process by forcing XLA to
expose N host devices.  Env vars must be set before jax initializes a backend,
hence this module-level block in conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's sitecustomize may have imported jax already (TPU plugin
# registration), freezing JAX_PLATFORMS before this file runs — override via
# the live config so tests always see the 8-device virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_config(tmp_path, monkeypatch):
    """Point the config layer at a per-test temp file."""
    monkeypatch.setenv("DISTRIBUTED_TPU_CONFIG",
                       str(tmp_path / "cluster_config.json"))
    yield


@pytest.fixture(autouse=True)
def _no_leaked_interrupt():
    """A leaked process-global interrupt flag silently NO-OPS every
    compiled sampler (the scan skips all steps and returns the noised
    input) — and most assertions still pass on no-op outputs, so the
    leak is near-invisible.  Guard every test on both sides."""
    from comfyui_distributed_tpu.runtime import interrupt as itr
    itr.clear_interrupt()
    yield
    itr.clear_interrupt()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
