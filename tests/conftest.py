"""Test harness: an 8-device virtual CPU mesh (SURVEY.md §4).

The reference's multi-process browser+HTTP topology is untestable in CI; the
TPU framework's collectives are testable single-process by forcing XLA to
expose N host devices.  Env vars must be set before jax initializes a backend,
hence this module-level block in conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's sitecustomize may have imported jax already (TPU plugin
# registration), freezing JAX_PLATFORMS before this file runs — override via
# the live config so tests always see the 8-device virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

# Compilation is a one-time cost (the tensor-plane contract): share the
# persistent XLA compilation cache across the whole suite AND across runs
# (repo-local .jax_cache/, gitignored).  The many tiny-model programs the
# tests compile are identical across modules and rounds — virtual weights
# differ only in VALUES, not HLO — so each compiles once per container
# instead of once per test module.  min_compile_secs=0: the suite's
# compiles are individually small but collectively dominate its
# wall-clock.  DTPU_COMPILE_CACHE_DIR=off opts out.
from comfyui_distributed_tpu.runtime.manager import \
    enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache(
    min_compile_secs=0.0,
    default_dir=os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache"))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Cheapest-first module order (the same principle bench.py's suite mode
# uses): the tier-1 gate runs this suite under a hard wall-clock timeout,
# and after the shard_map shim fix ~175 previously-uncollectable tests
# actually execute, pushing the full suite past that window.  Ordering
# modules by measured cost makes a timeout truncate the expensive
# sampling-heavy tail instead of the broad cheap majority — every
# completed test is a completed test either way.  Costs: measured module
# wall-clock seconds (2026-08-03 full run, warm compile cache); unlisted
# modules default cheap.  Stable sort keeps intra-module order (and
# module/class fixture scoping) intact.
_MODULE_COST_S = {
    "test_models.py": 778,
    "test_parallel.py": 300,
    "test_workflow.py": 160,
    "test_controlnet.py": 190,
    "test_train.py": 100,
    "test_samplers.py": 60,
    "test_server.py": 45,
    "test_tensor_plane.py": 28,
    "test_pipeline.py": 21,
    "test_observability.py": 19,
    # capture plane (PR 18): exporter rotation/retention units are
    # instant; the two ServerState e2e surfaces dominate (~15s total)
    "test_capture_plane.py": 15,
    "test_attention.py": 35,
    "test_multihost.py": 30,
    "test_checkpoints_canonical.py": 18,
    "test_torch_parity.py": 18,
    "test_bench.py": 16,
    "test_packaging.py": 13,
    # non-slow share only (the two loopback fault-acceptance tests are
    # marked slow in-file, ~40s each with real master+worker exec loops)
    "test_cluster.py": 12,
    # non-slow share only (the 3-tenant overload acceptance is marked
    # slow in-file, ~40s with a real loopback fleet + chaos)
    "test_overload.py": 2,
    # non-slow share only (the two loopback election/recovery
    # acceptance tests are marked slow in-file, ~20s each with real
    # master+standby+worker exec loops over a shared WAL)
    "test_durable.py": 12,
    "test_resource.py": 12,
    # pure-AST static analysis (dtpu-lint): parses the package ~15x
    # (fixtures + live-tree gate + the v1 AND v2/interprocedural
    # seeded mutations, each a full run_lint with call-graph build),
    # no device work
    "test_analysis.py": 36,
    # continuous batching (PR 12) + latent paging (PR 17): bucket-level
    # exactness, park/resume edge cases, preemption harness, and a few
    # real CB ServerStates on the tiny model (~60s warm-cache non-slow
    # share; the two-sampler exactness proofs are slow-marked in-file)
    "test_batching.py": 60,
    "test_tiling.py": 10,
    # cross-request compute reuse (PR 13): non-slow share only (the
    # tile-tier bit-exactness proofs and the SSE client-gone acceptance
    # are slow-marked in-file, ~25s together with real refine runs)
    "test_reuse.py": 15,
    # multi-master shard plane (PR 14): ring math + exec-less loopback
    # forwarding/takeover/router tests run in ~1s; the 3-master
    # kill-mid-upscale acceptance (~32s, real fan-out + absorb) is
    # slow-marked in-file
    "test_shard.py": 2,
    # traffic twin (PR 19): pure-Python discrete-event sim on a virtual
    # clock — no device work, whole module <2s
    "test_sim.py": 1,
    # critical-path analytics (PR 20): pure-stdlib blame/diff units and
    # virtual-clock sim round-trips are instant; the one ServerState
    # e2e surface (~10s) dominates
    "test_trace_analysis.py": 11,
}


# Tests marked `slow` at collection time (tier-1 runs `-m 'not slow'`).
# Criteria: measured call time >= ~12s in the 2026-08-03 full run AND the
# test was NOT passing in the seed baseline (it was uncollectable or
# failing through the empty-op-registry cascade) — so the timed gate
# keeps every test the seed gate effectively had, plus the cheap
# majority of the restored ones, and finishes inside its window.  The
# full `pytest tests/` run (README) still executes everything.
_SLOW_TESTS = {
    "test_parallel.py::TestDryrunMultichip::test_dryrun_green[8]",
    "test_parallel.py::TestDryrunMultichip::test_dryrun_green[16]",
    # TP serve workloads (ISSUE 16 budget guard + cache hygiene): the
    # 2-D-mesh bucket programs can't use the persistent compile cache
    # (see parallel/mesh._tp_compile_cache_guard — the disable is sticky
    # for the whole process), so they pay full compiles every run AND
    # strand every later test in the same process cacheless.  Tier-1
    # therefore runs NO in-process tensor>1 serve programs at all; the
    # slow tier and the bench tp_serve subprocess keep the coverage.
    "test_batching.py::TestBucketTensorParallel::"
    "test_late_join_bit_identical_to_solo_under_tp",
    "test_batching.py::TestBucketTensorParallel::"
    "test_zero_steady_state_retraces_under_tp",
    "test_batching.py::TestBucketTensorParallel::"
    "test_bucket_buffers_carry_canonical_rows_layout",
    "test_batching.py::TestLatentPagingTensorParallel::"
    "test_park_resume_bit_identical_under_tp",
    "test_parallel.py::TestServingTensorParallel::"
    "test_tp_sharded_sample_matches_replicated_oracle",
    "test_parallel.py::TestServingTensorParallel::"
    "test_upstream_sharded_concat_miscompile",
    "test_train.py::test_sharded_train_step_runs",
    "test_train.py::test_training_reduces_loss",
    "test_samplers.py::TestRound5SamplerLongTail::"
    "test_ksampler_runs_the_long_tail_end_to_end",
    "test_models.py::TestComponentLoadersRound5::"
    "test_dual_clip_loader_sdxl_towers",
    "test_models.py::TestComponentLoadersRound5::"
    "test_unet_loader_samples_end_to_end",
    "test_models.py::TestSelfAttentionGuidance::"
    "test_sag_changes_output_and_zero_scale_matches_plain",
    "test_models.py::TestSelfAttentionGuidance::"
    "test_sag_falls_back_without_uncond_benefit",
    "test_models.py::TestDeepShrink::test_node_patch_and_window",
    "test_models.py::TestCustomSampling::"
    "test_split_sigmas_two_stage_roundtrip",
    "test_models.py::TestCustomSampling::"
    "test_sampler_custom_matches_ksampler",
    "test_models.py::TestRegionalPromptingFixups::"
    "test_sibling_control_scoped_to_its_region",
    "test_models.py::TestRegionalPromptingFixups::"
    "test_sibling_control_reaches_sampling",
    "test_models.py::TestRegionalPromptingFixups::"
    "test_combined_negative_reaches_sampling",
    "test_models.py::TestTimestepRange::"
    "test_scheduled_prompts_change_sampling",
    "test_models.py::TestGligen::test_textbox_apply_and_sampling",
    "test_models.py::TestGligen::"
    "test_textbox_apply_reaches_combined_siblings",
    "test_models.py::TestModelPatchesRound4::test_model_sampling_discrete",
    "test_models.py::TestModelPatchesRound4::"
    "test_perp_neg_reduces_to_cfg_when_empty_is_negative",
    "test_models.py::TestModelPatchesRound4::"
    "test_perp_neg_guider_matches_patch",
    "test_models.py::TestModelPatchesRound4::test_hypertile_node_runs",
    "test_models.py::TestRescaleCFG::"
    "test_node_patches_and_rides_derivations",
    "test_models.py::TestHypernetwork::test_loader_node_steers_sampling",
    "test_models.py::TestCustomSamplingAdvanced::"
    "test_dual_cfg_with_controlnet",
    "test_models.py::TestCustomSamplingAdvanced::"
    "test_dual_cfg_collapses_to_cfg_when_cond2_is_negative",
    "test_models.py::TestCustomSamplingAdvanced::"
    "test_dual_cfg_distinct_middle_finite_and_differs",
    "test_models.py::TestCustomSamplingAdvanced::"
    "test_dual_cfg_honors_rescale_patch",
    "test_models.py::TestCustomSamplingAdvanced::"
    "test_cfg_guider_matches_sampler_custom",
    "test_models.py::TestCustomSamplingAdvanced::"
    "test_basic_guider_is_cfg_one",
    "test_models.py::TestFreeU::test_freeu_sampling_e2e",
    "test_models.py::TestFreeU::"
    "test_freeu_changes_output_and_params_shared",
    "test_models.py::TestRegionalPrompting::"
    "test_mask_node_and_multistep_finite",
    "test_models.py::TestRegionalPrompting::"
    "test_one_step_halves_match_single_cond_runs",
    "test_models.py::TestAdvancedOps::"
    "test_ksampler_advanced_window_composition",
    "test_models.py::TestSD21Family::test_v_prediction_pipeline_samples",
    "test_models.py::TestSDXLRefinerFamily::"
    "test_refiner_shaped_unet_forward_and_key_walk",
    "test_models.py::TestSDXLRefinerFamily::"
    "test_refiner_size_cond_steers_sampling",
    "test_models.py::TestTokenMerging::test_node_patches_and_steers",
    "test_controlnet.py::TestSamplingAndOps::"
    "test_positive_only_control_does_not_steer_uncond",
    "test_controlnet.py::TestSamplingAndOps::"
    "test_control_changes_sample_output",
    "test_controlnet.py::TestPerEntryControlWindows::"
    "test_each_entry_keeps_its_own_window",
    "test_controlnet.py::TestControlNetAdvancedRound5::"
    "test_full_window_matches_plain_apply_on_both_sides",
    "test_controlnet.py::TestControlNetAdvancedRound5::"
    "test_empty_window_is_exact_noop",
    "test_controlnet.py::TestSameNetChainedTwice::"
    "test_two_links_of_one_net_sum",
    "test_controlnet.py::TestControlNetChaining::"
    "test_zero_net_chain_is_additive_identity",
    "test_controlnet.py::TestControlNetChaining::"
    "test_per_entry_nets_both_steer",
    "test_attention.py::TestRingIntegration::"
    "test_sd_scale_unet_forward_default_threshold",
    "test_attention.py::TestRingIntegration::"
    "test_unet_forward_ring_matches_oracle",
    "test_workflow.py::TestSdxlRefinerFixture::"
    "test_two_stage_handoff_fans_out",
    "test_workflow.py::TestImg2ImgE2E::"
    "test_hires_fix_chain_not_reexpanded",
    "test_workflow.py::TestImg2ImgE2E::"
    "test_denoise_below_one_preserves_source_structure",
    "test_workflow.py::TestHiresFixE2E::test_hires_fix_fans_out",
    "test_workflow.py::TestRepoFixtures::test_upscale_fixture",
    "test_workflow.py::TestRound4Fixtures::test_inpaint_model_fixture",
    "test_workflow.py::TestIp2pFixture::test_ip2p_fixture_fans_out",
    "test_bench.py::test_real_ckpt_smoke_hook",
    # PR 2: the coalesced-vs-serial bit-equivalence proof pays the
    # module's first-in-process trace cost (~18s cold); the acceptance
    # invariants (1.3x overlap win, single coalesced dispatch) live in
    # the cheap non-slow tests of the same module
    "test_pipeline.py::TestCoalescedExecution::"
    "test_coalesced_matches_serial_per_prompt",
    "test_server.py::TestPromptExtraPnginfo::"
    "test_extra_data_reaches_saved_pngs",
    "test_server.py::TestProfiling::test_profile_endpoints",
    # PR 9 headroom trim (tier-1 gate budget, ROADMAP item 7): the
    # three priciest remaining non-slow tests (25s/25s/18s measured
    # 2026-08-04) move out of the timed gate — each is a deep-oracle
    # variant whose cheaper siblings still run; the full `pytest
    # tests/` (README) keeps them all
    "test_torch_parity.py::"
    "test_clip_text_encoder_matches_transformers[tiny]",
    "test_checkpoints.py::test_roundtrip_exact[tiny]",
    "test_controlnet.py::TestControlNetChaining::"
    "test_two_live_nets_accumulate",
    # PR 12: the continuous-batching late-join bit-exactness proof
    # (~14s warm, ~27s cold — two samplers x serial references), the
    # same precedent as PR 2's coalesced==serial proof; the cheap
    # behavioral tests of the same module (non-contiguous merge,
    # slot-exit provenance, fallback, zero-retrace churn) stay in the
    # gate, and `bench.py --phase batching` re-proves exactness on
    # every watchdog run
    "test_batching.py::TestBucketExactness::"
    "test_late_join_bit_identical_to_serial",
    # PR 17: the park/resume two-sampler serial-reference proof follows
    # the same precedent (~18s warm); the single-sampler executor-level
    # exactness test (TestSloPreemption::
    # test_preempted_row_resumes_and_matches_serial) and the park
    # edge-case tests stay in the gate, and `bench.py --phase preempt`
    # re-proves park/resume bit-exactness on every watchdog run
    "test_batching.py::TestLatentPagingExactness::"
    "test_park_resume_bit_identical_to_serial",
    # PR 17 gate-budget drift fix (satellite): the four priciest
    # non-slow tests from the 2026-08-07 baseline top-10 (13.4s, 13.0s,
    # 12.4s, 11.7s) move out of the timed window to make room for the
    # latent-paging coverage — each is a deep variant whose cheaper
    # siblings keep the behavior covered; `pytest tests/` runs them all
    "test_controlnet.py::TestControlNetAdvancedRound5::"
    "test_diff_loader_adds_base_weights",
    "test_workflow.py::TestImg2ImgE2E::test_variation_sweep_fans_out",
    "test_reuse.py::TestResultTier::"
    "test_clear_memory_invalidates_and_reports",
    "test_models.py::TestComponentLoadersRound5::"
    "test_clip_loader_op_virtual_and_type_validation",
    # PR 19 gate-budget replenish (satellite): the nine priciest
    # non-slow tests from the 2026-08-07 top-10 (15.5s..9.7s, ~108s
    # total) move out of the timed window to restore >=100s headroom
    # for the traffic-twin suite and future growth — each is a deep
    # variant whose cheaper siblings keep the behavior covered (the
    # tenth, torch-parity clip[sd15], stays: its [tiny] sibling is
    # already slow-marked and the gate should keep one clip parity
    # proof); the full `pytest tests/` (README) still runs them all
    "test_workflow.py::TestRepoFixtures::test_txt2img_fixture",
    "test_pipeline.py::TestCoalescedExecution::"
    "test_burst_coalesces_into_one_dispatch",
    "test_workflow.py::TestImg2ImgE2E::test_side_branch_not_fanned_out",
    "test_models.py::TestBf16WeightStorage::"
    "test_flag_casts_unet_clip_not_vae",
    "test_tensor_plane.py::TestWorkflowTensorPlane::"
    "test_spine_moves_zero_host_bytes",
    "test_observability.py::TestServerTraceLifecycle::"
    "test_single_prompt_trace_tree",
    "test_workflow.py::TestRegionalTiledUpscale::"
    "test_regional_spmd_matches_single_device_oracle",
    "test_reuse.py::TestKillSwitch::test_cache_off_means_zero_lookups",
    "test_workflow.py::TestPngWorkflowMetadata::"
    "test_save_image_embeds_and_round_trips",
    # PR 20 gate-budget trim (satellite): the two priciest non-slow
    # tests from the 2026-08-07 top-10 (16.7s, 12.4s) move out of the
    # timed window to offset the analytics suite — regional tiling
    # stays covered by TestRepoFixtures::test_regional_fixture_fans_out
    # and the round-4 fixtures by test_sdxl_dualprompt_fixture; the
    # full `pytest tests/` (README) still runs them all
    "test_workflow.py::TestRegionalTiledUpscale::"
    "test_regional_masks_engage",
    "test_workflow.py::TestRound4Fixtures::test_unclip_fixture",
}


def pytest_collection_modifyitems(session, config, items):
    for item in items:
        key = f"{os.path.basename(str(item.fspath))}::" \
            + item.nodeid.split("::", 1)[1]
        if key in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
    items.sort(key=lambda it: _MODULE_COST_S.get(
        os.path.basename(str(it.fspath)), 5))


# Gate-budget visibility (ROADMAP item 7): the tier-1 gate runs under a
# hard wall-clock window, and every PR grows the suite — print the
# top-10 slowest calls at the end of EVERY run so the next session sees
# where the budget went without re-running with --durations.
_test_durations: dict = {}


def pytest_runtest_logreport(report):
    if report.when == "call":
        _test_durations[report.nodeid] = report.duration


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if not _test_durations:
        return
    top = sorted(_test_durations.items(), key=lambda kv: -kv[1])[:10]
    total = sum(_test_durations.values())
    terminalreporter.write_sep(
        "=", f"top-10 slowest calls (of {total:.0f}s total call time; "
             "tier-1 window 870s)")
    for nodeid, dur in top:
        terminalreporter.write_line(f"{dur:7.2f}s  {nodeid}")


@pytest.fixture(autouse=True)
def _isolated_config(tmp_path, monkeypatch):
    """Point the config layer at a per-test temp file."""
    monkeypatch.setenv("DISTRIBUTED_TPU_CONFIG",
                       str(tmp_path / "cluster_config.json"))
    yield


@pytest.fixture(autouse=True)
def _no_leaked_interrupt():
    """A leaked process-global interrupt flag silently NO-OPS every
    compiled sampler (the scan skips all steps and returns the noised
    input) — and most assertions still pass on no-op outputs, so the
    leak is near-invisible.  Guard every test on both sides."""
    from comfyui_distributed_tpu.runtime import interrupt as itr
    itr.clear_interrupt()
    yield
    itr.clear_interrupt()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
