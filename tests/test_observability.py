"""End-to-end distributed job tracing (ISSUE 3): span model + traceparent
propagation, flight recorder, latency histograms with percentiles,
Prometheus exposition, JSON log correlation, metrics reset, and the
device-trace state-leak fix.

CPU-only, tier-1-eligible: no /root/reference dependency — the
two-participant acceptance test runs master AND worker as in-process
ServerStates over real loopback HTTP (aiohttp TestServer sockets), the
same topology test_server.py/test_dispatcher.py use.
"""

import asyncio
import json
import logging
import os
import re
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import logging as log_mod
from comfyui_distributed_tpu.utils import trace as tr


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


@pytest.fixture(autouse=True)
def tracing_on():
    """Tests assume the always-on default; restore whatever a prior test
    (or bench import) left behind."""
    was = tr.tracing_enabled()
    tr.set_tracing(True)
    yield
    tr.set_tracing(was)


def make_prompt(seed=1, steps=1, size=32, save=False):
    out_node = {"class_type": "SaveImage",
                "inputs": {"images": ["1", 0], "filename_prefix": "obs"}} \
        if save else {"class_type": "PreviewImage",
                      "inputs": {"images": ["1", 0]}}
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "cat", "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "9": {"class_type": "EmptyLatentImage",
              "inputs": {"width": size, "height": size, "batch_size": 1}},
        "8": {"class_type": "KSampler",
              "inputs": {"model": ["7", 0], "positive": ["5", 0],
                         "negative": ["6", 0], "latent_image": ["9", 0],
                         "seed": seed, "steps": steps, "cfg": 1.0,
                         "sampler_name": "euler", "scheduler": "normal",
                         "denoise": 1.0}},
        "1": {"class_type": "VAEDecode",
              "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
        "3": out_node,
    }


def make_distributed_prompt(seed=5, steps=1, size=32):
    """txt2img with DistributedSeed -> KSampler and a DistributedCollector
    between VAEDecode and the preview — the fan-out shape the master's
    interceptor orchestrates."""
    p = make_prompt(seed=seed, steps=steps, size=size)
    p["4"] = {"class_type": "DistributedSeed", "inputs": {"seed": seed}}
    p["8"]["inputs"]["seed"] = ["4", 0]
    p["2"] = {"class_type": "DistributedCollector",
              "inputs": {"images": ["1", 0]}}
    p["3"]["inputs"]["images"] = ["2", 0]
    return p


async def wait_remote_history(client, pid, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        hist = await (await client.get("/history")).json()
        if pid in hist:
            return hist[pid]
        await asyncio.sleep(0.05)
    raise AssertionError(f"prompt {pid} never finished")


def spans_by_name(rec):
    out = {}
    for s in rec["spans"]:
        out.setdefault(s["name"], []).append(s)
    return out


class TestTraceparent:
    def test_roundtrip(self):
        sp = tr.Span("x")
        header = tr.format_traceparent(sp)
        assert re.fullmatch(r"00-[0-9a-f]{32}-[0-9a-f]{16}-01", header)
        assert tr.parse_traceparent(header) == (sp.trace_id, sp.span_id)

    def test_malformed_headers_rejected(self):
        for bad in (None, "", "garbage", "00-zz-yy-01",
                    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # zero trace
                    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # zero span
                    "00-" + "a" * 31 + "-" + "b" * 16 + "-01"):  # short
            assert tr.parse_traceparent(bad) is None, bad

    def test_headers_empty_without_active_span(self):
        assert tr.traceparent_headers() == {}

    def test_headers_follow_current_span(self):
        root = tr.start_span("job")
        with tr.use_span(root):
            with tr.span("dispatch") as sp:
                h = tr.traceparent_headers()
                assert h[C.TRACEPARENT_HEADER] == tr.format_traceparent(sp)
        root.end()


class TestSpanContext:
    def test_child_parentage_and_status(self):
        root = tr.start_span("job", attrs={"prompt_id": "p_x"})
        with tr.use_span(root):
            with tr.span("execute") as e:
                assert e.trace_id == root.trace_id
                assert e.parent_id == root.span_id
                assert tr.current_trace_ids()["prompt_id"] == "p_x"
            with pytest.raises(ValueError):
                with tr.span("boom"):
                    raise ValueError("x")
        root.end()
        exported = tr.GLOBAL_TRACES.export(root.trace_id)
        boom = [s for s in exported if s["name"] == "boom"]
        assert boom and boom[0]["status"] == "error"

    def test_capture_reattach_across_thread(self):
        """The HostIOPool handoff contract: a span begun on one thread
        parents work submitted to another thread."""
        root = tr.start_span("job")
        seen = {}

        def work(captured):
            with tr.use_span(captured):
                with tr.span("deferred") as d:
                    seen["parent"] = d.parent_id
                    seen["trace"] = d.trace_id

        with tr.use_span(root):
            t = threading.Thread(target=work,
                                 args=(tr.capture_span_context(),))
            t.start()
            t.join(5)
        root.end()
        assert seen == {"parent": root.span_id, "trace": root.trace_id}

    def test_disabled_tracing_is_noop(self):
        tr.set_tracing(False)
        assert tr.start_span("job") is None
        with tr.span("x") as sp:
            assert sp is None
        assert tr.traceparent_headers() == {}
        tr.set_tracing(True)

    def test_stage_records_histogram_without_span(self):
        """stage() outside any trace still feeds the aggregate timeline
        and never fabricates orphan spans."""
        before = tr.GLOBAL_STAGES.snapshot().get("obs_stage",
                                                 {}).get("count", 0)
        with tr.stage("obs_stage"):
            pass
        snap = tr.GLOBAL_STAGES.snapshot()["obs_stage"]
        assert snap["count"] == before + 1


class TestHistogram:
    def test_bucket_and_percentile_math(self):
        h = tr.LatencyHistogram(bounds=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5, 5.0):
            h.record(v)
        cum = h.cumulative()
        assert cum == [(0.01, 2), (0.1, 3), (1.0, 4), (float("inf"), 5)]
        snap = h.snapshot()
        assert snap["count"] == 5
        assert snap["max_s"] == 5.0
        assert abs(snap["total_s"] - 5.56) < 1e-9
        # p50: rank 2.5 falls in the (0.01, 0.1] bucket
        assert 0.01 <= snap["p50_s"] <= 0.1
        # p99: rank ~4.95 falls in the overflow bucket, capped toward max
        assert 1.0 <= snap["p99_s"] <= 5.0
        assert h.percentile(0.0) == 0.0 or h.percentile(0.0) <= 0.01

    def test_empty_histogram(self):
        h = tr.LatencyHistogram()
        snap = h.snapshot()
        assert snap["count"] == 0 and snap["p99_s"] == 0.0

    def test_phase_stats_keeps_legacy_keys_and_adds_percentiles(self):
        ps = tr.PhaseStats()
        for v in (0.01, 0.02, 0.03):
            ps.record("x", v)
        snap = ps.snapshot()["x"]
        # legacy readers (bench stage_totals, metrics tests) rely on these
        assert snap["count"] == 3
        assert abs(snap["total_s"] - 0.06) < 1e-9
        assert abs(snap["max_s"] - 0.03) < 1e-9
        for k in ("mean_s", "p50_s", "p95_s", "p99_s"):
            assert k in snap
        ps.reset()
        assert ps.snapshot() == {}


class TestFlightRecorder:
    def _commit_one(self, rec, pid):
        sp = tr.Span(f"job_{pid}")
        rec.add(sp.trace_id, sp.to_dict(provisional=True))
        rec.commit(pid, sp.trace_id, status="ok",
                   root_span_id=sp.span_id, duration_s=0.1)
        return sp

    def test_ring_eviction(self):
        rec = tr.FlightRecorder(max_traces=3)
        spans = [self._commit_one(rec, f"p{i}") for i in range(5)]
        assert rec.size() == 3
        assert rec.get("p0") is None and rec.get("p1") is None
        assert rec.get("p4") is not None
        # evicted trace ids are unmapped: late spans for them are dropped
        rec.add(spans[0].trace_id, tr.Span("late").to_dict())
        assert rec.get("p0") is None
        index = rec.index()
        assert [e["prompt_id"] for e in index] == ["p4", "p3", "p2"]

    def test_ingest_dedupes_and_replaces_provisional(self):
        rec = tr.FlightRecorder(max_traces=4)
        sp = tr.Span("execute")
        prov = sp.to_dict(provisional=True)
        rec.ingest([prov])
        final = dict(prov)
        final.pop("provisional", None)
        final["duration_s"] = 9.9
        rec.ingest([final, {"not": "a span"}, None])
        spans = rec.export(sp.trace_id)
        assert len(spans) == 1
        assert spans[0]["duration_s"] == 9.9
        assert "provisional" not in spans[0]

    def test_late_arrival_after_commit_lands_in_ring(self):
        rec = tr.FlightRecorder(max_traces=4)
        sp = self._commit_one(rec, "pj")
        straggler = tr.Span("receive_tile", trace_id=sp.trace_id,
                            parent_id=sp.span_id)
        rec.add(sp.trace_id, straggler.to_dict())
        got = rec.get("pj")
        assert {"job_pj", "receive_tile"} <= \
            {s["name"] for s in got["spans"]}

    def test_span_cap_drops_beyond_limit(self):
        rec = tr.FlightRecorder(max_traces=2, max_spans=3)
        tid = tr.new_trace_id()
        for i in range(6):
            rec.add(tid, tr.Span(f"s{i}", trace_id=tid).to_dict())
        assert len(rec.export(tid)) == 3
        assert rec.dropped_spans == 3

    def test_build_span_tree_orphans_surface_as_roots(self):
        a = tr.Span("root")
        b = tr.Span("child", parent=a)
        orphan = tr.Span("remote", trace_id=a.trace_id,
                         parent_id="feedfacefeedface")
        tree = tr.build_span_tree([s.to_dict(provisional=True)
                                   for s in (a, b, orphan)])
        names = sorted(t["name"] for t in tree)
        assert names == ["remote", "root"]
        root = [t for t in tree if t["name"] == "root"][0]
        assert [c["name"] for c in root["children"]] == ["child"]


def run_with_client(fn, tmp_path, **state_kw):
    async def go():
        state = ServerState(
            config_path=str(tmp_path / "cfg.json"),
            input_dir=str(tmp_path / "input"),
            output_dir=str(tmp_path / "output"),
            **state_kw)
        app = build_app(state)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await fn(client, state)
        finally:
            await client.close()
    return asyncio.run(go())


# --- Prometheus text format validation (no prometheus_client in the
# container — assert the grammar and histogram invariants by hand) -----------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
    r" [0-9eE+.\-]+(?: [0-9]+)?$")

# OpenMetrics exemplar suffix (ISSUE 18): `# {label="v",...} value [ts]`
_EXEMPLAR_RE = re.compile(
    r"^\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\}"
    r" [0-9eE+.\-]+(?: [0-9eE+.\-]+)?$")


def validate_prometheus(text):
    """Grammar + histogram-invariant check; returns {family: type}."""
    types = {}
    samples = []
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# TYPE "):
            _, _, family, typ = line.split(" ", 3)
            types[family] = typ
        elif line.startswith("# HELP ") or not line.strip():
            continue
        else:
            if " # " in line:
                # exemplar-carrying sample: validate the suffix, then
                # the base sample; exemplars only ride _bucket series
                line, exemplar = line.split(" # ", 1)
                assert _EXEMPLAR_RE.match(exemplar), \
                    f"bad exemplar: {exemplar!r}"
                name = re.split(r"[{ ]", line, 1)[0]
                assert name.endswith("_bucket"), \
                    f"exemplar on non-bucket series: {line!r}"
            assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
            samples.append(line)
    # every sample belongs to a declared family
    for line in samples:
        name = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in types or base in types, f"undeclared: {name}"
    # histogram invariants: per labelset, cumulative buckets
    # non-decreasing and +Inf == _count
    hists = {f for f, t in types.items() if t == "histogram"}
    for fam in hists:
        buckets, counts = {}, {}
        for line in samples:
            if line.startswith(fam + "_bucket{"):
                labels = line[len(fam + "_bucket{"):line.index("}")]
                le = re.search(r'le="([^"]*)"', labels).group(1)
                key = re.sub(r'(,?)le="[^"]*"(,?)', ",", labels).strip(",")
                buckets.setdefault(key, []).append(
                    (le, float(line.rsplit(" ", 1)[1])))
            elif line.startswith(fam + "_count"):
                key = line[len(fam + "_count"):].lstrip("{")
                key = key[:key.index("}")] if "}" in key else ""
                counts[key] = float(line.rsplit(" ", 1)[1])
        for key, series in buckets.items():
            vals = [v for _, v in series]
            assert vals == sorted(vals), f"{fam}{{{key}}} not cumulative"
            les = [le for le, _ in series]
            assert les[-1] == "+Inf", f"{fam}{{{key}}} missing +Inf"
            assert key in counts and counts[key] == vals[-1], \
                f"{fam}{{{key}}} +Inf != _count"
    return types


class TestPrometheusExposition:
    def test_prom_endpoint_valid_and_complete(self, tmp_path):
        # make sure stage/phase/counter families have content
        with tr.stage("prom_probe_stage"):
            time.sleep(0.001)
        tr.GLOBAL_COUNTERS.bump("wire_tensor_msgs", 0)
        tr.GLOBAL_COUNTERS.bump("exec_runs", 0)

        async def body(client, state):
            r = await client.get("/distributed/metrics.prom")
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            text = await r.text()
            types = validate_prometheus(text)
            # stage histograms with _bucket/_sum/_count series
            assert types["dtpu_stage_seconds"] == "histogram"
            assert 'dtpu_stage_seconds_bucket{le="+Inf",' \
                   'stage="prom_probe_stage"}' in text
            assert 'dtpu_stage_seconds_sum{stage="prom_probe_stage"}' \
                in text
            assert 'dtpu_stage_seconds_count{stage="prom_probe_stage"}' \
                in text
            assert types["dtpu_node_seconds"] == "histogram"
            # existing wire/scheduler counters ride along
            assert 'dtpu_events_total{event="wire_tensor_msgs"}' in text
            assert 'dtpu_events_total{event="exec_runs"}' in text
            assert types["dtpu_jit_traces_total"] == "counter"
            assert types["dtpu_queue_remaining"] == "gauge"
            assert types["dtpu_prompts_executed_total"] == "counter"
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_label_escaping(self):
        tr.GLOBAL_STAGES.record('we"ird\\name\n', 0.001)
        try:
            text = tr.prometheus_text()
            validate_prometheus(text)
            assert r'stage="we\"ird\\name\n"' in text
        finally:
            tr.GLOBAL_STAGES.reset()


class TestMetricsReset:
    def test_reset_clears_aggregates(self, tmp_path):
        tr.GLOBAL_COUNTERS.bump("reset_probe", 3)
        tr.GLOBAL_STAGES.record("reset_probe_stage", 0.5)

        async def body(client, state):
            r = await client.post("/distributed/metrics/reset", json={})
            assert r.status == 200
            assert tr.GLOBAL_COUNTERS.get("reset_probe") == 0
            assert "reset_probe_stage" not in tr.GLOBAL_STAGES.snapshot()
        run_with_client(body, tmp_path, start_exec_thread=False)

    def test_reset_guard_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv(C.METRICS_RESET_ENV, "0")

        async def body(client, state):
            r = await client.post("/distributed/metrics/reset", json={})
            assert r.status == 403
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestJsonLogs:
    def test_log_lines_carry_trace_correlation(self):
        logger = logging.getLogger("comfyui_distributed_tpu")
        captured = []

        class Capture(logging.Handler):
            def emit(self, record):
                captured.append(self.format(record))

        h = Capture()
        logger.addHandler(h)
        try:
            log_mod.set_json_logs(True)
            root = tr.start_span("job", attrs={"prompt_id": "p_json"})
            with tr.use_span(root):
                with tr.span("execute") as e:
                    log_mod.log("hello from inside a span")
            root.end()
            log_mod.log("outside any span")
        finally:
            logger.removeHandler(h)
            log_mod.set_json_logs(False)
        inside = json.loads(captured[0])
        assert inside["msg"].endswith("hello from inside a span")
        assert inside["trace_id"] == root.trace_id
        assert inside["span_id"] == e.span_id
        assert inside["prompt_id"] == "p_json"
        outside = json.loads(captured[1])
        assert "trace_id" not in outside
        assert outside["level"] == "info"


class TestDeviceTraceLeakFix:
    def test_failed_stop_clears_state(self, monkeypatch, tmp_path):
        import jax
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: None)
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("profiler exploded")

        monkeypatch.setattr(jax.profiler, "stop_trace", boom)
        tr.start_device_trace(str(tmp_path / "t1"))
        with pytest.raises(RuntimeError, match="exploded"):
            tr.stop_device_trace()
        # the leak fix: state cleared despite the raise, so a new trace
        # can start instead of "trace already running" forever
        assert tr.trace_status()["running"] is False
        tr.start_device_trace(str(tmp_path / "t2"))
        assert tr.stop_device_trace() == str(tmp_path / "t2")


class TestServerTraceLifecycle:
    def test_single_prompt_trace_tree(self, tmp_path):
        """Local job: /prompt -> flight recorder holds job/queue_wait/
        execute/per-node spans under ONE trace id with intact links."""
        async def body(client, state):
            r = await client.post("/prompt", json={
                "prompt": make_prompt(seed=3), "client_id": "t"})
            assert r.status == 200
            pid = (await r.json())["prompt_id"]
            hist = await wait_remote_history(client, pid)
            assert hist["status"] == "success", hist
            r = await client.get(f"/distributed/trace/{pid}")
            assert r.status == 200
            rec = await r.json()
            assert rec["status"] == "ok"
            assert {s["trace_id"] for s in rec["spans"]} == \
                {rec["trace_id"]}
            by = spans_by_name(rec)
            for name in ("job", "queue_wait", "execute", "KSampler",
                         "VAEDecode"):
                assert name in by, (name, sorted(by))
            job = by["job"][0]
            assert by["execute"][0]["parent_id"] == job["span_id"]
            assert by["KSampler"][0]["parent_id"] == \
                by["execute"][0]["span_id"]
            # one root, children nested
            assert [t["name"] for t in rec["tree"]] == ["job"]
            # the index lists it newest-first
            idx = await (await client.get("/distributed/traces")).json()
            assert idx["traces"][0]["prompt_id"] == pid
        run_with_client(body, tmp_path, start_exec_thread=True)

    def test_slow_job_log_line(self, tmp_path, monkeypatch):
        monkeypatch.setenv(C.SLOW_JOB_ENV, "0.0001")
        logger = logging.getLogger("comfyui_distributed_tpu")
        lines = []

        class Capture(logging.Handler):
            def emit(self, record):
                lines.append(record.getMessage())

        h = Capture()
        logger.addHandler(h)
        try:
            async def body(client, state):
                r = await client.post("/prompt", json={
                    "prompt": make_prompt(seed=4), "client_id": "t"})
                pid = (await r.json())["prompt_id"]
                hist = await wait_remote_history(client, pid)
                assert hist["status"] == "success", hist
                slow = [ln for ln in lines if "SLOW job" in ln
                        and pid in ln]
                assert slow, lines[-5:]
                # the breakdown names at least the execute stage
                assert "execute=" in slow[0]
            run_with_client(body, tmp_path, start_exec_thread=True)
        finally:
            logger.removeHandler(h)

    def test_rejected_prompt_leaves_error_trace(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv(C.MAX_QUEUE_ENV, "1")

        async def body(client, state):
            state._exec_gate.clear()
            try:
                ok_pid = state.enqueue_prompt(make_prompt(1), "c")
                with pytest.raises(Exception):
                    state.enqueue_prompt(make_prompt(2), "c")
            finally:
                state._exec_gate.set()
            # the rejected prompt committed an error trace (postmortem)
            idx = await (await client.get("/distributed/traces")).json()
            errs = [t for t in idx["traces"] if t["status"] == "error"
                    and t["prompt_id"] != ok_pid]
            assert errs
        run_with_client(body, tmp_path, start_exec_thread=False)


class TestDistributedTraceAcceptance:
    def test_two_participant_fanout_one_trace_tree(self, tmp_path):
        """THE acceptance criterion: master + one worker over CPU
        loopback HTTP; GET /distributed/trace/<prompt_id> on the master
        returns ONE tree where master dispatch, worker execute and
        collect spans share a trace_id with parent/child links intact
        across the HTTP hop (traceparent out, spans shipped back on the
        final job_complete POST)."""
        async def go():
            wdir = tmp_path / "worker"
            mdir = tmp_path / "master"
            for d in (wdir, mdir):
                os.makedirs(d / "in"), os.makedirs(d / "out")
            worker_state = ServerState(
                config_path=str(wdir / "cfg.json"),
                input_dir=str(wdir / "in"), output_dir=str(wdir / "out"),
                is_worker=True, start_exec_thread=True)
            wclient = TestClient(TestServer(build_app(worker_state)))
            await wclient.start_server()
            wport = wclient.server.port
            # master config: one enabled loopback worker
            with open(mdir / "cfg.json", "w") as f:
                json.dump({"workers": [{"id": "w0", "host": "127.0.0.1",
                                        "port": wport, "enabled": True}],
                           "master": {"host": "127.0.0.1"},
                           "settings": {}}, f)
            master_state = ServerState(
                config_path=str(mdir / "cfg.json"),
                input_dir=str(mdir / "in"), output_dir=str(mdir / "out"),
                is_worker=False, start_exec_thread=True)
            mclient = TestClient(TestServer(build_app(master_state)))
            await mclient.start_server()
            master_state.port = mclient.server.port
            try:
                r = await mclient.post("/prompt", json={
                    "prompt": make_distributed_prompt(seed=11),
                    "client_id": "acc"})
                assert r.status == 200, await r.text()
                body = await r.json()
                assert body["workers"] == ["w0"], body
                pid = body["prompt_id"]
                hist = await wait_remote_history(mclient, pid)
                assert hist["status"] == "success", hist
                r = await mclient.get(f"/distributed/trace/{pid}")
                assert r.status == 200
                rec = await r.json()
                # ONE trace id across every span in the tree
                assert {s["trace_id"] for s in rec["spans"]} == \
                    {rec["trace_id"]}, rec["spans"]
                by = spans_by_name(rec)
                ids = {s["span_id"]: s for s in rec["spans"]}
                # master dispatch span for w0
                dispatch = [s for s in by.get("dispatch", [])
                            if (s.get("attrs") or {}).get("worker")
                            == "w0"]
                assert dispatch, sorted(by)
                # worker job span parents under THAT dispatch span
                wjobs = [s for s in by["job"]
                         if (s.get("attrs") or {}).get("role")
                         == "worker"]
                assert wjobs, by["job"]
                assert wjobs[0]["parent_id"] == dispatch[0]["span_id"]
                # worker execute span parents under the worker job span
                wexec = [s for s in by["execute"]
                         if s["parent_id"] == wjobs[0]["span_id"]]
                assert wexec, by["execute"]
                # master collect span, chained to the master root
                assert "collect" in by, sorted(by)
                node = by["collect"][0]
                while node.get("parent_id") in ids:
                    node = ids[node["parent_id"]]
                assert node["span_id"] == rec["root_span_id"]
                # the worker's upload and the master's receive both made
                # it into the same tree (cross-hop both directions)
                assert "receive_image" in by, sorted(by)
                assert "upload" in by, sorted(by)
            finally:
                await mclient.close()
                await wclient.close()
                worker_state.drain(5)
                master_state.drain(5)
        asyncio.run(go())
