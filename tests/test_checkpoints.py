"""Checkpoint interop: the torch<->flax mapping round-trips exactly and
covers every parameter leaf (so a real SD checkpoint fully populates the
model, and an exported one fully reconstructs)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import checkpoints as ckpt
from comfyui_distributed_tpu.models import clip as clip_mod
from comfyui_distributed_tpu.models import registry as reg
from comfyui_distributed_tpu.models import unet as unet_mod
from comfyui_distributed_tpu.models import vae as vae_mod


def _init_family(fam):
    rng = jax.random.PRNGKey(0)
    ds = fam.vae.downscale
    h = w = 8 * ds
    x = jnp.zeros((1, h // ds, w // ds, fam.latent_channels))
    ts = jnp.zeros((1,))
    ctx = jnp.zeros((1, 77, fam.unet.context_dim))
    unet_p = unet_mod.UNet(fam.unet).init(rng, x, ts, ctx)["params"]
    clip_ps = []
    for i, ccfg in enumerate(fam.clips):
        tok = jnp.zeros((1, ccfg.max_length), jnp.int32)
        clip_ps.append(clip_mod.CLIPTextModel(ccfg).init(
            jax.random.PRNGKey(i + 1), tok)["params"])
    img = jnp.zeros((1, h, w, 3))
    vae_p = vae_mod.VAE(fam.vae).init(jax.random.PRNGKey(9), img)["params"]
    return unet_p, clip_ps, vae_p


def _assert_trees_equal(a, b):
    fa = jax.tree_util.tree_flatten_with_path(a)
    fb = jax.tree_util.tree_flatten_with_path(b)
    keys_a = [jax.tree_util.keystr(k) for k, _ in fa[0]]
    keys_b = [jax.tree_util.keystr(k) for k, _ in fb[0]]
    assert keys_a == keys_b
    for (ka, va), (kb, vb) in zip(fa[0], fb[0]):
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb), rtol=1e-6,
                                   err_msg=jax.tree_util.keystr(ka))


# an SDXL-shaped family at tiny scale: vector conditioning (label_emb path),
# two text towers — one HF-layout, one OpenCLIP-layout with packed qkv and
# text_projection
TINY_XL_FAMILY = reg.ModelFamily(
    name="tiny_xl",
    unet=dataclasses.replace(unet_mod.TINY_CONFIG, adm_in_channels=32),
    vae=vae_mod.TINY_VAE_CONFIG,
    clips=(clip_mod.TINY_CLIP_CONFIG,
           dataclasses.replace(clip_mod.TINY_CLIP_CONFIG, projection_dim=48,
                               layout="openclip")),  # like the real bigG cfg
)


@pytest.mark.parametrize("family", [reg.FAMILIES["tiny"], TINY_XL_FAMILY],
                         ids=["tiny", "tiny_xl"])
def test_roundtrip_exact(family):
    unet_p, clip_ps, vae_p = _init_family(family)
    sd = ckpt.export_state_dict(unet_p, clip_ps, vae_p, family)
    # torch-side keys look like the reference ecosystem's checkpoints
    assert any(k.startswith("model.diffusion_model.input_blocks.0.0.weight")
               for k in sd)
    assert any(k.startswith("first_stage_model.encoder.conv_in") for k in sd)
    u2, c2, v2 = ckpt.convert_state_dict(sd, family)
    _assert_trees_equal(unet_p, u2)
    _assert_trees_equal(vae_p, v2)
    for a, b in zip(clip_ps, c2):
        _assert_trees_equal(a, b)


def test_openclip_packed_qkv_layout():
    """The exported OpenCLIP tower uses packed in_proj_weight, torch order."""
    fam = TINY_XL_FAMILY
    unet_p, clip_ps, vae_p = _init_family(fam)
    sd = ckpt.export_state_dict(unet_p, clip_ps, vae_p, fam)
    w = sd["conditioner.embedders.1.model.transformer.resblocks.0"
           ".attn.in_proj_weight"]
    W = fam.clips[1].width
    assert w.shape == (3 * W, W)
    # q slice matches the flax q kernel (transposed)
    q = clip_ps[1]["layers_0"]["q"]["kernel"]
    np.testing.assert_allclose(w[:W], np.asarray(q).T, rtol=1e-6)


def test_vae_attn_exports_4d_conv():
    """VAE attention q/k/v/proj_out must export as 1x1 convs [O, I, 1, 1] —
    strict-shape torch VAE loaders drop 2D tensors here (ADVICE r1)."""
    fam = reg.FAMILIES["tiny"]
    unet_p, clip_ps, vae_p = _init_family(fam)
    sd = ckpt.export_state_dict(unet_p, clip_ps, vae_p, fam)
    for enc_dec in ("encoder", "decoder"):
        for name in ("q", "k", "v", "proj_out"):
            w = sd[f"first_stage_model.{enc_dec}.mid.attn_1.{name}.weight"]
            assert w.ndim == 4 and w.shape[2:] == (1, 1), \
                f"{enc_dec}.{name}: {w.shape}"
    # round-trips exactly through the 4D form
    _, _, v2 = ckpt.convert_state_dict(sd, fam)
    _assert_trees_equal(vae_p, v2)


def test_transformer_proj_export_form_follows_family():
    """SD1.x-style configs export spatial-transformer proj_in/out as 1x1
    convs; use_linear_in_transformer configs export nn.Linear 2D."""
    conv_fam = reg.FAMILIES["tiny"]
    assert not conv_fam.unet.use_linear_in_transformer
    lin_fam = reg.ModelFamily(
        name="tiny_lin",
        unet=dataclasses.replace(conv_fam.unet,
                                 use_linear_in_transformer=True),
        vae=conv_fam.vae, clips=conv_fam.clips)
    for fam, ndim in ((conv_fam, 4), (lin_fam, 2)):
        unet_p, clip_ps, vae_p = _init_family(fam)
        sd = ckpt.export_state_dict(unet_p, clip_ps, vae_p, fam)
        key = next(k for k in sd if k.endswith(".proj_in.weight")
                   and k.startswith("model.diffusion_model"))
        assert sd[key].ndim == ndim, f"{fam.name}: {sd[key].shape}"
        u2, _, _ = ckpt.convert_state_dict(sd, fam)
        _assert_trees_equal(unet_p, u2)


def test_missing_keys_raise():
    fam = reg.FAMILIES["tiny"]
    unet_p, clip_ps, vae_p = _init_family(fam)
    sd = ckpt.export_state_dict(unet_p, clip_ps, vae_p, fam)
    del sd["model.diffusion_model.time_embed.0.weight"]
    with pytest.raises(KeyError):
        ckpt.convert_state_dict(sd, fam)


def test_file_roundtrip(tmp_path):
    fam = reg.FAMILIES["tiny"]
    unet_p, clip_ps, vae_p = _init_family(fam)
    path = str(tmp_path / "tiny.safetensors")
    ckpt.save_checkpoint(path, unet_p, clip_ps, vae_p, fam)
    u2, c2, v2 = ckpt.load_checkpoint(path, fam)
    _assert_trees_equal(unet_p, u2)


def _rrdb_torch_sd(params, naming="realesrgan", scale=2, num_blocks=2):
    """Synthesize a torch-layout ESRGAN state dict from flax RRDB params."""
    sd = {}

    def put(tkey, leaf):
        sd[tkey + ".weight"] = ckpt.t_conv_inv(np.asarray(leaf["kernel"]))
        sd[tkey + ".bias"] = np.asarray(leaf["bias"])

    if naming == "oldarch":
        # old ESRGAN arch "model.N" numbering: 0 = conv_first, 1 = shortcut
        # (sub.i = blocks, sub.last = trunk), then per-2x [Upsample, conv,
        # lrelu] triplets, HRconv, lrelu, conv_last
        n_up = {1: 0, 2: 1, 4: 2, 8: 3}[scale]
        names = dict(first="model.0",
                     body=f"model.1.sub.{{i}}.RDB{{j}}.conv{{k}}",
                     trunk=f"model.1.sub.{num_blocks}",
                     up="model.{model_idx}", hr=f"model.{2 + 3 * n_up}",
                     last=f"model.{4 + 3 * n_up}")
    else:
        names = {
            "realesrgan": dict(first="conv_first",
                               body="body.{i}.rdb{j}.conv{k}",
                               trunk="conv_body", up="conv_up{i}",
                               hr="conv_hr", last="conv_last"),
            "xinntao": dict(first="conv_first",
                            body="RRDB_trunk.{i}.RDB{j}.conv{k}",
                            trunk="trunk_conv", up="upconv{i}", hr="HRconv",
                            last="conv_last"),
        }[naming]
    put(names["first"], params["conv_first"])
    for i, blk in ((int(k.split("_")[1]), v) for k, v in params.items()
                   if k.startswith("rrdb_")):
        for j in range(3):
            for k in range(5):
                put(names["body"].format(i=i, j=j + 1, k=k + 1),
                    blk[f"db{j}"][f"conv{k}"])
    put(names["trunk"], params["trunk_conv"])
    for k in params:
        if k.startswith("up_"):
            i = int(k.split("_")[1])
            put(names["up"].format(i=i + 1, model_idx=3 + 3 * i), params[k])
    put(names["hr"], params["hr_conv"])
    put(names["last"], params["conv_last"])
    return sd


@pytest.mark.parametrize("naming", ["realesrgan", "xinntao", "oldarch"])
@pytest.mark.parametrize("scale", [1, 2, 4])
def test_upscaler_checkpoint_roundtrip(tmp_path, naming, scale):
    """All three torch naming schemes at 1x/2x/4x — the old-arch tail
    indices depend on scale (ADVICE r1: 4x was hardcoded)."""
    from comfyui_distributed_tpu.models.upscalers import (
        RRDBNet, TINY_RRDB_CONFIG)
    cfg = dataclasses.replace(TINY_RRDB_CONFIG, scale=scale)
    params = RRDBNet(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8, 8, 3)))["params"]
    sd = _rrdb_torch_sd(params, naming, scale=scale,
                        num_blocks=cfg.num_blocks)
    path = str(tmp_path / "up.safetensors")
    ckpt.save_state_dict(sd, path)
    loaded = ckpt.load_upscaler_checkpoint(path, cfg)
    _assert_trees_equal(params, loaded)


def test_registry_loads_real_file(tmp_path, monkeypatch):
    """load_pipeline picks up an on-disk checkpoint instead of virtual init."""
    monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny")
    fam = reg.FAMILIES["tiny"]
    unet_p, clip_ps, vae_p = _init_family(fam)
    path = str(tmp_path / "real.safetensors")
    ckpt.save_checkpoint(path, unet_p, clip_ps, vae_p, fam)
    reg.clear_pipeline_cache()
    pipe = reg.load_pipeline("real.safetensors", models_dir=str(tmp_path))
    _assert_trees_equal(pipe.unet_params, unet_p)
    reg.clear_pipeline_cache()
