"""Overlapped execution pipeline (ISSUE 2): batch-coalescing scheduler,
compute/host-IO overlap, raw-tensor wire negotiation, backpressure and
graceful drain."""

import asyncio
import os
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from comfyui_distributed_tpu.models import registry
from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.server.app import ServerState, build_app
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import net as net_mod
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.image import (
    decode_tensor,
    encode_png,
    encode_tensor,
)
from comfyui_distributed_tpu.workflow import scheduler as sched
from comfyui_distributed_tpu.workflow.executor import WorkflowExecutor


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(registry.FAMILY_ENV, "tiny")
    yield


def make_prompt(seed, steps=1, size=32, text="cat", batch=1):
    return {
        "7": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": "tiny.safetensors"}},
        "5": {"class_type": "CLIPTextEncode",
              "inputs": {"text": text, "clip": ["7", 1]}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["7", 1]}},
        "9": {"class_type": "EmptyLatentImage",
              "inputs": {"width": size, "height": size,
                         "batch_size": batch}},
        "8": {"class_type": "KSampler",
              "inputs": {"model": ["7", 0], "positive": ["5", 0],
                         "negative": ["6", 0], "latent_image": ["9", 0],
                         "seed": seed, "steps": steps, "cfg": 2.0,
                         "sampler_name": "euler", "scheduler": "normal",
                         "denoise": 1.0}},
        "1": {"class_type": "VAEDecode",
              "inputs": {"samples": ["8", 0], "vae": ["7", 2]}},
        "3": {"class_type": "PreviewImage", "inputs": {"images": ["1", 0]}},
    }


def make_state(tmp_path, **kw):
    return ServerState(config_path=str(tmp_path / "cfg.json"),
                       input_dir=str(tmp_path / "in"),
                       output_dir=str(tmp_path / "out"), **kw)


def wait_history(state, pids, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(p in state._history for p in pids):
            return {p: state._history[p] for p in pids}
        time.sleep(0.01)
    raise AssertionError(f"prompts never finished: "
                         f"{[p for p in pids if p not in state._history]}")


def staged_burst(state, prompts, client="c"):
    """Enqueue a burst behind the held exec gate so one pop sees it all."""
    state._exec_gate.clear()
    try:
        return [state.enqueue_prompt(p, client) for p in prompts]
    finally:
        state._exec_gate.set()


class TestCoalescingSignature:
    def test_seed_only_difference_shares_signature(self):
        a = sched.coalesce_signature(make_prompt(1))
        b = sched.coalesce_signature(make_prompt(999))
        assert a is not None and a == b

    def test_shape_affecting_widgets_split_signatures(self):
        base = sched.coalesce_signature(make_prompt(1))
        assert sched.coalesce_signature(make_prompt(1, steps=2)) != base
        assert sched.coalesce_signature(make_prompt(1, size=64)) != base
        assert sched.coalesce_signature(make_prompt(1, text="dog")) != base
        assert sched.coalesce_signature(make_prompt(1, batch=2)) != base

    def test_unsafe_graphs_are_not_coalescable(self):
        p = make_prompt(1)
        p["99"] = {"class_type": "DistributedCollector",
                   "inputs": {"images": ["1", 0]}}
        assert sched.coalesce_signature(p) is None
        # hidden orchestration state -> never merged
        p2 = make_prompt(1)
        p2["8"]["hidden"] = {"multi_job_id": "x"}
        assert sched.coalesce_signature(p2) is None
        # no EmptyLatentImage batch source -> no safe way to batch
        p3 = {k: v for k, v in make_prompt(1).items() if k != "9"}
        assert sched.coalesce_signature(p3) is None


class TestCoalescedExecution:
    def test_coalesced_matches_serial_per_prompt(self):
        """Per-prompt results survive batch splitting: the merged run's
        prompt-major chunks equal each prompt's own serial output (each
        prompt keeps its exact (seed, fold-idx) noise streams)."""
        seeds = [11, 22, 33, 44]
        serial = []
        for s in seeds:
            res = WorkflowExecutor(OpContext()).execute(make_prompt(s))
            serial.append(res.images)
        graph, hidden = sched.build_coalesced(
            [make_prompt(s) for s in seeds])
        assert hidden == {"8": {"coalesced_seeds": seeds}}
        ctx = OpContext()
        ctx.coalesce = len(seeds)
        res = WorkflowExecutor(ctx).execute(graph, hidden=hidden)
        chunks = sched.split_images(res.images, len(seeds))
        assert [len(c) for c in chunks] == [1, 1, 1, 1]
        for mine, theirs in zip(chunks, serial):
            for a, b in zip(mine, theirs):
                np.testing.assert_allclose(a, b, atol=1e-4)

    def test_burst_coalesces_into_one_dispatch(self, tmp_path):
        """Acceptance: a 4-prompt signature-identical burst dispatches
        exactly ONE compiled execution (vs 4 serial) with zero new
        traces once the shape is warm."""
        st = make_state(tmp_path, overlap=True, coalesce=True)
        # warm both shapes: batch-1 (single) and the coalesced batch-4
        wait_history(st, [st.enqueue_prompt(make_prompt(0), "warm")])
        wait_history(st, staged_burst(
            st, [make_prompt(50 + i) for i in range(4)]))
        runs0 = trace_mod.GLOBAL_COUNTERS.get("exec_runs")
        mark = trace_mod.GLOBAL_RETRACES.mark()
        hist = wait_history(st, staged_burst(
            st, [make_prompt(100 + i) for i in range(4)]))
        assert trace_mod.GLOBAL_COUNTERS.get("exec_runs") - runs0 == 1
        assert trace_mod.GLOBAL_RETRACES.since(mark)["traces"] == 0
        for h in hist.values():
            assert h["status"] == "success"
            assert h["coalesced"] == 4 and h["images"] == 1
        assert st.drain(10)

    def test_mixed_signatures_keep_client_order(self, tmp_path):
        """Overlap/coalescing never reorders one client's prompts: only a
        CONTIGUOUS same-signature run coalesces, so a later compatible
        prompt cannot jump an incompatible one queued between them."""
        st = make_state(tmp_path, overlap=True, coalesce=True)
        wait_history(st, [st.enqueue_prompt(make_prompt(0), "warm")])
        # the middle prompt differs in TEXT — a different signature but
        # the same compiled program, so the test stays cheap cold
        a1, b, a2 = staged_burst(st, [make_prompt(1),
                                      make_prompt(2, text="dog"),
                                      make_prompt(3)])
        hist = wait_history(st, [a1, b, a2])
        assert all(h["status"] == "success" for h in hist.values())
        # a1 ran alone (b broke the contiguous run), then b, then a2
        assert "coalesced" not in hist[a1]
        assert hist[a1]["finished_at"] <= hist[b]["finished_at"]
        assert hist[b]["finished_at"] <= hist[a2]["finished_at"]
        assert st.drain(10)

    def test_failure_hits_only_its_group(self, tmp_path, monkeypatch):
        """An interrupt (or any failure) during a coalesced group fails
        that group's prompts only; the next group runs clean."""
        from comfyui_distributed_tpu.ops import basic as ops_basic
        real = ops_basic.KSampler.execute
        boom = {"on": True}

        def fake(self, ctx, *a, **kw):
            if boom["on"]:
                raise InterruptedError("execution interrupted (test)")
            return real(self, ctx, *a, **kw)

        monkeypatch.setattr(ops_basic.KSampler, "execute", fake)
        st = make_state(tmp_path, overlap=True, coalesce=True)
        pids = staged_burst(st, [make_prompt(200 + i) for i in range(3)])
        hist = wait_history(st, pids)
        for h in hist.values():
            assert h["status"] == "error" and h["coalesced"] == 3
            assert "interrupted" in h["error"]
        assert st.metrics["prompts_failed"] >= 3
        boom["on"] = False
        ok = wait_history(st, [st.enqueue_prompt(make_prompt(7), "c")])
        assert list(ok.values())[0]["status"] == "success"
        assert st.drain(10)


class TestOverlapInvariants:
    def test_overlap_beats_serial_on_a_4_prompt_queue(self, tmp_path,
                                                      monkeypatch):
        """Acceptance: bench.py --phase pipeline's core measurement —
        overlapped+coalesced >= 1.3x serial imgs/s for a 4-prompt queue,
        one dispatch for the group, zero retraces when warm."""
        import bench
        monkeypatch.setenv("DTPU_DEFAULT_FAMILY", "tiny")
        m = bench.measure_pipeline(n_prompts=4, steps=1)
        assert m["speedup"] >= 1.3, m
        assert m["overlapped_exec_runs"] == 1, m
        assert m["serial_exec_runs"] == 4, m
        assert m["retraces_timed_round"] == 0, m

    def test_spine_invariants_hold_under_overlapped_executor(self):
        """PR 1's tensor-plane invariants survive the overlap: with host
        edges deferred to the pool, the KSampler->VAEDecode spine still
        moves zero d2h bytes and a repeated run still retraces nothing
        (the deferred fetch is attributed to the output node)."""
        pool = net_mod.HostIOPool(max_workers=2, max_pending=4)
        try:
            def run():
                ctx = OpContext(host_pool=pool)
                return WorkflowExecutor(ctx).execute(
                    make_prompt(5)).wait_host()

            run()
            res = run()
            assert len(res.images) == 1
            spine = ["8", "1"]          # KSampler, VAEDecode
            assert res.host_transfer_bytes("d2h", nodes=spine) == 0
            assert res.retraces["traces"] == 0
            # the deferred fetch was counted — against the output node
            assert res.host_transfer_bytes("d2h") > 0
            assert res.transfers.get("3", {}).get("d2h_bytes", 0) > 0
        finally:
            pool.shutdown()

    def test_coalesced_pngs_embed_their_own_seed(self, tmp_path):
        """Provenance: a coalesced run's saved PNGs each embed the
        metadata of THEIR prompt (seed re-applied from the scheduler's
        overrides), not prompt 0's — reloading any PNG reproduces its
        own image."""
        import json

        from PIL import Image
        seeds = [71, 72, 73]
        prompts = []
        for s in seeds:
            p = make_prompt(s)
            p["3"] = {"class_type": "SaveImage",
                      "inputs": {"images": ["1", 0],
                                 "filename_prefix": "prov"}}
            prompts.append(p)
        graph, hidden = sched.build_coalesced(prompts)
        ctx = OpContext(output_dir=str(tmp_path))
        ctx.coalesce = len(seeds)
        WorkflowExecutor(ctx).execute(graph, hidden=hidden).wait_host()
        names = sorted(os.listdir(tmp_path))
        assert len(names) == 3
        embedded = []
        for n in names:
            meta = json.loads(Image.open(tmp_path / n).info["prompt"])
            embedded.append(meta["8"]["inputs"]["seed"])
        assert embedded == seeds

    def test_deferred_save_writes_pngs(self, tmp_path):
        """SaveImage's disk write rides the pool but still lands, with
        continuing counters, once the run is joined."""
        pool = net_mod.HostIOPool()
        try:
            prompt = make_prompt(5)
            prompt["3"] = {"class_type": "SaveImage",
                           "inputs": {"images": ["1", 0],
                                      "filename_prefix": "ovl"}}
            ctx = OpContext(host_pool=pool, output_dir=str(tmp_path))
            WorkflowExecutor(ctx).execute(prompt).wait_host()
            ctx2 = OpContext(host_pool=pool, output_dir=str(tmp_path))
            WorkflowExecutor(ctx2).execute(prompt).wait_host()
            names = sorted(os.listdir(tmp_path))
            assert names == ["ovl_00000.png", "ovl_00001.png"]
        finally:
            pool.shutdown()


class TestWireFormat:
    def test_tensor_wire_roundtrip_bit_exact(self, rng):
        arr = rng.random((2, 9, 7, 3)).astype(np.float32)
        back = decode_tensor(encode_tensor(arr))
        assert back.dtype == np.float32
        np.testing.assert_array_equal(back, arr)  # BIT-exact, beyond PNG

    def test_negotiation_and_tensor_upload(self, tmp_path, rng):
        """A master advertising the raw-tensor type receives bit-exact
        tensors on /distributed/job_complete; a peer WITHOUT the
        wire_formats route negotiates down to PNG."""
        async def body():
            net_mod.reset_wire_cache()
            state = make_state(tmp_path, start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            # a "legacy" peer: no /distributed/wire_formats route
            from aiohttp import web
            legacy = TestClient(TestServer(web.Application()))
            await legacy.start_server()
            try:
                url = str(client.server.make_url("")).rstrip("/")
                fmt = await net_mod.negotiate_wire_format(url)
                assert fmt == C.TENSOR_WIRE_CONTENT_TYPE
                # codec is the best one BOTH sides support (this build
                # talks to itself, so its own best decoder)
                from comfyui_distributed_tpu.utils.image import \
                    tensor_codecs
                assert net_mod.wire_codec(url) == tensor_codecs()[0]
                legacy_url = str(legacy.server.make_url("")).rstrip("/")
                assert await net_mod.negotiate_wire_format(legacy_url) \
                    == "image/png"

                await state.jobs.prepare_job("j1")
                img = rng.random((1, 8, 8, 3)).astype(np.float32)
                import aiohttp
                form = aiohttp.FormData()
                form.add_field("multi_job_id", "j1")
                form.add_field("worker_id", "worker_0")
                form.add_field("image_index", "0")
                form.add_field("is_last", "true")
                form.add_field("image", encode_tensor(img),
                               filename="img_0.dtt",
                               content_type=C.TENSOR_WIRE_CONTENT_TYPE)
                r = await client.post("/distributed/job_complete",
                                      data=form)
                assert r.status == 200
                q = await state.jobs.get_queue("j1")
                item = q.get_nowait()
                np.testing.assert_array_equal(item["tensor"], img)

                # PNG stays accepted on the same route (fallback path)
                await state.jobs.prepare_job("j2")
                form = aiohttp.FormData()
                form.add_field("multi_job_id", "j2")
                form.add_field("image", encode_png(img),
                               filename="img.png",
                               content_type="image/png")
                r = await client.post("/distributed/job_complete",
                                      data=form)
                assert r.status == 200
            finally:
                net_mod.reset_wire_cache()
                await legacy.close()
                await client.close()
        asyncio.run(body())

    def test_wire_env_forces_png(self, monkeypatch):
        async def body():
            net_mod.reset_wire_cache()
            monkeypatch.setenv(C.WIRE_FORMAT_ENV, "png")
            assert await net_mod.negotiate_wire_format(
                "http://127.0.0.1:1") == "image/png"
            net_mod.reset_wire_cache()
        asyncio.run(body())


class TestBackpressureAndDrain:
    def test_queue_cap_returns_429(self, tmp_path, monkeypatch):
        monkeypatch.setenv(C.MAX_QUEUE_ENV, "2")

        async def body():
            state = make_state(tmp_path, start_exec_thread=False)
            assert state.max_queue == 2
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                for i in range(2):
                    r = await client.post("/prompt", json={
                        "prompt": make_prompt(i), "client_id": "c"})
                    assert r.status == 200
                r = await client.post("/prompt", json={
                    "prompt": make_prompt(9), "client_id": "c"})
                assert r.status == 429
                body_json = await r.json()
                assert body_json["queue_remaining"] == 2
                assert body_json["max_queue"] == 2
                qs = await (await client.get(
                    "/distributed/queue_status")).json()
                assert qs["max_queue"] == 2
                assert qs["queue_remaining"] == 2
            finally:
                await client.close()
        asyncio.run(body())

    def test_drain_finishes_inflight_then_refuses(self, tmp_path):
        st = make_state(tmp_path, overlap=True, coalesce=True)
        pids = staged_burst(st, [make_prompt(300 + i) for i in range(4)])
        assert st.drain() is True
        hist = wait_history(st, pids, timeout=5)
        assert all(h["status"] == "success" for h in hist.values())
        with pytest.raises(RuntimeError, match="draining"):
            st.enqueue_prompt(make_prompt(1), "c")

    def test_metrics_expose_pipeline_block(self, tmp_path):
        async def body():
            state = make_state(tmp_path, start_exec_thread=False)
            client = TestClient(TestServer(build_app(state)))
            await client.start_server()
            try:
                m = await (await client.get("/distributed/metrics")).json()
                pipe = m["pipeline"]
                assert {"stages", "counters", "overlap", "coalesce",
                        "max_queue"} <= set(pipe)
                # the stage timeline carries the per-job stages once any
                # pipelined work ran in this process
                for key in ("queue_wait", "compute"):
                    if trace_mod.GLOBAL_STAGES.snapshot().get(key):
                        assert key in pipe["stages"]
            finally:
                await client.close()
        asyncio.run(body())
