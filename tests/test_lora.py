"""LoRA patching: kohya key resolution, delta math, op + cache behavior."""

import dataclasses
import os

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import checkpoints as ckpt
from comfyui_distributed_tpu.models import lora as lora_mod
from comfyui_distributed_tpu.models import registry as reg
from comfyui_distributed_tpu.ops.base import OpContext, get_op


@pytest.fixture(autouse=True)
def tiny_family(monkeypatch):
    monkeypatch.setenv(reg.FAMILY_ENV, "tiny")
    yield
    lora_mod.clear_lora_cache()


@pytest.fixture
def pipe():
    return reg.load_pipeline("lora-base.ckpt")


def _init_params(fam):
    """Virtual-init (unet, clips, vae) param trees for a family."""
    import jax
    from comfyui_distributed_tpu.models import clip as clip_mod
    from comfyui_distributed_tpu.models import unet as unet_mod
    from comfyui_distributed_tpu.models import vae as vae_mod
    rng = jax.random.PRNGKey(0)
    u = unet_mod.UNet(fam.unet).init(
        rng, jnp.zeros((1, 8, 8, 4)), jnp.zeros((1,)),
        jnp.zeros((1, 77, fam.unet.context_dim)))["params"]
    cs = [clip_mod.CLIPTextModel(c).init(
        rng, jnp.zeros((1, 77), jnp.int32))["params"] for c in fam.clips]
    v = vae_mod.VAE(fam.vae).init(rng, jnp.zeros((1, 16, 16, 3)))["params"]
    return u, cs, v


def _export(pipe):
    return ckpt.export_state_dict(pipe.unet_params, pipe.clip_params,
                                  pipe.vae_params, pipe.family)


def _make_kohya_lora(sd, index, modules, rank=2, seed=0):
    """Synthetic kohya-format LoRA for the given module names."""
    rng = np.random.default_rng(seed)
    out = {}
    for mod in modules:
        key, rows = index[mod]
        w = sd[key]
        out_f = (rows.stop - rows.start) if rows is not None else w.shape[0]
        out[f"{mod}.lora_down.weight"] = rng.standard_normal(
            (rank, int(np.prod(w.shape[1:])))).astype(np.float32)
        out[f"{mod}.lora_up.weight"] = rng.standard_normal(
            (out_f, rank)).astype(np.float32)
        # 0-d array like real kohya files (a bare scalar breaks save_file)
        out[f"{mod}.alpha"] = np.full((), rank / 2, np.float32)
    return out


class TestKeyIndex:
    def test_unet_and_te_modules_indexed(self, pipe):
        sd = _export(pipe)
        index = lora_mod.build_key_index(sd, pipe.family)
        unet_mods = [m for m in index if m.startswith("lora_unet_")]
        te_mods = [m for m in index if m.startswith("lora_te_")]
        assert unet_mods and te_mods
        # kohya names are flattened torch paths; spot-check both towers
        assert any("attn1_to_q" in m for m in unet_mods)
        assert any("self_attn_q_proj" in m for m in te_mods)
        for mod, (key, rows) in index.items():
            assert key in sd

    def test_openclip_tower_gets_hf_aliases_with_row_slices(self):
        """Real kohya SD2/SDXL-te2 LoRAs use HF-converted names; for an
        OpenCLIP-serialized tower those alias onto the packed in_proj's
        q/k/v row blocks."""
        import dataclasses as dc
        from comfyui_distributed_tpu.models.clip import TINY_CLIP_CONFIG
        fam = dc.replace(
            reg.FAMILIES["tiny"], name="tiny_oc",
            clips=(dc.replace(TINY_CLIP_CONFIG, layout="openclip"),))
        p = reg.DiffusionPipeline("oc", fam,
                                  *_init_params(fam))
        sd = ckpt.export_state_dict(p.unet_params, p.clip_params,
                                    p.vae_params, fam)
        index = lora_mod.build_key_index(sd, fam)
        W = fam.clips[0].width
        for j, proj in enumerate(("q_proj", "k_proj", "v_proj")):
            mod = f"lora_te_text_model_encoder_layers_0_self_attn_{proj}"
            assert mod in index, mod
            key, rows = index[mod]
            assert key.endswith("attn.in_proj_weight")
            assert (rows.start, rows.stop) == (j * W, (j + 1) * W)
        # slice application touches ONLY that row block
        mod = "lora_te_text_model_encoder_layers_0_self_attn_k_proj"
        lora_sd = _make_kohya_lora(sd, index, [mod])
        key, rows = index[mod]
        base = sd[key].copy()
        n, unmatched = lora_mod.apply_lora_to_state_dict(
            sd, lora_sd, index, 1.0, 1.0)
        assert n == 1 and unmatched == []
        assert not np.allclose(sd[key][rows], base[rows])
        np.testing.assert_array_equal(sd[key][:W], base[:W])        # q rows
        np.testing.assert_array_equal(sd[key][2 * W:], base[2 * W:])  # v

    def test_sdxl_style_two_towers_use_te1_te2(self):
        from tests.test_checkpoints import TINY_XL_FAMILY, _init_family
        unet_p, clip_ps, vae_p = _init_family(TINY_XL_FAMILY)
        sd = ckpt.export_state_dict(unet_p, clip_ps, vae_p, TINY_XL_FAMILY)
        index = lora_mod.build_key_index(sd, TINY_XL_FAMILY)
        assert any(m.startswith("lora_te1_") for m in index)
        assert any(m.startswith("lora_te2_") for m in index)
        assert not any(m.startswith("lora_te_") for m in index)


class TestDeltaMath:
    def test_applied_delta_matches_manual(self, pipe):
        """patched == base + strength * alpha/rank * up@down, exactly, in
        torch layout — through export -> apply -> re-export."""
        sd = _export(pipe)
        index = lora_mod.build_key_index(sd, pipe.family)
        mod = next(m for m in sorted(index) if m.endswith("attn1_to_q"))
        lora_sd = _make_kohya_lora(sd, index, [mod])
        key, _rows = index[mod]
        base = sd[key].copy()

        patched = lora_mod.apply_lora_to_pipeline(
            pipe, "unit.safetensors", 0.7, 0.7)
        # write the synthetic lora into the cache-bypassing low-level API
        sd2 = _export(pipe)
        n, unmatched = lora_mod.apply_lora_to_state_dict(
            sd2, lora_sd, index, 0.7, 0.7)
        assert n == 1 and unmatched == []
        up = lora_sd[f"{mod}.lora_up.weight"]
        down = lora_sd[f"{mod}.lora_down.weight"]
        alpha, rank = float(lora_sd[f"{mod}.alpha"]), down.shape[0]
        expect = base + 0.7 * (alpha / rank) * (up @ down).reshape(base.shape)
        np.testing.assert_allclose(sd2[key], expect,
                                   rtol=1e-5, atol=1e-6)

    def test_conv_module_delta_shape(self, pipe):
        """1x1-conv-layout modules (e.g. SD1.x proj_in) reshape correctly."""
        sd = _export(pipe)
        index = lora_mod.build_key_index(sd, pipe.family)
        mod = next(m for m in sorted(index) if m.endswith("proj_in"))
        base_shape = sd[index[mod][0]].shape
        lora_sd = _make_kohya_lora(sd, index, [mod])
        n, unmatched = lora_mod.apply_lora_to_state_dict(
            sd, lora_sd, index, 1.0, 1.0)
        assert n == 1 and unmatched == []
        assert sd[index[mod][0]].shape == base_shape

    def test_strengths_gate_towers_independently(self, pipe):
        sd = _export(pipe)
        index = lora_mod.build_key_index(sd, pipe.family)
        umod = next(m for m in sorted(index) if m.startswith("lora_unet_")
                    and m.endswith("to_q"))
        tmod = next(m for m in sorted(index) if m.startswith("lora_te_")
                    and m.endswith("q_proj"))
        lora_sd = _make_kohya_lora(sd, index, [umod, tmod])
        ub, tb = sd[index[umod][0]].copy(), sd[index[tmod][0]].copy()
        n, _ = lora_mod.apply_lora_to_state_dict(
            sd, lora_sd, index, 1.0, 0.0)   # model only
        assert n == 1
        assert not np.allclose(sd[index[umod][0]], ub)
        np.testing.assert_array_equal(sd[index[tmod][0]], tb)


class TestPipelineAndOp:
    def test_virtual_lora_changes_outputs_deterministically(self, pipe):
        p1 = lora_mod.apply_lora_to_pipeline(pipe, "styleA.safetensors",
                                             1.0, 1.0)
        lora_mod.clear_lora_cache()
        p2 = lora_mod.apply_lora_to_pipeline(pipe, "styleA.safetensors",
                                             1.0, 1.0)
        ctx, _ = pipe.encode_prompt(["a cat"])
        c1, _ = p1.encode_prompt(["a cat"])
        c2, _ = p2.encode_prompt(["a cat"])
        assert not np.allclose(np.asarray(ctx), np.asarray(c1))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))

    def test_patched_pipeline_cached(self, pipe):
        a = lora_mod.apply_lora_to_pipeline(pipe, "x.safetensors", 1.0, 1.0)
        b = lora_mod.apply_lora_to_pipeline(pipe, "x.safetensors", 1.0, 1.0)
        assert a is b
        c = lora_mod.apply_lora_to_pipeline(pipe, "x.safetensors", 0.5, 1.0)
        assert c is not a

    def test_real_file_loaded_from_models_dir(self, pipe, tmp_path):
        sd = _export(pipe)
        index = lora_mod.build_key_index(sd, pipe.family)
        mod = next(m for m in sorted(index) if m.endswith("attn1_to_q"))
        lora_sd = _make_kohya_lora(sd, index, [mod], seed=9)
        from safetensors.numpy import save_file
        save_file(lora_sd, str(tmp_path / "real.safetensors"))
        patched = lora_mod.apply_lora_to_pipeline(
            pipe, "real.safetensors", 1.0, 1.0,
            models_dir=str(tmp_path))
        out = ckpt.export_state_dict(patched.unet_params,
                                     patched.clip_params,
                                     patched.vae_params, patched.family)
        up = lora_sd[f"{mod}.lora_up.weight"]
        down = lora_sd[f"{mod}.lora_down.weight"]
        alpha, rank = float(lora_sd[f"{mod}.alpha"]), down.shape[0]
        key = index[mod][0]
        expect = sd[key] + (alpha / rank) * (up @ down).reshape(sd[key].shape)
        np.testing.assert_allclose(out[key], expect,
                                   rtol=1e-4, atol=1e-5)

    def test_loraloader_op_mixed_model_clip_sources(self, pipe):
        """MODEL and CLIP wired from different checkpoints are patched
        independently — the CLIP edge must NOT be replaced by the model
        checkpoint's text encoder."""
        other = reg.load_pipeline("lora-other.ckpt")
        op = get_op("LoraLoader")
        m2, c2 = op.execute(OpContext(), pipe, other, "mix.safetensors",
                            0.5, 0.5)
        assert m2 is not c2
        assert m2.name.startswith(pipe.name)
        assert c2.name.startswith(other.name)
        # strength 0 on one side passes that input through untouched
        m3, c3 = op.execute(OpContext(), pipe, other, "mix.safetensors",
                            0.5, 0.0)
        assert c3 is other

    def test_loraloader_op(self, pipe):
        op = get_op("LoraLoader")
        m2, c2 = op.execute(OpContext(), pipe, pipe, "opstyle.safetensors",
                            0.8, 0.8)
        assert m2 is c2 and m2 is not pipe
        # zero strengths: identity, no patching work
        m3, c3 = op.execute(OpContext(), pipe, pipe, "opstyle.safetensors",
                            0.0, 0.0)
        assert m3 is pipe and c3 is pipe


class TestCacheCollisions:
    def test_same_name_different_family_not_collided(self):
        """Two pipelines sharing a ckpt filename but differing in family
        must not cross-pollinate derived/LoRA caches (cache_token, not
        name, keys them)."""
        a = reg.load_pipeline("shared.ckpt", family_name="tiny")
        import dataclasses as dc
        fam_b = dc.replace(reg.FAMILIES["tiny"], name="tinyB")
        b = reg.DiffusionPipeline("shared.ckpt", fam_b,
                                  a.unet_params, a.clip_params,
                                  a.vae_params)
        assert a.cache_token != b.cache_token
        pa = lora_mod.apply_lora_to_pipeline(a, "s.safetensors", 1.0, 1.0)
        pb = lora_mod.apply_lora_to_pipeline(b, "s.safetensors", 1.0, 1.0)
        assert pa is not pb


class TestSharedTrees:
    def test_model_only_patch_shares_clip_and_vae(self, pipe):
        p = lora_mod.apply_lora_to_pipeline(pipe, "m.safetensors", 1.0, 0.0)
        assert p.clip_params is pipe.clip_params
        assert p.vae_params is pipe.vae_params
        assert p.unet_params is not pipe.unet_params

    def test_clip_only_patch_shares_unet_and_vae(self, pipe):
        p = lora_mod.apply_lora_to_pipeline(pipe, "c.safetensors", 0.0, 1.0)
        assert p.unet_params is pipe.unet_params
        assert p.vae_params is pipe.vae_params
        assert p.clip_params is not pipe.clip_params
