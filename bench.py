#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): **SDXL 1024px images/sec/chip** — full
txt2img on the native pipeline (CLIP encode -> 20-step CFG denoise loop ->
VAE decode), virtual weights (deterministic random init; the reference
publishes no numbers and no checkpoints ship in this image, SURVEY.md §6).

``vs_baseline`` is 1.0 by definition: the reference publishes **zero**
performance numbers (``/root/reference/README.md`` is qualitative only;
BASELINE.json ``published: {}``), so there is no external number to ratio
against; cross-round BENCH_r{N}.json values are the comparable series.

Env/flags let CI run a smaller config (``--family tiny``) without changing
the metric name printed for the flagship run.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--family", default="sdxl", choices=["sdxl", "sd15", "tiny"])
    p.add_argument("--height", type=int, default=1024)
    p.add_argument("--width", type=int, default=1024)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--cfg", type=float, default=7.5)
    p.add_argument("--sampler", default="euler")
    p.add_argument("--scheduler", default="karras")
    p.add_argument("--repeats", type=int, default=3)
    return p.parse_args()


def bf16_params(tree):
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16)
        if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, tree)


def main():
    args = parse_args()
    from comfyui_distributed_tpu.models.registry import load_pipeline

    dev = jax.devices()[0]
    print(f"[bench] platform={dev.platform} kind="
          f"{getattr(dev, 'device_kind', '?')} family={args.family} "
          f"{args.width}x{args.height} steps={args.steps} batch={args.batch}",
          file=sys.stderr)

    if args.family == "tiny":
        args.height = min(args.height, 128)
        args.width = min(args.width, 128)

    t0 = time.time()
    pipe = load_pipeline("bench.ckpt", family_name=args.family)
    # bf16 weight storage: the UNet computes in bf16 anyway, and fp32 SDXL
    # weights (10.3 GB) would crowd a 16 GB v5e chip
    pipe.unet_params = bf16_params(pipe.unet_params)
    pipe.clip_params = [bf16_params(p) for p in pipe.clip_params]
    print(f"[bench] init {time.time()-t0:.1f}s", file=sys.stderr)

    B = args.batch
    ds = pipe.family.vae.downscale
    lat = jnp.zeros((B, args.height // ds, args.width // ds,
                     pipe.family.latent_channels), jnp.float32)
    prompts = ["a photograph of an astronaut riding a horse"] * B
    context, pooled = pipe.encode_prompt(prompts)
    uncond, _ = pipe.encode_prompt([""] * B)
    y = None
    if pipe.family.unet.adm_in_channels:
        extra = pipe.family.unet.adm_in_channels - pooled.shape[-1]
        y = jnp.concatenate(
            [pooled, jnp.zeros((B, extra), pooled.dtype)], axis=-1)
    seeds = np.arange(B, dtype=np.uint64) + 42

    def run():
        z = pipe.sample(lat, context, uncond, seeds, steps=args.steps,
                        cfg=args.cfg, sampler_name=args.sampler,
                        scheduler=args.scheduler, y=y)
        img = pipe.vae_decode(z)
        img.block_until_ready()
        return img

    t0 = time.time()
    run()  # compile + first batch
    print(f"[bench] compile+first {time.time()-t0:.1f}s", file=sys.stderr)

    t0 = time.time()
    for _ in range(args.repeats):
        run()
    elapsed = time.time() - t0
    n_chips = 1  # bench runs single-chip; scaling measured via dryrun/mesh tests
    ips = (B * args.repeats) / elapsed / n_chips
    print(f"[bench] {args.repeats}x batch={B}: {elapsed:.2f}s "
          f"-> {ips:.4f} img/s/chip", file=sys.stderr)

    metric = (f"{args.family}_{args.width}x{args.height}_"
              f"{args.steps}step_images_per_sec_per_chip")
    print(json.dumps({
        "metric": metric,
        "value": round(ips, 4),
        "unit": "images/sec/chip",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
